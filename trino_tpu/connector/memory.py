"""Memory connector: writable in-RAM tables.

Reference parity: plugin/trino-memory (MemoryMetadata.java, MemoryPagesStore
.java, MemoryPageSinkProvider) — CREATE TABLE / INSERT / CTAS targets and the
engine-test workhorse. Tables live as host numpy column arrays; page sources
re-page them at scan capacity.
"""

from __future__ import annotations

import math
import threading
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from trino_tpu import types as T
from trino_tpu.connector.spi import (
    ColumnHandle, ColumnMetadata, Connector, ConnectorMetadata,
    ConnectorPageSink, ConnectorPageSource, ConnectorSplitManager,
    ConnectorTableHandle, SchemaTableName, Split, TableMetadata,
    TableStatistics, ColumnStatistics, pad_to_capacity, split_range)
from trino_tpu.page import Column, Dictionary, Page


class _StoredTable:
    def __init__(self, metadata: TableMetadata):
        self.metadata = metadata
        self.arrays: List[np.ndarray] = [
            np.empty(0, dtype=object if T.is_string(c.type)
                     else T.to_numpy_dtype(c.type))
            for c in metadata.columns]
        self.valids: List[Optional[np.ndarray]] = [
            None for _ in metadata.columns]
        self.dictionaries: List[Optional[Dictionary]] = [
            None for _ in metadata.columns]
        # write tokens whose staged rows already committed: a retried
        # attempt re-staging under the same token commits as a NO-OP,
        # so QUERY-level retry of INSERT/CTAS is duplicate-free
        # (bounded — see spi.WriteTokenLedger)
        from trino_tpu.connector.spi import WriteTokenLedger
        self.committed_tokens = WriteTokenLedger()

    @property
    def row_count(self) -> int:
        return len(self.arrays[0]) if self.arrays else 0


class MemoryMetadata(ConnectorMetadata):
    def __init__(self):
        self._lock = threading.Lock()
        self._schemas = {"default"}
        self._tables: Dict[SchemaTableName, _StoredTable] = {}

    def list_schemas(self) -> List[str]:
        return sorted(self._schemas)

    def create_schema(self, name: str):
        self._schemas.add(name)

    def drop_schema(self, name: str):
        self._schemas.discard(name)

    def list_tables(self, schema: Optional[str] = None) -> List[SchemaTableName]:
        return sorted((n for n in self._tables
                       if schema is None or n.schema == schema),
                      key=lambda n: (n.schema, n.table))

    def get_table_handle(self, name: SchemaTableName):
        if name in self._tables:
            return ConnectorTableHandle(name)
        return None

    def get_table_metadata(self, handle: ConnectorTableHandle) -> TableMetadata:
        return self._tables[handle.name].metadata

    def get_table_statistics(self, handle: ConnectorTableHandle) -> TableStatistics:
        t = self._tables[handle.name]
        return TableStatistics(float(t.row_count), {
            c.name: ColumnStatistics() for c in t.metadata.columns})

    def create_table(self, metadata: TableMetadata,
                     ignore_existing: bool = False):
        with self._lock:
            if metadata.name in self._tables:
                if ignore_existing:
                    return
                raise ValueError(f"table already exists: {metadata.name}")
            self._schemas.add(metadata.name.schema)
            self._tables[metadata.name] = _StoredTable(metadata)

    def drop_table(self, handle: ConnectorTableHandle):
        with self._lock:
            self._tables.pop(handle.name, None)

    def stored(self, name: SchemaTableName) -> _StoredTable:
        return self._tables[name]


class MemorySplitManager(ConnectorSplitManager):
    def __init__(self, metadata: MemoryMetadata):
        self._metadata = metadata

    def get_splits(self, handle: ConnectorTableHandle,
                   target_splits: int = 1) -> List[Split]:
        rows = self._metadata.stored(handle.name).row_count
        parts = max(1, min(target_splits, math.ceil(max(rows, 1) / 4096)))
        return [Split(handle, p, parts) for p in range(parts)]


class MemoryPageSource(ConnectorPageSource):
    def __init__(self, metadata: MemoryMetadata):
        self._metadata = metadata

    def pages(self, split: Split, columns: Sequence[ColumnHandle],
              page_capacity: int) -> Iterator[Page]:
        stored = self._metadata.stored(split.table.name)
        total = stored.row_count
        start, end = split_range(total, split.part, split.total_parts)
        off = start
        while True:
            hi = min(off + page_capacity, end)
            n = max(hi - off, 0)
            cols = []
            for ch in columns:
                i = ch.ordinal
                raw = stored.arrays[i][off:hi]
                valid = None
                if stored.valids[i] is not None:
                    valid = pad_to_capacity(stored.valids[i][off:hi].astype(bool),
                                 page_capacity, False)
                if T.is_string(ch.type):
                    d = stored.dictionaries[i]
                    if d is None:
                        d, _ = Dictionary.build(
                            np.asarray(stored.arrays[i], dtype=object))
                        stored.dictionaries[i] = d
                    fill = np.where(raw == None, d.values[0] if len(d) else "",  # noqa: E711
                                    raw)
                    codes = pad_to_capacity(d.encode(fill), page_capacity, 0)
                    cols.append(Column.from_numpy(codes, ch.type, valid, d))
                else:
                    arr = pad_to_capacity(np.asarray(raw, T.to_numpy_dtype(ch.type)),
                               page_capacity, 0)
                    cols.append(Column.from_numpy(arr, ch.type, valid))
            yield Page(tuple(cols), n)
            off = hi
            if off >= end:
                break


class MemoryPageSink(ConnectorPageSink):
    """Staged, token-deduplicated sink (MemoryPageSinkProvider rethought
    for retried writes): appended pages decode to host columns in the
    SINK, not the table — finish() commits the whole staging atomically
    under the table lock, once per write token. A failed attempt's
    abort() (or simply dropping the sink) leaves the table untouched,
    and a token that already committed commits again as a no-op — the
    two halves of duplicate-free QUERY-level write retry."""

    def __init__(self, stored: _StoredTable, lock: threading.Lock,
                 write_token: Optional[str] = None):
        self._stored = stored
        self._lock = lock
        self._token = write_token
        # staged per column: (filled values, nulls mask) chunks
        self._staged: List[List] = [[] for _ in stored.metadata.columns]

    def append_page(self, page: Page):
        stored = self._stored
        n = int(page.num_rows)
        if n == 0:
            return
        for i, col in enumerate(page.columns):
            vals = col.to_numpy(n)  # decoded objects incl. None
            typ = stored.metadata.columns[i].type
            nulls = np.array([v is None for v in vals], dtype=bool)
            if T.is_string(typ):
                filled = np.asarray(
                    ["" if v is None else v for v in vals], dtype=object)
            else:
                filled = np.asarray(
                    [0 if v is None else v for v in vals],
                    dtype=T.to_numpy_dtype(typ))
            self._staged[i].append((filled, nulls))

    def finish(self):
        stored = self._stored
        staged, self._staged = self._staged, [
            [] for _ in stored.metadata.columns]
        with self._lock:
            if self._token is not None and \
                    not stored.committed_tokens.commit(self._token):
                return   # an earlier attempt already committed
            for i, chunks in enumerate(staged):
                if not chunks:
                    continue
                typ = stored.metadata.columns[i].type
                filled = np.concatenate([c[0] for c in chunks])
                nulls = np.concatenate([c[1] for c in chunks])
                if T.is_string(typ):
                    stored.dictionaries[i] = None  # pool changes; lazy
                stored.arrays[i] = np.concatenate(
                    [stored.arrays[i], filled])
                if nulls.any() or stored.valids[i] is not None:
                    old_valid = stored.valids[i]
                    if old_valid is None:
                        old_valid = np.ones(
                            len(stored.arrays[i]) - len(filled),
                            dtype=bool)
                    stored.valids[i] = np.concatenate([old_valid, ~nulls])

    def abort(self):
        self._staged = [[] for _ in self._stored.metadata.columns]


class MemoryConnector(Connector):
    # staged write-token sink above: the engine may retry writes here
    idempotent_writes = True

    def __init__(self):
        metadata = MemoryMetadata()
        super().__init__("memory", metadata, MemorySplitManager(metadata),
                         MemoryPageSource(metadata))
        self._metadata = metadata

    def page_sink(self, handle: ConnectorTableHandle,
                  write_token: Optional[str] = None) -> ConnectorPageSink:
        return MemoryPageSink(self._metadata.stored(handle.name),
                              self._metadata._lock, write_token)


def create_connector() -> Connector:
    return MemoryConnector()
