"""Query + node memory accounting with a low-memory killer.

Reference parity: memory/MemoryPool.java:44 + lib/trino-memory-context
(AggregatedMemoryContext tree) + memory/ClusterMemoryManager.java with
memory/TotalReservationLowMemoryKiller.java — accounting is hierarchical:
every blocking materialization (join build side, aggregation/sort/window
collect, exchange buffers) reserves its page bytes against the query's
`query_max_memory` ledger AND the process-wide `NodeMemoryPool`. A
reservation that would overflow the query limit fails the query with the
reference's "Query exceeded per-node memory limit" error; one that would
overflow the NODE pool invokes the low-memory killer, which picks a victim
query by policy (`total-reservation`: the largest ledger) and fails it with
CLUSTER_OUT_OF_MEMORY — retryable, so retry_policy=QUERY re-runs the
victim once the pressure clears.

TPU framing: the pool models one chip's HBM, the scarce resource a fused
streaming pipeline does NOT consume (pages flow through one kernel) but
blocking operators do. Reservations are tracked per operator tag so errors
name the offender, and freed when an operator's output is consumed
(operator scopes call free()). At query end the ledger must read zero; a
nonzero ledger on a successful query is a reservation LEAK, surfaced as a
query warning and counted on the pool (system.runtime.nodes).
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional, Tuple

from trino_tpu.errors import (CLUSTER_OUT_OF_MEMORY,
                              EXCEEDED_LOCAL_MEMORY_LIMIT, TrinoError)


class ExceededMemoryLimitError(TrinoError, RuntimeError):
    """io.trino.ExceededMemoryLimitException analog (RuntimeError kept in
    the bases for pre-taxonomy callers)."""

    CODE = EXCEEDED_LOCAL_MEMORY_LIMIT


class ClusterOutOfMemoryError(TrinoError, RuntimeError):
    """The low-memory killer's verdict: this query was selected (or timed
    out waiting for a victim's release) when a reservation would overflow
    the NODE pool. Retryable — re-running after the pressure clears may
    succeed (ClusterMemoryManager kill + FTE retry contract)."""

    CODE = CLUSTER_OUT_OF_MEMORY


@contextlib.contextmanager
def degrade_to_spill(session):
    """Graceful degradation for a fragment retry after an
    ExceededMemoryLimitError / ClusterOutOfMemoryError: force the spill
    path on and pull every spill threshold under the memory limit, so
    blocking operators flush to host partitions instead of materializing
    over-limit device pages (TaskExecutor's revoke-memory-then-retry
    analog). Restores the session's property bag on exit."""
    saved = dict(session.properties)
    limit = int(session.get("query_max_memory"))
    threshold = max(1, limit // 4)
    session.properties["spill_enabled"] = True
    for prop in ("join_spill_threshold_bytes", "agg_spill_threshold_bytes",
                 "sort_spill_threshold_bytes"):
        session.properties[prop] = min(int(session.get(prop)), threshold)
    try:
        yield
    finally:
        session.properties.clear()
        session.properties.update(saved)


def _fmt_bytes(n: int) -> str:
    units = ("B", "kB", "MB", "GB", "TB")
    v = float(n)
    for u in units:
        if abs(v) < 1024 or u == units[-1]:
            return f"{int(v)}{u}" if u == "B" else f"{v:.2f}{u}"
        v /= 1024
    return f"{n}B"


def page_bytes(page) -> int:
    """Device bytes of one Page (sum of Column.nbytes)."""
    return sum(col.nbytes for col in page.columns)


def live_page_bytes(page, rows: int) -> int:
    """Data bytes of the LIVE rows of a Page: pages are capacity-padded
    (Page.filter keeps its input capacity), so raw Column.nbytes measures
    padding too — stats counters must scale to the live row count or a
    2-row selective result reports megabytes."""
    cap = max(int(page.capacity), 1)
    return page_bytes(page) * int(rows) // cap


class NodeMemoryPool:
    """Process-wide reservation pool all queries share (MemoryPool.java +
    ClusterMemoryManager collapsed to the single-node case).

    `limit` is the node's reservable byte budget (None = unbounded — the
    engine's default, since tests and direct runners size their own
    queries). When a reservation would overflow the pool, the low-memory
    killer picks a victim by `killer_policy`:

      total-reservation  kill the query with the largest ledger
                         (TotalReservationLowMemoryKiller)
      none               never kill; the requester fails

    The victim is marked killed (it raises ClusterOutOfMemoryError at its
    next reservation or cooperative checkpoint) and the requester WAITS for
    the victim's unwind to release bytes, up to its `wait_s`; a timeout
    fails the requester with the same retryable error.
    """

    def __init__(self, limit_bytes: Optional[int] = None,
                 killer_policy: str = "total-reservation"):
        self._cond = threading.Condition()
        self.limit = limit_bytes
        self.killer_policy = killer_policy
        self.reserved = 0
        self.peak = 0
        self.kills = 0          # victims selected by the killer
        self.leaks = 0          # successful queries that ended nonzero
        self.leaked_bytes = 0
        # where the limit came from: "default" (unbounded / hand-set) or
        # "measured" (sized from the backend's reported per-device memory
        # minus the scan-cache budget at startup — autosize_node_pool)
        self.budget_source = "default"
        # when True (set by autosize_node_pool), `limit` is ONE chip's
        # HBM budget and device-hinted reservations are enforced against
        # THAT chip's running total — a mesh query staging n shards must
        # not trip a single-chip limit with the cross-chip sum. Hand-set
        # limits (tests, chaos harnesses, explicit server config) keep
        # the historical global-sum enforcement.
        self.enforce_per_device = False
        # per-chip accounting: reservations carrying a device hint (mesh
        # shard executors, sharded staging) attribute bytes to the chip
        # that holds them. The pool `limit` models ONE chip's HBM, so the
        # per-device gauges are what say whether any single chip is near
        # its budget. Advisory after attempt rollbacks (like by_tag).
        self.device_reserved: Dict[int, int] = {}
        self.device_peak: Dict[int, int] = {}
        # HBM pinned by cross-query caches (the device-resident table
        # cache, exec/table_cache.py): tracked SEPARATELY from query
        # reservations — cache residency outlives queries, so it must
        # not trip the per-query leak detector — but counted against
        # the pool limit at admission time, so a cache can never pin
        # HBM a live query's reservation was promised
        self.cache_reserved = 0
        self.device_cache_reserved: Dict[int, int] = {}
        self._contexts: Dict[str, "QueryMemoryContext"] = {}

    # ------------------------------------------------------- configuration

    def set_limit(self, limit_bytes: Optional[int]) -> None:
        with self._cond:
            self.limit = limit_bytes
            self._cond.notify_all()

    @contextlib.contextmanager
    def limited(self, limit_bytes: Optional[int],
                killer_policy: Optional[str] = None):
        """Scoped pool reconfiguration (tests / chaos harnesses)."""
        with self._cond:
            saved = (self.limit, self.killer_policy)
            self.limit = limit_bytes
            if killer_policy is not None:
                self.killer_policy = killer_policy
            self._cond.notify_all()
        try:
            yield self
        finally:
            with self._cond:
                self.limit, self.killer_policy = saved
                self._cond.notify_all()

    # -------------------------------------------------------- registration

    def register(self, ctx: "QueryMemoryContext") -> None:
        with self._cond:
            self._contexts[ctx.query_id] = ctx

    def unregister(self, ctx: "QueryMemoryContext") -> None:
        with self._cond:
            if self._contexts.get(ctx.query_id) is ctx:
                del self._contexts[ctx.query_id]
            self._cond.notify_all()

    def reserved_of(self, query_id: str) -> int:
        ctx = self._contexts.get(query_id)
        return ctx.reserved if ctx is not None else 0

    # ----------------------------------------------------------- the pool

    def acquire(self, ctx: "QueryMemoryContext", nbytes: int, tag: str,
                wait_s: float, device: Optional[int] = None) -> None:
        """Grant `nbytes` to `ctx` or raise ClusterOutOfMemoryError.

        Runs the low-memory killer when the pool would overflow; blocks
        (releasing the pool lock) while a marked victim unwinds."""
        deadline: Optional[float] = None
        with self._cond:
            while True:
                if ctx.kill_reason is not None:
                    raise ClusterOutOfMemoryError(ctx.kill_reason)
                if self.enforce_per_device and device is not None:
                    # per-chip budget: this chip's total is what the
                    # limit bounds (the global sum spans n chips' HBM)
                    current = self.device_reserved.get(device, 0)
                else:
                    current = self.reserved
                if self.limit is None or current + nbytes <= self.limit:
                    self.reserved += nbytes
                    self.peak = max(self.peak, self.reserved)
                    if device is not None:
                        d = self.device_reserved.get(device, 0) + nbytes
                        self.device_reserved[device] = d
                        self.device_peak[device] = max(
                            self.device_peak.get(device, 0), d)
                    return
                # kill at most ONE victim per pressure event: while a
                # marked victim still holds bytes, spurious wakeups (any
                # unrelated free() notifies) must WAIT for its unwind,
                # not cascade-kill the rest of the fleet
                if not any(c.kill_reason is not None and c.reserved > 0
                           for c in self._contexts.values()):
                    if self.killer_policy == "none":
                        # never kill: the requester fails, and NO kill
                        # is recorded (pool_kills must read zero on a
                        # node whose killer is disabled)
                        raise ClusterOutOfMemoryError(
                            f"node memory pool exhausted (killer "
                            f"disabled): [{tag}] requested "
                            f"{_fmt_bytes(nbytes)} with "
                            f"{_fmt_bytes(self.reserved)}/"
                            f"{_fmt_bytes(self.limit)} reserved")
                    victim = self._select_victim_locked()
                    if victim is None or victim is ctx:
                        # the requester itself is the largest reservation
                        # (or nothing is killable): self-inflicted
                        # pressure — fail the requester; its retry
                        # re-runs with spill forced
                        self._kill_locked(ctx, nbytes, tag, ctx)
                        raise ClusterOutOfMemoryError(ctx.kill_reason)
                    self._kill_locked(victim, nbytes, tag, ctx)
                if deadline is None:
                    deadline = time.monotonic() + max(0.0, wait_s)
                remaining = deadline - time.monotonic()
                if remaining <= 0 or not self._cond.wait(remaining):
                    raise ClusterOutOfMemoryError(
                        f"node memory pool exhausted: [{tag}] requested "
                        f"{_fmt_bytes(nbytes)} with {_fmt_bytes(self.reserved)}"
                        f"/{_fmt_bytes(self.limit)} reserved and no victim "
                        f"released within {wait_s:.1f}s")

    def release(self, nbytes: int, device: Optional[int] = None) -> None:
        if nbytes <= 0:
            return
        with self._cond:
            self.reserved = max(0, self.reserved - nbytes)
            if device is not None:
                self.device_reserved[device] = max(
                    0, self.device_reserved.get(device, 0) - nbytes)
            self._cond.notify_all()

    # ----------------------------------------------- cache residency

    def reserve_cache(self, nbytes: int,
                      device: Optional[int] = None) -> bool:
        """Admit `nbytes` of cross-query cache residency (the HBM table
        cache) against the pool budget. Never kills and never blocks —
        a cache that cannot fit simply isn't admitted (returns False);
        live queries always win the HBM."""
        nbytes = int(nbytes)
        if nbytes <= 0:
            return True
        with self._cond:
            if self.limit is not None:
                if self.enforce_per_device and device is not None:
                    current = (self.device_reserved.get(device, 0)
                               + self.device_cache_reserved.get(device, 0))
                else:
                    current = self.reserved + self.cache_reserved
                if current + nbytes > self.limit:
                    return False
            self.cache_reserved += nbytes
            key = device if device is not None else 0
            self.device_cache_reserved[key] = \
                self.device_cache_reserved.get(key, 0) + nbytes
            return True

    def free_cache(self, nbytes: int, device: Optional[int] = None) -> None:
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        with self._cond:
            self.cache_reserved = max(0, self.cache_reserved - nbytes)
            key = device if device is not None else 0
            self.device_cache_reserved[key] = max(
                0, self.device_cache_reserved.get(key, 0) - nbytes)
            self._cond.notify_all()

    def reset_context(self, ctx: "QueryMemoryContext") -> None:
        """Atomically drop ALL of a context's reservation and clear its
        kill mark (between retry attempts): a killed victim must hand
        back every byte the killer wanted — and the mark must clear
        under the pool lock so it can't race a concurrent re-kill."""
        with self._cond:
            delta = ctx.reserved
            ctx.reserved = 0
            ctx.kill_reason = None
            self.reserved = max(0, self.reserved - delta)
            for d, b in ctx.by_device.items():
                self.device_reserved[d] = max(
                    0, self.device_reserved.get(d, 0) - b)
            ctx.by_device.clear()
            self._cond.notify_all()

    # ---------------------------------------------------------- the killer

    def _select_victim_locked(self) -> Optional["QueryMemoryContext"]:
        if self.killer_policy == "none":
            return None
        # total-reservation: largest live ledger not already marked
        best = None
        for c in self._contexts.values():
            if c.kill_reason is not None or c.reserved <= 0:
                continue
            if best is None or c.reserved > best.reserved:
                best = c
        return best

    def _kill_locked(self, victim: "QueryMemoryContext", nbytes: int,
                     tag: str, requester: "QueryMemoryContext") -> None:
        if victim.kill_reason is not None:
            return
        victim.kill_reason = (
            f"Query killed because the node is out of memory (low-memory "
            f"killer, policy {self.killer_policy}): query "
            f"{requester.query_id} [{tag}] requested {_fmt_bytes(nbytes)} "
            f"with {_fmt_bytes(self.reserved)}/{_fmt_bytes(self.limit)} "
            f"reserved; victim {victim.query_id} held "
            f"{_fmt_bytes(victim.reserved)}. Please retry in a few minutes")
        victim.kills += 1
        self.kills += 1
        # wake the victim if it is itself blocked in acquire()
        self._cond.notify_all()

    def record_leak(self, nbytes: int) -> None:
        with self._cond:
            self.leaks += 1
            self.leaked_bytes += nbytes


# the process-wide pool (the single node's HBM budget; unbounded until a
# server/operator sizes it — LocalMemoryManager singleton scope)
NODE_POOL = NodeMemoryPool()


def measured_device_memory_bytes() -> Optional[int]:
    """The backend's reported per-device memory capacity (TPU HBM via
    device.memory_stats()['bytes_limit']); None when the backend doesn't
    report (the CPU backend, including the forced 8-device dev mesh)."""
    try:
        import jax
        dev = jax.local_devices()[0]
        stats = dev.memory_stats()
        if stats:
            limit = int(stats.get("bytes_limit") or 0)
            return limit or None
    except Exception:
        return None
    return None


def autosize_node_pool(scan_cache_budget: Optional[int] = None,
                       pool: Optional[NodeMemoryPool] = None
                       ) -> Tuple[Optional[int], str]:
    """Size the node pool from the backend's MEASURED per-device memory
    at startup (replacing any hand-tuned constant): per-chip budget =
    measured HBM minus the connector scan-cache budget (the staged-column
    LRU owns that slice of HBM by design), floored at a quarter of the
    chip so a misconfigured cache budget can't zero the pool. Backends
    that don't report capacity (CPU) keep the current static default and
    return source "default". Returns (limit_bytes, source); the chosen
    budget and source surface in system.runtime.nodes and /v1/metrics."""
    pool = pool if pool is not None else NODE_POOL
    measured = measured_device_memory_bytes()
    if measured is None:
        pool.budget_source = "default"
        return pool.limit, "default"
    if scan_cache_budget is None:
        try:
            from trino_tpu.connector import tpch
            scan_cache_budget = int(tpch._DEVICE_COL_CACHE_BYTES)
        except Exception:
            scan_cache_budget = 0
    limit = max(measured - int(scan_cache_budget), measured // 4)
    pool.set_limit(limit)
    pool.budget_source = "measured"
    # the measured limit is PER-CHIP HBM: device-hinted reservations
    # (mesh shards) enforce against their chip's total, not the mesh sum
    pool.enforce_per_device = True
    return limit, "measured"


class QueryMemoryContext:
    """Single-query reservation ledger checked against query_max_memory,
    mirrored into a NodeMemoryPool when one is attached (the query level
    of the query→operator→node hierarchy; by_tag is the operator level).

    Mutations come from the query's own executor thread; the killer thread
    only writes `kill_reason`/`kills` under the pool lock."""

    _anon = 0

    def __init__(self, limit_bytes: Optional[int],
                 query_id: Optional[str] = None,
                 pool: Optional[NodeMemoryPool] = None,
                 wait_s: float = 2.0):
        self.limit = int(limit_bytes) if limit_bytes is not None else None
        self.reserved = 0
        self.peak = 0
        self.by_tag: Dict[str, int] = {}
        self.by_device: Dict[int, int] = {}
        if not query_id:
            QueryMemoryContext._anon += 1
            query_id = f"ctx_{QueryMemoryContext._anon}"
        self.query_id = query_id
        self.pool = pool
        self.wait_s = float(wait_s)
        self.kill_reason: Optional[str] = None
        self.kills = 0          # times this query was selected as victim
        if pool is not None:
            pool.register(self)

    def reserve(self, nbytes: int, tag: str = "operator",
                device: Optional[int] = None) -> None:
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        if self.kill_reason is not None:
            raise ClusterOutOfMemoryError(self.kill_reason)
        if self.limit is not None and self.reserved + nbytes > self.limit:
            raise ExceededMemoryLimitError(
                f"Query exceeded per-node memory limit of "
                f"{_fmt_bytes(self.limit)} [{tag} requested "
                f"{_fmt_bytes(nbytes)}, reserved "
                f"{_fmt_bytes(self.reserved)}]")
        if self.pool is not None:
            self.pool.acquire(self, nbytes, tag, self.wait_s, device)
        self.reserved += nbytes
        self.by_tag[tag] = self.by_tag.get(tag, 0) + nbytes
        if device is not None:
            self.by_device[device] = self.by_device.get(device, 0) + nbytes
        self.peak = max(self.peak, self.reserved)

    def free(self, nbytes: int, tag: str = "operator",
             device: Optional[int] = None) -> None:
        nbytes = int(nbytes)
        released = min(max(nbytes, 0), self.reserved)
        self.reserved -= released
        if tag in self.by_tag:
            self.by_tag[tag] = max(0, self.by_tag[tag] - nbytes)
        if device is not None:
            self.by_device[device] = max(
                0, self.by_device.get(device, 0) - nbytes)
        if self.pool is not None:
            self.pool.release(released, device)

    def poll(self) -> None:
        """Cooperative kill checkpoint: raise if the low-memory killer (or
        a `memory` fault site) marked this query."""
        if self.kill_reason is not None:
            raise ClusterOutOfMemoryError(self.kill_reason)

    def clear_kill(self) -> None:
        """Clear the kill mark under the pool lock (a task-scope retry is
        about to re-run): unlocked clearing could race a concurrent
        re-kill and leave a requester waiting on a victim that never
        unwinds."""
        if self.pool is not None:
            with self.pool._cond:
                self.kill_reason = None
                self.pool._cond.notify_all()
        else:
            self.kill_reason = None

    def rollback_to(self, mark: int) -> None:
        """Release everything reserved past `mark` back to the pool — a
        failed attempt's unfreed reservations must not stack across
        retries. (by_tag is advisory after a rollback: it names offenders
        in error messages, it is not the ledger.)"""
        delta = self.reserved - int(mark)
        if delta <= 0:
            return
        self.reserved = int(mark)
        if self.pool is not None:
            self.pool.release(delta)

    def reset_attempt(self) -> None:
        """Between retry attempts: drop the failed attempt's reservations
        and clear a kill mark so the re-run starts clean (all bytes go
        back to the pool — a killed victim releases what the killer was
        reclaiming, not just its latest task's delta)."""
        if self.pool is not None:
            self.pool.reset_context(self)
        else:
            self.reserved = 0
            self.kill_reason = None
        self.by_tag.clear()

    def close(self) -> int:
        """Query end: the ledger must read zero. Returns the leaked byte
        count (0 when clean), releases any remainder back to the pool, and
        unregisters from it."""
        leaked = self.reserved
        self.rollback_to(0)
        if self.pool is not None:
            self.pool.unregister(self)
        return leaked
