"""Cross-process cache tier: an mmap'd result cache shared by the fleet.

Reference parity: the reference engine scales its front door by putting a
dispatcher in front of many coordinators; the per-coordinator state that
makes the fast path fast (result sets, prepared statements) is external
(client-side or a fronting cache). Here the fleet's worker processes
share ONE file-backed mmap region so a result the engine computed once
is answerable by EVERY worker with zero IPC on the hit path — a read is
a couple of cache-line loads plus an unpickle, no socket, no lock.

Layout (one file, created by the fleet parent, mapped by every member):

    HEADER      generation counter, ring-allocator cursor, geometry
    TABLE GENS  open-addressed (table-hash -> last-invalidation gen)
    SLOTS       open-addressed (key-hash -> seq, data offset, put gen)
    QUOTA       open-addressed token buckets (group-hash -> tokens, stamp)
    DATA        ring-allocated pickled (tables, CachedResult) records

Concurrency model: writers (the engine publishing results, invalidation,
quota acquire) serialize through an fcntl lock on the backing file;
readers are LOCK-FREE and validate with a seqlock — each slot carries a
sequence number that goes odd while the slot (or the data it points at)
is being rewritten, so a reader that raced a writer re-reads the
sequence after copying the payload and retries/misses on a mismatch.
Torn data is additionally caught by the key hash embedded at the front
of every data record, and (v2) by a blake2b content digest embedded per
record: an entry whose payload bytes do not hash to the recorded digest
— a torn write that beat the seqlock, a flipped bit in the backing file,
a record half-overwritten by a crashed writer — is a COUNTED cache miss
(stats["corrupt"], exported as trino_tpu_fleet_shm_corrupt_total), never
an unpickle exception through a worker's hit path.

Invalidation reuses the `_GenerationGuard` discipline from
exec/plan_cache.py, lifted across process boundaries: `generation()`
snapshots the global counter BEFORE the work whose output will be
published; `put()` rejects when any referenced table was invalidated
since; `get()` re-validates every entry's tables against the live
table-generation region AT READ TIME. A stale publish — a result
computed against pre-INSERT data landing after the INSERT's
invalidation — is therefore structurally impossible fleet-wide, not
just per process, and a worker that missed a bus message can never
serve stale data (the bus is advisory; the generation check is the
authority).
"""

from __future__ import annotations

import fcntl
import hashlib
import mmap
import os
import pickle
import struct
import threading
import time
from typing import Any, Iterable, Optional, Tuple

MAGIC = b"TPUFLEET"
# v2: data records carry a blake2b-16 payload digest between the length
# and the pickled payload (record = key_hash16 + len u32 + digest16 +
# payload). Version-checked at map time, so a v1 file from an older
# fleet process is rejected, not misread.
VERSION = 2
_REC_OVERHEAD = 36      # key_hash(16) + len(4) + digest(16)

HEADER_FMT = "<8sIIIIQQQQQQQ"           # magic, ver, slots, tslots, qslots,
HEADER_SIZE = 128                       # data_off, data_size, head, gen,
                                        # flush_gen, puts, invalidations
TABLE_REC = 32     # hash16 + gen u64 + pad
SLOT_REC = 48      # seq u32 + len u32 + hash16 + offset u64 + put_gen u64
QUOTA_REC = 48     # hash16 + tokens f64 + stamp f64 + pad
PROBE = 32         # max open-addressing probe distance

DEFAULT_SLOTS = 4096
DEFAULT_TABLE_SLOTS = 512
DEFAULT_QUOTA_SLOTS = 256
DEFAULT_DATA_BYTES = 64 << 20


def key_fingerprint(key: Any) -> bytes:
    """Stable 16-byte digest of a cache key, identical across processes.

    Keys are the runner's result-cache keys — nested tuples of
    primitives, type-display strings, and literal values. `repr` is
    value-deterministic for those (pickle is NOT: its memo encodes
    object identity, so two processes building equal keys from interned
    vs. non-interned strings would hash differently)."""
    return hashlib.blake2b(repr(key).encode(), digest_size=16).digest()


def table_fingerprint(table: Tuple[str, str, str]) -> bytes:
    return hashlib.blake2b(repr(tuple(table)).encode(),
                           digest_size=16).digest()


def group_fingerprint(group: str) -> bytes:
    return hashlib.blake2b(f"group:{group}".encode(),
                           digest_size=16).digest()


class SharedCacheTier:
    """One member's view of the fleet cache file (engine or worker)."""

    def __init__(self, path: str, create: bool = False,
                 slots: int = DEFAULT_SLOTS,
                 table_slots: int = DEFAULT_TABLE_SLOTS,
                 quota_slots: int = DEFAULT_QUOTA_SLOTS,
                 data_bytes: int = DEFAULT_DATA_BYTES):
        self.path = path
        self._wlock = threading.Lock()   # in-process writer serialization
        if create:
            self._create(path, slots, table_slots, quota_slots, data_bytes)
        self._fd = os.open(path, os.O_RDWR)
        total = os.fstat(self._fd).st_size
        self._mm = mmap.mmap(self._fd, total)
        hdr = struct.unpack_from(HEADER_FMT, self._mm, 0)
        if hdr[0] != MAGIC or hdr[1] != VERSION:
            raise ValueError(f"not a fleet cache file: {path}")
        self.slots = hdr[2]
        self.table_slots = hdr[3]
        self.quota_slots = hdr[4]
        self.data_off = hdr[5]
        self.data_size = hdr[6]
        self.table_off = HEADER_SIZE
        self.slot_off = self.table_off + self.table_slots * TABLE_REC
        self.quota_off = self.slot_off + self.slots * SLOT_REC
        # process-local traffic counters (obs gauges; fleet status)
        self.stats = {"hits": 0, "misses": 0, "puts": 0, "put_rejects": 0,
                      "invalidations": 0, "quota_rejections": 0,
                      "corrupt": 0}

    @staticmethod
    def _create(path, slots, table_slots, quota_slots, data_bytes):
        data_off = (HEADER_SIZE + table_slots * TABLE_REC
                    + slots * SLOT_REC + quota_slots * QUOTA_REC)
        total = data_off + data_bytes
        fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
        try:
            os.ftruncate(fd, total)
            header = struct.pack(HEADER_FMT, MAGIC, VERSION, slots,
                                 table_slots, quota_slots, data_off,
                                 data_bytes, 0, 0, 0, 0, 0)
            os.pwrite(fd, header, 0)
        finally:
            os.close(fd)

    def close(self) -> None:
        try:
            self._mm.close()
        finally:
            os.close(self._fd)

    # ------------------------------------------------------ header fields

    def _u64(self, off: int) -> int:
        return struct.unpack_from("<Q", self._mm, off)[0]

    def _put_u64(self, off: int, value: int) -> None:
        struct.pack_into("<Q", self._mm, off, value)

    # header u64 field offsets (after magic/ver/counts = 8+4+4+4+4 = 24)
    _OFF_DATA_OFF = 24
    _OFF_DATA_SIZE = 32
    _OFF_HEAD = 40
    _OFF_GEN = 48
    _OFF_FLUSH_GEN = 56
    _OFF_PUTS = 64
    _OFF_INVALIDATIONS = 72

    def generation(self) -> int:
        """Global invalidation generation — snapshot BEFORE the work
        whose output will be published (the _GenerationGuard contract)."""
        return self._u64(self._OFF_GEN)

    class _locked:
        """fcntl write lock on the backing file + the in-process mutex
        (flock is per-fd/process; two threads of one process must not
        both think they hold it)."""

        def __init__(self, tier):
            self.tier = tier

        def __enter__(self):
            self.tier._wlock.acquire()
            fcntl.flock(self.tier._fd, fcntl.LOCK_EX)

        def __exit__(self, *exc):
            fcntl.flock(self.tier._fd, fcntl.LOCK_UN)
            self.tier._wlock.release()

    # -------------------------------------------------- table generations

    def _table_probe(self, digest: bytes) -> Iterable[int]:
        base = int.from_bytes(digest[:8], "little") % self.table_slots
        for i in range(min(PROBE, self.table_slots)):
            yield self.table_off + ((base + i) % self.table_slots) * TABLE_REC

    def table_generation(self, table) -> int:
        """Last invalidation generation recorded for `table` (0 = never
        invalidated since the file was created)."""
        digest = table_fingerprint(table)
        for off in self._table_probe(digest):
            stored = self._mm[off:off + 16]
            if stored == digest:
                return self._u64(off + 16)
            if stored == b"\x00" * 16:
                return 0
        return 0    # probe chain exhausted without a match

    def invalidate(self, table) -> None:
        """Bump the global generation and stamp it on the table's slot.
        If the (bounded) table region is full, fall back to the nuclear
        flush generation — EVERY entry older than this moment becomes
        invalid, which is conservative but never stale."""
        digest = table_fingerprint(table)
        with self._locked(self):
            gen = self._u64(self._OFF_GEN) + 1
            self._put_u64(self._OFF_GEN, gen)
            self._put_u64(self._OFF_INVALIDATIONS,
                          self._u64(self._OFF_INVALIDATIONS) + 1)
            for off in self._table_probe(digest):
                stored = self._mm[off:off + 16]
                if stored == digest or stored == b"\x00" * 16:
                    self._mm[off:off + 16] = digest
                    self._put_u64(off + 16, gen)
                    break
            else:
                self._put_u64(self._OFF_FLUSH_GEN, gen)
        self.stats["invalidations"] += 1

    def _entry_valid(self, put_gen: int, tables) -> bool:
        if self._u64(self._OFF_FLUSH_GEN) > put_gen:
            return False
        return all(self.table_generation(tk) <= put_gen for tk in tables)

    # -------------------------------------------------------- result slots

    def _slot_probe(self, digest: bytes) -> Iterable[int]:
        base = int.from_bytes(digest[:8], "little") % self.slots
        for i in range(min(PROBE, self.slots)):
            yield self.slot_off + ((base + i) % self.slots) * SLOT_REC

    def put(self, key_hash: bytes, entry: Any, tables, gen: Optional[int]
            ) -> bool:
        """Publish a pickled (tables, entry) record under `key_hash`.
        `gen` is the generation snapshot taken before the execution that
        produced `entry`; a concurrent invalidation of any referenced
        table since then rejects the publish (stale-publish guard)."""
        tables = tuple(sorted(tuple(tk) for tk in tables))
        payload = pickle.dumps((tables, entry), protocol=4)
        record = (key_hash + struct.pack("<I", len(payload))
                  + hashlib.blake2b(payload, digest_size=16).digest()
                  + payload)
        if len(record) > self.data_size // 2:
            return False    # one oversized result must not wipe the ring
        with self._locked(self):
            if gen is not None:
                flush = self._u64(self._OFF_FLUSH_GEN)
                if flush > gen or any(
                        self.table_generation(tk) > gen for tk in tables):
                    self.stats["put_rejects"] += 1
                    return False
            start = self._alloc_locked(len(record))
            self._mm[self.data_off + start:
                     self.data_off + start + len(record)] = record
            self._write_slot_locked(key_hash, start, len(record),
                                    self._u64(self._OFF_GEN))
            self._put_u64(self._OFF_PUTS, self._u64(self._OFF_PUTS) + 1)
        self.stats["puts"] += 1
        return True

    def _alloc_locked(self, n: int) -> int:
        """Ring-allocate `n` contiguous bytes in the data region; any
        live slot whose record the allocation (or a wrap skip) would
        overwrite is killed first, so a concurrent reader can only ever
        observe a bumped sequence, never silently-swapped bytes.

        ORDERING CONTRACT (writer-side integrity): _kill_overlaps_locked
        runs — bumping each overlapped slot's seq and zeroing its length
        — strictly BEFORE the caller writes the new record's bytes into
        the heap range this returns. A reader racing the wrap therefore
        either sees the old seq with the old intact bytes, or the bumped
        seq (retry/miss); it can never validate old slot metadata
        against new heap bytes. test_integrity.py forces a ring wrap
        under concurrent readers to pin this ordering."""
        head = self._u64(self._OFF_HEAD)
        start = head % self.data_size
        ranges = []
        if start + n > self.data_size:
            ranges.append((start, self.data_size))    # wrap skip is dead
            head += self.data_size - start
            start = 0
        ranges.append((start, start + n))
        self._kill_overlaps_locked(ranges)
        self._put_u64(self._OFF_HEAD, head + n)
        return start

    def _kill_overlaps_locked(self, ranges) -> None:
        # one contiguous read of the slot region + iter_unpack, not
        # `slots` individual unpack_from calls: this scan runs on EVERY
        # put while holding the fleet-wide flock that quota try_acquire
        # also serializes through, so its constant factor is what a
        # publish stalls the whole fleet's quota-checked hit path by
        region = bytes(self._mm[self.slot_off:
                                self.slot_off + self.slots * SLOT_REC])
        for i, rec in enumerate(struct.iter_unpack("<II16sQQQ", region)):
            seq, length, _, rec_off, _, _ = rec
            if length == 0:
                continue
            for lo, hi in ranges:
                if rec_off < hi and rec_off + length > lo:
                    off = self.slot_off + i * SLOT_REC
                    struct.pack_into("<II", self._mm, off, seq + 2, 0)
                    self._mm[off + 8:off + 24] = b"\x00" * 16
                    break

    def _write_slot_locked(self, key_hash, rec_off, length, put_gen):
        target = reuse = None
        for off in self._slot_probe(key_hash):
            stored = self._mm[off + 8:off + 24]
            if stored == key_hash:
                target = off
                break
            length_here = struct.unpack_from("<I", self._mm, off + 4)[0]
            if reuse is None and (stored == b"\x00" * 16
                                  or length_here == 0):
                reuse = off
        if target is None:
            target = reuse if reuse is not None else \
                next(iter(self._slot_probe(key_hash)))    # evict chain head
        seq = struct.unpack_from("<I", self._mm, target)[0]
        struct.pack_into("<I", self._mm, target, seq + 1)      # odd: writing
        self._mm[target + 8:target + 24] = key_hash
        self._put_u64(target + 24, rec_off)
        self._put_u64(target + 32, put_gen)
        struct.pack_into("<I", self._mm, target + 4, length)
        struct.pack_into("<I", self._mm, target, seq + 2)      # even: live

    def peek_slot(self, key_hash: bytes) -> Optional[Tuple[int, int]]:
        """(seq, put_gen) of the live slot for `key_hash`, or None — the
        cheap revalidation read a worker's hot local copy rides on."""
        for off in self._slot_probe(key_hash):
            seq = struct.unpack_from("<I", self._mm, off)[0]
            if seq & 1:
                return None
            if self._mm[off + 8:off + 24] == key_hash:
                if struct.unpack_from("<I", self._mm, off + 4)[0] == 0:
                    return None
                return seq, self._u64(off + 32)
        return None

    def get(self, key_hash: bytes
            ) -> Optional[Tuple[Any, tuple, int, int]]:
        """Lock-free read: (entry, tables, put_gen, slot_seq) or None.
        Validates the seqlock around the payload copy AND the entry's
        table generations — a hit can never be stale."""
        for _ in range(3):
            found = self._locate(key_hash)
            if found is None:
                self.stats["misses"] += 1
                return None
            slot_off, seq, rec_off, length, put_gen = found
            raw = bytes(self._mm[self.data_off + rec_off:
                                 self.data_off + rec_off + length])
            if struct.unpack_from("<I", self._mm, slot_off)[0] != seq:
                continue    # writer raced the copy — retry
            if raw[:16] != key_hash:
                continue
            (paylen,) = struct.unpack_from("<I", raw, 16)
            if paylen != length - _REC_OVERHEAD:
                continue
            payload = raw[_REC_OVERHEAD:]
            # content digest: the seq re-check above proved the bytes
            # were STABLE during the copy, so a mismatch here is real
            # corruption (torn write from a crashed writer, flipped bit
            # in the backing file) — a counted miss, never an unpickle
            # crash through the hit path, and no point retrying
            if hashlib.blake2b(payload, digest_size=16).digest() \
                    != raw[20:36]:
                self.stats["corrupt"] += 1
                self.stats["misses"] += 1
                return None
            try:
                tables, entry = pickle.loads(payload)
            except Exception:   # digest-clean yet undecodable (pickle
                self.stats["corrupt"] += 1      # written by a buggy or
                self.stats["misses"] += 1       # incompatible writer)
                return None
            if not self._entry_valid(put_gen, tables):
                self.stats["misses"] += 1
                return None
            self.stats["hits"] += 1
            return entry, tables, put_gen, seq
        self.stats["misses"] += 1
        return None

    def _locate(self, key_hash):
        for off in self._slot_probe(key_hash):
            seq = struct.unpack_from("<I", self._mm, off)[0]
            if seq & 1:
                continue
            if self._mm[off + 8:off + 24] != key_hash:
                continue
            length = struct.unpack_from("<I", self._mm, off + 4)[0]
            if length == 0:
                return None
            rec_off = self._u64(off + 24)
            if rec_off + length > self.data_size:
                return None
            return off, seq, rec_off, length, self._u64(off + 32)
        return None

    def entry_count(self) -> int:
        n = 0
        for i in range(self.slots):
            off = self.slot_off + i * SLOT_REC
            if struct.unpack_from("<I", self._mm, off + 4)[0] > 0:
                n += 1
        return n

    # ------------------------------------------------------ quota buckets

    def _quota_probe(self, digest: bytes) -> Iterable[int]:
        base = int.from_bytes(digest[:8], "little") % self.quota_slots
        for i in range(min(PROBE, self.quota_slots)):
            yield self.quota_off + ((base + i) % self.quota_slots) * QUOTA_REC

    def try_acquire(self, group: str, rate: float, burst: float,
                    n: float = 1.0) -> bool:
        """Fleet-wide token bucket for `group`: refill at `rate`
        tokens/s up to `burst`, consume `n`. The bucket state lives in
        shared memory, so the quota binds across every worker process —
        N workers enforcing rate R admit R total, not N*R. Clocked on
        CLOCK_MONOTONIC, which is system-wide on Linux."""
        digest = group_fingerprint(group)
        now = time.monotonic()
        with self._locked(self):
            slot = None
            for off in self._quota_probe(digest):
                stored = self._mm[off:off + 16]
                if stored == digest:
                    slot = off
                    break
                if stored == b"\x00" * 16 and slot is None:
                    slot = off
            if slot is None:
                return True    # quota region full: fail open, never wedge
            if self._mm[slot:slot + 16] != digest:
                self._mm[slot:slot + 16] = digest
                tokens, stamp = burst, now
            else:
                tokens, stamp = struct.unpack_from("<dd", self._mm,
                                                   slot + 16)
                tokens = min(burst, tokens + max(0.0, now - stamp) * rate)
            ok = tokens >= n
            if ok:
                tokens -= n
            struct.pack_into("<dd", self._mm, slot + 16, tokens, now)
        if not ok:
            self.stats["quota_rejections"] += 1
        return ok
