"""Lake connector: directory-backed columnar tables behind the SPI.

Reference parity: plugin/trino-hive / plugin/trino-iceberg collapsed to
the single-node case — a catalog rooted at a base directory, one
directory per table holding immutable columnar data files plus a JSON
MANIFEST that is the single source of truth (Iceberg's metadata
pointer). Everything transactional goes through the manifest:

  - COMMIT IS AN ATOMIC MANIFEST SWAP (write tmp + os.replace): readers
    see the old file list or the new one, never a torn state.
  - The idempotent staged-write-token protocol (PR 8's sink contract):
    a sink stages rows host-side, writes data files under unique names
    at finish(), and appends them to the manifest ONLY if its token has
    not already committed — a replayed INSERT/CTAS attempt (QUERY-level
    retry) deletes its freshly-written orphans and no-ops, so writes
    are exactly-once on files too. abort() deletes the attempt's files.
  - Partitioned tables (CREATE ... WITH (partitioned_by = 'a,b')) split
    each commit's rows by partition value into one file per partition —
    a selective predicate then prunes whole files.

Pruning: every data file carries per-row-group min/max/null-count zone
maps in the manifest. `eligible_files` / `eligible_groups` evaluate the
scan's TupleDomain (static pushdown AND join dynamic filters — the
engine augments the handle's constraint at iteration time) against the
zones; skipped files/groups count into process counters plus a
thread-local the executor drains into the query's stats
(`files_pruned` / `row_groups_pruned`).

Split model: splits index the PRUNED file list (recomputed
deterministically from (manifest, constraint) on both the split-manager
and page-source sides — stateless like every other connector here);
split p of n reads files p, p+n, p+2n, ...

Data-plane integrity (PR 17): every commit records blake2b content
digests — per data file (physical bytes) and per (row group, column)
(canonical decoded content, format.py) — and reads verify them under
`lake_verify_checksums` (off / `row_group` default / `file`). A
mismatch, torn write, or undecodable file raises the classified
LAKE_DATA_CORRUPTION error (never a decode crash, never silent wrong
rows) and quarantines the file in a per-process ledger so repeated
scans fail fast with the path in the error. The manifest itself is a
VERSIONED LOG (the Iceberg metadata-pointer model): each commit writes
an immutable `manifest-<v>.json` plus an atomically-swapped pointer
(`manifest.json`) carrying the version and the manifest's own digest;
the last `lake_manifest_history` versions are retained for
integrity.py's fsck rollback. Split contexts keep pinning the exact
in-memory snapshot, so retention never tears a running query.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
import os
import re
import shutil
import threading
import time
import uuid
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.connector.lake import format as F
from trino_tpu.errors import LakeDataCorruptionError
from trino_tpu.connector.spi import (
    ColumnHandle, ColumnMetadata, ColumnStatistics, Connector,
    ConnectorMetadata, ConnectorPageSink, ConnectorPageSource,
    ConnectorSplitManager, ConnectorTableHandle, SchemaTableName, Split,
    TableMetadata, TableStatistics, WriteTokenLedger, pad_to_capacity)
from trino_tpu.page import Column, Dictionary, Page
from trino_tpu.predicate import TupleDomain

MANIFEST = "manifest.json"           # the atomically-swapped POINTER
DATA_DIR = "data"
_MAX_MANIFEST_TOKENS = 512
# retained manifest versions (fsck rollback depth); session property
# `lake_manifest_history` overrides per commit via set_commit_options
DEFAULT_MANIFEST_HISTORY = 8
# read-side verification level when the executor set none (the session
# default is the same): "off" | "row_group" | "file"
DEFAULT_VERIFY = "row_group"
VERIFY_LEVELS = ("off", "row_group", "file")
_MANIFEST_V = re.compile(r"manifest-(\d+)\.json$")

# process-lifetime counters (obs/metrics.py gauges sample these)
LAKE_STATS = {
    "files_written": 0, "files_scanned": 0, "files_pruned": 0,
    "row_groups_scanned": 0, "row_groups_pruned": 0,
    "manifest_commits": 0, "replayed_commits": 0, "aborted_writes": 0,
    "corruption_detected": 0, "files_quarantined": 0,
}
_STATS_LOCK = threading.Lock()

# per-process corruption quarantine: a file that failed verification
# fails FAST on every later scan (path in the error) until fsck clears
# it — repeated scans must not re-pay the read+hash of provably bad
# bytes, and must never race one lucky page out of a flaky device
_QUARANTINE: Dict[str, str] = {}
_QUARANTINE_LOCK = threading.Lock()


def quarantine_file(path: str, reason: str) -> None:
    path = os.path.abspath(path)
    with _QUARANTINE_LOCK:
        fresh = path not in _QUARANTINE
        _QUARANTINE[path] = reason
    if fresh:
        _count("files_quarantined")


def quarantined_reason(path: str) -> Optional[str]:
    with _QUARANTINE_LOCK:
        return _QUARANTINE.get(os.path.abspath(path))


def clear_quarantine(path: Optional[str] = None) -> None:
    """Drop one path (fsck repaired/GC'd it) or the whole ledger."""
    with _QUARANTINE_LOCK:
        if path is None:
            _QUARANTINE.clear()
        else:
            _QUARANTINE.pop(os.path.abspath(path), None)


def quarantined_files() -> Dict[str, str]:
    with _QUARANTINE_LOCK:
        return dict(_QUARANTINE)


# verified-content ledger: digests are checked ONCE per physical file
# content — keyed on (path, st_mtime_ns, st_size), holding the
# ("file",) marker and (group, column) pairs already proven clean. Data
# files are immutable (commits write new files, never rewrite), so the
# stamp only changes when the bytes change, and a warm scan re-pays
# decode but not the hash. Deep re-verification is fsck's job
# (`--scrub` / lake_fsck walk every digest regardless of this ledger);
# an armed `corrupt` fault site also bypasses it — injected corruption
# models a flip at THIS read, which the digests must catch every time.
_VERIFIED: Dict[Tuple[str, int, int], set] = {}
_VERIFIED_CAP = 8192     # files; wholesale reset beyond (re-verify)


def _verified_stamp(path: str) -> Optional[Tuple[str, int, int]]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (path, st.st_mtime_ns, st.st_size)


def _verified_seen(stamp) -> frozenset:
    with _QUARANTINE_LOCK:
        return frozenset(_VERIFIED.get(stamp) or ())


def _verified_mark(stamp, marks) -> None:
    if stamp is None or not marks:
        return
    with _QUARANTINE_LOCK:
        if len(_VERIFIED) >= _VERIFIED_CAP and stamp not in _VERIFIED:
            _VERIFIED.clear()
        _VERIFIED.setdefault(stamp, set()).update(marks)


def clear_verified(path: Optional[str] = None) -> None:
    with _QUARANTINE_LOCK:
        if path is None:
            _VERIFIED.clear()
        else:
            for k in [k for k in _VERIFIED if k[0] == path]:
                _VERIFIED.pop(k, None)

# per-scan counters the executing query's thread accumulates across
# get_splits + pages() and the executor drains into its collector
# (Connector.take_scan_stats) — thread-local because concurrent queries
# scan on their own executor threads
_TLS = threading.local()


def _count(name: str, n: int = 1) -> None:
    if n:
        with _STATS_LOCK:
            LAKE_STATS[name] += n
        d = getattr(_TLS, "scan", None)
        if d is not None:
            d[name] = d.get(name, 0) + n


def _begin_scan_stats() -> None:
    if getattr(_TLS, "scan", None) is None:
        _TLS.scan = {}


def take_scan_stats() -> Dict[str, int]:
    """Drain this thread's accumulated scan counters (the executor calls
    this once per finished scan and folds them into the query stats)."""
    d = getattr(_TLS, "scan", None) or {}
    _TLS.scan = None
    return d


def lake_stats() -> Dict[str, int]:
    with _STATS_LOCK:
        return dict(LAKE_STATS)


def set_scan_options(verify: Optional[str] = None,
                     faults=None) -> None:
    """Executor-thread scan options (same thread-local discipline as the
    scan stats): the session's `lake_verify_checksums` level and the
    query's FaultInjector (fault site `corrupt`). Unset/unknown level
    falls back to DEFAULT_VERIFY, so a bare connector read — tests,
    dictionary builds, paths that never saw a session — still verifies
    at the default level."""
    _TLS.verify = verify
    _TLS.faults = faults


def _scan_verify() -> str:
    v = getattr(_TLS, "verify", None)
    return v if v in VERIFY_LEVELS else DEFAULT_VERIFY


def _scan_faults():
    return getattr(_TLS, "faults", None)


def _verified_read(tdir: str, entry: dict, fmt: str,
                   all_names: Sequence[str], names: Sequence[str],
                   groups: Sequence[int], group_rows: int
                   ) -> Dict[str, Tuple[np.ndarray,
                                        Optional[np.ndarray]]]:
    """One data-file read under the integrity contract: quarantine
    fast-fail, optional physical-digest check (`file` level), decode
    with every exception classified (never a raw decode crash), the
    `corrupt` fault site's deterministic in-memory bit flip, then
    per-(row group, column) content verification (`row_group`+ levels,
    once per file content via the verified ledger). Any mismatch
    quarantines the file and raises the classified
    LAKE_DATA_CORRUPTION error carrying the path."""
    path = os.path.join(tdir, entry["path"])
    reason = quarantined_reason(path)
    if reason is not None:
        raise LakeDataCorruptionError(
            f"lake file quarantined after earlier corruption: {path} "
            f"({reason}); run lake_fsck to repair")
    verify = _scan_verify()
    faults = _scan_faults()
    injected = faults is not None and faults.consume("corrupt",
                                                     entry["path"])
    # the verified-content ledger never applies under an armed injector:
    # the site models corruption at THIS read, past the storage stack
    stamp = None if injected else _verified_stamp(path)
    seen = _verified_seen(stamp) if stamp is not None else frozenset()
    new_marks: List = []
    if verify == "file" and entry.get("digest") and "file" not in seen:
        try:
            got_digest, got_bytes = F.file_digest(path)
        except OSError as e:
            quarantine_file(path, f"unreadable: {e}")
            _count("corruption_detected")
            raise LakeDataCorruptionError(
                f"lake data file unreadable: {path} ({e})") from e
        want_bytes = int(entry.get("bytes") or got_bytes)
        if got_digest != entry["digest"] or got_bytes != want_bytes:
            quarantine_file(path, "file digest mismatch")
            _count("corruption_detected")
            raise LakeDataCorruptionError(
                f"lake data corruption: {path} file digest mismatch "
                f"(recorded {entry['digest']}, read {got_digest})")
        new_marks.append("file")
    try:
        got = F.read_groups(path, fmt, all_names, names, groups,
                            group_rows=group_rows)
    except Exception as e:   # noqa: BLE001 — NEVER a decode crash: a
        # flipped bit in a compressed stream throws deep inside the
        # codec; the contract is one classified error, path included
        quarantine_file(path, f"undecodable: {e}")
        _count("corruption_detected")
        raise LakeDataCorruptionError(
            f"lake data corruption: {path} is undecodable "
            f"({type(e).__name__}: {e})") from e
    if injected:
        _flip_decoded(got, faults)
    if verify in ("row_group", "file"):
        meta = entry.get("groups") or []
        off = 0
        for g in groups:
            rows = int(meta[g]["rows"]) if g < len(meta) else 0
            digests = (meta[g].get("digests") or {}) \
                if g < len(meta) else {}
            for n in names:
                want = digests.get(n)
                if want is None or (g, n) in seen:
                    continue    # pre-digest entry / already proven
                arr, valid = got[n]
                have = F.column_chunk_digest(
                    arr[off:off + rows],
                    None if valid is None else valid[off:off + rows])
                if have != want:
                    _count("corruption_detected")
                    if not injected:
                        # an injected flip corrupted MEMORY, not the
                        # file — quarantining would poison good bytes
                        quarantine_file(
                            path, f"group {g} column {n!r} digest "
                                  f"mismatch")
                    raise LakeDataCorruptionError(
                        f"lake data corruption: {path} row group {g} "
                        f"column {n!r} digest mismatch (recorded "
                        f"{want}, read {have})"
                        + (" [injected]" if injected else ""))
                new_marks.append((g, n))
            off += rows
    _verified_mark(stamp, new_marks)
    return got


def _flip_decoded(got, faults) -> None:
    """Fault site `corrupt`: deterministically flip one BIT of one
    decoded value (seeded — same seed, same statement sequence, same
    flip), modeling corruption that slipped past the storage stack.
    With verification on, the digest check above MUST catch it; with
    `lake_verify_checksums = off` it flows into pages — the silent
    wrong answer the chaos suite proves the default level prevents.
    Targets the first fixed-width (non-string) column: a flipped string
    would fault the shared-dictionary encode path instead of producing
    the silently-wrong rows this site exists to model."""
    for name in sorted(got):
        arr, valid = got[name]
        if len(arr) == 0 or arr.dtype.kind in ("U", "S", "O"):
            continue
        arr = arr.copy()
        i = faults.draw_index(len(arr))
        # high bit of the top byte: a LARGE perturbation (exponent bit
        # for floats, ~2^62 for int64), so an unverified read is
        # unmistakably wrong, not lost in float tolerance
        view = arr.view(np.uint8).reshape(len(arr), arr.dtype.itemsize)
        view[i, -1] ^= 0x40
        got[name] = (arr, valid)
        return


# ------------------------------------------------------------ zone pruning


def _zone_matches(domain, zone: dict) -> bool:
    """May any row of a chunk with this zone satisfy the domain?
    Conservative: missing zones never prune."""
    if zone is None:
        return True
    lo, hi = zone.get("min"), zone.get("max")
    if lo is None or hi is None:
        # value-free chunk (all null): only a null-admitting domain matches
        return bool(domain.null_allowed) or zone.get("nulls", 0) == 0
    return domain.overlaps_range(lo, hi)


def _chunk_matches(constraint: TupleDomain, zones: dict) -> bool:
    if constraint.is_none():
        return False
    if constraint.is_all() or not zones:
        return True
    for col, domain in constraint.domains.items():
        if not _zone_matches(domain, zones.get(col)):
            return False
    return True


def eligible_files(manifest: dict, constraint: TupleDomain
                   ) -> Tuple[List[dict], int]:
    """(kept file entries, pruned count) — deterministic from the
    manifest + constraint, shared by split manager and page source."""
    kept, pruned = [], 0
    for entry in manifest.get("files", ()):
        if _chunk_matches(constraint, entry.get("file_zones") or {}):
            kept.append(entry)
        else:
            pruned += 1
    return kept, pruned


def eligible_groups(entry: dict, constraint: TupleDomain
                    ) -> Tuple[List[int], int]:
    groups = entry.get("groups") or []
    kept, pruned = [], 0
    for g, grp in enumerate(groups):
        if _chunk_matches(constraint, grp.get("zones") or {}):
            kept.append(g)
        else:
            pruned += 1
    return kept, pruned


def _file_zones(groups: List[dict], names: Sequence[str]) -> dict:
    """Fold per-group zones into one per-file zone map."""
    out = {}
    for name in names:
        lo = hi = None
        nulls = 0
        for grp in groups:
            z = (grp.get("zones") or {}).get(name)
            if z is None:
                return {}
            nulls += int(z.get("nulls", 0))
            if z["min"] is None:
                continue
            lo = z["min"] if lo is None else min(lo, z["min"])
            hi = z["max"] if hi is None else max(hi, z["max"])
        out[name] = {"min": lo, "max": hi, "nulls": nulls}
    return out


# --------------------------------------------------------------- metadata


class LakeMetadata(ConnectorMetadata):
    """Manifest-backed metadata over the versioned manifest log: commits
    write an immutable `manifest-<v>.json` and atomically swap the
    pointer (`manifest.json` — kept name, so table discovery is
    unchanged) carrying the version plus the manifest's own digest. The
    manifest cache is keyed on the pointer's (version, digest), so two
    commits landing within one mtime granule can never serve stale
    metadata; legacy single-file manifests fall back to the
    (st_mtime_ns, size) stamp."""

    # the engine consults zone maps / constraint pruning for this
    # connector (gates the dynamic-filter handle augmentation too)
    supports_zone_maps = True

    def __init__(self, base_dir: str, fmt: Optional[str] = None,
                 manifest_history: int = DEFAULT_MANIFEST_HISTORY):
        self.base_dir = os.path.abspath(base_dir)
        os.makedirs(self.base_dir, exist_ok=True)
        self.default_format = F.validate_format(fmt) if fmt \
            else F.default_format()
        self.manifest_history = max(1, int(manifest_history))
        self._lock = threading.RLock()
        self._cache: Dict[SchemaTableName, Tuple[tuple, dict]] = {}
        # per-(table, manifest version, column) string pools: every page
        # of a scan encodes onto ONE sorted pool (stable codes across
        # files — the same table-level dictionary discipline as the
        # memory connector)
        self._dicts: Dict[tuple, Dictionary] = {}

    # ------------------------------------------------------------ layout

    def table_dir(self, name: SchemaTableName) -> str:
        return os.path.join(self.base_dir, name.schema, name.table)

    def _manifest_path(self, name: SchemaTableName) -> str:
        return os.path.join(self.table_dir(name), MANIFEST)

    def _version_path(self, name: SchemaTableName, version: int) -> str:
        return os.path.join(self.table_dir(name),
                            f"manifest-{int(version)}.json")

    def load_manifest(self, name: SchemaTableName) -> Optional[dict]:
        path = self._manifest_path(name)
        try:
            st = os.stat(path)
        except OSError:
            return None
        try:
            with open(path, "rb") as f:
                raw = f.read()
            pointer = json.loads(raw)
        except (OSError, ValueError) as e:
            raise LakeDataCorruptionError(
                f"torn lake manifest pointer: {path} "
                f"({type(e).__name__}: {e}); run lake_fsck to roll "
                f"back") from e
        if "columns" in pointer:
            # legacy single-file manifest (pre-log layout): the pointer
            # IS the manifest; (st_mtime_ns, size) stays its cache key
            stamp = (st.st_mtime_ns, st.st_size)
            with self._lock:
                hit = self._cache.get(name)
                if hit is not None and hit[0] == stamp:
                    return hit[1]
                self._cache[name] = (stamp, pointer)
            return pointer
        # stamp on the pointer's manifest VERSION (+ digest): mtime
        # granularity can no longer alias two commits to one cache key
        stamp = (int(pointer.get("version", 0)),
                 str(pointer.get("digest") or ""))
        with self._lock:
            hit = self._cache.get(name)
            if hit is not None and hit[0] == stamp:
                return hit[1]
        vpath = os.path.join(self.table_dir(name),
                             os.path.basename(str(pointer.get("path")
                                                  or "")))
        try:
            with open(vpath, "rb") as f:
                vraw = f.read()
        except OSError as e:
            raise LakeDataCorruptionError(
                f"lake manifest missing: {vpath} (pointer names "
                f"version {pointer.get('version')}); run lake_fsck to "
                f"roll back") from e
        digest = hashlib.blake2b(vraw, digest_size=16).hexdigest()
        if pointer.get("digest") and digest != pointer["digest"]:
            raise LakeDataCorruptionError(
                f"lake manifest digest mismatch: {vpath} (pointer "
                f"recorded {pointer['digest']}, read {digest}); run "
                f"lake_fsck to roll back")
        try:
            manifest = json.loads(vraw)
        except ValueError as e:
            raise LakeDataCorruptionError(
                f"lake manifest undecodable: {vpath} ({e}); run "
                f"lake_fsck to roll back") from e
        with self._lock:
            self._cache[name] = (stamp, manifest)
        return manifest

    def _require(self, name: SchemaTableName) -> dict:
        manifest = self.load_manifest(name)
        if manifest is None:
            raise KeyError(f"lake table not found: {name}")
        return manifest

    # ------------------------------------------------------- time travel

    def retained_versions(self, name: SchemaTableName) -> List[int]:
        """Manifest-log versions still on disk, newest first."""
        out = []
        try:
            for entry in os.scandir(self.table_dir(name)):
                m = _MANIFEST_V.match(entry.name)
                if m:
                    out.append(int(m.group(1)))
        except OSError:
            pass
        return sorted(out, reverse=True)

    def load_manifest_version(self, name: SchemaTableName,
                              version: int) -> dict:
        """Load a specific retained `manifest-<v>.json` snapshot.
        Raises KeyError when the version was never committed or has been
        pruned past `lake_manifest_history` (and is not MV-pinned)."""
        version = int(version)
        current = self._require(name)
        if int(current.get("version", 0)) == version:
            return current
        vpath = self._version_path(name, version)
        try:
            with open(vpath, "rb") as f:
                raw = f.read()
        except OSError:
            raise KeyError(
                f"version {version} of lake table {name} is not "
                f"retained (current is {current.get('version')}; older "
                f"snapshots are pruned past lake_manifest_history)")
        try:
            return json.loads(raw)
        except ValueError as e:
            raise LakeDataCorruptionError(
                f"lake manifest undecodable: {vpath} ({e}); run "
                f"lake_fsck to roll back") from e

    def resolve_version(self, name: SchemaTableName,
                        version: Optional[int] = None,
                        timestamp: Optional[float] = None) -> int:
        """Resolve a time-travel pin to a committed manifest version.
        `version` validates retention; `timestamp` (epoch seconds) picks
        the newest retained version committed at or before it."""
        if version is not None:
            self.load_manifest_version(name, int(version))
            return int(version)
        assert timestamp is not None
        best = None
        for v in self.retained_versions(name):
            m = self.load_manifest_version(name, v)
            committed = float(m.get("committed_at") or 0.0)
            if committed <= float(timestamp):
                best = v if best is None else max(best, v)
        if best is None:
            raise KeyError(
                f"no retained snapshot of lake table {name} committed "
                f"at or before timestamp {timestamp}")
        return best

    def added_files(self, name: SchemaTableName, v_from: int,
                    v_to: int) -> Optional[List[dict]]:
        """Manifest delta: file entries added between `v_from` and
        `v_to`. Append-only commits (INSERT) extend the file list, so
        the diff is the suffix; returns None (`delta_unavailable`) when
        either version is no longer retained or the diff is not a pure
        append (rollback/rewrite commits)."""
        v_from, v_to = int(v_from), int(v_to)
        if v_from == v_to:
            return []
        if v_from > v_to:
            return None
        try:
            m_from = self.load_manifest_version(name, v_from)
            m_to = self.load_manifest_version(name, v_to)
        except KeyError:
            return None
        from_paths = [e["path"] for e in m_from.get("files") or ()]
        to_files = list(m_to.get("files") or ())
        if [e["path"] for e in to_files[:len(from_paths)]] != from_paths:
            return None
        return to_files[len(from_paths):]

    # ------------------------------------------------------------ MV pins

    def mv_dir(self) -> str:
        """Materialized-view records live beside the schemas as flat
        JSON files (`_mv/<schema>.<view>.json`) — a directory of files,
        so table discovery (which wants directories) skips it."""
        return os.path.join(self.base_dir, "_mv")

    def mv_pinned_versions(self, name: SchemaTableName) -> frozenset:
        """Base-table manifest versions pinned as MV delta baselines.
        Retention and fsck GC must keep these alive: a pruned baseline
        forces the next REFRESH into a full recompute at best and a
        torn delta at worst."""
        pins = set()
        key = f"{name.schema}.{name.table}"
        try:
            entries = list(os.scandir(self.mv_dir()))
        except OSError:
            return frozenset()
        for entry in entries:
            if not entry.name.endswith(".json"):
                continue
            try:
                with open(entry.path, "rb") as f:
                    rec = json.loads(f.read())
            except (OSError, ValueError):
                continue
            # the LIVE watermark rides the storage table's manifest
            # (committed atomically with the refresh's data swap); the
            # record file only points at the storage table
            st = rec.get("storage") or {}
            try:
                sm = self.load_manifest(
                    SchemaTableName(st["schema"], st["table"]))
            except Exception:
                sm = None
            bv = ((sm or {}).get("mv") or {}).get("base_versions") or {}
            v = bv.get(key)
            if v is not None:
                try:
                    pins.add(int(v))
                except (ValueError, TypeError):
                    pass
        return frozenset(pins)

    def _swap_manifest(self, name: SchemaTableName, manifest: dict,
                       history: Optional[int] = None) -> None:
        """COMMIT: write the immutable `manifest-<v>.json`, then swap
        the pointer with tmp + os.replace — the pointer rename is the
        whole transaction (readers see old or new, never torn). The
        last `history` versions are retained for fsck rollback; older
        log files are pruned (running queries pin the in-memory
        manifest SNAPSHOT via their split context, so pruning a file
        never tears a scan)."""
        version = int(manifest.get("version", 0))
        # commit timestamp (epoch seconds) — the `FOR TIMESTAMP AS OF`
        # resolution key; legacy manifests without it sort as 0
        manifest["committed_at"] = time.time()
        vpath = self._version_path(name, version)
        raw = json.dumps(manifest).encode()
        tmp = f"{vpath}.tmp.{uuid.uuid4().hex[:8]}"
        with open(tmp, "wb") as f:
            f.write(raw)
        os.replace(tmp, vpath)
        pointer = {"pointer_version": 1, "version": version,
                   "path": os.path.basename(vpath),
                   "digest": hashlib.blake2b(raw,
                                             digest_size=16).hexdigest()}
        path = self._manifest_path(name)
        tmp = f"{path}.tmp.{uuid.uuid4().hex[:8]}"
        with open(tmp, "w") as f:
            json.dump(pointer, f)
        os.replace(tmp, path)
        with self._lock:
            self._cache.pop(name, None)
        keep = max(1, int(history if history is not None
                          else self.manifest_history))
        floor = version - keep
        if floor >= 0:
            # MV delta baselines are live references: a pinned version
            # stays in the log (and its files stay fsck-referenced)
            # until the next REFRESH advances the pin
            pinned = self.mv_pinned_versions(name)
            try:
                for entry in os.scandir(self.table_dir(name)):
                    m = _MANIFEST_V.match(entry.name)
                    if m and int(m.group(1)) <= floor \
                            and int(m.group(1)) not in pinned:
                        os.remove(entry.path)
            except OSError:
                pass

    # ----------------------------------------------------------- listing

    def list_schemas(self) -> List[str]:
        out = {"default"}
        try:
            for entry in os.scandir(self.base_dir):
                # underscore-prefixed dirs are engine metadata (`_mv`
                # view records), not schemas
                if entry.is_dir() and not entry.name.startswith("_"):
                    out.add(entry.name)
        except OSError:
            pass
        return sorted(out)

    def list_tables(self, schema: Optional[str] = None
                    ) -> List[SchemaTableName]:
        schemas = [schema] if schema else self.list_schemas()
        out = []
        for s in schemas:
            sdir = os.path.join(self.base_dir, s)
            try:
                entries = list(os.scandir(sdir))
            except OSError:
                continue
            for entry in entries:
                if entry.is_dir() and os.path.exists(
                        os.path.join(entry.path, MANIFEST)):
                    out.append(SchemaTableName(s, entry.name))
        return sorted(out, key=lambda n: (n.schema, n.table))

    def get_table_handle(self, name: SchemaTableName
                         ) -> Optional[ConnectorTableHandle]:
        if self.load_manifest(name) is None:
            return None
        return ConnectorTableHandle(name)

    def get_table_metadata(self, handle: ConnectorTableHandle
                           ) -> TableMetadata:
        m = self._require(handle.name)
        cols = tuple(ColumnMetadata(c["name"], T.parse_type(c["type"]))
                     for c in m["columns"])
        return TableMetadata(handle.name, cols)

    def partition_columns(self, name: SchemaTableName) -> List[str]:
        return list(self._require(name).get("partition_by") or [])

    def manifest_for_handle(self, handle: ConnectorTableHandle) -> dict:
        """The manifest snapshot a handle reads: the pinned version for
        time-travel handles, else the current pointer."""
        if getattr(handle, "version", None) is not None:
            return self.load_manifest_version(handle.name, handle.version)
        return self._require(handle.name)

    def get_table_statistics(self, handle: ConnectorTableHandle
                             ) -> TableStatistics:
        try:
            m = self.manifest_for_handle(handle)
        except KeyError:
            m = None
        if m is None:
            return TableStatistics.unknown()
        rows = float(sum(int(e["rows"]) for e in m.get("files", ())))
        cols: Dict[str, ColumnStatistics] = {}
        for c in m["columns"]:
            name = c["name"]
            lo = hi = None
            nulls = 0
            known = True
            for e in m.get("files", ()):
                z = (e.get("file_zones") or {}).get(name)
                if z is None:
                    known = False
                    break
                nulls += int(z.get("nulls", 0))
                if z["min"] is not None:
                    lo = z["min"] if lo is None else min(lo, z["min"])
                    hi = z["max"] if hi is None else max(hi, z["max"])
            if known and rows:
                cols[name] = ColumnStatistics(
                    null_fraction=nulls / rows,
                    min_value=lo, max_value=hi)
            else:
                cols[name] = ColumnStatistics()
        return TableStatistics(rows, cols)

    # ----------------------------------------------------------- pushdown

    def apply_filter(self, handle: ConnectorTableHandle,
                     constraint: TupleDomain):
        # accept the domain as the file/row-group pruning hint; the
        # engine still applies the predicate row-wise (SPI contract)
        merged = handle.constraint.intersect(constraint)
        return (dataclasses.replace(handle, constraint=merged), constraint)

    def apply_limit(self, handle: ConnectorTableHandle, limit: int):
        if handle.limit is not None and handle.limit <= limit:
            return None
        return dataclasses.replace(handle, limit=limit)

    # -------------------------------------------------------------- DDL

    def create_table(self, metadata: TableMetadata,
                     ignore_existing: bool = False):
        props = dict(metadata.properties or ())
        partition_by = props.pop("partitioned_by", "") or ""
        fmt = props.pop("format", None)
        group_rows = int(props.pop("row_group_rows",
                                   F.DEFAULT_ROW_GROUP_ROWS))
        if group_rows <= 0:
            raise ValueError("row_group_rows must be positive")
        if props:
            raise ValueError(
                f"unknown lake table properties: {sorted(props)} "
                "(supported: partitioned_by, format, row_group_rows)")
        fmt = F.validate_format(fmt) if fmt else self.default_format
        part_cols = [c.strip() for c in str(partition_by).split(",")
                     if c.strip()]
        names = {c.name for c in metadata.columns}
        for pc in part_cols:
            if pc not in names:
                raise ValueError(
                    f"partitioned_by column not in table: {pc}")
        with self._lock:
            if self.load_manifest(metadata.name) is not None:
                if ignore_existing:
                    return
                raise ValueError(
                    f"table already exists: {metadata.name}")
            os.makedirs(os.path.join(self.table_dir(metadata.name),
                                     DATA_DIR), exist_ok=True)
            self._swap_manifest(metadata.name, {
                "version": 1,
                "format": fmt,
                "row_group_rows": group_rows,
                "columns": [{"name": c.name, "type": c.type.display()}
                            for c in metadata.columns],
                "partition_by": part_cols,
                "files": [],
                "committed_tokens": [],
            })

    def drop_table(self, handle: ConnectorTableHandle):
        with self._lock:
            shutil.rmtree(self.table_dir(handle.name), ignore_errors=True)
            self._cache.pop(handle.name, None)
            sdir = os.path.join(self.base_dir, handle.name.schema)
            try:  # prune an emptied schema dir (best effort)
                if not os.listdir(sdir):
                    os.rmdir(sdir)
            except OSError:
                pass

    # ------------------------------------------------------- dictionaries

    def table_dictionary(self, name: SchemaTableName, column: str,
                         manifest: dict) -> Dictionary:
        """One sorted string pool per (table, manifest version, column):
        built from the union of every file's values on first use, so
        codes are stable across files and pages (shared-dictionary
        kernels see ONE pool per scan)."""
        scope = manifest.get("dict_scope")
        key = (name, int(manifest.get("version", 0)), column)
        if scope is None:
            with self._lock:
                d = self._dicts.get(key)
            if d is not None:
                return d
        fmt = manifest["format"]
        group_rows = int(manifest.get("row_group_rows",
                                      F.DEFAULT_ROW_GROUP_ROWS))
        all_names = [c["name"] for c in manifest["columns"]]
        values: List[np.ndarray] = []
        tdir = self.table_dir(name)
        for entry in manifest.get("files", ()):
            ngroups = len(entry.get("groups") or [])
            if ngroups == 0:
                continue
            got = _verified_read(tdir, entry, fmt, all_names, [column],
                                 list(range(ngroups)),
                                 group_rows=group_rows)
            arr, valid = got[column]
            arr = np.asarray(arr, dtype=object)
            if valid is not None:
                arr = arr[np.asarray(valid, dtype=bool)]
            values.append(arr)
        pool = np.unique(np.concatenate(values)) if values \
            else np.empty(0, dtype=object)
        d = Dictionary(np.asarray(pool, dtype=object))
        if scope is not None:
            # delta-restricted pools are one-shot (a refresh's scan);
            # equal-valued rebuilds are deterministic, so codes stay
            # consistent without polluting the versioned cache
            return d
        with self._lock:
            # bound the cache to a manifest_history-deep window per
            # table: time-travel/delta scans of recent versions keep
            # their pools; building a new version no longer evicts a
            # concurrently-pinned snapshot's pool (deeper pins rebuild
            # per scan rather than growing the cache unboundedly)
            vers = [k[1] for k in self._dicts if k[0] == name]
            floor = max(vers + [key[1]]) - self.manifest_history
            self._dicts = {k: v for k, v in self._dicts.items()
                           if k[0] != name or k[1] > floor}
            if key[1] > floor:
                self._dicts[key] = d
        return d


# ------------------------------------------------------------------ splits


class LakeSplitManager(ConnectorSplitManager):
    def __init__(self, metadata: LakeMetadata):
        self._metadata = metadata

    def get_splits(self, handle: ConnectorTableHandle,
                   target_splits: int = 1) -> List[Split]:
        _begin_scan_stats()
        # time-travel handles pin a committed snapshot; current-version
        # handles read the pointer — either way the chosen manifest
        # rides the splits, so the scan is byte-identical to ONE
        # committed version regardless of concurrent writes
        manifest = self._metadata.manifest_for_handle(handle)
        delta_from = getattr(handle, "delta_from", None)
        if delta_from is not None:
            v_to = int(manifest.get("version", 0))
            added = self._metadata.added_files(handle.name, delta_from,
                                               v_to)
            if added is None:
                raise KeyError(
                    f"lake manifest delta unavailable for "
                    f"{handle.name}: versions {delta_from}..{v_to} "
                    f"are not a retained pure append")
            manifest = dict(manifest)
            manifest["files"] = added
            # delta snapshots must not share (table, version) dictionary
            # pools with the full snapshot they were cut from
            manifest["dict_scope"] = f"delta-{delta_from}-{v_to}"
        kept, pruned = eligible_files(manifest, handle.constraint)
        _count("files_pruned", pruned)
        parts = max(1, min(max(target_splits, 1), max(len(kept), 1)))
        # the manifest SNAPSHOT rides on every split: all splits of one
        # query read the same committed version even if a concurrent
        # write swaps the manifest mid-query (old-or-new, never torn)
        return [Split(handle, p, parts, host=p, context=manifest)
                for p in range(parts)]


# ------------------------------------------------------------------- scan


class LakePageSource(ConnectorPageSource):
    def __init__(self, metadata: LakeMetadata):
        self._metadata = metadata

    def pages(self, split: Split, columns: Sequence[ColumnHandle],
              page_capacity: int) -> Iterator[Page]:
        _begin_scan_stats()
        md = self._metadata
        name = split.table.name
        # read the split-time manifest snapshot: a commit between
        # get_splits and pages() must not tear this query's file list
        manifest = split.context if isinstance(split.context, dict) \
            else md._require(name)
        fmt = manifest["format"]
        group_rows = int(manifest.get("row_group_rows",
                                      F.DEFAULT_ROW_GROUP_ROWS))
        all_names = [c["name"] for c in manifest["columns"]]
        tdir = md.table_dir(name)
        kept, _ = eligible_files(manifest, split.table.constraint)
        mine = kept[split.part::split.total_parts]
        limit = split.table.limit
        emitted = 0
        for entry in mine:
            groups, pruned = eligible_groups(entry, split.table.constraint)
            _count("row_groups_pruned", pruned)
            if not groups:
                continue
            _count("files_scanned")
            _count("row_groups_scanned", len(groups))
            got = _verified_read(tdir, entry, fmt, all_names,
                                 [c.name for c in columns], groups,
                                 group_rows=group_rows)
            arrays = [got[c.name] for c in columns]
            rows = len(arrays[0][0]) if arrays else 0
            off = 0
            while off < rows:
                hi = min(off + page_capacity, rows)
                n = hi - off
                cols = []
                for ch, (arr, valid) in zip(columns, arrays):
                    v = None
                    if valid is not None:
                        v = pad_to_capacity(
                            np.asarray(valid[off:hi], dtype=bool),
                            page_capacity, False)
                    if T.is_string(ch.type):
                        d = md.table_dictionary(name, ch.name, manifest)
                        if len(d) == 0:
                            # every value null: the pool is empty, so
                            # emit the reserved null/padding code -1
                            # (decode maps it to None)
                            codes = np.full(page_capacity, -1,
                                            dtype=np.int32)
                        else:
                            raw = np.asarray(arr[off:hi], dtype=object)
                            if v is not None:
                                raw = np.where(
                                    np.asarray(valid[off:hi],
                                               dtype=bool),
                                    raw, d.values[0])
                            codes = pad_to_capacity(d.encode(raw),
                                                    page_capacity, 0)
                        cols.append(Column.from_numpy(codes, ch.type, v,
                                                      d))
                    else:
                        vals = pad_to_capacity(
                            np.asarray(arr[off:hi],
                                       T.to_numpy_dtype(ch.type)),
                            page_capacity, 0)
                        cols.append(Column.from_numpy(vals, ch.type, v))
                yield Page(tuple(cols), n)
                emitted += n
                if limit is not None and emitted >= limit:
                    return
                off = hi


# ------------------------------------------------------------------- sink


class LakePageSink(ConnectorPageSink):
    """Staged, token-deduplicated file sink: appended pages decode to
    host column chunks; finish() writes one data file per partition
    group under unique names and commits them with ONE atomic manifest
    swap — once per write token, so a replayed attempt deletes its
    orphans and no-ops (exactly-once INSERT/CTAS under QUERY retry)."""

    def __init__(self, metadata: LakeMetadata, name: SchemaTableName,
                 write_token: Optional[str] = None):
        self._metadata = metadata
        self._name = name
        self._token = write_token
        manifest = metadata._require(name)
        self._types = [T.parse_type(c["type"]) for c in manifest["columns"]]
        self._names = [c["name"] for c in manifest["columns"]]
        self._part_cols = [self._names.index(p)
                           for p in manifest.get("partition_by") or []]
        self._fmt = manifest["format"]
        self._group_rows = int(manifest.get("row_group_rows",
                                            F.DEFAULT_ROW_GROUP_ROWS))
        self._staged: List[List] = [[] for _ in self._types]
        self._written: List[str] = []
        self._history: Optional[int] = None
        self._replace = False
        self._mv_meta: Optional[dict] = None

    def set_commit_options(self, history: Optional[int] = None,
                           replace: bool = False,
                           mv_meta: Optional[dict] = None) -> None:
        """Executor hook: session `lake_manifest_history` for THIS commit
        (retained manifest-log depth). getattr-gated at the call site so
        the SPI sink surface is unchanged. `replace` commits this write's
        files as the table's ENTIRE file set (the MV refresh swap — prior
        files stay on disk, referenced by retained manifest versions);
        `mv_meta` is stamped into the committed manifest under `"mv"`, so
        an MV's refresh watermark (base versions + refreshed_at) lands in
        the SAME atomic pointer swap as its data."""
        self._history = None if history is None else max(1, int(history))
        self._replace = bool(replace)
        self._mv_meta = mv_meta

    def append_page(self, page: Page):
        n = int(page.num_rows)
        if n == 0:
            return
        for i, col in enumerate(page.columns):
            vals = col.to_numpy(n)   # decoded objects incl. None
            typ = self._types[i]
            nulls = np.array([v is None for v in vals], dtype=bool)
            if T.is_string(typ):
                filled = np.asarray(
                    ["" if v is None else v for v in vals], dtype=object)
            else:
                filled = np.asarray(
                    [0 if v is None else v for v in vals],
                    dtype=T.to_numpy_dtype(typ))
            self._staged[i].append((filled, nulls))

    def _partition_groups(self, arrays, valids) -> List[Tuple[dict, object]]:
        """[(partition value dict, row-index array)] — one data file per
        distinct partition tuple; unpartitioned tables are one group."""
        rows = len(arrays[0]) if arrays else 0
        if not self._part_cols or rows == 0:
            return [({}, None)]
        keys = list(zip(*[
            [None if (valids[c] is not None and not valids[c][r])
             else arrays[c][r] for r in range(rows)]
            for c in self._part_cols]))
        by_key: Dict[tuple, list] = {}
        for r, k in enumerate(keys):
            by_key.setdefault(k, []).append(r)
        out = []
        for k in sorted(by_key, key=lambda t: tuple(
                (v is None, v) for v in t)):
            pv = {self._names[c]: F._json_scalar(v)
                  for c, v in zip(self._part_cols, k)}
            out.append((pv, np.asarray(by_key[k], dtype=np.int64)))
        return out

    def finish(self):
        md = self._metadata
        staged, self._staged = self._staged, [[] for _ in self._types]
        arrays: List[np.ndarray] = []
        valids: List[Optional[np.ndarray]] = []
        rows = 0
        for i, chunks in enumerate(staged):
            if not chunks:
                arrays.append(np.empty(0, dtype=object
                                       if T.is_string(self._types[i])
                                       else T.to_numpy_dtype(
                                           self._types[i])))
                valids.append(None)
                continue
            arrays.append(np.concatenate([c[0] for c in chunks]))
            nulls = np.concatenate([c[1] for c in chunks])
            valids.append(~nulls if nulls.any() else None)
            rows = len(arrays[-1])
        tdir = md.table_dir(self._name)
        entries: List[dict] = []
        if rows:
            for pv, idx in self._partition_groups(arrays, valids):
                parrs = arrays if idx is None else [a[idx] for a in arrays]
                pvals = valids if idx is None else \
                    [None if v is None else v[idx] for v in valids]
                fname = (f"{DATA_DIR}/{self._token or 'w'}-"
                         f"{uuid.uuid4().hex[:12]}"
                         f"{F.file_extension(self._fmt)}")
                path = os.path.join(tdir, fname)
                nrows = F.write_file(path, self._fmt, self._names, parrs,
                                     pvals, group_rows=self._group_rows)
                self._written.append(path)
                groups = F.build_zones(self._names, parrs, pvals,
                                       group_rows=self._group_rows)
                # content digests recorded AT COMMIT: file digest over
                # the physical bytes just written, group digests over the
                # canonical decoded content (codec-independent)
                fdigest, fbytes = F.file_digest(path)
                for grp, dg in zip(groups, F.build_digests(
                        self._names, parrs, pvals,
                        group_rows=self._group_rows)):
                    grp["digests"] = dg
                entries.append({
                    "path": fname, "rows": nrows,
                    "digest": fdigest, "bytes": fbytes,
                    "partition": pv,
                    "file_zones": _file_zones(groups, self._names),
                    "groups": groups,
                })
        with md._lock:
            manifest = md._require(self._name)
            tokens = list(manifest.get("committed_tokens") or [])
            if self._token is not None and self._token in tokens:
                # an earlier attempt already committed: replay no-op —
                # this attempt's freshly-written files are orphans
                for p in self._written:
                    try:
                        os.remove(p)
                    except OSError:
                        pass
                self._written = []
                _count("replayed_commits")
                return
            manifest = dict(manifest)
            if self._replace:
                manifest["files"] = entries
            else:
                manifest["files"] = \
                    list(manifest.get("files") or []) + entries
            if self._mv_meta is not None:
                manifest["mv"] = self._mv_meta
            if self._token is not None:
                tokens.append(self._token)
                manifest["committed_tokens"] = \
                    tokens[-_MAX_MANIFEST_TOKENS:]
            manifest["version"] = int(manifest.get("version", 0)) + 1
            md._swap_manifest(self._name, manifest,
                              history=self._history)
        self._written = []
        _count("manifest_commits")
        _count("files_written", len(entries))

    def abort(self):
        self._staged = [[] for _ in self._types]
        for p in self._written:
            try:
                os.remove(p)
            except OSError:
                pass
        if self._written:
            _count("aborted_writes")
        self._written = []


# -------------------------------------------------------------- connector


class LakeConnector(Connector):
    # staged write-token sink + manifest-swap commit: the engine may
    # retry writes here — chaos included — without double-write risk
    idempotent_writes = True

    def __init__(self, base_dir: str, fmt: Optional[str] = None):
        metadata = LakeMetadata(base_dir, fmt)
        super().__init__("lake", metadata, LakeSplitManager(metadata),
                         LakePageSource(metadata))
        self._metadata = metadata

    def page_sink(self, handle: ConnectorTableHandle,
                  write_token: Optional[str] = None) -> ConnectorPageSink:
        return LakePageSink(self._metadata, handle.name, write_token)

    # the executor drains per-scan prune counters through this hook
    # (thread-local: the scan ran on the caller's thread)
    @staticmethod
    def take_scan_stats() -> Dict[str, int]:
        return take_scan_stats()

    # executor hook: session verify level + the query's fault injector
    # ride a thread-local down to the read path (the SPI scan signature
    # carries no session)
    @staticmethod
    def set_scan_options(verify: Optional[str] = None,
                         faults=None) -> None:
        set_scan_options(verify=verify, faults=faults)

    def fsck(self, **kwargs) -> dict:
        """pointer → manifest → files → row-groups integrity walk with
        rollback + orphan GC (connector/lake/integrity.py)."""
        from trino_tpu.connector.lake.integrity import lake_fsck
        return lake_fsck(self._metadata, **kwargs)


def create_connector(base_dir: Optional[str] = None,
                     fmt: Optional[str] = None) -> LakeConnector:
    """Lake catalog rooted at `base_dir` ($TRINO_TPU_LAKE_DIR, else a
    fresh per-process temp directory — the dev/test default)."""
    if base_dir is None:
        base_dir = os.environ.get("TRINO_TPU_LAKE_DIR")
    if base_dir is None:
        import tempfile
        base_dir = tempfile.mkdtemp(prefix="trino_tpu_lake_")
    return LakeConnector(base_dir, fmt)
