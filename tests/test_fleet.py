"""Fleet serving (trino_tpu/fleet/): SO_REUSEPORT workers over one
engine, cross-process cache tier, quotas, drain, rolling restart.

The ISSUE-13 acceptance suite. Unit layers (shm tier seqlock +
generation guard, bus, registry, keyer parity) run in-process; the
end-to-end tests spawn REAL worker subprocesses sharing one port
(JAX_PLATFORMS=cpu, hard ready/exit timeouts) against an engine in this
process, so tier-1 exercises the production topology bounded.
"""

import json
import os
import socket
import threading
import time
import urllib.request

import pytest

from trino_tpu.fleet.shm import SharedCacheTier, key_fingerprint

pytestmark = pytest.mark.skipif(
    not hasattr(socket, "SO_REUSEPORT"),
    reason="fleet serving needs SO_REUSEPORT")


# ------------------------------------------------------------ shm tier


def test_shm_roundtrip_and_generation_guard(tmp_path):
    tier = SharedCacheTier(str(tmp_path / "c.shm"), create=True,
                           data_bytes=1 << 20)
    kh = key_fingerprint(("k", 1))
    table = ("tpch", "tiny", "nation")
    gen = tier.generation()
    assert tier.get(kh) is None
    assert tier.put(kh, {"rows": [1, 2]}, [table], gen)
    entry, tables, put_gen, seq = tier.get(kh)
    assert entry == {"rows": [1, 2]} and tables == (table,)
    # peek matches the full read (the hot-copy revalidation contract)
    assert tier.peek_slot(kh) == (seq, put_gen)
    # invalidation drops it for every future read
    tier.invalidate(table)
    assert tier.get(kh) is None
    # the _GenerationGuard discipline across processes: a put carrying a
    # generation snapshot older than an invalidation of any referenced
    # table is REJECTED — a stale publish is structurally impossible
    stale_gen = tier.generation()
    tier.invalidate(table)
    assert not tier.put(kh, {"stale": True}, [table], stale_gen)
    assert tier.get(kh) is None
    # an unrelated table's entry survives
    other = key_fingerprint(("k", 2))
    assert tier.put(other, "v", [("c", "s", "other")], tier.generation())
    tier.invalidate(table)
    assert tier.get(other)[0] == "v"
    tier.close()


def test_shm_ring_wrap_no_corruption(tmp_path):
    """Overwriting ring allocation must kill overlapped slots: old keys
    either miss or return their OWN value, never another record's."""
    tier = SharedCacheTier(str(tmp_path / "c.shm"), create=True,
                           data_bytes=64 << 10, slots=256)
    for i in range(800):
        tier.put(key_fingerprint(("w", i)), {"i": i, "pad": "x" * 300},
                 [("c", "s", "t")], tier.generation())
    survivors = 0
    for i in range(800):
        found = tier.get(key_fingerprint(("w", i)))
        if found is None:
            continue
        assert found[0]["i"] == i
        survivors += 1
    assert 0 < survivors < 800    # wrapped: some evicted, none corrupt
    tier.close()


def test_shm_quota_bucket_is_shared(tmp_path):
    """Two handles on one file drain ONE bucket — the fleet-wide
    semantics N worker processes get."""
    path = str(tmp_path / "c.shm")
    a = SharedCacheTier(path, create=True, data_bytes=1 << 16)
    b = SharedCacheTier(path)
    assert a.try_acquire("g", rate=1.0, burst=2.0)
    assert b.try_acquire("g", rate=1.0, burst=2.0)
    assert not a.try_acquire("g", rate=1.0, burst=2.0)
    assert not b.try_acquire("g", rate=1.0, burst=2.0)
    # refund (the all-or-nothing chain walk's rollback)
    assert a.try_acquire("g", rate=1.0, burst=2.0, n=-1.0)
    assert b.try_acquire("g", rate=1.0, burst=2.0)
    a.close()
    b.close()


def test_quota_allows_chain_refund(tmp_path):
    from trino_tpu.fleet.registry import quota_allows
    tier = SharedCacheTier(str(tmp_path / "c.shm"), create=True,
                           data_bytes=1 << 16)
    quotas = {"root": {"rate": 0.0, "burst": 10.0},
              "root.leaf": {"rate": 0.0, "burst": 1.0}}
    assert quota_allows(tier, quotas, "root.leaf")      # 1 from each
    assert not quota_allows(tier, quotas, "root.leaf")  # leaf empty
    # the failed attempt refunded root: 9 left there, leaf still empty
    assert quota_allows(tier, quotas, "root")
    for _ in range(8):
        assert quota_allows(tier, quotas, "root")
    assert not quota_allows(tier, quotas, "root")
    tier.close()


# ------------------------------------------------------- bus + registry


def test_bus_fanout_and_send_to(tmp_path):
    from trino_tpu.fleet.bus import FleetBus
    got_a, got_b = [], []
    a = FleetBus(str(tmp_path), "a", on_message=got_a.append)
    b = FleetBus(str(tmp_path), "b", on_message=got_b.append)
    try:
        assert a.publish({"kind": "x"}) == 2          # both members
        assert a.publish({"kind": "y"}, exclude_self=True) == 1
        assert b.send_to("a", {"kind": "direct"})
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and (
                len(got_a) < 2 or len(got_b) < 2):
            time.sleep(0.01)
        assert {m["kind"] for m in got_a} == {"x", "direct"}
        assert {m["kind"] for m in got_b} == {"x", "y"}
    finally:
        a.close()
        b.close()


def test_prepared_registry_persistence(tmp_path):
    from trino_tpu.fleet.registry import PreparedRegistry
    r1 = PreparedRegistry(str(tmp_path))
    r1.register("q1", "SELECT 1")
    # a late joiner (restarted worker) sees statements PREPAREd before
    # it was born — the sticky-routing durability half
    r2 = PreparedRegistry(str(tmp_path))
    assert r2.get("q1") == "SELECT 1"
    r2.remove("q1")
    assert PreparedRegistry(str(tmp_path)).get("q1") is None


def test_load_quota_map(tmp_path):
    from trino_tpu.fleet.registry import load_quota_map
    path = tmp_path / "rg.json"
    path.write_text(json.dumps({"rootGroups": [
        {"name": "adhoc", "resultCacheQps": 5,
         "subGroups": [{"name": "alice", "result_cache_qps": 2,
                        "result_cache_qps_burst": 7}]},
        {"name": "free"}]}))
    quotas = load_quota_map(str(path))
    assert quotas["adhoc"]["rate"] == 5
    assert quotas["adhoc.alice"] == {"rate": 2.0, "burst": 7.0}
    assert "free" not in quotas
    assert load_quota_map(str(tmp_path / "missing.json")) == {}


# --------------------------------------------- keyer/mirror parity (no
# subprocesses: the engine runs here, the keyer plays the worker)


@pytest.fixture(scope="module")
def mirrored_server(tmp_path_factory):
    from trino_tpu.exec import LocalQueryRunner
    from trino_tpu.fleet.server import MirroredResultSetCache
    from trino_tpu.server import TrinoServer
    d = tmp_path_factory.mktemp("mirror")
    tier = SharedCacheTier(str(d / "c.shm"), create=True)
    runner = LocalQueryRunner.tpch("tiny")
    cache = MirroredResultSetCache(tier)
    runner._result_cache = cache
    runner._plan_cache.add_invalidation_hook(cache.invalidate)
    srv = TrinoServer(runner).start()
    yield srv, runner, tier
    srv.stop()


def _http(base, sql, headers=None):
    req = urllib.request.Request(f"{base}/v1/statement",
                                 data=sql.encode(), method="POST")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    resp = urllib.request.urlopen(req, timeout=30)
    payload = json.loads(resp.read())
    hdrs = dict(resp.headers)
    rows = list(payload.get("data", []))
    while "nextUri" in payload:
        r2 = urllib.request.urlopen(payload["nextUri"], timeout=30)
        payload = json.loads(r2.read())
        hdrs.update(dict(r2.headers))
        rows.extend(payload.get("data", []))
    return payload, rows, hdrs


def test_keyer_digest_matches_engine_publish(mirrored_server):
    """The load-bearing parity: a worker-side StatementKeyer — no
    catalogs, no planner — must land on the byte-identical digest the
    engine's mirrored put used, for plain SQL and EXECUTE ... USING."""
    from trino_tpu.fleet.keys import StatementKeyer
    srv, runner, tier = mirrored_server
    _, _, hdrs = _http(srv.base_uri,
                       "PREPARE kp FROM SELECT n_name FROM nation "
                       "WHERE n_nationkey = ?")
    added = next(v for k, v in hdrs.items()
                 if k.lower() == "x-trino-added-prepare")
    from urllib.parse import unquote
    name, _, enc = added.partition("=")
    name, psql = unquote(name), unquote(enc)
    _, rows, _ = _http(srv.base_uri, "EXECUTE kp USING 3",
                       headers={"X-Trino-Prepared-Statement": added})
    assert rows == [["CANADA"]]
    keyer = StatementKeyer(runner.session.catalog, runner.session.schema,
                           runner.session.start_date)
    digest = keyer.key_for("EXECUTE kp USING 3", {}, None, None,
                           {name: psql})
    assert digest is not None
    found = tier.get(digest)
    assert found is not None and found[0].rows == (("CANADA",),)
    # a different parameter VALUE is a different result key
    miss = keyer.key_for("EXECUTE kp USING 4", {}, None, None,
                         {name: psql})
    assert miss != digest
    # plain SQL parity
    _http(srv.base_uri, "SELECT count(*) FROM region")
    d2 = keyer.key_for("SELECT count(*) FROM region", {}, None, None, {})
    assert tier.get(d2)[0].rows == ((5,),)
    # a plan-affecting session override fragments the key (it fragments
    # the engine's plan-cache key too)
    d3 = keyer.key_for("SELECT count(*) FROM region",
                       {"join_distribution_type": "BROADCAST"},
                       None, None, {})
    assert d3 != d2
    # non-keyable statements defer to the engine
    assert keyer.key_for("INSERT INTO t VALUES (1)", {}, None, None,
                         {}) is None
    assert keyer.key_for("EXECUTE unknown USING 1", {}, None, None,
                         {}) is None


def test_mirrored_cache_invalidation_reaches_tier(mirrored_server):
    from trino_tpu.fleet.keys import StatementKeyer
    srv, runner, tier = mirrored_server
    _http(srv.base_uri, "CREATE TABLE memory.default.minv (a BIGINT)")
    _http(srv.base_uri, "INSERT INTO memory.default.minv VALUES (1)")
    _, rows, _ = _http(srv.base_uri,
                       "SELECT count(*) FROM memory.default.minv")
    assert rows == [[1]]
    keyer = StatementKeyer(runner.session.catalog, runner.session.schema,
                           runner.session.start_date)
    digest = keyer.key_for("SELECT count(*) FROM memory.default.minv",
                           {}, None, None, {})
    assert tier.get(digest) is not None
    # ONE INSERT drops plans, local caches, AND the shared tier
    _http(srv.base_uri, "INSERT INTO memory.default.minv VALUES (2)")
    assert tier.get(digest) is None
    _, rows, _ = _http(srv.base_uri,
                       "SELECT count(*) FROM memory.default.minv")
    assert rows == [[2]]


# ----------------------------------------------- the fleet, end to end


FLEET_RG = {"groups": [
    {"name": "global"},
    {"name": "fleetq", "resultCacheQps": 0, "resultCacheQpsBurst": 2},
]}


@pytest.fixture(scope="module")
def fleet(tmp_path_factory):
    from trino_tpu.fleet import FleetServer
    d = tmp_path_factory.mktemp("fleet")
    rg_path = str(d / "rg.json")
    with open(rg_path, "w") as fh:
        json.dump(FLEET_RG, fh)
    server = FleetServer(
        workers=2, resource_groups_path=rg_path,
        warmup_manifest={"statements": [
            {"name": "fleet_probe",
             "sql": "SELECT n_name, n_regionkey FROM nation "
                    "WHERE n_nationkey = ?",
             "using": "0"}]}).start()
    yield server
    server.stop()


def _fleet_status(fleet, worker_id=None):
    out = []
    for rec in fleet.workers():
        if worker_id is not None and rec["worker_id"] != worker_id:
            continue
        uri = f"http://127.0.0.1:{rec['admin_port']}/v1/fleet/status"
        out.append(json.loads(
            urllib.request.urlopen(uri, timeout=10).read()))
    return out


def test_fleet_hit_served_by_worker(fleet):
    """A repeated EXECUTE is answered from the shared tier by a WORKER
    process — the engine never sees the second request."""
    _http(fleet.base_uri, "EXECUTE fleet_probe USING 7")   # publish
    deadline = time.monotonic() + 10
    served = 0
    while time.monotonic() < deadline and served == 0:
        payload, rows, _ = _http(fleet.base_uri,
                                 "EXECUTE fleet_probe USING 7")
        assert payload["stats"]["state"] == "FINISHED"
        assert rows == [["GERMANY", 3]]
        served = sum(s["counters"]["hits"] for s in _fleet_status(fleet))
    assert served >= 1


def test_fleet_insert_invalidates_everywhere(fleet):
    """Correctness under writes: one INSERT through any worker drops
    the fleet-wide cached answer (generation check, not just the bus),
    so the next read re-executes against the new data."""
    _http(fleet.base_uri, "CREATE TABLE memory.default.finv (a BIGINT)")
    _http(fleet.base_uri, "INSERT INTO memory.default.finv VALUES (1)")
    sql = "SELECT count(*) FROM memory.default.finv"
    _, rows, _ = _http(fleet.base_uri, sql)
    assert rows == [[1]]
    for _ in range(3):   # let a worker cache it locally
        _http(fleet.base_uri, sql)
    _http(fleet.base_uri, "INSERT INTO memory.default.finv VALUES (2)")
    for _ in range(4):   # whichever worker we land on: fresh data
        _, rows, _ = _http(fleet.base_uri, sql)
        assert rows == [[2]]


def test_fleet_sticky_prepared_statements(fleet):
    """PREPARE through one connection, EXECUTE through another with NO
    prepared header: the fleet registry + bus + engine ingestion make
    the name resolve wherever the EXECUTE lands."""
    _http(fleet.base_uri,
          "PREPARE fleet_sticky FROM SELECT r_name FROM region "
          "WHERE r_regionkey = ?")
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        payload, rows, _ = _http(fleet.base_uri,
                                 "EXECUTE fleet_sticky USING 1")
        if rows == [["AMERICA"]]:
            return
        time.sleep(0.1)
    pytest.fail(f"sticky EXECUTE never resolved: {payload}")


def test_fleet_quota_rejects_fleet_wide(fleet):
    """The shared-memory token bucket binds across ALL workers: burst 2
    at rate 0 admits exactly 2 fast-path hits fleet-wide, then
    QUERY_QUEUE_FULL."""
    sql = "SELECT count(*) FROM supplier"
    hdr = {"X-Trino-Session": "resource_group=fleetq"}
    _http(fleet.base_uri, sql, headers=hdr)     # executes (miss path)
    ok = rejected = 0
    for _ in range(8):
        payload, _, _ = _http(fleet.base_uri, sql, headers=hdr)
        if payload["stats"]["state"] == "FINISHED":
            ok += 1
        elif payload.get("error", {}).get("errorName") == \
                "QUERY_QUEUE_FULL":
            rejected += 1
    assert rejected >= 1
    assert ok <= 2 + 1   # burst 2 (+1 if a race served pre-publish)


def test_fleet_aggregated_metrics_and_queries(fleet):
    """One scrape of the fleet port sees engine families AND per-worker
    fleet series; worker cache hits are ingested into the engine's
    tracker so system.runtime.queries reflects fleet traffic."""
    _http(fleet.base_uri, "EXECUTE fleet_probe USING 9")
    _http(fleet.base_uri, "EXECUTE fleet_probe USING 9")
    time.sleep(0.6)    # one hit-batch flush interval
    text = urllib.request.urlopen(f"{fleet.base_uri}/v1/metrics",
                                  timeout=15).read().decode()
    assert "trino_tpu_fleet_worker_hits" in text
    assert "trino_tpu_fleet_workers" in text
    assert "trino_tpu_plan_cache_hits" in text      # engine family
    _, rows, _ = _http(
        fleet.base_uri,
        "SELECT count(*) FROM system.runtime.queries "
        "WHERE query LIKE 'EXECUTE fleet_probe%'")
    assert rows[0][0] >= 1
    # group accounting aggregated on the engine: served_from_cache sees
    # worker-landed hits (exact counts ride the bus batches; queried
    # over SQL because the engine is a subprocess now — no in-process
    # groups object to reach into)
    _, rows, _ = _http(
        fleet.base_uri,
        "SELECT served_from_cache FROM "
        "system.runtime.resource_groups WHERE name = 'global'")
    assert rows and rows[0][0] >= 1


def test_fleet_rolling_restart_zero_drop(fleet):
    """The zero-drop upgrade: replace every worker mid-load; the closed
    loop sees no errors and every worker pid changes."""
    from trino_tpu.fleet.bench_client import run as client_run
    _http(fleet.base_uri, "EXECUTE fleet_probe USING 2")
    before = {r["pid"] for r in fleet.workers()}
    assert len(before) == 2
    result = {}

    def _restart():
        time.sleep(0.3)
        result["fresh"] = fleet.rolling_restart()

    th = threading.Thread(target=_restart, daemon=True)
    th.start()
    rec = client_run("127.0.0.1", fleet.port, duration_s=5.0,
                     warmup_s=0.0, threads=3, mode="hit",
                     probe="fleet_probe", values=25)
    th.join(timeout=60)
    after = {r["pid"] for r in fleet.workers()}
    assert rec["errors"] == 0, rec
    assert rec["completed"] > 50
    assert not (before & after), (before, after)
    assert len(after) == 2
    assert len(result.get("fresh", [])) == 2


# ------------------------------------------- single-process satellites


def test_server_quota_over_http(tmp_path):
    """Per-group QPS quota on the single-process fast path: over-quota
    hits answer QUERY_QUEUE_FULL and count as rejections, not serves."""
    from trino_tpu.exec import LocalQueryRunner
    from trino_tpu.server import TrinoServer
    rg = {"groups": [{"name": "capped", "result_cache_qps": 0,
                      "result_cache_qps_burst": 3}]}
    path = tmp_path / "rg.json"
    path.write_text(json.dumps(rg))
    srv = TrinoServer(LocalQueryRunner.tpch("tiny"),
                      resource_groups_path=str(path)).start()
    try:
        hdr = {"X-Trino-Session": "resource_group=capped"}
        _http(srv.base_uri, "SELECT count(*) FROM nation", headers=hdr)
        ok = rejected = 0
        for _ in range(8):
            payload, _, _ = _http(srv.base_uri,
                                  "SELECT count(*) FROM nation",
                                  headers=hdr)
            if payload["stats"]["state"] == "FINISHED":
                ok += 1
            else:
                assert payload["error"]["errorName"] == \
                    "QUERY_QUEUE_FULL"
                rejected += 1
        assert ok == 3 and rejected == 5
        g = srv.groups.get_or_create("capped")
        assert g.served_from_cache == 3
        assert g.cache_hit_rejections == 5
        # surfaced in the system table
        _, rows, _ = _http(
            srv.base_uri,
            "SELECT served_from_cache, cache_hit_rejections FROM "
            "system.runtime.resource_groups WHERE name = 'capped'")
        assert rows == [[3, 5]]
        # the deployment-knob docs are SQL-discoverable
        _, rows, _ = _http(
            srv.base_uri,
            "SELECT count(*) FROM system.runtime.server_properties "
            "WHERE name = 'drain_timeout_s'")
        assert rows == [[1]]
    finally:
        srv.stop()


def test_resource_group_config_hot_reload(tmp_path):
    """Editing the JSON re-applies on mtime change without a restart —
    limits AND quotas move; a malformed edit keeps the old tree."""
    from trino_tpu.exec import LocalQueryRunner
    from trino_tpu.server import TrinoServer
    path = tmp_path / "rg.json"
    path.write_text(json.dumps(
        {"groups": [{"name": "hot", "maxQueued": 7}]}))
    srv = TrinoServer(LocalQueryRunner.tpch("tiny"),
                      resource_groups_path=str(path)).start()
    try:
        assert srv.groups.get_or_create("hot").max_queued == 7
        path.write_text(json.dumps(
            {"groups": [{"name": "hot", "maxQueued": 3,
                         "resultCacheQps": 9}]}))
        os.utime(path, (time.time() + 5, time.time() + 5))
        srv._rg_watch._checked = 0.0   # skip the 1s stat throttle
        _http(srv.base_uri, "SELECT 1")    # any POST triggers the check
        g = srv.groups.get_or_create("hot")
        assert g.max_queued == 3 and g.result_cache_qps == 9
        assert srv._rg_reloads == 1
        # malformed edit: warn, keep serving with the previous config
        path.write_text("{not json")
        os.utime(path, (time.time() + 10, time.time() + 10))
        srv._rg_watch._checked = 0.0
        _http(srv.base_uri, "SELECT 1")
        assert srv.groups.get_or_create("hot").max_queued == 3
    finally:
        srv.stop()


def test_server_stop_drains_open_stream():
    """Satellite: stop() no longer strands open nextUri streams — a
    mid-pagination client finishes its result during the drain window,
    new POSTs are rejected, and teardown completes."""
    from trino_tpu.exec import LocalQueryRunner
    from trino_tpu.server import TrinoServer
    srv = TrinoServer(LocalQueryRunner.tpch("tiny"),
                      stream_ring_chunks=1, result_cache=False,
                      scan_cache=False).start()
    req = urllib.request.Request(f"{srv.base_uri}/v1/statement",
                                 data=b"SELECT c_custkey FROM customer",
                                 method="POST")
    payload = json.loads(urllib.request.urlopen(req, timeout=30).read())
    while "nextUri" in payload and not payload.get("data"):
        payload = json.loads(urllib.request.urlopen(
            payload["nextUri"], timeout=30).read())
    rows = list(payload.get("data", []))
    stopped = threading.Event()
    threading.Thread(target=lambda: (srv.stop(), stopped.set()),
                     daemon=True).start()
    time.sleep(0.2)
    assert not stopped.is_set()    # stream open: drain is waiting
    req2 = urllib.request.Request(f"{srv.base_uri}/v1/statement",
                                  data=b"SELECT 1", method="POST")
    rejected = json.loads(urllib.request.urlopen(req2, timeout=10).read())
    assert rejected["error"]["errorName"] == "SERVER_SHUTTING_DOWN"
    while "nextUri" in payload:
        payload = json.loads(urllib.request.urlopen(
            payload["nextUri"], timeout=30).read())
        rows.extend(payload.get("data", []))
    assert len(rows) == 1500
    assert payload["stats"]["state"] == "FINISHED"
    assert stopped.wait(20)


def test_server_stop_fast_when_idle():
    from trino_tpu.exec import LocalQueryRunner
    from trino_tpu.server import TrinoServer
    srv = TrinoServer(LocalQueryRunner.tpch("tiny")).start()
    t0 = time.monotonic()
    srv.stop()
    assert time.monotonic() - t0 < 5.0


def test_prometheus_merge():
    from trino_tpu.fleet.metrics import merge_prometheus
    a = ("# HELP m_total things\n# TYPE m_total counter\n"
         "m_total 3\nm_total{w=\"1\"} 2\n"
         "wall_seconds_sum 5.1e-05\n")
    b = ("# HELP m_total things\n# TYPE m_total counter\n"
         "m_total 4\nm_total{w=\"2\"} 5\n"
         "wall_seconds_sum 4.9e-05\n")
    merged = merge_prometheus([a, b])
    lines = merged.splitlines()
    assert "m_total 7" in lines
    assert 'm_total{w="1"} 2' in lines
    assert 'm_total{w="2"} 5' in lines
    assert lines.count("# TYPE m_total counter") == 1
    # negative-exponent floats are legal exposition (a 51us histogram
    # sum renders as 5.1e-05) and must merge, not silently drop
    summed = next(float(line.split()[1]) for line in lines
                  if line.startswith("wall_seconds_sum "))
    assert abs(summed - 1e-4) < 1e-9, summed
