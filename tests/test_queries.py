"""End-to-end SQL correctness vs the sqlite oracle.

Reference parity: testing/trino-testing AbstractTestQueries +
AbstractTestAggregations + AbstractTestJoinQueries, instantiated over the
tpch tiny schema with H2-style oracle comparison (QueryAssertions.java).
Engine and oracle read the SAME generated data; oracle SQL is adapted for
scaled-int decimals (see tests/oracle.py).
"""

import pytest

from trino_tpu.exec import LocalQueryRunner
from trino_tpu.expr.functions import days_from_civil

from oracle import assert_same, load_tpch_sqlite

SF = 0.01


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch("tiny")


@pytest.fixture(scope="module")
def oracle():
    conn = load_tpch_sqlite(SF)
    yield conn
    conn.close()


def d(text: str) -> int:
    y, m, dd = text.split("-")
    return days_from_civil(int(y), int(m), int(dd))


def check(runner, oracle, engine_sql, oracle_sql=None, ordered=False):
    got = runner.execute(engine_sql)
    cur = oracle.execute(oracle_sql or engine_sql)
    expected = cur.fetchall()
    assert_same(got.rows, expected, ordered)
    return got


# ----------------------------------------------------------- basic queries

def test_select_constants(runner):
    assert runner.execute("SELECT 1, 'x', true, 1.5e0").rows == \
        [(1, "x", True, 1.5)]


def test_scan_and_filter(runner, oracle):
    check(runner, oracle,
          "SELECT n_nationkey, n_name FROM nation WHERE n_regionkey = 1")


def test_arithmetic_and_aliases(runner, oracle):
    check(runner, oracle,
          "SELECT n_nationkey + 100, n_nationkey * 2 FROM nation")


def test_order_by_limit(runner, oracle):
    check(runner, oracle,
          "SELECT n_name FROM nation ORDER BY n_name DESC LIMIT 5",
          ordered=True)


def test_distinct(runner, oracle):
    check(runner, oracle,
          "SELECT DISTINCT n_regionkey FROM nation")


def test_in_list_and_between(runner, oracle):
    check(runner, oracle,
          "SELECT n_name FROM nation WHERE n_regionkey IN (0, 3) "
          "AND n_nationkey BETWEEN 5 AND 20")


def test_case_expression(runner, oracle):
    check(runner, oracle,
          "SELECT n_name, CASE WHEN n_regionkey = 0 THEN 'africa' "
          "WHEN n_regionkey = 1 THEN 'america' ELSE 'other' END FROM nation")


def test_string_functions(runner, oracle):
    check(runner, oracle,
          "SELECT upper(n_name), length(n_name), substr(n_name, 1, 3) "
          "FROM nation",
          "SELECT upper(n_name), length(n_name), substr(n_name, 1, 3) "
          "FROM nation")


def test_like(runner, oracle):
    check(runner, oracle,
          "SELECT n_name FROM nation WHERE n_name LIKE '%IA'")


def test_null_handling(runner):
    rows = runner.execute(
        "SELECT NULL IS NULL, 1 + CAST(NULL AS bigint), "
        "coalesce(NULL, 7)").rows
    assert rows == [(True, None, 7)]


def test_aggregations(runner, oracle):
    check(runner, oracle,
          "SELECT count(*), sum(n_regionkey), min(n_name), max(n_name) "
          "FROM nation")


def test_group_by_having(runner, oracle):
    check(runner, oracle,
          "SELECT n_regionkey, count(*) FROM nation GROUP BY n_regionkey "
          "HAVING count(*) >= 5")


def test_agg_filter_clause(runner, oracle):
    check(runner, oracle,
          "SELECT count(*) FILTER (WHERE n_regionkey = 2) FROM nation",
          "SELECT count(CASE WHEN n_regionkey = 2 THEN 1 END) FROM nation")


def test_join_inner(runner, oracle):
    check(runner, oracle,
          "SELECT n_name, r_name FROM nation JOIN region "
          "ON n_regionkey = r_regionkey")


def test_join_left_with_condition(runner, oracle):
    check(runner, oracle,
          "SELECT r_name, n_name FROM region LEFT JOIN nation "
          "ON r_regionkey = n_regionkey AND n_name LIKE 'A%'")


def test_implicit_join(runner, oracle):
    check(runner, oracle,
          "SELECT s_name, n_name FROM supplier, nation "
          "WHERE s_nationkey = n_nationkey AND n_regionkey = 2")


def test_union(runner, oracle):
    check(runner, oracle,
          "SELECT n_regionkey FROM nation UNION SELECT r_regionkey + 3 "
          "FROM region")
    check(runner, oracle,
          "SELECT n_regionkey FROM nation UNION ALL SELECT r_regionkey "
          "FROM region")


def test_subquery_in(runner, oracle):
    check(runner, oracle,
          "SELECT n_name FROM nation WHERE n_regionkey IN "
          "(SELECT r_regionkey FROM region WHERE r_name LIKE 'A%')")


def test_scalar_subquery(runner, oracle):
    check(runner, oracle,
          "SELECT n_name FROM nation "
          "WHERE n_nationkey > (SELECT avg(n_nationkey) FROM nation)",
          "SELECT n_name FROM nation "
          "WHERE n_nationkey > (SELECT avg(n_nationkey) FROM nation)")


def test_exists_correlated(runner, oracle):
    check(runner, oracle,
          "SELECT r_name FROM region WHERE EXISTS "
          "(SELECT 1 FROM nation WHERE n_regionkey = r_regionkey "
          "AND n_name LIKE 'I%')")


def test_not_exists_correlated(runner, oracle):
    check(runner, oracle,
          "SELECT c_custkey FROM customer WHERE NOT EXISTS "
          "(SELECT 1 FROM orders WHERE o_custkey = c_custkey) "
          "ORDER BY c_custkey LIMIT 20", ordered=True)


def test_cte(runner, oracle):
    check(runner, oracle,
          "WITH r AS (SELECT r_regionkey k FROM region WHERE r_regionkey < 3) "
          "SELECT n_name FROM nation, r WHERE n_regionkey = k")


def test_values(runner):
    rows = runner.execute("SELECT * FROM (VALUES (1, 'a'), (2, 'b')) "
                          "t(x, y) ORDER BY x DESC").rows
    assert rows == [(2, "b"), (1, "a")]


def test_rollup(runner, oracle):
    check(runner, oracle,
          "SELECT n_regionkey, count(*) FROM nation GROUP BY ROLLUP "
          "(n_regionkey)",
          "SELECT n_regionkey, count(*) FROM nation GROUP BY n_regionkey "
          "UNION ALL SELECT NULL, count(*) FROM nation")


def test_date_functions(runner, oracle):
    check(runner, oracle,
          "SELECT o_orderkey, year(o_orderdate) FROM orders "
          "WHERE o_orderkey <= 50",
          f"SELECT o_orderkey, CAST(strftime('%Y', o_orderdate * 86400, "
          f"'unixepoch') AS INTEGER) FROM orders WHERE o_orderkey <= 50")


def test_ctas_insert_memory(runner):
    runner.execute("CREATE TABLE memory.default.t_ctas AS "
                   "SELECT n_nationkey, n_name FROM nation "
                   "WHERE n_regionkey = 0")
    out = runner.execute("SELECT count(*) FROM memory.default.t_ctas")
    assert out.only_value() == 5
    runner.execute("INSERT INTO memory.default.t_ctas "
                   "SELECT n_nationkey, n_name FROM nation "
                   "WHERE n_regionkey = 1")
    out = runner.execute(
        "SELECT count(*), min(n_name) FROM memory.default.t_ctas")
    assert out.rows[0][0] == 10
    runner.execute("DROP TABLE memory.default.t_ctas")


def test_show_and_explain(runner):
    tables = runner.execute("SHOW TABLES")
    assert ("lineitem",) in tables.rows
    out = runner.execute("EXPLAIN (TYPE LOGICAL) SELECT count(*) FROM nation")
    assert "Aggregation" in out.only_value()
    out = runner.execute("EXPLAIN SELECT sum(l_quantity) FROM lineitem")
    assert "Fragment" in out.only_value()


# ------------------------------------------------------------ TPC-H queries

def test_tpch_q1(runner, oracle):
    engine = """
SELECT l_returnflag, l_linestatus, sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
       count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus"""
    # decimals are scaled ints in the oracle: discount/tax scale 2 -> the
    # literal 1 is 100; products accumulate scale 4 and 6
    o = f"""
SELECT l_returnflag, l_linestatus, sum(l_quantity),
       sum(l_extendedprice),
       sum(l_extendedprice * (100 - l_discount)),
       sum(l_extendedprice * (100 - l_discount) * (100 + l_tax)),
       count(*)
FROM lineitem
WHERE l_shipdate <= {d('1998-12-01') - 90}
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus"""
    check(runner, oracle, engine, o, ordered=True)


def test_tpch_q3(runner, oracle):
    engine = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate, l_orderkey
LIMIT 10"""
    o = f"""
SELECT l_orderkey, sum(l_extendedprice * (100 - l_discount)),
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey AND o_orderdate < {d('1995-03-15')}
  AND l_shipdate > {d('1995-03-15')}
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY 2 DESC, o_orderdate, l_orderkey
LIMIT 10"""
    check(runner, oracle, engine, o, ordered=True)


def test_tpch_q5(runner, oracle):
    engine = """
SELECT n_name, sum(l_extendedprice * (1 - l_discount)) AS revenue
FROM customer, orders, lineitem, supplier, nation, region
WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey
  AND l_suppkey = s_suppkey AND c_nationkey = s_nationkey
  AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
  AND r_name = 'ASIA' AND o_orderdate >= DATE '1994-01-01'
  AND o_orderdate < DATE '1994-01-01' + INTERVAL '1' YEAR
GROUP BY n_name ORDER BY revenue DESC, n_name"""
    o = f"""
SELECT n_name, sum(l_extendedprice * (100 - l_discount))
FROM customer, orders, lineitem, supplier, nation, region
WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey
  AND l_suppkey = s_suppkey AND c_nationkey = s_nationkey
  AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
  AND r_name = 'ASIA' AND o_orderdate >= {d('1994-01-01')}
  AND o_orderdate < {d('1995-01-01')}
GROUP BY n_name ORDER BY 2 DESC, n_name"""
    check(runner, oracle, engine, o, ordered=True)


def test_tpch_q6(runner, oracle):
    engine = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
  AND l_discount BETWEEN 0.06 - 0.01 AND 0.06 + 0.01
  AND l_quantity < 24"""
    o = f"""
SELECT sum(l_extendedprice * l_discount)
FROM lineitem
WHERE l_shipdate >= {d('1994-01-01')} AND l_shipdate < {d('1995-01-01')}
  AND l_discount BETWEEN 5 AND 7 AND l_quantity < 2400"""
    check(runner, oracle, engine, o)


def test_tpch_q13(runner, oracle):
    engine = """
SELECT c_count, count(*) AS custdist
FROM (SELECT c_custkey, count(o_orderkey) AS c_count
      FROM customer LEFT OUTER JOIN orders
        ON c_custkey = o_custkey AND o_comment NOT LIKE '%special%'
      GROUP BY c_custkey) AS c_orders
GROUP BY c_count ORDER BY custdist DESC, c_count DESC"""
    o = """
SELECT c_count, count(*) AS custdist
FROM (SELECT c_custkey, count(o_orderkey) AS c_count
      FROM customer LEFT OUTER JOIN orders
        ON c_custkey = o_custkey AND o_comment NOT LIKE '%special%'
      GROUP BY c_custkey) AS c_orders
GROUP BY c_count ORDER BY custdist DESC, c_count DESC"""
    check(runner, oracle, engine, o, ordered=True)


def test_tpch_q14(runner, oracle):
    engine = """
SELECT sum(CASE WHEN p_type LIKE 'PROMO%'
                THEN l_extendedprice * (1 - l_discount) ELSE 0 END) AS promo,
       sum(l_extendedprice * (1 - l_discount)) AS total
FROM lineitem, part
WHERE l_partkey = p_partkey AND l_shipdate >= DATE '1995-09-01'
  AND l_shipdate < DATE '1995-10-01'"""
    o = f"""
SELECT sum(CASE WHEN p_type LIKE 'PROMO%'
                THEN l_extendedprice * (100 - l_discount) ELSE 0 END),
       sum(l_extendedprice * (100 - l_discount))
FROM lineitem, part
WHERE l_partkey = p_partkey AND l_shipdate >= {d('1995-09-01')}
  AND l_shipdate < {d('1995-10-01')}"""
    check(runner, oracle, engine, o)


def test_tpch_q18(runner, oracle):
    engine = """
SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
       sum(l_quantity)
FROM customer, orders, lineitem
WHERE o_orderkey IN (SELECT l_orderkey FROM lineitem
                     GROUP BY l_orderkey HAVING sum(l_quantity) > 200)
  AND c_custkey = o_custkey AND o_orderkey = l_orderkey
GROUP BY c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
ORDER BY o_totalprice DESC, o_orderdate, o_orderkey LIMIT 100"""
    o = """
SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
       sum(l_quantity)
FROM customer, orders, lineitem
WHERE o_orderkey IN (SELECT l_orderkey FROM lineitem
                     GROUP BY l_orderkey HAVING sum(l_quantity) > 20000)
  AND c_custkey = o_custkey AND o_orderkey = l_orderkey
GROUP BY c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
ORDER BY o_totalprice DESC, o_orderdate, o_orderkey LIMIT 100"""
    check(runner, oracle, engine, o, ordered=True)


def test_tpch_q22(runner, oracle):
    engine = """
SELECT cntrycode, count(*) AS numcust, sum(c_acctbal) AS totacctbal
FROM (SELECT substring(c_phone, 1, 2) AS cntrycode, c_acctbal
      FROM customer
      WHERE substring(c_phone, 1, 2) IN ('13', '31', '23', '29', '30')
        AND c_acctbal > (SELECT avg(c_acctbal) FROM customer
                         WHERE c_acctbal > 0.00)
        AND NOT EXISTS (SELECT * FROM orders WHERE o_custkey = c_custkey))
     AS custsale
GROUP BY cntrycode ORDER BY cntrycode"""
    o = """
SELECT cntrycode, count(*) AS numcust, sum(c_acctbal) AS totacctbal
FROM (SELECT substr(c_phone, 1, 2) AS cntrycode, c_acctbal
      FROM customer
      WHERE substr(c_phone, 1, 2) IN ('13', '31', '23', '29', '30')
        AND c_acctbal > (SELECT avg(c_acctbal) FROM customer
                         WHERE c_acctbal > 0)
        AND NOT EXISTS (SELECT * FROM orders WHERE o_custkey = c_custkey))
     AS custsale
GROUP BY cntrycode ORDER BY cntrycode"""
    check(runner, oracle, engine, o, ordered=True)


# ----------------------------------------------- round-2 regression fixes

def test_correlated_count_subquery_zero(runner, oracle):
    # count over an empty correlated group must be 0, not NULL
    # (TransformCorrelatedScalarAggregationToJoin semantics)
    check(runner, oracle,
          "SELECT n_name, (SELECT count(*) FROM supplier"
          " WHERE s_nationkey = n_nationkey) FROM nation")


def test_correlated_count_in_predicate(runner, oracle):
    check(runner, oracle,
          "SELECT n_name FROM nation WHERE "
          "(SELECT count(*) FROM supplier WHERE s_nationkey = n_nationkey)"
          " = 0")


def test_exists_with_having_rejected(runner):
    import pytest as _pytest
    from trino_tpu.sql.analyzer import SemanticError
    with _pytest.raises(SemanticError):
        runner.execute(
            "SELECT n_name FROM nation WHERE EXISTS (SELECT s_nationkey "
            "FROM supplier WHERE s_nationkey = n_nationkey "
            "GROUP BY s_nationkey HAVING count(*) > 5)")


def test_union_mixed_dictionaries_sorted(runner, oracle):
    # varchar columns from different tables have different dictionaries;
    # the union must re-encode before the blocking sort
    check(runner, oracle,
          "SELECT name FROM (SELECT n_name AS name FROM nation "
          "UNION ALL SELECT r_name AS name FROM region) t ORDER BY name",
          ordered=True)


def test_union_mixed_dictionaries_groupby(runner, oracle):
    check(runner, oracle,
          "SELECT name, count(*) FROM (SELECT n_name AS name FROM nation "
          "UNION ALL SELECT r_name AS name FROM region) t GROUP BY name")


def test_nullif_keeps_first_arg_type(runner):
    out = runner.execute("SELECT NULLIF(1, 1), NULLIF(2, 3)")
    assert out.rows == [(None, 2)]


def test_tpch_q9(runner, oracle):
    # 6-way implicit join: requires cross-join elimination + reordering
    # (BASELINE ladder config #4; ReorderJoins.java:96 analog)
    sql = """
SELECT nation, o_year, sum(amount) AS sum_profit FROM (
  SELECT n_name AS nation, extract(year FROM o_orderdate) AS o_year,
         l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity
           AS amount
  FROM part, supplier, lineitem, partsupp, orders, nation
  WHERE s_suppkey = l_suppkey AND ps_suppkey = l_suppkey
    AND ps_partkey = l_partkey AND p_partkey = l_partkey
    AND o_orderkey = l_orderkey AND s_nationkey = n_nationkey
    AND p_name LIKE '%green%'
) profit GROUP BY nation, o_year ORDER BY nation, o_year DESC
"""
    oracle_sql = """
SELECT nation, o_year, sum(amount) AS sum_profit FROM (
  SELECT n_name AS nation,
         CAST(strftime('%Y', o_orderdate * 86400, 'unixepoch') AS INTEGER)
           AS o_year,
         l_extendedprice * (100 - l_discount) - ps_supplycost * l_quantity
           AS amount
  FROM part, supplier, lineitem, partsupp, orders, nation
  WHERE s_suppkey = l_suppkey AND ps_suppkey = l_suppkey
    AND ps_partkey = l_partkey AND p_partkey = l_partkey
    AND o_orderkey = l_orderkey AND s_nationkey = n_nationkey
    AND p_name LIKE '%green%'
) profit GROUP BY nation, o_year ORDER BY nation, o_year DESC
"""
    check(runner, oracle, sql, oracle_sql, ordered=True)


def test_join_reorder_no_cross(runner):
    # the q9 join graph must plan with zero cross joins
    plan = runner.execute("""EXPLAIN
SELECT count(*) FROM part, supplier, lineitem, partsupp, orders, nation
WHERE s_suppkey = l_suppkey AND ps_suppkey = l_suppkey
  AND ps_partkey = l_partkey AND p_partkey = l_partkey
  AND o_orderkey = l_orderkey AND s_nationkey = n_nationkey
""").rows[0][0]
    assert "cross" not in plan.lower(), plan


def test_tpch_q21(runner, oracle):
    # general correlated EXISTS/NOT EXISTS with non-equality correlation
    sql = """
SELECT s_name, count(*) AS numwait
FROM supplier, lineitem l1, orders, nation
WHERE s_suppkey = l1.l_suppkey AND o_orderkey = l1.l_orderkey
  AND o_orderstatus = 'F' AND l1.l_receiptdate > l1.l_commitdate
  AND EXISTS (SELECT * FROM lineitem l2
              WHERE l2.l_orderkey = l1.l_orderkey
                AND l2.l_suppkey <> l1.l_suppkey)
  AND NOT EXISTS (SELECT * FROM lineitem l3
                  WHERE l3.l_orderkey = l1.l_orderkey
                    AND l3.l_suppkey <> l1.l_suppkey
                    AND l3.l_receiptdate > l3.l_commitdate)
  AND s_nationkey = n_nationkey AND n_name = 'SAUDI ARABIA'
GROUP BY s_name ORDER BY numwait DESC, s_name LIMIT 100
"""
    check(runner, oracle, sql, sql, ordered=True)


# -------------------------------------------------------- window functions

def test_window_ranking(runner, oracle):
    check(runner, oracle,
          "SELECT n_name, row_number() OVER (PARTITION BY n_regionkey "
          "ORDER BY n_name), rank() OVER (PARTITION BY n_regionkey "
          "ORDER BY n_name), dense_rank() OVER (PARTITION BY n_regionkey "
          "ORDER BY n_name) FROM nation")


def test_window_rank_with_ties(runner, oracle):
    check(runner, oracle,
          "SELECT s_suppkey, rank() OVER (ORDER BY s_nationkey), "
          "dense_rank() OVER (ORDER BY s_nationkey) FROM supplier")


def test_window_running_agg(runner, oracle):
    check(runner, oracle,
          "SELECT n_name, sum(n_nationkey) OVER (PARTITION BY n_regionkey "
          "ORDER BY n_name), count(*) OVER (PARTITION BY n_regionkey "
          "ORDER BY n_name), min(n_name) OVER (PARTITION BY n_regionkey "
          "ORDER BY n_name), max(n_nationkey) OVER (PARTITION BY "
          "n_regionkey ORDER BY n_name) FROM nation")


def test_window_whole_partition(runner, oracle):
    check(runner, oracle,
          "SELECT n_name, sum(n_nationkey) OVER (PARTITION BY n_regionkey), "
          "count(*) OVER () FROM nation")


def test_window_lead_lag(runner, oracle):
    check(runner, oracle,
          "SELECT n_name, lead(n_name) OVER (ORDER BY n_name), "
          "lag(n_name) OVER (ORDER BY n_name), "
          "lag(n_nationkey, 2) OVER (ORDER BY n_name) FROM nation")


def test_window_first_last_value(runner, oracle):
    check(runner, oracle,
          "SELECT n_name, first_value(n_name) OVER (PARTITION BY "
          "n_regionkey ORDER BY n_name), last_value(n_name) OVER "
          "(PARTITION BY n_regionkey ORDER BY n_name) FROM nation")


def test_window_pct_cume_ntile(runner, oracle):
    check(runner, oracle,
          "SELECT s_suppkey, percent_rank() OVER (ORDER BY s_nationkey), "
          "cume_dist() OVER (ORDER BY s_nationkey), "
          "ntile(3) OVER (ORDER BY s_suppkey) FROM supplier")


def test_window_rows_frame(runner, oracle):
    check(runner, oracle,
          "SELECT n_name, sum(n_nationkey) OVER (ORDER BY n_name "
          "ROWS BETWEEN UNBOUNDED PRECEDING AND CURRENT ROW) FROM nation")


# ------------------------------------------------- outer joins (round 3)

@pytest.fixture(scope="module")
def outer_runner():
    r = LocalQueryRunner.tpch("tiny")
    r.execute("CREATE TABLE memory.default.lft (k bigint, a varchar)")
    r.execute("INSERT INTO memory.default.lft VALUES "
              "(1, 'one'), (2, 'two'), (NULL, 'nil'), (5, 'five')")
    r.execute("CREATE TABLE memory.default.rgt (k bigint, b varchar)")
    r.execute("INSERT INTO memory.default.rgt VALUES "
              "(1, 'uno'), (3, 'tres'), (NULL, 'nul')")
    return r


def test_full_outer_join_sql(outer_runner):
    rows = sorted(outer_runner.execute(
        "SELECT l.k, a, r.k, b FROM memory.default.lft l "
        "FULL OUTER JOIN memory.default.rgt r ON l.k = r.k").rows, key=str)
    assert rows == sorted([
        (1, "one", 1, "uno"), (2, "two", None, None),
        (None, "nil", None, None), (5, "five", None, None),
        (None, None, 3, "tres"), (None, None, None, "nul")], key=str)


def test_right_outer_join_sql(outer_runner):
    rows = sorted(outer_runner.execute(
        "SELECT l.k, a, r.k, b FROM memory.default.lft l "
        "RIGHT JOIN memory.default.rgt r ON l.k = r.k").rows, key=str)
    assert rows == sorted([
        (1, "one", 1, "uno"), (None, None, 3, "tres"),
        (None, None, None, "nul")], key=str)


def test_in_subquery_null_build_3vl(outer_runner):
    # 4 not in rgt, but rgt.k contains NULL -> NULL (filtered out by WHERE,
    # and visible as NULL when selected)
    rows = outer_runner.execute(
        "SELECT k, k IN (SELECT k FROM memory.default.rgt) "
        "FROM memory.default.lft").rows
    got = {r[0]: r[1] for r in rows}
    assert got[1] is True
    assert got[2] is None        # no match + NULL in subquery -> NULL
    assert got[None] is None
    assert got[5] is None


def test_not_in_null_build_filters_all(outer_runner):
    # NOT IN against a subquery containing NULL: membership is UNKNOWN for
    # every non-matching row, so WHERE keeps nothing but definite matches'
    # complement — here, nothing at all (Trino 3VL; round-3 caveat removed)
    rows = outer_runner.execute(
        "SELECT k FROM memory.default.lft "
        "WHERE k NOT IN (SELECT k FROM memory.default.rgt)").rows
    assert rows == []


def test_not_in_null_free_build(outer_runner):
    rows = outer_runner.execute(
        "SELECT k FROM memory.default.lft "
        "WHERE k NOT IN (SELECT k FROM memory.default.rgt "
        "                WHERE k IS NOT NULL)").rows
    # NULL probe key -> UNKNOWN against non-empty build -> filtered
    assert sorted(r[0] for r in rows) == [2, 5]


def test_not_in_empty_build_keeps_all(outer_runner):
    rows = outer_runner.execute(
        "SELECT k FROM memory.default.lft "
        "WHERE k NOT IN (SELECT k FROM memory.default.rgt WHERE k > 99)").rows
    # x NOT IN (empty) is TRUE, even for NULL x
    assert sorted((r[0] is None, r[0]) for r in rows) == \
        [(False, 1), (False, 2), (False, 5), (True, None)]


def test_not_exists_keeps_null_key_rows(outer_runner):
    # NOT EXISTS: a NULL correlation key never matches -> row kept (EXISTS
    # anti semantics differ from NOT IN: no 3VL escalation from build NULLs)
    rows = outer_runner.execute(
        "SELECT a FROM memory.default.lft l WHERE NOT EXISTS ("
        "SELECT 1 FROM memory.default.rgt r WHERE r.k = l.k)").rows
    assert sorted(r[0] for r in rows) == ["five", "nil", "two"]


def test_in_null_probe_empty_build_is_false(outer_runner):
    rows = outer_runner.execute(
        "SELECT k, k IN (SELECT k FROM memory.default.rgt WHERE k > 99) "
        "FROM memory.default.lft").rows
    # IN over an empty set is FALSE for every probe value, including NULL
    assert all(r[1] is False for r in rows)


def test_lag_varchar_with_default(outer_runner):
    # dictionary-encoded arg + literal default: codes must be re-encoded
    # onto a union pool, not decoded through the arg's dictionary
    rows = outer_runner.execute(
        "SELECT k, lag(a, 1, 'zzz') OVER (ORDER BY k) "
        "FROM memory.default.lft WHERE k IS NOT NULL").rows
    got = sorted([r for r in rows], key=lambda r: r[0])
    assert got == [(1, "zzz"), (2, "one"), (5, "two")]


# ------------------------------------------- DISTINCT aggregation (round 3)

def test_count_distinct_global(runner, oracle):
    check(runner, oracle,
          "SELECT count(DISTINCT o_orderstatus) FROM orders")


def test_count_distinct_grouped(runner, oracle):
    check(runner, oracle,
          "SELECT o_orderpriority, count(DISTINCT o_orderstatus), count(*) "
          "FROM orders GROUP BY o_orderpriority")


def test_sum_avg_distinct(runner, oracle):
    check(runner, oracle,
          "SELECT c_mktsegment, sum(DISTINCT c_nationkey), "
          "count(DISTINCT c_nationkey) FROM customer GROUP BY c_mktsegment")


def test_count_distinct_with_nulls(outer_runner):
    rows = outer_runner.execute(
        "SELECT count(DISTINCT k), count(k), count(*) "
        "FROM memory.default.lft").rows
    assert rows == [(3, 3, 4)]


def test_count_distinct_mixed_with_plain(runner, oracle):
    check(runner, oracle,
          "SELECT l_returnflag, count(DISTINCT l_shipmode), sum(l_quantity) "
          "FROM lineitem GROUP BY l_returnflag")


# ------------------------------------------------ EXPLAIN ANALYZE (round 3)

def test_explain_analyze(runner):
    text = runner.execute(
        "EXPLAIN ANALYZE SELECT l_returnflag, count(*) FROM lineitem "
        "GROUP BY l_returnflag").only_value()
    assert "Aggregation" in text and "TableScan" in text
    assert "output:" in text and "rows" in text and "ms" in text
    # scan emitted the full table; agg reduced to the flag count
    assert "output: 3 rows" in text


def test_explain_analyze_runs_query_once(runner):
    # ANALYZE executes: verify row counts come from a real run
    text = runner.execute(
        "EXPLAIN ANALYZE SELECT * FROM nation WHERE n_regionkey = 1"
    ).only_value()
    assert "output: 5 rows" in text


# ----------------------------------- full TPC-H suite vs oracle (round 3)

from tpch_sql import PASSING, QUERIES  # noqa: E402


@pytest.mark.parametrize("name", PASSING)
def test_tpch_suite_vs_oracle(runner, oracle, name):
    engine_sql, oracle_sql, ordered = QUERIES[name]
    check(runner, oracle, engine_sql, oracle_sql, ordered)


def test_order_by_unselected_column(runner, oracle):
    check(runner, oracle,
          "SELECT c_custkey FROM customer ORDER BY c_acctbal, c_custkey "
          "LIMIT 10", ordered=True)


def test_order_by_unselected_expression(runner, oracle):
    check(runner, oracle,
          "SELECT n_name FROM nation ORDER BY n_regionkey * 100 + "
          "n_nationkey LIMIT 7", ordered=True)


def test_order_by_alias_wins_over_source(runner):
    # output alias shadows the source column in ORDER BY scope
    rows = runner.execute(
        "SELECT n_nationkey, 25 - n_nationkey AS o "
        "FROM nation ORDER BY o LIMIT 3").rows
    assert [r[1] for r in rows] == [1, 2, 3]


# --------------------------------------------- bounded frames (round 3)

def test_window_bounded_rows_frame(runner, oracle):
    check(runner, oracle,
          "SELECT n_nationkey, "
          "sum(n_nationkey) OVER (ORDER BY n_nationkey "
          "ROWS BETWEEN 2 PRECEDING AND CURRENT ROW), "
          "min(n_nationkey) OVER (ORDER BY n_nationkey "
          "ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) FROM nation")


def test_window_bounded_frame_partitioned(runner, oracle):
    check(runner, oracle,
          "SELECT s_suppkey, "
          "avg(s_suppkey) OVER (PARTITION BY s_nationkey ORDER BY s_suppkey "
          "ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING), "
          "sum(s_acctbal) OVER (PARTITION BY s_nationkey ORDER BY s_suppkey "
          "ROWS BETWEEN CURRENT ROW AND 2 FOLLOWING) FROM supplier")


def test_window_frame_unbounded_following(runner, oracle):
    check(runner, oracle,
          "SELECT n_nationkey, "
          "max(n_nationkey) OVER (ORDER BY n_nationkey "
          "ROWS BETWEEN 1 FOLLOWING AND UNBOUNDED FOLLOWING), "
          "first_value(n_name) OVER (ORDER BY n_nationkey "
          "ROWS BETWEEN 1 PRECEDING AND 1 FOLLOWING) FROM nation")


def test_nth_value_nonpositive_rejected(outer_runner):
    # window/NthValueFunction: INVALID_FUNCTION_ARGUMENT for n <= 0
    import pytest as _pytest
    with _pytest.raises(Exception, match="NTH_VALUE must be greater"):
        outer_runner.execute(
            "SELECT nth_value(a, 0) OVER (ORDER BY k) "
            "FROM memory.default.lft")


def test_dynamic_filtering_matches_disabled(runner, oracle):
    # build-side key range prefilter must not change INNER join results
    sql = ("SELECT o_orderkey, o_totalprice FROM orders, customer "
           "WHERE o_custkey = c_custkey AND c_custkey BETWEEN 40 AND 55")
    runner.execute("SET SESSION enable_dynamic_filtering = false")
    try:
        off = runner.execute(sql).rows
    finally:
        runner.execute("RESET SESSION enable_dynamic_filtering")
    on = runner.execute(sql).rows
    assert sorted(off) == sorted(on)
    cur = oracle.execute(sql)
    assert_same(on, cur.fetchall(), ordered=False)


def test_spilled_join_matches_inmemory(runner, oracle):
    # force the spill path (build keys only in HBM, host-side attach)
    sql = ("SELECT o_orderkey, c_name FROM orders, customer "
           "WHERE o_custkey = c_custkey AND o_orderkey <= 100")
    runner.execute("SET SESSION join_spill_threshold_bytes = 1024")
    try:
        spilled = runner.execute(sql).rows
    finally:
        runner.execute("RESET SESSION join_spill_threshold_bytes")
    normal = runner.execute(sql).rows
    assert sorted(spilled) == sorted(normal)
    assert_same(spilled, oracle.execute(sql).fetchall(), ordered=False)


def test_spilled_composite_key_join(runner, oracle):
    sql = ("SELECT l_orderkey, l_linenumber, ps_availqty "
           "FROM lineitem, partsupp "
           "WHERE l_partkey = ps_partkey AND l_suppkey = ps_suppkey "
           "AND l_orderkey <= 40")
    runner.execute("SET SESSION join_spill_threshold_bytes = 1024")
    try:
        spilled = runner.execute(sql).rows
    finally:
        runner.execute("RESET SESSION join_spill_threshold_bytes")
    assert_same(spilled, oracle.execute(sql).fetchall(), ordered=False)


def test_spilled_nonunique_build_falls_back(runner, oracle):
    # build side (lineitem keyed by l_orderkey) has duplicate keys: the
    # spill path must detect it and fall back to the expansion kernel
    sql = ("SELECT o_orderkey, l_linenumber FROM orders, lineitem "
           "WHERE o_orderkey = l_orderkey AND o_orderkey <= 30")
    runner.execute("SET SESSION join_spill_threshold_bytes = 1024")
    try:
        spilled = runner.execute(sql).rows
    finally:
        runner.execute("RESET SESSION join_spill_threshold_bytes")
    assert_same(spilled, oracle.execute(sql).fetchall(), ordered=False)
