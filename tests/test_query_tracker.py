"""QueryTracker state machine + registry semantics.

Reference parity: execution/QueryStateMachine.java — legal edges only
(QUEUED -> RUNNING -> FINISHED|FAILED|CANCELED, QUEUED -> FAILED|CANCELED
for admission failures and pre-run cancels), terminal states are final,
and concurrent readers never see a terminal state without its stats.
"""

import json
import urllib.request

import pytest

from trino_tpu.exec.query_tracker import (CANCELED, FAILED, FINISHED,
                                          QUEUED, RUNNING, QueryTracker)


def _begin(tracker, sql="SELECT 1"):
    return tracker.begin(sql)


def test_happy_path_transitions():
    t = QueryTracker()
    info = _begin(t)
    assert info.state == QUEUED
    t.running(info)
    assert info.state == RUNNING and info.started is not None
    t.finish(info, rows=3)
    assert info.state == FINISHED and info.rows == 3


def test_illegal_transitions_rejected():
    t = QueryTracker()
    info = _begin(t)
    t.running(info)
    t.finish(info, rows=1)
    # FINISHED is terminal: no resurrection, no re-finish, no fail
    with pytest.raises(ValueError):
        t.running(info)
    with pytest.raises(ValueError):
        t.finish(info, rows=2)
    with pytest.raises(ValueError):
        t.fail(info, "late failure")
    assert info.state == FINISHED and info.rows == 1


def test_finish_requires_running():
    t = QueryTracker()
    info = _begin(t)
    with pytest.raises(ValueError):
        t.finish(info, rows=1)      # QUEUED -> FINISHED skips RUNNING
    t.fail(info, "admission failed", error_name="QUERY_QUEUE_FULL")
    assert info.state == FAILED     # QUEUED -> FAILED is legal


def test_canceled_is_terminal():
    t = QueryTracker()
    info = _begin(t)
    t.running(info)
    t.cancel(info)
    assert info.state == CANCELED
    assert info.error_name == "USER_CANCELED"
    # cancel of a terminal query is a no-op (first writer wins) ...
    t.cancel(info, "second cancel")
    assert info.error == "Query was canceled by user"
    # ... but RUNNING/FINISHED transitions out of CANCELED are illegal
    with pytest.raises(ValueError):
        t.running(info)
    with pytest.raises(ValueError):
        t.finish(info, rows=1)
    assert info.state == CANCELED


def test_cancel_races_finish_first_writer_wins():
    t = QueryTracker()
    info = _begin(t)
    t.running(info)
    t.finish(info, rows=5)
    t.cancel(info)                  # raced and lost: no-op
    assert info.state == FINISHED and info.rows == 5


def test_registry_prunes_terminal_only():
    t = QueryTracker(keep=3)
    infos = [_begin(t, f"SELECT {i}") for i in range(3)]
    for info in infos:
        t.running(info)
        t.finish(info, rows=0)
    live = _begin(t, "SELECT 'live'")
    t.running(live)                 # RUNNING: must never be pruned
    _begin(t, "SELECT 'new'")       # pushes registry past keep
    ids = {q.query_id for q in t.list()}
    assert live.query_id in ids
    assert infos[0].query_id not in ids    # oldest terminal pruned
    t.finish(live, rows=0)


def test_concurrent_result_paging_stays_isolated():
    """Two queries page their buffered results interleaved through the
    server (both in paging state RUNNING at once): rows never bleed
    across registries/buffers (the per-query-lock bar)."""
    from trino_tpu.exec import LocalQueryRunner
    from trino_tpu.server import TrinoServer

    srv = TrinoServer(LocalQueryRunner.tpch("tiny")).start()

    def _post(sql):
        req = urllib.request.Request(f"{srv.base_uri}/v1/statement",
                                     data=sql.encode(), method="POST")
        with urllib.request.urlopen(req) as resp:
            return json.loads(resp.read())

    def _get(uri):
        with urllib.request.urlopen(uri) as resp:
            return json.loads(resp.read())

    try:
        # >1000 rows each => multiple pages (PAGE_ROWS = 1000)
        pa = _post("SELECT c_custkey FROM customer ORDER BY c_custkey")
        pb = _post("SELECT o_orderkey FROM orders ORDER BY o_orderkey")
        rows_a, rows_b = [], []
        states_a, states_b = [], []
        while "nextUri" in pa or "nextUri" in pb:
            if "nextUri" in pa:
                pa = _get(pa["nextUri"])
                rows_a.extend(pa.get("data", []))
                states_a.append(pa["stats"]["state"])
            if "nextUri" in pb:
                pb = _get(pb["nextUri"])
                rows_b.extend(pb.get("data", []))
                states_b.append(pb["stats"]["state"])
        # both were observed mid-paging (state RUNNING) simultaneously
        assert "RUNNING" in states_a and "RUNNING" in states_b
        assert [r[0] for r in rows_a] == list(range(1, 1501))
        assert len(rows_b) == 15000
        keys_b = [r[0] for r in rows_b]
        assert keys_b == sorted(keys_b)
        # customer keys top out at 1500; order keys reach far higher —
        # a single bled page would break either check
        assert max(keys_b) > 1500
    finally:
        srv.stop()
