"""Spill-to-host partition store + device hash partitioner.

Reference parity: spiller/ (FileSingleStreamSpiller.java,
GenericPartitioningSpiller.java) + operator/aggregation/builder/
SpillableHashAggregationBuilder.java:47, re-thought for this topology:
the scarce resource is HBM and single-op scratch, while the HOST has
~125GB RAM behind a fast PCIe/tunnel link — so "disk" is host memory and
the spill unit is a hash PARTITION (Grace aggregation), not a sorted
run. Each over-budget batch is group-compacted (Step.INTERMEDIATE),
partition-sorted ON DEVICE by a mix64 of its group keys, fetched in one
transfer, and split host-side at partition boundaries; finalization
re-stages one bounded partition at a time. The same store backs sort
spill (range partitions instead of hash).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu import types as T
from trino_tpu.errors import EXCEEDED_SPILL_LIMIT, TrinoError
from trino_tpu.page import Column, Page

_SM1 = jnp.uint64(0xBF58476D1CE4E5B9)
_SM2 = jnp.uint64(0x94D049BB133111EB)
_NULL_TAG = jnp.uint64(0x9E3779B97F4A7C15)
_GOLDEN = 0x9E3779B97F4A7C15
_U64 = (1 << 64) - 1


def _mix64(x: jnp.ndarray) -> jnp.ndarray:
    x = (x ^ (x >> 30)) * _SM1
    x = (x ^ (x >> 27)) * _SM2
    return x ^ (x >> 31)


def _canonical_key_hash(page: Page, key_channels: Sequence[int]
                        ) -> jnp.ndarray:
    """Per-row u64 hash of the group key tuple with NULLs canonicalized
    (every NULL in a column hashes identically — a group's rows MUST land
    in one partition; join's _key_u64 treats null keys as dead instead)."""
    acc = jnp.zeros(page.capacity, dtype=jnp.uint64)
    for ch in key_channels:
        c = page.column(ch)
        v = c.values
        if v.dtype == jnp.bool_:
            u = v.astype(jnp.uint64)
        elif jnp.issubdtype(v.dtype, jnp.floating):
            u = jax.lax.bitcast_convert_type(
                v.astype(jnp.float64) + 0.0, jnp.uint64)
        else:
            u = v.astype(jnp.uint64)
        if c.valid is not None:
            u = jnp.where(c.valid, u, _NULL_TAG)
        acc = _mix64(acc ^ _mix64(u))
    return acc


def _partition_sort(page: Page, pid: jnp.ndarray, npart: int):
    """ONE stable sort moves each partition's rows together (dead rows
    route past the last partition); the caller fetches the live prefix in
    one transfer and slices at the counts' offsets."""
    live = page.row_mask()
    pid = jnp.where(live, pid, npart)
    payload = []
    for c in page.columns:
        payload.append(c.values)
        if c.valid is not None:
            payload.append(c.valid)
    out = jax.lax.sort([pid] + payload, num_keys=1, is_stable=True)
    it = iter(out[1:])
    cols = []
    for c in page.columns:
        values = next(it)
        valid = next(it) if c.valid is not None else None
        cols.append(Column(values, valid, c.type, c.dictionary))
    counts = jax.ops.segment_sum(
        live.astype(jnp.int64), pid, num_segments=npart + 1)[:npart]
    return Page(tuple(cols), page.num_rows), counts


def partition_by_hash(key_channels: Sequence[int], npart: int,
                      salt: int = 0):
    """op(page) -> (page sorted by partition id, int64 counts[npart]).

    `salt` derives an independent hash family per recursion depth: a
    partition that misses its budget repartitions with salt = depth so
    its keys REDISTRIBUTE instead of all landing in one child again
    (rows of any single key still colocate — required for
    correctness — at every salt). salt=0 is byte-identical to the
    historical hash, so warm kernel-cache keys stay valid."""
    key_channels = tuple(key_channels)
    salt_mix = jnp.uint64((_GOLDEN * (int(salt) + 1)) & _U64) \
        if salt else None

    def op(page: Page):
        h = _canonical_key_hash(page, key_channels)
        if salt_mix is not None:
            h = _mix64(h ^ salt_mix)
        pid = (h % jnp.uint64(npart)).astype(jnp.int32)
        return _partition_sort(page, pid, npart)

    return op


def leading_rank(channel: int, ascending: bool, nulls_first: bool):
    """Monotonic u64 rank of ONE sort key: ascending rank order == the
    key's OUTPUT order, with direction, NULL placement and NaN-largest
    folded in. Range-partitioning on this rank keeps ties (equal leading
    keys) inside one partition, so per-partition full sorts compose into
    a correct global order (the sort-spill invariant)."""

    def op(page: Page) -> jnp.ndarray:
        c = page.column(channel)
        v = c.values
        if v.dtype == jnp.bool_:
            u = v.astype(jnp.uint64)
        elif jnp.issubdtype(v.dtype, jnp.floating):
            # NaN canonicalizes to +inf: it RANKS with +inf (same
            # partition), and the per-partition full sort orders NaN
            # after +inf via its own nan-flag sub-key
            f = v.astype(jnp.float64)
            f = jnp.where(jnp.isnan(f), jnp.inf, f) + 0.0
            bits = jax.lax.bitcast_convert_type(f, jnp.uint64)
            neg = bits >> 63 == 1
            u = jnp.where(neg, ~bits, bits | jnp.uint64(1) << 63)
        elif jnp.issubdtype(v.dtype, jnp.unsignedinteger):
            u = v.astype(jnp.uint64)
        else:
            u = v.astype(jnp.uint64) ^ (jnp.uint64(1) << 63)
        if not ascending:
            u = ~u
        # reserve the extremes for NULLs
        u = (u >> 2) + jnp.uint64(1)
        if c.valid is not None:
            null_rank = jnp.uint64(0) if nulls_first \
                else jnp.uint64(0xFFFFFFFFFFFFFFFF)
            u = jnp.where(c.valid, u, null_rank)
        return u

    return op


def rank_bounds(npart: int):
    """op(ranks, num_rows) -> u64 bounds[npart-1]: quantile split points
    of the live ranks (dead rows sort to the top via u64 max)."""

    def op(ranks: jnp.ndarray, live: jnp.ndarray, num_rows) -> jnp.ndarray:
        masked = jnp.where(live, ranks, jnp.uint64(0xFFFFFFFFFFFFFFFF))
        s = jax.lax.sort([masked], num_keys=1)[0]
        q = (jnp.arange(1, npart, dtype=jnp.int64)
             * num_rows.astype(jnp.int64)) // npart
        return jnp.take(s, q, mode="clip")

    return op


def partition_by_range(channel: int, ascending: bool, nulls_first: bool,
                       npart: int):
    """op(page, bounds) -> (page sorted by range partition id, counts).
    side='right' keeps every row equal to a boundary value in one
    partition (multi-key ties must not straddle partitions)."""
    rank = leading_rank(channel, ascending, nulls_first)

    def op(page: Page, bounds: jnp.ndarray):
        r = rank(page)
        pid = jnp.searchsorted(bounds, r, side="right").astype(jnp.int32)
        return _partition_sort(page, pid, npart)

    return op


class ExceededSpillLimitError(TrinoError, RuntimeError):
    """A spill reservation would push the query past its host-RAM spill
    budget (`spill_max_bytes`): classified, non-retryable — re-running
    spills the same bytes again (ExceededSpillLimitException analog)."""

    CODE = EXCEEDED_SPILL_LIMIT


def default_spill_limit_bytes() -> int:
    """The session default for `spill_max_bytes` when unset (0): half of
    physical host RAM — the host side of the topology is the spill
    device, and leaving half for everything else keeps the OOM killer
    (the OS one) out of the picture."""
    try:
        total = os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
        return max(int(total) // 2, 1 << 30)
    except (AttributeError, OSError, ValueError):
        return 64 << 30


def resolve_spill_limit(session) -> int:
    """Session `spill_max_bytes`; 0 = the host-RAM-derived default."""
    v = int(session.get("spill_max_bytes"))
    return v if v > 0 else default_spill_limit_bytes()


class SpillLedger:
    """Process-wide host-RAM accounting for spill partition stores (the
    NODE_POOL discipline applied to the HOST side): every store charges
    its pieces here per query and frees them on drop/close, so the
    `trino_tpu_spill_bytes` gauge reads what spill actually holds and an
    over-budget query fails with a CLASSIFIED error instead of silently
    exhausting host RAM."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reserved = 0
        self.peak = 0
        self.denials = 0
        self.by_query: Dict[str, int] = {}

    def reserve(self, nbytes: int, query_id: str,
                limit: Optional[int]) -> None:
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        with self._lock:
            held = self.by_query.get(query_id, 0)
            if limit is not None and held + nbytes > limit:
                self.denials += 1
                raise ExceededSpillLimitError(
                    f"Query exceeded spill limit of {_fmt_bytes(limit)} "
                    f"[spill store requested {_fmt_bytes(nbytes)} with "
                    f"{_fmt_bytes(held)} spilled]")
            self.by_query[query_id] = held + nbytes
            self.reserved += nbytes
            self.peak = max(self.peak, self.reserved)

    def release(self, nbytes: int, query_id: str) -> None:
        nbytes = int(nbytes)
        if nbytes <= 0:
            return
        with self._lock:
            held = self.by_query.get(query_id, 0)
            freed = min(nbytes, held)
            if held - freed <= 0:
                self.by_query.pop(query_id, None)
            else:
                self.by_query[query_id] = held - freed
            self.reserved = max(0, self.reserved - freed)


# the process singleton every store charges (host RAM is shared)
SPILL_LEDGER = SpillLedger()


def _fmt_bytes(n: int) -> str:
    from trino_tpu.exec.memory import _fmt_bytes as fmt
    return fmt(int(n))


def _pow2(n: int) -> int:
    return max(1 << max(int(n) - 1, 0).bit_length(), 8)


class HostPartitionStore:
    """Per-partition host-RAM pieces of spilled pages.

    A piece is [(values_np, valid_np|None)] per column; `meta` captures
    (type, dictionary) per column from the first spill (all spilled pages
    share one layout — same plan node). Byte-accounted per partition and
    — when a ledger is attached — against the process SpillLedger under
    the owning query's `spill_max_bytes` budget."""

    def __init__(self, npart: int, ledger: Optional[SpillLedger] = None,
                 query_id: str = "", limit: Optional[int] = None):
        self.npart = npart
        self.pieces: List[List[list]] = [[] for _ in range(npart)]
        self.meta: Optional[List[Tuple[T.Type, object]]] = None
        self.bytes = 0
        self.part_bytes = [0] * npart
        self.ledger = ledger
        self.query_id = query_id
        self.limit = limit

    # --------------------------------------------------- byte accounting

    def _settle(self, p: int, delta: int) -> None:
        """Charge (positive) or release (negative) partition p's bytes,
        mirrored into the ledger. Charges can raise
        ExceededSpillLimitError — callers charge BEFORE appending."""
        if delta > 0:
            if self.ledger is not None:
                self.ledger.reserve(delta, self.query_id, self.limit)
            self.bytes += delta
            self.part_bytes[p] += delta
        elif delta < 0:
            if self.ledger is not None:
                self.ledger.release(-delta, self.query_id)
            self.bytes = max(0, self.bytes + delta)
            self.part_bytes[p] = max(0, self.part_bytes[p] + delta)

    @staticmethod
    def _piece_bytes(piece) -> int:
        return sum(v.nbytes + (m.nbytes if m is not None else 0)
                   for v, m in piece)

    def spill_partitioned(self, page: Page, counts: np.ndarray) -> None:
        """Fetch a partition-sorted page's live rows in ONE transfer and
        slice at partition offsets."""
        counts = np.asarray(counts, dtype=np.int64)
        total = int(counts.sum())
        if total == 0:
            return
        if self.meta is None:
            self.meta = [(c.type, c.dictionary) for c in page.columns]
        fetch = []
        for c in page.columns:
            fetch.append(c.values[:total])
            fetch.append(None if c.valid is None else c.valid[:total])
        got = jax.device_get([f for f in fetch if f is not None])
        it = iter(got)
        host_cols = []
        for c in page.columns:
            vals = np.asarray(next(it))
            valid = None if c.valid is None else np.asarray(next(it))
            host_cols.append((vals, valid))
        offs = np.concatenate([[0], np.cumsum(counts)])
        for p in range(self.npart):
            lo, hi = int(offs[p]), int(offs[p + 1])
            if hi <= lo:
                continue
            piece = [(vals[lo:hi],
                      None if valid is None else valid[lo:hi])
                     for vals, valid in host_cols]
            self._settle(p, self._piece_bytes(piece))
            self.pieces[p].append(piece)

    def add_piece(self, p: int, piece) -> None:
        """Append a host-built piece (heavy-key splitting) with the same
        accounting as a device spill."""
        self._settle(p, self._piece_bytes(piece))
        self.pieces[p].append(piece)

    def partition_rows(self, p: int) -> int:
        return sum(len(piece[0][0]) for piece in self.pieces[p])

    def partition_bytes(self, p: int) -> int:
        return self.part_bytes[p]

    def chunk_rows_for(self, p: int, budget_bytes: int) -> int:
        """Rows per bounded restage chunk so one staged chunk stays
        within `budget_bytes` (floor 4096 keeps degenerate budgets from
        devolving into row-at-a-time staging)."""
        rows = self.partition_rows(p)
        if rows <= 0:
            return 4096
        per_row = max(1, self.part_bytes[p] // rows)
        return max(4096, int(budget_bytes) // per_row)

    def _stage(self, spans, n: int,
               capacity: Optional[int] = None) -> Page:
        """Build ONE device page from host (piece, lo, hi) spans."""
        capacity = capacity if capacity is not None else _pow2(max(n, 1))
        cols = []
        for ci in range(len(self.meta)):
            vals = np.concatenate(
                [piece[ci][0][lo:hi] for piece, lo, hi in spans])
            has_valid = any(piece[ci][1] is not None
                            for piece, lo, hi in spans)
            valid = None
            if has_valid:
                valid = np.concatenate(
                    [piece[ci][1][lo:hi] if piece[ci][1] is not None
                     else np.ones(hi - lo, dtype=bool)
                     for piece, lo, hi in spans])
            typ, d = self.meta[ci]
            pv = np.zeros(capacity, dtype=vals.dtype)
            pv[:n] = vals
            pm = None
            if valid is not None:
                pm = np.zeros(capacity, dtype=bool)
                pm[:n] = valid
            cols.append(Column(jnp.asarray(pv),
                               None if pm is None else jnp.asarray(pm),
                               typ, d))
        return Page(tuple(cols), jnp.asarray(n, dtype=jnp.int32))

    def restage(self, p: int, capacity: int) -> Optional[Page]:
        """Concatenate partition p host-side and stage ONE device page."""
        if not self.pieces[p] or self.meta is None:
            return None
        n = self.partition_rows(p)
        spans = [(piece, 0, len(piece[0][0])) for piece in self.pieces[p]]
        return self._stage(spans, n, capacity)

    def iter_partition_chunks(self, p: int,
                              chunk_rows: int) -> Iterator[Page]:
        """Partition p as bounded device pages of <= chunk_rows live rows
        each — the restage transient of an over-budget partition never
        exceeds one chunk (recursion, chunked folds, chunked-build joins
        all pull through this). Does NOT drop the partition, so a caller
        can iterate it repeatedly (the chunked-build join re-streams the
        probe partition per build chunk)."""
        if not self.pieces[p] or self.meta is None:
            return
        chunk_rows = max(int(chunk_rows), 1)
        spans = []
        acc = 0
        for piece in self.pieces[p]:
            n = len(piece[0][0])
            lo = 0
            while lo < n:
                take = min(chunk_rows - acc, n - lo)
                spans.append((piece, lo, lo + take))
                acc += take
                lo += take
                if acc == chunk_rows:
                    yield self._stage(spans, acc)
                    spans, acc = [], 0
        if spans:
            yield self._stage(spans, acc)

    def drain_partition_chunks(self, p: int,
                               chunk_rows: int) -> Iterator[Page]:
        """iter_partition_chunks that RELEASES each piece (bytes back to
        the ledger, host array refs dropped) as soon as its last row has
        been staged — single-pass consumers (recursive repartition into
        a child store, chunked folds) never double-hold a partition's
        bytes against the spill budget while transferring it."""
        if not self.pieces[p] or self.meta is None:
            return
        chunk_rows = max(int(chunk_rows), 1)
        pieces = self.pieces[p]
        spans = []
        acc = 0
        done: List[list] = []
        while pieces:
            piece = pieces.pop(0)
            n = len(piece[0][0])
            lo = 0
            while lo < n:
                take = min(chunk_rows - acc, n - lo)
                spans.append((piece, lo, lo + take))
                acc += take
                lo += take
                if acc == chunk_rows:
                    yield self._stage(spans, acc)
                    spans, acc = [], 0
                    # pieces fully covered by now-staged spans release;
                    # the current piece may still have unstaged rows
                    for d in done:
                        self._settle(p, -self._piece_bytes(d))
                    done = []
            done.append(piece)
        if spans:
            yield self._stage(spans, acc)
        for d in done:
            self._settle(p, -self._piece_bytes(d))

    def drop(self, p: int) -> None:
        self._settle(p, -self.part_bytes[p])
        self.pieces[p] = []

    def close(self) -> None:
        """Release every partition (generator finally blocks call this so
        an abandoned or failed operator can never strand ledger bytes)."""
        for p in range(self.npart):
            self.drop(p)


# ---------------------------------------------------------------------------
# host-side heavy-hitter detection + splitting (the per-partition analog of
# parallel/exchange.detect_heavy_keys' top-k discipline, over spilled pieces)

_NP_SM1 = np.uint64(0xBF58476D1CE4E5B9)
_NP_SM2 = np.uint64(0x94D049BB133111EB)
_NP_NULL_TAG = np.uint64(_GOLDEN)


def _np_mix64(x: np.ndarray) -> np.ndarray:
    x = (x ^ (x >> np.uint64(30))) * _NP_SM1
    x = (x ^ (x >> np.uint64(27))) * _NP_SM2
    return x ^ (x >> np.uint64(31))


def _np_piece_key_hash(piece, key_idxs: Sequence[int]) -> np.ndarray:
    """Host mirror of `_canonical_key_hash` over one spilled piece: the
    composite-key identity heavy detection and splitting group rows by.
    (It need not match the DEVICE hash bit-for-bit — it only has to be
    consistent across pieces and across the two sides of a join.)"""
    n = len(piece[0][0])
    acc = np.zeros(n, dtype=np.uint64)
    with np.errstate(over="ignore"):
        for ci in key_idxs:
            vals, valid = piece[ci]
            if vals.dtype == np.bool_:
                u = vals.astype(np.uint64)
            elif np.issubdtype(vals.dtype, np.floating):
                u = (vals.astype(np.float64) + 0.0).view(np.uint64)
            else:
                u = vals.astype(np.uint64)
            if valid is not None:
                u = np.where(valid, u, _NP_NULL_TAG)
            acc = _np_mix64(acc ^ _np_mix64(u))
    return acc


def partition_key_hashes(store: HostPartitionStore, p: int,
                         key_idxs: Sequence[int]) -> List[np.ndarray]:
    """Per-piece canonical key hashes of one partition — computed ONCE
    and shared by detection + splitting (the pieces are exactly the
    large spilled partitions these paths exist for)."""
    return [_np_piece_key_hash(piece, key_idxs)
            for piece in store.pieces[p]]


def detect_partition_heavy_keys(store: HostPartitionStore, p: int,
                                key_idxs: Sequence[int], limit: int,
                                min_count: int,
                                piece_hashes=None) -> np.ndarray:
    """Top-`limit` key identities of partition p whose row count reaches
    `min_count` (uint64 canonical hashes). A heavy key is exactly what
    recursive repartitioning can NEVER split — every row of one key
    re-hashes to one child at any salt — so these are split out into the
    dedicated bounded paths instead of recursing forever."""
    if not store.pieces[p]:
        return np.empty(0, dtype=np.uint64)
    if piece_hashes is None:
        piece_hashes = partition_key_hashes(store, p, key_idxs)
    hashes = np.concatenate(piece_hashes)
    keys, counts = np.unique(hashes, return_counts=True)
    mask = counts >= max(int(min_count), 1)
    keys, counts = keys[mask], counts[mask]
    if len(keys) > int(limit):
        top = np.argsort(counts)[::-1][:int(limit)]
        keys = keys[top]
    return keys


def split_partition(store: HostPartitionStore, p: int,
                    key_idxs: Sequence[int],
                    heavy: np.ndarray,
                    piece_hashes=None) -> HostPartitionStore:
    """Move partition p's rows whose key identity is in `heavy` into a
    NEW single-partition store (same ledger/budget); the source keeps the
    rest. Pure host work — no device round trip. `piece_hashes` reuses
    the detection pass's per-piece hashes (must align with the
    partition's piece list at call time)."""
    sub = HostPartitionStore(1, ledger=store.ledger,
                             query_id=store.query_id, limit=store.limit)
    sub.meta = None if store.meta is None else list(store.meta)
    old_bytes = store.part_bytes[p]
    rest_pieces: List[list] = []
    heavy_pieces: List[list] = []
    if piece_hashes is None:
        piece_hashes = partition_key_hashes(store, p, key_idxs)
    for piece, h in zip(store.pieces[p], piece_hashes):
        mask = np.isin(h, heavy)
        if not mask.any():
            # no heavy rows here: keep the piece BY REFERENCE — a
            # fancy-indexed all-True copy would double host RAM traffic
            # on exactly the memory-pressure path this split relieves
            rest_pieces.append(piece)
            continue
        heavy_pieces.append(
            [(v[mask], None if m is None else m[mask])
             for v, m in piece])
        if not mask.all():
            keep = ~mask
            rest_pieces.append(
                [(v[keep], None if m is None else m[keep])
                 for v, m in piece])
    # settle: release the whole old partition first, then re-charge the
    # two halves — a transient double-charge could trip the budget for
    # bytes that already live in host RAM
    store.pieces[p] = []
    store._settle(p, -old_bytes)
    for piece in rest_pieces:
        store.add_piece(p, piece)
    for piece in heavy_pieces:
        sub.add_piece(0, piece)
    return sub
