"""Adaptive aggregation/join strategies + recursive hybrid spill.

Covers the PR-10 robustness surface: the reduction-ratio mode
controller's exact-threshold transitions (downgrade -> bypass ->
re-upgrade), the end-to-end high-NDV GROUP BY downgrade with
sqlite-oracle parity, the skewed-build partitioned hybrid join
(recursion fires, depth stays bounded, heavy keys split, max-depth
fallback), the host-side spill ledger (budget -> classified
EXCEEDED_SPILL_LIMIT, drains to zero), and the degrade-re-run
inheritance contract (the spill-forced retry starts in the mode the
failed attempt observed, not cold).
"""

import pytest

from trino_tpu.exec import LocalQueryRunner
from trino_tpu.exec.adaptive import (AdaptiveQueryState, AggMode,
                                     AggModeController, BYPASS_PROBE_EVERY,
                                     DOWNGRADE_RATIO, UPGRADE_RATIO)
from trino_tpu.exec.spill import SPILL_LEDGER

from oracle import assert_same, load_tpch_sqlite

AGG_SQL = ("SELECT l_orderkey, l_linenumber, sum(l_extendedprice) AS s "
           "FROM lineitem GROUP BY l_orderkey, l_linenumber")
SKEW_JOIN_SQL = ("SELECT count(*), sum(l2.l_extendedprice) "
                 "FROM lineitem l1 JOIN lineitem l2 "
                 "ON l1.l_orderkey = l2.l_orderkey")


def _tight_session(runner, **extra):
    props = {"page_capacity": 2048, "scan_page_capacity": 2048,
             "spill_partition_count": 4,
             "agg_spill_threshold_bytes": 1 << 15,
             "join_spill_threshold_bytes": 1 << 14,
             "spill_max_recursion": 2}
    props.update(extra)
    for k, v in props.items():
        runner.session.set(k, v)
    return runner


@pytest.fixture(scope="module")
def oracle():
    conn = load_tpch_sqlite(0.01)
    yield conn
    conn.close()


@pytest.fixture(scope="module")
def baseline():
    """Spill-free reference results (default thresholds, same engine)."""
    r = LocalQueryRunner.tpch("tiny")
    return {
        "agg": sorted(r.execute(AGG_SQL).rows),
        "join": r.execute(SKEW_JOIN_SQL).rows,
    }


# ------------------------------------------------------------- controller


def test_controller_downgrades_at_exact_threshold():
    ctl = AggModeController()
    assert ctl.mode == AggMode.FULL
    # just below the threshold: no transition
    assert ctl.observe(1000, int(1000 * DOWNGRADE_RATIO) - 1) is None
    assert ctl.mode == AggMode.FULL
    # exactly at the threshold: one lattice step down
    assert ctl.observe(1000, int(1000 * DOWNGRADE_RATIO)) == "downgrade"
    assert ctl.mode == AggMode.SHRUNKEN
    assert ctl.observe(1000, 1000) == "downgrade"
    assert ctl.mode == AggMode.BYPASS
    # already at the bottom: stays
    assert ctl.observe(1000, 1000) is None
    assert ctl.downgrades == 2


def test_controller_reupgrades_with_hysteresis():
    ctl = AggModeController(mode=AggMode.BYPASS)
    # in the hysteresis band: no transition either way
    mid = int(1000 * (DOWNGRADE_RATIO + UPGRADE_RATIO) / 2)
    assert ctl.observe(1000, mid) is None
    assert ctl.mode == AggMode.BYPASS
    # at the upgrade threshold: one step back up per observation
    assert ctl.observe(1000, int(1000 * UPGRADE_RATIO)) == "upgrade"
    assert ctl.mode == AggMode.SHRUNKEN
    assert ctl.observe(1000, 1) == "upgrade"
    assert ctl.mode == AggMode.FULL
    assert ctl.observe(1000, 1) is None     # already at the top
    assert ctl.upgrades == 2
    assert ctl.history == [AggMode.BYPASS, AggMode.SHRUNKEN, AggMode.FULL]


def test_controller_bypass_gated_by_spill():
    ctl = AggModeController(mode=AggMode.SHRUNKEN, allow_bypass=False)
    assert ctl.observe(100, 100) is None    # bypass unreachable
    assert ctl.mode == AggMode.SHRUNKEN
    ctl.allow_bypass = True                 # degrade re-run forces spill on
    assert ctl.observe(100, 100) == "downgrade"
    assert ctl.mode == AggMode.BYPASS


def test_controller_bypass_probe_cadence():
    ctl = AggModeController(mode=AggMode.BYPASS)
    probes = []
    for _ in range(2 * BYPASS_PROBE_EVERY):
        probes.append(ctl.should_probe())
        ctl.note_flush()
    assert probes.count(True) == 2          # one probe per cadence window
    assert probes[0] is True                # first flush measures


def test_controller_initial_mode_from_cbo():
    assert AggModeController.initial_mode(None, None) == AggMode.FULL
    assert AggModeController.initial_mode(10.0, 1000.0) == AggMode.FULL
    # estimated NDV ~ rows: start shrunken (never straight to bypass)
    assert AggModeController.initial_mode(900.0, 1000.0) == AggMode.SHRUNKEN


def test_adaptive_state_attempt_history():
    state = AdaptiveQueryState()
    ctl = state.agg_controller(7, ndv=None, rows=None)
    ctl.observe(100, 100)                   # downgrade to shrunken
    again = state.agg_controller(7)         # the retry attempt
    assert again is ctl                     # same controller, same mode
    assert state.attempt_initial_modes[7] == [AggMode.FULL,
                                              AggMode.SHRUNKEN]


# ------------------------------------------------------- end-to-end: agg


def test_high_ndv_groupby_downgrades_oracle_green(oracle, baseline):
    r = _tight_session(LocalQueryRunner.tpch("tiny"))
    got = r.execute(AGG_SQL)
    stats = r.last_query_stats
    assert stats["agg_mode_downgrades"] > 0, \
        "high-NDV GROUP BY must downgrade the partial-agg mode"
    assert stats["spilled_bytes"] > 0
    expected = oracle.execute(
        "SELECT l_orderkey, l_linenumber, sum(l_extendedprice) "
        "FROM lineitem GROUP BY l_orderkey, l_linenumber").fetchall()
    assert_same(got.rows, expected, ordered=False)
    assert sorted(got.rows) == baseline["agg"]
    assert SPILL_LEDGER.reserved == 0       # stores drained with the query


def test_adaptive_off_pins_full_mode(baseline):
    r = _tight_session(LocalQueryRunner.tpch("tiny"),
                       adaptive_partial_agg=False)
    got = r.execute(AGG_SQL)
    assert r.last_query_stats["agg_mode_downgrades"] == 0
    assert sorted(got.rows) == baseline["agg"]


def test_agg_recursion_and_explain_analyze_footer(baseline):
    r = _tight_session(LocalQueryRunner.tpch("tiny"))
    got = r.execute(AGG_SQL)
    stats = r.last_query_stats
    assert stats["agg_recursions"] > 0
    assert sorted(got.rows) == baseline["agg"]
    text = r.execute("EXPLAIN ANALYZE " + AGG_SQL).only_value()
    assert "adaptive:" in text and "spill recursions" in text


def test_agg_fallback_at_zero_recursion(baseline):
    """spill_max_recursion=0: over-budget partitions go straight to the
    bounded chunked fold — still correct, fallbacks counted."""
    r = _tight_session(LocalQueryRunner.tpch("tiny"),
                       spill_max_recursion=0)
    got = r.execute(AGG_SQL)
    stats = r.last_query_stats
    assert stats["spill_fallbacks"] > 0
    assert stats["agg_recursions"] == 0
    assert sorted(got.rows) == baseline["agg"]


# ------------------------------------------------------ end-to-end: join


def test_skewed_build_join_recursion_bounded(oracle, baseline):
    r = _tight_session(LocalQueryRunner.tpch("tiny"))
    got = r.execute(SKEW_JOIN_SQL)
    stats = r.last_query_stats
    assert stats["join_recursions"] > 0, \
        "a duplicate-key over-threshold build must repartition recursively"
    # bounded depth: with npart=4 and max_recursion=2 a full recursion
    # tree has at most npart + npart^2 recursion events per side-store
    # pair; far under that in practice, but the bound is the contract
    npart = 4
    assert stats["join_recursions"] <= npart + npart * npart
    expected = oracle.execute(
        "SELECT count(*), sum(l2.l_extendedprice) FROM lineitem l1 "
        "JOIN lineitem l2 ON l1.l_orderkey = l2.l_orderkey").fetchall()
    assert_same(got.rows, expected, ordered=False)
    assert got.rows == baseline["join"]
    assert SPILL_LEDGER.reserved == 0


def test_heavy_key_split_fires():
    """One dominant build key: recursion can never split it (every row
    of one key re-hashes together at any salt) — the heavy-key path
    must split it out and still produce exact results."""
    r = _tight_session(LocalQueryRunner.tpch("tiny"))
    r.execute("DROP TABLE IF EXISTS memory.default.hk")
    r.execute("CREATE TABLE memory.default.hk AS SELECT "
              "CASE WHEN l_orderkey % 2 = 0 THEN 7 ELSE l_orderkey END "
              "AS k, l_partkey AS v FROM lineitem")
    sql = ("SELECT count(*), sum(b.v) FROM lineitem l "
           "JOIN memory.default.hk b ON l.l_orderkey = b.k")
    base = LocalQueryRunner.tpch("tiny")
    base.execute("DROP TABLE IF EXISTS memory.default.hk")
    base.execute("CREATE TABLE memory.default.hk AS SELECT "
                 "CASE WHEN l_orderkey % 2 = 0 THEN 7 ELSE l_orderkey END "
                 "AS k, l_partkey AS v FROM lineitem")
    expected = base.execute(sql).rows
    got = r.execute(sql)
    stats = r.last_query_stats
    assert stats["heavy_key_splits"] > 0
    assert got.rows == expected


def test_join_fallback_when_heavy_detection_disabled():
    """spill_heavy_key_limit=0 + a dominant key: recursion exhausts its
    depth without shrinking and the bounded chunked-build fallback must
    finish the partition — no unbounded recursion, no OOM."""
    r = _tight_session(LocalQueryRunner.tpch("tiny"),
                       spill_heavy_key_limit=0, spill_max_recursion=1)
    r.execute("DROP TABLE IF EXISTS memory.default.hk2")
    r.execute("CREATE TABLE memory.default.hk2 AS SELECT "
              "CAST(7 AS bigint) AS k, l_partkey AS v FROM lineitem "
              "WHERE l_orderkey % 4 = 0")
    sql = ("SELECT count(*), sum(b.v) FROM lineitem l "
           "JOIN memory.default.hk2 b ON l.l_orderkey = b.k")
    base = LocalQueryRunner.tpch("tiny")
    base.execute("DROP TABLE IF EXISTS memory.default.hk2")
    base.execute("CREATE TABLE memory.default.hk2 AS SELECT "
                 "CAST(7 AS bigint) AS k, l_partkey AS v FROM lineitem "
                 "WHERE l_orderkey % 4 = 0")
    expected = base.execute(sql).rows
    got = r.execute(sql)
    stats = r.last_query_stats
    assert stats["spill_fallbacks"] > 0
    assert got.rows == expected


# ------------------------------------------------------------ spill ledger


def test_spill_budget_exceeded_is_classified():
    r = _tight_session(LocalQueryRunner.tpch("tiny"),
                       spill_max_bytes=8192)
    from trino_tpu.errors import TrinoError
    with pytest.raises(TrinoError) as ei:
        r.execute(AGG_SQL)
    assert ei.value.error_name == "EXCEEDED_SPILL_LIMIT"
    assert not ei.value.retryable
    # the failed query's stores released everything on unwind
    assert SPILL_LEDGER.reserved == 0
    assert SPILL_LEDGER.denials > 0


def test_spill_gauges_and_queries_column():
    r = _tight_session(LocalQueryRunner.tpch("tiny"))
    r.execute(AGG_SQL, query_id="spill_gauge_probe")
    rows = r.execute(
        "SELECT query_id, spilled_bytes FROM system.runtime.queries "
        "WHERE query_id = 'spill_gauge_probe'").rows
    assert rows and rows[0][1] > 0
    from trino_tpu.obs.metrics import REGISTRY
    text = REGISTRY.render()
    assert "trino_tpu_spill_bytes" in text
    assert "trino_tpu_spill_peak_bytes" in text
    assert "trino_tpu_adaptive_events_total" in text


# --------------------------------------------- degrade-re-run inheritance


def test_degrade_rerun_inherits_adaptive_state(monkeypatch, baseline):
    """The OOM degrade path re-runs once with spill forced; the re-run
    must START in the downgraded mode the failed attempt observed —
    not cold in FULL (the PR-10 bugfix)."""
    from trino_tpu.exec.memory import (ClusterOutOfMemoryError,
                                       QueryMemoryContext)
    r = _tight_session(LocalQueryRunner.tpch("tiny"),
                       retry_policy="QUERY")
    orig = QueryMemoryContext.reserve
    fired = {"n": 0}

    def boom(self, nbytes, tag="operator", device=None):
        # synthetic killer verdict at the FIRST finalize restage: by
        # then the streaming loop has already observed and downgraded
        if tag == "agg-restage" and fired["n"] == 0:
            fired["n"] = 1
            raise ClusterOutOfMemoryError(
                "synthetic node pressure (degrade-inheritance test)")
        return orig(self, nbytes, tag, device)

    monkeypatch.setattr(QueryMemoryContext, "reserve", boom)
    got = r.execute(AGG_SQL)
    assert fired["n"] == 1                  # first attempt died mid-finalize
    assert sorted(got.rows) == baseline["agg"]
    state = r._adaptive
    histories = [h for h in state.attempt_initial_modes.values()
                 if len(h) >= 2]
    assert histories, "the re-run must reuse the query's adaptive state"
    first, second = histories[0][0], histories[0][1]
    # the second attempt starts where the first one's observations left
    # off — strictly below FULL on the lattice
    assert second != AggMode.FULL
    assert AggMode.LATTICE.index(second) >= AggMode.LATTICE.index(first)
