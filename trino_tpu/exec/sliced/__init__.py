"""Preemptible sliced execution: bounded-work slices + checkpoints.

The subsystem behind ROADMAP item 5: long operators execute as
row-budgeted SLICES driven by a resumable executor loop, so the engine
can act BETWEEN slices without any cooperation from the kernel body —
DELETE cancels within one slice, the low-memory killer reclaims a
victim's HBM at the next slice boundary instead of waiting out the
query, serve-tier backpressure parks the producer at a boundary, and
fragment retry resumes from the last durable per-shard checkpoint
instead of re-running whole fragments.

  scheduler.SliceScheduler    the per-query slice driver: row budget
                              (slice_target_rows) tuned by a wall-clock
                              EWMA toward slice_target_ms, slice
                              counters, and the boundary protocol
                              (fault site `slice`, budget retune)
  checkpoint.OperatorCheckpoint / CheckpointStore
                              explicit operator state between slices:
                              consumed cursors, partial output pages,
                              emitted watermarks — what a retry resumes
                              from instead of starting over

The matching write-side half lives in the connector SPI: idempotent
page sinks (write tokens + commit-on-finish, connector/spi.py) make
QUERY-level retry safe for INSERT/CTAS.
"""

from trino_tpu.exec.sliced.checkpoint import (CheckpointStore,  # noqa: F401
                                              OperatorCheckpoint,
                                              checkpoint_stats)
from trino_tpu.exec.sliced.scheduler import SliceScheduler  # noqa: F401
