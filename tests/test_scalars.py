"""Scalar function conformance suite (round-4 breadth sweep).

Reference parity: operator/scalar/MathFunctions.java, StringFunctions.java,
DateTimeFunctions.java semantics, AbstractTestQueries-style: engine results
asserted against python-computed expectations (sqlite lacks most of these),
evaluated over real table rows so the dictionary-table paths are exercised.
"""

import math

import pytest

from trino_tpu.exec import LocalQueryRunner


@pytest.fixture(scope="module")
def r():
    return LocalQueryRunner.tpch("tiny")


def one(r, expr):
    return r.execute(f"SELECT {expr}").rows[0][0]


# ------------------------------------------------------------------- math

def test_trig_and_log(r):
    assert one(r, "sin(0e0)") == 0.0
    assert abs(one(r, "cos(0e0)") - 1.0) < 1e-12
    assert abs(one(r, "tan(1e0)") - math.tan(1.0)) < 1e-12
    assert abs(one(r, "asin(1e0)") - math.pi / 2) < 1e-12
    assert abs(one(r, "acos(1e0)")) < 1e-12
    assert abs(one(r, "atan(1e0)") - math.atan(1.0)) < 1e-12
    assert abs(one(r, "atan2(1e0, 2e0)") - math.atan2(1, 2)) < 1e-12
    assert abs(one(r, "cbrt(27e0)") - 3.0) < 1e-12
    assert abs(one(r, "log2(8e0)") - 3.0) < 1e-12
    assert abs(one(r, "log(3e0, 81e0)") - 4.0) < 1e-12
    assert abs(one(r, "radians(180e0)") - math.pi) < 1e-12
    assert abs(one(r, "degrees(pi())") - 180.0) < 1e-9
    assert abs(one(r, "e()") - math.e) < 1e-12


def test_truncate_and_mod(r):
    assert one(r, "truncate(8.9e0)") == 8.0
    assert one(r, "truncate(-8.9e0)") == -8.0
    assert one(r, "mod(7, 3)") == 1
    assert one(r, "mod(-7, 3)") == -1          # truncated, not floored


# ------------------------------------------------------------------- date

def test_date_trunc(r):
    assert str(one(r, "date_trunc('month', DATE '1995-03-15')")) \
        == "1995-03-01"
    assert str(one(r, "date_trunc('year', DATE '1995-03-15')")) \
        == "1995-01-01"
    assert str(one(r, "date_trunc('quarter', DATE '1995-05-15')")) \
        == "1995-04-01"
    # 1995-03-15 was a Wednesday; ISO week starts Monday
    assert str(one(r, "date_trunc('week', DATE '1995-03-15')")) \
        == "1995-03-13"


def test_date_diff_and_add(r):
    assert one(r, "date_diff('day', DATE '1995-03-01', "
                  "DATE '1995-03-15')") == 14
    assert one(r, "date_diff('week', DATE '1995-03-01', "
                  "DATE '1995-03-15')") == 2
    assert one(r, "date_diff('month', DATE '1995-01-31', "
                  "DATE '1995-03-30')") == 1     # not a full 2 months yet
    assert one(r, "date_diff('month', DATE '1995-01-31', "
                  "DATE '1995-03-31')") == 2
    assert one(r, "date_diff('year', DATE '1994-06-01', "
                  "DATE '1995-05-31')") == 0
    assert str(one(r, "date_add('day', 14, DATE '1995-03-01')")) \
        == "1995-03-15"
    assert str(one(r, "date_add('month', 1, DATE '1995-01-31')")) \
        == "1995-02-28"                          # end-of-month clamp
    assert str(one(r, "date_add('year', -1, DATE '1996-02-29')")) \
        == "1995-02-28"


def test_day_parts(r):
    # 1995-03-15 was a Wednesday (ISO dow 3), day-of-year 74
    assert one(r, "day_of_week(DATE '1995-03-15')") == 3
    assert one(r, "dow(DATE '1995-03-15')") == 3
    assert one(r, "day_of_year(DATE '1995-03-15')") == 74
    assert one(r, "week(DATE '1995-03-15')") == 11
    assert one(r, "week(DATE '1996-01-01')") == 1
    assert str(one(r, "last_day_of_month(DATE '1995-02-10')")) \
        == "1995-02-28"
    assert str(one(r, "last_day_of_month(DATE '1996-02-10')")) \
        == "1996-02-29"


# ----------------------------------------------------------------- string

def test_pad_and_split(r):
    assert one(r, "lpad('abc', 6, 'xy')") == "xyxabc"
    assert one(r, "rpad('abc', 6, 'xy')") == "abcxyx"
    assert one(r, "lpad('abcdef', 3, 'x')") == "abc"   # truncates
    assert one(r, "split_part('a,b,c', ',', 2)") == "b"
    assert one(r, "split_part('a,b,c', ',', 5)") is None
    assert one(r, "concat_ws('-', 'a', 'b', 'c')") == "a-b-c"


def test_strpos_codepoint_starts(r):
    assert one(r, "strpos('hello', 'll')") == 3
    assert one(r, "strpos('hello', 'z')") == 0
    assert one(r, "codepoint('A')") == 65
    assert one(r, "starts_with('hello', 'he')") is True
    assert one(r, "starts_with('hello', 'lo')") is False


def test_regexp_family(r):
    assert one(r, "regexp_like('hello123', '[0-9]+')") is True
    assert one(r, "regexp_like('hello', '^[0-9]+$')") is False
    assert one(r, "regexp_extract('abc123def', '[0-9]+')") == "123"
    assert one(r, "regexp_extract('abcdef', '[0-9]+')") is None
    assert one(r, "regexp_extract('a1b2', '([a-z])([0-9])', 2)") == "1"
    assert one(r, "regexp_replace('a1b2c3', '[0-9]')") == "abc"
    assert one(r, "regexp_replace('a1b2', '([a-z])([0-9])', '$2$1')") \
        == "1a2b"


def test_string_fns_over_table_rows(r):
    # exercised over a real dictionary column, not just literals
    rows = r.execute(
        "SELECT n_name, lpad(n_name, 4, '.'), strpos(n_name, 'AN'), "
        "regexp_like(n_name, '^[A-C]') FROM nation ORDER BY n_name "
        "LIMIT 3").rows
    assert rows[0][0] == "ALGERIA"
    assert rows[0][1] == "ALGE"
    assert rows[0][2] == 0
    assert rows[0][3] is True


# --------------------------------------------------------------- try_cast

def test_try_cast(r):
    assert one(r, "try_cast('123' AS bigint)") == 123
    assert one(r, "try_cast('12x' AS bigint)") is None
    assert one(r, "try_cast('1.5' AS double)") == 1.5
    assert one(r, "try_cast('abc' AS double)") is None
    assert str(one(r, "try_cast('1995-03-15' AS date)")) == "1995-03-15"
    assert one(r, "try_cast('not-a-date' AS date)") is None
    assert one(r, "try_cast('true' AS boolean)") is True
    assert one(r, "try_cast(42 AS double)") == 42.0


def test_try_cast_over_rows(r):
    rows = r.execute(
        "SELECT try_cast(substr(n_name, 1, 1) AS bigint) FROM nation "
        "LIMIT 2").rows
    assert all(v[0] is None for v in rows)


def test_try_cast_numeric_out_of_range(r):
    # Trino: out-of-range numeric TRY_CAST yields NULL, not saturation
    assert one(r, "try_cast(1e300 AS bigint)") is None
    assert one(r, "try_cast(-1e300 AS bigint)") is None
    assert one(r, "try_cast(1e10 AS integer)") is None
    assert one(r, "try_cast(300 AS tinyint)") is None
    assert one(r, "try_cast(100 AS tinyint)") == 100
    assert one(r, "try_cast(12345678901234 AS decimal(5,2))") is None
    assert one(r, "try_cast(1.5e0 AS decimal(5,2))") is not None
    assert one(r, "try_cast(0e0 / 0e0 AS bigint)") is None   # NaN
    # decimal source -> int target: bound exceeds int64, must not crash
    assert one(r, "try_cast(l_extendedprice AS bigint) FROM lineitem "
                  "LIMIT 1") is not None
    assert one(r, "try_cast(cast(123.45 AS decimal(12,2)) AS tinyint)") \
        == 123
    assert one(r, "try_cast(cast(1234.5 AS decimal(12,2)) AS tinyint)") \
        is None
    # int64 near the float64 rounding boundary stays exact
    assert one(r, "try_cast(999999999999999999 AS decimal(18,0))") \
        is not None
    # float64 == 2^63 exactly: out of bigint range -> NULL, not saturation
    assert one(r, "try_cast(9223372036854775808e0 AS bigint)") is None


def test_concat_ws_null_args(r):
    # Trino: NULL value args are skipped; only a NULL separator nulls out
    assert one(r, "concat_ws('-', 'a', cast(NULL AS varchar), 'c')") \
        == "a-c"
    assert one(r, "concat_ws(cast(NULL AS varchar), 'a', 'b')") is None
    rows = r.execute(
        "SELECT concat_ws(',', 'x', try_cast(substr(n_name, 1, 1) "
        "AS varchar), 'y') FROM nation LIMIT 1").rows
    assert rows[0][0] in ("x,A,y", "x,y") or rows[0][0].count(",") >= 1


def test_bitwise_and_width_bucket(r):
    assert one(r, "bitwise_and(12, 10)") == 8
    assert one(r, "bitwise_or(12, 10)") == 14
    assert one(r, "bitwise_xor(12, 10)") == 6
    assert one(r, "bitwise_not(0)") == -1
    assert one(r, "bitwise_left_shift(1, 4)") == 16
    assert one(r, "bitwise_right_shift(-1, 62)") == 3
    assert one(r, "bitwise_right_shift_arithmetic(-8, 2)") == -2
    assert one(r, "bit_count(9, 64)") == 2
    assert one(r, "width_bucket(5.3e0, 0e0, 10e0, 5)") == 3
    assert one(r, "width_bucket(-1e0, 0e0, 10e0, 5)") == 0
    assert one(r, "width_bucket(11e0, 0e0, 10e0, 5)") == 6


def test_format_datetime(r):
    assert one(r, "format_datetime(DATE '1995-03-15', 'yyyy-MM-dd')") \
        == "1995-03-15"
    assert one(r, "format_datetime(DATE '1995-03-15', 'MMM yyyy')") \
        == "Mar 1995"
    assert one(r, "date_format(DATE '1995-03-15', '%Y/%m/%d')") \
        == "1995/03/15"
    rows = r.execute(
        "SELECT o_orderdate, format_datetime(o_orderdate, 'yyyy-MM') "
        "FROM orders LIMIT 3").rows
    for d, s in rows:
        assert s == d.strftime("%Y-%m")
