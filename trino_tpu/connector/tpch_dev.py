"""Device-side TPC-H column generation.

Reference parity: plugin/trino-tpch streams rows from io.airlift.tpch on
worker CPUs. This host has ONE core and the chip sits behind a ~95ms
tunnel, so host hashing + column transfer dominated SF100 scans (round-4
measurement: q9 SF100 wall was mostly datagen). The fix is TPU-first:
`tpch_gen.column_stream` / `code_stream` are array-module agnostic, so the
SAME hash-stream expressions jit onto the device — generation becomes a
few fused elementwise kernels per chunk, bit-identical to the host path
by construction (one shared code body), verified by
tests/test_connector.py::test_device_gen_matches_host.

Only lineitem's order-index map (8B/row) is uploaded per chunk — the
seekable line-count index stays host-side — cutting tunnel traffic ~7x
for a q9-style scan and eliminating host hashing entirely.
"""

from __future__ import annotations

import collections
from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from trino_tpu.connector import tpch_gen as G

_DEV_TABLES = {"supplier", "customer", "part", "partsupp", "orders",
               "lineitem"}
# rowmap-derived: generated host-side (cheap repeat, no hashing)
_HOST_ONLY = {("lineitem", "l_linenumber")}
_NEEDS_OIDX = {("lineitem", c) for c in
               ("l_orderkey", "l_shipdate", "l_commitdate",
                "l_receiptdate", "l_returnflag", "l_linestatus")}


def supported(table: str, column: str) -> bool:
    """Device generation covers every numeric + pooled column of the big
    tables; formatted (per-row unique) strings and the tiny fixed tables
    stay on the host path."""
    if table not in _DEV_TABLES:
        return False
    if (table, column) in _HOST_ONLY:
        return False
    kind = G.string_kind(table, column)
    if kind == "formatted":
        return False
    return True


_JIT_CACHE: Dict[tuple, object] = {}


def _chunk_fn(table: str, column: str, sf: float, cap: int,
              needs_oidx: bool):
    key = (table, column, round(sf * 1000), cap, needs_oidx)
    fn = _JIT_CACHE.get(key)
    if fn is not None:
        return fn
    pooled = G.string_kind(table, column) == "pooled"
    lut = None
    if pooled:
        lut = jnp.asarray(G._pool_for(table, column, sf).lut)

    def body(start, oidx):
        idx = start + jax.lax.iota(jnp.uint64, cap)
        if pooled:
            raw = G.code_stream(table, sf, column, idx, oidx)
            return jnp.take(lut, raw, mode="clip").astype(jnp.int32)
        return G.column_stream(table, sf, column, idx, oidx)

    if needs_oidx:
        fn = jax.jit(lambda start, oidx: body(start, oidx))
    else:
        f0 = jax.jit(lambda start: body(start, None))
        fn = lambda start, oidx: f0(start)   # noqa: E731
    _JIT_CACHE[key] = fn
    return fn


# small LRU of per-chunk device order-index arrays: the columns of one
# scan chunk are staged consecutively, so a handful of entries gives full
# reuse of one reconstruction
_OIDX_CACHE: "collections.OrderedDict[tuple, jnp.ndarray]" = \
    collections.OrderedDict()
_OIDX_CACHE_MAX = 4


def _oidx_fn(sf: float, cap: int):
    """Jitted on-device order-index reconstruction for lineitem chunks.

    dbgen's defining seekability trick re-thought for the chip: the
    per-order line count is ITSELF a hash stream (1 + mix64(o) % 7), so a
    chunk's order map needs no host data at all beyond two scalars — the
    first covering order and its absolute start row. The device generates
    the local line counts, cumsums them into order-start positions, and
    scatter-marks each start; an inclusive cumsum of the marks is then
    exactly `oidx - o_first` per row. ~45MB/chunk of tunnel upload gone."""
    key = ("oidx", round(sf * 1000), cap)
    fn = _JIT_CACHE.get(key)
    if fn is not None:
        return fn

    def f(o_first, s0, start):
        # at most `cap` orders cover `cap` rows (every order has >=1 line)
        o_ids = (o_first + jax.lax.iota(jnp.int64, cap)).astype(jnp.uint64)
        lines = (1 + (G._u64("lineitem", "l_count", sf, o_ids)
                      % np.uint64(7))).astype(jnp.int64)
        # absolute start row of order o_first+j+1, relative to the chunk
        rel = (s0 + jnp.cumsum(lines)) - start
        ind = jnp.zeros(cap, jnp.int32).at[rel].add(1, mode="drop")
        return o_first + jnp.cumsum(ind).astype(jnp.int64)

    fn = jax.jit(f)
    _JIT_CACHE[key] = fn
    return fn


def _device_oidx(sf: float, start: int, end: int, cap: int) -> jnp.ndarray:
    key = (round(sf * 1000), start, end, cap)
    got = _OIDX_CACHE.get(key)
    if got is not None:
        _OIDX_CACHE.move_to_end(key)
        return got
    # host side: two scalars from the cached line index (bisect, O(log n))
    _, starts = G._line_index(sf)
    o_first = int(np.searchsorted(starts, start, side="right")) - 1
    s0 = int(starts[o_first])
    dev = _oidx_fn(sf, cap)(jnp.int64(o_first), jnp.int64(s0),
                            jnp.int64(start))
    while len(_OIDX_CACHE) >= _OIDX_CACHE_MAX:
        _OIDX_CACHE.popitem(last=False)
    _OIDX_CACHE[key] = dev
    return dev


def generate(table: str, sf: float, column: str, start: int, end: int,
             cap: int) -> jnp.ndarray:
    """Device array [cap] for rows [start, end); tail rows are garbage
    padding (a Page's num_rows delimits live rows)."""
    needs_oidx = (table, column) in _NEEDS_OIDX
    fn = _chunk_fn(table, column, sf, cap, needs_oidx)
    oidx = _device_oidx(sf, start, end, cap) if needs_oidx else None
    return fn(jnp.uint64(start), oidx)
