"""Scopes + AST-expression -> RowExpression translation.

Reference parity: sql/analyzer/Scope.java + sql/planner/TranslationMap.java +
ExpressionAnalyzer typing (via sql/analyzer.py rules here). Translation is
typed bottom-up; coercions become Call("cast", ...) nodes; BETWEEN/IN(list)
desugar with per-side coercions so decimal scales always align.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from trino_tpu import types as T
from trino_tpu.expr.ir import (BoundParam, Call, Literal, RowExpression,
                               SpecialForm, SpecialKind, SymbolRef)
from trino_tpu.expr.functions import days_from_civil
from trino_tpu.sql import tree as t
from trino_tpu.sql.analyzer import (SemanticError, arithmetic_call,
                                    can_coerce, common_type, comparison_call,
                                    is_aggregate, is_window, resolve_scalar)
from trino_tpu.planner.nodes import Symbol


@dataclasses.dataclass(frozen=True)
class Field:
    """One visible column of a relation (sql/analyzer/Field.java)."""

    name: Optional[str]           # None for anonymous expressions
    qualifier: Optional[str]      # relation alias / table name
    symbol: Symbol


class Scope:
    """Name-resolution scope with outer parent for correlated subqueries."""

    def __init__(self, fields: Sequence[Field],
                 parent: Optional["Scope"] = None):
        self.fields = list(fields)
        self.parent = parent

    def try_resolve(self, parts: Tuple[str, ...]
                    ) -> Optional[Tuple[int, Field]]:
        """(scope_level, field); level 0 = this scope, 1+ = outer scopes.
        Identifier matching is case-INSENSITIVE (Trino semantics: a
        quoted \"YEAR\" alias resolves for an unquoted `year` lookup)."""
        def eq(a, b):
            return a is not None and b is not None and \
                a.casefold() == b.casefold()
        if len(parts) == 1:
            name = parts[0]
            matches = [f for f in self.fields if eq(f.name, name)]
        else:
            qualifier, name = parts[-2], parts[-1]
            matches = [f for f in self.fields
                       if eq(f.name, name) and eq(f.qualifier, qualifier)]
        if len(matches) > 1:
            raise SemanticError(f"column '{'.'.join(parts)}' is ambiguous")
        if matches:
            return 0, matches[0]
        if self.parent is not None:
            r = self.parent.try_resolve(parts)
            if r is not None:
                return r[0] + 1, r[1]
        return None

    def resolve(self, parts: Tuple[str, ...]) -> Tuple[int, Field]:
        r = self.try_resolve(parts)
        if r is None:
            raise SemanticError(f"column '{'.'.join(parts)}' cannot be resolved")
        return r


def cast_to(expr: RowExpression, target: T.Type) -> RowExpression:
    if expr.type == target:
        return expr
    # varchar(n) length coercions are representation no-ops (dictionary
    # codes / host strings carry no length) — and a cast Call would defeat
    # the compiler's dictionary-folded string comparisons
    if T.is_string(expr.type) and T.is_string(target):
        return expr
    if isinstance(expr, Literal) and expr.value is None:
        return Literal(None, target)
    # fold literal int -> decimal casts at plan time (LiteralEncoder analog)
    if isinstance(expr, Literal) and isinstance(target, T.DecimalType) and \
            T.is_integral(expr.type):
        return Literal(expr.value * 10 ** target.scale, target)
    if isinstance(expr, Literal) and isinstance(target, T.DecimalType) and \
            isinstance(expr.type, T.DecimalType):
        delta = target.scale - expr.type.scale
        if delta >= 0:
            return Literal(expr.value * 10 ** delta, target)
    # fold literal string casts at plan time: CAST('1999-02-22' AS DATE)
    # (+ numeric variants) is the TPC-DS date-arithmetic idiom
    if isinstance(expr, Literal) and T.is_string(expr.type) and \
            expr.value is not None:
        s = str(expr.value).strip()
        try:
            if isinstance(target, T.DateType):
                return Literal(_parse_date(s), target)
            if isinstance(target, T.TimestampType):
                return Literal(_parse_timestamp(s), target)
            if T.is_integral(target):
                return Literal(int(s), target)
            if isinstance(target, (T.DoubleType, T.RealType)):
                return Literal(float(s), target)
            if isinstance(target, T.DecimalType):
                import decimal as _dec
                q = _dec.Decimal(s).scaleb(target.scale)
                return Literal(
                    int(q.to_integral_value(rounding=_dec.ROUND_HALF_UP)),
                    target)
        except (ValueError, ArithmeticError) as e:
            raise SemanticError(f"cannot cast '{s}' to "
                                f"{target.display()}: {e}")
    return Call("cast", (expr,), target)


def _parse_date(text: str) -> int:
    y, m, d = text.strip().split("-")
    return days_from_civil(int(y), int(m), int(d))


_MICROS = {"DAY": 86_400_000_000, "HOUR": 3_600_000_000,
           "MINUTE": 60_000_000, "SECOND": 1_000_000}


def _interval_literal(node: t.IntervalLiteral) -> Literal:
    unit, end = node.unit, node.end_unit
    if unit in ("YEAR", "MONTH"):
        if end == "MONTH" and unit == "YEAR":
            yy, mm = node.value.split("-")
            months = int(yy) * 12 + int(mm)
        else:
            months = int(node.value) * (12 if unit == "YEAR" else 1)
        return Literal(node.sign * months, T.INTERVAL_YEAR_MONTH)
    if unit in _MICROS:
        if end is not None:
            raise SemanticError(
                f"INTERVAL {unit} TO {end} literals not supported")
        micros = int(node.value) * _MICROS[unit]
        return Literal(node.sign * micros, T.INTERVAL_DAY_TIME)
    raise SemanticError(f"unsupported interval unit {unit}")


def _decimal_literal(text: str) -> Literal:
    neg = text.startswith("-")
    body = text.lstrip("+-")
    if "." in body:
        whole, frac = body.split(".")
    else:
        whole, frac = body, ""
    scale = len(frac)
    digits = (whole + frac).lstrip("0") or "0"
    precision = max(len(digits), scale + 1)
    value = int(whole + frac or "0")
    return Literal(-value if neg else value,
                   T.DecimalType(min(precision, 18), min(scale, 18)))


class ExpressionTranslator:
    """AST expression -> typed RowExpression against a Scope.

    `substitutions` maps already-planned RowExpressions (group-by keys,
    aggregate calls, window calls) to their output symbols — the
    TranslationMap mechanism, keyed structurally.
    `subquery_handler(node) -> RowExpression` is provided by the planner to
    splice subquery plans in (SubqueryPlanner role); None = reject subqueries.
    `on_outer_reference` is called with (level, Field) for correlated refs.
    """

    def __init__(self, scope: Scope,
                 substitutions: Optional[Dict[RowExpression, Symbol]] = None,
                 subquery_handler: Optional[Callable] = None,
                 on_outer_reference: Optional[Callable] = None,
                 session=None, grouping_handler: Optional[Callable] = None):
        self.scope = scope
        self.substitutions = substitutions or {}
        self.subquery_handler = subquery_handler
        self.on_outer_reference = on_outer_reference
        self.session = session
        self.grouping_handler = grouping_handler

    def _sub(self, expr: RowExpression) -> RowExpression:
        sym = self.substitutions.get(expr)
        return sym.ref() if sym is not None else expr

    def translate(self, node: t.Expression) -> RowExpression:
        out = self._translate(node)
        return out

    def _translate(self, node: t.Expression) -> RowExpression:
        # --------------------------------------------------------- literals
        if isinstance(node, t.NullLiteral):
            return Literal(None, T.UNKNOWN)
        if isinstance(node, t.BooleanLiteral):
            return Literal(node.value, T.BOOLEAN)
        if isinstance(node, t.LongLiteral):
            if -(2 ** 31) <= node.value < 2 ** 31:
                return Literal(node.value, T.INTEGER)
            return Literal(node.value, T.BIGINT)
        if isinstance(node, t.DoubleLiteral):
            return Literal(node.value, T.DOUBLE)
        if isinstance(node, t.DecimalLiteral):
            return _decimal_literal(node.text)
        if isinstance(node, t.StringLiteral):
            return Literal(node.value, T.VarcharType(max(len(node.value), 1)))
        if isinstance(node, t.DateLiteral):
            return Literal(_parse_date(node.text), T.DATE)
        if isinstance(node, t.TimestampLiteral):
            return Literal(_parse_timestamp(node.text), T.TIMESTAMP)
        if isinstance(node, t.IntervalLiteral):
            return _interval_literal(node)
        if isinstance(node, t.CurrentTime):
            if self.session is None or node.function != "DATE":
                raise SemanticError(f"current_{node.function.lower()} "
                                    "not available here")
            return Literal(self.session.start_date, T.DATE)
        if isinstance(node, t.Parameter):
            # a `?` marker: only plannable under EXECUTE ... USING, which
            # stashes the bound value types on the session before planning
            # (ParameterRewriter analog — the plan stays value-free, so
            # the plan cache reuses it across executions)
            types = getattr(self.session, "param_types", None) \
                if self.session is not None else None
            if types is None:
                raise SemanticError(
                    "parameters are only supported in EXECUTE ... USING")
            if node.position >= len(types):
                raise SemanticError(
                    f"parameter ?{node.position + 1} has no bound value "
                    f"({len(types)} provided)")
            return BoundParam(node.position, types[node.position])
        # ------------------------------------------------------- references
        if isinstance(node, t.Identifier):
            return self._column((node.value,))
        if isinstance(node, t.DereferenceExpression):
            parts = _dereference_parts(node)
            if parts is None:
                raise SemanticError(f"unsupported dereference: {node}")
            return self._column(parts)
        # ------------------------------------------------------- operators
        if isinstance(node, t.ArithmeticBinary):
            a = self._translate(node.left)
            b = self._translate(node.right)
            return self._sub(make_arithmetic(node.op, a, b))
        if isinstance(node, t.ArithmeticUnary):
            a = self._translate(node.value)
            if node.op == "+":
                return a
            return self._sub(Call("negate", (a,), a.type))
        if isinstance(node, t.ComparisonExpression):
            a = self._translate(node.left)
            b = self._translate(node.right)
            return self._sub(make_comparison(node.op, a, b))
        if isinstance(node, t.LogicalBinary):
            a = self._to_bool(self._translate(node.left))
            b = self._to_bool(self._translate(node.right))
            kind = SpecialKind.AND if node.op == "AND" else SpecialKind.OR
            return SpecialForm(kind, (a, b), T.BOOLEAN)
        if isinstance(node, t.NotExpression):
            a = self._to_bool(self._translate(node.value))
            return SpecialForm(SpecialKind.NOT, (a,), T.BOOLEAN)
        if isinstance(node, t.IsNullPredicate):
            a = self._translate(node.value)
            return SpecialForm(SpecialKind.IS_NULL, (a,), T.BOOLEAN)
        if isinstance(node, t.IsNotNullPredicate):
            a = self._translate(node.value)
            inner = SpecialForm(SpecialKind.IS_NULL, (a,), T.BOOLEAN)
            return SpecialForm(SpecialKind.NOT, (inner,), T.BOOLEAN)
        if isinstance(node, t.BetweenPredicate):
            v = self._translate(node.value)
            lo = self._translate(node.min)
            hi = self._translate(node.max)
            return SpecialForm(SpecialKind.AND, (
                make_comparison(">=", v, lo),
                make_comparison("<=", v, hi)), T.BOOLEAN)
        if isinstance(node, t.InPredicate):
            return self._in_predicate(node)
        if isinstance(node, t.LikePredicate):
            v = self._translate(node.value)
            p = self._translate(node.pattern)
            args = (v, p)
            if node.escape is not None:
                args = args + (self._translate(node.escape),)
            return Call("like", args, T.BOOLEAN)
        if isinstance(node, t.ExistsPredicate):
            return self._subquery(node)
        if isinstance(node, t.SubqueryExpression):
            return self._subquery(node)
        # ------------------------------------------------------ conditionals
        if isinstance(node, t.SearchedCaseExpression):
            whens = [(self._to_bool(self._translate(w.operand)),
                      self._translate(w.result)) for w in node.when_clauses]
            default = (self._translate(node.default)
                       if node.default is not None else None)
            return _make_case(whens, default)
        if isinstance(node, t.SimpleCaseExpression):
            operand = self._translate(node.operand)
            whens = []
            for w in node.when_clauses:
                cond = make_comparison("=", operand,
                                       self._translate(w.operand))
                whens.append((cond, self._translate(w.result)))
            default = (self._translate(node.default)
                       if node.default is not None else None)
            return _make_case(whens, default)
        if isinstance(node, t.IfExpression):
            cond = self._to_bool(self._translate(node.condition))
            then = self._translate(node.true_value)
            els = (self._translate(node.false_value)
                   if node.false_value is not None else None)
            return _make_case([(cond, then)], els)
        if isinstance(node, t.CoalesceExpression):
            args = [self._translate(a) for a in node.operands]
            ct = args[0].type
            for a in args[1:]:
                nt = common_type(ct, a.type)
                if nt is None:
                    raise SemanticError("COALESCE argument types differ")
                ct = nt
            args = tuple(cast_to(a, ct) for a in args)
            return SpecialForm(SpecialKind.COALESCE, args, ct)
        if isinstance(node, t.NullIfExpression):
            # Trino contract: the comparison runs at the common type but the
            # result keeps the FIRST argument's type and (uncast) value, so
            # the IR type always agrees with the produced dtype
            a = self._translate(node.first)
            b = self._translate(node.second)
            ct = common_type(a.type, b.type)
            if ct is None:
                raise SemanticError("NULLIF argument types differ")
            cond = Call("eq", (cast_to(a, ct), cast_to(b, ct)), T.BOOLEAN)
            return SpecialForm(SpecialKind.IF,
                               (cond, Literal(None, a.type), a), a.type)
        # ----------------------------------------------------------- casts
        if isinstance(node, t.Cast):
            a = self._translate(node.value)
            target = T.parse_type(node.target_type)
            if isinstance(a, Literal) and a.value is None:
                return Literal(None, target)
            if node.safe:
                # TRY_CAST: NULL instead of error on unconvertible values
                return Call("try_cast", (a,), target)
            return cast_to(a, target)
        if isinstance(node, t.Extract):
            a = self._translate(node.value)
            fn = node.field.lower()
            if fn not in ("year", "month", "day", "quarter"):
                raise SemanticError(f"EXTRACT({node.field}) not supported")
            return self._sub(Call(fn, (a,), T.BIGINT))
        # ------------------------------------------------------- functions
        if isinstance(node, t.FunctionCall):
            return self._function_call(node)
        if isinstance(node, t.Row):
            raise SemanticError("ROW constructor not supported here")
        raise SemanticError(f"unsupported expression: {node!r}")

    # ------------------------------------------------------------- helpers

    def _column(self, parts: Tuple[str, ...]) -> RowExpression:
        level, field = self.scope.resolve(parts)
        if level > 0 and self.on_outer_reference is not None:
            self.on_outer_reference(level, field)
        return self._sub(field.symbol.ref())

    def _to_bool(self, e: RowExpression) -> RowExpression:
        if not isinstance(e.type, T.BooleanType):
            raise SemanticError(
                f"expected boolean, got {e.type.display()}: {e}")
        return e

    def _in_predicate(self, node: t.InPredicate) -> RowExpression:
        if isinstance(node.value_list, t.SubqueryExpression):
            return self._subquery(node)
        assert isinstance(node.value_list, t.InListExpression)
        v = self._translate(node.value)
        items = [self._translate(x) for x in node.value_list.values]
        ct = v.type
        for it in items:
            nt = common_type(ct, it.type)
            if nt is None:
                raise SemanticError(
                    f"IN list type mismatch: {ct.display()} vs "
                    f"{it.type.display()}")
            ct = nt
        v = cast_to(v, ct)
        eqs = tuple(make_comparison("=", v, cast_to(it, ct)) for it in items)
        if len(eqs) == 1:
            return eqs[0]
        out = eqs[0]
        for e in eqs[1:]:
            out = SpecialForm(SpecialKind.OR, (out, e), T.BOOLEAN)
        return out

    def _subquery(self, node: t.Expression) -> RowExpression:
        if self.subquery_handler is None:
            raise SemanticError("subqueries are not allowed here")
        return self.subquery_handler(self, node)

    def _function_call(self, node: t.FunctionCall) -> RowExpression:
        name = node.name.suffix.lower()
        if name == "grouping":
            # decoded from the GroupId set index (GroupingOperationRewriter
            # analog); only meaningful above ROLLUP/CUBE/GROUPING SETS
            if self.grouping_handler is None:
                raise SemanticError(
                    "grouping() outside a grouping-sets aggregation")
            return self.grouping_handler(self, node)
        if is_aggregate(name) or is_window(name):
            # aggregates/windows must have been planned already; look up the
            # translated form in substitutions
            key = self.aggregate_key(node)
            sym = self.substitutions.get(key)
            if sym is None:
                raise SemanticError(
                    f"aggregate/window {name}() not allowed in this context")
            return sym.ref()
        args = tuple(self._translate(a) for a in node.args)
        resolved = resolve_scalar(name, [a.type for a in args])
        args = tuple(cast_to(a, ty)
                     for a, ty in zip(args, resolved.arg_types))
        return self._sub(Call(resolved.name, args, resolved.return_type))

    def aggregate_key(self, node: t.FunctionCall) -> RowExpression:
        """Canonical RowExpression key for an aggregate/window call AST."""
        name = node.name.suffix.lower()
        args = tuple(self._translate(a) for a in node.args)
        filt = (self._translate(node.filter)
                if node.filter is not None else None)
        key_args = args if filt is None else args + (filt,)
        tag = f"$agg_{name}{'_distinct' if node.distinct else ''}"
        return Call(tag, key_args, T.UNKNOWN)


def _parse_timestamp(text: str) -> int:
    """'yyyy-mm-dd hh:mm:ss[.fff]' -> micros since epoch."""
    date_part, _, time_part = text.strip().partition(" ")
    days = _parse_date(date_part)
    micros = days * 86_400_000_000
    if time_part:
        hh, mm, ss = (time_part.split(":") + ["0", "0"])[:3]
        sec, _, frac = ss.partition(".")
        micros += (int(hh) * 3600 + int(mm) * 60 + int(sec)) * 1_000_000
        if frac:
            micros += int((frac + "000000")[:6])
    return micros


def _dereference_parts(node: t.Expression) -> Optional[Tuple[str, ...]]:
    if isinstance(node, t.Identifier):
        return (node.value,)
    if isinstance(node, t.DereferenceExpression):
        base = _dereference_parts(node.base)
        if base is None:
            return None
        return base + (node.field.value,)
    return None


def make_arithmetic(op: str, a: RowExpression,
                    b: RowExpression) -> RowExpression:
    resolved = arithmetic_call(op, a.type, b.type)
    if resolved.name in ("date_add_ym", "date_add_dt"):
        # canonical arg order: (date, interval)
        if isinstance(a.type, (T.IntervalDayTimeType, T.IntervalYearMonthType)):
            a, b = b, a
        if op == "-":
            b = Call("negate", (b,), b.type)
        return Call(resolved.name, (a, b), resolved.return_type)
    out = resolved.return_type
    # cross-class operands (int with decimal) coerce to the decimal class so
    # the kernel's scale handling sees two decimals
    if isinstance(out, T.DecimalType):
        a = _as_decimal(a)
        b = _as_decimal(b)
    elif isinstance(out, (T.DoubleType, T.RealType)):
        a = cast_to(a, out)
        b = cast_to(b, out)
    return Call(resolved.name, (a, b), out)


def _as_decimal(e: RowExpression) -> RowExpression:
    if isinstance(e.type, T.DecimalType):
        return e
    digits = {T.TinyintType: 3, T.SmallintType: 5, T.IntegerType: 10,
              T.BigintType: 18}.get(type(e.type))
    if digits is None:
        raise SemanticError(f"cannot treat {e.type.display()} as decimal")
    return cast_to(e, T.DecimalType(digits, 0))


def make_comparison(op: str, a: RowExpression,
                    b: RowExpression) -> RowExpression:
    if op in ("IS DISTINCT FROM", "IS NOT DISTINCT FROM"):
        eq, ct = comparison_call("=", a.type, b.type)
        # null-safe equality: translate via case on IS NULL flags
        a = cast_to(a, ct)
        b = cast_to(b, ct)
        a_null = SpecialForm(SpecialKind.IS_NULL, (a,), T.BOOLEAN)
        b_null = SpecialForm(SpecialKind.IS_NULL, (b,), T.BOOLEAN)
        both_null = SpecialForm(SpecialKind.AND, (a_null, b_null), T.BOOLEAN)
        eq_call = Call("eq", (a, b), T.BOOLEAN)
        eq_or = SpecialForm(SpecialKind.OR, (
            both_null,
            SpecialForm(SpecialKind.AND, (
                SpecialForm(SpecialKind.NOT, (a_null,), T.BOOLEAN),
                SpecialForm(SpecialKind.AND, (
                    SpecialForm(SpecialKind.NOT, (b_null,), T.BOOLEAN),
                    eq_call), T.BOOLEAN)), T.BOOLEAN)), T.BOOLEAN)
        not_distinct = SpecialForm(SpecialKind.COALESCE, (
            eq_or, Literal(False, T.BOOLEAN)), T.BOOLEAN)
        if op == "IS NOT DISTINCT FROM":
            return not_distinct
        return SpecialForm(SpecialKind.NOT, (not_distinct,), T.BOOLEAN)
    resolved, ct = comparison_call(op, a.type, b.type)
    return Call(resolved.name, (cast_to(a, ct), cast_to(b, ct)), T.BOOLEAN)


def _make_case(whens: List[Tuple[RowExpression, RowExpression]],
               default: Optional[RowExpression]) -> RowExpression:
    result_types = [v.type for _, v in whens]
    if default is not None:
        result_types.append(default.type)
    ct = result_types[0]
    for rt in result_types[1:]:
        nt = common_type(ct, rt)
        if nt is None:
            raise SemanticError("CASE branches have incompatible types")
        ct = nt
    args: List[RowExpression] = []
    for cond, val in whens:
        args += [cond, cast_to(val, ct)]
    args.append(cast_to(default, ct) if default is not None
                else Literal(None, ct))
    return SpecialForm(SpecialKind.SWITCH, tuple(args), ct)
