"""Process-wide metrics registry with Prometheus text rendering.

Reference parity: the reference exports engine counters through JMX
MBeans (io.airlift.stats CounterStat/DistributionStat on QueryManager,
MemoryPool, resource groups) and publishes them as OpenMetrics via the
jmx-prometheus agent every production deployment runs. Here the registry
is native: counters/histograms are fed by query lifecycle events
(obs/listeners.py), and gauges SAMPLE live engine state at scrape time —
the query tracker, the node memory pool, every live resource-group tree,
and the jit kernel cache — so `GET /v1/metrics` and
`system.runtime.metrics` always reflect the current process without any
background collection thread.

Naming follows Prometheus conventions: `trino_tpu_` prefix, `_total`
suffix on monotonic counters, base units (bytes, seconds).
"""

from __future__ import annotations

import math
import threading
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

LabelSet = Tuple[Tuple[str, str], ...]

# query wall-clock histogram buckets (seconds): spans compile-dominated
# millisecond queries to SF100 multi-minute rungs. The DEFAULT is
# session-independent and overridable process-wide via
# $TRINO_TPU_METRICS_WALL_BUCKETS (comma-separated seconds) or per
# deployment via TrinoServer(metrics_wall_buckets=...) -> set_wall_buckets
DEFAULT_WALL_BUCKETS = (0.005, 0.025, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0,
                        600.0)


def _env_wall_buckets() -> Tuple[float, ...]:
    import os
    raw = os.environ.get("TRINO_TPU_METRICS_WALL_BUCKETS", "")
    try:
        out = tuple(sorted(float(x) for x in raw.split(",") if x.strip()))
    except ValueError:
        return DEFAULT_WALL_BUCKETS
    return out or DEFAULT_WALL_BUCKETS


WALL_BUCKETS = _env_wall_buckets()

# preemption-latency buckets (seconds): cancel-request -> unwind is
# slice-bounded, so the interesting range is milliseconds to a few
# seconds, far below query walls
PREEMPT_BUCKETS = (0.001, 0.005, 0.025, 0.1, 0.25, 0.5, 1.0, 5.0, 30.0)


def _labels(kw: Dict[str, Any]) -> LabelSet:
    return tuple(sorted((str(k), str(v)) for k, v in kw.items()))


def _render_labels(labels: LabelSet) -> str:
    if not labels:
        return ""
    body = ",".join(
        '%s="%s"' % (k, v.replace("\\", "\\\\").replace('"', '\\"')
                     .replace("\n", "\\n"))
        for k, v in labels)
    return "{" + body + "}"


class Counter:
    """Monotonic counter family (one value per label set). `labeled`
    families never fabricate an unlabeled zero sample: a placeholder
    series that vanishes after the first real labeled increment reads as
    a counter reset to anything monitoring it."""

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 labeled: bool = False):
        self.name = name
        self.help = help
        self.labeled = labeled
        self._registry = registry
        self._values: Dict[LabelSet, float] = {}

    def inc(self, amount: float = 1.0, **labels) -> None:
        if amount == 0:
            return
        key = _labels(labels)
        with self._registry._lock:
            self._values[key] = self._values.get(key, 0.0) + float(amount)

    def samples(self) -> Iterable[Tuple[str, LabelSet, float]]:
        with self._registry._lock:
            items = list(self._values.items())
        if not items:
            if self.labeled:
                return              # family header only, no samples yet
            items = [((), 0.0)]     # label-less family exists from birth
        for key, value in items:
            yield self.name, key, value


class Histogram:
    """Cumulative-bucket histogram family (Prometheus semantics).
    `labeled` families render no samples until the first observation —
    same phantom-series discipline as labeled counters (an unlabeled
    zero series that vanishes after the first real labeled observation
    reads as a reset)."""

    def __init__(self, registry: "MetricsRegistry", name: str, help: str,
                 buckets: Tuple[float, ...] = WALL_BUCKETS,
                 labeled: bool = False):
        self.name = name
        self.help = help
        self.labeled = labeled
        self.buckets = tuple(sorted(buckets))
        self._registry = registry
        self._counts: Dict[LabelSet, List[int]] = {}
        self._sums: Dict[LabelSet, float] = {}
        self._totals: Dict[LabelSet, int] = {}

    def observe(self, value: float, **labels) -> None:
        key = _labels(labels)
        with self._registry._lock:
            counts = self._counts.setdefault(key, [0] * len(self.buckets))
            for i, b in enumerate(self.buckets):
                if value <= b:
                    counts[i] += 1
            self._sums[key] = self._sums.get(key, 0.0) + float(value)
            self._totals[key] = self._totals.get(key, 0) + 1

    def set_buckets(self, buckets: Tuple[float, ...]) -> None:
        """Re-bucket the family (deployment configuration — TrinoServer
        metrics_wall_buckets). Bucket counts are per-observation
        cumulative, so prior observations cannot be re-binned: the
        family RESETS (counts, sums, totals) — same visible effect as a
        process restart with the new buckets, which is when bucket
        boundaries legitimately change. A scrape-side monitor sees a
        counter reset, the semantics Prometheus defines for restarts."""
        with self._registry._lock:
            self.buckets = tuple(sorted(buckets))
            self._counts.clear()
            self._sums.clear()
            self._totals.clear()

    def samples(self) -> Iterable[Tuple[str, LabelSet, float]]:
        with self._registry._lock:
            keys = list(self._counts) or ([] if self.labeled else [()])
            counts = {k: list(v) for k, v in self._counts.items()}
            sums, totals = dict(self._sums), dict(self._totals)
        for key in keys:
            cum = counts.get(key, [0] * len(self.buckets))
            for b, c in zip(self.buckets, cum):
                yield (self.name + "_bucket",
                       key + (("le", _fmt_float(b)),), float(c))
            yield (self.name + "_bucket", key + (("le", "+Inf"),),
                   float(totals.get(key, 0)))
            yield self.name + "_sum", key, sums.get(key, 0.0)
            yield self.name + "_count", key, float(totals.get(key, 0))


def _fmt_float(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    out = repr(float(v))
    return out[:-2] if out.endswith(".0") else out


class MetricsRegistry:
    """Instrument + gauge-callback registry; render() is the scrape."""

    def __init__(self):
        self._lock = threading.RLock()
        self._counters: Dict[str, Counter] = {}
        self._histograms: Dict[str, Histogram] = {}
        # each callback yields (name, help, value, labels_dict) gauge
        # samples from live engine state at scrape time
        self._gauge_callbacks: List[Callable[[], Iterable[tuple]]] = []

    def counter(self, name: str, help: str,
                labeled: bool = False) -> Counter:
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(self, name, help,
                                                   labeled)
            return c

    def histogram(self, name: str, help: str,
                  buckets: Tuple[float, ...] = WALL_BUCKETS,
                  labeled: bool = False) -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(self, name, help,
                                                       buckets, labeled)
            return h

    def register_gauges(self, callback: Callable[[], Iterable[tuple]]
                        ) -> None:
        with self._lock:
            if callback not in self._gauge_callbacks:
                self._gauge_callbacks.append(callback)

    # ---------------------------------------------------------- scrape

    def _gauge_samples(self) -> List[Tuple[str, str, LabelSet, float]]:
        out = []
        with self._lock:
            callbacks = list(self._gauge_callbacks)
        for cb in callbacks:
            try:
                for name, help, value, labels in cb():
                    out.append((name, help, _labels(labels), float(value)))
            except Exception:   # a broken sampler must not fail the scrape
                continue
        return out

    def render(self) -> str:
        """The Prometheus text exposition (format 0.0.4): families
        grouped under one HELP/TYPE header each."""
        lines: List[str] = []
        with self._lock:
            counters = list(self._counters.values())
            histograms = list(self._histograms.values())
        for c in sorted(counters, key=lambda c: c.name):
            lines.append(f"# HELP {c.name} {c.help}")
            lines.append(f"# TYPE {c.name} counter")
            for name, labels, value in c.samples():
                lines.append(f"{name}{_render_labels(labels)} "
                             f"{_fmt_value(value)}")
        for h in sorted(histograms, key=lambda h: h.name):
            lines.append(f"# HELP {h.name} {h.help}")
            lines.append(f"# TYPE {h.name} histogram")
            for name, labels, value in h.samples():
                lines.append(f"{name}{_render_labels(labels)} "
                             f"{_fmt_value(value)}")
        gauges = self._gauge_samples()
        seen_header = set()
        for name, help, labels, value in sorted(gauges):
            if name not in seen_header:
                seen_header.add(name)
                lines.append(f"# HELP {name} {help}")
                lines.append(f"# TYPE {name} gauge")
            lines.append(f"{name}{_render_labels(labels)} "
                         f"{_fmt_value(value)}")
        return "\n".join(lines) + "\n"

    def samples(self) -> List[Tuple[str, str, str, float]]:
        """(name, kind, labels, value) rows for system.runtime.metrics —
        the same data render() exposes, shaped for a table scan."""
        rows: List[Tuple[str, str, str, float]] = []
        with self._lock:
            counters = list(self._counters.values())
            histograms = list(self._histograms.values())
        for c in counters:
            for name, labels, value in c.samples():
                rows.append((name, "counter", _render_labels(labels)[1:-1],
                             value))
        for h in histograms:
            for name, labels, value in h.samples():
                rows.append((name, "histogram", _render_labels(labels)[1:-1],
                             value))
        for name, _help, labels, value in self._gauge_samples():
            rows.append((name, "gauge", _render_labels(labels)[1:-1], value))
        return sorted(rows)


def _fmt_value(v: float) -> str:
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


# the process-wide registry (singleton scope, like TRACKER / NODE_POOL)
REGISTRY = MetricsRegistry()

# counter/histogram families fed by query lifecycle events
# (obs/listeners.py fires these on every tracker transition)
QUERIES_TOTAL = REGISTRY.counter(
    "trino_tpu_queries_total",
    "Queries reaching a terminal state, by state.", labeled=True)
QUERY_ROWS_TOTAL = REGISTRY.counter(
    "trino_tpu_query_rows_total", "Result rows returned by queries.")
QUERY_BYTES_TOTAL = REGISTRY.counter(
    "trino_tpu_query_bytes_total", "Output bytes produced by queries.")
QUERY_RETRIES_TOTAL = REGISTRY.counter(
    "trino_tpu_query_retries_total",
    "Task/query retry attempts across all queries.")
FAULTS_INJECTED_TOTAL = REGISTRY.counter(
    "trino_tpu_faults_injected_total",
    "Chaos faults injected across all queries.")
SPILLED_BYTES_TOTAL = REGISTRY.counter(
    "trino_tpu_query_spilled_bytes_total",
    "Bytes spilled to host partitions across all queries.")
QUERY_WALL_SECONDS = REGISTRY.histogram(
    "trino_tpu_query_wall_seconds",
    "Query wall-clock duration from start to terminal state.")
EXCHANGE_BYTES_TOTAL = REGISTRY.counter(
    "trino_tpu_exchange_bytes_total",
    "Bytes moved through inter-fragment exchanges (on-device "
    "collectives; live-row estimate).")
EXCHANGE_ROWS_TOTAL = REGISTRY.counter(
    "trino_tpu_exchange_rows_total",
    "Rows moved through inter-fragment exchanges.")
EXCHANGES_TOTAL = REGISTRY.counter(
    "trino_tpu_exchanges_total",
    "Inter-fragment exchanges by data-plane mode: 'fused' = collective "
    "inlined in a co-scheduled mesh program (pages never leave the "
    "producing XLA program); 'staged' = standalone collective over "
    "host-staged per-shard fragment outputs.", labeled=True)
SLICES_TOTAL = REGISTRY.counter(
    "trino_tpu_slices_total",
    "Bounded-work execution slices completed across all queries "
    "(preemptible sliced execution, exec/sliced/).")
CHECKPOINTS_TOTAL = REGISTRY.counter(
    "trino_tpu_checkpoints_total",
    "Operator checkpoints by operation: 'saved' = durable state written "
    "at a slice/shard boundary; 'restored' = a retry resumed from one "
    "instead of re-executing.", labeled=True)
CHECKPOINT_BYTES_TOTAL = REGISTRY.counter(
    "trino_tpu_checkpoint_bytes_total",
    "Bytes of operator state checkpointed across all queries.")
PREEMPTIONS_TOTAL = REGISTRY.counter(
    "trino_tpu_preemptions_total",
    "Queries preempted (canceled/killed between slices) across the "
    "process lifetime.")
ADAPTIVE_EVENTS_TOTAL = REGISTRY.counter(
    "trino_tpu_adaptive_events_total",
    "Adaptive operator strategy events by kind: partial-aggregation "
    "mode transitions (agg_mode_downgrades/agg_mode_upgrades), "
    "recursive spill repartition rounds (agg_recursions/"
    "join_recursions), heavy-hitter key splits (heavy_key_splits), and "
    "bounded chunked fallbacks at max recursion depth "
    "(spill_fallbacks).", labeled=True)
MXU_JOINS_TOTAL = REGISTRY.counter(
    "trino_tpu_mxu_joins_total",
    "Joins executed as density-partitioned indicator matmuls on the "
    "matrix unit (ops/join_mxu.py) across the process lifetime.")
MXU_FLOPS_TOTAL = REGISTRY.counter(
    "trino_tpu_mxu_flops_total",
    "Cost-model MACs (2 flops each) issued by matrix-unit join probe "
    "dispatches across the process lifetime.")
PREEMPT_LATENCY_SECONDS = REGISTRY.histogram(
    "trino_tpu_preempt_latency_seconds",
    "Cancel-request to unwind wall per preempted query — bounded by "
    "one slice's wall under sliced execution.",
    buckets=PREEMPT_BUCKETS)
GROUP_WALL_SECONDS = REGISTRY.histogram(
    "trino_tpu_group_wall_seconds",
    "Query wall-clock duration by resource group and terminal outcome "
    "(FINISHED/FAILED/CANCELED) — the per-group latency/SLO surface the "
    "serving tier alerts on.", labeled=True)
LISTENER_ERRORS_TOTAL = REGISTRY.counter(
    "trino_tpu_listener_errors_total",
    "Event-listener callbacks that raised, by listener type. Failures "
    "are swallowed (a broken plugin must not fail queries) and logged "
    "once per listener; this counter is the ongoing signal.",
    labeled=True)
COMPILE_SECONDS_TOTAL = REGISTRY.counter(
    "trino_tpu_query_compile_seconds_total",
    "Summed XLA compile wall attributed to queries (measured at the "
    "jit cache's AOT compile sites) — the compile half of "
    "compile-vs-execute accounting.")
DEVICE_SECONDS_TOTAL = REGISTRY.counter(
    "trino_tpu_query_device_seconds_total",
    "Summed measured device wall attributed to queries (fused-chain "
    "dispatches fenced at chain granularity under operator-level "
    "collection).")
MV_REFRESH_TOTAL = REGISTRY.counter(
    "trino_tpu_mv_refresh_total",
    "Materialized-view refreshes by mode: 'delta' = incremental merge "
    "over the manifest-log diff, 'full' = complete recompute, 'noop' = "
    "base versions unchanged since the last refresh.", labeled=True)
MV_REFRESH_SECONDS_TOTAL = REGISTRY.counter(
    "trino_tpu_mv_refresh_seconds_total",
    "Summed wall-clock spent executing materialized-view refreshes.")
MV_REWRITE_HITS_TOTAL = REGISTRY.counter(
    "trino_tpu_mv_rewrite_hits_total",
    "Queries rewritten onto a fresh materialized view's storage table.")
MV_REWRITE_STALE_TOTAL = REGISTRY.counter(
    "trino_tpu_mv_rewrite_stale_total",
    "Rewrite/serve attempts refused because the view exceeded the "
    "session's mv_max_staleness_s budget.")
MV_CACHE_REPUBLISH_TOTAL = REGISTRY.counter(
    "trino_tpu_mv_cache_republish_total",
    "Result-cache entries UPDATED in place by a refresh (the "
    "update-on-write flip: re-executed rewritten statements republished "
    "under their original keys).")


def set_wall_buckets(buckets) -> None:
    """Deployment-time bucket configuration for the wall histograms
    (TrinoServer(metrics_wall_buckets=...)); resets the families — see
    Histogram.set_buckets. Applies to BOTH wall families: the per-group
    SLO histogram alerts on the same latency envelope the deployment
    tuned the query-wall buckets for."""
    bounds = tuple(float(b) for b in buckets)
    QUERY_WALL_SECONDS.set_buckets(bounds)
    GROUP_WALL_SECONDS.set_buckets(bounds)


def _engine_gauges():
    """Live engine state sampled at scrape time: tracker states, node
    memory pool, resource groups, jit kernel cache."""
    from trino_tpu.exec.query_tracker import TRACKER
    states: Dict[str, int] = {}
    for q in TRACKER.list():
        states[q.state] = states.get(q.state, 0) + 1
    for state, n in sorted(states.items()):
        yield ("trino_tpu_queries", "Tracked queries by lifecycle state.",
               n, {"state": state})

    from trino_tpu.exec.memory import NODE_POOL
    pool = "Node memory pool "
    yield ("trino_tpu_pool_limit_bytes", pool + "reservable budget.",
           NODE_POOL.limit or 0, {})
    yield ("trino_tpu_pool_reserved_bytes", pool + "current reservation.",
           NODE_POOL.reserved, {})
    yield ("trino_tpu_pool_peak_bytes", pool + "peak reservation.",
           NODE_POOL.peak, {})
    yield ("trino_tpu_pool_kills", pool + "low-memory-killer victims.",
           NODE_POOL.kills, {})
    yield ("trino_tpu_pool_leaks", pool + "reservation leaks at query end.",
           NODE_POOL.leaks, {})
    yield ("trino_tpu_pool_leaked_bytes", pool + "bytes leaked total.",
           NODE_POOL.leaked_bytes, {})
    for d in sorted(set(NODE_POOL.device_reserved)
                    | set(NODE_POOL.device_peak)):
        labels = {"device": d}
        yield ("trino_tpu_pool_device_reserved_bytes",
               pool + "current reservation attributed per mesh device.",
               NODE_POOL.device_reserved.get(d, 0), labels)
        yield ("trino_tpu_pool_device_peak_bytes",
               pool + "peak reservation attributed per mesh device.",
               NODE_POOL.device_peak.get(d, 0), labels)

    from trino_tpu.exec.spill import SPILL_LEDGER
    spill = "Spill partition stores: "
    yield ("trino_tpu_spill_bytes",
           spill + "host RAM currently held by spilled partitions.",
           SPILL_LEDGER.reserved, {})
    yield ("trino_tpu_spill_peak_bytes",
           spill + "peak host RAM held since process start.",
           SPILL_LEDGER.peak, {})
    yield ("trino_tpu_spill_limit_denials",
           spill + "reservations denied by a query's spill_max_bytes "
           "budget (EXCEEDED_SPILL_LIMIT failures).",
           SPILL_LEDGER.denials, {})

    from trino_tpu.exec.resource_groups import list_all_groups
    for g in list_all_groups():
        labels = {"group": g.name}
        yield ("trino_tpu_resource_group_queued",
               "Queued queries per resource group.", g.queued, labels)
        yield ("trino_tpu_resource_group_running",
               "Running queries per resource group.", len(g.running),
               labels)
        yield ("trino_tpu_resource_group_served_from_cache",
               "Completed queries answered from the result cache per "
               "resource group (zero-dispatch fast path; counted so "
               "group QPS quotas see cached traffic).",
               g.served_from_cache, labels)
        if g.cache_hit_rejections or g.result_cache_qps is not None:
            yield ("trino_tpu_resource_group_cache_hit_rejections",
                   "Fast-path hits rejected by the group's "
                   "result_cache_qps token bucket (QUERY_QUEUE_FULL "
                   "on the wire).",
                   g.cache_hit_rejections, labels)

    from trino_tpu.exec import jit_cache
    js = jit_cache.stats()
    yield ("trino_tpu_jit_cache_kernels",
           "Compiled kernels resident in the jit cache.", js["size"], {})
    yield ("trino_tpu_jit_cache_hits",
           "Jit cache hits since process start.", js["hits"], {})
    yield ("trino_tpu_jit_cache_misses",
           "Jit cache misses (kernel builds) since process start.",
           js["misses"], {})
    yield ("trino_tpu_jit_cache_param_hits",
           "Hits on a canonical (literal-hoisted) key whose parameter "
           "values changed since that key's previous call — kernel "
           "sharing per-literal keying could not have expressed.",
           js["param_hits"], {})
    yield ("trino_tpu_jit_cache_evictions_total",
           "Kernels evicted from the in-process LRU since process start "
           "(evicted shapes reload from the persistent XLA cache).",
           js["evictions"], {})
    yield ("trino_tpu_jit_compiles_total",
           "XLA compiles performed through the profiled dispatch path "
           "(one per new input signature of a chain/program kernel) — "
           "each one a timed, query-attributed event.",
           js["compiles"], {})
    yield ("trino_tpu_jit_compile_seconds_total",
           "Summed wall of profiled-path XLA compiles since process "
           "start.", js["compile_s"], {})
    yield ("trino_tpu_jit_compiled_hlo_ops_total",
           "Summed HLO instruction count of profiled-path compiles.",
           js["hlo_ops"], {})
    yield ("trino_tpu_jit_aot_fallbacks_total",
           "Profiled dispatches that fell back to the plain jitted "
           "callable (signature mismatch at call time) — a systematic "
           "nonzero rate means the AOT accounting path is misfiring.",
           js["aot_fallbacks"], {})

    from trino_tpu.obs.history import HISTORY
    hs = HISTORY.stats()
    hist = "Query-history ring (obs/history.py): "
    yield ("trino_tpu_history_entries",
           hist + "completed queries currently retained.",
           hs["entries"], {})
    yield ("trino_tpu_history_max_entries",
           hist + "retention bound (history_max_entries).",
           hs["max_entries"], {})
    yield ("trino_tpu_history_recorded_total",
           hist + "terminal queries recorded since process start.",
           hs["recorded"], {})
    yield ("trino_tpu_history_evicted_total",
           hist + "records dropped by the FIFO bound.",
           hs["evicted"], {})

    from trino_tpu.exec import plan_cache
    ps = plan_cache.stats()
    yield ("trino_tpu_plan_cache_entries",
           "Optimized plans resident across live plan caches.",
           ps["entries"], {})
    yield ("trino_tpu_plan_cache_hits",
           "Plan cache hits since process start — statements that "
           "skipped parse/analyze/plan/optimize.", ps["hits"], {})
    yield ("trino_tpu_plan_cache_misses",
           "Plan cache misses (full plans built) since process start.",
           ps["misses"], {})
    yield ("trino_tpu_plan_cache_evictions_total",
           "Plans evicted by the per-runner LRU since process start.",
           ps["evictions"], {})
    yield ("trino_tpu_plan_cache_invalidations_total",
           "Plans dropped by DDL/INSERT table invalidation since "
           "process start.", ps["invalidations"], {})

    from trino_tpu.serve.caches import (result_cache_stats,
                                        scan_cache_stats)
    rs = result_cache_stats()
    yield ("trino_tpu_result_cache_entries",
           "Materialized results resident across live result caches.",
           rs["entries"], {})
    yield ("trino_tpu_result_cache_hits",
           "Result cache hits since process start — statements answered "
           "with zero planning, zero compiles, zero execution.",
           rs["hits"], {})
    yield ("trino_tpu_result_cache_misses",
           "Result cache misses (statements executed) since process "
           "start.", rs["misses"], {})
    yield ("trino_tpu_result_cache_evictions_total",
           "Results evicted by the LRU since process start.",
           rs["evictions"], {})
    yield ("trino_tpu_result_cache_invalidations_total",
           "Results dropped by DDL/INSERT table invalidation since "
           "process start.", rs["invalidations"], {})
    ss = scan_cache_stats()
    yield ("trino_tpu_scan_cache_entries",
           "Staged table scans resident across live scan caches.",
           ss["entries"], {})
    yield ("trino_tpu_scan_cache_bytes",
           "Device bytes pinned by staged scan pages.", ss["bytes"], {})
    yield ("trino_tpu_scan_cache_hits",
           "Scan cache hits since process start — table scans served "
           "from staged device pages.", ss["hits"], {})
    yield ("trino_tpu_scan_cache_misses",
           "Scan cache misses (scans staged from the connector) since "
           "process start.", ss["misses"], {})

    from trino_tpu.exec.table_cache import (device_residency,
                                            table_cache_stats)
    ts = table_cache_stats()
    tc = "Device-resident hot-table cache: "
    yield ("trino_tpu_table_cache_entries",
           tc + "promoted (table, columns) working sets resident.",
           ts["entries"], {})
    yield ("trino_tpu_table_cache_bytes",
           tc + "HBM pinned by resident columns.", ts["bytes"], {})
    yield ("trino_tpu_table_cache_hits",
           tc + "scans served entirely from HBM (zero host->device "
           "staging).", ts["hits"], {})
    yield ("trino_tpu_table_cache_misses",
           tc + "scans that staged from the connector.",
           ts["misses"], {})
    yield ("trino_tpu_table_cache_evictions",
           tc + "entries evicted under the byte budget.",
           ts["evictions"], {})
    yield ("trino_tpu_table_cache_promotions",
           tc + "working sets promoted since process start.",
           ts["promotions"], {})
    yield ("trino_tpu_table_cache_invalidations",
           tc + "entries dropped by DDL/INSERT invalidation.",
           ts["invalidations"], {})
    for dev, nbytes in sorted(device_residency().items(),
                              key=lambda kv: -1 if kv[0] is None
                              else kv[0]):
        # None = promoted outside a pinned shard (the default device);
        # a distinct label value so it can never collide with a real
        # device-0 series in the exposition
        yield ("trino_tpu_table_cache_device_bytes",
               tc + "resident bytes attributed per mesh device.",
               nbytes, {"device": "default" if dev is None else dev})

    try:
        from trino_tpu.connector.lake import lake_stats
        ls = lake_stats()
        lk = "Lake connector: "
        yield ("trino_tpu_lake_files_written",
               lk + "data files committed since process start.",
               ls["files_written"], {})
        yield ("trino_tpu_lake_files_scanned",
               lk + "data files read by scans.", ls["files_scanned"], {})
        yield ("trino_tpu_lake_files_pruned",
               lk + "data files skipped by partition/zone-map pruning "
               "against the scan TupleDomain.", ls["files_pruned"], {})
        yield ("trino_tpu_lake_row_groups_pruned",
               lk + "row groups skipped by zone-map pruning.",
               ls["row_groups_pruned"], {})
        yield ("trino_tpu_lake_manifest_commits",
               lk + "atomic manifest swaps committed.",
               ls["manifest_commits"], {})
        yield ("trino_tpu_lake_replayed_commits",
               lk + "write-token replays detected (retried INSERT/CTAS "
               "attempts that no-op'd — the exactly-once proof).",
               ls["replayed_commits"], {})
        yield ("trino_tpu_lake_corruption_detected",
               lk + "read-side content-verification failures (file or "
               "row-group digest mismatch, undecodable file) — each "
               "classified LAKE_DATA_CORRUPTION, never silent wrong "
               "rows.", ls["corruption_detected"], {})
        yield ("trino_tpu_lake_files_quarantined",
               lk + "data files in the per-process corruption "
               "quarantine (fail-fast until lake_fsck clears them).",
               ls["files_quarantined"], {})
    except Exception:   # lake import must never fail the scrape
        pass

    from trino_tpu.exec.sliced.checkpoint import checkpoint_stats
    cs = checkpoint_stats()
    yield ("trino_tpu_checkpoints_saved",
           "Operator checkpoints saved since process start (sliced "
           "execution slice/shard boundaries).", cs["saved"], {})
    yield ("trino_tpu_checkpoints_restored",
           "Operator checkpoints a retry resumed from since process "
           "start (work NOT re-executed).", cs["restored"], {})
    yield ("trino_tpu_checkpoints_dropped",
           "Operator checkpoints released since process start.",
           cs["dropped"], {})

    from trino_tpu.serve.streaming import stream_stats
    st = stream_stats()
    yield ("trino_tpu_streams_open",
           "Result streams currently open (producing or draining).",
           st["open"], {})
    yield ("trino_tpu_stream_buffered_chunks",
           "Result chunks resident in open stream ring buffers "
           "(bounded per stream by the ring size — the backpressure "
           "signal).", st["buffered_chunks"], {})


REGISTRY.register_gauges(_engine_gauges)
