"""Test configuration: run on CPU with 8 virtual devices.

Multi-chip hardware is not available in CI; sharding tests exercise a virtual
8-device CPU mesh (mirrors how the driver dry-runs dryrun_multichip). Must be
set before jax initializes — conftest is imported before any test module.

The `mesh` marker (pytest.ini) tags the multi-chip sharded-execution suite
(tests/test_mesh_queries.py): under this conftest it runs inline on the
forced 8-device mesh; collected into a process whose backend came up with
fewer devices, the module re-runs itself in a subprocess with
XLA_FLAGS=--xla_force_host_platform_device_count=8 — either way tier-1
exercises the sharded path without a TPU.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the shell pre-sets the tpu tunnel
flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_enable_x64", True)
# The axon sitecustomize registers the TPU backend at interpreter startup and
# overrides JAX_PLATFORMS from the env; the config knob still wins.
jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite compiles hundreds of fused query
# kernels; caching them on disk makes re-runs near-instant and keeps
# cumulative in-process LLVM compilation (which has crashed the CPU backend
# under the full 22-query distributed sweep) bounded.
import trino_tpu

trino_tpu.enable_persistent_cache()

import pytest


@pytest.fixture(autouse=True)
def node_pool_leak_gate():
    """Leak gate: after EVERY engine test the node memory pool must read
    zero reserved bytes — a nonzero pool means some query's ledger closed
    dirty or never closed (the reservation-leak class of bug this round's
    resource-governance layer exists to catch). Server tests finish
    queries on background executor threads, so give stragglers a short
    grace window before failing."""
    yield
    import time

    from trino_tpu.exec.memory import NODE_POOL
    deadline = time.monotonic() + 5.0
    while NODE_POOL.reserved != 0 and time.monotonic() < deadline:
        time.sleep(0.02)
    leaked, culprits = NODE_POOL.reserved, list(NODE_POOL._contexts)
    if leaked:
        # reset so exactly ONE test reports the leak — without this,
        # every subsequent test inherits the nonzero pool (plus the 5s
        # grace wait) and the real culprit drowns in cascade failures
        with NODE_POOL._cond:
            NODE_POOL._contexts.clear()
            NODE_POOL.reserved = 0
            NODE_POOL._cond.notify_all()
    assert leaked == 0, (
        f"node memory pool leaked {leaked} bytes "
        f"(live contexts: {culprits})")
