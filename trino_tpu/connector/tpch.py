"""TPC-H generator connector: deterministic in-memory data, no files.

Reference parity: plugin/trino-tpch (TpchMetadata.java, TpchRecordSetProvider
.java, TpchSplitManager.java) — schemas tiny/sf1/sf10/... expose the 8 TPC-H
tables, rows generated on demand. The reference delegates to io.airlift.tpch
(a dbgen port); here a seeded NumPy generator produces the same schema and
spec-shaped distributions (correctness is asserted engine-vs-oracle on the
SAME generated data, the H2QueryRunner pattern, so exact dbgen bitstreams are
not load-bearing).

All varchar columns come dictionary-encoded; dates are int32 days since epoch;
prices are short decimals (scaled int64).
"""

from __future__ import annotations

import math
import zlib
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from trino_tpu import types as T
from trino_tpu.connector.spi import (
    ColumnHandle, ColumnMetadata, Connector, ConnectorMetadata,
    ConnectorPageSource, ConnectorSplitManager, ConnectorTableHandle,
    ColumnStatistics, SchemaTableName, Split, TableMetadata, TableStatistics,
    pad_to_capacity, split_range)
from trino_tpu.expr.functions import days_from_civil
from trino_tpu.page import Column, Dictionary, Page

_D12_2 = T.DecimalType(12, 2)

SCHEMAS = {
    "tiny": 0.01, "sf1": 1.0, "sf10": 10.0, "sf100": 100.0,
    "sf300": 300.0, "sf1000": 1000.0,
}

# table -> (columns, base row count at sf1); row counts per TPC-H spec 4.2.5
TABLES: Dict[str, tuple] = {
    "region": ((("r_regionkey", T.BIGINT), ("r_name", T.VarcharType(25)),
                ("r_comment", T.VarcharType(152))), None),
    "nation": ((("n_nationkey", T.BIGINT), ("n_name", T.VarcharType(25)),
                ("n_regionkey", T.BIGINT), ("n_comment", T.VarcharType(152))),
               None),
    "supplier": ((("s_suppkey", T.BIGINT), ("s_name", T.VarcharType(25)),
                  ("s_address", T.VarcharType(40)), ("s_nationkey", T.BIGINT),
                  ("s_phone", T.VarcharType(15)), ("s_acctbal", _D12_2),
                  ("s_comment", T.VarcharType(101))), 10_000),
    "customer": ((("c_custkey", T.BIGINT), ("c_name", T.VarcharType(25)),
                  ("c_address", T.VarcharType(40)), ("c_nationkey", T.BIGINT),
                  ("c_phone", T.VarcharType(15)), ("c_acctbal", _D12_2),
                  ("c_mktsegment", T.VarcharType(10)),
                  ("c_comment", T.VarcharType(117))), 150_000),
    "part": ((("p_partkey", T.BIGINT), ("p_name", T.VarcharType(55)),
              ("p_mfgr", T.VarcharType(25)), ("p_brand", T.VarcharType(10)),
              ("p_type", T.VarcharType(25)), ("p_size", T.INTEGER),
              ("p_container", T.VarcharType(10)), ("p_retailprice", _D12_2),
              ("p_comment", T.VarcharType(23))), 200_000),
    "partsupp": ((("ps_partkey", T.BIGINT), ("ps_suppkey", T.BIGINT),
                  ("ps_availqty", T.INTEGER), ("ps_supplycost", _D12_2),
                  ("ps_comment", T.VarcharType(199))), 800_000),
    "orders": ((("o_orderkey", T.BIGINT), ("o_custkey", T.BIGINT),
                ("o_orderstatus", T.VarcharType(1)), ("o_totalprice", _D12_2),
                ("o_orderdate", T.DATE),
                ("o_orderpriority", T.VarcharType(15)),
                ("o_clerk", T.VarcharType(15)), ("o_shippriority", T.INTEGER),
                ("o_comment", T.VarcharType(79))), 1_500_000),
    "lineitem": ((("l_orderkey", T.BIGINT), ("l_partkey", T.BIGINT),
                  ("l_suppkey", T.BIGINT), ("l_linenumber", T.INTEGER),
                  ("l_quantity", _D12_2), ("l_extendedprice", _D12_2),
                  ("l_discount", _D12_2), ("l_tax", _D12_2),
                  ("l_returnflag", T.VarcharType(1)),
                  ("l_linestatus", T.VarcharType(1)), ("l_shipdate", T.DATE),
                  ("l_commitdate", T.DATE), ("l_receiptdate", T.DATE),
                  ("l_shipinstruct", T.VarcharType(25)),
                  ("l_shipmode", T.VarcharType(10)),
                  ("l_comment", T.VarcharType(44))), None),  # ~4x orders
}

_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_NATIONS = [  # (name, regionkey) per TPC-H spec
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4), ("JAPAN", 2),
    ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0), ("MOZAMBIQUE", 0),
    ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3), ("SAUDI ARABIA", 4),
    ("VIETNAM", 2), ("RUSSIA", 3), ("UNITED KINGDOM", 3),
    ("UNITED STATES", 1)]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
_INSTRUCTS = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]
_CONTAINERS = [f"{a} {b}" for a in ("SM", "LG", "MED", "JUMBO", "WRAP")
               for b in ("CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN",
                         "DRUM")]
_TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
_TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
_TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
_COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished",
    "chartreuse", "chiffon", "chocolate", "coral", "cornflower", "cornsilk",
    "cream", "cyan", "dark", "deep", "dim", "dodger", "drab", "firebrick",
    "floral", "forest", "frosted", "gainsboro", "ghost", "goldenrod", "green",
    "grey", "honeydew", "hot", "indian", "ivory", "khaki", "lace", "lavender",
    "lawn", "lemon", "light", "lime", "linen", "magenta", "maroon", "medium",
    "metallic", "midnight", "mint", "misty", "moccasin", "navajo", "navy",
    "olive", "orange", "orchid", "pale", "papaya", "peach", "peru", "pink",
    "plum", "powder", "puff", "purple", "red", "rose", "rosy", "royal",
    "saddle", "salmon", "sandy", "seashell", "sienna", "sky", "slate", "smoke",
    "snow", "spring", "steel", "tan", "thistle", "tomato", "turquoise",
    "violet", "wheat", "white", "yellow"]
_WORDS = [
    "about", "above", "according", "accounts", "after", "against", "along",
    "among", "around", "asymptotes", "attainments", "bold", "braids",
    "carefully", "courts", "deposits", "dependencies", "depths", "dolphins",
    "dugouts", "engage", "escapades", "even", "excuses", "express", "final",
    "fluffily", "foxes", "furiously", "gifts", "grouches", "ideas",
    "instructions", "ironic", "packages", "pending", "pinto", "platelets",
    "quickly", "quietly", "regular", "requests", "sauternes", "sentiments",
    "silent", "sleepy", "slyly", "special", "theodolites", "unusual",
    "waters", "wishes"]

_MIN_DATE = days_from_civil(1992, 1, 1)
_MAX_ORDER_DATE = days_from_civil(1998, 8, 2)
_CURRENT_DATE = days_from_civil(1995, 6, 17)


def _comments(rng: np.ndarray, n: int, max_len: int) -> np.ndarray:
    """Deterministic word-salad comments: pool of 2048 phrases indexed by rng."""
    pool_size = min(2048, max(64, n // 4))
    pr = np.random.default_rng(12345)
    words = np.array(_WORDS)
    picks = pr.integers(0, len(words), size=(pool_size, 5))
    pool = np.array([" ".join(words[r])[:max_len] for r in picks],
                    dtype=object)
    return pool[rng % pool_size]


def _phone(rng_nation: np.ndarray, seq: np.ndarray) -> np.ndarray:
    country = rng_nation + 10
    p1 = (seq * 7919 + 13) % 900 + 100
    p2 = (seq * 104729 + 7) % 900 + 100
    p3 = (seq * 1299709 + 3) % 9000 + 1000
    return np.array([f"{c}-{a}-{b}-{d}" for c, a, b, d in
                     zip(country, p1, p2, p3)], dtype=object)


def _table_seed(table: str, sf: float) -> int:
    """Stable across processes (unlike hash(): PYTHONHASHSEED-randomized) so
    every worker generating a split sees the same data."""
    return zlib.crc32(f"{table}:{round(sf * 1000)}".encode())


def _gen_table(table: str, sf: float) -> Dict[str, np.ndarray]:
    """Generate full host arrays for one table at one scale factor."""
    rng = np.random.default_rng(_table_seed(table, sf))
    if table == "region":
        n = 5
        return {
            "r_regionkey": np.arange(n, dtype=np.int64),
            "r_name": np.array(_REGIONS, dtype=object),
            "r_comment": _comments(np.arange(n), n, 152),
        }
    if table == "nation":
        n = 25
        return {
            "n_nationkey": np.arange(n, dtype=np.int64),
            "n_name": np.array([x[0] for x in _NATIONS], dtype=object),
            "n_regionkey": np.array([x[1] for x in _NATIONS], dtype=np.int64),
            "n_comment": _comments(np.arange(n), n, 152),
        }
    if table == "supplier":
        n = max(1, int(10_000 * sf))
        seq = np.arange(n)
        nation = rng.integers(0, 25, n)
        return {
            "s_suppkey": np.arange(1, n + 1, dtype=np.int64),
            "s_name": np.array([f"Supplier#{i:09d}" for i in range(1, n + 1)],
                               dtype=object),
            "s_address": _comments(rng.integers(0, 1 << 30, n), n, 40),
            "s_nationkey": nation.astype(np.int64),
            "s_phone": _phone(nation, seq),
            "s_acctbal": rng.integers(-99999, 999999, n).astype(np.int64),
            "s_comment": _comments(rng.integers(0, 1 << 30, n), n, 101),
        }
    if table == "customer":
        n = max(1, int(150_000 * sf))
        seq = np.arange(n)
        nation = rng.integers(0, 25, n)
        return {
            "c_custkey": np.arange(1, n + 1, dtype=np.int64),
            "c_name": np.array([f"Customer#{i:09d}" for i in range(1, n + 1)],
                               dtype=object),
            "c_address": _comments(rng.integers(0, 1 << 30, n), n, 40),
            "c_nationkey": nation.astype(np.int64),
            "c_phone": _phone(nation, seq),
            "c_acctbal": rng.integers(-99999, 999999, n).astype(np.int64),
            "c_mktsegment": np.array(_SEGMENTS, dtype=object)[
                rng.integers(0, 5, n)],
            "c_comment": _comments(rng.integers(0, 1 << 30, n), n, 117),
        }
    if table == "part":
        n = max(1, int(200_000 * sf))
        c1 = rng.integers(0, len(_COLORS), n)
        c2 = rng.integers(0, len(_COLORS), n)
        colors = np.array(_COLORS)
        mfgr = rng.integers(1, 6, n)
        brand = mfgr * 10 + rng.integers(1, 6, n)
        t1 = rng.integers(0, len(_TYPE_S1), n)
        t2 = rng.integers(0, len(_TYPE_S2), n)
        t3 = rng.integers(0, len(_TYPE_S3), n)
        types_arr = np.array(
            [f"{_TYPE_S1[a]} {_TYPE_S2[b]} {_TYPE_S3[c]}"
             for a, b, c in zip(t1, t2, t3)], dtype=object)
        # retailprice formula per spec: 90000+((pk/10)%20001)+100*(pk%1000)
        pk = np.arange(1, n + 1, dtype=np.int64)
        retail = 90000 + (pk // 10) % 20001 + 100 * (pk % 1000)
        return {
            "p_partkey": pk,
            "p_name": np.array(
                [f"{colors[a]} {colors[b]}" for a, b in zip(c1, c2)],
                dtype=object),
            "p_mfgr": np.array([f"Manufacturer#{m}" for m in mfgr],
                               dtype=object),
            "p_brand": np.array([f"Brand#{b}" for b in brand], dtype=object),
            "p_type": types_arr,
            "p_size": rng.integers(1, 51, n).astype(np.int32),
            "p_container": np.array(_CONTAINERS, dtype=object)[
                rng.integers(0, len(_CONTAINERS), n)],
            "p_retailprice": retail,
            "p_comment": _comments(rng.integers(0, 1 << 30, n), n, 23),
        }
    if table == "partsupp":
        nparts = max(1, int(200_000 * sf))
        nsupp = max(1, int(10_000 * sf))
        # 4 suppliers per part, spec formula spreads across supplier space
        pk = np.repeat(np.arange(1, nparts + 1, dtype=np.int64), 4)
        i = np.tile(np.arange(4, dtype=np.int64), nparts)
        sk = (pk + i * (nsupp // 4 + (pk - 1) // nsupp)) % nsupp + 1
        n = len(pk)
        return {
            "ps_partkey": pk,
            "ps_suppkey": sk,
            "ps_availqty": rng.integers(1, 10000, n).astype(np.int32),
            "ps_supplycost": rng.integers(100, 100001, n).astype(np.int64),
            "ps_comment": _comments(rng.integers(0, 1 << 30, n), n, 199),
        }
    if table == "orders":
        n = max(1, int(1_500_000 * sf))
        ncust = max(1, int(150_000 * sf))
        # only 2/3 of customers have orders (spec: custkey % 3 != 0 ... keep
        # simple: random custkey among non-multiples of 3)
        ck = rng.integers(1, max(ncust, 2), n).astype(np.int64)
        ck = np.where(ck % 3 == 0, np.maximum((ck + 1) % (ncust + 1), 1), ck)
        odate = rng.integers(_MIN_DATE, _MAX_ORDER_DATE - 151, n).astype(
            np.int32)
        status_roll = odate + 151 < _CURRENT_DATE
        half = rng.random(n) < 0.5
        status = np.where(status_roll, "F",
                          np.where(half, "O", "P")).astype(object)
        return {
            "o_orderkey": np.arange(1, n + 1, dtype=np.int64),
            "o_custkey": ck,
            "o_orderstatus": status,
            "o_totalprice": rng.integers(85000, 55558642, n).astype(np.int64),
            "o_orderdate": odate,
            "o_orderpriority": np.array(_PRIORITIES, dtype=object)[
                rng.integers(0, 5, n)],
            "o_clerk": np.array(
                [f"Clerk#{c:09d}" for c in
                 rng.integers(1, max(2, int(1000 * sf)) + 1, n)],
                dtype=object),
            "o_shippriority": np.zeros(n, dtype=np.int32),
            "o_comment": _comments(rng.integers(0, 1 << 30, n), n, 79),
        }
    if table == "lineitem":
        orders = get_table("orders", sf)
        norders = len(orders["o_orderkey"])
        lines = rng.integers(1, 8, norders)  # 1..7 lines per order
        okey = np.repeat(orders["o_orderkey"], lines)
        odate = np.repeat(orders["o_orderdate"], lines)
        n = len(okey)
        linenumber = (np.arange(n, dtype=np.int64)
                      - np.repeat(np.cumsum(lines) - lines, lines) + 1)
        nparts = max(1, int(200_000 * sf))
        nsupp = max(1, int(10_000 * sf))
        pk = rng.integers(1, nparts + 1, n).astype(np.int64)
        i4 = rng.integers(0, 4, n).astype(np.int64)
        sk = (pk + i4 * (nsupp // 4 + (pk - 1) // nsupp)) % nsupp + 1
        qty = rng.integers(1, 51, n).astype(np.int64)
        # extendedprice = qty * retailprice-of-part (decimal(12,2) scaled)
        part_retail = 90000 + (pk // 10) % 20001 + 100 * (pk % 1000)
        eprice = qty * part_retail
        discount = rng.integers(0, 11, n).astype(np.int64)  # 0.00-0.10
        tax = rng.integers(0, 9, n).astype(np.int64)        # 0.00-0.08
        sdate = odate + rng.integers(1, 122, n)
        cdate = odate + rng.integers(30, 91, n)
        rdate = sdate + rng.integers(1, 31, n)
        returned = rdate <= _CURRENT_DATE
        rflag_roll = rng.random(n) < 0.5
        rflag = np.where(returned, np.where(rflag_roll, "R", "A"), "N").astype(
            object)
        lstatus = np.where(sdate > _CURRENT_DATE, "O", "F").astype(object)
        return {
            "l_orderkey": okey,
            "l_partkey": pk,
            "l_suppkey": sk,
            "l_linenumber": linenumber.astype(np.int32),
            "l_quantity": qty * 100,  # decimal(12,2) scaled
            "l_extendedprice": eprice,
            "l_discount": discount,
            "l_tax": tax,
            "l_returnflag": rflag,
            "l_linestatus": lstatus,
            "l_shipdate": sdate.astype(np.int32),
            "l_commitdate": cdate.astype(np.int32),
            "l_receiptdate": rdate.astype(np.int32),
            "l_shipinstruct": np.array(_INSTRUCTS, dtype=object)[
                rng.integers(0, 4, n)],
            "l_shipmode": np.array(_SHIPMODES, dtype=object)[
                rng.integers(0, 7, n)],
            "l_comment": _comments(rng.integers(0, 1 << 30, n), n, 44),
        }
    raise KeyError(table)


_TABLE_CACHE: Dict[tuple, Dict[str, np.ndarray]] = {}
_DICT_CACHE: Dict[tuple, Dictionary] = {}
_ROWCOUNT_CACHE: Dict[tuple, int] = {}


def get_table(table: str, sf: float) -> Dict[str, np.ndarray]:
    key = (table, round(sf * 1000))
    if key not in _TABLE_CACHE:
        _TABLE_CACHE[key] = _gen_table(table, sf)
    return _TABLE_CACHE[key]


def _column_type(table: str, column: str) -> T.Type:
    for name, typ in TABLES[table][0]:
        if name == column:
            return typ
    raise KeyError(column)


def table_dictionary(table: str, sf: float, column: str) -> Dictionary:
    """Shared per-(table, sf, column) dictionary so every page of a scan uses
    one pool (stable codes across splits; one trace per table)."""
    key = (table, round(sf * 1000), column)
    if key not in _DICT_CACHE:
        data = get_table(table, sf)[column]
        _DICT_CACHE[key] = Dictionary.build(data)[0]
    return _DICT_CACHE[key]


class TpchMetadata(ConnectorMetadata):
    """plugin/trino-tpch TpchMetadata.java analog."""

    def list_schemas(self) -> List[str]:
        return sorted(SCHEMAS)

    def list_tables(self, schema: Optional[str] = None) -> List[SchemaTableName]:
        schemas = [schema] if schema else sorted(SCHEMAS)
        return [SchemaTableName(s, t) for s in schemas for t in sorted(TABLES)]

    def get_table_handle(self, name: SchemaTableName) -> Optional[ConnectorTableHandle]:
        if name.schema in SCHEMAS and name.table in TABLES:
            return ConnectorTableHandle(name)
        return None

    def get_table_metadata(self, handle: ConnectorTableHandle) -> TableMetadata:
        cols = tuple(ColumnMetadata(n, t)
                     for n, t in TABLES[handle.name.table][0])
        return TableMetadata(handle.name, cols)

    def get_table_statistics(self, handle: ConnectorTableHandle) -> TableStatistics:
        sf = SCHEMAS[handle.name.schema]
        rows = float(table_row_count(handle.name.table, sf))
        cols: Dict[str, ColumnStatistics] = {}
        for name, typ in TABLES[handle.name.table][0]:
            ndv = rows if name.endswith("key") else min(rows, 1000.0)
            cols[name] = ColumnStatistics(null_fraction=0.0,
                                          distinct_count=ndv)
        return TableStatistics(rows, cols)

    def apply_filter(self, handle, constraint):
        # accept the whole domain for split pruning; engine re-applies row-wise
        merged = handle.constraint.intersect(constraint)
        return (ConnectorTableHandle(handle.name, merged, handle.limit),
                constraint)

    def apply_limit(self, handle, limit):
        if handle.limit is not None and handle.limit <= limit:
            return None
        return ConnectorTableHandle(handle.name, handle.constraint, limit)


def table_row_count(table: str, sf: float) -> int:
    if table == "region":
        return 5
    if table == "nation":
        return 25
    if table == "lineitem":
        # replay only the generator's FIRST draw (lines-per-order) — metadata
        # and split planning must not materialize the table (sf1000 = ~6B rows)
        key = ("lineitem_rows", round(sf * 1000))
        if key not in _ROWCOUNT_CACHE:
            norders = max(1, int(1_500_000 * sf))
            rng = np.random.default_rng(_table_seed("lineitem", sf))
            _ROWCOUNT_CACHE[key] = int(rng.integers(1, 8, norders).sum())
        return _ROWCOUNT_CACHE[key]
    if table == "partsupp":
        return max(1, int(200_000 * sf)) * 4
    base = TABLES[table][1]
    return max(1, int(base * sf))


class TpchSplitManager(ConnectorSplitManager):
    def get_splits(self, handle: ConnectorTableHandle,
                   target_splits: int = 1) -> List[Split]:
        sf = SCHEMAS[handle.name.schema]
        rows = table_row_count(handle.name.table, sf)
        parts = max(1, min(target_splits, math.ceil(rows / 4096)))
        return [Split(handle, p, parts, host=p) for p in range(parts)]


import collections
import os

_DEVICE_COL_CACHE: "collections.OrderedDict[tuple, Column]" = \
    collections.OrderedDict()
# LRU byte budget for staged table columns (HBM residency is finite;
# unbounded growth was flagged in round 2). Override for small chips.
_DEVICE_COL_CACHE_BYTES = int(os.environ.get(
    "TRINO_TPU_SCAN_CACHE_BYTES", 4 << 30))
_DEVICE_COL_CACHE_USED = 0


def _staged_column(table: str, sf: float, name: str, typ: T.Type,
                   off: int, hi: int, page_capacity: int) -> Column:
    """Encode + pad + stage one column slice to device, once per
    (table, sf, column, slice, capacity), LRU-evicted under a byte budget.

    The reference streams table data from storage per query; TPC-H data here
    is immutable generator output, so re-staging identical bytes to HBM on
    every execution would only re-measure PCIe. Real-table residency analog:
    Trino's memory connector / a warmed OS page cache."""
    global _DEVICE_COL_CACHE_USED
    key = (table, round(sf * 1000), name, off, hi, page_capacity)
    col = _DEVICE_COL_CACHE.get(key)
    if col is not None:
        _DEVICE_COL_CACHE.move_to_end(key)
        return col
    raw = get_table(table, sf)[name][off:hi]
    if T.is_string(typ):
        d = table_dictionary(table, sf, name)
        codes = pad_to_capacity(d.encode(raw), page_capacity, 0)
        col = Column.from_numpy(codes, typ, dictionary=d)
    else:
        arr = pad_to_capacity(np.asarray(raw, T.to_numpy_dtype(typ)),
                              page_capacity, 0)
        col = Column.from_numpy(arr, typ)
    nbytes = col.nbytes
    if nbytes > _DEVICE_COL_CACHE_BYTES:
        return col       # larger than the whole budget: never cache
    while (_DEVICE_COL_CACHE_USED + nbytes > _DEVICE_COL_CACHE_BYTES
           and _DEVICE_COL_CACHE):
        _, evicted = _DEVICE_COL_CACHE.popitem(last=False)
        _DEVICE_COL_CACHE_USED -= evicted.nbytes
    _DEVICE_COL_CACHE[key] = col
    _DEVICE_COL_CACHE_USED += nbytes
    return col


class TpchPageSource(ConnectorPageSource):
    def pages(self, split: Split, columns: Sequence[ColumnHandle],
              page_capacity: int) -> Iterator[Page]:
        handle = split.table
        table = handle.name.table
        sf = SCHEMAS[handle.name.schema]
        total = table_row_count(table, sf)
        start, end = split_range(total, split.part, split.total_parts)
        if handle.limit is not None:
            end = min(end, start + handle.limit)
        for off in range(start, end, page_capacity):
            hi = min(off + page_capacity, end)
            n = hi - off
            cols = [_staged_column(table, sf, ch.name, ch.type, off, hi,
                                   page_capacity) for ch in columns]
            yield Page(tuple(cols), n)


def create_connector() -> Connector:
    return Connector("tpch", TpchMetadata(), TpchSplitManager(),
                     TpchPageSource())
