"""Window operator: sort-partitioned, fully vectorized frame evaluation.

Reference parity: operator/window/WindowOperator.java (962) + window/
framework (rank/row_number/lead/lag/first/last/nth + aggregates over frames,
FramedWindowFunction.java, WindowPartition.java). The reference buffers a
PagesIndex, sorts it, then walks partitions row-by-row; on TPU the whole
input becomes one sorted page and every function lowers to segmented
prefix-scans / segment-reduces on the VPU:

  partition boundaries -> segment ids (cumsum of change flags)
  ROW frames  -> running prefix ops reset at segment starts
  RANGE frames -> the same, read at the current peer-group end (SQL's
                  peer-inclusive default frame)
  whole-partition frames -> segment-reduce + gather back

Supported frames: UNBOUNDED PRECEDING .. CURRENT ROW (ROWS and RANGE),
UNBOUNDED PRECEDING .. UNBOUNDED FOLLOWING, and bounded ROWS frames with
literal offsets (<k> PRECEDING/FOLLOWING): sum/avg/count evaluate as
prefix-sum differences, min/max via segmented pow-2 doubling tables, and
value functions index directly into the [lo, hi] range. RANGE frames with
value offsets and GROUPS frames raise at lowering.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Sequence, Tuple

import jax
import jax.numpy as jnp

from trino_tpu import types as T
from trino_tpu.ops.sort import SortKey, _sort_operands
from trino_tpu.page import Column, Page

RANKING = ("row_number", "rank", "dense_rank", "percent_rank", "cume_dist",
           "ntile")
VALUE = ("lead", "lag", "first_value", "last_value", "nth_value")
AGGREGATE = ("sum", "avg", "min", "max", "count")


from typing import Optional


@dataclasses.dataclass(frozen=True)
class WindowSpec:
    name: str
    arg_channels: Tuple[int, ...]
    out_type: T.Type
    frame_whole: bool    # UNBOUNDED..UNBOUNDED (or no ORDER BY)
    frame_rows: bool     # ROWS vs RANGE for the running frame
    # bounded ROWS frame: (start_off, end_off) row offsets relative to the
    # current row (negative = PRECEDING); None inside the tuple = unbounded
    # on that side. None overall = use frame_whole/frame_rows.
    bounds: Optional[Tuple[Optional[int], Optional[int]]] = None


def window(partition_channels: Sequence[int],
           order_keys: Sequence[SortKey],
           specs: Sequence[WindowSpec]
           ) -> Callable[[Page], Page]:
    """op(page) -> page sorted by (partition, order) with one appended
    column per spec. Consumers see rows grouped by partition; SQL row order
    is otherwise unspecified."""
    partition_channels = tuple(partition_channels)
    order_keys = tuple(order_keys)
    specs = tuple(specs)
    sort_keys = tuple(SortKey(c) for c in partition_channels) + order_keys

    def op(page: Page) -> Page:
        n = page.capacity
        idx = jnp.arange(n, dtype=jnp.int64)
        if sort_keys:
            operands = _sort_operands(page, sort_keys)
            out = jax.lax.sort(
                operands + [jnp.arange(n, dtype=jnp.int32)],
                num_keys=len(operands) + 1)
            page = page.gather(out[-1], page.num_rows)
        live = page.row_mask()

        def change_flags(channels) -> jnp.ndarray:
            """True where any listed column differs from the previous row."""
            flag = jnp.zeros(n, dtype=jnp.bool_).at[0].set(True)
            for ch in channels:
                col = page.column(ch)
                prev = jnp.roll(col.values, 1)
                differ = col.values != prev
                if col.valid is not None:
                    pv = jnp.roll(col.valid, 1)
                    differ = (differ & col.valid & pv) | (col.valid != pv)
                flag = flag | differ
            # dead rows (sorted last) start their own segment so their
            # contributions never bleed into a live partition
            dead = ~live
            flag = flag | (dead != jnp.roll(dead, 1))
            return flag.at[0].set(True)

        seg_b = change_flags(partition_channels)
        seg_start = jax.lax.cummax(jnp.where(seg_b, idx, 0))
        seg_id = (jnp.cumsum(seg_b) - 1).astype(jnp.int32)
        seg_len = jnp.zeros(n, dtype=jnp.int64).at[seg_id].add(
            jnp.where(live, 1, 0))[seg_id]
        rn0 = idx - seg_start                      # 0-based row number

        if order_keys:
            peer_b = seg_b | change_flags(
                tuple(k.channel for k in order_keys))
        else:
            peer_b = seg_b                          # all rows are peers
        peer_start = jax.lax.cummax(jnp.where(peer_b, idx, 0))
        peer_id = (jnp.cumsum(peer_b) - 1).astype(jnp.int32)
        peer_len = jnp.zeros(n, dtype=jnp.int64).at[peer_id].add(
            jnp.where(live, 1, 0))[peer_id]
        peer_end0 = peer_start - seg_start + peer_len  # rel end (exclusive)

        cols = list(page.columns)
        for spec in specs:
            cols.append(_eval(spec, page, live, idx, seg_b, seg_id,
                              seg_start, seg_len, rn0, peer_b, peer_start,
                              peer_end0))
        return Page(tuple(cols), page.num_rows)

    return op


def _eval(spec: WindowSpec, page: Page, live, idx, seg_b, seg_id, seg_start,
          seg_len, rn0, peer_b, peer_start, peer_end0) -> Column:
    name = spec.name
    n = page.capacity
    dtype = spec.out_type.dtype

    def arg(i: int) -> Column:
        return page.column(spec.arg_channels[i])

    if name == "row_number":
        return Column((rn0 + 1).astype(dtype), None, spec.out_type, None)
    if name == "rank":
        return Column((peer_start - seg_start + 1).astype(dtype), None,
                      spec.out_type, None)
    if name == "dense_rank":
        pb_cum = jnp.cumsum(peer_b)
        dense = pb_cum - jnp.take(pb_cum, seg_start, mode="clip") + 1
        return Column(dense.astype(dtype), None, spec.out_type, None)
    if name == "percent_rank":
        rank = (peer_start - seg_start).astype(jnp.float64)
        denom = jnp.maximum(seg_len - 1, 1).astype(jnp.float64)
        pr = jnp.where(seg_len <= 1, 0.0, rank / denom)
        return Column(pr, None, spec.out_type, None)
    if name == "cume_dist":
        cd = peer_end0.astype(jnp.float64) / \
            jnp.maximum(seg_len, 1).astype(jnp.float64)
        return Column(cd, None, spec.out_type, None)
    if name == "ntile":
        k = jnp.maximum(arg(0).values.astype(jnp.int64), 1)
        base = seg_len // k
        rem = seg_len % k
        cut = rem * (base + 1)
        tile = jnp.where(
            rn0 < cut,
            rn0 // jnp.maximum(base + 1, 1),
            rem + (rn0 - cut) // jnp.maximum(base, 1))
        return Column((tile + 1).astype(dtype), None, spec.out_type, None)

    if name in ("lead", "lag"):
        x = arg(0)
        off = arg(1).values.astype(jnp.int64) if len(spec.arg_channels) > 1 \
            else jnp.ones(n, dtype=jnp.int64)
        tgt = idx + off if name == "lead" else idx - off
        in_seg = (tgt >= seg_start) & (tgt < seg_start + seg_len) & live
        tgt_c = jnp.clip(tgt, 0, n - 1)
        vals = jnp.take(x.values, tgt_c)
        valid = in_seg
        if x.valid is not None:
            valid = valid & jnp.take(x.valid, tgt_c)
        out_dict = x.dictionary
        if len(spec.arg_channels) > 2:       # explicit default
            dflt = arg(2)
            dvals = dflt.values
            if x.dictionary != dflt.dictionary:
                # dictionary-encoded arg with a differently-encoded default
                # (e.g. literal singleton pool): re-encode both onto a shared
                # union pool at trace time (dictionaries are static aux data)
                if x.dictionary is None or dflt.dictionary is None:
                    raise NotImplementedError(
                        "lead/lag mixes dictionary and non-dictionary "
                        "operands")
                from trino_tpu.page import union_dictionaries
                out_dict, (rx, rd) = union_dictionaries(
                    [x.dictionary, dflt.dictionary])
                vals = jnp.take(rx, jnp.clip(vals, 0), mode="clip")
                dvals = jnp.take(rd, jnp.clip(dvals, 0), mode="clip")
            vals = jnp.where(in_seg, vals, dvals)
            valid = jnp.where(in_seg, valid,
                              dflt.valid if dflt.valid is not None
                              else jnp.ones(n, jnp.bool_))
        return Column(vals, valid, spec.out_type, out_dict)

    if name in ("first_value", "last_value", "nth_value"):
        x = arg(0)
        if spec.bounds is not None:
            lo, hi, nonempty = _bounded_range(spec, idx, seg_start, seg_len,
                                              live)
            if name == "first_value":
                tgt = lo
            elif name == "last_value":
                tgt = hi
            else:
                nth = arg(1).values.astype(jnp.int64)
                tgt = lo + nth - 1
                # lower guard: literal n <= 0 is rejected at planning
                # (Trino INVALID_FUNCTION_ARGUMENT); a dynamic n <= 0
                # yields NULL here rather than reading before the frame
                # (potentially the previous partition)
                nonempty = nonempty & (tgt <= hi) & (tgt >= lo)
            in_frame = nonempty
        else:
            if name == "first_value":
                tgt = seg_start
            elif name == "last_value":
                if spec.frame_whole:
                    tgt = seg_start + seg_len - 1
                elif spec.frame_rows:
                    tgt = idx                   # frame ends at current row
                else:
                    tgt = seg_start + peer_end0 - 1  # peer-incl. RANGE
            else:
                nth = arg(1).values.astype(jnp.int64)
                tgt = seg_start + nth - 1
            frame_end = seg_start + seg_len if spec.frame_whole else (
                idx + 1 if spec.frame_rows else seg_start + peer_end0)
            in_frame = (tgt >= seg_start) & (tgt < frame_end)
        tgt_c = jnp.clip(tgt, 0, n - 1)
        vals = jnp.take(x.values, tgt_c)
        valid = in_frame
        if x.valid is not None:
            valid = valid & jnp.take(x.valid, tgt_c)
        return Column(vals, valid, spec.out_type, x.dictionary)

    if name in AGGREGATE:
        return _eval_aggregate(spec, page, live, idx, seg_b, seg_id,
                               seg_start, seg_len, peer_start, peer_end0)
    raise NotImplementedError(f"window function {name}")


def _bounded_range(spec, idx, seg_start, seg_len, live):
    """[lo, hi] absolute row positions of a bounded ROWS frame, clipped to
    the partition (FramedWindowFunction's frame computation, vectorized)."""
    bs, be = spec.bounds
    seg_end = seg_start + seg_len - 1
    lo = seg_start if bs is None else jnp.maximum(idx + bs, seg_start)
    hi = seg_end if be is None else jnp.minimum(idx + be, seg_end)
    return lo, hi, (hi >= lo) & live


def _segmented_scan(values: jnp.ndarray, boundaries: jnp.ndarray, combine):
    """Inclusive segmented prefix scan: `combine` applied within segments,
    restarting wherever boundaries is True (classic flag-value trick)."""
    def op(a, b):
        fa, va = a
        fb, vb = b
        return fa | fb, jnp.where(fb, vb, combine(va, vb))
    _, out = jax.lax.associative_scan(op, (boundaries, values))
    return out


def _bounded_counts(cnt_contrib, seg_b, seg_start, lo, hi, nonempty, n):
    """Frame row count via prefix-sum difference (shared by every bounded
    aggregate's validity bit)."""
    prefc = _segmented_scan(cnt_contrib, seg_b, jnp.add)
    c_hi = jnp.take(prefc, jnp.clip(hi, 0, n - 1))
    c_lo = jnp.where(lo > seg_start,
                     jnp.take(prefc, jnp.clip(lo - 1, 0, n - 1)), 0)
    return jnp.where(nonempty, c_hi - c_lo, 0)


def _eval_aggregate(spec, page, live, idx, seg_b, seg_id, seg_start,
                    seg_len, peer_start, peer_end0) -> Column:
    name = spec.name
    n = page.capacity
    counting = name == "count"
    if spec.arg_channels:
        x = page.column(spec.arg_channels[0])
        xvalid = live & (x.valid if x.valid is not None
                         else jnp.ones(n, jnp.bool_))
        xv = x.values
    else:                                   # count(*)
        xvalid = live
        xv = jnp.ones(n, dtype=jnp.int64)

    if name in ("sum", "avg", "count"):
        acc_dtype = jnp.float64 if jnp.issubdtype(xv.dtype, jnp.floating) \
            else jnp.int64
        contrib = jnp.where(xvalid, xv, 0).astype(acc_dtype)
        cnt_contrib = jnp.where(xvalid, 1, 0).astype(jnp.int64)
        if spec.bounds is not None:
            # bounded ROWS frame: prefix-sum difference pref[hi]-pref[lo-1]
            lo, hi, nonempty = _bounded_range(spec, idx, seg_start, seg_len,
                                              live)
            pref = _segmented_scan(contrib, seg_b, jnp.add)
            s_hi = jnp.take(pref, jnp.clip(hi, 0, n - 1))
            s_lo = jnp.where(lo > seg_start,
                             jnp.take(pref, jnp.clip(lo - 1, 0, n - 1)), 0)
            sums = jnp.where(nonempty, s_hi - s_lo, 0)
            cnts = _bounded_counts(cnt_contrib, seg_b, seg_start, lo, hi,
                                   nonempty, n)
        elif spec.frame_whole:
            sums = jnp.zeros(n, dtype=acc_dtype).at[seg_id].add(
                contrib)[seg_id]
            cnts = jnp.zeros(n, dtype=jnp.int64).at[seg_id].add(
                cnt_contrib)[seg_id]
        else:
            run_s = _segmented_scan(contrib, seg_b, jnp.add)
            run_c = _segmented_scan(cnt_contrib, seg_b, jnp.add)
            if spec.frame_rows:
                sums, cnts = run_s, run_c
            else:   # RANGE: all peers share the frame ending at peer end
                at = jnp.clip(seg_start + peer_end0 - 1, 0, n - 1)
                sums = jnp.take(run_s, at)
                cnts = jnp.take(run_c, at)
        if counting:
            return Column(cnts.astype(spec.out_type.dtype), None,
                          spec.out_type, None)
        if name == "avg":
            if jnp.issubdtype(spec.out_type.dtype, jnp.floating):
                vals = sums / jnp.maximum(cnts, 1)
            else:
                # decimal average: round half up at the result scale
                c = jnp.maximum(cnts, 1)
                q = jnp.sign(sums) * ((jnp.abs(sums) + c // 2) // c)
                vals = q.astype(spec.out_type.dtype)
            return Column(vals.astype(spec.out_type.dtype), cnts > 0,
                          spec.out_type, None)
        return Column(sums.astype(spec.out_type.dtype), cnts > 0,
                      spec.out_type, None)

    # min / max
    is_float = jnp.issubdtype(xv.dtype, jnp.floating)
    if is_float:
        neutral = jnp.array(jnp.inf if name == "min" else -jnp.inf,
                            dtype=xv.dtype)
    else:
        info = jnp.iinfo(xv.dtype)
        neutral = jnp.array(info.max if name == "min" else info.min,
                            dtype=xv.dtype)
    contrib = jnp.where(xvalid, xv, neutral)
    combine = jnp.minimum if name == "min" else jnp.maximum
    cnt_contrib = jnp.where(xvalid, 1, 0).astype(jnp.int64)
    if spec.bounds is not None:
        res, cnts = _bounded_minmax(spec, contrib, cnt_contrib, combine,
                                    neutral, idx, seg_b, seg_id, seg_start,
                                    seg_len, live, n)
    elif spec.frame_whole:
        init = jnp.full(n, neutral)
        res = (init.at[seg_id].min(contrib) if name == "min"
               else init.at[seg_id].max(contrib))[seg_id]
        cnts = jnp.zeros(n, dtype=jnp.int64).at[seg_id].add(
            cnt_contrib)[seg_id]
    else:
        run = _segmented_scan(contrib, seg_b, combine)
        run_c = _segmented_scan(cnt_contrib, seg_b, jnp.add)
        if spec.frame_rows:
            res, cnts = run, run_c
        else:
            at = jnp.clip(seg_start + peer_end0 - 1, 0, n - 1)
            res = jnp.take(run, at)
            cnts = jnp.take(run_c, at)
    dictionary = page.column(spec.arg_channels[0]).dictionary \
        if spec.arg_channels else None
    return Column(res, cnts > 0, spec.out_type, dictionary)


def _bounded_minmax(spec, contrib, cnt_contrib, combine, neutral, idx,
                    seg_b, seg_id, seg_start, seg_len, live, n):
    """min/max over a bounded ROWS frame.

    Prefix differences don't invert min/max, so:
      - unbounded-start frames read the running segmented scan at hi;
      - unbounded-end frames read a reversed running scan at lo;
      - two-sided frames use segmented power-of-two doubling (sparse-table
        style): level k holds min over [i, i+2^k) ∩ segment, and any window
        of length ≤ 2^(k+1) is covered by two overlapping level-k reads.
        Levels are static (frame offsets are literals), so the whole thing
        stays one fused XLA program.
    """
    bs, be = spec.bounds
    lo, hi, nonempty = _bounded_range(spec, idx, seg_start, seg_len, live)
    lo_c = jnp.clip(lo, 0, n - 1)
    hi_c = jnp.clip(hi, 0, n - 1)
    if bs is None:
        run = _segmented_scan(contrib, seg_b, combine)
        res = jnp.take(run, hi_c)
    elif be is None:
        # suffix scan: reverse, with boundaries at original segment ENDS
        end_flags = jnp.roll(seg_b, -1).at[-1].set(True)
        run_r = _segmented_scan(jnp.flip(contrib, 0), jnp.flip(end_flags, 0),
                                combine)
        res = jnp.take(jnp.flip(run_r, 0), lo_c)
    else:
        window_len = be - bs + 1
        k_max = max(window_len.bit_length() - 1, 0)
        levels = [contrib]
        step = 1
        for _ in range(k_max):
            prev = levels[-1]
            ahead = jnp.clip(idx + step, 0, n - 1).astype(jnp.int32)
            same = ((idx + step) < n) & \
                (jnp.take(seg_id, ahead) == seg_id)
            levels.append(combine(prev, jnp.where(
                same, jnp.take(prev, ahead), neutral)))
            step *= 2
        flat = jnp.stack(levels).reshape(-1)
        length = jnp.maximum(hi - lo + 1, 1)
        k = jnp.zeros(n, dtype=jnp.int64)
        for j in range(1, k_max + 1):
            k = k + (length >= (1 << j)).astype(jnp.int64)
        shift = jnp.left_shift(jnp.int64(1), k)
        p2 = jnp.clip(hi - shift + 1, 0, n - 1)
        res = combine(jnp.take(flat, k * n + lo_c),
                      jnp.take(flat, k * n + p2))
    res = jnp.where(nonempty, res, neutral)
    cnts = _bounded_counts(cnt_contrib, seg_b, seg_start, lo, hi, nonempty,
                           n)
    return res, cnts
