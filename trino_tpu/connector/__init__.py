"""Connector SPI: the contract every data source implements.

Reference parity: core/trino-spi/src/main/java/io/trino/spi/connector/
(ConnectorMetadata.java:50, ConnectorSplitManager, ConnectorPageSource.java:24,
ConnectorPageSink, Plugin.java:33). Same shape in Python: metadata resolution,
split generation, page sources yielding host/device columnar Pages, pushdown
negotiation via TupleDomain (applyFilter:907) and limit (applyLimit:888).
"""

from trino_tpu.connector.spi import (  # noqa: F401
    CatalogManager, ColumnHandle, ColumnMetadata, Connector, ConnectorMetadata,
    ConnectorPageSink, ConnectorPageSource, ConnectorSplitManager,
    ConnectorTableHandle, SchemaTableName, Split, TableMetadata,
    TableStatistics, ColumnStatistics)
