"""EventListener registry: query lifecycle events with stats payloads.

Reference parity: core/trino-spi eventlistener/ — EventListener.java's
queryCreated/queryCompleted SPI, dispatched by QueryMonitor.java at
state-machine transitions, with the loaded listeners configured through
EventListenerManager. Here listeners register in-process; the query
tracker (exec/query_tracker.py) fires `query_created` when a query
registers, `query_completed` when it FINISHes, and `query_failed` when
it FAILs or is CANCELED, each carrying the query's final stats snapshot
and trace dump when the runner recorded them.

Metric side-effects are NOT a listener: the fire_* functions update the
process metrics registry unconditionally, so unregistering every
listener cannot silence /v1/metrics. Listener exceptions are swallowed —
a broken plugin must not fail queries (the reference wraps every
listener call the same way) — but never silently: each failure counts on
`trino_tpu_listener_errors_total{listener=...}` and the FIRST failure
per listener type logs the full traceback (one line of log noise per
broken plugin, not one per query).

The query-history ring (obs/history.py) is itself a listener on this
bus; the fire path imports it lazily so the ring is armed the moment any
query completes, without a module-level cycle.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Dict, List, Optional

log = logging.getLogger("trino_tpu.obs")


@dataclasses.dataclass
class QueryEvent:
    """The payload all three events share (QueryCreatedEvent /
    QueryCompletedEvent collapse onto one shape: a created event simply
    has no terminal fields yet)."""

    query_id: str
    state: str
    user: str
    query: str
    wall_ms: Optional[int] = None
    cpu_time_ms: int = 0
    rows: int = 0
    output_bytes: int = 0
    retries: int = 0
    faults_injected: int = 0
    resource_group: Optional[str] = None
    peak_memory_bytes: int = 0
    error: Optional[str] = None
    error_name: Optional[str] = None
    stats: Optional[Dict[str, Any]] = None    # QueryStatsCollector.snapshot()
    trace: Optional[Dict[str, Any]] = None    # structured span dump
    trace_file: Optional[str] = None          # exported Chrome-trace path


class EventListener:
    """Base listener (EventListener.java): override any subset."""

    def query_created(self, event: QueryEvent) -> None:
        pass

    def query_completed(self, event: QueryEvent) -> None:
        pass

    def query_failed(self, event: QueryEvent) -> None:
        pass


class LoggingEventListener(EventListener):
    """The default implementation: lifecycle lines on the
    `trino_tpu.obs` logger (the reference ships an event logger the same
    way; operators replace it with their own sink)."""

    def query_created(self, event: QueryEvent) -> None:
        log.debug("query created %s user=%s", event.query_id, event.user)

    def query_completed(self, event: QueryEvent) -> None:
        log.info("query completed %s rows=%d wall_ms=%s cpu_ms=%d "
                 "bytes=%d", event.query_id, event.rows, event.wall_ms,
                 event.cpu_time_ms, event.output_bytes)

    def query_failed(self, event: QueryEvent) -> None:
        log.info("query failed %s state=%s error=%s: %s", event.query_id,
                 event.state, event.error_name, event.error)


_LOCK = threading.Lock()
_LISTENERS: List[EventListener] = [LoggingEventListener()]


def register_listener(listener: EventListener) -> EventListener:
    with _LOCK:
        if listener not in _LISTENERS:
            _LISTENERS.append(listener)
    return listener


def unregister_listener(listener: EventListener) -> None:
    with _LOCK:
        if listener in _LISTENERS:
            _LISTENERS.remove(listener)


def listeners() -> List[EventListener]:
    with _LOCK:
        return list(_LISTENERS)


def event_from_info(info) -> QueryEvent:
    """Build the payload from a QueryInfo (exec/query_tracker.py)."""
    return QueryEvent(
        query_id=info.query_id, state=info.state, user=info.user,
        query=info.query, wall_ms=info.wall_ms,
        cpu_time_ms=info.cpu_time_ms, rows=info.rows,
        output_bytes=info.output_bytes, retries=info.retries,
        faults_injected=info.faults_injected,
        resource_group=info.resource_group,
        peak_memory_bytes=info.pool_peak_bytes,
        error=info.error, error_name=info.error_name,
        stats=info.stats, trace=info.trace,
        trace_file=info.trace_file)


# listener types whose failure has already been logged (log ONCE per
# listener, count every failure — the counter is the ongoing signal)
_ERROR_LOGGED: set = set()


def _dispatch(method: str, event: QueryEvent) -> None:
    for listener in listeners():
        try:
            getattr(listener, method)(event)
        except Exception:   # noqa: BLE001 — a plugin must not fail queries
            name = type(listener).__name__
            from trino_tpu.obs import metrics as m
            m.LISTENER_ERRORS_TOTAL.inc(listener=name)
            if name not in _ERROR_LOGGED:
                _ERROR_LOGGED.add(name)
                log.exception(
                    "event listener %r failed on %s (logged once; "
                    "further failures count on "
                    "trino_tpu_listener_errors_total)", name, method)


def fire_query_created(info) -> None:
    _dispatch("query_created", event_from_info(info))


def _record_terminal_metrics(info) -> None:
    from trino_tpu.obs import metrics as m
    m.QUERIES_TOTAL.inc(state=info.state)
    m.QUERY_ROWS_TOTAL.inc(info.rows)
    m.QUERY_BYTES_TOTAL.inc(info.output_bytes)
    m.QUERY_RETRIES_TOTAL.inc(info.retries)
    m.FAULTS_INJECTED_TOTAL.inc(info.faults_injected)
    if info.stats:
        m.SPILLED_BYTES_TOTAL.inc(info.stats.get("spilled_bytes", 0))
        m.EXCHANGE_BYTES_TOTAL.inc(info.stats.get("exchange_bytes", 0))
        m.EXCHANGE_ROWS_TOTAL.inc(info.stats.get("exchange_rows", 0))
        m.EXCHANGES_TOTAL.inc(info.stats.get("exchanges_fused", 0),
                              mode="fused")
        m.EXCHANGES_TOTAL.inc(info.stats.get("exchanges_staged", 0),
                              mode="staged")
        m.SLICES_TOTAL.inc(info.stats.get("slices_executed", 0))
        m.CHECKPOINTS_TOTAL.inc(info.stats.get("checkpoints_saved", 0),
                                op="saved")
        m.CHECKPOINTS_TOTAL.inc(
            info.stats.get("checkpoints_restored", 0), op="restored")
        m.CHECKPOINT_BYTES_TOTAL.inc(
            info.stats.get("checkpoint_bytes", 0))
        preempt_ms = float(info.stats.get("preempt_latency_ms", 0) or 0)
        if preempt_ms > 0:
            m.PREEMPTIONS_TOTAL.inc()
            m.PREEMPT_LATENCY_SECONDS.observe(preempt_ms / 1000.0)
        for kind in ("agg_mode_downgrades", "agg_mode_upgrades",
                     "agg_recursions", "join_recursions",
                     "heavy_key_splits", "spill_fallbacks"):
            n = info.stats.get(kind, 0)
            if n:
                m.ADAPTIVE_EVENTS_TOTAL.inc(n, kind=kind)
        m.MXU_JOINS_TOTAL.inc(info.stats.get("mxu_joins", 0))
        m.MXU_FLOPS_TOTAL.inc(info.stats.get("mxu_flops", 0))
    if info.stats:
        m.COMPILE_SECONDS_TOTAL.inc(
            float(info.stats.get("compile_time_ms", 0) or 0) / 1000.0)
        m.DEVICE_SECONDS_TOTAL.inc(
            float(info.stats.get("device_time_ms", 0) or 0) / 1000.0)
    if info.wall_ms is not None:
        m.QUERY_WALL_SECONDS.observe(info.wall_ms / 1000.0)
        # the serving tier's SLO surface: per-resource-group latency by
        # outcome — a group's p99 regression or failure-rate spike is one
        # PromQL query away (histogram_quantile over group series)
        m.GROUP_WALL_SECONDS.observe(
            info.wall_ms / 1000.0,
            group=info.resource_group or "global", outcome=info.state)


def _ensure_history() -> None:
    """Arm the query-history ring (its listener registers on import):
    lazy so listeners.py has no module-level dependency on history.py,
    unconditional so the ring records no matter who drove the query."""
    from trino_tpu.obs import history  # noqa: F401 — import side effect


def fire_query_completed(info) -> None:
    _ensure_history()
    _record_terminal_metrics(info)
    _dispatch("query_completed", event_from_info(info))


def fire_query_failed(info) -> None:
    _ensure_history()
    _record_terminal_metrics(info)
    _dispatch("query_failed", event_from_info(info))
