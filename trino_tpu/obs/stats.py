"""Per-query stats pipeline: QueryStatsCollector + OperatorStats.

Reference parity: execution/QueryStats.java (query-level rollup: planning
vs execution wall, raw input/output, spilled bytes) +
operator/OperatorStats.java (per-operator wall time, positions, bytes,
rolled up by PlanNodeStatsSummarizer for EXPLAIN ANALYZE). The collector
is created once per query by the runner and threaded through the local
planner, the distributed scheduler, and the jit cache, so every surface —
EXPLAIN ANALYZE, system.runtime.queries, event listeners, bench.py —
reports the SAME numbers.

Two collection levels, because per-operator instrumentation is not free
on this engine. Query-level collection (phases, output rows/bytes, jit
cache hits/misses, compile walls, spill bytes) is ALWAYS on;
operator-level collection turns on per query via the
`collect_operator_stats` session property or EXPLAIN ANALYZE. Since
round 13 operator-level collection NO LONGER splits fused kernel chains:
a chain records one measured device wall per dispatch
(`block_until_ready` at chain granularity only) and obs/profiler.py
apportions it across the chain's operators by XLA cost analysis — the
instrumented query executes the SAME executables as the plain one (the
jit cache stays warm across the toggle). Blocking operators are still
timed inclusively at their output boundary; under EXPLAIN ANALYZE
`fence` additionally pins their asynchronously dispatched device work
with `block_until_ready` (the OperationTimer discipline, TPU edition).

Device-time truth: `device_time_ms` is the summed measured chain wall
(collected only when operator-level collection fences chains),
`compile_time_ms` is the summed wall of every XLA compile this query
triggered (measured at the jit cache's AOT compile sites, always on),
and host time = execution - device - compile is what
`QueryInfo.cpu_time_ms` now means.

Threading contract: one collector belongs to one query, mutated by that
query's executor thread only (distributed shards dispatch sequentially
on it); cross-thread readers consume the immutable snapshot() taken at
query end.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
from typing import Any, Dict, List, Optional, Tuple

from trino_tpu.obs.spans import Span


@dataclasses.dataclass
class OperatorStats:
    """One plan node's runtime counters (OperatorStats.java analog):
    output rows/pages/bytes + inclusive wall time; exclusive time and
    input rows derive from the child links at render/snapshot time."""

    node_id: int
    name: str
    output_rows: int = 0
    pages: int = 0
    output_bytes: int = 0
    wall_s: float = 0.0
    # measured device wall apportioned to this operator by the XLA cost
    # model (obs/profiler.py): the operator's share of its fused chain's
    # block_until_ready wall. Sums to the measured chain walls across a
    # query's operators — the device-attribution contract.
    device_s: float = 0.0
    # True when wall_s holds an EXCLUSIVE cost-model share (fused chain
    # entries, mesh program nodes) rather than the inclusive boundary
    # wall the counting wrapper measures — the renderer must not
    # subtract children from a share that never contained them
    fused: bool = False
    source_ids: Tuple[int, ...] = ()


class QueryStatsCollector:
    def __init__(self, query_id: str = "", operator_level: bool = False,
                 fence: bool = False):
        self.query_id = query_id
        self.operator_level = bool(operator_level)
        self.fence = bool(fence)
        self.root = Span(query_id or "query", kind="query")
        self._stack: List[Span] = [self.root]
        self.phases: Dict[str, float] = {}
        self.operators: Dict[int, OperatorStats] = {}
        self.output_rows = 0
        self.output_bytes = 0
        self.spilled_bytes = 0
        self.jit_hits = 0
        self.jit_misses = 0
        # device-time truth (round 13, obs/profiler.py + exec/jit_cache):
        # device_time_s sums the measured per-dispatch chain walls
        # (fenced at chain granularity under operator-level collection;
        # 0.0 when the query ran unfenced — device time then remains
        # folded into execution wall). compile_time_s sums the wall of
        # every XLA compile this query triggered, measured at the jit
        # cache's AOT compile sites with the compiled program's HLO
        # instruction count and cost-model flops/bytes alongside.
        self.device_time_s = 0.0
        self.compile_time_s = 0.0
        self.jit_compiles = 0
        self.compiled_hlo_ops = 0
        self.estimated_flops = 0.0
        self.estimated_bytes = 0.0
        # hits on a canonical key whose literal parameter values differ
        # from that key's previous call — kernel sharing that per-literal
        # keying could not have expressed
        self.jit_param_hits = 0
        # plan cache consults (exec/plan_cache.py): a hit means this
        # query skipped parse->plan->optimize and re-ran a cached plan
        self.plan_cache_hits = 0
        self.plan_cache_misses = 0
        # serving-tier caches (trino_tpu/serve/caches.py): a result-cache
        # hit answered with zero planning/compiles/execution; a
        # scan-cache hit reused staged device pages for a table scan
        self.result_cache_hits = 0
        self.result_cache_misses = 0
        self.scan_cache_hits = 0
        self.scan_cache_misses = 0
        # device-resident table cache (exec/table_cache.py): a hit
        # served a scan entirely from HBM-resident columns; and the
        # data-plane proof for it — scan_staging_bytes counts every
        # host->device byte table scans staged this query (0 on a warm
        # cached scan, the `exchanges_fused`-style counter contract)
        self.table_cache_hits = 0
        self.table_cache_misses = 0
        self.scan_staging_bytes = 0
        # lake connector pruning (connector/lake/): whole data files
        # and row groups skipped via partition values + min/max zone
        # maps evaluated against the scan's TupleDomain (static
        # pushdown and join dynamic filters alike)
        self.files_pruned = 0
        self.row_groups_pruned = 0
        # streaming delivery (trino_tpu/serve/streaming.py): chunks that
        # left through the result ring buffer. Output rows/bytes are
        # counted ONCE at the producer regardless of whether the result
        # was streamed, buffered, or served from the result cache.
        self.streamed_chunks = 0
        self.streamed_rows = 0
        self.retries = 0
        self.faults_injected = 0
        # inter-fragment exchange data plane (exec/mesh_exec.py +
        # exec/distributed.py): 'fused' exchanges ran as collectives
        # inlined in a co-scheduled mesh program; 'staged' exchanges ran
        # as standalone collectives over host-staged per-shard fragment
        # outputs (the fallback dispatch loop). Rows/bytes are live-row
        # estimates of what crossed the exchange.
        self.exchanges_fused = 0
        self.exchanges_staged = 0
        self.exchange_rows = 0
        self.exchange_bytes = 0
        # mesh shape the query executed over (0 = single-device)
        self.mesh_devices = 0
        # preemptible sliced execution (exec/sliced/): bounded-work
        # slices the query executed, operator checkpoints saved/restored
        # (restored > 0 on a retried query = the retry RESUMED instead
        # of re-running — slices re-executed < slices total), bytes
        # checkpointed, and the measured cancel-request -> unwind wall
        # when the query was preempted (0.0 = never preempted)
        self.slices_executed = 0
        self.checkpoints_saved = 0
        self.checkpoints_restored = 0
        self.checkpoint_bytes = 0
        self.preempt_latency_ms = 0.0
        # adaptive operator strategies (exec/adaptive.py + the spill
        # paths in exec/local_planner.py): partial-aggregation mode
        # transitions (full -> shrunken -> bypass and back), recursive
        # spill repartition rounds (salted re-hash of an over-budget
        # partition), heavy-hitter keys split into dedicated bounded
        # paths, and bounded chunked fallbacks at max recursion depth —
        # every strategy switch is a first-class observable event
        self.agg_mode_downgrades = 0
        self.agg_mode_upgrades = 0
        self.agg_recursions = 0
        self.join_recursions = 0
        self.heavy_key_splits = 0
        self.spill_fallbacks = 0
        # MXU join path (ops/join_mxu.py + the exec/local_planner
        # router): joins this query actually ran as density-partitioned
        # indicator matmuls on the matrix unit, and the summed
        # cost-model MACs those dispatches issued (2 flops per
        # multiply-accumulate — the same convention as the XLA
        # cost-model estimated_flops above, which additionally counts
        # the matmul flops of every mxu kernel at its compile)
        self.mxu_joins = 0
        self.mxu_flops = 0

    # ----------------------------------------------------------- spans

    @contextlib.contextmanager
    def span(self, name: str, kind: str = "internal", **attrs):
        s = Span(name, kind=kind, attrs=attrs)
        self._stack[-1].children.append(s)
        self._stack.append(s)
        try:
            yield s
        finally:
            s.finish()
            self._stack.pop()

    @contextlib.contextmanager
    def phase(self, name: str):
        """A named query phase (planning/execution): a span plus an
        accumulated wall bucket — retries re-enter the same bucket."""
        with self.span(name, kind="phase") as s:
            try:
                yield s
            finally:
                s.finish()
                self.phases[name] = self.phases.get(name, 0.0) + s.wall_s

    # ------------------------------------------------------- operators

    def register(self, node) -> OperatorStats:
        """Stats slot for a plan node (the SAME node object re-executed —
        a task retry, a shared subtree, a per-shard task — accumulates
        into one slot; a QUERY-level re-run re-plans, so the runner
        clears `operators` between attempts to keep id() keys valid)."""
        st = self.operators.get(id(node))
        if st is None:
            st = OperatorStats(
                id(node), type(node).__name__,
                source_ids=tuple(id(s) for s in node.sources))
            self.operators[id(node)] = st
        return st

    def input_rows(self, st: OperatorStats) -> int:
        return sum(self.operators[s].output_rows
                   for s in st.source_ids if s in self.operators)

    # -------------------------------------------------------- counters

    def add_output(self, rows: int, nbytes: int) -> None:
        self.output_rows += int(rows)
        self.output_bytes += int(nbytes)

    def add_spill(self, nbytes: int) -> None:
        self.spilled_bytes += int(nbytes)

    def jit_hit(self, key=None) -> None:
        self.jit_hits += 1

    def jit_miss(self, key=None) -> None:
        self.jit_misses += 1

    def jit_param_hit(self, key=None) -> None:
        self.jit_param_hits += 1

    def add_device_time(self, wall_s: float) -> None:
        """One fused chain dispatch's measured device wall (the whole
        chain fenced once); per-operator shares land on OperatorStats."""
        self.device_time_s += float(wall_s)

    def add_compile(self, wall_s: float, hlo_ops: int = 0,
                    flops: float = 0.0, nbytes: float = 0.0) -> None:
        """One XLA compile this query triggered (jit-cache AOT site)."""
        self.compile_time_s += float(wall_s)
        self.jit_compiles += 1
        self.compiled_hlo_ops += int(hlo_ops)
        self.estimated_flops += float(flops)
        self.estimated_bytes += float(nbytes)

    def plan_cache_hit(self) -> None:
        self.plan_cache_hits += 1

    def plan_cache_miss(self) -> None:
        self.plan_cache_misses += 1

    def result_cache_hit(self) -> None:
        self.result_cache_hits += 1

    def result_cache_miss(self) -> None:
        self.result_cache_misses += 1

    def scan_cache_hit(self) -> None:
        self.scan_cache_hits += 1

    def scan_cache_miss(self) -> None:
        self.scan_cache_misses += 1

    def table_cache_hit(self) -> None:
        self.table_cache_hits += 1

    def table_cache_miss(self) -> None:
        self.table_cache_misses += 1

    def add_scan_staging(self, nbytes: int) -> None:
        """Host->device bytes staged by table scans (connector pages);
        cached scans add nothing — the zero-transfer proof."""
        self.scan_staging_bytes += int(nbytes)

    def add_pruned(self, files: int = 0, row_groups: int = 0) -> None:
        self.files_pruned += int(files)
        self.row_groups_pruned += int(row_groups)

    def add_streamed(self, chunks: int, rows: int) -> None:
        self.streamed_chunks += int(chunks)
        self.streamed_rows += int(rows)

    def mxu_join(self, n: int = 1) -> None:
        """One join routed onto the matrix-unit matmul path."""
        self.mxu_joins += int(n)

    def add_mxu_flops(self, flops: int) -> None:
        """One mxu probe dispatch's cost-model MAC count."""
        self.mxu_flops += int(flops)

    def add_exchange(self, mode: str, rows: int = 0, nbytes: int = 0
                     ) -> None:
        """One inter-fragment exchange applied; mode 'fused' (collective
        inside a co-scheduled mesh program) or 'staged' (standalone
        collective over host-staged fragment outputs)."""
        if mode == "fused":
            self.exchanges_fused += 1
        else:
            self.exchanges_staged += 1
        self.exchange_rows += int(rows)
        self.exchange_bytes += int(nbytes)

    # -------------------------------------------------------- finish

    def finish(self) -> None:
        self.root.finish()

    @property
    def execution_s(self) -> float:
        return self.phases.get("execution", 0.0)

    @property
    def planning_s(self) -> float:
        return self.phases.get("planning", 0.0)

    @property
    def host_time_s(self) -> float:
        """Execution wall with measured device and compile time taken
        out: what the HOST spent scheduling, staging, and shuffling —
        the number cpu_time_ms now reports. Without fenced device
        measurement (plain queries) device_time_s is 0 and this still
        subtracts the always-measured compile walls."""
        return max(self.execution_s - self.device_time_s
                   - self.compile_time_s, 0.0)

    def operator_rows(self) -> List[Dict[str, Any]]:
        out = []
        for st in self.operators.values():
            out.append({
                "name": st.name,
                "input_rows": self.input_rows(st),
                "output_rows": st.output_rows,
                "output_bytes": st.output_bytes,
                "pages": st.pages,
                "wall_ms": round(st.wall_s * 1000, 3),
                "device_ms": round(st.device_s * 1000, 3),
            })
        return out

    def snapshot(self) -> Dict[str, Any]:
        """The immutable query-end rollup (QueryStats.java wire shape):
        what QueryInfo.stats, event payloads, and bench.py carry."""
        snap: Dict[str, Any] = {
            "query_id": self.query_id,
            "wall_s": round(self.root.wall_s, 6),
            "planning_s": round(self.planning_s, 6),
            "execution_s": round(self.execution_s, 6),
            "output_rows": self.output_rows,
            "output_bytes": self.output_bytes,
            "spilled_bytes": self.spilled_bytes,
            "jit_hits": self.jit_hits,
            "jit_misses": self.jit_misses,
            "jit_param_hits": self.jit_param_hits,
            "device_time_ms": round(self.device_time_s * 1000, 3),
            "compile_time_ms": round(self.compile_time_s * 1000, 3),
            "host_time_ms": round(self.host_time_s * 1000, 3),
            "jit_compiles": self.jit_compiles,
            "compiled_hlo_ops": self.compiled_hlo_ops,
            "estimated_flops": self.estimated_flops,
            "estimated_bytes": self.estimated_bytes,
            "plan_cache_hits": self.plan_cache_hits,
            "plan_cache_misses": self.plan_cache_misses,
            "result_cache_hits": self.result_cache_hits,
            "result_cache_misses": self.result_cache_misses,
            "scan_cache_hits": self.scan_cache_hits,
            "scan_cache_misses": self.scan_cache_misses,
            "table_cache_hits": self.table_cache_hits,
            "table_cache_misses": self.table_cache_misses,
            "scan_staging_bytes": self.scan_staging_bytes,
            "files_pruned": self.files_pruned,
            "row_groups_pruned": self.row_groups_pruned,
            "streamed_chunks": self.streamed_chunks,
            "streamed_rows": self.streamed_rows,
            "retries": self.retries,
            "faults_injected": self.faults_injected,
            "exchanges_fused": self.exchanges_fused,
            "exchanges_staged": self.exchanges_staged,
            "exchange_rows": self.exchange_rows,
            "exchange_bytes": self.exchange_bytes,
            "mesh_devices": self.mesh_devices,
            "slices_executed": self.slices_executed,
            "checkpoints_saved": self.checkpoints_saved,
            "checkpoints_restored": self.checkpoints_restored,
            "checkpoint_bytes": self.checkpoint_bytes,
            "preempt_latency_ms": self.preempt_latency_ms,
            "agg_mode_downgrades": self.agg_mode_downgrades,
            "agg_mode_upgrades": self.agg_mode_upgrades,
            "agg_recursions": self.agg_recursions,
            "join_recursions": self.join_recursions,
            "heavy_key_splits": self.heavy_key_splits,
            "spill_fallbacks": self.spill_fallbacks,
            "mxu_joins": self.mxu_joins,
            "mxu_flops": self.mxu_flops,
        }
        if self.operators:
            snap["operators"] = self.operator_rows()
        return snap

    def trace_json(self) -> Dict[str, Any]:
        """The per-query structured span dump (query -> phases ->
        fragments/exchanges), with operator spans synthesized from the
        collected OperatorStats when operator-level collection ran (a
        streaming operator has no contiguous lifetime, so its 'span' is
        its inclusive wall, parented under the query root)."""
        dump = self.root.to_json()
        if self.operators:
            origin = self.root.start_s
            ops = []
            for st in self.operators.values():
                op = Span(st.name, kind="operator", start_s=origin,
                          attrs={"output_rows": st.output_rows,
                                 "output_bytes": st.output_bytes,
                                 "pages": st.pages,
                                 "device_ms": round(st.device_s * 1000,
                                                    3)})
                op.end_s = origin + st.wall_s
                ops.append(op._to_json(origin))
            dump.setdefault("children", []).extend(ops)
        return dump


def maybe_span(collector: Optional[QueryStatsCollector], name: str,
               kind: str = "internal", **attrs):
    """Span scope that degrades to a no-op without a collector (the
    execution paths run with collector=None outside runner.execute)."""
    if collector is None:
        return contextlib.nullcontext()
    return collector.span(name, kind=kind, **attrs)


def maybe_phase(collector: Optional[QueryStatsCollector], name: str):
    if collector is None:
        return contextlib.nullcontext()
    return collector.phase(name)


def render_analyzed_plan(plan, collector: QueryStatsCollector,
                         total_rows: int, total_wall_s: float,
                         label: str = "single device") -> str:
    """EXPLAIN ANALYZE text: the executed plan annotated with each node's
    rows, bytes, and wall time (PlanPrinter.textDistributedPlan with
    operator stats). Exclusive time subtracts the children's inclusive
    walls, clamped at zero (a fused child can complete inside its
    parent's read)."""
    from trino_tpu.planner.nodes import format_plan

    def cumulative(st) -> float:
        """Inclusive wall estimate: fused slots hold an EXCLUSIVE
        cost-model share, so their subtree adds the children's
        cumulative walls; wrapper-measured slots are already
        inclusive."""
        if not st.fused:
            return st.wall_s
        return st.wall_s + sum(
            cumulative(collector.operators[s]) for s in st.source_ids
            if s in collector.operators)

    def annotate(node):
        st = collector.operators.get(id(node))
        if st is None:
            return ""
        if st.fused:
            # the share IS this operator's own time (exclusive by
            # construction — subtracting inclusive children from it
            # would clamp every fused operator to 0.00ms)
            own = st.wall_s
        else:
            child_wall = sum(collector.operators[s].wall_s
                             for s in st.source_ids
                             if s in collector.operators)
            own = max(st.wall_s - child_wall, 0.0)
        text = (f"output: {st.output_rows} rows ({st.pages} pages, "
                f"{_fmt_bytes(st.output_bytes)}), "
                f"time: {own * 1000:.2f}ms "
                f"({cumulative(st) * 1000:.2f}ms cumulative)")
        if st.device_s > 0:
            text += f", device: {st.device_s * 1000:.2f}ms"
        return text

    text = format_plan(plan, annotate=annotate)
    text += (f"\n\nQuery: {total_rows} rows, "
             f"wall {total_wall_s * 1000:.2f}ms ({label}), "
             f"planning {collector.planning_s * 1000:.2f}ms, "
             f"device {collector.device_time_s * 1000:.2f}ms / "
             f"compile {collector.compile_time_s * 1000:.2f}ms / "
             f"host {collector.host_time_s * 1000:.2f}ms, "
             f"jit {collector.jit_hits} hits / "
             f"{collector.jit_misses} misses / "
             f"{collector.jit_param_hits} param hits / "
             f"{collector.jit_compiles} compiles, "
             f"plan cache {collector.plan_cache_hits} hits / "
             f"{collector.plan_cache_misses} misses")
    if collector.spilled_bytes:
        text += f", spilled {_fmt_bytes(collector.spilled_bytes)}"
    if collector.mxu_joins:
        text += (f"\nmxu: {collector.mxu_joins} matmul joins, "
                 f"{collector.mxu_flops:.3g} probe flops")
    if (collector.agg_mode_downgrades or collector.agg_mode_upgrades
            or collector.agg_recursions or collector.join_recursions
            or collector.heavy_key_splits or collector.spill_fallbacks):
        text += (f"\nadaptive: {collector.agg_mode_downgrades} agg "
                 f"downgrades / {collector.agg_mode_upgrades} upgrades, "
                 f"{collector.agg_recursions} agg + "
                 f"{collector.join_recursions} join spill recursions, "
                 f"{collector.heavy_key_splits} heavy-key splits, "
                 f"{collector.spill_fallbacks} chunked fallbacks")
    return text


def _fmt_bytes(n: int) -> str:
    from trino_tpu.exec.memory import _fmt_bytes as fmt
    return fmt(int(n))
