"""Expression typing, coercion, and function resolution rules.

Reference parity: core/trino-main sql/analyzer/ExpressionAnalyzer.java (2,795
LoC) + TypeCoercion.java + metadata/FunctionRegistry.java:372. The planner
calls into these rules while translating AST expressions; keeping them here
mirrors the reference's analyzer/planner split without the Analysis side-table
machinery (we type during translation instead).

Decimal result types follow Trino's DecimalOperators:
  add/sub:  scale max(s1,s2), precision max(p1-s1,p2-s2)+scale+1
  multiply: precision p1+p2, scale s1+s2
  divide:   precision p1+s2+max(0,s2-s1), scale max(s1,s2)
(precision clamps to 18 — short-decimal int64 path, types.DecimalType).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

from trino_tpu import types as T
from trino_tpu.errors import GENERIC_USER_ERROR, TrinoError


class SemanticError(TrinoError):
    """Analysis-time user error: never retryable (re-running the same
    statement re-fails the same way — the FTE non-retryable class)."""

    CODE = GENERIC_USER_ERROR


@dataclasses.dataclass(frozen=True)
class ResolvedFunction:
    """Outcome of function resolution: registry name + types."""

    name: str                      # canonical registry/compiler name
    arg_types: Tuple[T.Type, ...]  # post-coercion argument types
    return_type: T.Type


AGGREGATE_NAMES = frozenset({
    "count", "sum", "avg", "min", "max", "count_if", "bool_and", "bool_or",
    "every", "arbitrary", "any_value", "stddev", "stddev_pop", "stddev_samp",
    "variance", "var_pop", "var_samp", "approx_distinct", "corr", "covar_pop",
    "covar_samp", "regr_slope", "regr_intercept", "checksum", "geometric_mean",
    "min_by", "max_by", "approx_percentile", "array_agg", "histogram",
    "map_agg",
})

WINDOW_NAMES = frozenset({
    "row_number", "rank", "dense_rank", "percent_rank", "cume_dist", "ntile",
    "lag", "lead", "first_value", "last_value", "nth_value",
})


def is_aggregate(name: str) -> bool:
    return name.lower() in AGGREGATE_NAMES


def is_window(name: str) -> bool:
    return name.lower() in WINDOW_NAMES


# --------------------------------------------------------------- coercion

def can_coerce(src: T.Type, dst: T.Type) -> bool:
    """Implicit coercion lattice (TypeCoercion.canCoerce)."""
    if src == dst:
        return True
    if isinstance(src, T.UnknownType):
        return True
    order = (T.TinyintType, T.SmallintType, T.IntegerType, T.BigintType)
    if isinstance(src, order) and isinstance(dst, order):
        return order.index(type(src)) <= order.index(type(dst))
    if isinstance(src, order) and isinstance(dst, (T.DoubleType, T.RealType,
                                                   T.DecimalType)):
        return True
    if isinstance(src, T.DecimalType):
        if isinstance(dst, T.DoubleType) or isinstance(dst, T.RealType):
            return True
        if isinstance(dst, T.DecimalType):
            return (dst.scale >= src.scale and
                    dst.precision - dst.scale >= src.precision - src.scale)
        return False
    if isinstance(src, T.RealType) and isinstance(dst, T.DoubleType):
        return True
    if isinstance(src, (T.VarcharType, T.CharType)) and isinstance(
            dst, (T.VarcharType, T.CharType)):
        return True
    if isinstance(src, T.DateType) and isinstance(dst, T.TimestampType):
        return True
    return False


def common_type(a: T.Type, b: T.Type) -> Optional[T.Type]:
    """Least common supertype for comparisons/CASE/set-ops
    (TypeCoercion.getCommonSuperType)."""
    if a == b:
        return a
    if isinstance(a, T.UnknownType):
        return b
    if isinstance(b, T.UnknownType):
        return a
    if isinstance(a, T.DecimalType) and isinstance(b, T.DecimalType):
        scale = max(a.scale, b.scale)
        whole = max(a.precision - a.scale, b.precision - b.scale)
        return T.DecimalType(min(whole + scale, 18), scale)
    ints = (T.TinyintType, T.SmallintType, T.IntegerType, T.BigintType)
    if isinstance(a, ints) and isinstance(b, T.DecimalType):
        return common_type(_int_as_decimal(a), b)
    if isinstance(b, ints) and isinstance(a, T.DecimalType):
        return common_type(a, _int_as_decimal(b))
    if can_coerce(a, b):
        return b
    if can_coerce(b, a):
        return a
    # numeric tower fallback: anything numeric with double/real -> double
    if T.is_numeric(a) and T.is_numeric(b):
        return T.DOUBLE
    return None


def _int_as_decimal(t: T.Type) -> T.DecimalType:
    digits = {T.TinyintType: 3, T.SmallintType: 5, T.IntegerType: 10,
              T.BigintType: 18}[type(t)]
    return T.DecimalType(digits, 0)


# ------------------------------------------------- arithmetic result types

def arithmetic_type(op: str, a: T.Type, b: T.Type) -> T.Type:
    """+ - * / % result type (DecimalOperators / BigintOperators)."""
    if isinstance(a, (T.DoubleType,)) or isinstance(b, (T.DoubleType,)):
        return T.DOUBLE
    if isinstance(a, T.RealType) or isinstance(b, T.RealType):
        return T.REAL
    ints = (T.TinyintType, T.SmallintType, T.IntegerType, T.BigintType)
    if isinstance(a, ints) and isinstance(b, ints):
        order = [T.TinyintType, T.SmallintType, T.IntegerType, T.BigintType]
        win = max(order.index(type(a)), order.index(type(b)))
        # integer arithmetic stays integer; div is integer division
        return (T.TINYINT, T.SMALLINT, T.INTEGER, T.BIGINT)[win]
    da = a if isinstance(a, T.DecimalType) else (
        _int_as_decimal(a) if isinstance(a, ints) else None)
    db = b if isinstance(b, T.DecimalType) else (
        _int_as_decimal(b) if isinstance(b, ints) else None)
    if da is None or db is None:
        raise SemanticError(
            f"cannot apply operator {op} to {a.display()}, {b.display()}")
    p1, s1, p2, s2 = da.precision, da.scale, db.precision, db.scale
    if op in ("+", "-"):
        scale = max(s1, s2)
        precision = max(p1 - s1, p2 - s2) + scale + 1
    elif op == "*":
        precision, scale = p1 + p2, s1 + s2
    elif op == "/":
        scale = max(s1, s2)
        precision = p1 + s2 + max(0, s2 - s1)
    elif op == "%":
        scale = max(s1, s2)
        precision = min(p1 - s1, p2 - s2) + scale
    else:
        raise SemanticError(f"unknown operator {op}")
    return T.DecimalType(min(precision, 18), min(scale, 18))


_ARITH_NAMES = {"+": "add", "-": "subtract", "*": "multiply", "/": "divide",
                "%": "modulus"}
_CMP_NAMES = {"=": "eq", "<>": "ne", "<": "lt", "<=": "le", ">": "gt",
              ">=": "ge"}


def arithmetic_call(op: str, a: T.Type, b: T.Type) -> ResolvedFunction:
    # date/timestamp ± interval
    if isinstance(a, T.DateType) and isinstance(
            b, (T.IntervalDayTimeType, T.IntervalYearMonthType)):
        name = ("date_add_ym" if isinstance(b, T.IntervalYearMonthType)
                else "date_add_dt")
        return ResolvedFunction(name, (a, b), a)
    if isinstance(b, T.DateType) and isinstance(
            a, (T.IntervalDayTimeType, T.IntervalYearMonthType)) and op == "+":
        name = ("date_add_ym" if isinstance(a, T.IntervalYearMonthType)
                else "date_add_dt")
        return ResolvedFunction(name, (b, a), b)
    out = arithmetic_type(op, a, b)
    # operands coerce to a common computation type; decimal ops rescale inside
    return ResolvedFunction(_ARITH_NAMES[op], (a, b), out)


def comparison_call(op: str, a: T.Type, b: T.Type
                    ) -> Tuple[ResolvedFunction, T.Type]:
    """Comparison: (resolved fn, operand coercion target)."""
    ct = common_type(a, b)
    if ct is None:
        raise SemanticError(
            f"cannot compare {a.display()} with {b.display()}")
    base = _CMP_NAMES.get(op)
    if base is None:
        raise SemanticError(f"unsupported comparison {op}")
    return ResolvedFunction(base, (ct, ct), T.BOOLEAN), ct


# ------------------------------------------------------ scalar signatures

def resolve_scalar(name: str, arg_types: Sequence[T.Type]) -> ResolvedFunction:
    """FunctionRegistry.resolveFunction analog for scalar calls."""
    n = name.lower()
    args = tuple(arg_types)

    def sig(out, coerced=None):
        return ResolvedFunction(n, tuple(coerced or args), out)

    if n in ("abs", "ceil", "ceiling", "floor", "negate"):
        if not args or not T.is_numeric(args[0]):
            raise SemanticError(f"{n}() requires a numeric argument")
        canonical = "ceil" if n == "ceiling" else n
        out = args[0]
        if n in ("ceil", "ceiling", "floor") and isinstance(
                args[0], T.DecimalType):
            out = T.DecimalType(args[0].precision - args[0].scale + 1, 0)
        return ResolvedFunction(canonical, args, out)
    if n == "round":
        if len(args) == 1:
            out = args[0]
            if isinstance(args[0], T.DecimalType):
                out = T.DecimalType(args[0].precision - args[0].scale + 1, 0)
            return ResolvedFunction("round", args, out)
        return ResolvedFunction("round_digits", args, args[0])
    if n == "truncate":
        if len(args) == 2:
            return ResolvedFunction("truncate", (T.DOUBLE, T.BIGINT),
                                    T.DOUBLE)
        return ResolvedFunction("truncate", (T.DOUBLE,), T.DOUBLE)
    if n in ("sqrt", "exp", "ln", "log10", "log2", "power", "pow", "cbrt",
             "sin", "cos", "tan", "asin", "acos", "atan", "atan2", "sinh",
             "cosh", "tanh", "radians", "degrees", "log"):
        canonical = "power" if n == "pow" else n
        coerced = tuple(T.DOUBLE for _ in args)
        return ResolvedFunction(canonical, coerced, T.DOUBLE)
    if n in ("pi", "e"):
        if args:
            raise SemanticError(f"{n}() takes no arguments")
        return ResolvedFunction(n, (), T.DOUBLE)
    if n == "mod":
        return ResolvedFunction("modulus", args,
                                common_type(args[0], args[1]) or args[0])
    if n == "sign":
        return sig(args[0])
    if n in ("bitwise_and", "bitwise_or", "bitwise_xor",
             "bitwise_left_shift", "bitwise_right_shift",
             "bitwise_right_shift_arithmetic"):
        return ResolvedFunction(n, (T.BIGINT, T.BIGINT), T.BIGINT)
    if n == "bitwise_not":
        return ResolvedFunction(n, (T.BIGINT,), T.BIGINT)
    if n == "bit_count":
        return ResolvedFunction(n, (T.BIGINT, T.BIGINT), T.BIGINT)
    if n == "width_bucket":
        return ResolvedFunction(
            n, (T.DOUBLE, T.DOUBLE, T.DOUBLE, T.BIGINT), T.BIGINT)
    if n in ("format_datetime", "date_format"):
        if len(args) != 2 or not T.is_string(args[1]):
            raise SemanticError(f"{n}(temporal, pattern) takes a "
                                "temporal and a varchar pattern")
        return ResolvedFunction(n, args, T.VarcharType())
    if n in ("greatest", "least"):
        ct = args[0]
        for t2 in args[1:]:
            nt = common_type(ct, t2)
            if nt is None:
                raise SemanticError(f"{n}() mixed argument types")
            ct = nt
        return ResolvedFunction(n, tuple(ct for _ in args), ct)
    if n in ("year", "month", "day", "quarter", "day_of_week", "dow",
             "day_of_year", "doy", "week", "week_of_year", "day_of_month",
             "hour", "minute", "second"):
        canonical = {"dow": "day_of_week", "doy": "day_of_year",
                     "week_of_year": "week", "day_of_month": "day"}.get(n, n)
        return ResolvedFunction(canonical, args, T.BIGINT)
    if n == "date_trunc":
        return sig(args[1] if len(args) > 1 else T.DATE)
    if n == "date_diff":
        if len(args) == 3 and {type(args[1]), type(args[2])} == \
                {T.DateType, T.TimestampType}:
            # mixed operands: DATE coerces to TIMESTAMP (TypeCoercion)
            coerced = (args[0], T.TIMESTAMP, T.TIMESTAMP)
            return ResolvedFunction(n, coerced, T.BIGINT)
        return sig(T.BIGINT)
    if n == "date_add":
        return sig(args[2] if len(args) > 2 else T.DATE)
    if n == "last_day_of_month":
        return sig(T.DATE)
    if n in ("lower", "upper", "trim", "ltrim", "rtrim", "reverse"):
        return sig(args[0])
    if n in ("substr", "substring"):
        return ResolvedFunction("substr", args, args[0])
    if n in ("replace", "lpad", "rpad", "split_part", "regexp_replace",
             "regexp_extract", "concat_ws"):
        return sig(T.VarcharType())
    if n == "concat":
        return sig(args[0] if T.is_string(args[0]) else T.VarcharType())
    if n in ("length", "strpos", "codepoint"):
        return ResolvedFunction(n, args, T.BIGINT)
    if n in ("like", "regexp_like", "starts_with"):
        return sig(T.BOOLEAN)
    if n == "try_cast":
        # synthesized by the translator for TRY_CAST; target type is
        # pre-resolved there
        return sig(args[0])
    # ------------------------------------------------- array/map functions
    if n == "array_ctor":
        if not args:
            raise SemanticError("ARRAY[] needs an element type; "
                                "cast to a typed empty array")
        ct = args[0]
        for a in args[1:]:
            nt = common_type(ct, a)
            if nt is None:
                raise SemanticError("ARRAY elements have mixed types")
            ct = nt
        return ResolvedFunction("array_ctor", (ct,) * len(args),
                                T.ArrayType(element=ct))
    if n == "cardinality":
        if not isinstance(args[0], (T.ArrayType, T.MapType)):
            raise SemanticError("cardinality() needs ARRAY or MAP")
        return ResolvedFunction("cardinality", args, T.BIGINT)
    if n == "element_at":
        if isinstance(args[0], T.ArrayType):
            return ResolvedFunction("element_at", (args[0], T.BIGINT),
                                    args[0].element)
        if isinstance(args[0], T.MapType):
            return ResolvedFunction("map_element_at",
                                    (args[0], args[0].key),
                                    args[0].value)
        raise SemanticError("element_at() needs ARRAY or MAP")
    if n == "contains":
        if not isinstance(args[0], T.ArrayType):
            raise SemanticError("contains() needs an ARRAY")
        return ResolvedFunction(
            "contains", (args[0], args[0].element), T.BOOLEAN)
    raise SemanticError(f"unknown function: {name}()")


def resolve_aggregate(name: str, arg_types: Sequence[T.Type]
                      ) -> ResolvedFunction:
    """Aggregate output types (mirrors ops/aggregate.get_aggregate)."""
    n = name.lower()
    args = tuple(arg_types)
    if n == "count":
        return ResolvedFunction("count", args, T.BIGINT)
    a = args[0] if args else T.UNKNOWN
    if n == "sum":
        if isinstance(a, (T.DecimalType,)):
            return ResolvedFunction("sum", args, T.DecimalType(18, a.scale))
        if isinstance(a, T.DoubleType):
            return ResolvedFunction("sum", args, T.DOUBLE)
        if isinstance(a, T.RealType):
            return ResolvedFunction("sum", args, T.REAL)
        if T.is_integral(a):
            return ResolvedFunction("sum", args, T.BIGINT)
        raise SemanticError(f"sum() does not accept {a.display()}")
    if n == "avg":
        if isinstance(a, T.DecimalType):
            return ResolvedFunction("avg", args, a)
        if isinstance(a, T.RealType):
            return ResolvedFunction("avg", args, T.REAL)
        if T.is_numeric(a):
            return ResolvedFunction("avg", args, T.DOUBLE)
        raise SemanticError(f"avg() does not accept {a.display()}")
    if n in ("min", "max"):
        return ResolvedFunction(n, args, a)
    if n in ("count_if",):
        return ResolvedFunction("count_if", args, T.BIGINT)
    if n in ("bool_and", "bool_or", "every"):
        canonical = "bool_and" if n == "every" else n
        return ResolvedFunction(canonical, args, T.BOOLEAN)
    if n in ("stddev", "stddev_samp", "stddev_pop", "variance", "var_samp",
             "var_pop", "geometric_mean"):
        return ResolvedFunction(n, args, T.DOUBLE)
    if n in ("arbitrary", "any_value"):
        return ResolvedFunction("arbitrary", args, a)
    if n == "approx_distinct":
        return ResolvedFunction("approx_distinct", args, T.BIGINT)
    if n == "approx_percentile":
        if len(args) != 2:
            raise SemanticError(
                "approx_percentile(x, percentile) takes two arguments")
        return ResolvedFunction("approx_percentile",
                                (args[0], T.DOUBLE), args[0])
    if n == "checksum":
        return ResolvedFunction("checksum", args, T.BIGINT)
    if n in ("corr", "covar_pop", "covar_samp", "regr_slope",
             "regr_intercept"):
        return ResolvedFunction(n, tuple(T.DOUBLE for _ in args), T.DOUBLE)
    if n in ("min_by", "max_by"):
        if len(args) != 2:
            raise SemanticError(f"{n}() takes exactly two arguments")
        return ResolvedFunction(n, args, args[0])
    if n == "array_agg":
        return ResolvedFunction("array_agg", args,
                                T.ArrayType(element=a))
    if n == "histogram":
        return ResolvedFunction("histogram", args,
                                T.MapType(key=a, value=T.BIGINT))
    if n == "map_agg":
        if len(args) != 2:
            raise SemanticError("map_agg(key, value) takes two arguments")
        return ResolvedFunction("map_agg", args,
                                T.MapType(key=args[0], value=args[1]))
    raise SemanticError(f"unknown aggregate: {name}()")


# ------------------------------------------- prepared-statement parameters
#
# PREPARE stores the raw AST with `?` markers (sql/tree.Parameter, lexer-
# numbered left to right); EXECUTE ... USING binds one constant per marker.
# The checks here are the ExpressionAnalyzer.analyzeParameters slice: arity
# must match exactly, and each bound value must be a constant whose type
# the comparison/coercion rules can place in the marker's context (the
# context check itself happens during planning, where a mis-typed
# parameter fails the same way a mis-typed literal would — e.g. "cannot
# compare decimal(12,2) with varchar").


def count_parameters(stmt) -> int:
    """Number of `?` markers in a statement AST (markers are numbered
    contiguously by the lexer, so the count is max position + 1)."""
    from trino_tpu.sql import tree as t

    return 1 + max((n.position for n in t.walk(stmt)
                    if isinstance(n, t.Parameter)), default=-1)


def check_execute_arity(name: str, markers: int, provided: int) -> None:
    """EXECUTE ... USING arity: one value per marker, no extras
    (io.trino.sql.analyzer: "Incorrect number of parameters")."""
    if markers != provided:
        raise SemanticError(
            f"incorrect number of parameters for prepared statement "
            f"'{name}': expected {markers} but found {provided}")


def substitute_parameters(stmt, parameters):
    """Rebuild a statement AST with each `?` marker replaced by its bound
    value EXPRESSION — the non-cached execution path (DDL/INSERT prepared
    statements, and any runner that plans per execution). Equivalent to
    re-parsing the statement with the values spliced in."""
    import dataclasses as _dc

    from trino_tpu.sql import tree as t

    def walk(x):
        if isinstance(x, t.Parameter):
            if x.position >= len(parameters):
                raise SemanticError(
                    f"parameter ?{x.position + 1} has no bound value")
            return parameters[x.position]
        if _dc.is_dataclass(x) and isinstance(x, t.Node):
            changed = False
            fields = {}
            for f in _dc.fields(x):
                old = getattr(x, f.name)
                new = walk(old)
                fields[f.name] = new
                changed = changed or new is not old
            return _dc.replace(x, **fields) if changed else x
        if isinstance(x, tuple):
            out = tuple(walk(item) for item in x)
            return out if any(a is not b for a, b in zip(out, x)) else x
        if isinstance(x, list):
            return [walk(item) for item in x]
        return x
    return walk(stmt)
