"""Lake connector package: file-based columnar tables behind the SPI.

`create_connector()` builds a catalog rooted at $TRINO_TPU_LAKE_DIR (or
a per-process temp directory); see connector.py for the manifest/commit
model and format.py for the parquet/npz codecs (pyarrow is strictly
optional — the .npz native format is the dependency-free fallback).
"""

from trino_tpu.connector.lake.connector import (  # noqa: F401
    LakeConnector, LakeMetadata, LakePageSink, LakePageSource,
    LakeSplitManager, create_connector, eligible_files, eligible_groups,
    lake_stats, take_scan_stats)
from trino_tpu.connector.lake.format import (  # noqa: F401
    HAVE_PYARROW, default_format)
