"""Plan-shape regression tests — BasePlanTest-style matchers.

Reference parity: sql/planner/assertions/BasePlanTest.java:49 +
PlanMatchPattern.java — assert optimizer OUTPUT SHAPE (join order, predicate
pushdown, TopN formation, exchange placement, partial/final aggregation
split) over EXPLAIN text, so optimizer changes in later rounds cannot
silently regress plan quality. The text matchers parse the plan printer's
indented tree into (depth, op, detail) rows.
"""

import re

import pytest

from trino_tpu.exec import LocalQueryRunner

from tpch_sql import PASSING, QUERIES


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch("tiny")


class PlanText:
    """Indented plan-printer output as a queryable node list."""

    LINE = re.compile(r"^(\s*)- (\w+)(\[(.*)\])?$")

    def __init__(self, text: str):
        self.text = text
        self.nodes = []                      # (depth, op, detail)
        for line in text.splitlines():
            m = self.LINE.match(line)
            if m:
                depth = len(m.group(1)) // 3
                self.nodes.append((depth, m.group(2), m.group(4) or ""))

    def ops(self):
        return [op for _, op, _ in self.nodes]

    def find(self, op, detail_substr=""):
        return [(d, o, det) for d, o, det in self.nodes
                if o == op and detail_substr in det]

    def has(self, op, detail_substr=""):
        return bool(self.find(op, detail_substr))

    def parent_of(self, op, detail_substr=""):
        """The node one level above the first match."""
        for i, (d, o, det) in enumerate(self.nodes):
            if o == op and detail_substr in det:
                for j in range(i - 1, -1, -1):
                    if self.nodes[j][0] == d - 1:
                        return self.nodes[j]
        return None

    def children_of(self, index):
        d = self.nodes[index][0]
        out = []
        for j in range(index + 1, len(self.nodes)):
            if self.nodes[j][0] <= d:
                break
            if self.nodes[j][0] == d + 1:
                out.append((j, self.nodes[j]))
        return out

    def real_cross_joins(self):
        """Cross joins EXCEPT the scalar-subquery broadcast pattern (a cross
        against EnforceSingleRow is how scalar subqueries decorrelate)."""
        out = []
        for i, (d, o, det) in enumerate(self.nodes):
            if o == "Join" and "cross" in det:
                kids = [n for _, n in self.children_of(i)]
                if not any(op == "EnforceSingleRow" for _, op, _ in kids):
                    out.append((d, o, det))
        return out


def plan(runner, sql) -> PlanText:
    """Single-tree logical plan (fragment boundaries reset indentation, so
    shape assertions use TYPE LOGICAL; distributed shape uses dplan)."""
    return PlanText(
        runner.execute("EXPLAIN (TYPE LOGICAL) " + sql).only_value())


# ------------------------------------------------------------- join order

@pytest.mark.parametrize("name", PASSING)
def test_no_cross_joins(runner, name):
    """EliminateCrossJoins / ReorderJoins: every TPC-H plan is cross-free."""
    p = plan(runner, QUERIES[name][0])
    assert not p.real_cross_joins(), \
        f"{name} has a cross join:\n{p.text}"


def test_q3_builds_topn_not_sort_limit(runner):
    p = plan(runner, QUERIES["q3"][0])
    assert p.has("TopN")
    assert not p.has("Sort"), "ORDER BY+LIMIT must fuse into TopN"


# ------------------------------------------------------ predicate pushdown

def test_filter_pushed_to_scan_q6(runner):
    p = plan(runner, QUERIES["q6"][0])
    assert not p.has("Join")
    # the only Filter sits directly above the lineitem scan
    filters = p.find("Filter")
    assert len(filters) == 1
    d, _, det = filters[0]
    assert "l_shipdate" in det or "shipdate" in det
    below = [n for n in p.nodes if n[0] == d + 1]
    assert any(op == "TableScan" and "lineitem" in detail
               for _, op, detail in below)


def test_dimension_filter_pushed_below_join(runner):
    sql = ("SELECT n_name FROM nation, region "
           "WHERE n_regionkey = r_regionkey AND r_name = 'EUROPE'")
    p = plan(runner, sql)
    # the region filter must sit under the join (build side), not above it
    f = p.find("Filter", "EUROPE")
    assert f, p.text
    joins = p.find("Join")
    assert joins and f[0][0] > joins[0][0], \
        f"filter not pushed below join:\n{p.text}"


# ------------------------------------------------------- semi joins / exists

def test_in_subquery_forms_semijoin(runner):
    sql = ("SELECT count(*) FROM orders WHERE o_custkey IN "
           "(SELECT c_custkey FROM customer)")
    p = plan(runner, sql)
    assert p.has("SemiJoin")


# -------------------------------------------------------- distributed shape

def dplan(runner, sql) -> str:
    return runner.execute(
        "EXPLAIN (TYPE DISTRIBUTED) " + sql).only_value()


def test_q1_distributed_splits_partial_final(runner):
    text = dplan(runner, QUERIES["q1"][0])
    assert "Aggregation[partial" in text
    assert "Aggregation[final" in text
    assert "RemoteSource" in text
    # partial agg and final agg live in different fragments
    frag_of = {}
    current = None
    for line in text.splitlines():
        m = re.match(r"\s*Fragment (\d+)", line)
        if m:
            current = int(m.group(1))
        if "Aggregation[partial" in line:
            frag_of["partial"] = current
        if "Aggregation[final" in line:
            frag_of["final"] = current
    assert frag_of["partial"] != frag_of["final"]


def test_broadcast_join_replicates_small_side(runner):
    text = dplan(runner,
                 "SELECT count(*) FROM orders, customer "
                 "WHERE o_custkey = c_custkey")
    assert "replicated" in text


def test_partitioned_join_repartitions_both_sides(runner):
    runner.execute("SET SESSION join_distribution_type = 'PARTITIONED'")
    try:
        text = dplan(runner,
                     "SELECT count(*) FROM orders, customer "
                     "WHERE o_custkey = c_custkey")
    finally:
        runner.execute("RESET SESSION join_distribution_type")
    assert "partitioned" in text
    assert text.count("RemoteSource") >= 2


def test_distinct_agg_not_split(runner):
    text = dplan(runner,
                 "SELECT o_orderpriority, count(DISTINCT o_orderstatus) "
                 "FROM orders GROUP BY o_orderpriority")
    assert "Aggregation[partial" not in text
    assert "Aggregation[single" in text


# ------------------------------------------------------------ join ordering

def test_q9_join_order_starts_from_part(runner):
    """Greedy reorder keeps the selective part-filter side early; regression
    guard for the q9 ordering that round 2 fixed."""
    p = plan(runner, QUERIES["q9"][0])
    joins = p.find("Join")
    assert len(joins) >= 5
    assert not p.has("Join", "cross")


def test_q21_exists_and_not_exists_shape(runner):
    p = plan(runner, QUERIES["q21"][0])
    # EXISTS -> semi/mark machinery without cross joins
    assert not p.has("Join", "cross")
