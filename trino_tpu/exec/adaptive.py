"""Adaptive operator strategies: runtime re-decision of CBO choices.

Reference grounding (PAPERS.md): "Partial Partial Aggregates" — partial
aggregation should shrink or bypass ITSELF at runtime when the observed
reduction ratio says NDV is effectively high, instead of burning a sort
per page that collapses nothing — and "Design Trade-offs for a Robust
Dynamic Hybrid Hash Join" — spill partitions that miss their budget must
recursively repartition (fresh hash salt) with heavy-hitter keys split
out, because a bad NDV/skew estimate is a *runtime* problem no better
estimate fixes.

This module holds the decision state; the execution paths live in
exec/local_planner.py (aggregation buffer loop + `_finalize_agg_spill`,
join `_run_partitioned_inner`) and exec/spill.py (salted partitioning,
heavy-key detection/splitting, the spill ledger).

The aggregation mode lattice (session prop `adaptive_partial_agg`):

  full      per-page sort-based partial aggregation + buffer compaction
            (the classic path — wins when groups collapse early)
  shrunken  per-page partial SKIPPED: pages map to per-row partial
            states (no sort), duplicates are caught only by the
            amortized buffer compaction — one sort per buffer instead
            of one per page
  bypass    compaction skipped too: per-row states go straight to host
            spill partitions and the per-partition finalize does ALL
            the grouping (zero wasted reduction work at NDV ~ rows;
            reachable only when spill is enabled)

The controller starts from the CBO hint (estimated group NDV / input
rows, stamped by planner/optimizer.annotate_adaptive_hints) and
re-decides at every buffer-compaction boundary from the OBSERVED
reduction ratio `groups_out / rows_in`, with hysteresis so a borderline
ratio doesn't thrash. Decisions happen only at compaction boundaries —
between device dispatches — so the sliced executor's cooperative
boundary (cancel / low-memory kill / chaos) is never blocked by a mode
switch. In bypass, every `BYPASS_PROBE_EVERY`-th flush still compacts
as a probe so a recovering ratio can re-upgrade.

`AdaptiveQueryState` is the per-QUERY carrier: it outlives a failed
attempt, so the memory-degrade re-run (exec/runner.py's spill-forced
retry) starts from the mode and heavy keys the failed attempt OBSERVED
instead of re-learning them from scratch.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

# downgrade when a compaction keeps >= this fraction of its input rows
# (partial aggregation is not collapsing groups)
DOWNGRADE_RATIO = 0.8
# re-upgrade when a compaction keeps <= this fraction (hysteresis gap
# between the two keeps a borderline ratio from thrashing)
UPGRADE_RATIO = 0.4
# in bypass, compact every Nth flush anyway to re-measure the ratio
BYPASS_PROBE_EVERY = 4


class AggMode:
    FULL = "full"
    SHRUNKEN = "shrunken"
    BYPASS = "bypass"
    LATTICE = (FULL, SHRUNKEN, BYPASS)


class AggModeController:
    """Reduction-ratio monitor for ONE aggregation operator.

    Owns the mode and the transition counts; the executor mirrors
    transitions into the query's QueryStatsCollector
    (`agg_mode_downgrades` / `agg_mode_upgrades`)."""

    def __init__(self, mode: str = AggMode.FULL,
                 allow_bypass: bool = True):
        self.mode = mode
        self.allow_bypass = bool(allow_bypass)
        self.downgrades = 0
        self.upgrades = 0
        self.flushes = 0
        self.last_ratio: Optional[float] = None
        self.history: List[str] = [mode]

    @staticmethod
    def initial_mode(ndv: Optional[float],
                     rows: Optional[float]) -> str:
        """The CBO's pick: estimated groups / input rows at or past the
        downgrade threshold starts SHRUNKEN (never straight to BYPASS —
        full bypass needs runtime confirmation, estimates miss)."""
        if ndv and rows and rows > 0 and ndv / rows >= DOWNGRADE_RATIO:
            return AggMode.SHRUNKEN
        return AggMode.FULL

    def note_flush(self) -> None:
        self.flushes += 1

    def should_probe(self) -> bool:
        """In bypass: is this flush a ratio-probing compaction?"""
        if self.mode != AggMode.BYPASS:
            return True
        return self.flushes % BYPASS_PROBE_EVERY == 0

    def observe(self, rows_in: int, groups_out: int) -> Optional[str]:
        """One compaction boundary's measurement. Returns 'downgrade',
        'upgrade', or None; at most one lattice step per observation."""
        if rows_in <= 0:
            return None
        ratio = float(groups_out) / float(rows_in)
        self.last_ratio = ratio
        i = AggMode.LATTICE.index(self.mode)
        if ratio >= DOWNGRADE_RATIO and i < len(AggMode.LATTICE) - 1:
            nxt = AggMode.LATTICE[i + 1]
            if nxt == AggMode.BYPASS and not self.allow_bypass:
                return None
            self.mode = nxt
            self.downgrades += 1
            self.history.append(nxt)
            return "downgrade"
        if ratio <= UPGRADE_RATIO and i > 0:
            self.mode = AggMode.LATTICE[i - 1]
            self.upgrades += 1
            self.history.append(self.mode)
            return "upgrade"
        return None


class AdaptiveQueryState:
    """Per-query adaptive state, shared by every executor the query runs
    (local pipeline, shard executors) and — the point — by every retry
    ATTEMPT: the runner keeps one instance for the query's lifetime, so
    the once-per-query spill-forced degrade re-run inherits the failed
    attempt's observed modes and heavy keys instead of restarting cold.

    Keyed by STRUCTURAL operator identity (group-by / join-clause
    symbol names), not plan-node ids: a re-run that re-plans past a
    missed plan cache builds fresh node objects, and the inherited
    state must still find its controller. In distributed runs every
    shard executor binds the shared controller, so
    `attempt_initial_modes` records one entry per executor binding
    (one per attempt on the local engine)."""

    def __init__(self):
        self.agg: Dict[object, AggModeController] = {}
        self.join_heavy: Dict[object, Tuple[int, ...]] = {}
        # per-operator list of the mode each executor binding started
        # in (the regression surface for the degrade-rerun inheritance
        # contract)
        self.attempt_initial_modes: Dict[object, List[str]] = {}

    def agg_controller(self, node_id, ndv: Optional[float] = None,
                       rows: Optional[float] = None,
                       allow_bypass: bool = True) -> AggModeController:
        ctl = self.agg.get(node_id)
        if ctl is None:
            ctl = AggModeController(
                AggModeController.initial_mode(ndv, rows), allow_bypass)
            self.agg[node_id] = ctl
        else:
            # a re-run may force spill on (degrade), flipping bypass
            # from unreachable to reachable
            ctl.allow_bypass = bool(allow_bypass)
        self.attempt_initial_modes.setdefault(node_id, []).append(ctl.mode)
        return ctl

    def record_join_heavy(self, node_id, keys) -> None:
        self.join_heavy[node_id] = tuple(int(k) for k in keys)

    def join_heavy_hint(self, node_id) -> Tuple[int, ...]:
        return self.join_heavy.get(node_id, ())
