"""Query mesh: device topology + sharded page placement.

Reference parity: the scheduler's node topology (NodeScheduler/
InternalNodeManager) collapses, TPU-first, into a jax.sharding.Mesh with a
single 'workers' axis; split->node assignment (SURVEY §2.10 'source
parallelism') becomes host pages placed shard-by-shard onto the mesh.
Multi-host pods extend the same mesh across processes (single-controller
JAX); DCN boundaries stay outside this module.
"""

from __future__ import annotations

import functools
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from trino_tpu.page import Column, Page


class QueryMesh:
    """One query-engine worker per device along axis 'workers'."""

    AXIS = "workers"

    def __init__(self, devices: Optional[Sequence] = None):
        devices = list(devices if devices is not None else jax.devices())
        self.mesh = Mesh(np.array(devices), (self.AXIS,))
        self.n = len(devices)

    def device_of(self, shard: int):
        """The physical device executing worker `shard`'s task pipelines."""
        return self.mesh.devices.flat[shard]

    # ---------------------------------------------------------- placement

    def replicated(self, tree):
        spec = NamedSharding(self.mesh, P())
        return jax.device_put(tree, spec)

    def shard_pages(self, pages: List[Page]) -> Page:
        """Stack n per-worker pages into one global Page whose leading axis is
        sharded over the mesh (the split->node assignment step).

        Assembled via make_array_from_single_device_arrays so per-shard
        blocks that already live on their devices (e.g. the output of a
        previous exchange) are used in place — no host round trip and no
        cross-device stack."""
        assert len(pages) == self.n, f"need {self.n} pages, got {len(pages)}"
        sharding = NamedSharding(self.mesh, P(self.AXIS))
        devices = list(self.mesh.devices.flat)

        def stack(*leaves):
            blocks = [
                jax.device_put(jnp.expand_dims(jnp.asarray(leaf), 0), dev)
                for leaf, dev in zip(leaves, devices)]
            shape = (self.n,) + blocks[0].shape[1:]
            return jax.make_array_from_single_device_arrays(
                shape, sharding, blocks)

        return jax.tree_util.tree_map(stack, *pages)

    def shard_map(self, fn: Callable, *, in_specs=None, out_specs=None,
                  check_rep: bool = False) -> Callable:
        """Wrap fn as a per-shard program over the mesh (one Trino 'task'
        per device; collectives inside fn are the exchange data plane).

        Inputs stacked by shard_pages arrive as (1, ...) blocks per shard;
        fn sees them squeezed to per-worker shapes and its outputs are
        re-expanded so the global result keeps the sharded leading axis.
        """
        in_specs = in_specs if in_specs is not None else P(self.AXIS)
        out_specs = out_specs if out_specs is not None else P(self.AXIS)

        def wrapped(*args):
            squeezed = jax.tree_util.tree_map(
                lambda x: jnp.squeeze(x, axis=0), args)
            out = fn(*squeezed)
            return jax.tree_util.tree_map(
                lambda x: jnp.expand_dims(x, axis=0), out)

        try:
            return shard_map(wrapped, mesh=self.mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_rep)
        except TypeError:  # pre-0.8 jax spells it check_rep
            return shard_map(wrapped, mesh=self.mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=check_rep)

    def unshard(self, tree):
        """Fetch a sharded tree to host as per-shard list (axis 0)."""
        gathered = jax.device_get(tree)
        return gathered
