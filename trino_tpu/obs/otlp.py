"""OTLP/JSON trace span export, wired from the event-listener registry.

Reference parity: trino-main's OpenTelemetry integration
(io.opentelemetry wiring in ServerMainModule) exports query spans over
OTLP so fleet operators correlate engine traces with everything else.
Here the engine's spans are the structured dump obs/spans.py already
records per query (QueryInfo.trace); this module converts that dump to
the OTLP/JSON `ResourceSpans` shape and ships it from a query_completed/
query_failed event listener — OFF by default, enabled by registering the
listener (install_otlp_exporter, the TrinoServer `otlp_export` option,
or $TRINO_TPU_OTLP_ENDPOINT / $TRINO_TPU_OTLP_FILE).

Targets: an `http(s)://` endpoint receives one POST per query at
`<endpoint>/v1/traces` (the OTLP/HTTP JSON binding); any other target is
a file path appended one JSON line per query (the file-exporter shape
collectors replay). Export failures are swallowed and logged — tracing
must never fail queries (the same contract as every other listener).

Span identity: the trace id derives from the query id (16 bytes of its
blake2b), span ids from the path to the span in the tree — stable,
collision-resistant, and reproducible across re-exports of one query.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import time
from typing import Any, Dict, List, Optional

from trino_tpu.obs.listeners import (EventListener, QueryEvent,
                                     register_listener,
                                     unregister_listener)

log = logging.getLogger("trino_tpu.obs.otlp")


def _hex_id(seed: str, nbytes: int) -> str:
    return hashlib.blake2b(seed.encode(), digest_size=nbytes).hexdigest()


def _attr_value(v: Any) -> Dict[str, Any]:
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}    # OTLP JSON carries int64 as string
    if isinstance(v, float):
        return {"doubleValue": v}
    return {"stringValue": str(v)}


def _attributes(attrs: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [{"key": str(k), "value": _attr_value(v)}
            for k, v in attrs.items()]


def spans_to_otlp(trace: Dict[str, Any], query_id: str,
                  end_unix_ns: Optional[int] = None) -> Dict[str, Any]:
    """One query's span dump (Span.to_json shape: relative start_ms /
    wall_ms trees) -> an OTLP/JSON ResourceSpans payload. The dump's
    times are relative to the query root; `end_unix_ns` (default: now)
    anchors them on the wall clock so the absolute timestamps line up
    with when the export happened."""
    if end_unix_ns is None:
        end_unix_ns = time.time_ns()
    root_wall_ns = int(trace.get("wall_ms", 0.0) * 1e6)
    origin_ns = end_unix_ns - root_wall_ns
    trace_id = _hex_id(query_id, 16)
    spans: List[Dict[str, Any]] = []

    def walk(node: Dict[str, Any], path: str, parent_span_id: str) -> None:
        span_id = _hex_id(f"{query_id}/{path}", 8)
        start_ns = origin_ns + int(node.get("start_ms", 0.0) * 1e6)
        attrs = dict(node.get("attrs", ()))
        attrs["trino.span.kind"] = node.get("kind", "internal")
        span = {
            "traceId": trace_id,
            "spanId": span_id,
            "name": node.get("name", "span"),
            "kind": 1,     # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(start_ns),
            "endTimeUnixNano": str(
                start_ns + int(node.get("wall_ms", 0.0) * 1e6)),
            "attributes": _attributes(attrs),
        }
        if parent_span_id:
            span["parentSpanId"] = parent_span_id
        spans.append(span)
        for i, child in enumerate(node.get("children", ())):
            walk(child, f"{path}/{i}:{child.get('name', '')}", span_id)

    walk(trace, trace.get("name", "query"), "")
    return {
        "resourceSpans": [{
            "resource": {"attributes": _attributes(
                {"service.name": "trino-tpu",
                 "trino.query_id": query_id})},
            "scopeSpans": [{
                "scope": {"name": "trino_tpu.obs"},
                "spans": spans,
            }],
        }],
    }


class OtlpSpanExporter(EventListener):
    """The listener: exports every completed/failed query's trace."""

    def __init__(self, endpoint: Optional[str] = None,
                 path: Optional[str] = None, timeout_s: float = 2.0):
        if (endpoint is None) == (path is None):
            raise ValueError(
                "OtlpSpanExporter needs exactly one of endpoint / path")
        self.endpoint = endpoint
        self.path = path
        self.timeout_s = timeout_s
        self.exported = 0
        self.failed = 0

    def query_completed(self, event: QueryEvent) -> None:
        self._export(event)

    def query_failed(self, event: QueryEvent) -> None:
        self._export(event)

    def _export(self, event: QueryEvent) -> None:
        if not event.trace:
            return     # nothing recorded (e.g. a pre-execute failure)
        try:
            payload = spans_to_otlp(event.trace, event.query_id)
            if self.path is not None:
                with open(self.path, "a") as f:
                    f.write(json.dumps(payload) + "\n")
            else:
                import urllib.request
                req = urllib.request.Request(
                    self.endpoint.rstrip("/") + "/v1/traces",
                    data=json.dumps(payload).encode(),
                    headers={"Content-Type": "application/json"},
                    method="POST")
                urllib.request.urlopen(req, timeout=self.timeout_s).close()
            self.exported += 1
        except Exception:   # noqa: BLE001 — tracing must not fail queries
            self.failed += 1
            log.exception("OTLP span export failed for %s", event.query_id)


def install_otlp_exporter(target: Optional[str] = None
                          ) -> Optional[OtlpSpanExporter]:
    """Register an exporter for `target` (http(s) endpoint or file
    path), falling back to $TRINO_TPU_OTLP_ENDPOINT then
    $TRINO_TPU_OTLP_FILE. Returns None (exporting stays OFF) when no
    target is configured anywhere."""
    target = (target or os.environ.get("TRINO_TPU_OTLP_ENDPOINT")
              or os.environ.get("TRINO_TPU_OTLP_FILE"))
    if not target:
        return None
    if target.startswith("http://") or target.startswith("https://"):
        exporter = OtlpSpanExporter(endpoint=target)
    else:
        exporter = OtlpSpanExporter(path=target)
    return register_listener(exporter)


def uninstall_otlp_exporter(exporter: OtlpSpanExporter) -> None:
    unregister_listener(exporter)
