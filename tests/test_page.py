"""Columnar core tests (types, Column/Page, dictionary encoding).

Mirrors the reference's spi-level unit tier (core/trino-spi tests, SURVEY §4):
drive the data model directly with numpy rows.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.page import Column, Dictionary, Page, concat_pages


def test_type_registry_roundtrip():
    for text, typ in [
        ("bigint", T.BIGINT), ("integer", T.INTEGER), ("double", T.DOUBLE),
        ("boolean", T.BOOLEAN), ("varchar", T.VARCHAR), ("date", T.DATE),
        ("decimal(12,2)", T.DecimalType(12, 2)),
        ("varchar(25)", T.VarcharType(25)),
    ]:
        assert T.parse_type(text) == typ


def test_coercion_lattice():
    assert T.common_super_type(T.INTEGER, T.BIGINT) == T.BIGINT
    assert T.common_super_type(T.BIGINT, T.DOUBLE) == T.DOUBLE
    assert T.common_super_type(T.UNKNOWN, T.DATE) == T.DATE
    assert T.common_super_type(
        T.DecimalType(12, 2), T.DecimalType(10, 4)) == T.DecimalType(14, 4)
    # bigint forces 19 integer digits -> would exceed short-decimal precision;
    # round 1 falls back to double rather than long decimals
    assert T.common_super_type(T.DecimalType(10, 2), T.BIGINT) == T.DOUBLE
    assert T.common_super_type(T.DecimalType(10, 2), T.INTEGER) == T.DecimalType(12, 2)
    assert T.common_super_type(
        T.TimestampType(3), T.TimestampType(6)) == T.TimestampType(6)
    assert T.common_super_type(T.BOOLEAN, T.BIGINT) is None


def test_dictionary_sorted_codes_preserve_order():
    d, codes = Dictionary.build(["cherry", "apple", "banana", "apple"])
    assert list(d.values) == ["apple", "banana", "cherry"]
    assert codes.tolist() == [2, 0, 1, 0]
    assert d.code_of("banana") == 1
    assert d.code_of("zzz") == -1
    # code order == string order
    assert (codes[1] < codes[2]) == ("apple" < "banana")


def test_page_from_numpy_and_back():
    page = Page.from_numpy(
        [np.array([1, 2, 3]), np.array([1.5, 2.5, 3.5]),
         np.array(["b", "a", "b"], dtype=object)],
        [T.BIGINT, T.DOUBLE, T.VARCHAR])
    assert page.capacity == 3 and int(page.num_rows) == 3
    rows = page.to_pylist()
    assert rows == [(1, 1.5, "b"), (2, 2.5, "a"), (3, 3.5, "b")]


def test_page_filter_compacts():
    page = Page.from_numpy([np.arange(8), np.arange(8) * 10.0],
                           [T.BIGINT, T.DOUBLE])
    mask = jnp.asarray([True, False, True, False, True, False, False, True])
    out = page.filter(mask)
    assert out.capacity == 8
    assert int(out.num_rows) == 4
    assert out.to_pylist() == [(0, 0.0), (2, 20.0), (4, 40.0), (7, 70.0)]


def test_page_filter_respects_num_rows():
    # rows beyond num_rows are padding and must not pass the filter
    page = Page.from_numpy([np.arange(8)], [T.BIGINT])
    page = Page(page.columns, jnp.asarray(5, dtype=jnp.int32))
    out = page.filter(jnp.ones(8, dtype=jnp.bool_))
    assert int(out.num_rows) == 5


def test_page_filter_under_jit():
    page = Page.from_numpy([np.arange(16), np.arange(16) * 2.0],
                           [T.BIGINT, T.DOUBLE])

    @jax.jit
    def go(p):
        return p.filter(p.column(0).values % 3 == 0)

    out = go(page)
    assert int(out.num_rows) == 6
    assert [r[0] for r in out.to_pylist()] == [0, 3, 6, 9, 12, 15]


def test_nulls_roundtrip():
    page = Page.from_numpy([np.array([1, 2, 3])], [T.BIGINT],
                           valids=[np.array([True, False, True])])
    assert page.to_pylist() == [(1,), (None,), (3,)]


def test_concat_pages():
    p1 = Page.from_numpy([np.array([1, 2])], [T.BIGINT])
    p2 = Page.from_numpy([np.array([3])], [T.BIGINT])
    out = concat_pages([p1, p2])
    assert out.to_pylist() == [(1,), (2,), (3,)]


def test_page_is_pytree():
    page = Page.from_numpy([np.arange(4)], [T.BIGINT])
    leaves = jax.tree_util.tree_leaves(page)
    assert len(leaves) == 2  # values + num_rows
    rebuilt = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(page), leaves)
    assert rebuilt.to_pylist() == page.to_pylist()
