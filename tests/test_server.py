"""/v1/statement wire protocol vs a stdlib HTTP client.

Reference parity: the documented Trino client protocol
(client/trino-client StatementClientV1.java:61 — POST, follow nextUri,
typed columns, data rows, Set-Session headers, DELETE cancel) exercised
exactly the way the stock CLI drives it.
"""

import json
import urllib.request

import pytest

from trino_tpu.exec import LocalQueryRunner
from trino_tpu.server import TrinoServer


@pytest.fixture(scope="module")
def server():
    srv = TrinoServer(LocalQueryRunner.tpch("tiny")).start()
    yield srv
    srv.stop()


def _post(server, sql, headers=None):
    req = urllib.request.Request(
        f"{server.base_uri}/v1/statement", data=sql.encode(), method="POST")
    req.add_header("X-Trino-User", "test")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read()), dict(resp.headers)


def _get(uri):
    with urllib.request.urlopen(uri) as resp:
        return json.loads(resp.read()), dict(resp.headers)


def run_query(server, sql, headers=None):
    """Client loop: POST, then follow nextUri until absent. Data may
    appear in ANY response including the first (StatementClientV1 reads
    it wherever it shows up — the result-cache fast path answers
    FINISHED with the rows inline in the POST response)."""
    payload, hdrs = _post(server, sql, headers)
    columns = payload.get("columns")
    rows = list(payload.get("data", []))
    states = [payload["stats"]["state"]]
    while "nextUri" in payload:
        payload, h = _get(payload["nextUri"])
        hdrs.update(h)
        states.append(payload["stats"]["state"])
        if "columns" in payload:
            columns = payload["columns"]
        rows.extend(payload.get("data", []))
    return payload, columns, rows, states, hdrs


def test_simple_query(server):
    payload, columns, rows, states, _ = run_query(
        server, "SELECT n_nationkey, n_name FROM nation "
                "WHERE n_regionkey = 1 ORDER BY n_nationkey")
    assert states[0] == "QUEUED" and states[-1] == "FINISHED"
    assert [c["name"] for c in columns] == ["n_nationkey", "n_name"]
    assert columns[0]["type"] == "bigint"
    assert columns[1]["type"].startswith("varchar")
    assert columns[0]["typeSignature"]["rawType"] == "bigint"
    assert rows == [[1, "ARGENTINA"], [2, "BRAZIL"], [3, "CANADA"],
                    [17, "PERU"], [24, "UNITED STATES"]]
    assert "error" not in payload


def test_typed_values(server):
    _, columns, rows, _, _ = run_query(
        server, "SELECT o_orderdate, o_totalprice, o_orderkey = 1 "
                "FROM orders WHERE o_orderkey = 1")
    assert columns[0]["type"] == "date"
    assert columns[1]["type"].startswith("decimal")
    (date_s, price_s, flag), = rows
    assert len(date_s.split("-")) == 3       # ISO date string
    assert "." in price_s                     # decimal as string
    assert flag is True


def test_paging(server):
    payload, _, rows, states, _ = run_query(
        server, "SELECT c_custkey FROM customer")
    assert len(rows) == 1500
    # at least one intermediate page: RUNNING while producing, or
    # FINISHING while the result ring drains (the streaming lifecycle)
    assert states.count("RUNNING") + states.count("FINISHING") >= 1
    assert "nextUri" not in payload


def test_error_surfaced_as_query_error(server):
    payload, _, _, states, _ = run_query(server, "SELECT bogus_fn(1)")
    assert states[-1] == "FAILED"
    assert "bogus_fn" in payload["error"]["message"]
    assert payload["error"]["errorType"] == "USER_ERROR"


def test_set_session_header_roundtrip(server):
    payload, _, _, _, hdrs = run_query(
        server, "SET SESSION join_distribution_type = 'PARTITIONED'")
    assert payload.get("updateType") == "SET SESSION"
    assert hdrs.get("X-Trino-Set-Session") == \
        "join_distribution_type=PARTITIONED"
    _, _, _, _, hdrs = run_query(
        server, "RESET SESSION join_distribution_type")
    assert hdrs.get("X-Trino-Clear-Session") == "join_distribution_type"


def test_catalog_schema_headers(server):
    _, _, rows, _, _ = run_query(
        server, "SELECT count(*) FROM nation",
        headers={"X-Trino-Catalog": "tpch", "X-Trino-Schema": "tiny"})
    assert rows == [[25]]


def test_cancel():
    # a dedicated max_running=1 server: occupy the single executor so the
    # victim stays deterministically QUEUED when the DELETE lands (cancel
    # of a TERMINAL query is a no-op, reference semantics — racing a bare
    # SELECT 1 against the default executor POOL would flake)
    srv = TrinoServer(LocalQueryRunner.tpch("tiny"), max_running=1).start()
    try:
        blocker, _ = _post(srv, "SELECT count(*) FROM lineitem l1, "
                                "lineitem l2 WHERE l1.l_orderkey = "
                                "l2.l_orderkey AND l1.l_partkey = "
                                "l2.l_partkey")
        payload, _ = _post(srv, "SELECT 1")
        uri = payload["nextUri"]
        req = urllib.request.Request(uri, method="DELETE")
        with urllib.request.urlopen(req) as resp:
            assert resp.status == 204
        payload, _ = _get(uri)
        assert payload["stats"]["state"] == "CANCELED"
        assert payload["error"]["errorCode"] == 3      # USER_CANCELED
        while "nextUri" in blocker:                    # drain the blocker
            blocker, _ = _get(blocker["nextUri"])
        assert blocker["stats"]["state"] == "FINISHED"
    finally:
        srv.stop()


def test_cancel_finished_query_is_noop(server):
    """DELETE on a FINISHED query must not destroy access to its
    buffered results (code-review finding)."""
    import time
    payload, _ = _post(server, "SELECT n_nationkey FROM nation")
    uri = payload["nextUri"]
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        p, _ = _get(uri)
        if p["stats"]["state"] not in ("QUEUED", "RUNNING"):
            break
        time.sleep(0.05)
    req = urllib.request.Request(uri, method="DELETE")
    with urllib.request.urlopen(req) as resp:
        assert resp.status == 204
    rows = []
    p, _ = _get(uri)
    rows.extend(p.get("data", []))
    while "nextUri" in p:
        p, _ = _get(p["nextUri"])
        rows.extend(p.get("data", []))
    assert p["stats"]["state"] == "FINISHED"
    assert len(rows) == 25


def test_unknown_query_404(server):
    try:
        _get(f"{server.base_uri}/v1/statement/executing/nope/slug/0")
        assert False, "expected 404"
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_concurrent_paging_during_long_query(server):
    """Dispatch queue (round 5): a running query must not block another
    client paging an already-finished query's results
    (DispatchManager.java:140 / resource-group max_running=1 shape)."""
    import threading
    import time

    # finish a short query first; keep its page-0 URI (a statement no
    # earlier test cached — a result-cache hit answers the POST inline
    # with no nextUri to page)
    payload, _ = _post(server, "SELECT n_nationkey, n_regionkey "
                               "FROM nation")
    first_uri = payload["nextUri"]
    while "nextUri" in payload:
        payload, _ = _get(payload["nextUri"])
    # launch a LONG query in a side thread (self-join at tiny ~seconds)
    long_sql = ("SELECT count(*) FROM lineitem l1, lineitem l2 "
                "WHERE l1.l_orderkey = l2.l_orderkey "
                "AND l1.l_partkey = l2.l_partkey")
    done = {}

    def run_long():
        done["result"] = run_query(server, long_sql)
    th = threading.Thread(target=run_long)
    th.start()
    # while it runs, page the finished query's buffered results: must be
    # immediate (no engine lock on the paging path)
    t0 = time.perf_counter()
    page, _ = _get(first_uri)
    elapsed = time.perf_counter() - t0
    assert elapsed < 2.0, f"paging blocked for {elapsed:.1f}s"
    assert page.get("data") or "nextUri" in page
    th.join(timeout=120)
    assert done["result"][2][0][0] > 0       # long query completed too


def test_invalid_token_is_404_not_500(server):
    """A malformed or negative page token must answer 404, not crash the
    handler into an HTTP 500 (the _resolve int() fix)."""
    payload, _ = _post(server, "SELECT 1")
    base = payload["nextUri"].rsplit("/", 1)[0]
    for bad in ("abc", "-1", "1x", ""):
        try:
            _get(f"{base}/{bad}")
            assert False, f"expected 404 for token {bad!r}"
        except urllib.error.HTTPError as e:
            assert e.code == 404, f"token {bad!r} -> {e.code}"
    # drain the good query so the module fixture stays clean
    while "nextUri" in payload:
        payload, _ = _get(payload["nextUri"])


def test_pruned_query_answers_410_gone():
    """Past the keep bound, a finished query's results are pruned and a
    late GET answers 410 Gone (retrying is pointless), not a bare 404."""
    from trino_tpu.exec import LocalQueryRunner
    srv = TrinoServer(LocalQueryRunner.tpch("tiny"), keep=2).start()
    try:
        # finish one query and hold its page-0 URI, then submit enough
        # queries to push it past the keep bound
        first, _ = _post(srv, "SELECT 100")
        first_uri = first["nextUri"]
        p = first
        while "nextUri" in p:
            p, _ = _get(p["nextUri"])
        for i in range(8):       # push the first query past keep=2
            run_query(srv, f"SELECT {200 + i}")
        try:
            _get(first_uri)
            assert False, "expected 410"
        except urllib.error.HTTPError as e:
            assert e.code == 410
        # a never-existed id still answers 404
        try:
            _get(f"{srv.base_uri}/v1/statement/executing/nope/slug/0")
            assert False, "expected 404"
        except urllib.error.HTTPError as e:
            assert e.code == 404
    finally:
        srv.stop()


def test_cancel_running_query_frees_executor(server):
    """DELETE on a RUNNING query transitions it to CANCELED at the next
    cooperative checkpoint and the executor picks up the next queued
    query (the ISSUE acceptance bar for cancellation)."""
    import time
    long_sql = ("SELECT count(*) FROM lineitem l1, lineitem l2, "
                "lineitem l3 WHERE l1.l_orderkey = l2.l_orderkey "
                "AND l2.l_orderkey = l3.l_orderkey "
                "AND l1.l_partkey = l2.l_partkey AND l1.l_tax = l2.l_tax")
    # small scan pages => MANY page-batch checkpoints, so the cooperative
    # cancel lands in seconds even when the fused join kernels are warm
    # (one giant fused program can otherwise run minutes checkpoint-free)
    hdrs = {"X-Trino-Session": "scan_page_capacity=4096,page_capacity=4096"}
    payload, _ = _post(server, long_sql, headers=hdrs)
    uri = payload["nextUri"]
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        p, _ = _get(uri)
        if p["stats"]["state"] == "RUNNING":
            break
        time.sleep(0.05)
    req = urllib.request.Request(uri, method="DELETE")
    with urllib.request.urlopen(req) as resp:
        assert resp.status == 204
    p, _ = _get(uri)
    assert p["stats"]["state"] == "CANCELED"
    assert p["error"]["errorName"] == "USER_CANCELED"
    # the executor pool must serve the next client promptly even though
    # the canceled query would have run for much longer
    _, _, rows, _, _ = run_query(server, "SELECT 41 + 1")
    assert rows == [[42]]
    # the RUNNER observes the cancel at its next cooperative checkpoint
    # and the tracker records CANCELED under the server's query id (the
    # server answers CANCELED immediately; the tracker flips when the
    # executing thread actually unwinds — poll for it)
    from trino_tpu.exec.query_tracker import TRACKER
    deadline = time.monotonic() + 120
    state = None
    while time.monotonic() < deadline:
        state = next((q.state for q in TRACKER.list()
                      if q.query_id == p["id"]), None)
        if state == "CANCELED":
            break
        time.sleep(0.1)
    assert state == "CANCELED", state


def test_concurrent_submit_poll_cancel_race(server):
    """N client threads submit/poll/cancel concurrently: no HTTP 500s,
    every query reaches a terminal state, and the registry (now
    lock-guarded) never corrupts."""
    import threading

    N = 8
    results = [None] * N
    failures = []

    def client(i):
        try:
            sql = f"SELECT n_nationkey + {i} FROM nation"
            payload, _ = _post(server, sql)
            if i % 3 == 0:
                # cancel mid-flight (QUEUED or RUNNING — both legal)
                req = urllib.request.Request(payload["nextUri"],
                                             method="DELETE")
                with urllib.request.urlopen(req) as resp:
                    assert resp.status == 204
            while "nextUri" in payload:
                payload, _ = _get(payload["nextUri"])
            results[i] = payload["stats"]["state"]
        except BaseException as e:  # noqa: BLE001
            failures.append((i, e))

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(N)]
    for th in threads:
        th.start()
    for th in threads:
        th.join(timeout=120)
    assert not failures, failures
    assert all(r in ("FINISHED", "CANCELED") for r in results), results
    # cancels observed as CANCELED or raced to FINISHED; non-cancelled
    # clients must all have finished
    assert all(results[i] == "FINISHED" for i in range(N) if i % 3)


def test_concurrent_queries_interleave(server):
    """max_running > 1 (round 7): independent queries genuinely run
    concurrently — the tracker observes >= 2 simultaneously RUNNING
    server queries while the pool drains a batch."""
    import threading
    import time

    from trino_tpu.exec.query_tracker import TRACKER

    sql = ("SELECT count(*) FROM lineitem l1, lineitem l2 "
           "WHERE l1.l_orderkey = l2.l_orderkey "
           "AND l1.l_partkey = l2.l_partkey")
    ids = []
    for i in range(3):
        payload, _ = _post(server, sql + f" AND {i} = {i}")
        ids.append(payload["id"])
    # the pool should mark several RUNNING almost immediately
    seen_concurrent = 0
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        states = {q.query_id: q.state for q in TRACKER.list()}
        running = sum(1 for qid in ids if states.get(qid) == "RUNNING")
        seen_concurrent = max(seen_concurrent, running)
        if seen_concurrent >= 2:
            break
        time.sleep(0.01)
    # drain them all (also proves none was lost to the pool rework)
    for qid in ids:
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            states = {q.query_id: q.state for q in TRACKER.list()}
            if states.get(qid) == "FINISHED":
                break
            time.sleep(0.05)
        assert states.get(qid) == "FINISHED", states.get(qid)
    assert seen_concurrent >= 2, seen_concurrent


def test_resource_group_routing(server):
    """The resource_group session property routes a query through the
    named group and lands in system.runtime.queries +
    system.runtime.resource_groups."""
    _, _, rows, _, _ = run_query(
        server, "SELECT 5",
        headers={"X-Trino-Session": "resource_group=etl.nightly"})
    assert rows == [[5]]
    _, _, rows, _, _ = run_query(
        server,
        "SELECT resource_group FROM system.runtime.queries "
        "WHERE query = 'SELECT 5'")
    assert ["etl.nightly"] in rows
    _, _, rows, _, _ = run_query(
        server,
        "SELECT name, parent, finished FROM "
        "system.runtime.resource_groups ORDER BY name")
    by_name = {r[0]: r for r in rows}
    assert "etl" in by_name and "etl.nightly" in by_name
    assert by_name["etl.nightly"][1] == "etl"
    assert by_name["etl.nightly"][2] >= 1


def test_queue_full_admission(server):
    """Admission control: an over-limit submit fails as
    QUERY_QUEUE_FULL, not an HTTP error (InternalResourceGroup
    canQueueMore analog) — driven through a zero-capacity group so no
    timing games are needed. The statement must be one the result cache
    has never seen: a cache hit consumes no executor resources and is
    legitimately answered without admission."""
    server.groups.configure("zeroq", max_queued=0)
    payload, _, _, _, _ = run_query(
        server, "SELECT 1 + 0 * 9",
        headers={"X-Trino-Session": "resource_group=zeroq"})
    assert payload["stats"]["state"] == "FAILED"
    assert payload["error"]["errorName"] == "QUERY_QUEUE_FULL"
    # the default group still admits
    _, _, rows, _, _ = run_query(server, "SELECT 7")
    assert rows == [[7]]


def test_bad_session_value_fails_unknown_name_tolerated(server):
    # unknown property names from newer clients are ignored
    payload, _, rows, _, _ = run_query(
        server, "SELECT 1", {"X-Trino-Session": "not_a_real_prop=1"})
    assert rows == [[1]] and "error" not in payload
    # a KNOWN property with a malformed value fails the query visibly
    payload, _, _, _, _ = run_query(
        server, "SELECT 1", {"X-Trino-Session": "retry_attempts=abc"})
    assert payload["error"]["errorName"] == "INVALID_SESSION_PROPERTY"
    # ... and terminates its tracker entry (no phantom QUEUED row)
    from trino_tpu.exec.query_tracker import TRACKER
    info = next(q for q in TRACKER.list() if q.query_id == payload["id"])
    assert info.state == "FAILED"
    assert info.error_name == "INVALID_SESSION_PROPERTY"
