"""Fleet bus: unix-datagram fan-out between fleet members.

A tiny local pub/sub for the coordination traffic that is ADVISORY, not
authoritative: invalidation notices (workers drop their hot local
copies — the shm generation check in fleet/shm.py is the authority, so
a lost datagram can delay eviction of a dead local copy but can never
cause a stale answer), prepared-statement registration (a PREPARE on
any worker becomes visible fleet-wide immediately; the on-disk registry
covers late joiners), cache-hit accounting batches (workers -> engine,
for fleet-aggregated group counters and system.runtime.queries), drain
requests, and config-reload nudges.

Every member binds `<fleet_dir>/bus/<name>.sock`; `publish` sends the
JSON message to every socket in the directory (best-effort, non-
blocking — a dead member's stale socket file is unlinked on the first
failed send). `send_to` addresses one member by name.
"""

from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

MAX_DGRAM = 60000


class FleetBus:
    def __init__(self, fleet_dir: str, name: str,
                 on_message: Optional[Callable[[Dict], None]] = None):
        self.dir = os.path.join(fleet_dir, "bus")
        os.makedirs(self.dir, exist_ok=True)
        self.name = name
        self.path = os.path.join(self.dir, f"{name}.sock")
        try:
            os.unlink(self.path)
        except FileNotFoundError:
            pass
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        self._sock.bind(self.path)
        self._sock.settimeout(0.25)
        self._send = socket.socket(socket.AF_UNIX, socket.SOCK_DGRAM)
        self._send.setblocking(False)
        self._on_message = on_message
        # dropped datagrams by message kind: the bus is best-effort by
        # design, but SILENT loss hid real problems (a wedged receiver,
        # oversize hit batches) — count every drop, log once per kind
        self._drops: Dict[str, int] = {}
        self._drop_logged: set = set()
        self._drops_lock = threading.Lock()
        self._closed = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if on_message is not None:
            self._thread = threading.Thread(target=self._recv_loop,
                                            daemon=True,
                                            name=f"fleet-bus-{name}")
            self._thread.start()

    # ------------------------------------------------------------ sending

    def members(self) -> List[str]:
        try:
            return sorted(f[:-5] for f in os.listdir(self.dir)
                          if f.endswith(".sock"))
        except FileNotFoundError:
            return []

    def publish(self, message: Dict, exclude_self: bool = False) -> int:
        """Send to every live member socket; returns the delivered
        count. Best-effort: full buffers and vanished members drop the
        datagram (the shm generation check keeps that safe)."""
        kind = str(message.get("kind", "?"))
        data = json.dumps(message).encode()
        if len(data) > MAX_DGRAM:
            self._record_drop(kind, "<oversize>")
            return 0
        delivered = 0
        for member in self.members():
            if exclude_self and member == self.name:
                continue
            if self._send_one(member, data, kind):
                delivered += 1
        return delivered

    def send_to(self, member: str, message: Dict) -> bool:
        return self._send_one(member, json.dumps(message).encode(),
                              str(message.get("kind", "?")))

    def _send_one(self, member: str, data: bytes, kind: str = "?"
                  ) -> bool:
        path = os.path.join(self.dir, f"{member}.sock")
        try:
            self._send.sendto(data, path)
            return True
        except (ConnectionRefusedError, FileNotFoundError):
            if member != self.name:
                self._reap_stale(path)
            self._record_drop(kind, member)
            return False
        except (BlockingIOError, OSError):
            self._record_drop(kind, member)
            return False

    def _record_drop(self, kind: str, member: str) -> None:
        with self._drops_lock:
            self._drops[kind] = self._drops.get(kind, 0) + 1
            first = kind not in self._drop_logged
            self._drop_logged.add(kind)
        if first:
            print(f"fleet-bus[{self.name}]: dropped {kind!r} datagram "
                  f"to {member} (further {kind!r} drops counted in "
                  f"trino_tpu_fleet_bus_drops_total, not logged)",
                  file=sys.stderr)

    def drops_snapshot(self) -> Dict[str, int]:
        with self._drops_lock:
            return dict(self._drops)

    @staticmethod
    def _reap_stale(path: str) -> None:
        """Unlink a dead member's socket — but only when the path has
        existed for a while: a member restarting under the SAME name
        (engine warm restart) may have re-bound between our failed send
        and this cleanup, and unlinking its fresh socket would mute it
        on the bus forever. The binder unlinks its own stale path at
        bind time, so skipping here is always safe."""
        try:
            if time.time() - os.stat(path).st_mtime > 5.0:
                os.unlink(path)
        except OSError:
            pass

    # ---------------------------------------------------------- receiving

    def _recv_loop(self) -> None:
        while not self._closed.is_set():
            try:
                data, _ = self._sock.recvfrom(MAX_DGRAM)
            except socket.timeout:
                continue
            except OSError:
                return
            try:
                message = json.loads(data)
            except ValueError:
                continue
            try:
                self._on_message(message)
            except Exception:   # noqa: BLE001 — a bad handler must not
                continue        # kill the bus thread

    def close(self) -> None:
        self._closed.set()
        try:
            self._sock.close()
        finally:
            self._send.close()
            try:
                os.unlink(self.path)
            except OSError:
                pass
        if self._thread is not None:
            self._thread.join(timeout=2)
