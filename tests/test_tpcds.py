"""TPC-DS subset vs the sqlite oracle (ladder config #5: q64/q72 shapes).

Reference parity: plugin/trino-tpcds + testing TpcdsQueryRunner — the
decision-support schema through the full engine. Engine SQL uses real
decimal/date types; oracle SQL runs on scaled ints + int days (same
adaptations as the TPC-H oracle, tests/oracle.py).
"""

import pytest

from trino_tpu.exec import LocalQueryRunner

from oracle import assert_same, load_tpcds_sqlite

SF = 0.01


@pytest.fixture(scope="module")
def runner():
    r = LocalQueryRunner.tpch("tiny")
    r.execute("USE tpcds.tiny")
    return r


@pytest.fixture(scope="module")
def oracle():
    conn = load_tpcds_sqlite(SF)
    yield conn
    conn.close()


def check(runner, oracle, engine_sql, oracle_sql=None, ordered=False):
    got = runner.execute(engine_sql)
    cur = oracle.execute(oracle_sql or engine_sql)
    expected = cur.fetchall()
    assert_same(got.rows, expected, ordered)
    return got


def test_scan_and_dimensions(runner, oracle):
    check(runner, oracle,
          "SELECT count(*), count(DISTINCT d_year) FROM date_dim "
          "WHERE d_year BETWEEN 1998 AND 2002")


def test_q3_shape(runner, oracle):
    """TPC-DS q3: store_sales x date_dim x item, brand aggregation."""
    sql = """
SELECT d_year, i_brand_id, i_brand, sum(ss_ext_sales_price) AS sum_agg
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manufact_id = 436 AND d_moy = 12
GROUP BY d_year, i_brand_id, i_brand
ORDER BY d_year, sum_agg DESC, i_brand_id LIMIT 100"""
    check(runner, oracle, sql, ordered=True)


def test_q42_shape(runner, oracle):
    sql = """
SELECT d_year, i_category_id, i_category, sum(ss_ext_sales_price)
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manufact_id > 500 AND d_year = 2000 AND d_moy = 11
GROUP BY d_year, i_category_id, i_category
ORDER BY 4 DESC, d_year, i_category_id, i_category LIMIT 100"""
    check(runner, oracle, sql, ordered=True)


def test_q72(runner, oracle):
    """TPC-DS q72: the 10-way catalog_sales x inventory join."""
    engine = """
SELECT i_item_desc, w_warehouse_name, d1.d_week_seq,
       sum(CASE WHEN p_promo_sk IS NULL THEN 1 ELSE 0 END) no_promo,
       sum(CASE WHEN p_promo_sk IS NOT NULL THEN 1 ELSE 0 END) promo,
       count(*) total_cnt
FROM catalog_sales
JOIN inventory ON (cs_item_sk = inv_item_sk)
JOIN warehouse ON (w_warehouse_sk = inv_warehouse_sk)
JOIN item ON (i_item_sk = cs_item_sk)
JOIN customer_demographics ON (cs_bill_cdemo_sk = cd_demo_sk)
JOIN household_demographics ON (cs_bill_hdemo_sk = hd_demo_sk)
JOIN date_dim d1 ON (cs_sold_date_sk = d1.d_date_sk)
JOIN date_dim d2 ON (inv_date_sk = d2.d_date_sk)
JOIN date_dim d3 ON (cs_ship_date_sk = d3.d_date_sk)
LEFT JOIN promotion ON (cs_promo_sk = p_promo_sk)
LEFT JOIN catalog_returns ON (cr_item_sk = cs_item_sk
                              AND cr_order_number = cs_order_number)
WHERE d1.d_week_seq = d2.d_week_seq
  AND inv_quantity_on_hand < cs_quantity
  AND d3.d_date > d1.d_date + INTERVAL '5' DAY
  AND hd_buy_potential = '>10000'
  AND d1.d_year = 1999
  AND cd_marital_status = 'D'
GROUP BY i_item_desc, w_warehouse_name, d1.d_week_seq
ORDER BY total_cnt DESC, i_item_desc, w_warehouse_name, d1.d_week_seq
LIMIT 100"""
    oracle_sql = engine.replace("d1.d_date + INTERVAL '5' DAY",
                                "d1.d_date + 5")
    check(runner, oracle, engine, oracle_sql, ordered=True)


def test_q64_shape(runner, oracle):
    """TPC-DS q64 core: the cross-channel sales/returns CTE join with
    income bands and first/second-year comparison (reduced projection,
    same join topology)."""
    engine = """
WITH cs_ui AS (
  SELECT cs_item_sk,
         sum(cs_ext_list_price) AS sale,
         sum(cr_refunded_cash + cr_return_amount) AS refund
  FROM catalog_sales, catalog_returns
  WHERE cs_item_sk = cr_item_sk AND cs_order_number = cr_order_number
  GROUP BY cs_item_sk
  HAVING sum(cs_ext_list_price) > 2 * sum(cr_refunded_cash
                                          + cr_return_amount))
SELECT i_product_name, s_store_name, s_zip, d1.d_year,
       count(*) AS cnt,
       sum(ss_wholesale_cost) AS s1, sum(ss_list_price) AS s2,
       sum(ss_coupon_amt) AS s3
FROM store_sales, store_returns, cs_ui, date_dim d1,
     customer, customer_demographics cd1, household_demographics hd1,
     customer_address ad1, income_band ib1, item, store
WHERE ss_store_sk = s_store_sk
  AND ss_sold_date_sk = d1.d_date_sk
  AND ss_customer_sk = c_customer_sk
  AND ss_cdemo_sk = cd1.cd_demo_sk
  AND ss_hdemo_sk = hd1.hd_demo_sk
  AND ss_addr_sk = ad1.ca_address_sk
  AND ss_item_sk = i_item_sk
  AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND ss_item_sk = cs_ui.cs_item_sk
  AND hd1.hd_income_band_sk = ib1.ib_income_band_sk
  AND i_color IN ('maroon', 'burnished', 'dim', 'steel', 'navajo',
                  'chocolate')
  AND i_current_price BETWEEN 35 AND 45
GROUP BY i_product_name, s_store_name, s_zip, d1.d_year
ORDER BY i_product_name, s_store_name, cnt LIMIT 100"""
    oracle_sql = engine.replace("BETWEEN 35 AND 45",
                                "BETWEEN 3500 AND 4500")
    check(runner, oracle, engine, oracle_sql, ordered=True)


def test_tpcds_inventory_week_join(runner, oracle):
    check(runner, oracle,
          "SELECT w_state, count(*) FROM inventory, warehouse, date_dim "
          "WHERE inv_warehouse_sk = w_warehouse_sk "
          "AND inv_date_sk = d_date_sk AND d_year = 2000 "
          "AND inv_quantity_on_hand < 10 GROUP BY w_state")
