"""Operator tests — driven RowPagesBuilder-style (SURVEY §4 unit tier)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.expr import Call, InputRef, Literal
from trino_tpu.ops import (
    AggSpec, JoinType, SortKey, Step, filter_project, hash_aggregate,
    hash_join, limit, order_by, top_n)
from trino_tpu.page import Page


def page_of(*cols):
    arrays, typs, valids = [], [], []
    for c in cols:
        if len(c) == 3:
            a, t, v = c
        else:
            (a, t), v = c, None
        arrays.append(np.asarray(a) if not isinstance(a, np.ndarray) else a)
        typs.append(t)
        valids.append(None if v is None else np.asarray(v, dtype=bool))
    return Page.from_numpy(arrays, typs, valids=valids)


# ---------------------------------------------------------------------------
# aggregation

def test_global_aggregation():
    page = page_of(([1, 2, 3, 4], T.BIGINT), ([1.0, 2.0, 3.0, 4.0], T.DOUBLE))
    op = hash_aggregate([], [
        AggSpec("sum", 0, T.BIGINT), AggSpec("count", None, None),
        AggSpec("avg", 1, T.DOUBLE), AggSpec("min", 0, T.BIGINT),
        AggSpec("max", 1, T.DOUBLE)])
    out = jax.jit(op)(page)
    assert out.to_pylist() == [(10, 4, 2.5, 1, 4.0)]


def test_group_by_aggregation():
    page = page_of(([2, 1, 2, 1, 3], T.BIGINT), ([10.0, 20.0, 30.0, 40.0, 50.0], T.DOUBLE))
    op = hash_aggregate([0], [AggSpec("sum", 1, T.DOUBLE),
                              AggSpec("count", None, None)])
    out = jax.jit(op)(page)
    rows = sorted(out.to_pylist())
    assert rows == [(1, 60.0, 2), (2, 40.0, 2), (3, 50.0, 1)]


def test_group_by_null_key_and_null_inputs():
    page = page_of(([1, 1, 2, 2], T.BIGINT, [1, 0, 1, 0]),
                   ([5.0, 6.0, 7.0, 8.0], T.DOUBLE, [1, 1, 0, 1]))
    op = hash_aggregate([0], [AggSpec("sum", 1, T.DOUBLE),
                              AggSpec("count", 1, T.DOUBLE)])
    out = jax.jit(op)(page)
    rows = out.to_pylist()
    # nulls group together (one NULL group from rows 1 & 3)
    by_key = {r[0]: r[1:] for r in rows}
    assert by_key[1] == (5.0, 1)
    assert by_key[2] == (None, 0)  # sum of all-null group is NULL, count 0
    assert by_key[None] == (14.0, 2)
    assert len(rows) == 3


def test_group_by_respects_num_rows():
    page = page_of(([1, 2, 1, 2, 9, 9], T.BIGINT), ([1, 1, 1, 1, 1, 1], T.BIGINT))
    page = Page(page.columns, jnp.asarray(4, jnp.int32))  # last two rows dead
    op = hash_aggregate([0], [AggSpec("sum", 1, T.BIGINT)])
    out = jax.jit(op)(page)
    assert sorted(out.to_pylist()) == [(1, 2), (2, 2)]


def test_partial_then_final_aggregation():
    page = page_of(([1, 2, 1, 2], T.BIGINT), ([1.0, 2.0, 3.0, 4.0], T.DOUBLE))
    partial = hash_aggregate([0], [AggSpec("avg", 1, T.DOUBLE)],
                             step=Step.PARTIAL)
    p_out = jax.jit(partial)(page)
    # partial layout: key, avg_sum, avg_count
    assert p_out.num_columns == 3
    final = hash_aggregate([0], [AggSpec("avg", 1, T.DOUBLE)], step=Step.FINAL,
                           partial_state_channels=[[1, 2]])
    f_out = jax.jit(final)(p_out)
    assert sorted(f_out.to_pylist()) == [(1, 2.0), (2, 3.0)]


def test_aggregation_filter_mask_channel():
    # count(x) FILTER (WHERE flag)
    page = page_of(([1, 1, 1, 1], T.BIGINT), ([10, 20, 30, 40], T.BIGINT),
                   ([True, False, True, False], T.BOOLEAN))
    op = hash_aggregate([0], [AggSpec("sum", 1, T.BIGINT, mask_channel=2)])
    out = jax.jit(op)(page)
    assert out.to_pylist() == [(1, 40)]


# ---------------------------------------------------------------------------
# join

def test_inner_join_duplicate_keys():
    probe = page_of(([1, 2, 3, 2], T.BIGINT), ([10.0, 20.0, 30.0, 40.0], T.DOUBLE))
    build = page_of(([2, 2, 1], T.BIGINT), ([100, 200, 300], T.BIGINT))
    op = hash_join([0], [0], JoinType.INNER, output_capacity=8)
    out, total = jax.jit(op)(probe, build)
    assert int(total) == 5  # 1x1 + 2x2 + 0 + 2x2... probe row 2 & 4 each match 2
    rows = sorted(out.to_pylist())
    assert rows == [(1, 10.0, 1, 300), (2, 20.0, 2, 100), (2, 20.0, 2, 200),
                    (2, 40.0, 2, 100), (2, 40.0, 2, 200)]


def test_join_overflow_detection():
    probe = page_of(([1, 1], T.BIGINT))
    build = page_of(([1, 1, 1], T.BIGINT))
    op = hash_join([0], [0], JoinType.INNER, output_capacity=4)
    out, total = jax.jit(op)(probe, build)
    assert int(total) == 6 and int(out.num_rows) == 4  # truncated, flagged


def test_left_join_null_extension():
    probe = page_of(([1, 5], T.BIGINT))
    build = page_of(([1], T.BIGINT), ([99], T.BIGINT))
    op = hash_join([0], [0], JoinType.LEFT, output_capacity=4)
    out, _ = jax.jit(op)(probe, build)
    assert sorted(out.to_pylist(), key=str) == [(1, 1, 99), (5, None, None)]


def test_null_keys_never_match():
    probe = page_of(([1, 2], T.BIGINT, [0, 1]))
    build = page_of(([1, 2], T.BIGINT, [0, 1]), ([7, 8], T.BIGINT))
    op = hash_join([0], [0], JoinType.INNER, output_capacity=4)
    out, total = jax.jit(op)(probe, build)
    assert out.to_pylist() == [(2, 2, 8)]


def test_semi_and_anti_join():
    probe = page_of(([1, 2, 3, 4], T.BIGINT))
    build = page_of(([2, 4, 4], T.BIGINT))
    semi = hash_join([0], [0], JoinType.SEMI)
    out, _ = jax.jit(semi)(probe, build)
    assert [r[0] for r in out.to_pylist()] == [2, 4]
    anti = hash_join([0], [0], JoinType.ANTI)
    out, _ = jax.jit(anti)(probe, build)
    assert [r[0] for r in out.to_pylist()] == [1, 3]


def test_composite_semi_anti_join():
    # exercises the verified expansion path (scatter-back per probe row)
    probe = page_of(([1, 1, 2, 3], T.BIGINT), ([10, 20, 10, 30], T.BIGINT))
    build = page_of(([1, 2, 2], T.BIGINT), ([10, 10, 10], T.BIGINT))
    semi = hash_join([0, 1], [0, 1], JoinType.SEMI)
    out, total = jax.jit(semi)(probe, build)
    assert sorted(r[:2] for r in out.to_pylist()) == [(1, 10), (2, 10)]
    assert int(total) == 2
    anti = hash_join([0, 1], [0, 1], JoinType.ANTI)
    out, total = jax.jit(anti)(probe, build)
    assert sorted(r[:2] for r in out.to_pylist()) == [(1, 20), (3, 30)]
    assert int(total) == 2


def test_composite_semi_overflow_contract():
    # cap too small for the hash expansion -> total > cap signals re-run
    probe = page_of(([1, 1, 1], T.BIGINT), ([5, 5, 5], T.BIGINT))
    build = page_of(([1] * 8, T.BIGINT), ([5] * 8, T.BIGINT))
    semi = hash_join([0, 1], [0, 1], JoinType.SEMI, output_capacity=4)
    out, total = jax.jit(semi)(probe, build)
    assert int(total) > 4  # 24 hash matches exceed cap; executor must re-run
    big = hash_join([0, 1], [0, 1], JoinType.SEMI, output_capacity=32)
    out, total = jax.jit(big)(probe, build)
    assert int(total) == 3 and int(out.num_rows) == 3


def test_composite_key_join():
    probe = page_of(([1, 1, 2], T.BIGINT), ([10, 20, 10], T.BIGINT))
    build = page_of(([1, 2], T.BIGINT), ([10, 10], T.BIGINT), ([111, 222], T.BIGINT))
    op = hash_join([0, 1], [0, 1], JoinType.INNER, output_capacity=6)
    out, _ = jax.jit(op)(probe, build)
    assert sorted(out.to_pylist()) == [(1, 10, 1, 10, 111), (2, 10, 2, 10, 222)]


def test_join_under_single_jit_with_filter():
    probe = page_of((np.arange(100) % 10, T.BIGINT), (np.arange(100, dtype=float), T.DOUBLE))
    build = page_of(([3, 7], T.BIGINT), ([333, 777], T.BIGINT))
    join_op = hash_join([0], [0], JoinType.INNER, output_capacity=128)

    @jax.jit
    def frag(p, b):
        out, total = join_op(p, b)
        agg = hash_aggregate([0], [AggSpec("count", None, None)])(out)
        return agg, total

    agg, total = frag(probe, build)
    assert int(total) == 20
    assert sorted(agg.to_pylist()) == [(3, 10), (7, 10)]


# ---------------------------------------------------------------------------
# sort / topn / limit

def test_order_by_asc_desc_nulls():
    page = page_of(([3, 1, 2, 1], T.BIGINT, [1, 1, 0, 1]),
                   ([1.0, 2.0, 3.0, 4.0], T.DOUBLE))
    # ASC: nulls last (Trino default)
    out = jax.jit(order_by([SortKey(0, ascending=True)]))(page)
    assert [r[0] for r in out.to_pylist()] == [1, 1, 3, None]
    # DESC: nulls first
    out = jax.jit(order_by([SortKey(0, ascending=False)]))(page)
    assert [r[0] for r in out.to_pylist()] == [None, 3, 1, 1]
    # stability: equal keys keep input order
    out = jax.jit(order_by([SortKey(0)]))(page)
    assert out.to_pylist()[0] == (1, 2.0) and out.to_pylist()[1] == (1, 4.0)


def test_order_by_multi_key_and_float_desc():
    page = page_of(([1, 1, 2], T.BIGINT), ([5.0, 9.0, 1.0], T.DOUBLE))
    out = jax.jit(order_by([SortKey(0, True), SortKey(1, False)]))(page)
    assert out.to_pylist() == [(1, 9.0), (1, 5.0), (2, 1.0)]


def test_nan_sorts_largest():
    page = page_of(([1.0, float("nan"), 0.5], T.DOUBLE))
    out = jax.jit(order_by([SortKey(0, True)]))(page)
    vals = [r[0] for r in out.to_pylist()]
    assert vals[0] == 0.5 and vals[1] == 1.0 and np.isnan(vals[2])
    out = jax.jit(order_by([SortKey(0, False)]))(page)
    vals = [r[0] for r in out.to_pylist()]
    assert np.isnan(vals[0]) and vals[1] == 1.0


def test_top_n_and_limit():
    page = page_of((np.arange(10)[::-1].copy(), T.BIGINT))
    out = jax.jit(top_n(3, [SortKey(0, True)]))(page)
    assert [r[0] for r in out.to_pylist()] == [0, 1, 2]
    out = jax.jit(limit(4))(page)
    assert int(out.num_rows) == 4


def test_filter_project_operator():
    page = page_of(([1, 2, 3, 4], T.BIGINT), ([2.0, 4.0, 6.0, 8.0], T.DOUBLE))
    op = filter_project(
        Call("gt", (InputRef(0, T.BIGINT), Literal(1, T.BIGINT)), T.BOOLEAN),
        [Call("multiply", (InputRef(1, T.DOUBLE), Literal(10.0, T.DOUBLE)), T.DOUBLE)])
    out = jax.jit(op)(page)
    assert out.to_pylist() == [(40.0,), (60.0,), (80.0,)]


def test_min_max_varchar_keeps_dictionary():
    page = page_of(([1, 1, 2], T.BIGINT),
                   (np.array(["bb", "aa", "cc"], dtype=object), T.VARCHAR))
    op = hash_aggregate([0], [AggSpec("min", 1, T.VARCHAR),
                              AggSpec("max", 1, T.VARCHAR)])
    out = jax.jit(op)(page)
    assert sorted(out.to_pylist()) == [(1, "aa", "bb"), (2, "cc", "cc")]


def test_composite_join_total_after_collision_filter():
    probe = page_of(([1, 2], T.BIGINT), ([10, 20], T.BIGINT))
    build = page_of(([1, 2], T.BIGINT), ([10, 99], T.BIGINT))
    op = hash_join([0, 1], [0, 1], JoinType.INNER, output_capacity=4)
    out, total = jax.jit(op)(probe, build)
    # only (1,10) truly matches; total must reflect the post-verify count
    assert int(out.num_rows) == 1 and int(total) == 1


# ---------------------------------------------------------------------------
# outer joins (FULL/RIGHT) + composite-key verification

def test_full_join_kernel_and_finisher():
    from trino_tpu.ops.join import unmatched_build_page
    probe = page_of(([1, 5], T.BIGINT))
    build = page_of(([1, 7], T.BIGINT), ([11, 77], T.BIGINT))
    op = hash_join([0], [0], JoinType.FULL, output_capacity=4)
    out, total, bm = jax.jit(op)(probe, build)
    assert sorted(out.to_pylist(), key=str) == [(1, 1, 11), (5, None, None)]
    assert list(np.asarray(bm)) == [True, False]
    fin = unmatched_build_page(((T.BIGINT, None),))
    tail = jax.jit(fin)(build, bm)
    assert tail.to_pylist() == [(None, 7, 77)]


def test_full_join_null_keys_both_sides():
    probe = page_of(([1, 2], T.BIGINT, [1, 0]))
    build = page_of(([1, 3], T.BIGINT, [0, 1]), ([10, 30], T.BIGINT))
    op = hash_join([0], [0], JoinType.FULL, output_capacity=8)
    out, total, bm = jax.jit(op)(probe, build)
    # null probe key never matches -> both probe rows null-extended
    assert sorted(out.to_pylist(), key=str) == [
        (1, None, None), (None, None, None)]
    assert list(np.asarray(bm)) == [False, False]


def test_left_composite_collision_rescue(monkeypatch):
    # force total hash collision: every composite key hashes identically, so
    # verification must both drop fabricated matches AND rescue probe rows
    # whose every candidate was a collision (ADVICE r1/r2 carryover)
    import trino_tpu.ops.join as J
    monkeypatch.setattr(J, "_mix64", lambda x: jnp.zeros_like(
        x.astype(jnp.uint64)))
    probe = page_of(([1, 2], T.BIGINT), ([10, 20], T.BIGINT))
    build = page_of(([1, 9], T.BIGINT), ([10, 99], T.BIGINT),
                    ([111, 999], T.BIGINT))
    op = hash_join([0, 1], [0, 1], JoinType.LEFT, output_capacity=8)
    out, total = op(probe, build)  # not jit: monkeypatch must stay visible
    assert sorted(out.to_pylist(), key=str) == [
        (1, 10, 1, 10, 111), (2, 20, None, None, None)]
    assert int(total) == 2


def test_mark_join_build_null_3vl():
    # IN-subquery 3VL: no match + NULL on build side => NULL, not FALSE
    probe = page_of(([1, 4, 7], T.BIGINT, [1, 1, 0]))
    build = page_of(([1, 2], T.BIGINT, [1, 0]))
    op = hash_join([0], [0], JoinType.MARK)
    out, _ = jax.jit(op)(probe, build)
    marks = [r[-1] for r in out.to_pylist()]
    # 1 matches -> TRUE; 4 has no match but build has NULL -> NULL;
    # NULL probe vs non-empty build -> NULL
    assert marks == [True, None, None]


def test_mark_join_no_build_nulls_definite_false():
    probe = page_of(([1, 4], T.BIGINT))
    build = page_of(([1, 2], T.BIGINT))
    op = hash_join([0], [0], JoinType.MARK)
    out, _ = jax.jit(op)(probe, build)
    assert [r[-1] for r in out.to_pylist()] == [True, False]
