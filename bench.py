"""Benchmark: TPC-H q6 at SF1 end-to-end wall-clock on the real chip.

Measurement ladder config (BASELINE.md): tiny-q6 smoke is covered by tests;
this times SF1 q6 through the full engine (parse -> plan -> optimize ->
execute, host paging + device kernels). Prints ONE JSON line.

vs_baseline: the reference repo publishes no numbers (BASELINE.md); the
denominator used here is 1.0 s — the ballpark single-node Trino q6 SF1
wall-clock its LocalQueryRunner benchmarks show on server CPUs — so
vs_baseline > 1 means faster than that estimate.
"""

import json
import time

Q6 = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
  AND l_discount BETWEEN 0.06 - 0.01 AND 0.06 + 0.01
  AND l_quantity < 24
"""

BASELINE_ESTIMATE_S = 1.0


def main():
    from trino_tpu.exec import LocalQueryRunner

    runner = LocalQueryRunner.tpch("sf1")
    # generation + warm-up (compile) run, untimed
    warm = runner.execute(Q6)
    assert len(warm.rows) == 1

    times = []
    for _ in range(3):
        t0 = time.perf_counter()
        out = runner.execute(Q6)
        times.append(time.perf_counter() - t0)
    wall = sorted(times)[1]  # median of 3
    print(json.dumps({
        "metric": "tpch_q6_sf1_wall_s",
        "value": round(wall, 4),
        "unit": "s",
        "vs_baseline": round(BASELINE_ESTIMATE_S / wall, 3),
    }))


if __name__ == "__main__":
    main()
