"""Plan optimizer: rewrite rules + exchange placement + fragmenter.

Reference parity: sql/planner/PlanOptimizers.java (the ~60-pass pipeline) with
the rules that carry TPC-H/DS (SURVEY.md §2.3):
- predicate pushdown incl. cross-join -> inner-join criteria extraction
  (optimizations/PredicatePushDown.java + EliminateCrossJoins intent)
- projection/column pruning (PruneUnreferencedOutputs)
- identity-projection removal, adjacent filter/project merging
- Limit+Sort -> TopN (CreatePartialTopN's single-node half)
- domain extraction into scans (PushPredicateIntoTableScan + DomainTranslator)
- limit pushdown into scans (PushLimitIntoTableScan)
- join distribution choice by stats (DetermineJoinDistributionType)
- AddExchanges: REMOTE exchange placement by partitioning properties —
  on the TPU these lower to mesh collectives (SURVEY §2.11): repartition =
  all_to_all, broadcast = all_gather, gather = single-shard collect
- partial aggregation below exchanges (PushPartialAggregationThroughExchange)
- PlanFragmenter.createSubPlans: cut at REMOTE exchanges
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from trino_tpu import types as T
from trino_tpu.expr.ir import (Call, Literal, RowExpression, SpecialForm,
                               SpecialKind, SymbolRef)
from trino_tpu.metadata import Metadata, Session
from trino_tpu.planner.nodes import (
    AggCall, AggregationNode, AggStep, DistinctLimitNode,
    EnforceSingleRowNode, ExchangeKind, ExchangeNode, ExchangeScope,
    FilterNode, GroupIdNode, JoinClause, JoinDistribution, JoinKind, JoinNode,
    LimitNode, OffsetNode, Ordering, OutputNode, PlanNode, ProjectNode,
    SemiJoinNode, SortNode, Symbol, TableScanNode, TopNNode, UnionNode,
    UnnestNode, ValuesNode, WindowNode, TableWriterNode,
    AssignUniqueIdNode)
from trino_tpu.predicate import Domain, Range, TupleDomain


def conjuncts(e: Optional[RowExpression]) -> List[RowExpression]:
    if e is None:
        return []
    if isinstance(e, SpecialForm) and e.kind is SpecialKind.AND:
        out = []
        for a in e.args:
            out.extend(conjuncts(a))
        return out
    return [e]


def combine(parts: Sequence[RowExpression]) -> Optional[RowExpression]:
    parts = list(parts)
    if not parts:
        return None
    out = parts[0]
    for p in parts[1:]:
        out = SpecialForm(SpecialKind.AND, (out, p), T.BOOLEAN)
    return out


def disjuncts(e: Optional[RowExpression]) -> List[RowExpression]:
    if e is None:
        return []
    if isinstance(e, SpecialForm) and e.kind is SpecialKind.OR:
        out = []
        for a in e.args:
            out.extend(disjuncts(a))
        return out
    return [e]


def combine_or(parts: Sequence[RowExpression]) -> RowExpression:
    out = parts[0]
    for p in parts[1:]:
        out = SpecialForm(SpecialKind.OR, (out, p), T.BOOLEAN)
    return out


def extract_common_predicates(e: RowExpression) -> RowExpression:
    """(A ∧ B) ∨ (A ∧ C)  ->  A ∧ (B ∨ C), recursively
    (sql/planner/iterative/rule/ExtractCommonPredicatesExpressionRewriter).

    Kleene 3VL distributivity makes the rewrite exact. Load-bearing for
    q19-style filters: the factored-out equality conjunct becomes a join
    clause instead of a post-cross-join residual."""
    if not isinstance(e, SpecialForm):
        return e
    if e.kind is SpecialKind.AND:
        parts = [extract_common_predicates(c) for c in conjuncts(e)]
        return combine(parts)
    if e.kind is SpecialKind.OR:
        branches = [conjuncts(extract_common_predicates(d))
                    for d in disjuncts(e)]
        common = [c for c in branches[0]
                  if all(c in b for b in branches[1:])]
        if not common:
            return combine_or([combine(b) for b in branches])
        residuals = []
        for b in branches:
            rem = [c for c in b if c not in common]
            if not rem:
                # x ∨ (x ∧ y) = x: this branch absorbs the whole OR
                return combine(common)
            residuals.append(combine(rem))
        return combine(common + [combine_or(residuals)])
    return e


def symbols_in(e: RowExpression) -> Set[str]:
    out: Set[str] = set()

    def visit(x):
        if isinstance(x, SymbolRef):
            out.add(x.name)
        for c in x.children():
            visit(c)
    visit(e)
    return out


def _substitute(e: RowExpression,
                mapping: Dict[str, RowExpression]) -> RowExpression:
    if isinstance(e, SymbolRef):
        return mapping.get(e.name, e)
    if isinstance(e, Call):
        return Call(e.name, tuple(_substitute(a, mapping) for a in e.args),
                    e.type)
    if isinstance(e, SpecialForm):
        return SpecialForm(e.kind,
                           tuple(_substitute(a, mapping) for a in e.args),
                           e.type)
    return e


# ---------------------------------------------------------------------------
# generic bottom-up rewriting


def rewrite_sources(node: PlanNode, fn) -> PlanNode:
    new_sources = [fn(s) for s in node.sources]
    if all(a is b for a, b in zip(new_sources, node.sources)):
        return node
    return node.with_sources(new_sources)


class Rule:
    """One rewrite; return None when not applicable (iterative/Rule.java)."""

    def apply(self, node: PlanNode, ctx: "OptimizerContext"
              ) -> Optional[PlanNode]:
        raise NotImplementedError


@dataclasses.dataclass
class OptimizerContext:
    metadata: Metadata
    session: Session
    stats: "StatsEstimator"


def run_rules(root: PlanNode, rules: Sequence[Rule], ctx: OptimizerContext,
              max_passes: int = 10) -> PlanNode:
    """Fixpoint bottom-up rewriter (IterativeOptimizer.exploreGroup without
    the Memo: plans here are small enough to rewrite directly)."""
    for _ in range(max_passes):
        changed = [False]

        def walk(node: PlanNode) -> PlanNode:
            node = rewrite_sources(node, walk)
            for rule in rules:
                out = rule.apply(node, ctx)
                if out is not None and out is not node:
                    changed[0] = True
                    node = rewrite_sources(out, walk)
            return node

        root = walk(root)
        if not changed[0]:
            break
    return root


# ---------------------------------------------------------------------------
# stats (cost/StatsCalculator condensed)


class StatsEstimator:
    """Row-count + NDV estimation driving join distribution/ordering.

    cost/ parity (FilterStatsCalculator.java, JoinStatsRule.java,
    StatsCalculator): per-column distinct counts propagate bottom-up
    (scan stats -> filter scaling -> join/aggregate pass-through), join
    cardinality uses the classic |L||R| / max(ndv_l, ndv_r) with
    exponential damping across clauses, GROUP BY uses the NDV product,
    and LIKE selectivity comes from the connector's dictionary pool —
    the round-4 q9 join-order regression was exactly a missing
    dictionary-LIKE estimate plus FK columns claiming table-sized NDVs.
    """

    FILTER_SELECTIVITY = 0.33
    RANGE_SELECTIVITY = 0.3
    SEMI_SELECTIVITY = 0.5
    LIKE_SELECTIVITY = 0.25      # fallback when no dictionary answers

    def __init__(self, metadata: Metadata):
        self.metadata = metadata
        self._cache: Dict[int, float] = {}
        self._ndv_cache: Dict[Tuple[int, str], Optional[float]] = {}

    def rows(self, node: PlanNode) -> float:
        key = node.id
        if key not in self._cache:
            self._cache[key] = self._estimate(node)
        return self._cache[key]

    # ------------------------------------------------------------- NDV

    def ndv(self, node: PlanNode, sym: str) -> Optional[float]:
        """Distinct count of `sym` in node's output, None when unknown."""
        key = (node.id, sym)
        if key not in self._ndv_cache:
            self._ndv_cache[key] = self._ndv(node, sym)
        return self._ndv_cache[key]

    def _ndv(self, node: PlanNode, sym: str) -> Optional[float]:
        if isinstance(node, TableScanNode):
            try:
                stats = self.metadata.get_table_statistics(
                    node.catalog, node.table)
            except Exception:
                return None
            for s, col in node.assignments:
                if s.name == sym:
                    cs = (stats.columns or {}).get(col.name)
                    if cs is not None and cs.distinct_count:
                        return min(float(cs.distinct_count),
                                   self.rows(node))
                    return None
            return None
        if isinstance(node, ProjectNode):
            for s, e in node.assignments:
                if s.name == sym:
                    if isinstance(e, SymbolRef):
                        return self._capped(node.source, e.name,
                                            self.rows(node))
                    return None
            return None
        if isinstance(node, JoinNode):
            cap = self.rows(node)
            for side in (node.left, node.right):
                if any(s.name == sym for s in side.outputs):
                    return self._capped(side, sym, cap)
            return None
        if isinstance(node, AggregationNode):
            if any(s.name == sym for s in node.group_by):
                return self._capped(node.source, sym, self.rows(node))
            return None
        if isinstance(node, SemiJoinNode):
            return self._capped(node.source, sym, self.rows(node))
        if node.sources:
            return self._capped(node.sources[0], sym, self.rows(node))
        return None

    def _capped(self, src: PlanNode, sym: str, cap: float
                ) -> Optional[float]:
        n = self.ndv(src, sym)
        return None if n is None else min(n, max(cap, 1.0))

    def _scan_of(self, node: PlanNode, sym: str
                 ) -> Optional[Tuple[TableScanNode, str]]:
        """Descend identity chains to the scan providing `sym` (for the
        connector LIKE-selectivity hook)."""
        while True:
            if isinstance(node, TableScanNode):
                for s, col in node.assignments:
                    if s.name == sym:
                        return node, col.name
                return None
            if isinstance(node, ProjectNode):
                for s, e in node.assignments:
                    if s.name == sym:
                        if isinstance(e, SymbolRef):
                            sym = e.name
                            break
                        return None
                else:
                    return None
                node = node.source
            elif isinstance(node, FilterNode):
                node = node.source
            else:
                return None

    # ------------------------------------------------------ selectivity

    def _scan_selectivity(self, node: TableScanNode, stats) -> float:
        """Domain-based selectivity per constrained column
        (FilterStatsCalculator's point/range estimates)."""
        sel = 1.0
        domains = node.table.constraint.domains
        if domains is None:
            return sel
        for col, dom in domains.items():
            ndv = None
            cstats = (stats.columns or {}).get(col) if stats else None
            if cstats is not None and cstats.distinct_count:
                ndv = float(cstats.distinct_count)
            values = dom.values_if_discrete()
            if values is not None:
                k = len(values)
                sel *= min(1.0, k / ndv) if ndv else 0.1
            else:
                sel *= self.RANGE_SELECTIVITY
        return max(sel, 1e-6)

    def _conjunct_selectivity(self, p: RowExpression,
                              source: Optional[PlanNode]) -> float:
        def sym_lit(call):
            if len(call.args) == 2 and isinstance(call.args[0], SymbolRef) \
                    and isinstance(call.args[1], Literal):
                return call.args[0].name
            return None

        if isinstance(p, Call) and p.name == "eq":
            if source is not None:
                s = sym_lit(p)
                n = self.ndv(source, s) if s else None
                if n:
                    return 1.0 / n
            return 0.1
        if isinstance(p, Call) and p.name in ("lt", "le", "gt", "ge"):
            return self.RANGE_SELECTIVITY
        if isinstance(p, Call) and p.name == "like" and source is not None:
            if isinstance(p.args[0], SymbolRef) and \
                    isinstance(p.args[1], Literal):
                hit = self._scan_of(source, p.args[0].name)
                if hit is not None:
                    scan, col = hit
                    try:
                        conn = self.metadata.connector(scan.catalog)
                        est = conn.metadata.estimate_like_selectivity(
                            scan.table, col, p.args[1].value)
                        if est is not None:
                            return max(est, 1e-6)
                    except Exception:
                        pass
            return self.LIKE_SELECTIVITY
        if isinstance(p, SpecialForm) and p.kind is SpecialKind.BETWEEN:
            return self.RANGE_SELECTIVITY
        if isinstance(p, SpecialForm) and p.kind is SpecialKind.IN:
            k = len(p.args) - 1
            if source is not None and isinstance(p.args[0], SymbolRef):
                n = self.ndv(source, p.args[0].name)
                if n:
                    return min(1.0, k / n)
            return min(1.0, 0.1 * k)
        if isinstance(p, SpecialForm) and p.kind is SpecialKind.NOT:
            return max(1e-6, 1.0 - self._conjunct_selectivity(
                p.args[0], source))
        return 0.9  # UNKNOWN_FILTER_COEFFICIENT

    def _filter_selectivity(self, pred: RowExpression,
                            source: Optional[PlanNode] = None) -> float:
        sel = 1.0
        for p in conjuncts(pred):
            sel *= self._conjunct_selectivity(p, source)
        return max(sel, 1e-6)

    # ------------------------------------------------------------ rows

    @staticmethod
    def join_cardinality(lr: float, rr: float,
                         clause_ndvs) -> float:
        """|L JOIN R| = |L||R| * prod of per-clause 1/max(ndv), clauses
        sorted strongest-first with exponential damping (correlated
        composite keys would otherwise be catastrophically under-
        estimated — the SQL Server/Trino compromise)."""
        sels = []
        for nl, nr in clause_ndvs:
            d = max(nl or 0.0, nr or 0.0)
            if d > 0:
                sels.append(1.0 / d)
            else:
                sels.append(1.0 / max(min(lr, rr), 1.0))  # PK-FK fallback
        out = lr * rr
        for i, s in enumerate(sorted(sels)):
            out *= s ** (1.0 / (2 ** i))
        return max(out, 1.0)

    def _estimate(self, node: PlanNode) -> float:
        if isinstance(node, TableScanNode):
            stats = self.metadata.get_table_statistics(node.catalog,
                                                       node.table)
            base = stats.row_count if stats.row_count is not None else 1e6
            if node.table.limit is not None:
                base = min(base, float(node.table.limit))
            if not node.table.constraint.is_all():
                base *= self._scan_selectivity(node, stats)
            return max(base, 1.0)
        if isinstance(node, ValuesNode):
            return float(len(node.rows))
        if isinstance(node, FilterNode):
            return max(1.0, self.rows(node.source)
                       * self._filter_selectivity(node.predicate,
                                                  node.source))
        if isinstance(node, (LimitNode, TopNNode, DistinctLimitNode)):
            return min(self.rows(node.source), float(node.count))
        if isinstance(node, AggregationNode):
            src = self.rows(node.source)
            if not node.group_by:
                return 1.0
            # group count = NDV product, capped by input rows
            prod = 1.0
            known = True
            for s in node.group_by:
                n = self.ndv(node.source, s.name)
                if n is None:
                    known = False
                    break
                prod *= n
            if known:
                return max(1.0, min(src, prod))
            return max(1.0, src ** 0.75)
        if isinstance(node, JoinNode):
            lr = self.rows(node.left)
            rr = self.rows(node.right)
            if node.kind == JoinKind.CROSS and not node.criteria:
                return lr * rr
            clause_ndvs = [(self.ndv(node.left, c.left.name),
                            self.ndv(node.right, c.right.name))
                           for c in node.criteria]
            out = self.join_cardinality(lr, rr, clause_ndvs)
            if node.kind == JoinKind.LEFT:
                out = max(out, lr)
            elif node.kind == JoinKind.RIGHT:
                out = max(out, rr)
            elif node.kind == JoinKind.FULL:
                out = max(out, lr, rr)
            if node.filter is not None:
                out *= self.FILTER_SELECTIVITY
            return max(out, 1.0)
        if isinstance(node, SemiJoinNode):
            return self.rows(node.source)
        if isinstance(node, UnionNode):
            return sum(self.rows(c) for c in node.children)
        if isinstance(node, GroupIdNode):
            return self.rows(node.source) * len(node.grouping_sets)
        if node.sources:
            return self.rows(node.sources[0])
        return 1e6


# ---------------------------------------------------------------------------
# rules


_FOLD_ARITH = {
    "add": lambda a, b: a + b,
    "subtract": lambda a, b: a - b,
    "multiply": lambda a, b: a * b,
}
_FOLD_CMP = {
    "eq": lambda a, b: a == b, "ne": lambda a, b: a != b,
    "lt": lambda a, b: a < b, "le": lambda a, b: a <= b,
    "gt": lambda a, b: a > b, "ge": lambda a, b: a >= b,
}


def fold_constants(e: RowExpression) -> RowExpression:
    """ExpressionInterpreter-lite (sql/planner/ExpressionInterpreter.java
    partial evaluation): fold arithmetic/comparisons over literals so
    `BETWEEN 1200 AND (1200 + 11)` becomes domain-extractable and reaches
    scan pushdown. Division/modulo keep their kernel rounding semantics
    (not folded); decimal +,-,* fold exactly in scaled-int space because
    the translator already aligned argument scales."""
    if isinstance(e, Call):
        args = tuple(fold_constants(a) for a in e.args)
        e = Call(e.name, args, e.type)
        if len(args) == 2 and all(
                isinstance(a, Literal) and a.value is not None
                and isinstance(a.value, (int, float))
                and not isinstance(a.value, bool) for a in args):
            a, b = args
            if e.name in _FOLD_ARITH and a.type == b.type == e.type:
                return Literal(_FOLD_ARITH[e.name](a.value, b.value),
                               e.type)
            if e.name in _FOLD_CMP and a.type == b.type:
                return Literal(_FOLD_CMP[e.name](a.value, b.value),
                               e.type)
        if e.name == "negate" and len(args) == 1 and \
                isinstance(args[0], Literal) and \
                args[0].value is not None and e.type == args[0].type:
            return Literal(-args[0].value, e.type)
        return e
    if isinstance(e, SpecialForm):
        args = tuple(fold_constants(a) for a in e.args)
        return SpecialForm(e.kind, args, e.type)
    return e


class FoldConstants(Rule):
    def apply(self, node, ctx):
        if isinstance(node, FilterNode):
            folded = fold_constants(node.predicate)
            if folded != node.predicate:
                return FilterNode(node.source, folded)
        if isinstance(node, ProjectNode):
            assigns = tuple((s, fold_constants(x))
                            for s, x in node.assignments)
            if assigns != node.assignments:
                return ProjectNode(node.source, assigns)
        return None


class ExtractCommonPredicates(Rule):
    def apply(self, node: PlanNode, ctx: "OptimizerContext"
              ) -> Optional[PlanNode]:
        if not isinstance(node, FilterNode):
            return None
        new = extract_common_predicates(node.predicate)
        if new == node.predicate:
            return None
        return FilterNode(node.source, new)


class MergeFilters(Rule):
    def apply(self, node, ctx):
        if isinstance(node, FilterNode) and isinstance(node.source,
                                                       FilterNode):
            pred = combine(conjuncts(node.predicate) +
                           conjuncts(node.source.predicate))
            return FilterNode(node.source.source, pred)
        return None


class RemoveIdentityProjections(Rule):
    def apply(self, node, ctx):
        if isinstance(node, ProjectNode) and node.is_identity() and \
                tuple(node.outputs) == tuple(node.source.outputs):
            return node.source
        return None


class MergeAdjacentProjects(Rule):
    """InlineProjections: project(project(x)) -> project(x) when safe."""

    def apply(self, node, ctx):
        if not (isinstance(node, ProjectNode)
                and isinstance(node.source, ProjectNode)):
            return None
        inner = node.source
        mapping = {s.name: e for s, e in inner.assignments}
        # avoid duplicating expensive inner expressions referenced twice
        ref_counts: Dict[str, int] = {}
        for _, e in node.assignments:
            for name in symbols_in(e):
                ref_counts[name] = ref_counts.get(name, 0) + 1
        for s, e in inner.assignments:
            if not isinstance(e, (SymbolRef, Literal)) and \
                    ref_counts.get(s.name, 0) > 1:
                return None
        new_assigns = tuple(
            (s, _substitute(e, mapping)) for s, e in node.assignments)
        return ProjectNode(inner.source, new_assigns)


class EvaluateZeroLimit(Rule):
    def apply(self, node, ctx):
        if isinstance(node, LimitNode) and node.count == 0:
            return ValuesNode(tuple(node.outputs), ())
        return None


class MergeLimits(Rule):
    def apply(self, node, ctx):
        if isinstance(node, LimitNode) and isinstance(node.source, LimitNode):
            return LimitNode(node.source.source,
                             min(node.count, node.source.count))
        return None


class CreateTopN(Rule):
    """Limit over Sort -> TopN (MergeLimitWithSort.java)."""

    def apply(self, node, ctx):
        if isinstance(node, LimitNode) and isinstance(node.source, SortNode) \
                and node.count <= 100_000:
            return TopNNode(node.source.source, node.count,
                            node.source.order_by)
        return None


class CreateDistinctLimit(Rule):
    def apply(self, node, ctx):
        if isinstance(node, LimitNode) and \
                isinstance(node.source, AggregationNode) and \
                not node.source.aggregations and \
                tuple(node.source.group_by) == tuple(node.source.outputs):
            return DistinctLimitNode(node.source.source, node.count) \
                if False else None  # keep agg shape; operator later
        return None


class PushLimitThroughProject(Rule):
    def apply(self, node, ctx):
        if isinstance(node, LimitNode) and isinstance(node.source,
                                                      ProjectNode):
            return ProjectNode(LimitNode(node.source.source, node.count,
                                         node.partial),
                               node.source.assignments)
        return None


class PredicatePushDown(Rule):
    """optimizations/PredicatePushDown.java condensed:
    - through Project (substitute assignments)
    - into Join: equality conjuncts spanning both sides of a CROSS/INNER join
      become join criteria; side-local conjuncts push to that side
    - into SemiJoin source side
    - through Aggregation on group-by-only conjuncts
    - through Union (per-child substitution)
    """

    def apply(self, node, ctx):
        if not isinstance(node, FilterNode):
            return None
        parts = conjuncts(node.predicate)
        src = node.source

        if isinstance(src, ProjectNode):
            mapping = {s.name: e for s, e in src.assignments}
            # only push conjuncts whose symbols are all plain aliases or
            # cheap expressions
            pushed, kept = [], []
            for p in parts:
                subbed = _substitute(p, mapping)
                pushed.append(subbed)
            if not pushed:
                return None
            return ProjectNode(FilterNode(src.source, combine(pushed)),
                               src.assignments)

        if isinstance(src, JoinNode) and src.kind in (JoinKind.CROSS,
                                                      JoinKind.INNER):
            left_syms = {s.name for s in src.left.outputs}
            right_syms = {s.name for s in src.right.outputs}
            new_criteria = list(src.criteria)
            left_parts, right_parts, residual = [], [], []
            changed = False
            for p in parts:
                syms = symbols_in(p)
                if syms and syms <= left_syms:
                    left_parts.append(p)
                    changed = True
                elif syms and syms <= right_syms:
                    right_parts.append(p)
                    changed = True
                else:
                    eq = self._as_equi_clause(p, left_syms, right_syms)
                    if eq is not None:
                        new_criteria.append(eq)
                        changed = True
                    else:
                        residual.append(p)
            if not changed:
                return None
            left = src.left if not left_parts else FilterNode(
                src.left, combine(left_parts))
            right = src.right if not right_parts else FilterNode(
                src.right, combine(right_parts))
            kind = src.kind
            if kind == JoinKind.CROSS and new_criteria:
                kind = JoinKind.INNER
            out: PlanNode = JoinNode(kind, left, right, tuple(new_criteria),
                                     src.filter, src.distribution)
            if residual:
                out = FilterNode(out, combine(residual))
            return out

        if isinstance(src, JoinNode) and src.kind == JoinKind.LEFT:
            # push left-side-only conjuncts into the probe side
            left_syms = {s.name for s in src.left.outputs}
            left_parts, kept = [], []
            for p in parts:
                syms = symbols_in(p)
                if syms and syms <= left_syms:
                    left_parts.append(p)
                else:
                    kept.append(p)
            if not left_parts:
                return None
            left = FilterNode(src.left, combine(left_parts))
            out = JoinNode(src.kind, left, src.right, src.criteria,
                           src.filter, src.distribution)
            if kept:
                out = FilterNode(out, combine(kept))
            return out

        if isinstance(src, SemiJoinNode):
            source_syms = {s.name for s in src.source.outputs}
            pushable, kept = [], []
            for p in parts:
                syms = symbols_in(p)
                if syms and syms <= source_syms:
                    pushable.append(p)
                else:
                    kept.append(p)
            if not pushable:
                return None
            inner = FilterNode(src.source, combine(pushable))
            out = SemiJoinNode(inner, src.filtering_source, src.source_keys,
                               src.filtering_keys, src.match_symbol,
                               src.negate, src.null_aware)
            if kept:
                out = FilterNode(out, combine(kept))
            return out

        if isinstance(src, AggregationNode) and src.group_by:
            group = {s.name for s in src.group_by}
            pushable, kept = [], []
            for p in parts:
                syms = symbols_in(p)
                if syms and syms <= group:
                    pushable.append(p)
                else:
                    kept.append(p)
            if not pushable:
                return None
            inner = FilterNode(src.source, combine(pushable))
            out = AggregationNode(inner, src.group_by, src.aggregations,
                                  src.step)
            if kept:
                out = FilterNode(out, combine(kept))
            return out

        return None

    @staticmethod
    def _as_equi_clause(p: RowExpression, left_syms, right_syms
                        ) -> Optional[JoinClause]:
        if isinstance(p, Call) and p.name == "eq" and len(p.args) == 2:
            a, b = p.args
            if isinstance(a, SymbolRef) and isinstance(b, SymbolRef):
                if a.name in left_syms and b.name in right_syms:
                    return JoinClause(Symbol(a.name, a.type),
                                      Symbol(b.name, b.type))
                if b.name in left_syms and a.name in right_syms:
                    return JoinClause(Symbol(b.name, b.type),
                                      Symbol(a.name, a.type))
        return None


class PruneColumns(Rule):
    """PruneUnreferencedOutputs: narrow scans/projects to referenced symbols.

    Applied top-down from the root in one dedicated pass (prune_unreferenced)
    — kept out of the bottom-up loop.
    """

    def apply(self, node, ctx):
        return None


def prune_unreferenced(root: OutputNode) -> OutputNode:
    def needed_of(node: PlanNode, required: Set[str]) -> PlanNode:
        if isinstance(node, ProjectNode):
            kept = tuple((s, e) for s, e in node.assignments
                         if s.name in required)
            if not kept and node.assignments:
                # zero-column pages lose their capacity/row-count carrier;
                # keep the cheapest assignment (count(*) over a projection)
                kept = (min(node.assignments,
                            key=lambda se: len(str(se[1]))),)
            child_req = set()
            for _, e in kept:
                child_req |= symbols_in(e)
            src = needed_of(node.source, child_req)
            return ProjectNode(src, kept)
        if isinstance(node, FilterNode):
            req = required | symbols_in(node.predicate)
            return FilterNode(needed_of(node.source, req), node.predicate)
        if isinstance(node, TableScanNode):
            kept = tuple((s, c) for s, c in node.assignments
                         if s.name in required)
            if not kept:
                kept = node.assignments[:1]  # keep one column for count(*)
            return TableScanNode(node.catalog, node.table, kept)
        if isinstance(node, JoinNode):
            req = set(required)
            for c in node.criteria:
                req.add(c.left.name)
                req.add(c.right.name)
            if node.filter is not None:
                req |= symbols_in(node.filter)
            left = needed_of(node.left, req)
            right = needed_of(node.right, req)
            out_syms = None
            if node.kind in (JoinKind.INNER, JoinKind.LEFT):
                # PruneJoinColumns: emit only downstream-needed symbols
                # (plus residual-filter inputs, evaluated on the joined
                # layout) — join keys themselves can drop, saving the
                # probe-capacity build-column gathers
                keep = set(required)
                if node.filter is not None:
                    keep |= symbols_in(node.filter)
                full = left.outputs + right.outputs
                kept = tuple(s for s in full if s.name in keep)
                if not kept:
                    kept = left.outputs[:1]   # count(*) carrier
                if len(kept) != len(full):
                    out_syms = kept
            return JoinNode(node.kind, left, right, node.criteria,
                            node.filter, node.distribution, out_syms)
        if isinstance(node, SemiJoinNode):
            req = set(required)
            req |= {s.name for s in node.source_keys}
            filt_req = {s.name for s in node.filtering_keys}
            source = needed_of(node.source, req)
            filtering = needed_of(node.filtering_source, filt_req)
            return SemiJoinNode(source, filtering, node.source_keys,
                                node.filtering_keys, node.match_symbol,
                                node.negate, node.null_aware)
        if isinstance(node, AggregationNode):
            kept_aggs = tuple((s, a) for s, a in node.aggregations
                              if s.name in required or not required)
            req = {s.name for s in node.group_by}
            for _, a in kept_aggs:
                for arg in a.args:
                    req |= symbols_in(arg)
                if a.filter is not None:
                    req |= symbols_in(a.filter)
            return AggregationNode(needed_of(node.source, req),
                                   node.group_by, kept_aggs, node.step)
        if isinstance(node, GroupIdNode):
            req = set(required)
            for gs in node.grouping_sets:
                req |= {s.name for s in gs}
            req |= {s.name for s in node.passthrough}
            req.discard(node.group_id_symbol.name)
            return GroupIdNode(needed_of(node.source, req),
                               node.grouping_sets, node.group_id_symbol,
                               node.passthrough)
        if isinstance(node, UnnestNode):
            req = set(required) | {s.name for s in node.arrays}
            return node.with_sources([needed_of(node.source, req)])
        if isinstance(node, (SortNode, TopNNode)):
            req = set(required) | {o.symbol.name for o in node.order_by}
            src = needed_of(node.source, req)
            return node.with_sources([src])
        if isinstance(node, WindowNode):
            req = set(required)
            req |= {s.name for s in node.partition_by}
            req |= {o.symbol.name for o in node.order_by}
            for _, wf in node.functions:
                for a in wf.args:
                    req |= symbols_in(a)
            return WindowNode(needed_of(node.source, req), node.partition_by,
                              node.order_by, node.functions)
        if isinstance(node, UnionNode):
            keep_idx = [i for i, s in enumerate(node.symbols)
                        if s.name in required]
            if not keep_idx:
                keep_idx = [0]
            children = []
            for j, child in enumerate(node.children):
                child_req = {node.mappings[i][j].name for i in keep_idx}
                children.append(needed_of(child, child_req))
            return UnionNode(
                tuple(children),
                tuple(node.symbols[i] for i in keep_idx),
                tuple(node.mappings[i] for i in keep_idx))
        if isinstance(node, (LimitNode, OffsetNode, DistinctLimitNode,
                             EnforceSingleRowNode)):
            return node.with_sources(
                [needed_of(node.sources[0], set(required))])
        if isinstance(node, ValuesNode):
            return node
        if isinstance(node, ExchangeNode):
            req = set(required) | {s.name for s in node.partition_keys}
            return node.with_sources([needed_of(node.source, req)])
        if isinstance(node, (TableWriterNode, AssignUniqueIdNode)):
            req = set(required)
            if isinstance(node, TableWriterNode):
                req |= {s.name for s in node.column_symbols}
            if isinstance(node, AssignUniqueIdNode):
                req.discard(node.id_symbol.name)
            return node.with_sources([needed_of(node.sources[0], req)])
        return rewrite_sources(
            node, lambda s: needed_of(s, set(required)))

    out_req = {s.name for s in root.symbols}
    return OutputNode(needed_of(root.source, out_req), root.column_names,
                      root.symbols)


class PushPredicateIntoTableScan(Rule):
    """Extract a TupleDomain from scan-adjacent filters and offer it to the
    connector (DomainTranslator + PushPredicateIntoTableScan.java). The
    residual expression always stays — connectors treat domains as pruning
    hints (SPI contract in connector/spi.py)."""

    def apply(self, node, ctx):
        if not (isinstance(node, FilterNode)
                and isinstance(node.source, TableScanNode)):
            return None
        scan = node.source
        sym_to_col = {s.name: c for s, c in scan.assignments}
        domains: Dict[str, Domain] = {}
        for p in conjuncts(node.predicate):
            extracted = _extract_domain(p, sym_to_col)
            if extracted is None:
                extracted = _extract_or_domain(p, sym_to_col)
            if extracted is None:
                continue
            col, dom = extracted
            domains[col] = (domains[col].intersect(dom)
                            if col in domains else dom)
        if not domains:
            return None
        td = TupleDomain.with_column_domains(domains)
        if scan.table.constraint.intersect(td) == scan.table.constraint:
            return None  # already pushed
        conn = ctx.metadata.connector(scan.catalog)
        result = conn.metadata.apply_filter(scan.table, td)
        if result is None:
            return None
        new_handle, _ = result
        new_scan = TableScanNode(scan.catalog, new_handle, scan.assignments)
        return FilterNode(new_scan, node.predicate)


def _unwrap_literal(e: RowExpression) -> RowExpression:
    """See through value-preserving integer-widening casts so
    `bigint_col < 100` (planned as lt(col, cast(100))) still yields a
    pushable domain. Only integer->integer casts unwrap: a decimal/date
    cast changes the RAW representation the zone maps compare against."""
    from trino_tpu import types as _T
    if (isinstance(e, Call) and e.name == "cast" and len(e.args) == 1
            and isinstance(e.args[0], Literal)
            and isinstance(e.type, (_T.BigintType, _T.IntegerType))
            and isinstance(e.args[0].type,
                           (_T.BigintType, _T.IntegerType))):
        return Literal(e.args[0].value, e.type)
    return e


def _extract_domain(p: RowExpression, sym_to_col
                    ) -> Optional[Tuple[str, Domain]]:
    if not (isinstance(p, Call) and len(p.args) == 2):
        return None
    a, b = (_unwrap_literal(x) for x in p.args)
    if isinstance(a, SymbolRef) and isinstance(b, Literal) and \
            b.value is not None and a.name in sym_to_col:
        col, val, op = sym_to_col[a.name].name, b.value, p.name
    elif isinstance(b, SymbolRef) and isinstance(a, Literal) and \
            a.value is not None and b.name in sym_to_col:
        col, val = sym_to_col[b.name].name, a.value
        op = {"lt": "gt", "le": "ge", "gt": "lt", "ge": "le"}.get(
            p.name, p.name)
    else:
        return None
    typ = p.args[0].type
    if op == "eq":
        return col, Domain.single_value(typ, val)
    if op == "lt":
        return col, Domain.from_range(typ, Range.less_than(val))
    if op == "le":
        return col, Domain.from_range(typ, Range.less_equal(val))
    if op == "gt":
        return col, Domain.from_range(typ, Range.greater_than(val))
    if op == "ge":
        return col, Domain.from_range(typ, Range.greater_equal(val))
    return None


def _extract_or_domain(p: RowExpression, sym_to_col
                       ) -> Optional[Tuple[str, Domain]]:
    """Disjunctions over ONE column union into a multi-range domain:
    `k IN (...)` (desugared to an OR-chain of eq by plan time) and ORed
    range predicates like `(k >= 1 AND k < 5) OR k = 9`. Any branch that
    constrains a different column — or nothing extractable — poisons the
    whole disjunction (the OR is then not a row filter on one column)."""
    if not (isinstance(p, SpecialForm) and p.kind is SpecialKind.OR):
        return None
    out_col: Optional[str] = None
    out_dom: Optional[Domain] = None
    stack = list(p.args)
    while stack:
        branch = stack.pop()
        if isinstance(branch, SpecialForm) and \
                branch.kind is SpecialKind.OR:
            stack.extend(branch.args)
            continue
        # a branch may be a conjunctive range over the column
        branch_dom: Optional[Domain] = None
        for c in conjuncts(branch):
            got = _extract_domain(c, sym_to_col) \
                or _extract_or_domain(c, sym_to_col)
            if got is None:
                return None
            col, d = got
            if out_col is None:
                out_col = col
            elif col != out_col:
                return None
            branch_dom = d if branch_dom is None \
                else branch_dom.intersect(d)
        if branch_dom is None:
            return None
        out_dom = branch_dom if out_dom is None \
            else out_dom.union(branch_dom)
    if out_col is None or out_dom is None:
        return None
    return out_col, out_dom


class PushLimitIntoTableScan(Rule):
    def apply(self, node, ctx):
        if not (isinstance(node, LimitNode)
                and isinstance(node.source, TableScanNode)):
            return None
        scan = node.source
        conn = ctx.metadata.connector(scan.catalog)
        new_handle = conn.metadata.apply_limit(scan.table, node.count)
        if new_handle is None:
            return None
        return LimitNode(TableScanNode(scan.catalog, new_handle,
                                       scan.assignments),
                         node.count, node.partial)


class DetermineJoinDistributionType(Rule):
    """Broadcast small build sides, partition large ones
    (iterative/rule/DetermineJoinDistributionType.java)."""

    def apply(self, node, ctx):
        if not isinstance(node, JoinNode) or \
                node.distribution != JoinDistribution.AUTO:
            return None
        if node.kind in (JoinKind.FULL, JoinKind.RIGHT):
            # FULL/RIGHT joins cannot broadcast the build side: the
            # unmatched-build pass would emit duplicates on every shard
            # (same restriction as the reference's replicated-join rules)
            return JoinNode(node.kind, node.left, node.right, node.criteria,
                            node.filter, JoinDistribution.PARTITIONED)
        forced = ctx.session.get("join_distribution_type")
        if forced == "BROADCAST":
            dist = JoinDistribution.REPLICATED
        elif forced == "PARTITIONED":
            dist = JoinDistribution.PARTITIONED
        else:
            threshold = ctx.session.get("join_broadcast_threshold_rows")
            build_rows = ctx.stats.rows(node.right)
            dist = (JoinDistribution.REPLICATED
                    if build_rows <= threshold
                    else JoinDistribution.PARTITIONED)
        return JoinNode(node.kind, node.left, node.right, node.criteria,
                        node.filter, dist)


class FlipJoinSides(Rule):
    """Build on the smaller input (ReorderJoins' local decision: the engine
    always builds the hash table on the right child)."""

    def apply(self, node, ctx):
        if not isinstance(node, JoinNode) or node.kind != JoinKind.INNER \
                or not node.criteria:
            return None
        if getattr(node, "_flip_checked", False):
            return None
        object.__setattr__(node, "_flip_checked", True)
        left_rows = ctx.stats.rows(node.left)
        right_rows = ctx.stats.rows(node.right)
        if right_rows > left_rows * 1.5:
            flipped = JoinNode(
                node.kind, node.right, node.left,
                tuple(JoinClause(c.right, c.left) for c in node.criteria),
                node.filter, node.distribution)
            object.__setattr__(flipped, "_flip_checked", True)
            # preserve output order with a projection
            want = node.outputs
            assigns = tuple((s, s.ref()) for s in want)
            return ProjectNode(flipped, assigns)
        return None


# ---------------------------------------------------------------------------
# join reordering (EliminateCrossJoins.java + ReorderJoins.java:96 greedy)


def reorder_joins(root: PlanNode, ctx: OptimizerContext) -> PlanNode:
    """Reassociate each maximal INNER/CROSS join tree along its equality
    graph so no avoidable cross join remains.

    The reference does DP enumeration over connected subgraphs
    (ReorderJoins.JoinEnumerator:168, capped at 9 relations) with full cost
    comparison; a greedy nearest-neighbor over estimated row counts picks the
    same plans for TPC-H's PK-FK star/snowflake shapes: start from the
    cheapest connected pair, then always attach the connected source that
    minimizes the estimated intermediate size. Cross joins only happen when
    the predicate graph is genuinely disconnected (EliminateCrossJoins'
    contract)."""
    if ctx.session.get("join_reordering_strategy") == "NONE":
        return root

    def walk(node: PlanNode) -> PlanNode:
        if isinstance(node, JoinNode) and \
                node.kind in (JoinKind.INNER, JoinKind.CROSS):
            sources: List[PlanNode] = []
            edges: List[JoinClause] = []
            filters: List[RowExpression] = []

            def flatten(n: PlanNode):
                if isinstance(n, JoinNode) and \
                        n.kind in (JoinKind.INNER, JoinKind.CROSS):
                    flatten(n.left)
                    flatten(n.right)
                    edges.extend(n.criteria)
                    if n.filter is not None:
                        filters.extend(conjuncts(n.filter))
                else:
                    sources.append(walk(n))

            flatten(node)
            if len(sources) < 3:
                # nothing to reorder (flatten already walked the leaves)
                return node.with_sources(sources)
            out = _build_join_tree(sources, edges, filters, ctx)
            want = node.outputs
            have = set(s.name for s in out.outputs)
            assigns = tuple((s, s.ref()) for s in want if s.name in have)
            return ProjectNode(out, assigns)
        return rewrite_sources(node, walk)

    return walk(root)


_DP_MAX_RELATIONS = 9    # ReorderJoins.java JoinEnumerator cap


def _build_join_tree(sources: List[PlanNode], edges: List[JoinClause],
                     filters: List[RowExpression],
                     ctx: OptimizerContext) -> PlanNode:
    syms_of = [{s.name for s in src.outputs} for src in sources]

    def locate(name: str) -> Optional[int]:
        for i, syms in enumerate(syms_of):
            if name in syms:
                return i
        return None

    located = []  # (source_a, source_b, clause); a owns clause.left
    for c in edges:
        a, b = locate(c.left.name), locate(c.right.name)
        if a is None or b is None or a == b:
            # degenerate (same-source equality or unknown symbol): filter
            filters.append(Call("eq", (c.left.ref(), c.right.ref()),
                                T.BOOLEAN))
        else:
            located.append((a, b, c))

    n = len(sources)
    if n <= _DP_MAX_RELATIONS:
        current = _dp_join_tree(sources, located, ctx)
    else:
        current = _greedy_join_tree(sources, syms_of, located, ctx)
    if filters:
        current = FilterNode(current, combine(filters))
    return current


def _dp_join_tree(sources: List[PlanNode], located,
                  ctx: OptimizerContext) -> PlanNode:
    """Selinger-style bitmask DP over connected subsets, minimizing the
    sum of intermediate result sizes (ReorderJoins.JoinEnumerator:168 with
    JoinStatsRule cardinalities). Cross joins only appear when the
    equality graph is genuinely disconnected."""
    n = len(sources)
    rows = [ctx.stats.rows(s) for s in sources]
    edge_info = []   # (mask_a, mask_b, per-clause selectivity)
    for a, b, c in located:
        na = ctx.stats.ndv(sources[a], c.left.name)
        nb = ctx.stats.ndv(sources[b], c.right.name)
        d = max(na or 0.0, nb or 0.0)
        if d <= 0:
            # unknown NDV: the same PK-FK fallback join_cardinality uses,
            # anchored on the edge's smaller endpoint
            d = max(min(rows[a], rows[b]), 1.0)
        edge_info.append((1 << a, 1 << b, 1.0 / d))

    from functools import lru_cache

    @lru_cache(maxsize=None)
    def mask_rows(mask: int) -> float:
        out = 1.0
        for i in range(n):
            if mask & (1 << i):
                out *= rows[i]
        sels = [s for ma, mb, s in edge_info
                if (mask & ma) and (mask & mb)]
        for i, s in enumerate(sorted(sels)):
            out *= s ** (1.0 / (2 ** i))
        return max(out, 1.0)

    def connects(ma: int, mb: int) -> bool:
        return any(((ea & ma) and (eb & mb)) or ((eb & ma) and (ea & mb))
                   for ea, eb, _ in edge_info)

    best: Dict[int, Tuple[float, Optional[Tuple[int, int]]]] = {}
    for i in range(n):
        best[1 << i] = (0.0, None)

    full = (1 << n) - 1
    # iterate masks in popcount order so sub-results exist
    masks = sorted(range(1, full + 1), key=lambda m: bin(m).count("1"))
    for mask in masks:
        if mask in best:
            continue
        size = mask_rows(mask)
        # a cross join (disconnected partition) carries a huge penalty so
        # it survives ONLY when the equality graph is genuinely
        # disconnected — parents then avoid any split whose subtree needs
        # one (EliminateCrossJoins' contract)
        CROSS_PENALTY = 1e12
        picked: Optional[Tuple[float, Tuple[int, int]]] = None
        # enumerate proper submask partitions (canonical: sub contains
        # lowest set bit, so each split is seen once)
        low = mask & (-mask)
        sub = (mask - 1) & mask
        while sub:
            other = mask ^ sub
            if (sub & low) and sub in best and other in best:
                cost = best[sub][0] + best[other][0] + size
                if not connects(sub, other):
                    cost += CROSS_PENALTY
                if picked is None or cost < picked[0]:
                    picked = (cost, (sub, other))
            sub = (sub - 1) & mask
        if picked is not None:
            best[mask] = picked
    if full not in best:
        # degenerate (shouldn't happen): chain greedily
        return _greedy_join_tree(sources,
                                 [{s.name for s in src.outputs}
                                  for src in sources], located, ctx)

    def build(mask: int) -> Tuple[PlanNode, Set[str], Set[int]]:
        _, split = best[mask]
        if split is None:
            i = mask.bit_length() - 1
            return sources[i], {s.name for s in sources[i].outputs}, {i}
        a, b = split
        na, sa, ia = build(a)
        nb, sb, ib = build(b)
        # probe (left) = larger estimated side; build (right) = smaller
        if mask_rows(a) < mask_rows(b):
            na, sa, ia, nb, sb, ib = nb, sb, ib, na, sa, ia
        criteria = []
        for x, y, c in located:
            if x in ia and y in ib:
                criteria.append(c)
            elif y in ia and x in ib:
                criteria.append(JoinClause(c.right, c.left))
        kind = JoinKind.INNER if criteria else JoinKind.CROSS
        return (JoinNode(kind, na, nb, tuple(criteria)),
                sa | sb, ia | ib)

    node, _, _ = build(full)
    return node


def _greedy_join_tree(sources: List[PlanNode], syms_of, located,
                      ctx: OptimizerContext) -> PlanNode:
    """Greedy nearest-neighbor fallback for >_DP_MAX_RELATIONS trees."""
    rows = [ctx.stats.rows(s) for s in sources]
    n = len(sources)

    best: Optional[Tuple[float, int, int]] = None
    for a, b, _ in located:
        cost = max(rows[a], rows[b])
        if best is None or cost < best[0]:
            best = (cost, a, b)
    if best is None:
        order = sorted(range(n), key=lambda i: rows[i])
        first, second = order[0], order[1]
    else:
        _, first, second = best

    used = {first, second}
    current = _join_step(sources[first], syms_of[first], sources[second],
                         second, located, used)
    cur_rows = max(rows[first], rows[second])
    cur_syms = syms_of[first] | syms_of[second]

    while len(used) < n:
        candidates = []
        for j in range(n):
            if j in used:
                continue
            connected = any((a in used and b == j) or (b in used and a == j)
                            for a, b, _ in located)
            est = max(cur_rows, rows[j]) if connected else cur_rows * rows[j]
            candidates.append((not connected, est, j))
        candidates.sort()
        _, est, j = candidates[0]
        current = _join_step(current, cur_syms, sources[j], j, located, used)
        used.add(j)
        cur_rows = est
        cur_syms |= syms_of[j]
    return current


def _join_step(left: PlanNode, left_syms: Set[str], right: PlanNode,
               right_idx: int, located, used: Set[int]) -> PlanNode:
    """Join `right` (source right_idx) onto `left`, consuming every edge
    between the current set and right_idx, oriented left-first."""
    criteria = []
    for a, b, c in located:
        if a in used and b == right_idx:
            criteria.append(c)
        elif b in used and a == right_idx:
            criteria.append(JoinClause(c.right, c.left))
    kind = JoinKind.INNER if criteria else JoinKind.CROSS
    return JoinNode(kind, left, right, tuple(criteria))


# ---------------------------------------------------------------------------
# exchange placement (AddExchanges.java:120 condensed)


def add_exchanges(root: OutputNode, ctx: OptimizerContext) -> OutputNode:
    """Insert REMOTE exchanges bottom-up.

    Partitioning property lattice is reduced to: 'source' (leaf-split
    partitioned), 'hashed(keys)', 'single'. Requirements:
      final agg keys / join keys / semi keys -> hashed; Output/Sort/Limit
      root -> single. Broadcast build sides replicate instead of hashing.
    """

    def visit(node: PlanNode) -> Tuple[PlanNode, str]:
        # returns (new_node, partitioning) where partitioning in
        # {"single", "source", "hashed"}
        if isinstance(node, (TableScanNode,)):
            return node, "source"
        if isinstance(node, ValuesNode):
            return node, "single"
        if isinstance(node, (FilterNode, ProjectNode, UnnestNode)):
            src, part = visit(node.source)
            return node.with_sources([src]), part

        if isinstance(node, AggregationNode):
            src, part = visit(node.source)
            if part == "single":
                return node.with_sources([src]), "single"
            # partial on the source partitioning, repartition/gather, final
            return _split_aggregation(node, src, ctx)

        if isinstance(node, GroupIdNode):
            src, part = visit(node.source)
            return node.with_sources([src]), part

        if isinstance(node, JoinNode):
            left, lpart = visit(node.left)
            right, rpart = visit(node.right)
            if node.distribution == JoinDistribution.REPLICATED or \
                    not node.criteria:
                if rpart != "single":
                    right = ExchangeNode(right, ExchangeScope.REMOTE,
                                         ExchangeKind.BROADCAST)
                return node.with_sources([left, right]), lpart
            lkeys = tuple(c.left for c in node.criteria)
            rkeys = tuple(c.right for c in node.criteria)
            left = ExchangeNode(left, ExchangeScope.REMOTE,
                                ExchangeKind.REPARTITION, lkeys)
            right = ExchangeNode(right, ExchangeScope.REMOTE,
                                 ExchangeKind.REPARTITION, rkeys)
            return node.with_sources([left, right]), "hashed"

        if isinstance(node, SemiJoinNode):
            src, spart = visit(node.source)
            filt, fpart = visit(node.filtering_source)
            # broadcast the filtering side (usually small; exact when keys
            # are replicated everywhere)
            if fpart != "single":
                filt = ExchangeNode(filt, ExchangeScope.REMOTE,
                                    ExchangeKind.BROADCAST)
            return node.with_sources([src, filt]), spart

        if isinstance(node, (SortNode,)):
            src, part = visit(node.source)
            if part != "single":
                if ctx.session.get("distributed_sort"):
                    # local sort then ordered merge gather
                    local = SortNode(src, node.order_by)
                    merged = ExchangeNode(local, ExchangeScope.REMOTE,
                                          ExchangeKind.MERGE, (),
                                          node.order_by)
                    return merged, "single"
                src = ExchangeNode(src, ExchangeScope.REMOTE,
                                   ExchangeKind.GATHER)
            return node.with_sources([src]), "single"

        if isinstance(node, TopNNode):
            src, part = visit(node.source)
            if part == "single":
                return node.with_sources([src]), "single"
            partial = TopNNode(src, node.count, node.order_by, "partial")
            gathered = ExchangeNode(partial, ExchangeScope.REMOTE,
                                    ExchangeKind.GATHER)
            return TopNNode(gathered, node.count, node.order_by,
                            "final"), "single"

        if isinstance(node, LimitNode):
            src, part = visit(node.source)
            if part == "single":
                return node.with_sources([src]), "single"
            partial = LimitNode(src, node.count, partial=True)
            gathered = ExchangeNode(partial, ExchangeScope.REMOTE,
                                    ExchangeKind.GATHER)
            return LimitNode(gathered, node.count), "single"

        if isinstance(node, (OffsetNode, EnforceSingleRowNode,
                             DistinctLimitNode)):
            src, part = visit(node.sources[0])
            if part != "single":
                src = ExchangeNode(src, ExchangeScope.REMOTE,
                                   ExchangeKind.GATHER)
            return node.with_sources([src]), "single"

        if isinstance(node, WindowNode):
            src, part = visit(node.source)
            if part != "single" and node.partition_by:
                src = ExchangeNode(src, ExchangeScope.REMOTE,
                                   ExchangeKind.REPARTITION,
                                   node.partition_by)
                return node.with_sources([src]), "hashed"
            if part != "single":
                src = ExchangeNode(src, ExchangeScope.REMOTE,
                                   ExchangeKind.GATHER)
            return node.with_sources([src]), "single"

        if isinstance(node, UnionNode):
            children = []
            for c in node.children:
                cc, cpart = visit(c)
                children.append(cc)
            return node.with_sources(children), "source"

        if isinstance(node, TableWriterNode):
            src, part = visit(node.source)
            return node.with_sources([src]), part

        if isinstance(node, OutputNode):
            src, part = visit(node.source)
            if part != "single":
                src = ExchangeNode(src, ExchangeScope.REMOTE,
                                   ExchangeKind.GATHER)
            return node.with_sources([src]), "single"

        src_parts = [visit(s) for s in node.sources]
        return node.with_sources([s for s, _ in src_parts]), \
            (src_parts[0][1] if src_parts else "single")

    out, _ = visit(root)
    return out


def _grouped_exchange_kind(agg: AggregationNode, src: PlanNode,
                           ctx: OptimizerContext) -> str:
    """Partitioned vs. global GROUP BY strategy ("Global Hash Tables
    Strike Back" mapped onto the mesh): a LOW-NDV grouping collapses into
    tiny partial states per shard, so gathering those states to one shard
    (the shared/global hash table) beats paying an all_to_all; a HIGH-NDV
    grouping must radix-partition so the final aggregation parallelizes
    and no single chip materializes every group. The CBO's NDV product
    picks the strategy; unknown NDV defaults to partitioned (the safe
    choice at scale)."""
    if not agg.group_by:
        return ExchangeKind.GATHER
    threshold = int(ctx.session.get("partitioned_agg_min_ndv"))
    groups = 1.0
    for s in agg.group_by:
        n = ctx.stats.ndv(src, s.name)
        if n is None:
            return ExchangeKind.REPARTITION
        groups *= max(n, 1.0)
    # cap the NDV product at the input row count BEFORE comparing: a
    # multi-key product can exceed the threshold while the true group
    # count (bounded by rows) stays tiny (float product cannot
    # meaningfully overflow — it saturates, and saturation > threshold)
    groups = min(groups, ctx.stats.rows(src))
    return (ExchangeKind.REPARTITION if groups >= threshold
            else ExchangeKind.GATHER)


def _split_aggregation(agg: AggregationNode, src: PlanNode,
                       ctx: OptimizerContext) -> Tuple[PlanNode, str]:
    """partial agg -> exchange -> final agg
    (PushPartialAggregationThroughExchange.java). DISTINCT or FILTER aggs
    can't split; gather instead. The exchange kind for grouped
    aggregations is CBO-chosen: REPARTITION (partitioned strategy) vs
    GATHER (global strategy) by estimated group NDV."""
    from trino_tpu.ops.aggregate import SINGLE_STEP_AGGREGATES
    splittable = all(not a.distinct and a.filter is None
                     and a.name not in SINGLE_STEP_AGGREGATES
                     for _, a in agg.aggregations)
    if not splittable:
        # unsplittable aggs need every row of a group in ONE kernel call,
        # so a grouped agg must repartition regardless of NDV
        kind = (ExchangeKind.REPARTITION if agg.group_by
                else ExchangeKind.GATHER)
        ex = ExchangeNode(src, ExchangeScope.REMOTE, kind,
                          tuple(agg.group_by))
        return agg.with_sources([ex]), ("hashed" if agg.group_by
                                        else "single")
    # The PARTIAL node carries the same aggregations tuple; the execution
    # planner derives the operator-level state-column layout from the step
    # (keys + state columns per agg) and the FINAL side consumes positionally
    # through the exchange collective.
    partial = AggregationNode(src, agg.group_by, agg.aggregations,
                              AggStep.PARTIAL)
    kind = _grouped_exchange_kind(agg, src, ctx)
    ex = ExchangeNode(partial, ExchangeScope.REMOTE, kind,
                      tuple(agg.group_by))
    final = AggregationNode(ex, agg.group_by, agg.aggregations, AggStep.FINAL)
    return final, ("hashed" if kind == ExchangeKind.REPARTITION
                   else "single")


# ---------------------------------------------------------------------------
# fragmenter (PlanFragmenter.java:90)


@dataclasses.dataclass
class PlanFragment:
    """One stage program: executes `root` over its partitioning; consumes
    child fragments through the RemoteSourceNodes cut at REMOTE exchanges.

    `partition_keys` is the fragment's partitioning HANDLE (the reference's
    PartitioningHandle): for a "hashed" fragment, the symbol names whose
    hash placed each row on its shard — the mesh scheduler uses it to
    recognize co-partitioned inputs (a join over inputs repartitioned on
    the same clause keys needs no further exchange)."""

    fragment_id: int
    root: PlanNode
    partitioning: str               # single | source | hashed
    children: List["PlanFragment"]
    partition_keys: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class RemoteSourceNode(PlanNode):
    """Placeholder consuming a child fragment's output
    (plan/RemoteSourceNode.java)."""

    fragment_id: int
    symbols: Tuple[Symbol, ...]
    kind: str
    partition_keys: Tuple[Symbol, ...] = ()
    order_by: Tuple[Ordering, ...] = ()
    id: int = -1

    @property
    def sources(self):
        return ()

    @property
    def outputs(self):
        return self.symbols

    def with_sources(self, sources):
        return self

    def node_name(self):
        return f"RemoteSource[{self.fragment_id}, {self.kind}]"


def fragment_plan(root: OutputNode) -> PlanFragment:
    """Cut the plan at REMOTE exchanges into a fragment tree."""
    counter = [0]

    def cut(node: PlanNode, partitioning: str
            ) -> Tuple[PlanNode, List[PlanFragment]]:
        if isinstance(node, ExchangeNode) and \
                node.scope == ExchangeScope.REMOTE:
            child_part = ("hashed" if node.kind == ExchangeKind.REPARTITION
                          else "source")
            child_root, grandchildren = cut(node.source, child_part)
            counter[0] += 1
            fid = counter[0]
            frag = PlanFragment(fid, child_root, child_part, grandchildren,
                                tuple(s.name for s in node.partition_keys))
            remote = RemoteSourceNode(fid, tuple(node.source.outputs),
                                      node.kind, node.partition_keys,
                                      node.order_by)
            return remote, [frag]
        new_sources = []
        frags: List[PlanFragment] = []
        for s in node.sources:
            ns, f = cut(s, partitioning)
            new_sources.append(ns)
            frags.extend(f)
        if node.sources:
            node = node.with_sources(new_sources)
        return node, frags

    root_node, children = cut(root, "single")
    return PlanFragment(0, root_node, "single", children)


# ---------------------------------------------------------------------------
# pipeline (PlanOptimizers.java ordering)


def annotate_adaptive_hints(node: PlanNode,
                            ctx: OptimizerContext) -> PlanNode:
    """Stamp CBO NDV/skew estimates onto aggregation and join nodes as
    adaptive-strategy hints (exec/adaptive.py): aggregations carry
    (input rows, group NDV) so the partial-agg mode controller starts
    in the right lattice position; inner joins carry the build side's
    rows/NDV duplication so an over-threshold skewed build routes to
    the partitioned hybrid join without a wasted unique-probe prep.
    Runs LAST in optimize() — every other rule rebuilds nodes through
    with_sources, which preserves the fields, but the estimates
    themselves must see the final shape."""
    new_sources = [annotate_adaptive_hints(s, ctx) for s in node.sources]
    if not all(a is b for a, b in zip(new_sources, node.sources)):
        node = node.with_sources(new_sources)
    try:
        if isinstance(node, AggregationNode) and node.group_by and \
                node.step in (AggStep.SINGLE, AggStep.PARTIAL):
            rows = ctx.stats.rows(node.source)
            groups = ctx.stats.rows(node)
            if rows and groups:
                node = dataclasses.replace(
                    node, rows_estimate=float(rows),
                    ndv_estimate=float(groups))
        elif isinstance(node, JoinNode) and node.criteria and \
                node.kind == JoinKind.INNER:
            brows = ctx.stats.rows(node.right)
            ndvs = [ctx.stats.ndv(node.right, c.right.name)
                    for c in node.criteria]
            known = [n for n in ndvs if n]
            if brows and known:
                node = dataclasses.replace(
                    node, build_skew_estimate=(
                        float(brows) / max(min(known), 1.0)))
    except Exception:
        pass    # estimates are hints: a stats failure must not fail planning
    try:
        # MXU probe-strategy candidate (surfaced by EXPLAIN as `join
        # strategy: mxu-matmul | gather`): an INNER single-clause
        # equi-join is matmul-ELIGIBLE when the session enables the
        # path; the executor's runtime router re-decides from the
        # OBSERVED build-key density (the CBO has NDV but no key span),
        # so this stamp is the plan-time candidate, not the verdict.
        # Plan-cache-safe: mxu_join_* are PLAN_PROPERTIES.
        mxu_on = bool(ctx.session.get("mxu_join_enabled"))
        if isinstance(node, JoinNode) and node.criteria:
            strategy = "mxu-matmul" if (
                mxu_on and node.kind == JoinKind.INNER
                and len(node.criteria) == 1) else "gather"
            node = dataclasses.replace(node, join_strategy=strategy)
        elif isinstance(node, SemiJoinNode):
            strategy = "mxu-matmul" if (
                mxu_on and len(node.source_keys) == 1) else "gather"
            node = dataclasses.replace(node, join_strategy=strategy)
    except Exception:
        pass
    return node


def optimize(root: OutputNode, metadata: Metadata, session: Session,
             distributed: bool = False) -> OutputNode:
    from trino_tpu.planner.validator import validate_plan
    ctx = OptimizerContext(metadata, session, StatsEstimator(metadata))
    rules = [
        FoldConstants(),
        MergeFilters(),
        ExtractCommonPredicates(),
        MergeAdjacentProjects(),
        RemoveIdentityProjections(),
        PredicatePushDown(),
        MergeLimits(),
        EvaluateZeroLimit(),
        PushLimitThroughProject(),
        CreateTopN(),
    ]
    root = run_rules(root, rules, ctx)
    root = validate_plan(prune_unreferenced(root))
    root = reorder_joins(root, ctx)
    root = run_rules(root, [
        MergeFilters(), MergeAdjacentProjects(), RemoveIdentityProjections(),
        PredicatePushDown(),
        PushPredicateIntoTableScan(), PushLimitIntoTableScan(),
        DetermineJoinDistributionType(), FlipJoinSides(),
    ], ctx)
    root = validate_plan(prune_unreferenced(root))
    if distributed:
        root = add_exchanges(root, ctx)
    return annotate_adaptive_hints(root, ctx)
