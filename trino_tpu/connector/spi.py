"""Connector SPI core types.

Reference parity (file:line cites into /root/reference):
- ConnectorMetadata            spi/connector/ConnectorMetadata.java:50
  (applyLimit:888, applyFilter:907 -> apply_filter/apply_limit here)
- ConnectorSplitManager        spi/connector/ConnectorSplitManager.java
- ConnectorPageSource          spi/connector/ConnectorPageSource.java:24
  (getNextPage:59 -> the pages() iterator)
- ConnectorPageSink            spi/connector/ConnectorPageSink.java
- TableStatistics              spi/statistics/TableStatistics.java
- CatalogManager               metadata/CatalogManager.java
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from trino_tpu import types as T
from trino_tpu.page import Page
from trino_tpu.predicate import TupleDomain


@dataclasses.dataclass(frozen=True)
class SchemaTableName:
    schema: str
    table: str

    def __str__(self):
        return f"{self.schema}.{self.table}"


@dataclasses.dataclass(frozen=True)
class ColumnMetadata:
    name: str
    type: T.Type
    hidden: bool = False


@dataclasses.dataclass(frozen=True)
class TableMetadata:
    name: SchemaTableName
    columns: Tuple[ColumnMetadata, ...]
    # CREATE TABLE ... WITH (key = value) properties, evaluated to plain
    # values (the ConnectorTableProperties channel: the lake connector
    # reads partitioned_by/format here; other connectors ignore them)
    properties: Tuple[Tuple[str, object], ...] = ()

    def column_index(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(name)


@dataclasses.dataclass(frozen=True)
class ColumnHandle:
    """Opaque per-connector column reference (spi/connector/ColumnHandle)."""

    name: str
    type: T.Type
    ordinal: int


@dataclasses.dataclass(frozen=True)
class ConnectorTableHandle:
    """Table reference + negotiated pushdowns riding through the planner.

    The reference threads pushdown state through connector-specific handle
    types; one generic handle with constraint/limit fields covers the built-in
    connectors here.
    """

    name: SchemaTableName
    constraint: TupleDomain = TupleDomain.all()
    limit: Optional[int] = None
    # Time travel: pinned manifest/snapshot version (`FOR VERSION AS OF`).
    # None = current. Only versioned connectors (the lake) honor it; the
    # planner rejects pins on connectors whose metadata lacks
    # resolve_version support.
    version: Optional[int] = None
    # Delta scan (incremental MV refresh): with `version` = v_to, scan
    # ONLY files added between delta_from and v_to (the manifest-log
    # diff). Never set by SQL — the MV refresher pins it through the
    # planner's scan-pin channel.
    delta_from: Optional[int] = None


@dataclasses.dataclass(frozen=True)
class Split:
    """Unit of leaf parallelism (spi/connector/ConnectorSplit).

    `part`/`total_parts` index a row-range partition of the table; `host` is a
    locality hint (mesh coordinate, not hostname, in the TPU build).
    `context` is opaque connector state captured at SPLIT time (the lake
    pins its manifest snapshot here, so every split of one query reads
    ONE committed version even while concurrent writes swap manifests).
    """

    table: ConnectorTableHandle
    part: int
    total_parts: int
    host: Optional[int] = None
    context: Optional[object] = dataclasses.field(default=None,
                                                  compare=False)


@dataclasses.dataclass(frozen=True)
class ColumnStatistics:
    null_fraction: Optional[float] = None
    distinct_count: Optional[float] = None
    min_value: Optional[object] = None
    max_value: Optional[object] = None
    avg_size_bytes: Optional[float] = None


@dataclasses.dataclass(frozen=True)
class TableStatistics:
    row_count: Optional[float] = None
    columns: Dict[str, ColumnStatistics] = dataclasses.field(
        default_factory=dict)

    @staticmethod
    def unknown() -> "TableStatistics":
        return TableStatistics()


class ConnectorMetadata:
    """spi/connector/ConnectorMetadata.java:50."""

    def list_schemas(self) -> List[str]:
        raise NotImplementedError

    def list_tables(self, schema: Optional[str] = None) -> List[SchemaTableName]:
        raise NotImplementedError

    def get_table_handle(self, name: SchemaTableName) -> Optional[ConnectorTableHandle]:
        raise NotImplementedError

    def get_table_metadata(self, handle: ConnectorTableHandle) -> TableMetadata:
        raise NotImplementedError

    def get_column_handles(self, handle: ConnectorTableHandle) -> List[ColumnHandle]:
        meta = self.get_table_metadata(handle)
        return [ColumnHandle(c.name, c.type, i)
                for i, c in enumerate(meta.columns)]

    def apply_filter(self, handle: ConnectorTableHandle,
                     constraint: TupleDomain
                     ) -> Optional[Tuple[ConnectorTableHandle, TupleDomain]]:
        """applyFilter:907 -> (new handle, remaining domain) or None.

        Default: accept the domain as a split-pruning hint but keep the whole
        constraint as 'remaining' so the engine still applies it row-wise.
        """
        return None

    def apply_limit(self, handle: ConnectorTableHandle,
                    limit: int) -> Optional[ConnectorTableHandle]:
        """applyLimit:888 -> new handle or None; limit here is advisory
        (connector may return more rows; engine still enforces)."""
        return None

    def get_table_statistics(self, handle: ConnectorTableHandle) -> TableStatistics:
        return TableStatistics.unknown()

    def estimate_like_selectivity(self, handle: ConnectorTableHandle,
                                  column: str, pattern: str,
                                  escape=None):
        """Fraction of rows matching `column LIKE pattern`, or None when
        unknown (FilterStatsCalculator hook: dictionary-encoded connectors
        can answer exactly from their pools — a LIKE misestimate was the
        round-4 q9 join-order regression)."""
        return None

    # -- writes (spi/connector/ConnectorMetadata beginCreateTable/beginInsert)

    def create_table(self, metadata: TableMetadata, ignore_existing: bool = False):
        raise NotImplementedError("connector does not support CREATE TABLE")

    def drop_table(self, handle: ConnectorTableHandle):
        raise NotImplementedError("connector does not support DROP TABLE")


class ConnectorSplitManager:
    """spi/connector/ConnectorSplitManager.java."""

    def get_splits(self, handle: ConnectorTableHandle,
                   target_splits: int = 1) -> List[Split]:
        raise NotImplementedError


class ConnectorPageSource:
    """spi/connector/ConnectorPageSource.java:24; pages() replaces the
    getNextPage:59 pull loop with a Python iterator of columnar Pages."""

    def pages(self, split: Split, columns: Sequence[ColumnHandle],
              page_capacity: int) -> Iterator[Page]:
        raise NotImplementedError


class WriteTokenLedger:
    """Bounded memory of committed write tokens (the idempotent-sink
    dedup set). A token only needs to outlive its own query's retries,
    so a few thousand most-recent entries is far beyond any live retry
    window — the bound exists so a long-lived serving process under
    sustained write traffic doesn't accrete one token string per write
    forever. Callers hold their own lock."""

    def __init__(self, max_tokens: int = 4096):
        import collections
        self._seen: "collections.OrderedDict" = collections.OrderedDict()
        self.max_tokens = max_tokens

    def commit(self, token) -> bool:
        """True exactly once per token: the first commit wins, replays
        are no-ops."""
        if token in self._seen:
            return False
        self._seen[token] = None
        while len(self._seen) > self.max_tokens:
            self._seen.popitem(last=False)
        return True

    def __contains__(self, token) -> bool:
        return token in self._seen


class ConnectorPageSink:
    """spi/connector/ConnectorPageSink.java — two-phase append target.

    Idempotent-write protocol (the FTE write contract the reference asks
    of connectors before allowing retried writes): a sink created with a
    `write_token` STAGES appended rows under that token and commits them
    atomically in `finish()` — and a token that already committed never
    commits again, so replaying a whole write attempt (QUERY-level
    retry, a fragment re-run after a mid-slice failure) is duplicate-
    free by construction. `abort()` drops the staging of a failed
    attempt. Sinks without a token keep the legacy append-as-you-go
    semantics, and connectors advertise the staged protocol with
    `Connector.idempotent_writes` — the engine only opens retry scopes
    around writes when every target connector declares it."""

    def append_page(self, page: Page):
        raise NotImplementedError

    def finish(self):
        pass

    def abort(self):
        """Drop this attempt's staged rows (failed/abandoned write)."""
        pass


class Connector:
    """One catalog instance (spi/connector/Connector.java)."""

    # True when page_sink() implements the staged write-token protocol
    # (commit-on-finish, token-deduplicated): the engine may then retry
    # write plans — chaos included — without double-write risk
    idempotent_writes = False

    def __init__(self, name: str, metadata: ConnectorMetadata,
                 split_manager: ConnectorSplitManager,
                 page_source: ConnectorPageSource):
        self.name = name
        self.metadata = metadata
        self.split_manager = split_manager
        self.page_source = page_source

    def page_sink(self, handle: ConnectorTableHandle,
                  write_token: Optional[str] = None) -> ConnectorPageSink:
        raise NotImplementedError(
            f"connector {self.name} does not support writes")


class CatalogManager:
    """metadata/CatalogManager.java — catalog name -> Connector registry."""

    def __init__(self):
        self._catalogs: Dict[str, Connector] = {}

    def register(self, catalog: str, connector: Connector):
        self._catalogs[catalog] = connector

    def get(self, catalog: str) -> Connector:
        if catalog not in self._catalogs:
            raise KeyError(f"catalog not found: {catalog}")
        return self._catalogs[catalog]

    def catalogs(self) -> List[str]:
        return sorted(self._catalogs)


def pad_to_capacity(arr, capacity: int, fill):
    """Pad/truncate a host array slice to the page capacity (padding rows sit
    beyond num_rows and are never read)."""
    import numpy as np
    if len(arr) >= capacity:
        return arr[:capacity]
    out = np.full(capacity, fill, dtype=arr.dtype)
    out[:len(arr)] = arr
    return out


def split_range(total_rows: int, part: int, total_parts: int) -> Tuple[int, int]:
    """Row range [start, end) of split `part` of `total_parts` over a table."""
    rows_per = math.ceil(total_rows / total_parts) if total_parts else total_rows
    start = min(part * rows_per, total_rows)
    end = min(start + rows_per, total_rows)
    return start, end
