"""Persistent query-history tier: a bounded ring of completed queries.

Reference parity: the reference keeps completed QueryInfos in
QueryTracker past expiry ONLY briefly; production deployments rely on an
EventListener writing a query-history store (the completed-queries table
every Trino operator queries after an incident). Here the store is
in-process: `HISTORY` is a bounded ring of `CompletedQuery` records fed
from the EventListener bus (query_completed / query_failed — CANCELED
arrives through query_failed with state CANCELED), retaining the final
stats snapshot, the span dump, and the error taxonomy AFTER the live
tracker entry is pruned. Surfaced as `system.runtime.completed_queries`
(connector/system.py) and `GET /v1/query/{id}` (server/app.py), which
fall back here when the tracker no longer knows the id.

Feeding rides the listener bus on purpose — the history tier consumes
the exact payload any external listener plugin would, so it doubles as
the bus's own in-process reference consumer. The listener registers at
module import; the fire_* path imports this module lazily, so direct
runners and servers alike always have the ring armed.

The ring is bounded by `history_max_entries` (session property on the
owning runner; TrinoServer(history_max_entries=...) for deployments).
Eviction is strict FIFO by completion order — the retention contract the
tests pin down.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Any, Dict, List, Optional

from trino_tpu.obs.listeners import EventListener, register_listener

DEFAULT_MAX_ENTRIES = 512


@dataclasses.dataclass
class CompletedQuery:
    """One terminal query, frozen at completion: identity, outcome,
    the device/compile/host time split, and the error taxonomy
    (error_name/error_type/retryable from trino_tpu/errors.py) — the
    record an operator reads after the live tracker pruned the id."""

    query_id: str
    state: str
    user: str
    query: str
    ended_at: float                      # wall-clock epoch seconds
    wall_ms: int = 0
    cpu_time_ms: int = 0                 # host time (device/compile out)
    device_time_ms: float = 0.0
    compile_time_ms: float = 0.0
    rows: int = 0
    output_bytes: int = 0
    retries: int = 0
    faults_injected: int = 0
    resource_group: Optional[str] = None
    peak_memory_bytes: int = 0
    error: Optional[str] = None
    error_name: Optional[str] = None
    error_type: Optional[str] = None
    retryable: Optional[bool] = None
    stats: Optional[Dict[str, Any]] = dataclasses.field(
        default=None, repr=False)
    trace: Optional[Dict[str, Any]] = dataclasses.field(
        default=None, repr=False)
    trace_file: Optional[str] = None     # exported Chrome-trace path


def _taxonomy(error_name: Optional[str]):
    """(error_type, retryable) for a StandardErrorCode name — the code
    registry in trino_tpu/errors.py is the single source of truth."""
    if not error_name:
        return None, None
    from trino_tpu import errors
    for value in vars(errors).values():
        if isinstance(value, errors.ErrorCode) and value.name == error_name:
            return value.type, value.retryable
    return None, None


class QueryHistory:
    """Bounded FIFO ring of CompletedQuery records, lock-guarded (the
    listener bus fires from executor threads while HTTP threads and
    system-table scans read)."""

    def __init__(self, max_entries: int = DEFAULT_MAX_ENTRIES):
        self._lock = threading.Lock()
        self._ring: "collections.deque[CompletedQuery]" = \
            collections.deque(maxlen=max(1, int(max_entries)))
        self.recorded = 0            # lifetime, for the evicted gauge

    @property
    def max_entries(self) -> int:
        return self._ring.maxlen or 0

    def resize(self, max_entries: int) -> None:
        n = max(1, int(max_entries))
        with self._lock:
            if n == self._ring.maxlen:
                return
            # keep the NEWEST entries on a shrink (deque(maxlen) drops
            # from the left as the old ring replays in order)
            self._ring = collections.deque(self._ring, maxlen=n)

    def record(self, entry: CompletedQuery) -> None:
        with self._lock:
            self._ring.append(entry)
            self.recorded += 1

    def list(self) -> List[CompletedQuery]:
        """Oldest-first snapshot (completion order)."""
        with self._lock:
            return list(self._ring)

    def get(self, query_id: str) -> Optional[CompletedQuery]:
        with self._lock:
            for entry in reversed(self._ring):
                if entry.query_id == query_id:
                    return entry
        return None

    def stats(self) -> Dict[str, int]:
        with self._lock:
            n = len(self._ring)
            return {"entries": n, "max_entries": self._ring.maxlen or 0,
                    "recorded": self.recorded, "evicted": self.recorded - n}

    def clear(self) -> None:  # for tests
        with self._lock:
            self._ring.clear()


def record_from_event(event) -> CompletedQuery:
    """Freeze a terminal QueryEvent (obs/listeners.py) into the history
    record shape — THE single CompletedQuery builder (the listener and
    record_from_info both come through here, so a new field can never
    silently exist on one feed and not the other)."""
    stats = event.stats or {}
    error_type, retryable = _taxonomy(event.error_name)
    return CompletedQuery(
        query_id=event.query_id, state=event.state, user=event.user,
        query=event.query, ended_at=time.time(),
        wall_ms=event.wall_ms or 0, cpu_time_ms=event.cpu_time_ms,
        device_time_ms=float(stats.get("device_time_ms", 0.0) or 0.0),
        compile_time_ms=float(stats.get("compile_time_ms", 0.0) or 0.0),
        rows=event.rows, output_bytes=event.output_bytes,
        retries=event.retries, faults_injected=event.faults_injected,
        resource_group=event.resource_group,
        peak_memory_bytes=event.peak_memory_bytes,
        error=event.error, error_name=event.error_name,
        error_type=error_type, retryable=retryable,
        stats=dict(stats) if stats else None,
        trace=event.trace, trace_file=event.trace_file)


def record_from_info(info) -> CompletedQuery:
    """Freeze a terminal QueryInfo (exec/query_tracker.py) into the
    history record shape, through the same event mapping the listener
    bus uses. ended_at converts the tracker's MONOTONIC end stamp to
    the epoch clock (the ring stamps records at completion — a record
    built later from the live tracker must agree, not drift with
    request time)."""
    from trino_tpu.obs.listeners import event_from_info
    rec = record_from_event(event_from_info(info))
    if info.ended is not None:
        import time as _time
        rec.ended_at = _time.time() - (_time.monotonic() - info.ended)
    return rec


HISTORY = QueryHistory()


class _HistoryListener(EventListener):
    """The ring's feed: every terminal event appends one record. FAILED
    and CANCELED queries are retained exactly like FINISHED ones — the
    history tier exists for the post-incident question."""

    def query_completed(self, event) -> None:
        self._record(event)

    def query_failed(self, event) -> None:
        self._record(event)

    @staticmethod
    def _record(event) -> None:
        HISTORY.record(record_from_event(event))


_LISTENER = register_listener(_HistoryListener())
