"""Device-time truth (round 13): cost-model operator attribution,
compile-vs-execute accounting, and Chrome-trace export.

The acceptance contract: `collect_operator_stats` observes the SAME
executables the plain query runs (no chain splitting — a warm
instrumented run dispatches zero new kernels), per-operator device
attribution sums to the measured chain walls, compile walls are measured
events rather than cold-vs-warm deltas, and the span tree exports as
valid Chrome-trace JSON.
"""

import json
import os
import re

import pytest

from trino_tpu.exec import LocalQueryRunner

from oracle import assert_same, load_tpch_sqlite
from tpch_sql import QUERIES

SF = 0.01


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch("tiny")


@pytest.fixture(scope="module")
def oracle():
    conn = load_tpch_sqlite(SF)
    yield conn
    conn.close()


def _with_operator_stats(runner, sql):
    runner.session.set("collect_operator_stats", True)
    try:
        out = runner.execute(sql)
    finally:
        runner.session.properties.pop("collect_operator_stats", None)
    return out, dict(runner.last_query_stats)


# ------------------------------------------------- no-splitting contract


@pytest.mark.parametrize("name", ["q1", "q6"])
def test_operator_stats_dispatch_same_kernels(runner, name):
    """THE regression this round exists for: after a plain warm run,
    turning operator-level collection on must dispatch ZERO new kernels
    — the old node-boundary instrumentation split fused chains into
    per-operator programs (jit misses on every instrumented run), which
    meant profiling changed what was measured."""
    engine_sql, _, _ = QUERIES[name]
    runner.execute(engine_sql)              # warm the fused chain shapes
    runner.execute(engine_sql)
    assert runner.last_query_stats["jit_misses"] == 0   # warm baseline
    _, snap = _with_operator_stats(runner, engine_sql)
    assert snap["jit_misses"] == 0, snap    # same executables, stats on
    assert snap["operators"], snap          # and rows were collected


@pytest.mark.parametrize("name", ["q1", "q5"])
def test_device_attribution_sums_to_chain_walls(runner, oracle, name):
    """Per-operator device shares (XLA cost-model apportionment of each
    fused chain's fenced wall) must sum to the collector's measured
    device total — attribution redistributes, never invents."""
    engine_sql, oracle_sql, ordered = QUERIES[name]
    got, snap = _with_operator_stats(runner, engine_sql)
    expected = oracle.execute(oracle_sql or engine_sql).fetchall()
    assert_same(got.rows, expected, ordered)    # instrumented == correct
    ops = snap["operators"]
    assert ops and snap["device_time_ms"] > 0, snap
    dev_sum = sum(o["device_ms"] for o in ops)
    assert abs(dev_sum - snap["device_time_ms"]) < 0.5, \
        (dev_sum, snap["device_time_ms"])
    # streaming chain operators carry nonzero device shares
    assert any(o["device_ms"] > 0 for o in ops
               if o["name"] in ("FilterNode", "ProjectNode")), ops


def test_plain_queries_skip_the_fence(runner):
    """Without operator-level collection no chain is fenced: device
    time reads 0 (it stays folded into execution wall) and no operator
    rows exist — the default path pays nothing for attribution."""
    runner.execute("SELECT count(*) FROM orders")
    snap = runner.last_query_stats
    assert snap["device_time_ms"] == 0.0
    assert "operators" not in snap


# --------------------------------------------- compile-vs-execute split


def test_compile_wall_is_a_measured_event(runner):
    """A never-seen chain shape pays a measured XLA compile (wall +
    HLO op count + cost-model flops/bytes); the warm re-run pays none.
    The structure below is unique to this test so the shared process
    jit cache cannot have warmed it."""
    sql = ("SELECT sum(l_quantity * 7 - l_tax * 3 + l_discount * 11) "
           "FROM lineitem WHERE l_partkey * 13 > l_suppkey * 17")
    runner.execute(sql)
    cold = dict(runner.last_query_stats)
    assert cold["compile_time_ms"] > 0, cold
    assert cold["jit_compiles"] >= 1, cold
    assert cold["compiled_hlo_ops"] > 0, cold
    assert cold["estimated_bytes"] > 0, cold
    runner.execute(sql)
    warm = dict(runner.last_query_stats)
    assert warm["compile_time_ms"] == 0.0, warm
    assert warm["jit_compiles"] == 0, warm


def test_cpu_time_means_host_time(runner):
    """host_time_ms (and QueryInfo.cpu_time_ms) = execution - device -
    compile, clamped at zero: the three walls partition execution."""
    from trino_tpu.exec.query_tracker import TRACKER
    sql = "SELECT max(o_totalprice) AS host_time_probe FROM orders"
    _, snap = _with_operator_stats(runner, sql)
    exec_ms = snap["execution_s"] * 1000
    assert snap["host_time_ms"] <= exec_ms + 1e-6, snap
    assert abs((snap["host_time_ms"] + snap["device_time_ms"]
                + snap["compile_time_ms"]) - exec_ms) < 1.0 \
        or snap["host_time_ms"] == 0.0, snap
    info = next(q for q in TRACKER.list() if q.query == sql)
    assert info.cpu_time_ms == int(snap["host_time_ms"]), \
        (info.cpu_time_ms, snap["host_time_ms"])


def test_explain_analyze_reports_the_split(runner):
    """EXPLAIN ANALYZE q1 (acceptance): device_time_ms and
    compile_time_ms render separately from host time in the footer, and
    fused-chain node annotations carry their device share."""
    engine_sql, _, _ = QUERIES["q1"]
    text = runner.execute("EXPLAIN ANALYZE " + engine_sql).only_value()
    m = re.search(r"device ([\d.]+)ms / compile ([\d.]+)ms / "
                  r"host ([\d.]+)ms", text)
    assert m, text
    assert float(m.group(1)) > 0, text          # chains were fenced
    assert "compiles" in text
    assert re.search(r"device: [\d.]+ms", text), text   # per-node share


# ------------------------------------------------- jit cache accounting


def test_jit_cache_exports_compile_ledger(runner):
    from trino_tpu.exec import jit_cache
    s = jit_cache.stats()
    for key in ("compiles", "compile_s", "hlo_ops", "aot_fallbacks"):
        assert key in s, s
    assert s["compiles"] >= 1 and s["compile_s"] > 0
    # the profiled AOT dispatch path must not be misfiring: fallbacks
    # mean signature drift between lower() and call time
    assert s["aot_fallbacks"] == 0, s
    runner.execute("SELECT name, value FROM system.runtime.metrics "
                   "WHERE name = 'trino_tpu_jit_compile_seconds_total'")


# ------------------------------------------------------- trace export


def _check_chrome_trace(payload):
    """The fast schema check (satellite): Chrome-trace JSON with
    well-typed ph/ts/dur on every complete event."""
    assert isinstance(payload, dict) and "traceEvents" in payload
    complete = [e for e in payload["traceEvents"] if e.get("ph") == "X"]
    assert complete, payload
    for e in payload["traceEvents"]:
        assert isinstance(e.get("ph"), str) and e["ph"] in ("X", "M"), e
        if e["ph"] == "X":
            assert isinstance(e["ts"], (int, float)), e
            assert isinstance(e["dur"], (int, float)), e
            assert isinstance(e.get("name"), str), e
    return complete


def test_chrome_trace_from_span_dump(runner):
    from trino_tpu.exec.query_tracker import TRACKER
    from trino_tpu.obs.spans import to_chrome_trace
    sql = "SELECT count(*) AS chrome_probe FROM customer"
    runner.execute(sql)
    info = next(q for q in TRACKER.list() if q.query == sql)
    payload = json.loads(json.dumps(to_chrome_trace(info.trace,
                                                    info.query_id)))
    complete = _check_chrome_trace(payload)
    cats = {e["cat"] for e in complete}
    assert "query" in cats and "phase" in cats, cats


def test_trace_export_distributed_q5(tmp_path):
    """Acceptance: an exported trace for a distributed q5 run opens as
    valid Chrome-trace JSON containing query, fragment, and operator
    spans; QueryInfo.trace_file points at the file."""
    from trino_tpu.exec.distributed import DistributedQueryRunner
    from trino_tpu.exec.query_tracker import TRACKER
    r = DistributedQueryRunner.tpch("tiny")
    r._trace_dir = str(tmp_path)
    r.session.set("trace_export", True)
    r.session.set("collect_operator_stats", True)
    engine_sql, _, _ = QUERIES["q5"]
    out = r.execute(engine_sql)
    assert out.rows
    info = next(q for q in TRACKER.list()
                if q.query == engine_sql and q.trace_file)
    assert os.path.exists(info.trace_file), info.trace_file
    with open(info.trace_file) as fh:
        payload = json.load(fh)
    complete = _check_chrome_trace(payload)
    cats = {e["cat"] for e in complete}
    assert {"query", "fragment", "operator"} <= cats, cats


def test_trace_export_off_by_default(runner):
    from trino_tpu.exec.query_tracker import TRACKER
    sql = "SELECT count(*) AS no_trace_probe FROM region"
    runner.execute(sql)
    info = next(q for q in TRACKER.list() if q.query == sql)
    assert info.trace_file is None
