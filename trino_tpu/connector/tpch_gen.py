"""Chunked, column-pruned TPC-H data streams.

Reference parity: plugin/trino-tpch delegates to io.airlift.tpch, a dbgen
port whose defining property is O(1) seekability — any worker can generate
any row range of any column without generating what precedes it (dbgen
reserves a fixed number of RNG draws per row so parallel chunks line up).
This module reproduces that PROPERTY tpu-first: every column is a stateless
counter-based hash stream (`value = f(mix64(row_index, column_seed))`), so

  * a scan split materializes ONLY the columns it reads, for ONLY its row
    range (SF100 lineitem is 600M rows; a q9 scan touches 7 of 16 columns);
  * generation is embarrassingly parallel and identical across processes
    (no sequential RNG state, unlike np.random.Generator);
  * low-cardinality strings are emitted as dictionary CODES into fixed
    sorted pools — no Python string objects on the scan path at all.

Scope note (BASELINE.md north-star asked for dbgen-bit-identical rows):
the airlift/dbgen RNG seed tables and text grammars are not present in the
reference repo and cannot be fetched (zero egress), so bit-identical output
is out of reach in this environment; the correctness contract remains
"engine and oracle read the SAME generated data" (H2QueryRunner pattern)
with spec-shaped distributions, exact spec row counts for the fixed-size
tables, and spec formulas where the spec gives them (retailprice, partsupp
supplier spread, date windows, status flags).
"""

from __future__ import annotations

import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from trino_tpu.expr.functions import days_from_civil

MIN_DATE = days_from_civil(1992, 1, 1)
MAX_ORDER_DATE = days_from_civil(1998, 8, 2)
CURRENT_DATE = days_from_civil(1995, 6, 17)

_GOLD = np.uint64(0x9E3779B97F4A7C15)
_SM1 = np.uint64(0xBF58476D1CE4E5B9)
_SM2 = np.uint64(0x94D049BB133111EB)


def _mix64(x: np.ndarray) -> np.ndarray:
    x = (x ^ (x >> np.uint64(30))) * _SM1
    x = (x ^ (x >> np.uint64(27))) * _SM2
    return x ^ (x >> np.uint64(31))


def _seed(table: str, column: str, sf: float) -> np.uint64:
    # sf participates so FK ranges re-roll rather than truncate across SFs
    tag = f"{table}.{column}:{round(sf * 1000)}"
    with np.errstate(over="ignore"):
        return np.uint64(zlib.crc32(tag.encode()) + 0x1000) * _GOLD


def _u64(table: str, column: str, sf: float, idx: np.ndarray) -> np.ndarray:
    with np.errstate(over="ignore"):
        x = (idx.astype(np.uint64) + np.uint64(1)) * _GOLD
        return _mix64(x + _seed(table, column, sf))


def _ui(table: str, column: str, sf: float, idx: np.ndarray,
        lo: int, hi: int) -> np.ndarray:
    """Uniform integer in [lo, hi] (inclusive), int64."""
    span = np.uint64(hi - lo + 1)
    with np.errstate(over="ignore"):
        return (lo + (_u64(table, column, sf, idx) % span)
                .astype(np.int64))


def _coin(table: str, column: str, sf: float, idx: np.ndarray) -> np.ndarray:
    return (_u64(table, column, sf, idx) & np.uint64(1)) == 0


# ------------------------------------------------------------------ pools

_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_NATIONS = [  # (name, regionkey) per TPC-H spec
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4), ("JAPAN", 2),
    ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0), ("MOZAMBIQUE", 0),
    ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3), ("SAUDI ARABIA", 4),
    ("VIETNAM", 2), ("RUSSIA", 3), ("UNITED KINGDOM", 3),
    ("UNITED STATES", 1)]
_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "HOUSEHOLD", "MACHINERY"]
_PRIORITIES = ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
_SHIPMODES = ["AIR", "FOB", "MAIL", "RAIL", "REG AIR", "SHIP", "TRUCK"]
_INSTRUCTS = ["COLLECT COD", "DELIVER IN PERSON", "NONE", "TAKE BACK RETURN"]
_CONTAINERS = [f"{a} {b}" for a in ("SM", "LG", "MED", "JUMBO", "WRAP")
               for b in ("CASE", "BOX", "BAG", "JAR", "PKG", "PACK", "CAN",
                         "DRUM")]
_TYPE_S1 = ["STANDARD", "SMALL", "MEDIUM", "LARGE", "ECONOMY", "PROMO"]
_TYPE_S2 = ["ANODIZED", "BURNISHED", "PLATED", "POLISHED", "BRUSHED"]
_TYPE_S3 = ["TIN", "NICKEL", "BRASS", "STEEL", "COPPER"]
_COLORS = [
    "almond", "antique", "aquamarine", "azure", "beige", "bisque", "black",
    "blanched", "blue", "blush", "brown", "burlywood", "burnished",
    "chartreuse", "chiffon", "chocolate", "coral", "cornflower", "cornsilk",
    "cream", "cyan", "dark", "deep", "dim", "dodger", "drab", "firebrick",
    "floral", "forest", "frosted", "gainsboro", "ghost", "goldenrod", "green",
    "grey", "honeydew", "hot", "indian", "ivory", "khaki", "lace", "lavender",
    "lawn", "lemon", "light", "lime", "linen", "magenta", "maroon", "medium",
    "metallic", "midnight", "mint", "misty", "moccasin", "navajo", "navy",
    "olive", "orange", "orchid", "pale", "papaya", "peach", "peru", "pink",
    "plum", "powder", "puff", "purple", "red", "rose", "rosy", "royal",
    "saddle", "salmon", "sandy", "seashell", "sienna", "sky", "slate", "smoke",
    "snow", "spring", "steel", "tan", "thistle", "tomato", "turquoise",
    "violet", "wheat", "white", "yellow"]
_WORDS = [
    "about", "above", "according", "accounts", "after", "against", "along",
    "among", "around", "asymptotes", "attainments", "bold", "braids",
    "carefully", "courts", "deposits", "dependencies", "depths", "dolphins",
    "dugouts", "engage", "escapades", "even", "excuses", "express", "final",
    "fluffily", "foxes", "furiously", "gifts", "grouches", "ideas",
    "instructions", "ironic", "packages", "pending", "pinto", "platelets",
    "quickly", "quietly", "regular", "requests", "sauternes", "sentiments",
    "silent", "sleepy", "slyly", "special", "theodolites", "unusual",
    "waters", "wishes"]

_COMMENT_POOL_SIZE = 2048


def _comment_pool(max_len: int) -> List[str]:
    """Fixed pool of word-salad phrases (dbgen's grammar text replaced by a
    bounded pool; comments are filter targets only via LIKE, which operates
    on dictionary VALUES, so a bounded pool preserves query semantics on
    the generated data)."""
    pr = np.random.default_rng(12345)
    words = np.array(_WORDS)
    picks = pr.integers(0, len(words), size=(_COMMENT_POOL_SIZE, 5))
    return [" ".join(words[r])[:max_len] for r in picks]


class _Pool:
    """Sorted dictionary pool + raw-index -> sorted-code LUT."""

    __slots__ = ("sorted_values", "lut")

    def __init__(self, raw: Sequence[str]):
        arr = np.asarray(raw, dtype=object)
        self.sorted_values, inv = np.unique(arr, return_inverse=True)
        self.lut = inv.astype(np.int32)


_POOL_CACHE: Dict[tuple, _Pool] = {}


def _pool(key: str, build) -> _Pool:
    p = _POOL_CACHE.get(key)
    if p is None:
        p = _POOL_CACHE[key] = _Pool(build())
    return p


def _clerk_pool(sf: float) -> _Pool:
    n = max(2, int(1000 * sf))
    return _pool(f"clerk:{round(sf*1000)}",
                 lambda: [f"Clerk#{c:09d}" for c in range(1, n + 1)])


_PART_NAME_POOL_KEY = "p_name"


def _part_name_pool() -> _Pool:
    return _pool(_PART_NAME_POOL_KEY,
                 lambda: [f"{a} {b}" for a in _COLORS for b in _COLORS])


def _part_type_pool() -> _Pool:
    return _pool("p_type", lambda: [f"{a} {b} {c}" for a in _TYPE_S1
                                    for b in _TYPE_S2 for c in _TYPE_S3])


def _brand_pool() -> _Pool:
    return _pool("p_brand", lambda: [f"Brand#{m}{n}" for m in range(1, 6)
                                     for n in range(1, 6)])


def _mfgr_pool() -> _Pool:
    return _pool("p_mfgr",
                 lambda: [f"Manufacturer#{m}" for m in range(1, 6)])


# --------------------------------------------------------------- sizing

_BASE_ROWS = {"supplier": 10_000, "customer": 150_000, "part": 200_000,
              "orders": 1_500_000}


def _n(table: str, sf: float) -> int:
    return max(1, int(_BASE_ROWS[table] * sf))


_LINE_INDEX_CACHE: Dict[int, Tuple[np.ndarray, np.ndarray]] = {}


def _line_index(sf: float) -> Tuple[np.ndarray, np.ndarray]:
    """(lines per order int8, exclusive start offsets int64[len+1]).

    The seekable analog of dbgen's per-order line-count stream: chunk
    [a, b) of lineitem maps to orders via searchsorted on the offsets."""
    key = round(sf * 1000)
    got = _LINE_INDEX_CACHE.get(key)
    if got is None:
        norders = _n("orders", sf)
        lines = (1 + (_u64("lineitem", "l_count", sf,
                           np.arange(norders, dtype=np.uint64))
                      % np.uint64(7))).astype(np.int8)
        starts = np.zeros(norders + 1, dtype=np.int64)
        np.cumsum(lines, dtype=np.int64, out=starts[1:])
        got = _LINE_INDEX_CACHE[key] = (lines, starts)
    return got


def row_count(table: str, sf: float) -> int:
    if table == "region":
        return 5
    if table == "nation":
        return 25
    if table == "partsupp":
        return max(1, int(200_000 * sf)) * 4
    if table == "lineitem":
        return int(_line_index(sf)[1][-1])
    return _n(table, sf)


# ------------------------------------------------------- column streams
#
# The stream bodies below are ARRAY-MODULE AGNOSTIC: they receive an `idx`
# array that is either numpy (host generation: oracle loading, fallback
# path) or jax.numpy (device generation: the scan path evaluates the same
# hash streams ON the TPU — no 1-core host hashing, no column transfer).
# One shared code path is what makes the two bit-identical by construction.
# numpy-only constructs (arange/repeat/cumsum/errstate) stay in the
# chunk-level wrappers; inside streams only operators, astype, and the
# _where/_maximum/_take dispatch helpers are allowed.


def _is_np(x) -> bool:
    return isinstance(x, np.ndarray)


def _where(c, a, b):
    if _is_np(c):
        return np.where(c, a, b)
    import jax.numpy as jnp
    return jnp.where(c, a, b)


def _maximum(a, b):
    if _is_np(a):
        return np.maximum(a, b)
    import jax.numpy as jnp
    return jnp.maximum(a, b)


def _take(table_np: np.ndarray, idx):
    """Gather a small host constant table by (device or host) index."""
    if _is_np(idx):
        return table_np[idx]
    import jax.numpy as jnp
    return jnp.take(jnp.asarray(table_np), idx.astype(jnp.int64),
                    mode="clip")


def _retail_price(pk):
    # spec 4.2.3: 90000 + ((pk/10) mod 20001) + 100*(pk mod 1000)
    return 90000 + (pk // 10) % 20001 + 100 * (pk % 1000)


def _ps_suppkey(pk, i, nsupp: int):
    # spec: supplier spread formula
    return (pk + i * (nsupp // 4 + (pk - 1) // nsupp)) % nsupp + 1


def _order_cols(sf: float, oidx, which: str):
    """Order-level streams evaluated at arbitrary order indexes (0-based) —
    lineitem chunks call these with their covered order ids, which is what
    makes l_orderkey/l_shipdate consistent with the orders table without
    materializing it."""
    if which == "o_orderdate":
        return _ui("orders", "o_orderdate", sf, oidx, MIN_DATE,
                   MAX_ORDER_DATE - 152).astype(np.int32)
    if which == "o_custkey":
        ncust = _n("customer", sf)
        ck = _ui("orders", "o_custkey", sf, oidx, 1, max(ncust, 2))
        # spec: a third of customers place no orders
        return _where(ck % 3 == 0, _maximum((ck + 1) % (ncust + 1), 1),
                      ck)
    raise KeyError(which)


def _lineitem_rowmap(sf: float, start: int, end: int
                     ) -> Tuple[np.ndarray, np.ndarray]:
    """Row range [start, end) -> (order index per row, line number 1-based)."""
    lines, starts = _line_index(sf)
    o_first = int(np.searchsorted(starts, start, side="right")) - 1
    o_last = int(np.searchsorted(starts, end - 1, side="right")) - 1
    reps = lines[o_first:o_last + 1].astype(np.int64)
    rel = np.repeat(np.arange(len(reps), dtype=np.int64), reps)
    row0 = int(starts[o_first])
    rel = rel[start - row0:end - row0]
    oidx = o_first + rel
    within = np.arange(start, end, dtype=np.int64) - starts[oidx]
    return oidx, within + 1


def column_stream(table: str, sf: float, column: str, idx,
                  oidx=None):
    """One numeric column evaluated at arbitrary row indexes `idx` (uint64,
    numpy OR jax array — shared path, see module note). `oidx` is the
    0-based covering order index per row, required for lineitem's
    order-correlated columns (l_orderkey/dates). Dates are int32 days;
    decimals are scaled int64 (decimal(12,2) -> cents)."""
    i64 = idx.astype(np.int64)
    if table == "region" and column == "r_regionkey":
        return i64
    if table == "nation":
        if column == "n_nationkey":
            return i64
        if column == "n_regionkey":
            return _take(np.array([x[1] for x in _NATIONS],
                                  dtype=np.int64), i64)
    if table == "supplier":
        if column == "s_suppkey":
            return i64 + 1
        if column == "s_nationkey":
            return _ui(table, column, sf, idx, 0, 24)
        if column == "s_acctbal":
            return _ui(table, column, sf, idx, -99999, 999999)
    if table == "customer":
        if column == "c_custkey":
            return i64 + 1
        if column == "c_nationkey":
            return _ui(table, column, sf, idx, 0, 24)
        if column == "c_acctbal":
            return _ui(table, column, sf, idx, -99999, 999999)
    if table == "part":
        pk = i64 + 1
        if column == "p_partkey":
            return pk
        if column == "p_size":
            return _ui(table, column, sf, idx, 1, 50).astype(np.int32)
        if column == "p_retailprice":
            return _retail_price(pk)
    if table == "partsupp":
        pk = i64 // 4 + 1
        i4 = i64 % 4
        if column == "ps_partkey":
            return pk
        if column == "ps_suppkey":
            return _ps_suppkey(pk, i4, max(1, int(10_000 * sf)))
        if column == "ps_availqty":
            return _ui(table, column, sf, idx, 1, 9999).astype(np.int32)
        if column == "ps_supplycost":
            return _ui(table, column, sf, idx, 100, 100000)
    if table == "orders":
        if column == "o_orderkey":
            return i64 + 1
        if column in ("o_custkey", "o_orderdate"):
            return _order_cols(sf, idx, column)
        if column == "o_totalprice":
            return _ui(table, column, sf, idx, 85000, 55558641)
        if column == "o_shippriority":
            return (i64 * 0).astype(np.int32)
    if table == "lineitem":
        if column == "l_orderkey":
            return oidx.astype(np.int64) + 1
        if column == "l_partkey":
            return _ui(table, column, sf, idx, 1,
                       max(1, int(200_000 * sf)))
        if column == "l_suppkey":
            pk = _ui(table, "l_partkey", sf, idx, 1,
                     max(1, int(200_000 * sf)))
            i4 = _ui(table, "l_i4", sf, idx, 0, 3)
            return _ps_suppkey(pk, i4, max(1, int(10_000 * sf)))
        if column == "l_quantity":
            return _ui(table, column, sf, idx, 1, 50) * 100
        if column == "l_extendedprice":
            pk = _ui(table, "l_partkey", sf, idx, 1,
                     max(1, int(200_000 * sf)))
            qty = _ui(table, "l_quantity", sf, idx, 1, 50)
            return qty * _retail_price(pk)
        if column == "l_discount":
            return _ui(table, column, sf, idx, 0, 10)
        if column == "l_tax":
            return _ui(table, column, sf, idx, 0, 8)
        if column == "l_shipdate":
            odate = _order_cols(sf, oidx.astype(np.uint64), "o_orderdate")
            return (odate + _ui(table, "l_sdays", sf, idx, 1, 121)
                    ).astype(np.int32)
        if column == "l_commitdate":
            odate = _order_cols(sf, oidx.astype(np.uint64), "o_orderdate")
            return (odate + _ui(table, "l_cdays", sf, idx, 30, 90)
                    ).astype(np.int32)
        if column == "l_receiptdate":
            sdate = column_stream(table, sf, "l_shipdate", idx, oidx)
            return (sdate + _ui(table, "l_rdays", sf, idx, 1, 30)
                    ).astype(np.int32)
    raise KeyError(f"{table}.{column} is not a numeric stream")


def numeric_chunk(table: str, sf: float, column: str,
                  start: int, end: int) -> np.ndarray:
    """Host (numpy) evaluation of column_stream for a row range."""
    idx = np.arange(start, end, dtype=np.uint64)
    oidx = None
    if table == "lineitem":
        oidx, lineno = _lineitem_rowmap(sf, start, end)
        if column == "l_linenumber":
            return lineno.astype(np.int32)
    with np.errstate(over="ignore"):
        return column_stream(table, sf, column, idx, oidx)


# string columns -> ("pooled", pool_fn) | ("formatted", None)
_STRING_KIND: Dict[Tuple[str, str], str] = {
    ("region", "r_name"): "pooled", ("region", "r_comment"): "pooled",
    ("nation", "n_name"): "pooled", ("nation", "n_comment"): "pooled",
    ("supplier", "s_name"): "formatted",
    ("supplier", "s_address"): "pooled",
    ("supplier", "s_phone"): "formatted",
    ("supplier", "s_comment"): "pooled",
    ("customer", "c_name"): "formatted",
    ("customer", "c_address"): "pooled",
    ("customer", "c_phone"): "formatted",
    ("customer", "c_mktsegment"): "pooled",
    ("customer", "c_comment"): "pooled",
    ("part", "p_name"): "pooled", ("part", "p_mfgr"): "pooled",
    ("part", "p_brand"): "pooled", ("part", "p_type"): "pooled",
    ("part", "p_container"): "pooled", ("part", "p_comment"): "pooled",
    ("partsupp", "ps_comment"): "pooled",
    ("orders", "o_orderstatus"): "pooled",
    ("orders", "o_orderpriority"): "pooled",
    ("orders", "o_clerk"): "pooled",
    ("orders", "o_comment"): "pooled",
    ("lineitem", "l_returnflag"): "pooled",
    ("lineitem", "l_linestatus"): "pooled",
    ("lineitem", "l_shipinstruct"): "pooled",
    ("lineitem", "l_shipmode"): "pooled",
    ("lineitem", "l_comment"): "pooled",
}

_COMMENT_LEN = {"r_comment": 152, "n_comment": 152, "s_comment": 101,
                "s_address": 40, "c_comment": 117, "c_address": 40,
                "p_comment": 23, "ps_comment": 199, "o_comment": 79,
                "l_comment": 44}


def string_kind(table: str, column: str) -> Optional[str]:
    return _STRING_KIND.get((table, column))


def _static_pool(key: str, values: Sequence[str]) -> _Pool:
    return _pool(key, lambda: list(values))


def _pool_for(table: str, column: str, sf: float) -> _Pool:
    if column in _COMMENT_LEN:
        ln = _COMMENT_LEN[column]
        return _pool(f"comment:{ln}", lambda: _comment_pool(ln))
    if column == "r_name":
        return _static_pool("r_name", _REGIONS)
    if column == "n_name":
        return _static_pool("n_name", [x[0] for x in _NATIONS])
    if column == "c_mktsegment":
        return _static_pool("c_mktsegment", _SEGMENTS)
    if column == "p_name":
        return _part_name_pool()
    if column == "p_mfgr":
        return _mfgr_pool()
    if column == "p_brand":
        return _brand_pool()
    if column == "p_type":
        return _part_type_pool()
    if column == "p_container":
        return _static_pool("p_container", _CONTAINERS)
    if column == "o_orderstatus":
        return _static_pool("o_orderstatus", ["F", "O", "P"])
    if column == "o_orderpriority":
        return _static_pool("o_orderpriority", _PRIORITIES)
    if column == "o_clerk":
        return _clerk_pool(sf)
    if column == "l_returnflag":
        return _static_pool("l_returnflag", ["A", "N", "R"])
    if column == "l_linestatus":
        return _static_pool("l_linestatus", ["F", "O"])
    if column == "l_shipinstruct":
        return _static_pool("l_shipinstruct", _INSTRUCTS)
    if column == "l_shipmode":
        return _static_pool("l_shipmode", _SHIPMODES)
    raise KeyError(f"{table}.{column} has no pool")


def pool_values(table: str, column: str, sf: float) -> np.ndarray:
    """Sorted dictionary values for a pooled string column."""
    return _pool_for(table, column, sf).sorted_values


def code_stream(table: str, sf: float, column: str, idx, oidx=None):
    """RAW pool index for a pooled column at row indexes `idx` (shared
    numpy/jax path; the caller maps raw -> sorted code via the pool LUT)."""
    if column in _COMMENT_LEN:
        return (_u64(table, column, sf, idx)
                % np.uint64(_COMMENT_POOL_SIZE)).astype(np.int64)
    if column in ("r_name", "n_name"):
        return idx.astype(np.int64)
    if column == "c_mktsegment":
        return _ui(table, column, sf, idx, 0, 4)
    if column == "p_name":
        c1 = _ui(table, "p_name1", sf, idx, 0, len(_COLORS) - 1)
        c2 = _ui(table, "p_name2", sf, idx, 0, len(_COLORS) - 1)
        return c1 * len(_COLORS) + c2
    if column == "p_mfgr":
        return _ui(table, "p_mfgr", sf, idx, 0, 4)
    if column == "p_brand":
        m = _ui(table, "p_mfgr", sf, idx, 0, 4)      # consistent with mfgr
        return m * 5 + _ui(table, "p_brandn", sf, idx, 0, 4)
    if column == "p_type":
        return _ui(table, column, sf, idx, 0,
                   len(_TYPE_S1) * len(_TYPE_S2) * len(_TYPE_S3) - 1)
    if column == "p_container":
        return _ui(table, column, sf, idx, 0, len(_CONTAINERS) - 1)
    if column == "o_orderstatus":
        odate = _order_cols(sf, idx, "o_orderdate").astype(np.int64)
        fulfilled = odate + 151 < CURRENT_DATE
        half = _coin(table, column, sf, idx)
        return _where(fulfilled, 0, _where(half, 1, 2))
    if column == "o_orderpriority":
        return _ui(table, column, sf, idx, 0, 4)
    if column == "o_clerk":
        return _ui(table, column, sf, idx, 0, max(2, int(1000 * sf)) - 1)
    if column in ("l_returnflag", "l_linestatus"):
        if column == "l_linestatus":
            sdate = column_stream(table, sf, "l_shipdate", idx, oidx) \
                .astype(np.int64)
            return _where(sdate > CURRENT_DATE, 1, 0)   # O / F
        rdate = column_stream(table, sf, "l_receiptdate", idx, oidx) \
            .astype(np.int64)
        returned = rdate <= CURRENT_DATE
        half = _coin(table, column, sf, idx)
        # pool sorted A,N,R: returned -> R or A, else N
        return _where(returned, _where(half, 2, 0), 1)
    if column == "l_shipinstruct":
        return _ui(table, column, sf, idx, 0, len(_INSTRUCTS) - 1)
    if column == "l_shipmode":
        return _ui(table, column, sf, idx, 0, len(_SHIPMODES) - 1)
    raise KeyError(f"{table}.{column} is not pooled")


def codes_chunk(table: str, sf: float, column: str,
                start: int, end: int) -> np.ndarray:
    """int32 codes (into pool_values' SORTED order) for a pooled column."""
    p = _pool_for(table, column, sf)
    idx = np.arange(start, end, dtype=np.uint64)
    oidx = None
    if table == "lineitem" and column in ("l_returnflag", "l_linestatus"):
        oidx, _ = _lineitem_rowmap(sf, start, end)
    with np.errstate(over="ignore"):
        raw = code_stream(table, sf, column, idx, oidx)
    return p.lut[raw]


def _phone(nation: np.ndarray, seq: np.ndarray) -> np.ndarray:
    country = nation + 10
    p1 = (seq * 7919 + 13) % 900 + 100
    p2 = (seq * 104729 + 7) % 900 + 100
    p3 = (seq * 1299709 + 3) % 9000 + 1000
    return np.array([f"{c}-{a}-{b}-{d}" for c, a, b, d in
                     zip(country, p1, p2, p3)], dtype=object)


def object_chunk(table: str, sf: float, column: str,
                 start: int, end: int) -> np.ndarray:
    """Python-object strings for a row range — formatted (per-row unique)
    columns, plus pooled columns decoded (oracle loading path). High-
    cardinality formatted columns are generated ONLY when a query actually
    reads them."""
    kind = string_kind(table, column)
    if kind == "pooled":
        p = _pool_for(table, column, sf)
        return p.sorted_values[codes_chunk(table, sf, column, start, end)]
    seq = np.arange(start, end, dtype=np.int64)
    if column in ("s_name", "c_name"):
        prefix = "Supplier" if column == "s_name" else "Customer"
        return np.array([f"{prefix}#{i:09d}" for i in seq + 1], dtype=object)
    if column in ("s_phone", "c_phone"):
        t = "supplier" if column == "s_phone" else "customer"
        nk = "s_nationkey" if column == "s_phone" else "c_nationkey"
        nation = numeric_chunk(t, sf, nk, start, end)
        return _phone(nation, seq)
    raise KeyError(f"{table}.{column}")
