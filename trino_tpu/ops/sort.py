"""Sort / TopN / Limit operators.

Reference parity: operator/OrderByOperator.java (389) + PagesIndex.java with
codegen'd PagesIndexComparator (sql/gen/OrderingCompiler.java), TopNOperator
.java, LimitOperator. On TPU: multi-operand `lax.sort` (bitonic, fully on the
VPU) with null-ordering flags as leading sub-keys replaces comparator codegen.

Ordering semantics (Trino): ASC defaults to NULLS LAST, DESC to NULLS FIRST;
ORDER BY is stable w.r.t. input order via a trailing row-index key.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional, Sequence

import jax
import jax.numpy as jnp

from trino_tpu import types as T
from trino_tpu.page import Page


@dataclasses.dataclass(frozen=True)
class SortKey:
    channel: int
    ascending: bool = True
    nulls_first: Optional[bool] = None  # None = Trino default for direction

    def resolved_nulls_first(self) -> bool:
        if self.nulls_first is not None:
            return self.nulls_first
        return not self.ascending


def _descending_form(values: jnp.ndarray) -> jnp.ndarray:
    """Map values so ascending sort yields descending order."""
    if values.dtype == jnp.bool_:
        return ~values
    if jnp.issubdtype(values.dtype, jnp.floating):
        # flip sign; NaN handled by leading nan-flag key (Trino: NaN largest)
        return -values
    if jnp.issubdtype(values.dtype, jnp.unsignedinteger):
        return ~values
    return -values  # int overflow only at INT_MIN; acceptable round 1


def _sort_operands(page: Page, keys: Sequence[SortKey]):
    dead = ~page.row_mask()
    operands = [dead]
    for k in keys:
        col = page.column(k.channel)
        values = col.values
        is_float = jnp.issubdtype(values.dtype, jnp.floating)
        if col.valid is not None:
            null_flag = ~col.valid
            flag = ~null_flag if k.resolved_nulls_first() else null_flag
            operands.append(flag)
            values = jnp.where(col.valid, values, jnp.zeros((), values.dtype))
        if is_float:
            # Trino orders NaN as largest; XLA's default float order already
            # totals NaN last ascending, but make it explicit & desc-correct
            nan = jnp.isnan(values)
            nan_key = nan if k.ascending else ~nan
            operands.append(nan_key)
            values = jnp.where(nan, jnp.zeros((), values.dtype), values)
        operands.append(values if k.ascending else _descending_form(values))
    return operands


def order_by(keys: Sequence[SortKey]) -> Callable[[Page], Page]:
    """Full sort of the page by keys (stable)."""
    keys = tuple(keys)

    def op(page: Page) -> Page:
        n = page.capacity
        operands = _sort_operands(page, keys)
        perm = jnp.arange(n, dtype=jnp.int32)
        out = jax.lax.sort(operands + [perm], num_keys=len(operands) + 1)
        order = out[-1]
        return page.gather(order, page.num_rows)

    return op


def top_n_masked(keys: Sequence[SortKey]) -> Callable[[Page, Any], Page]:
    """ORDER BY ... LIMIT ? with the COUNT as a runtime operand: the
    sort runs at full page capacity and the count only masks `num_rows`,
    so nothing in the traced program depends on it — one jitted
    executable (keyed literal-free, like a hoisted parameter) serves
    LIMIT 5 and LIMIT 500 of the same shape. This is what lets a warmup
    manifest cover a whole `LIMIT k` family with one compile."""
    sort_op = order_by(keys)

    def op(page: Page, count) -> Page:
        out = sort_op(page)
        return Page(out.columns,
                    jnp.minimum(out.num_rows,
                                jnp.asarray(count, dtype=jnp.int32)))

    return op


def top_n(count: int, keys: Sequence[SortKey]) -> Callable[[Page], Page]:
    """ORDER BY ... LIMIT n with the count baked in (TopNOperator
    analog): the masked kernel with a fixed count — mesh programs and
    other static callers keep this shape."""
    masked = top_n_masked(keys)

    def op(page: Page) -> Page:
        return masked(page, count)

    return op


def limit(count: int) -> Callable[[Page], Page]:
    def op(page: Page) -> Page:
        return Page(page.columns, jnp.minimum(page.num_rows, count))

    return op
