"""Recursive-descent SQL parser.

Reference parity: core/trino-parser SqlParser.java + AstBuilder.java over
SqlBase.g4 (1001 lines). Grammar coverage: full query expressions (WITH,
set operations, joins, grouping sets, window functions), the expression
grammar with Trino's precedence (OR < AND < NOT < comparison/predicates <
additive < multiplicative < unary < postfix), EXPLAIN [ANALYZE], SHOW,
SET/RESET SESSION, CREATE TABLE [AS], INSERT, DELETE, DROP, USE,
PREPARE/EXECUTE/DEALLOCATE, transactions.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from trino_tpu.sql import tree as t
from trino_tpu.sql.lexer import ParsingError, Token, tokenize

_COMPARISON_OPS = {"=", "<>", "!=", "<", "<=", ">", ">="}


class _Parser:
    def __init__(self, tokens: List[Token]):
        self.tokens = tokens
        self.pos = 0

    # ------------------------------------------------------------- utilities

    def peek(self, offset: int = 0) -> Token:
        return self.tokens[min(self.pos + offset, len(self.tokens) - 1)]

    def next(self) -> Token:
        tok = self.tokens[self.pos]
        if tok.kind != "EOF":
            self.pos += 1
        return tok

    def at_keyword(self, *words: str) -> bool:
        tok = self.peek()
        return tok.kind in ("KEYWORD", "IDENT") and tok.upper in words

    def accept_keyword(self, *words: str) -> bool:
        if self.at_keyword(*words):
            self.next()
            return True
        return False

    def expect_keyword(self, word: str) -> Token:
        if not self.at_keyword(word):
            self.error(f"expected {word}")
        return self.next()

    def at_op(self, *ops: str) -> bool:
        tok = self.peek()
        return tok.kind == "OP" and tok.text in ops

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> Token:
        if not self.at_op(op):
            self.error(f"expected '{op}'")
        return self.next()

    def error(self, message: str):
        tok = self.peek()
        got = tok.text or "<eof>"
        raise ParsingError(f"{message}, found {got!r}", tok.line, tok.column)

    def identifier(self) -> t.Identifier:
        tok = self.peek()
        if tok.kind == "IDENT":
            self.next()
            return t.Identifier(tok.text.lower())
        if tok.kind == "QIDENT":
            self.next()
            return t.Identifier(tok.text, quoted=True)
        # non-reserved keywords usable as identifiers
        if tok.kind == "KEYWORD" and tok.upper not in (
                "SELECT", "FROM", "WHERE", "GROUP", "HAVING", "ORDER",
                "UNION", "INTERSECT", "EXCEPT", "ON", "JOIN", "AND", "OR"):
            self.next()
            return t.Identifier(tok.text.lower())
        self.error("expected identifier")

    def qualified_name(self) -> t.QualifiedName:
        parts = [self.identifier().value]
        while self.at_op(".") and self.peek(1).kind in (
                "IDENT", "QIDENT", "KEYWORD"):
            self.next()
            parts.append(self.identifier().value)
        return t.QualifiedName(tuple(parts))

    # ------------------------------------------------------------ statements

    def statement(self) -> t.Statement:
        if self.at_keyword("SELECT", "WITH", "VALUES") or self.at_op("("):
            return self.query()
        if self.at_keyword("EXPLAIN"):
            return self.explain()
        if self.at_keyword("SHOW"):
            return self.show()
        if self.at_keyword("SET"):
            return self.set_session()
        if self.at_keyword("RESET"):
            self.next()
            self.expect_keyword("SESSION")
            return t.ResetSession(self.qualified_name())
        if self.at_keyword("CREATE"):
            return self.create()
        if self.at_keyword("DROP"):
            return self.drop()
        if self.at_keyword("INSERT"):
            return self.insert()
        if self.at_keyword("DELETE"):
            return self.delete()
        if self.at_keyword("USE"):
            return self.use()
        if self.at_keyword("PREPARE"):
            self.next()
            name = self.identifier()
            self.expect_keyword("FROM")
            return t.Prepare(name, self.statement())
        if self.at_keyword("EXECUTE"):
            self.next()
            name = self.identifier()
            params: Tuple[t.Expression, ...] = ()
            if self.accept_keyword("USING"):
                params = tuple(self.expression_list())
            return t.ExecuteStatement(name, params)
        if self.at_keyword("DEALLOCATE"):
            self.next()
            self.expect_keyword("PREPARE")
            return t.Deallocate(self.identifier())
        if self.at_keyword("COMMIT"):
            self.next()
            return t.Commit()
        if self.at_keyword("ROLLBACK"):
            self.next()
            return t.Rollback()
        if self.at_keyword("START"):
            self.next()
            self.expect_keyword("TRANSACTION")
            return t.StartTransaction()
        if self.at_keyword("ANALYZE"):
            self.next()
            return t.Analyze(self.qualified_name())
        if self.at_keyword("REFRESH"):
            self.next()
            self.expect_keyword("MATERIALIZED")
            self.expect_keyword("VIEW")
            return t.RefreshMaterializedView(self.qualified_name())
        self.error("unexpected statement")

    def explain(self) -> t.Explain:
        self.expect_keyword("EXPLAIN")
        analyze = self.accept_keyword("ANALYZE")
        explain_type = "DISTRIBUTED"
        if self.accept_op("("):
            while True:
                if self.accept_keyword("TYPE"):
                    explain_type = self.next().upper
                elif self.accept_keyword("FORMAT"):
                    self.next()
                else:
                    self.error("expected TYPE or FORMAT")
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        return t.Explain(self.statement(), analyze, explain_type)

    def show(self) -> t.Statement:
        self.expect_keyword("SHOW")
        if self.accept_keyword("TABLES"):
            schema = None
            if self.accept_keyword("FROM", "IN"):
                schema = self.qualified_name()
            like = None
            if self.accept_keyword("LIKE"):
                like = self.next().text
            return t.ShowTables(schema, like)
        if self.accept_keyword("SCHEMAS"):
            catalog = None
            if self.accept_keyword("FROM", "IN"):
                catalog = self.identifier().value
            return t.ShowSchemas(catalog)
        if self.accept_keyword("CATALOGS"):
            return t.ShowCatalogs()
        if self.accept_keyword("COLUMNS"):
            self.expect_keyword("FROM")
            return t.ShowColumns(self.qualified_name())
        if self.accept_keyword("SESSION"):
            return t.ShowSession()
        if self.accept_keyword("FUNCTIONS"):
            return t.ShowFunctions()
        if self.accept_keyword("STATS"):
            self.expect_keyword("FOR")
            if self.accept_op("("):
                rel = t.TableSubquery(self.query())
                self.expect_op(")")
            else:
                rel = t.Table(self.qualified_name())
            return t.ShowStats(rel)
        self.error("unsupported SHOW")

    def set_session(self) -> t.SetSession:
        self.expect_keyword("SET")
        self.expect_keyword("SESSION")
        name = self.qualified_name()
        self.expect_op("=")
        return t.SetSession(name, self.expression())

    def create(self) -> t.Statement:
        self.expect_keyword("CREATE")
        if self.accept_keyword("SCHEMA"):
            not_exists = self._if_not_exists()
            return t.CreateSchema(self.qualified_name(), not_exists)
        replace = False
        if self.accept_keyword("OR"):
            self.expect_keyword("REPLACE")
            replace = True
        if self.accept_keyword("MATERIALIZED"):
            self.expect_keyword("VIEW")
            not_exists = self._if_not_exists()
            name = self.qualified_name()
            props = self._with_properties()
            self.expect_keyword("AS")
            return t.CreateMaterializedView(
                name, self.query(), replace, not_exists, props)
        if self.accept_keyword("VIEW"):
            name = self.qualified_name()
            self.expect_keyword("AS")
            return t.CreateView(name, self.query(), replace)
        self.expect_keyword("TABLE")
        not_exists = self._if_not_exists()
        name = self.qualified_name()
        if self.at_op("(") and not self.peek(1).upper == "SELECT":
            self.expect_op("(")
            cols = []
            while True:
                cname = self.identifier()
                ctype = self.type_name()
                nullable = True
                if self.accept_keyword("NOT"):
                    self.expect_keyword("NULL")
                    nullable = False
                cols.append(t.ColumnDefinition(cname, ctype, nullable))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            props = self._with_properties()
            return t.CreateTable(name, tuple(cols), not_exists, props)
        props = self._with_properties()
        self.expect_keyword("AS")
        query = self.query()
        with_data = True
        if self.accept_keyword("WITH"):
            if self.accept_keyword("NO"):
                with_data = False
            self.expect_keyword("DATA")
        return t.CreateTableAsSelect(name, query, not_exists, with_data, props)

    def _if_not_exists(self) -> bool:
        if self.accept_keyword("IF"):
            self.expect_keyword("NOT")
            self.expect_keyword("EXISTS")
            return True
        return False

    def _with_properties(self):
        props = []
        if self.accept_keyword("WITH"):
            self.expect_op("(")
            while True:
                key = self.identifier().value
                self.expect_op("=")
                props.append((key, self.expression()))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
        return tuple(props)

    def drop(self) -> t.Statement:
        self.expect_keyword("DROP")
        kind = "VIEW" if self.accept_keyword("VIEW") else None
        if kind is None and self.accept_keyword("MATERIALIZED"):
            self.expect_keyword("VIEW")
            kind = "MATERIALIZED VIEW"
        if kind is None:
            if self.accept_keyword("SCHEMA"):
                kind = "SCHEMA"
            else:
                self.expect_keyword("TABLE")
                kind = "TABLE"
        exists = False
        if self.accept_keyword("IF"):
            self.expect_keyword("EXISTS")
            exists = True
        name = self.qualified_name()
        if kind == "MATERIALIZED VIEW":
            return t.DropMaterializedView(name, exists)
        if kind == "VIEW":
            return t.DropView(name, exists)
        if kind == "SCHEMA":
            return t.DropSchema(name, exists)
        return t.DropTable(name, exists)

    def insert(self) -> t.Insert:
        self.expect_keyword("INSERT")
        self.expect_keyword("INTO")
        target = self.qualified_name()
        columns: Tuple[t.Identifier, ...] = ()
        if self.at_op("(") and self.peek(1).upper not in ("SELECT", "WITH",
                                                          "VALUES"):
            self.expect_op("(")
            cols = [self.identifier()]
            while self.accept_op(","):
                cols.append(self.identifier())
            self.expect_op(")")
            columns = tuple(cols)
        return t.Insert(target, self.query(), columns)

    def delete(self) -> t.Delete:
        self.expect_keyword("DELETE")
        self.expect_keyword("FROM")
        table = self.qualified_name()
        where = self.expression() if self.accept_keyword("WHERE") else None
        return t.Delete(table, where)

    def use(self) -> t.Use:
        self.expect_keyword("USE")
        first = self.identifier()
        if self.accept_op("."):
            return t.Use(first, self.identifier())
        return t.Use(None, first)

    # ----------------------------------------------------- query expressions

    def query(self) -> t.Query:
        with_ = None
        if self.accept_keyword("WITH"):
            recursive = self.accept_keyword("RECURSIVE")
            queries = [self.with_query()]
            while self.accept_op(","):
                queries.append(self.with_query())
            with_ = t.With(recursive, tuple(queries))
        body, order_by, offset, limit = self.query_no_with()
        return t.Query(body, with_, order_by, offset, limit)

    def with_query(self) -> t.WithQuery:
        name = self.identifier()
        column_names: Tuple[t.Identifier, ...] = ()
        if self.accept_op("("):
            cols = [self.identifier()]
            while self.accept_op(","):
                cols.append(self.identifier())
            self.expect_op(")")
            column_names = tuple(cols)
        self.expect_keyword("AS")
        self.expect_op("(")
        query = self.query()
        self.expect_op(")")
        return t.WithQuery(name, query, column_names)

    def query_no_with(self):
        body = self.query_term()
        order_by: Tuple[t.SortItem, ...] = ()
        if self.accept_keyword("ORDER"):
            self.expect_keyword("BY")
            order_by = self.sort_items()
        offset = None
        if self.accept_keyword("OFFSET"):
            offset = self.expression()
            self.accept_keyword("ROW", "ROWS")
        limit = None
        if self.accept_keyword("LIMIT"):
            if self.accept_keyword("ALL"):
                limit = None
            else:
                limit = self.expression()
        elif self.accept_keyword("FETCH"):
            self.accept_keyword("FIRST", "NEXT")
            limit = self.expression()
            self.accept_keyword("ROW", "ROWS")
            self.accept_keyword("ONLY")
        # hoist trailing clauses into a bare QuerySpecification (Trino's
        # AstBuilder does the same when the body is a simple select)
        if isinstance(body, t.QuerySpecification) and not (
                body.order_by or body.limit or body.offset):
            body = t.QuerySpecification(
                body.select, body.from_, body.where, body.group_by,
                body.having, order_by, offset, limit)
            return body, (), None, None
        return body, order_by, offset, limit

    def query_term(self) -> t.QueryBody:
        left = self.query_term2()
        while self.at_keyword("UNION", "EXCEPT"):
            op = self.next().upper
            distinct = not self.accept_keyword("ALL")
            self.accept_keyword("DISTINCT")
            right = self.query_term2()
            left = t.SetOperation(op, distinct, left, right)
        return left

    def query_term2(self) -> t.QueryBody:
        left = self.query_primary()
        while self.at_keyword("INTERSECT"):
            self.next()
            distinct = not self.accept_keyword("ALL")
            self.accept_keyword("DISTINCT")
            right = self.query_primary()
            left = t.SetOperation("INTERSECT", distinct, left, right)
        return left

    def query_primary(self) -> t.QueryBody:
        if self.at_keyword("SELECT"):
            return self.query_specification()
        if self.accept_keyword("VALUES"):
            rows = [self.expression()]
            while self.accept_op(","):
                rows.append(self.expression())
            q = t.Values(tuple(rows))
            return t.QuerySpecification(
                t.Select(False, (t.AllColumns(),)), q)
        if self.accept_op("("):
            body, order_by, offset, limit = self.query_no_with()
            self.expect_op(")")
            if order_by or offset or limit:
                # parenthesized query with its own ordering
                return t.QuerySpecification(
                    t.Select(False, (t.AllColumns(),)),
                    t.TableSubquery(t.Query(body, None, order_by, offset,
                                            limit)))
            return body
        self.error("expected query")

    def query_specification(self) -> t.QuerySpecification:
        self.expect_keyword("SELECT")
        distinct = False
        if self.accept_keyword("DISTINCT"):
            distinct = True
        else:
            self.accept_keyword("ALL")
        items = [self.select_item()]
        while self.accept_op(","):
            items.append(self.select_item())
        from_ = None
        if self.accept_keyword("FROM"):
            from_ = self.relation()
            while self.accept_op(","):
                right = self.relation()
                from_ = t.Join("IMPLICIT", from_, right)
        where = self.expression() if self.accept_keyword("WHERE") else None
        group_by = None
        if self.accept_keyword("GROUP"):
            self.expect_keyword("BY")
            gdistinct = False
            if self.accept_keyword("DISTINCT"):
                gdistinct = True
            else:
                self.accept_keyword("ALL")
            group_by = t.GroupBy(gdistinct, tuple(self.grouping_elements()))
        having = self.expression() if self.accept_keyword("HAVING") else None
        return t.QuerySpecification(
            t.Select(distinct, tuple(items)), from_, where, group_by, having)

    def grouping_elements(self):
        elements = [self.grouping_element()]
        while self.accept_op(","):
            elements.append(self.grouping_element())
        return elements

    def grouping_element(self) -> t.GroupingElement:
        if self.at_keyword("ROLLUP") and self.peek(1).text == "(":
            self.next()
            self.expect_op("(")
            exprs = self.expression_list()
            self.expect_op(")")
            return t.Rollup(tuple(exprs))
        if self.at_keyword("CUBE") and self.peek(1).text == "(":
            self.next()
            self.expect_op("(")
            exprs = self.expression_list()
            self.expect_op(")")
            return t.Cube(tuple(exprs))
        if self.at_keyword("GROUPING") and self.peek(1).upper == "SETS":
            self.next()
            self.next()
            self.expect_op("(")
            sets = []
            while True:
                if self.accept_op("("):
                    if self.accept_op(")"):
                        sets.append(())
                    else:
                        sets.append(tuple(self.expression_list()))
                        self.expect_op(")")
                else:
                    sets.append((self.expression(),))
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return t.GroupingSets(tuple(sets))
        return t.SimpleGroupBy((self.expression(),))

    def select_item(self) -> t.Node:
        if self.at_op("*"):
            self.next()
            return t.AllColumns()
        # t.* / catalog.schema.t.*
        save = self.pos
        if self.peek().kind in ("IDENT", "QIDENT"):
            try:
                name = self.qualified_name()
                if self.at_op(".") and self.peek(1).text == "*":
                    self.next()
                    self.next()
                    return t.AllColumns(name)
            except ParsingError:
                pass
            self.pos = save
        expr = self.expression()
        alias = None
        if self.accept_keyword("AS"):
            alias = self.identifier()
        elif self.peek().kind in ("IDENT", "QIDENT"):
            alias = self.identifier()
        return t.SingleColumn(expr, alias)

    def sort_items(self) -> Tuple[t.SortItem, ...]:
        items = [self.sort_item()]
        while self.accept_op(","):
            items.append(self.sort_item())
        return tuple(items)

    def sort_item(self) -> t.SortItem:
        key = self.expression()
        ascending = True
        if self.accept_keyword("ASC"):
            pass
        elif self.accept_keyword("DESC"):
            ascending = False
        nulls_first = None
        if self.accept_keyword("NULLS"):
            if self.accept_keyword("FIRST"):
                nulls_first = True
            else:
                self.expect_keyword("LAST")
                nulls_first = False
        return t.SortItem(key, ascending, nulls_first)

    # -------------------------------------------------------------- relations

    def relation(self) -> t.Relation:
        left = self.sampled_relation()
        while True:
            if self.accept_keyword("CROSS"):
                self.expect_keyword("JOIN")
                right = self.sampled_relation()
                left = t.Join("CROSS", left, right)
                continue
            natural = self.at_keyword("NATURAL")
            if natural:
                self.next()
            join_type = None
            if self.accept_keyword("INNER"):
                join_type = "INNER"
            elif self.accept_keyword("LEFT"):
                self.accept_keyword("OUTER")
                join_type = "LEFT"
            elif self.accept_keyword("RIGHT"):
                self.accept_keyword("OUTER")
                join_type = "RIGHT"
            elif self.accept_keyword("FULL"):
                self.accept_keyword("OUTER")
                join_type = "FULL"
            if join_type is None and self.at_keyword("JOIN"):
                join_type = "INNER"
            if join_type is None:
                if natural:
                    self.error("expected JOIN after NATURAL")
                return left
            self.expect_keyword("JOIN")
            right = self.sampled_relation()
            criteria = None
            if not natural:
                if self.accept_keyword("ON"):
                    criteria = t.JoinOn(self.expression())
                elif self.accept_keyword("USING"):
                    self.expect_op("(")
                    cols = [self.identifier()]
                    while self.accept_op(","):
                        cols.append(self.identifier())
                    self.expect_op(")")
                    criteria = t.JoinUsing(tuple(cols))
            left = t.Join(join_type, left, right, criteria)

    def sampled_relation(self) -> t.Relation:
        rel = self.aliased_relation()
        if self.at_keyword("TABLESAMPLE"):
            self.next()
            self.next()  # BERNOULLI | SYSTEM
            self.expect_op("(")
            self.expression()
            self.expect_op(")")
        return rel

    def aliased_relation(self) -> t.Relation:
        rel = self.relation_primary()
        alias = None
        if self.accept_keyword("AS"):
            alias = self.identifier()
        elif self.peek().kind in ("IDENT", "QIDENT") and not self.at_keyword(
                "CROSS", "NATURAL", "INNER", "LEFT", "RIGHT", "FULL", "JOIN",
                "ON", "USING", "TABLESAMPLE"):
            alias = self.identifier()
        if alias is not None:
            column_names: Tuple[t.Identifier, ...] = ()
            if self.accept_op("("):
                cols = [self.identifier()]
                while self.accept_op(","):
                    cols.append(self.identifier())
                self.expect_op(")")
                column_names = tuple(cols)
            return t.AliasedRelation(rel, alias, column_names)
        return rel

    def relation_primary(self) -> t.Relation:
        if self.accept_op("("):
            if self.at_keyword("SELECT", "WITH", "VALUES"):
                query = self.query()
                self.expect_op(")")
                return t.TableSubquery(query)
            if self.at_op("("):
                # ambiguous: "((" opens either a nested subquery or a
                # parenthesized join tree (TPC-DS q72-style
                # "((((t JOIN ...) JOIN ...)"); try query, backtrack
                save = self.pos
                try:
                    query = self.query()
                    self.expect_op(")")
                    return t.TableSubquery(query)
                except ParsingError:
                    self.pos = save
            rel = self.relation()
            self.expect_op(")")
            return rel
        if self.at_keyword("UNNEST"):
            self.next()
            self.expect_op("(")
            exprs = self.expression_list()
            self.expect_op(")")
            with_ord = False
            if self.accept_keyword("WITH"):
                self.expect_keyword("ORDINALITY")
                with_ord = True
            return t.Unnest(tuple(exprs), with_ord)
        if self.at_keyword("VALUES"):
            self.next()
            rows = [self.expression()]
            while self.accept_op(","):
                rows.append(self.expression())
            return t.Values(tuple(rows))
        if self.at_keyword("TABLE"):
            self.next()
            return self._table_reference()
        if self.at_keyword("LATERAL"):
            self.next()
            self.expect_op("(")
            query = self.query()
            self.expect_op(")")
            return t.TableSubquery(query)
        return self._table_reference()

    def _table_reference(self) -> t.Table:
        """Table name with optional time travel:
        `name [FOR VERSION|TIMESTAMP AS OF <expr>]`."""
        name = self.qualified_name()
        version = timestamp = None
        if self.accept_keyword("FOR"):
            if self.accept_keyword("VERSION"):
                which = "version"
            elif self.accept_keyword("TIMESTAMP"):
                which = "timestamp"
            else:
                self.error("expected VERSION or TIMESTAMP after FOR")
            self.expect_keyword("AS")
            self.expect_keyword("OF")
            expr = self.expression()
            if which == "version":
                version = expr
            else:
                timestamp = expr
        return t.Table(name, version, timestamp)

    # ------------------------------------------------------------ expressions

    def expression_list(self) -> List[t.Expression]:
        out = [self.expression()]
        while self.accept_op(","):
            out.append(self.expression())
        return out

    def expression(self) -> t.Expression:
        return self.or_expression()

    def or_expression(self) -> t.Expression:
        left = self.and_expression()
        while self.at_keyword("OR"):
            self.next()
            left = t.LogicalBinary("OR", left, self.and_expression())
        return left

    def and_expression(self) -> t.Expression:
        left = self.not_expression()
        while self.at_keyword("AND"):
            self.next()
            left = t.LogicalBinary("AND", left, self.not_expression())
        return left

    def not_expression(self) -> t.Expression:
        if self.at_keyword("NOT"):
            self.next()
            return t.NotExpression(self.not_expression())
        return self.predicate()

    def predicate(self) -> t.Expression:
        left = self.value_expression()
        while True:
            if self.at_op(*_COMPARISON_OPS):
                op = self.next().text
                if op == "!=":
                    op = "<>"
                # quantified comparison: = ANY (subquery) etc.
                if self.at_keyword("ANY", "SOME", "ALL") and \
                        self.peek(1).text == "(":
                    self.error("quantified comparisons not supported")
                left = t.ComparisonExpression(op, left,
                                              self.value_expression())
                continue
            negated = False
            save = self.pos
            if self.at_keyword("NOT"):
                self.next()
                negated = True
            if self.accept_keyword("BETWEEN"):
                low = self.value_expression()
                self.expect_keyword("AND")
                high = self.value_expression()
                left = t.BetweenPredicate(left, low, high)
            elif self.accept_keyword("IN"):
                self.expect_op("(")
                if self.at_keyword("SELECT", "WITH"):
                    vl: t.Expression = t.SubqueryExpression(self.query())
                else:
                    vl = t.InListExpression(tuple(self.expression_list()))
                self.expect_op(")")
                left = t.InPredicate(left, vl)
            elif self.accept_keyword("LIKE"):
                pattern = self.value_expression()
                escape = None
                if self.accept_keyword("ESCAPE"):
                    escape = self.value_expression()
                left = t.LikePredicate(left, pattern, escape)
            elif self.accept_keyword("IS"):
                isnot = self.accept_keyword("NOT")
                if self.accept_keyword("NULL"):
                    left = t.IsNotNullPredicate(left) if isnot \
                        else t.IsNullPredicate(left)
                elif self.accept_keyword("DISTINCT"):
                    self.expect_keyword("FROM")
                    right = self.value_expression()
                    cmp = t.ComparisonExpression("IS DISTINCT FROM", left,
                                                 right)
                    left = t.NotExpression(cmp) if isnot else cmp
                elif self.accept_keyword("TRUE"):
                    cmp = t.ComparisonExpression(
                        "IS NOT DISTINCT FROM", left, t.BooleanLiteral(True))
                    left = t.NotExpression(cmp) if isnot else cmp
                elif self.accept_keyword("FALSE"):
                    cmp = t.ComparisonExpression(
                        "IS NOT DISTINCT FROM", left, t.BooleanLiteral(False))
                    left = t.NotExpression(cmp) if isnot else cmp
                else:
                    self.error("expected NULL or DISTINCT FROM after IS")
                if negated:
                    left = t.NotExpression(left)
                    negated = False
                continue
            else:
                if negated:
                    self.pos = save
                return left
            if negated:
                left = t.NotExpression(left)

    def value_expression(self) -> t.Expression:
        left = self.term()
        while self.at_op("+", "-", "||"):
            op = self.next().text
            right = self.term()
            if op == "||":
                left = t.FunctionCall(
                    t.QualifiedName(("concat",)), (left, right))
            else:
                left = t.ArithmeticBinary(op, left, right)
        return left

    def term(self) -> t.Expression:
        left = self.unary()
        while self.at_op("*", "/", "%"):
            op = self.next().text
            left = t.ArithmeticBinary(op, left, self.unary())
        return left

    def unary(self) -> t.Expression:
        if self.at_op("+"):
            self.next()
            return self.unary()
        if self.at_op("-"):
            self.next()
            value = self.unary()
            if isinstance(value, t.LongLiteral):
                return t.LongLiteral(-value.value)
            if isinstance(value, t.DoubleLiteral):
                return t.DoubleLiteral(-value.value)
            if isinstance(value, t.DecimalLiteral):
                return t.DecimalLiteral("-" + value.text)
            return t.ArithmeticUnary("-", value)
        return self.postfix()

    def postfix(self) -> t.Expression:
        expr = self.primary()
        while True:
            if self.at_op(".") and self.peek(1).kind in (
                    "IDENT", "QIDENT", "KEYWORD"):
                self.next()
                expr = t.DereferenceExpression(expr, self.identifier())
            elif self.at_op("["):
                self.next()
                index = self.expression()
                self.expect_op("]")
                expr = t.FunctionCall(
                    t.QualifiedName(("element_at",)), (expr, index))
            else:
                return expr

    _TYPE_KEYWORDS = (
        "VARCHAR", "CHAR", "DECIMAL", "NUMERIC", "BIGINT", "INTEGER", "INT",
        "SMALLINT", "TINYINT", "DOUBLE", "REAL", "BOOLEAN", "DATE",
        "TIMESTAMP", "TIME", "VARBINARY", "JSON", "ARRAY", "MAP", "ROW",
        "INTERVAL", "UUID")

    def type_name(self) -> str:
        tok = self.next()
        name = tok.text.lower()
        if name == "double" and self.at_keyword("PRECISION"):
            self.next()
        elif name == "timestamp" or name == "time":
            if self.accept_op("("):
                name += "(" + self.next().text + ")"
                self.expect_op(")")
            if self.at_keyword("WITH", "WITHOUT"):
                with_tz = self.next().upper == "WITH"
                self.expect_keyword("TIME")
                self.expect_keyword("ZONE")
                if with_tz:
                    name += " with time zone"
        elif self.at_op("("):
            self.next()
            params = [self.next().text]
            while self.accept_op(","):
                params.append(self.next().text)
            self.expect_op(")")
            name += "(" + ",".join(params) + ")"
        elif name == "array" or name == "map":
            if self.accept_op("<"):
                inner = [self.type_name()]
                while self.accept_op(","):
                    inner.append(self.type_name())
                self.expect_op(">")
                name += "(" + ",".join(inner) + ")"
        return name

    def primary(self) -> t.Expression:
        tok = self.peek()
        if tok.kind == "INTEGER":
            self.next()
            return t.LongLiteral(int(tok.text))
        if tok.kind == "DECIMAL":
            self.next()
            # Trino: unquoted decimal literal is DOUBLE unless
            # parse_decimal_literals_as_decimal; scientific notation = double
            if "e" in tok.text.lower():
                return t.DoubleLiteral(float(tok.text))
            return t.DecimalLiteral(tok.text)
        if tok.kind == "STRING":
            self.next()
            return t.StringLiteral(tok.text)
        if tok.kind == "PARAM":
            self.next()
            return t.Parameter(int(tok.text))
        if self.at_keyword("NULL"):
            self.next()
            return t.NullLiteral()
        if self.at_keyword("TRUE"):
            self.next()
            return t.BooleanLiteral(True)
        if self.at_keyword("FALSE"):
            self.next()
            return t.BooleanLiteral(False)
        if self.at_keyword("DATE") and self.peek(1).kind == "STRING":
            self.next()
            return t.DateLiteral(self.next().text)
        if self.at_keyword("DECIMAL") and self.peek(1).kind == "STRING":
            self.next()
            return t.DecimalLiteral(self.next().text)
        if self.at_keyword("TIMESTAMP") and self.peek(1).kind == "STRING":
            self.next()
            return t.TimestampLiteral(self.next().text)
        if self.at_keyword("INTERVAL") and self.peek(1).kind in ("STRING",
                                                                 "OP"):
            return self.interval()
        if self.at_keyword("CASE"):
            return self.case_expression()
        if self.at_keyword("CAST") or self.at_keyword("TRY_CAST"):
            safe = self.next().upper == "TRY_CAST"
            self.expect_op("(")
            value = self.expression()
            self.expect_keyword("AS")
            target = self.type_name()
            self.expect_op(")")
            return t.Cast(value, target, safe)
        if self.at_keyword("EXTRACT"):
            self.next()
            self.expect_op("(")
            field = self.next().upper
            self.expect_keyword("FROM")
            value = self.expression()
            self.expect_op(")")
            return t.Extract(field, value)
        if self.at_keyword("EXISTS") and self.peek(1).text == "(":
            self.next()
            self.expect_op("(")
            query = self.query()
            self.expect_op(")")
            return t.ExistsPredicate(t.SubqueryExpression(query))
        if self.at_keyword("CURRENT_DATE"):
            self.next()
            return t.CurrentTime("DATE")
        if self.at_keyword("CURRENT_TIMESTAMP", "LOCALTIMESTAMP"):
            self.next()
            return t.CurrentTime("TIMESTAMP")
        if self.at_keyword("ARRAY") and self.peek(1).text == "[":
            self.next()
            self.expect_op("[")
            items = [] if self.at_op("]") else self.expression_list()
            self.expect_op("]")
            return t.FunctionCall(t.QualifiedName(("array_ctor",)),
                                  tuple(items))
        if self.at_keyword("ROW") and self.peek(1).text == "(":
            self.next()
            self.expect_op("(")
            items = self.expression_list()
            self.expect_op(")")
            return t.Row(tuple(items))
        if self.at_keyword("GROUPING") and self.peek(1).text == "(":
            self.next()
            self.expect_op("(")
            args = self.expression_list()
            self.expect_op(")")
            return t.FunctionCall(t.QualifiedName(("grouping",)), tuple(args))
        if self.accept_op("("):
            if self.at_keyword("SELECT", "WITH"):
                query = self.query()
                self.expect_op(")")
                return t.SubqueryExpression(query)
            exprs = self.expression_list()
            self.expect_op(")")
            if len(exprs) == 1:
                return exprs[0]
            return t.Row(tuple(exprs))
        if tok.kind in ("IDENT", "QIDENT") or (
                tok.kind == "KEYWORD" and tok.upper not in (
                    "SELECT", "FROM", "WHERE", "AND", "OR", "ON")):
            return self.name_or_call()
        self.error("expected expression")

    def interval(self) -> t.IntervalLiteral:
        self.expect_keyword("INTERVAL")
        sign = 1
        if self.accept_op("-"):
            sign = -1
        elif self.accept_op("+"):
            pass
        value = self.next().text  # STRING
        unit = self.next().upper
        end_unit = None
        if self.accept_keyword("TO"):
            end_unit = self.next().upper
        return t.IntervalLiteral(value, unit, sign, end_unit)

    def case_expression(self) -> t.Expression:
        self.expect_keyword("CASE")
        operand = None
        if not self.at_keyword("WHEN"):
            operand = self.expression()
        whens = []
        while self.accept_keyword("WHEN"):
            cond = self.expression()
            self.expect_keyword("THEN")
            whens.append(t.WhenClause(cond, self.expression()))
        default = None
        if self.accept_keyword("ELSE"):
            default = self.expression()
        self.expect_keyword("END")
        if operand is None:
            return t.SearchedCaseExpression(tuple(whens), default)
        return t.SimpleCaseExpression(operand, tuple(whens), default)

    def name_or_call(self) -> t.Expression:
        name = self.qualified_name()
        lname = name.suffix.lower()
        if not self.at_op("("):
            if len(name.parts) == 1:
                return t.Identifier(name.parts[0])
            base: t.Expression = t.Identifier(name.parts[0])
            for part in name.parts[1:]:
                base = t.DereferenceExpression(base, t.Identifier(part))
            return base
        self.expect_op("(")
        if lname in ("coalesce",):
            args = self.expression_list()
            self.expect_op(")")
            return t.CoalesceExpression(tuple(args))
        if lname == "nullif":
            first = self.expression()
            self.expect_op(",")
            second = self.expression()
            self.expect_op(")")
            return t.NullIfExpression(first, second)
        if lname == "if":
            args = self.expression_list()
            self.expect_op(")")
            if len(args) == 2:
                return t.IfExpression(args[0], args[1])
            if len(args) == 3:
                return t.IfExpression(args[0], args[1], args[2])
            self.error(f"if() takes 2 or 3 arguments, got {len(args)}")
        distinct = False
        args: Tuple[t.Expression, ...] = ()
        if self.at_op("*"):
            self.next()
        elif not self.at_op(")"):
            if self.accept_keyword("DISTINCT"):
                distinct = True
            else:
                self.accept_keyword("ALL")
            args = tuple(self.expression_list())
        self.expect_op(")")
        filter_ = None
        if self.at_keyword("FILTER") and self.peek(1).text == "(":
            self.next()
            self.expect_op("(")
            self.expect_keyword("WHERE")
            filter_ = self.expression()
            self.expect_op(")")
        window = None
        if self.at_keyword("OVER"):
            self.next()
            window = self.window_spec()
        return t.FunctionCall(name, args, distinct, filter_, window)

    def window_spec(self) -> t.Window:
        self.expect_op("(")
        partition_by: Tuple[t.Expression, ...] = ()
        if self.accept_keyword("PARTITION"):
            self.expect_keyword("BY")
            partition_by = tuple(self.expression_list())
        order_by: Tuple[t.SortItem, ...] = ()
        if self.accept_keyword("ORDER"):
            self.expect_keyword("BY")
            order_by = self.sort_items()
        frame = None
        if self.at_keyword("RANGE", "ROWS", "GROUPS"):
            frame_type = self.next().upper
            if self.accept_keyword("BETWEEN"):
                start_type, start_value = self.frame_bound()
                self.expect_keyword("AND")
                end_type, end_value = self.frame_bound()
            else:
                start_type, start_value = self.frame_bound()
                end_type, end_value = None, None
            frame = t.WindowFrame(frame_type, start_type, start_value,
                                  end_type, end_value)
        self.expect_op(")")
        return t.Window(partition_by, order_by, frame)

    def frame_bound(self):
        if self.accept_keyword("UNBOUNDED"):
            if self.accept_keyword("PRECEDING"):
                return "UNBOUNDED_PRECEDING", None
            self.expect_keyword("FOLLOWING")
            return "UNBOUNDED_FOLLOWING", None
        if self.accept_keyword("CURRENT"):
            self.expect_keyword("ROW")
            return "CURRENT_ROW", None
        value = self.expression()
        if self.accept_keyword("PRECEDING"):
            return "PRECEDING", value
        self.expect_keyword("FOLLOWING")
        return "FOLLOWING", value


def parse_statement(sql: str) -> t.Statement:
    parser = _Parser(tokenize(sql))
    stmt = parser.statement()
    parser.accept_op(";")
    if parser.peek().kind != "EOF":
        parser.error("unexpected trailing input")
    return stmt


def parse_expression(sql: str) -> t.Expression:
    parser = _Parser(tokenize(sql))
    expr = parser.expression()
    if parser.peek().kind != "EOF":
        parser.error("unexpected trailing input")
    return expr
