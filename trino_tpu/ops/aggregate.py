"""Hash aggregation as sort-based segment reduction.

Reference parity: operator/HashAggregationOperator.java + the group-by hashes
(MultiChannelGroupByHash.java:853, BigintGroupByHash.java:425) and codegen'd
accumulators (operator/aggregation/AccumulatorCompiler.java:80).

TPU design: instead of an open-addressing hash table (pointer-chasing, bad fit
for the VPU), group-by = lexicographic `lax.sort` on the key columns, segment
boundary detection, then `jax.ops.segment_*` reductions — O(n log n) but
entirely vectorized, fusible, and deterministic. Distributed plans split the
work into PARTIAL (pre-exchange, per shard) and FINAL (post-exchange) steps
exactly like PushPartialAggregationThroughExchange.java; aggregate *state* is
a tuple of columns (e.g. avg = (sum, count)), mirroring the reference's
serialized accumulator states.

Null semantics: GROUP BY treats NULL as a regular group (null-first in the
sort key); aggregates skip NULL inputs; SUM/AVG/MIN/MAX of zero non-null rows
is NULL, COUNT is 0.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from trino_tpu import types as T
from trino_tpu.page import Column, Page


class Step:
    """Aggregation step (reference: operator/aggregation/AggregationNode.Step).

    INTERMEDIATE merges partial states and re-emits the PARTIAL layout —
    the spillable-aggregation compaction step
    (MergingHashAggregationBuilder.java analog): the executor folds an
    over-budget buffer of partial pages into one group-compacted partial
    page before deciding whether to spill it."""

    SINGLE = "single"
    PARTIAL = "partial"
    INTERMEDIATE = "intermediate"
    FINAL = "final"


@dataclasses.dataclass(frozen=True)
class StateColumn:
    """One column of aggregate state.

    contrib: (values, valid_mask) -> per-row contribution array
    reducer: 'sum' | 'min' | 'max' — also how partial states merge
    """

    type: T.Type
    contrib: Callable
    reducer: str


@dataclasses.dataclass(frozen=True)
class AggregateFunction:
    """Declarative aggregate: state columns + final projection.

    final: (state_value_arrays, nonnull_counts_or_None) -> (values, valid|None)
    """

    name: str
    state: Callable[[T.Type], Tuple[StateColumn, ...]]
    final: Callable
    output_type: Callable[[Optional[T.Type]], T.Type]


def _sum_state(in_type):
    acc_t = T.DOUBLE if isinstance(in_type, (T.DoubleType, T.RealType)) else T.BIGINT
    if isinstance(in_type, T.DecimalType):
        acc_t = in_type
    return (
        StateColumn(acc_t, lambda v, m: jnp.where(m, v, 0).astype(acc_t.dtype), "sum"),
        StateColumn(T.BIGINT, lambda v, m: m.astype(jnp.int64), "sum"),  # nnz
    )


def _sum_final(state, _):
    total, nnz = state
    return total, nnz > 0


def _count_state(in_type):
    return (StateColumn(T.BIGINT, lambda v, m: m.astype(jnp.int64), "sum"),)


def _count_final(state, _):
    return state[0], None


def _minmax_state(in_type, is_min):
    dt = in_type.dtype
    ident = _ident_for(jnp.dtype(dt), is_min)
    red = "min" if is_min else "max"
    return (
        StateColumn(in_type, lambda v, m: jnp.where(m, v, ident).astype(dt), red),
        StateColumn(T.BIGINT, lambda v, m: m.astype(jnp.int64), "sum"),
    )


def _minmax_final(state, _):
    value, nnz = state
    return value, nnz > 0


def _avg_state(in_type):
    if isinstance(in_type, T.DecimalType):
        sum_t = in_type
    else:
        sum_t = T.DOUBLE
    return (
        StateColumn(sum_t, lambda v, m: jnp.where(m, v, 0).astype(sum_t.dtype), "sum"),
        StateColumn(T.BIGINT, lambda v, m: m.astype(jnp.int64), "sum"),
    )


def _avg_final_factory(in_type):
    def final(state, _):
        total, nnz = state
        denom = jnp.maximum(nnz, 1)
        if isinstance(in_type, T.DecimalType):
            # decimal avg keeps scale, HALF_UP
            half = jax.lax.div(denom, jnp.int64(2))
            adj = jnp.where(total >= 0, total + half, total - half)
            value = jax.lax.div(adj, denom)
        else:
            value = total.astype(jnp.float64) / denom
        return value, nnz > 0
    return final


def _to_double(v, t: Optional[T.Type]):
    """Numeric column -> float64 true value (descale decimals)."""
    out = v.astype(jnp.float64)
    if isinstance(t, T.DecimalType) and t.scale:
        out = out / (10.0 ** t.scale)
    return out


def _count_if_state(in_type):
    return (StateColumn(T.BIGINT,
                        lambda v, m: (v & m).astype(jnp.int64), "sum"),)


def _bool_state(is_and):
    # AND folds with min over {0,1} (identity 1), OR with max (identity 0)
    ident = 1 if is_and else 0
    red = "min" if is_and else "max"
    return (
        StateColumn(T.BIGINT,
                    lambda v, m: jnp.where(m, v.astype(jnp.int64), ident),
                    red),
        StateColumn(T.BIGINT, lambda v, m: m.astype(jnp.int64), "sum"),
    )


def _bool_final(state, _):
    value, nnz = state
    return value > 0, nnz > 0


def _hash64(v: jnp.ndarray) -> jnp.ndarray:
    """splitmix64 over value bits (floats canonicalized so SQL-equal values
    hash equal) — the XxHash64 role in HLL/checksum states."""
    if jnp.issubdtype(v.dtype, jnp.floating):
        v = jax.lax.bitcast_convert_type(v.astype(jnp.float64) + 0.0,
                                         jnp.uint64)
    x = v.astype(jnp.uint64)
    x = (x + jnp.uint64(0x9E3779B97F4A7C15))
    x = (x ^ (x >> 30)) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> 27)) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> 31)


def _checksum_state(in_type):
    """Order-independent checksum: wrapping int64 sum of per-value hashes.
    Reference: operator/aggregation/ChecksumAggregationFunction — which
    emits varbinary(8); here the same 64 bits surface as BIGINT. The mask
    folds NULL rows to a zero contribution (the reference hashes SQL NULL
    to a constant — observable only when comparing checksums across
    engines, out of scope for the BIGINT surface)."""
    def contrib(v, m):
        return jnp.where(m, _hash64(v).astype(jnp.int64), 0)
    return (StateColumn(T.BIGINT, contrib, "sum"),
            StateColumn(T.BIGINT, lambda v, m: m.astype(jnp.int64), "sum"))


def _checksum_final(state, _):
    total, nnz = state
    # NULL over zero non-null rows (ChecksumAggregationFunction)
    return total.astype(jnp.int64), nnz > 0


_HLL_P = 11            # 2^11 = 2048 registers -> standard error 2.30%
_HLL_M = 1 << _HLL_P


def _hll_register_inputs(vals, elig):
    h = _hash64(vals)
    bucket = (h >> jnp.uint64(64 - _HLL_P)).astype(jnp.int32)
    w = (h & jnp.uint64((1 << 53) - 1)).astype(jnp.float64)
    # rho = 53 - floor(log2(w)) for w>0 (P(rho=r) = 2^-r), 54 when w == 0;
    # ints < 2^53 are exact in float64, so floor(log2) is exact
    rho = jnp.where(w > 0,
                    53 - jnp.floor(jnp.log2(jnp.maximum(w, 1.0))),
                    54.0).astype(jnp.int32)
    return jnp.where(elig, bucket, 0), jnp.where(elig, rho, 0)


def _hll_estimate(sum_present, cnt_present):
    """Raw HLL estimator + small-range linear counting (absent buckets
    contribute 2^0 = 1). Reference:
    operator/aggregation/ApproximateCountDistinctAggregation + airlift
    HyperLogLog."""
    m = float(_HLL_M)
    alpha = 0.7213 / (1.0 + 1.079 / m)
    sum_full = sum_present + (m - cnt_present)
    est = alpha * m * m / jnp.maximum(sum_full, 1e-12)
    zeros = m - cnt_present
    lc = m * jnp.log(m / jnp.maximum(zeros, 1.0))
    use_lc = (est <= 2.5 * m) & (zeros > 0)
    return jnp.round(jnp.where(use_lc, lc, est)).astype(jnp.int64)


def _hll_grouped(page: Page, spec: "AggSpec",
                 key_channels: Sequence[int]) -> Column:
    """approx_distinct over sorted groups: re-sort by (keys, bucket), fold
    registers per (group, bucket) RUN with one segment_max, then reduce
    runs per group — static shapes throughout (no [groups x m] registers
    materialized)."""
    n = page.capacity
    fn = get_aggregate("approx_distinct", spec.input_type)
    vals, elig, _ = _agg_inputs(page, spec, fn, page.row_mask())
    bucket, rho = _hll_register_inputs(vals, elig)
    operands = _sort_key_arrays(page, key_channels)
    sorted_ops = jax.lax.sort(
        operands + [bucket, rho.astype(jnp.int32),
                    elig.astype(jnp.int32)],
        num_keys=len(operands) + 1)
    live_s = ~sorted_ops[0]
    key_ops_s = sorted_ops[1:-3]
    bucket_s, rho_s, elig_s = sorted_ops[-3], sorted_ops[-2], sorted_ops[-1]
    # group ids on the sorted order
    gboundary = _boundary_scan(key_ops_s, n) & live_s
    group = jnp.cumsum(gboundary.astype(jnp.int32)) - 1
    # (group, bucket) runs
    rboundary = (gboundary |
                 (bucket_s != jnp.roll(bucket_s, 1)).at[0].set(True)) & live_s
    run = jnp.cumsum(rboundary.astype(jnp.int32)) - 1
    run_seg = jnp.where(live_s, run, n)
    reg_run = jax.ops.segment_max(jnp.where(elig_s > 0, rho_s, 0), run_seg,
                                  num_segments=n + 1)[:n]
    has_run = jax.ops.segment_max(elig_s, run_seg,
                                  num_segments=n + 1)[:n] > 0
    grp_run = jax.ops.segment_max(jnp.where(live_s, group, -1), run_seg,
                                  num_segments=n + 1)[:n]
    inv = jnp.where(has_run, jnp.exp2(-reg_run.astype(jnp.float64)), 0.0)
    grp_seg = jnp.where(has_run, grp_run, n)
    sum_present = jax.ops.segment_sum(inv, grp_seg, num_segments=n + 1)[:n]
    cnt_present = jax.ops.segment_sum(has_run.astype(jnp.float64), grp_seg,
                                      num_segments=n + 1)[:n]
    return Column(_hll_estimate(sum_present, cnt_present), None, T.BIGINT,
                  None)


def _hll_global(page: Page, spec: "AggSpec", live) -> Column:
    n = page.capacity
    fn = get_aggregate("approx_distinct", spec.input_type)
    vals, elig, _ = _agg_inputs(page, spec, fn, live)
    bucket, rho = _hll_register_inputs(vals, elig)
    seg = jnp.where(elig, bucket, _HLL_M)
    reg = jax.ops.segment_max(rho, seg, num_segments=_HLL_M + 1)[:_HLL_M]
    present = reg > 0
    sum_present = jnp.sum(
        jnp.where(present, jnp.exp2(-reg.astype(jnp.float64)), 0.0),
        keepdims=True)
    cnt_present = jnp.sum(present.astype(jnp.float64), keepdims=True)
    return Column(_hll_estimate(sum_present, cnt_present), None, T.BIGINT,
                  None)


def _percentile_grouped(page: Page, spec: "AggSpec",
                        key_channels: Sequence[int]) -> Column:
    """approx_percentile(x, p): nearest-rank pick within each sorted group
    (the qdigest role; exact at single step — error 0 <= any digest)."""
    n = page.capacity
    xcol = page.column(spec.input)
    vals, dictionary = xcol.values, xcol.dictionary
    elig = page.row_mask() & xcol.valid_mask()
    if spec.mask_channel is not None:
        fcol = page.column(spec.mask_channel)
        elig = elig & fcol.values & fcol.valid_mask()
    sort_vals = _nan_as_largest(vals) if jnp.issubdtype(
        vals.dtype, jnp.floating) else vals
    operands = _sort_key_arrays(page, key_channels)
    perm = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = jax.lax.sort(
        operands + [(~elig), sort_vals, perm],
        num_keys=len(operands) + 2)
    live_s = ~sorted_ops[0]
    key_ops_s = sorted_ops[1:-3]
    elig_s = ~sorted_ops[-3]
    perm_s = sorted_ops[-1]
    gboundary = _boundary_scan(key_ops_s, n) & live_s
    group = jnp.cumsum(gboundary.astype(jnp.int32)) - 1
    seg = jnp.where(live_s, group, n)
    pos = jnp.arange(n, dtype=jnp.int32)
    start = jax.ops.segment_min(jnp.where(live_s, pos, n), seg,
                                num_segments=n + 1)[:n]
    cnt = jax.ops.segment_sum(elig_s.astype(jnp.int32), seg,
                              num_segments=n + 1)[:n]
    pcol = page.column(spec.input2)
    p_sorted = jnp.take(pcol.values, perm_s, mode="clip") \
        .astype(jnp.float64)
    p_g = jax.ops.segment_max(jnp.where(live_s, p_sorted, -jnp.inf), seg,
                              num_segments=n + 1)[:n]
    k = jnp.clip(jnp.ceil(p_g * cnt.astype(jnp.float64)).astype(jnp.int32),
                 1, jnp.maximum(cnt, 1))
    idx = jnp.clip(start + k - 1, 0, n - 1)
    vals_s = jnp.take(vals, perm_s, mode="clip")
    out_vals = jnp.take(vals_s, idx, mode="clip")
    return Column(out_vals, cnt > 0, xcol.type, dictionary)


def _percentile_global(page: Page, spec: "AggSpec", live) -> Column:
    n = page.capacity
    xcol = page.column(spec.input)
    vals, dictionary = xcol.values, xcol.dictionary
    elig = live & xcol.valid_mask()
    if spec.mask_channel is not None:
        fcol = page.column(spec.mask_channel)
        elig = elig & fcol.values & fcol.valid_mask()
    sort_vals = _nan_as_largest(vals) if jnp.issubdtype(
        vals.dtype, jnp.floating) else vals
    perm = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = jax.lax.sort([(~elig), sort_vals, perm], num_keys=2)
    elig_s = ~sorted_ops[0]
    perm_s = sorted_ops[-1]
    cnt = jnp.sum(elig_s.astype(jnp.int32))
    pcol = page.column(spec.input2)
    p = jnp.max(jnp.where(live, pcol.values.astype(jnp.float64), -jnp.inf))
    k = jnp.clip(jnp.ceil(p * cnt.astype(jnp.float64)).astype(jnp.int32),
                 1, jnp.maximum(cnt, 1))
    idx = jnp.clip(k - 1, 0, n - 1)
    vals_s = jnp.take(vals, perm_s, mode="clip")
    out_vals = jnp.take(vals_s, idx[None], mode="clip")
    return Column(out_vals, (cnt > 0)[None], xcol.type, dictionary)


def _geomean_state_factory(in_type):
    def state(t):
        return (
            StateColumn(T.DOUBLE,
                        lambda v, m: jnp.where(
                            m, jnp.log(_to_double(v, in_type)), 0.0),
                        "sum"),
            StateColumn(T.BIGINT, lambda v, m: m.astype(jnp.int64), "sum"),
        )
    return state


def _geomean_final(state, _):
    s, n = state
    return jnp.exp(s / jnp.maximum(n.astype(jnp.float64), 1.0)), n > 0


# aggregates resolved by picking one row per group rather than reducing
# independent state columns (reference: MinMaxByNStateFactory / the min_by
# codegen path); these never split into PARTIAL/FINAL across an exchange
POSITIONAL_AGGREGATES = frozenset({"min_by", "max_by", "arbitrary"})

# moment aggregates computed with CENTERED sums (two passes over the sorted
# segments: means first, then squared deviations) for numerical stability —
# the naive E[x²]−E[x]² raw-moment form catastrophically cancels for large-
# mean data. Centered sums have no column-wise commutative merge, so these
# are single-step only (Trino instead merges central moments with Chan's
# update; reference operator/aggregation/state/CentralMomentsState.java —
# a future optimization would add a custom merge path to the FINAL step).
CENTERED_AGGREGATES = frozenset({
    "variance", "var_samp", "var_pop", "stddev", "stddev_samp", "stddev_pop",
    "corr", "covar_pop", "covar_samp", "regr_slope", "regr_intercept"})

# sketch aggregates with their own sorted evaluation (HyperLogLog register
# folding / rank selection) — single-step like DISTINCT: the whole group's
# rows must be colocated in one kernel call
SKETCH_AGGREGATES = frozenset({"approx_distinct", "approx_percentile"})

# collectors packing group elements into the list layout (ArrayBlock /
# MapBlock output) — single-step, and the executor pre-computes the
# static element capacity (list_len) from the collected page
COLLECT_AGGREGATES = frozenset({"array_agg", "histogram", "map_agg"})

# aggregates that must see every row of a group in ONE kernel invocation
SINGLE_STEP_AGGREGATES = (POSITIONAL_AGGREGATES | CENTERED_AGGREGATES
                          | SKETCH_AGGREGATES | COLLECT_AGGREGATES)


def get_aggregate(name: str, in_type: Optional[T.Type]) -> AggregateFunction:
    """Resolve an aggregate by name + input type (FunctionRegistry analog).

    For two-argument aggregates `in_type` is a tuple (first, second) of the
    argument types.
    """
    n = name.lower()
    tx, ty = (in_type if isinstance(in_type, tuple) else (in_type, None))
    if n == "count":
        return AggregateFunction("count", _count_state, _count_final,
                                 lambda t: T.BIGINT)
    if n == "count_if":
        return AggregateFunction("count_if", _count_if_state, _count_final,
                                 lambda t: T.BIGINT)
    if n in ("bool_and", "bool_or"):
        return AggregateFunction(
            n, lambda t: _bool_state(n == "bool_and"), _bool_final,
            lambda t: T.BOOLEAN)
    if n == "geometric_mean":
        return AggregateFunction(n, _geomean_state_factory(tx),
                                 _geomean_final, lambda t: T.DOUBLE)
    if n in CENTERED_AGGREGATES:
        # state/final unused — executed by the centered two-pass path
        return AggregateFunction(n, lambda t: (), None, lambda t: T.DOUBLE)
    if n in POSITIONAL_AGGREGATES:
        # state/final unused — executed by the positional row-selection path
        return AggregateFunction(n, lambda t: (), None, lambda t: tx)
    if n == "approx_distinct":
        return AggregateFunction(n, lambda t: (), None, lambda t: T.BIGINT)
    if n == "array_agg":
        return AggregateFunction(n, lambda t: (), None,
                                 lambda t: T.ArrayType(element=tx))
    if n == "histogram":
        return AggregateFunction(
            n, lambda t: (), None,
            lambda t: T.MapType(key=tx, value=T.BIGINT))
    if n == "map_agg":
        return AggregateFunction(
            n, lambda t: (), None, lambda t: T.MapType(key=tx, value=ty))
    if n == "approx_percentile":
        return AggregateFunction(n, lambda t: (), None, lambda t: tx)
    if n == "checksum":
        return AggregateFunction("checksum", _checksum_state,
                                 _checksum_final, lambda t: T.BIGINT)
    if n == "sum":
        out = in_type if isinstance(in_type, (T.DecimalType, T.DoubleType,
                                              T.RealType)) else T.BIGINT
        if isinstance(in_type, T.RealType):
            out = T.REAL
        return AggregateFunction("sum", _sum_state, _sum_final, lambda t: out)
    if n == "avg":
        # Trino: avg(real) -> real, avg(decimal) keeps type/scale, else double
        if isinstance(in_type, T.DecimalType):
            out = in_type
        elif isinstance(in_type, T.RealType):
            out = T.REAL
        else:
            out = T.DOUBLE
        return AggregateFunction("avg", _avg_state, _avg_final_factory(in_type),
                                 lambda t: out)
    if n == "min":
        return AggregateFunction(
            "min", lambda t: _minmax_state(t, True), _minmax_final,
            lambda t: in_type)
    if n == "max":
        return AggregateFunction(
            "max", lambda t: _minmax_state(t, False), _minmax_final,
            lambda t: in_type)
    raise KeyError(f"unknown aggregate function: {name}")


AGGREGATES = ("count", "sum", "avg", "min", "max", "count_if", "bool_and",
              "bool_or", "variance", "var_samp", "var_pop", "stddev",
              "stddev_samp", "stddev_pop", "geometric_mean", "corr",
              "covar_pop", "covar_samp", "regr_slope", "regr_intercept",
              "min_by", "max_by", "arbitrary")


@dataclasses.dataclass(frozen=True)
class AggSpec:
    """One aggregate call in a plan: fn(input_channel). input None = count(*).

    Two-argument aggregates (corr/covar/regr, min_by/max_by) carry the
    second argument in (input2, input2_type)."""

    name: str
    input: Optional[int]
    input_type: Optional[T.Type]
    mask_channel: Optional[int] = None  # e.g. count(x) FILTER (WHERE ...)
    distinct: bool = False
    input2: Optional[int] = None
    input2_type: Optional[T.Type] = None


def _sort_key_arrays(page: Page, key_channels: Sequence[int], dead=None):
    """Composite sort operands: dead-flag first, then (null, value) per key.

    Null rows' value lanes hold garbage; canonicalize them to 0 so all nulls
    of a key collate into ONE group (the null flag is a separate sort key).
    `dead` overrides the liveness flag (e.g. DISTINCT folds the aggregate's
    eligibility into it).
    """
    if dead is None:
        dead = ~page.row_mask()  # False (live) sorts before True (dead)
    operands = [dead]
    for ch in key_channels:
        col = page.column(ch)
        if col.valid is not None:
            operands.append(~col.valid)  # nulls group after non-nulls
            operands.append(jnp.where(col.valid, col.values,
                                      jnp.zeros((), col.values.dtype)))
        else:
            operands.append(col.values)
    return operands


def hash_aggregate(
    key_channels: Sequence[int],
    aggs: Sequence[AggSpec],
    step: str = Step.SINGLE,
    partial_state_channels: Optional[Sequence[Sequence[int]]] = None,
    list_len: Optional[int] = None,
) -> Callable[[Page], Page]:
    """Build a group-by aggregation operator.

    Output page layout: [key columns..., per-agg output columns...]. For
    step=PARTIAL the per-agg outputs are the raw state columns (consumed by a
    FINAL step whose partial_state_channels maps agg -> its state channels).
    Capacity: output keeps input capacity (#groups <= #rows).
    """
    key_channels = tuple(key_channels)
    for a in aggs:
        if a.distinct and (a.name in POSITIONAL_AGGREGATES
                           or (a.name in CENTERED_AGGREGATES
                               and a.input2 is not None)):
            # DISTINCT over a row-pair has no single-column first-occurrence
            # mask; refuse rather than silently dropping the qualifier
            raise NotImplementedError(f"{a.name}(DISTINCT ...)")
    if step != Step.SINGLE:
        for a in aggs:
            if a.distinct:
                # the optimizer keeps DISTINCT aggregations single-step
                # (no partial/final split across an exchange) because
                # distinctness is only decidable once a group's rows are
                # colocated; see add_exchanges' `splittable` guard.
                raise NotImplementedError(
                    f"{a.name}(DISTINCT ...) in {step} step")
            if a.name in SINGLE_STEP_AGGREGATES:
                # positional/centered state has no commutative column-wise
                # merge; the optimizer keeps these single-step
                raise NotImplementedError(f"{a.name}() in {step} step")
    resolved = [get_aggregate(a.name,
                              a.input_type if a.input2 is None
                              else (a.input_type, a.input2_type))
                for a in aggs]

    has_collect = any(a.name in COLLECT_AGGREGATES for a in aggs)

    def op(page: Page) -> Page:
        n = page.capacity
        if not key_channels:
            if has_collect:
                raise NotImplementedError(
                    "global array_agg/histogram/map_agg (no GROUP BY)")
            return _global_aggregate(page, aggs, resolved, step,
                                     partial_state_channels)
        sizes = None if has_collect else \
            _direct_key_sizes(page, key_channels, aggs)
        if sizes is not None:
            return _direct_aggregate(page, key_channels, aggs, resolved,
                                     step, partial_state_channels, sizes)
        operands = _sort_key_arrays(page, key_channels)
        perm = jnp.arange(n, dtype=jnp.int32)
        sorted_ops = jax.lax.sort(operands + [perm],
                                  num_keys=len(operands))
        perm_sorted = sorted_ops[-1]
        # boundary detection on the *sorted* key operands (incl. null flags)
        live_sorted = ~sorted_ops[0]
        boundary = _boundary_scan(sorted_ops[1:-1], n) & live_sorted
        group_of_sorted = jnp.cumsum(boundary.astype(jnp.int32)) - 1
        num_groups = jnp.sum(boundary).astype(jnp.int32)
        # route dead rows to an out-of-range segment id so they drop out
        seg = jnp.where(live_sorted, group_of_sorted, n)

        out_cols: List[Column] = []
        # group key output = first sorted row of each segment
        first_idx = jnp.zeros(n, dtype=jnp.int32).at[
            jnp.where(boundary, group_of_sorted, n)].set(
            jnp.arange(n, dtype=jnp.int32), mode="drop")
        key_row = jnp.take(perm_sorted, first_idx, mode="clip")
        for ch in key_channels:
            out_cols.append(page.column(ch).gather(key_row))

        agg_cols = _accumulate(page, aggs, resolved, step,
                               partial_state_channels, perm_sorted, seg, n,
                               key_channels, list_len)
        out_cols.extend(agg_cols)
        return Page(tuple(out_cols), num_groups)

    return op


def _agg_inputs(page: Page, spec: "AggSpec", fn, base_mask, gather=None):
    """Per-row (vals, mask, dictionary) for one aggregate — input column,
    second argument, argument nullness and FILTER mask folded in. The ONE
    definition shared by the sorted, global and direct aggregation paths
    (semantics must not depend on which path the group keys select).
    `gather` reorders row-space arrays (e.g. through a sort permutation)."""
    def g(a):
        return a if gather is None else jnp.take(a, gather, mode="clip")
    dictionary = None
    if spec.input is not None:
        col = page.column(spec.input)
        dictionary = col.dictionary
        vals = g(col.values)
        mask = base_mask & g(col.valid_mask())
    else:
        vals = jnp.zeros(page.capacity, dtype=jnp.int64)
        mask = base_mask
    if spec.input2 is not None:
        col2 = page.column(spec.input2)
        mask = mask & g(col2.valid_mask())
        vals = (vals, g(col2.values))
    if spec.mask_channel is not None:
        fcol = page.column(spec.mask_channel)
        mask = mask & g(fcol.values & fcol.valid_mask())
    return vals, mask, dictionary


def _final_state_contribs(page: Page, states, chans, live_mask, gather=None):
    """FINAL-step per-state (contribution, reducer): partial state columns
    with dead rows replaced by each reducer's identity — shared by the
    sorted, global and direct paths."""
    out = []
    for sc, ch in zip(states, chans):
        col = page.column(ch)
        vals = col.values if gather is None else \
            jnp.take(col.values, gather, mode="clip")
        if sc.reducer == "sum":
            ident = jnp.zeros((), dtype=vals.dtype)
        else:
            ident = _ident_for(vals.dtype, sc.reducer == "min")
        out.append((jnp.where(live_mask, vals, ident), sc.reducer))
    return out


_DIRECT_MAX_GROUPS = 4096


def _direct_key_sizes(page: Page, key_channels, aggs):
    """Static per-key code-space sizes when EVERY group key is
    dictionary-encoded and the combined key space is small — the
    BigintGroupByHash / dictionary-aware fast path (reference:
    operator/GroupByHash.java dictionary mode). Returns None when the
    sort-based general path must run."""
    for a in aggs:
        if a.distinct or a.name in SINGLE_STEP_AGGREGATES:
            return None
    sizes = []
    total = 1
    for ch in key_channels:
        col = page.column(ch)
        if col.dictionary is None:
            return None
        sizes.append(len(col.dictionary) + 1)   # +1: the NULL slot
        total *= sizes[-1]
    if total > _DIRECT_MAX_GROUPS:
        return None
    return tuple(sizes)


def _direct_aggregate(page: Page, key_channels, aggs, resolved, step,
                      partial_state_channels, sizes) -> Page:
    """Group-by over a small static key space WITHOUT sorting: segment ids
    are computed arithmetically from dictionary codes, states reduce with
    jax.ops.segment_*, and present groups compact to a tiny output page.
    Replaces an O(n log n) multi-operand lax.sort with O(n) scatters — the
    difference between ~10s and ~1s for q1-shaped aggregations on TPU."""
    n = page.capacity
    live = page.row_mask()
    nseg = 1
    for s in sizes:
        nseg *= s
    # combined code; NULL key -> last slot of its key's code space
    combined = jnp.zeros(n, dtype=jnp.int32)
    stride = nseg
    strides = []
    for ch, size in zip(key_channels, sizes):
        stride //= size
        strides.append(stride)
        col = page.column(ch)
        code = jnp.clip(col.values.astype(jnp.int32), 0, size - 2)
        if col.valid is not None:
            code = jnp.where(col.valid, code, size - 1)
        combined = combined + code * stride
    seg = jnp.where(live, combined, nseg)       # dead rows drop out
    n_out = nseg + 1

    cnt_live = jax.ops.segment_sum(live.astype(jnp.int32), seg,
                                   num_segments=n_out)[:nseg]
    present = cnt_live > 0
    num_groups = jnp.sum(present).astype(jnp.int32)
    pos = jnp.cumsum(present.astype(jnp.int32)) - 1
    scatter_idx = jnp.where(present, pos, nseg)

    def compact(values_per_slot, valid_per_slot=None):
        out_v = jnp.zeros((nseg,), dtype=values_per_slot.dtype).at[
            scatter_idx].set(values_per_slot, mode="drop")
        if valid_per_slot is None:
            return out_v, None
        out_m = jnp.zeros((nseg,), dtype=jnp.bool_).at[scatter_idx].set(
            valid_per_slot, mode="drop")
        return out_v, out_m

    out_cols: List[Column] = []
    slot = jnp.arange(nseg, dtype=jnp.int32)
    for ch, size, stride in zip(key_channels, sizes, strides):
        col = page.column(ch)
        code = (slot // stride) % size
        is_null = code == size - 1
        v, m = compact(code.astype(col.values.dtype),
                       ~is_null if col.valid is not None else None)
        out_cols.append(Column(v, m, col.type, col.dictionary))

    # two-phase accumulation: first collect EVERY state's contribution
    # array, then reduce all "sum" states of one dtype in ONE batched
    # segment_sum ([n, k] data) — per-call scatter cost on TPU (~350ms at
    # 4M rows) dominates, so q1's 19 sum states must share one scatter
    pending: List[dict] = []
    for ai, (spec, fn) in enumerate(zip(aggs, resolved)):
        states = fn.state(spec.input_type)
        entry = {"states": states, "contribs": []}
        if step in (Step.FINAL, Step.INTERMEDIATE):
            chans = partial_state_channels[ai]
            entry["dictionary"] = page.column(chans[0]).dictionary
            entry["contribs"] = _final_state_contribs(page, states, chans,
                                                      live)
        else:
            vals, mask, dictionary = _agg_inputs(page, spec, fn, live)
            entry["dictionary"] = dictionary
            for sc in states:
                entry["contribs"].append((sc.contrib(vals, mask),
                                          sc.reducer))
        pending.append(entry)

    sum_batches: dict = {}       # dtype -> list of contrib arrays
    sum_slots: dict = {}         # id(contrib) -> (dtype, index)
    for entry in pending:
        for contrib, reducer in entry["contribs"]:
            if reducer == "sum":
                lst = sum_batches.setdefault(contrib.dtype, [])
                sum_slots[id(contrib)] = (contrib.dtype, len(lst))
                lst.append(contrib)
    sum_results = {
        dt: jax.ops.segment_sum(jnp.stack(lst, axis=1), seg,
                                num_segments=n_out)[:nseg]
        for dt, lst in sum_batches.items()}

    def reduced(contrib, reducer):
        if reducer == "sum":
            dt, j = sum_slots[id(contrib)]
            return sum_results[dt][:, j]
        return _segment_reduce(contrib, seg, n_out, reducer)[:nseg]

    for (spec, fn), entry in zip(zip(aggs, resolved), pending):
        state_arrays = [reduced(c, r) for c, r in entry["contribs"]]
        states = entry["states"]
        dictionary = entry["dictionary"]
        if step in (Step.PARTIAL, Step.INTERMEDIATE):
            for sc, arr in zip(states, state_arrays):
                d = dictionary if T.is_string(sc.type) else None
                v, _ = compact(arr.astype(sc.type.dtype))
                out_cols.append(Column(v, None, sc.type, d))
        else:
            values, valid = fn.final(state_arrays, None)
            v, m = compact(values, valid)
            out_cols.append(_agg_out_column(fn, spec, v, m, dictionary))
    return Page(tuple(out_cols), num_groups)


def _boundary_scan(key_ops, n) -> jnp.ndarray:
    """Group-start flags over lexicographically sorted key arrays.

    NaN is ONE value for grouping/DISTINCT purposes (SQL/Trino semantics),
    so adjacent NaNs do NOT open a new group despite NaN != NaN.
    """
    boundary = jnp.zeros(n, dtype=jnp.bool_)
    for arr in key_ops:
        prev = jnp.roll(arr, 1)
        ne = arr != prev
        if jnp.issubdtype(arr.dtype, jnp.floating):
            ne = ne & ~(jnp.isnan(arr) & jnp.isnan(prev))
        boundary = boundary | ne
    return boundary.at[0].set(True)


def _nan_as_largest(v: jnp.ndarray) -> jnp.ndarray:
    """Canonicalize NaN to +inf: ORDER BY / min_by / max_by treat NaN as the
    largest value (Trino's totalOrder comparison)."""
    if jnp.issubdtype(v.dtype, jnp.floating):
        return jnp.where(jnp.isnan(v), jnp.asarray(jnp.inf, v.dtype), v)
    return v


def _segment_reduce(contrib, seg, n, reducer):
    if reducer == "sum":
        return jax.ops.segment_sum(contrib, seg, num_segments=n)
    if reducer == "min":
        return jax.ops.segment_min(contrib, seg, num_segments=n)
    if reducer == "max":
        return jax.ops.segment_max(contrib, seg, num_segments=n)
    raise ValueError(reducer)


def _distinct_first_mask(page: Page, key_channels: Sequence[int],
                         spec: "AggSpec") -> jnp.ndarray:
    """Row-order mask marking the first eligible row of each
    (group keys, argument value) pair — the MarkDistinctOperator.java:38
    analog, phrased as one extra lexicographic sort + boundary scan so
    DISTINCT costs O(n log n) on the VPU instead of a hash table.

    Eligibility folds in liveness, argument non-nullness (DISTINCT
    aggregates skip NULL inputs) and the aggregate's FILTER mask, so
    distinctness is computed over exactly the rows the aggregate sees.
    """
    n = page.capacity
    col = page.column(spec.input)
    eligible = page.row_mask() & col.valid_mask()
    if spec.mask_channel is not None:
        fcol = page.column(spec.mask_channel)
        eligible = eligible & fcol.values & fcol.valid_mask()
    # the argument is just one more sort key after the group keys
    operands = _sort_key_arrays(page, tuple(key_channels) + (spec.input,),
                                dead=~eligible)
    perm = jnp.arange(n, dtype=jnp.int32)
    sorted_ops = jax.lax.sort(operands + [perm], num_keys=len(operands))
    perm_s = sorted_ops[-1]
    elig_s = ~sorted_ops[0]
    first = _boundary_scan(sorted_ops[1:-1], n) & elig_s
    return jnp.zeros(n, dtype=jnp.bool_).at[perm_s].set(first)


def _accumulate(page, aggs, resolved, step, partial_state_channels,
                perm_sorted, seg, n, key_channels=(),
                list_len=None) -> List[Column]:
    """Per-agg state accumulation + (for FINAL/SINGLE) final projection."""
    out: List[Column] = []
    dmask_cache: dict = {}

    def distinct_mask(spec):
        # multiple DISTINCT aggs over one argument share the sort+mask
        key = (spec.input, spec.mask_channel)
        if key not in dmask_cache:
            dmask_cache[key] = jnp.take(
                _distinct_first_mask(page, key_channels, spec), perm_sorted,
                mode="clip")
        return dmask_cache[key]

    for ai, (spec, fn) in enumerate(zip(aggs, resolved)):
        if step in (Step.FINAL, Step.INTERMEDIATE):
            # inputs are partial state columns; merge with each state's
            # reducer (dead rows contribute the reducer identity)
            chans = partial_state_channels[ai]
            states = fn.state(spec.input_type)
            merged = [
                _segment_reduce(contrib, seg, n, reducer)
                for contrib, reducer in _final_state_contribs(
                    page, states, chans, seg < n, gather=perm_sorted)]
            if step == Step.INTERMEDIATE:
                d = page.column(chans[0]).dictionary
                for sc, arr in zip(states, merged):
                    sd = d if T.is_string(sc.type) else None
                    out.append(Column(arr.astype(sc.type.dtype), None,
                                      sc.type, sd))
                continue
            values, valid = fn.final(merged, None)
            out.append(_agg_out_column(fn, spec, values, valid,
                                       page.column(chans[0]).dictionary))
        elif spec.name in COLLECT_AGGREGATES:
            out.append(_collect_grouped(page, spec, fn, perm_sorted, seg,
                                        n, list_len))
        elif spec.name == "approx_distinct":
            out.append(_hll_grouped(page, spec, key_channels))
        elif spec.name == "approx_percentile":
            out.append(_percentile_grouped(page, spec, key_channels))
        elif spec.name in POSITIONAL_AGGREGATES:
            out.append(_positional_grouped(page, spec, perm_sorted, seg, n))
        elif spec.name in CENTERED_AGGREGATES:
            extra = distinct_mask(spec) if spec.distinct else None
            out.append(_centered_grouped(page, spec, perm_sorted, seg, n,
                                         extra))
        else:
            states = fn.state(spec.input_type)
            vals, mask, dictionary = _agg_inputs(page, spec, fn, seg < n,
                                                 gather=perm_sorted)
            if spec.distinct:
                mask = mask & distinct_mask(spec)
            state_arrays = []
            for sc in states:
                contrib = sc.contrib(vals, mask)
                state_arrays.append(_segment_reduce(contrib, seg, n, sc.reducer))
            if step == Step.PARTIAL:
                for sc, arr in zip(states, state_arrays):
                    d = dictionary if T.is_string(sc.type) else None
                    out.append(Column(arr.astype(sc.type.dtype), None, sc.type,
                                      d))
            else:  # SINGLE
                values, valid = fn.final(state_arrays, None)
                out.append(_agg_out_column(fn, spec, values, valid, dictionary))
    return out


def passthrough_partial(key_channels: Sequence[int],
                        aggs: Sequence["AggSpec"]):
    """BYPASS-mode partial aggregation ("Partial Partial Aggregates"
    full bypass): emit ONE PARTIAL-layout state row per INPUT row — key
    columns pass through untouched, each aggregate's state columns are
    its per-row contributions — with no sort and no segment reduction.
    O(n) map instead of O(n log n) sort: when observed NDV ~ rows the
    sort collapses nothing, so the adaptive executor routes pages here
    and lets the per-partition finalize (Step.INTERMEDIATE/FINAL over
    spilled hash partitions) do ALL the grouping once.

    Output is layout-identical to Step.PARTIAL, so pass-through pages,
    real partial pages, and compacted intermediate pages mix freely in
    one buffer/store."""
    key_channels = tuple(key_channels)
    for a in aggs:
        if a.distinct or a.name in SINGLE_STEP_AGGREGATES:
            # same restriction as PARTIAL: these need a whole group in
            # one kernel call (the executor routes them elsewhere)
            raise NotImplementedError(f"{a.name}() in bypass partial")
    resolved = [get_aggregate(a.name,
                              a.input_type if a.input2 is None
                              else (a.input_type, a.input2_type))
                for a in aggs]

    def op(page: Page) -> Page:
        live = page.row_mask()
        out_cols: List[Column] = [page.column(ch) for ch in key_channels]
        for spec, fn in zip(aggs, resolved):
            states = fn.state(spec.input_type)
            vals, mask, dictionary = _agg_inputs(page, spec, fn, live)
            for sc in states:
                d = dictionary if T.is_string(sc.type) else None
                out_cols.append(Column(
                    sc.contrib(vals, mask).astype(sc.type.dtype), None,
                    sc.type, d))
        return Page(tuple(out_cols), page.num_rows)

    return op


def group_max_size(key_channels: Sequence[int]):
    """Max live group size — the executor's sizing pre-pass for collect
    aggregates (one scalar fetch buys the static element capacity)."""
    key_channels = tuple(key_channels)

    def op(page: Page):
        n = page.capacity
        operands = _sort_key_arrays(page, key_channels)
        sorted_ops = jax.lax.sort(operands, num_keys=len(operands))
        live = ~sorted_ops[0]
        boundary = _boundary_scan(sorted_ops[1:], n) & live
        seg = jnp.where(live,
                        jnp.cumsum(boundary.astype(jnp.int32)) - 1, n)
        counts = jax.ops.segment_sum(live.astype(jnp.int32), seg,
                                     num_segments=n + 1)[:n]
        return jnp.max(counts)
    return op


def _collect_grouped(page: Page, spec: "AggSpec", fn, perm_sorted, seg,
                     n, list_len) -> Column:
    """array_agg / histogram / map_agg over sorted segments, packing each
    group's elements into the list layout (values [groups_cap, L] +
    lengths). L (`list_len`) is the executor-provided static element
    capacity (max group size fetched from the collected page — the
    data-dependent-shape escape hatch every blocking collector needs).
    NULL inputs are skipped (documented deviation from Trino's
    array_agg, which keeps them)."""
    if list_len is None:
        raise ValueError("collect aggregates need list_len")
    L = int(list_len)
    out_type = fn.output_type(None)
    idx = jnp.arange(n, dtype=jnp.int32)
    vals, mask, dictionary = _agg_inputs(page, spec, fn, seg < n,
                                         gather=perm_sorted)
    if spec.input2 is not None:
        vals, vals2 = vals
    else:
        vals2 = None
    if spec.name == "array_agg":
        elig = mask
        excl = jnp.cumsum(elig.astype(jnp.int32)) - elig.astype(jnp.int32)
        boundary = jnp.concatenate(
            [jnp.ones(1, jnp.bool_), seg[1:] != seg[:-1]])
        run_start = jax.lax.cummax(jnp.where(boundary, idx, 0))
        within = excl - jnp.take(excl, run_start, mode="clip")
        ok = elig & (within < L)
        srow = jnp.where(ok, seg, n)
        plane = jnp.zeros((n, L), dtype=vals.dtype).at[
            srow, jnp.clip(within, 0, L - 1)].set(vals, mode="drop")
        lengths = jnp.minimum(
            jax.ops.segment_sum(elig.astype(jnp.int32), seg,
                                num_segments=n + 1)[:n], L)
        return Column(plane, None, out_type, dictionary,
                      lengths=lengths.astype(jnp.int32))
    # histogram / map_agg: re-sort by (group segment, key value) so each
    # distinct key forms a run; pack one entry per run
    kv = _nan_as_largest(vals)
    segk = jnp.where(mask, seg, n)
    seg_s, kv_s, rows_s = jax.lax.sort([segk, kv, idx], num_keys=2)
    live = seg_s < n
    gb = jnp.concatenate([jnp.ones(1, jnp.bool_), seg_s[1:] != seg_s[:-1]])
    pb = (gb | jnp.concatenate(
        [jnp.ones(1, jnp.bool_), kv_s[1:] != kv_s[:-1]])) & live
    pair_id = jnp.cumsum(pb.astype(jnp.int32)) - 1
    pair_of_row = jnp.where(live, pair_id, n)
    g_start = jax.lax.cummax(jnp.where(gb, idx, 0))
    ordinal = pair_id - jnp.take(pair_id, g_start, mode="clip")
    first = pb & (ordinal < L)
    srow = jnp.where(first, seg_s, n)
    scol = jnp.clip(ordinal, 0, L - 1)
    keys_plane = jnp.zeros((n, L), dtype=kv_s.dtype).at[
        srow, scol].set(kv_s, mode="drop")
    aux_dict = None
    if spec.name == "histogram":
        counts = jax.ops.segment_sum(live.astype(jnp.int64), pair_of_row,
                                     num_segments=n + 1)[:n]
        aux_vals = jnp.take(counts, jnp.clip(pair_id, 0, n - 1),
                            mode="clip")
        aux_dtype = jnp.int64
    else:  # map_agg: first value seen for each key wins
        # vals2 is in the group-sort row order; re-order through the
        # secondary (group, key) sort's permutation
        aux_vals = jnp.take(vals2, rows_s, mode="clip")
        aux_dtype = vals2.dtype
        if spec.input2 is not None:
            aux_dict = page.column(spec.input2).dictionary
    aux_plane = jnp.zeros((n, L), dtype=aux_dtype).at[
        srow, scol].set(aux_vals.astype(aux_dtype), mode="drop")
    lengths = jnp.minimum(
        jax.ops.segment_sum(pb.astype(jnp.int32), seg_s,
                            num_segments=n + 1)[:n], L)
    return Column(keys_plane, None, out_type, dictionary,
                  lengths=lengths.astype(jnp.int32), aux=aux_plane,
                  aux_dictionary=aux_dict)


def _positional_grouped(page: Page, spec: "AggSpec", perm_sorted, seg,
                        n) -> Column:
    """min_by/max_by/arbitrary over sorted groups: pick ONE row per group
    (first at the y-extremum / first non-null), then gather x from it."""
    xcol = page.column(spec.input)
    xv = jnp.take(xcol.values, perm_sorted, mode="clip")
    xm = jnp.take(xcol.valid_mask(), perm_sorted, mode="clip")
    eligible = seg < n
    if spec.mask_channel is not None:
        fcol = page.column(spec.mask_channel)
        eligible = eligible & jnp.take(fcol.values & fcol.valid_mask(),
                                       perm_sorted, mode="clip")
    if spec.name == "arbitrary":
        eligible = eligible & xm
    else:
        ycol = page.column(spec.input2)
        yv = _nan_as_largest(jnp.take(ycol.values, perm_sorted, mode="clip"))
        ym = jnp.take(ycol.valid_mask(), perm_sorted, mode="clip")
        eligible = eligible & ym
        is_min = spec.name == "min_by"
        ident = _ident_for(yv.dtype, is_min)
        yc = jnp.where(eligible, yv, ident)
        ext = _segment_reduce(yc, seg, n, "min" if is_min else "max")
        eligible = eligible & (yc == jnp.take(ext, seg, mode="clip"))
    pos = jnp.where(eligible, jnp.arange(n, dtype=jnp.int32), n)
    first = jax.ops.segment_min(pos, seg, num_segments=n)
    has = first < n
    idx = jnp.clip(first, 0, n - 1)
    return Column(jnp.take(xv, idx), has & jnp.take(xm, idx), xcol.type,
                  xcol.dictionary)


def _positional_global(page: Page, spec: "AggSpec", live) -> Column:
    """Single-group variant of _positional_grouped (one output row)."""
    n = page.capacity
    xcol = page.column(spec.input)
    xv, xm = xcol.values, xcol.valid_mask()
    eligible = live
    if spec.mask_channel is not None:
        fcol = page.column(spec.mask_channel)
        eligible = eligible & fcol.values & fcol.valid_mask()
    if spec.name == "arbitrary":
        eligible = eligible & xm
    else:
        ycol = page.column(spec.input2)
        yv, ym = _nan_as_largest(ycol.values), ycol.valid_mask()
        eligible = eligible & ym
        is_min = spec.name == "min_by"
        ident = _ident_for(yv.dtype, is_min)
        yc = jnp.where(eligible, yv, ident)
        ext = jnp.min(yc) if is_min else jnp.max(yc)
        eligible = eligible & (yc == ext)
    pos = jnp.where(eligible, jnp.arange(n, dtype=jnp.int32), n)
    first = jnp.min(pos, keepdims=True)
    has = first < n
    idx = jnp.clip(first, 0, n - 1)
    return Column(jnp.take(xv, idx), has & jnp.take(xm, idx), xcol.type,
                  xcol.dictionary)


def _centered_finalize(kind: str, cnt, sa, sb, caa, cbb, cab):
    """Shared finalization of the centered-moment family. First argument `a`
    is the dependent variable, second `b` the independent one
    (regr_slope(y, x) argument order); var/stddev use `a` only."""
    nf = jnp.maximum(cnt.astype(jnp.float64), 1.0)
    if kind in ("var_pop", "stddev_pop"):
        value, valid = caa / nf, cnt > 0
    elif kind in ("variance", "var_samp", "stddev", "stddev_samp"):
        value, valid = caa / jnp.maximum(nf - 1.0, 1.0), cnt > 1
    elif kind == "covar_pop":
        value, valid = cab / nf, cnt > 0
    elif kind == "covar_samp":
        value, valid = cab / jnp.maximum(nf - 1.0, 1.0), cnt > 1
    elif kind == "corr":
        denom = jnp.sqrt(caa * cbb)
        value = cab / jnp.where(denom > 0, denom, 1.0)
        valid = (cnt > 1) & (denom > 0)
    elif kind == "regr_slope":
        value = cab / jnp.where(cbb > 0, cbb, 1.0)
        valid = (cnt > 0) & (cbb > 0)
    else:  # regr_intercept = mean(a) - slope * mean(b)
        slope = cab / jnp.where(cbb > 0, cbb, 1.0)
        value = sa / nf - slope * sb / nf
        valid = (cnt > 0) & (cbb > 0)
    if kind.startswith("stddev"):
        value = jnp.sqrt(jnp.maximum(value, 0.0))
    return value, valid


def _centered_grouped(page: Page, spec: "AggSpec", perm_sorted, seg,
                      n, extra_mask=None) -> Column:
    """variance/stddev/corr/covar/regr per group: segment means first, then
    segment sums of (centered) cross-products — numerically stable where the
    raw-moment form E[x²]−E[x]² cancels."""
    acol = page.column(spec.input)
    av = _to_double(jnp.take(acol.values, perm_sorted, mode="clip"),
                    spec.input_type)
    mask = jnp.take(acol.valid_mask(), perm_sorted, mode="clip") & (seg < n)
    bivar = spec.input2 is not None
    if bivar:
        bcol = page.column(spec.input2)
        bv = _to_double(jnp.take(bcol.values, perm_sorted, mode="clip"),
                        spec.input2_type)
        mask = mask & jnp.take(bcol.valid_mask(), perm_sorted, mode="clip")
    if spec.mask_channel is not None:
        fcol = page.column(spec.mask_channel)
        mask = mask & jnp.take(fcol.values & fcol.valid_mask(), perm_sorted,
                               mode="clip")
    if extra_mask is not None:     # DISTINCT first-occurrence mask
        mask = mask & extra_mask
    cnt = jax.ops.segment_sum(mask.astype(jnp.int64), seg, num_segments=n)
    nf = jnp.maximum(cnt.astype(jnp.float64), 1.0)
    sa = jax.ops.segment_sum(jnp.where(mask, av, 0.0), seg, num_segments=n)
    da = jnp.where(mask, av - jnp.take(sa / nf, seg, mode="clip"), 0.0)
    caa = jax.ops.segment_sum(da * da, seg, num_segments=n)
    sb = cbb = cab = None
    if bivar:
        sb = jax.ops.segment_sum(jnp.where(mask, bv, 0.0), seg,
                                 num_segments=n)
        db = jnp.where(mask, bv - jnp.take(sb / nf, seg, mode="clip"), 0.0)
        cbb = jax.ops.segment_sum(db * db, seg, num_segments=n)
        cab = jax.ops.segment_sum(da * db, seg, num_segments=n)
    value, valid = _centered_finalize(spec.name, cnt, sa, sb, caa, cbb, cab)
    return Column(value, valid, T.DOUBLE, None)


def _centered_global(page: Page, spec: "AggSpec", live,
                     extra_mask=None) -> Column:
    """Single-group variant of _centered_grouped (one output row)."""
    acol = page.column(spec.input)
    av = _to_double(acol.values, spec.input_type)
    mask = acol.valid_mask() & live
    bivar = spec.input2 is not None
    if bivar:
        bcol = page.column(spec.input2)
        bv = _to_double(bcol.values, spec.input2_type)
        mask = mask & bcol.valid_mask()
    if spec.mask_channel is not None:
        fcol = page.column(spec.mask_channel)
        mask = mask & fcol.values & fcol.valid_mask()
    if extra_mask is not None:     # DISTINCT first-occurrence mask
        mask = mask & extra_mask
    cnt = jnp.sum(mask.astype(jnp.int64), keepdims=True)
    nf = jnp.maximum(cnt.astype(jnp.float64), 1.0)
    sa = jnp.sum(jnp.where(mask, av, 0.0), keepdims=True)
    da = jnp.where(mask, av - sa / nf, 0.0)
    caa = jnp.sum(da * da, keepdims=True)
    sb = cbb = cab = None
    if bivar:
        sb = jnp.sum(jnp.where(mask, bv, 0.0), keepdims=True)
        db = jnp.where(mask, bv - sb / nf, 0.0)
        cbb = jnp.sum(db * db, keepdims=True)
        cab = jnp.sum(da * db, keepdims=True)
    value, valid = _centered_finalize(spec.name, cnt, sa, sb, caa, cbb, cab)
    return Column(value, valid, T.DOUBLE, None)


def _ident_for(dtype, is_min):
    if jnp.issubdtype(dtype, jnp.floating):
        return jnp.asarray(jnp.inf if is_min else -jnp.inf, dtype=dtype)
    if dtype == jnp.bool_:
        return jnp.asarray(is_min, dtype=dtype)
    info = jnp.iinfo(dtype)
    return jnp.asarray(info.max if is_min else info.min, dtype=dtype)


def _agg_out_column(fn, spec, values, valid, dictionary=None) -> Column:
    out_t = fn.output_type(spec.input_type)
    # min/max over varchar operate on dictionary codes; keep the pool so the
    # result decodes as strings
    if not T.is_string(out_t):
        dictionary = None
    return Column(values.astype(out_t.dtype), valid, out_t, dictionary)


def _global_aggregate(page, aggs, resolved, step, partial_state_channels):
    """No GROUP BY: one output row (reference: AggregationOperator.java)."""
    live = page.row_mask()
    out_cols: List[Column] = []
    dmask_cache: dict = {}

    def distinct_mask(spec):
        key = (spec.input, spec.mask_channel)
        if key not in dmask_cache:
            dmask_cache[key] = _distinct_first_mask(page, (), spec)
        return dmask_cache[key]

    for ai, (spec, fn) in enumerate(zip(aggs, resolved)):
        if spec.name == "approx_distinct":
            out_cols.append(_hll_global(page, spec, live))
            continue
        if spec.name == "approx_percentile":
            out_cols.append(_percentile_global(page, spec, live))
            continue
        if spec.name in POSITIONAL_AGGREGATES:
            out_cols.append(_positional_global(page, spec, live))
            continue
        if spec.name in CENTERED_AGGREGATES:
            extra = distinct_mask(spec) if spec.distinct else None
            out_cols.append(_centered_global(page, spec, live, extra))
            continue
        states = fn.state(spec.input_type)
        if step == Step.FINAL:
            chans = partial_state_channels[ai]
            merged = []
            for vals, reducer in _final_state_contribs(page, states, chans,
                                                       live):
                if reducer == "sum":
                    merged.append(jnp.sum(vals, keepdims=True))
                elif reducer == "min":
                    merged.append(jnp.min(vals, keepdims=True))
                else:
                    merged.append(jnp.max(vals, keepdims=True))
            values, valid = fn.final(merged, None)
            out_cols.append(_agg_out_column(
                fn, spec, values, valid, page.column(chans[0]).dictionary))
            continue
        vals, mask, dictionary = _agg_inputs(page, spec, fn, live)
        if spec.distinct:
            mask = mask & distinct_mask(spec)
        state_arrays = []
        for sc in states:
            contrib = sc.contrib(vals, mask)
            if sc.reducer == "sum":
                state_arrays.append(jnp.sum(contrib, keepdims=True))
            elif sc.reducer == "min":
                state_arrays.append(jnp.min(contrib, keepdims=True))
            else:
                state_arrays.append(jnp.max(contrib, keepdims=True))
        if step == Step.PARTIAL:
            for sc, arr in zip(states, state_arrays):
                d = dictionary if T.is_string(sc.type) else None
                out_cols.append(Column(arr.astype(sc.type.dtype), None, sc.type,
                                       d))
        else:
            values, valid = fn.final(state_arrays, None)
            out_cols.append(_agg_out_column(fn, spec, values, valid, dictionary))
    return Page(tuple(out_cols), jnp.asarray(1, dtype=jnp.int32))
