"""Literal hoisting: canonicalize lowered expressions for kernel sharing.

Reference parity: sql/gen/PageFunctionCompiler.java:101 — the reference
rewrites constants out of the expression tree before keying its generated
bytecode cache, so `l_quantity < 24` and `l_quantity < 25` share one
compiled PageProcessor and the constant arrives through a session slot.
Here the unit of compilation is an XLA executable, and on TPU compilation
dominates cold latency — so the same move matters more: this pass rewrites
trace-shape-irrelevant Literals into positional `Param` leaves, the
jit-cache key becomes the literal-free canonical tree (+ parameter dtypes,
carried by the Param nodes themselves), and the values flow into the
jitted kernel as a runtime scalar tuple (traced operands, not baked
constants). Second-and-later literal variants of a query shape then run
with ZERO XLA compiles.

What hoists: non-null numeric, decimal (scaled-int), date, timestamp, and
interval literals — comparison/arithmetic constants, IN-list members,
BETWEEN bounds, CASE outputs. Statement-level parameters (`BoundParam`,
from EXECUTE ... USING) fold into the same positional slots, pulling
their values from the execution's bound-value tuple — a cached
(value-free) plan re-executed with new parameters therefore dispatches
the same canonical kernels.

IN-list padding (round 10): an OR-chain of equality tests of ONE needle
against hoistable literals — the translator's desugaring of
`x IN (v1, .., vn)` — used to produce an n-branch canonical tree, so a
5-member and a 6-member list compiled twice. The chain now rewrites to a
single `$in_padded` node whose members ride as ONE padded parameter
vector of width-bucketed (power-of-two, minimum 8) length: every list
length within a bucket shares one executable. Padding slots repeat the
first member, which makes an explicit validity mask unnecessary — a
padding slot's comparison duplicates a real member's comparison, so it
can never change membership. The bucket width is baked into the
canonical tree (it IS trace shape).

What stays static (and why, per call site): see
expr/compiler.py STATIC_LITERAL_ARGS — LIKE/regex patterns and every
string-function literal feed host-side per-dictionary tables; date/format
unit strings select the kernel at trace time. Globally static here:
string literals (comparisons fold against the column's dictionary codes
at trace time), NULL literals (validity structure differs), and booleans
(worthless to parameterize, often trace-shaping). String/boolean
BoundParams bake in as Literals the same way (their kernels key
per-value, like hand-written string literals). Plan-level counts
(LIMIT/TopN, GROUPING set indices, window frame offsets) never pass
through this pass at all — they are operator-spec fields, not expression
leaves, and they size capacities or planes.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.expr.ir import (BoundParam, Call, Literal, Param,
                               RowExpression, SpecialForm, SpecialKind)

# minimum padded IN-list width: lists of 1..8 members share one bucket
# (comparing 8 scalars costs the same fused op as comparing 3 on TPU),
# so the common dashboard IN-lists all dispatch a single executable
IN_PAD_MIN_WIDTH = 8


def hoistable(lit: Literal) -> bool:
    """True when this literal's value can become a traced scalar operand
    without changing the trace: non-null, non-string (dictionary folds are
    host-side), non-boolean."""
    if lit.value is None:
        return False
    t = lit.type
    if T.is_string(t):
        return False
    if isinstance(t, T.BooleanType):
        return False
    return True


def param_value(lit: Literal) -> np.ndarray:
    """The runtime scalar for a hoisted literal: a 0-d numpy array of the
    type's device dtype, mirroring expr/compiler._lit_column exactly so
    the parameterized trace is operand-for-operand identical to the
    constant-embedding one. An explicit dtype (never a weak Python
    scalar) keeps jit's trace cache keyed stably across variants."""
    value = lit.value
    if isinstance(lit.type, T.DecimalType):
        value = int(value)   # scaled-int, same as _lit_column
    return np.asarray(value, dtype=lit.type.dtype)


def hoist_literals(expr: RowExpression, bound: Tuple = ()
                   ) -> Tuple[RowExpression, Tuple[np.ndarray, ...]]:
    """Canonicalize one lowered expression: (literal-free tree, values).

    Param indices are assigned in depth-first visitation order, so the
    canonical tree of any two literal variants of one shape is identical
    and their values tuples align positionally. `bound` is the statement
    parameter values (EXECUTE ... USING) BoundParam leaves draw from.
    """
    values: List[np.ndarray] = []
    out = _walk(expr, values, bound)
    return out, tuple(values)


def hoist_literal_seq(exprs: Sequence[RowExpression], bound: Tuple = ()
                      ) -> Tuple[Tuple[RowExpression, ...],
                                 Tuple[np.ndarray, ...]]:
    """Canonicalize a projection list with ONE shared params tuple:
    indices run on across expressions, so the whole operator passes a
    single values tuple to its compiled kernel."""
    values: List[np.ndarray] = []
    outs = tuple(_walk(e, values, bound) for e in exprs)
    return outs, tuple(values)


def materialize_bound(expr: RowExpression, bound: Tuple) -> RowExpression:
    """Replace BoundParam leaves with their bound values as Literals —
    the hoist-disabled execution path for prepared statements (kernels
    then key per-value, exactly like hand-written literals)."""
    if isinstance(expr, BoundParam):
        return _bound_literal(expr, bound)
    if isinstance(expr, Call):
        args = tuple(materialize_bound(a, bound) for a in expr.args)
        if all(a is b for a, b in zip(args, expr.args)):
            return expr
        return Call(expr.name, args, expr.type)
    if isinstance(expr, SpecialForm):
        args = tuple(materialize_bound(a, bound) for a in expr.args)
        if all(a is b for a, b in zip(args, expr.args)):
            return expr
        return SpecialForm(expr.kind, args, expr.type)
    return expr


def _bound_literal(e: BoundParam, bound: Tuple) -> Literal:
    if e.position >= len(bound):
        raise IndexError(
            f"statement parameter ?{e.position + 1} has no bound value "
            f"({len(bound)} bound)")
    return Literal(bound[e.position], e.type)


def _static_bound(e: BoundParam) -> bool:
    """Statement parameters whose values must bake in as Literals:
    strings fold against dictionaries host-side, booleans are often
    trace-shaping — the same rules `hoistable` applies to Literals."""
    return T.is_string(e.type) or isinstance(e.type, T.BooleanType)


def _walk(e: RowExpression, values: List[np.ndarray],
          bound: Tuple = ()) -> RowExpression:
    from trino_tpu.expr.compiler import STATIC_LITERAL_ARGS
    if isinstance(e, Literal):
        if not hoistable(e):
            return e
        values.append(param_value(e))
        return Param(len(values) - 1, e.type)
    if isinstance(e, BoundParam):
        lit = _bound_literal(e, bound)
        if _static_bound(e):
            return lit
        values.append(param_value(lit))
        return Param(len(values) - 1, e.type)
    if isinstance(e, Call):
        static = STATIC_LITERAL_ARGS.get(e.name)
        if static == "all":
            # the whole call (column subtree included) evaluates inside
            # host-side dictionary machinery that requires Literal args —
            # leave it byte-identical (bound params bake in as Literals)
            return materialize_bound(e, bound)
        args = tuple(materialize_bound(a, bound)
                     if (static is not None and i in static)
                     else _walk(a, values, bound)
                     for i, a in enumerate(e.args))
        return Call(e.name, args, e.type)
    if isinstance(e, SpecialForm):
        if e.kind is SpecialKind.OR:
            padded = _pad_in_chain(e, values, bound)
            if padded is not None:
                return padded
        return SpecialForm(e.kind,
                           tuple(_walk(a, values, bound) for a in e.args),
                           e.type)
    return e   # InputRef / SymbolRef / already-canonical Param


# ------------------------------------------------------- padded IN-lists


def _flatten_or(e: RowExpression, out: List[RowExpression]) -> None:
    if isinstance(e, SpecialForm) and e.kind is SpecialKind.OR:
        for a in e.args:
            _flatten_or(a, out)
    else:
        out.append(e)


def _match_in_chain(e: SpecialForm, bound: Tuple
                    ) -> Optional[Tuple[RowExpression, List[Literal]]]:
    """(needle, members) when `e` is an OR-chain of equality tests of ONE
    needle subtree against hoistable literals of one type — the
    translator's IN-list desugaring (and any hand-written equivalent;
    the rewrite is semantics-preserving for every such chain). Statement
    parameters (`IN (?, ?, ?)`) resolve to their bound values here, so
    prepared IN-lists ride the same padded vector literal lists do."""
    leaves: List[RowExpression] = []
    _flatten_or(e, leaves)
    if len(leaves) < 2:
        return None
    needle: Optional[RowExpression] = None
    members: List[Literal] = []
    for leaf in leaves:
        if not (isinstance(leaf, Call) and leaf.name == "eq"
                and len(leaf.args) == 2):
            return None
        lhs, rhs = leaf.args
        if isinstance(rhs, BoundParam) and not _static_bound(rhs):
            rhs = _bound_literal(rhs, bound)
        if not isinstance(rhs, Literal) or not hoistable(rhs):
            return None
        if isinstance(lhs, (Literal, BoundParam)):
            return None
        if needle is None:
            needle = lhs
        elif lhs != needle:
            return None
        members.append(rhs)
    if any(m.type != members[0].type for m in members):
        return None
    return needle, members


def pad_width(n: int) -> int:
    """Power-of-two bucket for an n-member IN-list, floored at
    IN_PAD_MIN_WIDTH so typical dashboard lists all share one bucket."""
    w = IN_PAD_MIN_WIDTH
    while w < n:
        w *= 2
    return w


def _pad_in_chain(e: SpecialForm, values: List[np.ndarray],
                  bound: Tuple) -> Optional[RowExpression]:
    """Rewrite an IN-style OR-chain to `$in_padded(needle, Param)` with
    the members as ONE width-bucketed padded parameter vector. Padding
    repeats the first member (a duplicate comparison, never a new match),
    so no separate validity mask rides along. The static width Literal in
    the canonical tree keys the bucket — a 9-member list (width 16) must
    not silently retrace a warm width-8 executable."""
    got = _match_in_chain(e, bound)
    if got is None:
        return None
    needle, members = got
    canon_needle = _walk(needle, values, bound)
    width = pad_width(len(members))
    vec = np.stack([param_value(m) for m in members]
                   + [param_value(members[0])] * (width - len(members)))
    values.append(vec)
    return Call("$in_padded",
                (canon_needle, Param(len(values) - 1, members[0].type),
                 Literal(width, T.INTEGER)),
                e.type)
