"""Property-docs lint: registering a knob without documenting it fails.

Every SESSION property (metadata.SESSION_PROPERTY_DEFAULTS) and every
server/fleet constructor property must carry a docs entry in
SESSION_PROPERTY_DOCS / SERVER_PROPERTY_DOCS — those dicts feed SHOW
SESSION and system.runtime.server_properties, so a missing entry is an
operator-invisible knob. The session check is bidirectional: a doc for
a property that no longer exists is stale and fails too.
"""

import inspect

from trino_tpu.metadata import (SERVER_PROPERTY_DOCS,
                                SESSION_PROPERTY_DEFAULTS,
                                SESSION_PROPERTY_DOCS)

# constructor parameters that inject collaborators rather than
# configure behavior — not operator-facing properties
_WIRING = {
    "self", "runner", "resource_groups", "result_cache", "scan_cache",
    "table_cache", "warmup_manifest", "worker_env", "engine_env",
    "engine_kwargs",
}


def test_every_session_property_documented():
    missing = set(SESSION_PROPERTY_DEFAULTS) - set(SESSION_PROPERTY_DOCS)
    assert not missing, \
        f"session properties without docs: {sorted(missing)}"


def test_no_stale_session_property_docs():
    stale = set(SESSION_PROPERTY_DOCS) - set(SESSION_PROPERTY_DEFAULTS)
    assert not stale, \
        f"docs for unregistered session properties: {sorted(stale)}"


def test_session_docs_are_substantive():
    for name, doc in SESSION_PROPERTY_DOCS.items():
        assert isinstance(doc, str) and len(doc.strip()) >= 20, \
            f"doc for {name!r} is empty or too thin"


def test_every_server_property_documented():
    from trino_tpu.fleet.server import FleetServer
    from trino_tpu.server.app import TrinoServer
    params = set()
    for ctor in (TrinoServer.__init__, FleetServer.__init__):
        params |= set(inspect.signature(ctor).parameters)
    missing = (params - _WIRING) - set(SERVER_PROPERTY_DOCS)
    assert not missing, \
        f"server properties without docs: {sorted(missing)}"


def test_server_docs_are_substantive():
    for name, doc in SERVER_PROPERTY_DOCS.items():
        assert isinstance(doc, str) and len(doc.strip()) >= 20, \
            f"doc for {name!r} is empty or too thin"
