"""system.runtime introspection tables.

Reference parity: connector/system/ (GlobalSystemConnector,
QuerySystemTable, NodeSystemTable) + execution/QueryTracker.java states.
"""

from trino_tpu.exec import LocalQueryRunner


def test_runtime_queries_shows_current_and_past():
    r = LocalQueryRunner.tpch("tiny")
    r.execute("SELECT count(*) FROM nation")
    rows = r.execute(
        "SELECT query_id, state, query, rows FROM system.runtime.queries "
        "ORDER BY query_id").rows
    states = {row[2]: row[1] for row in rows}
    assert states.get("SELECT count(*) FROM nation") == "FINISHED"
    # the introspection query itself is RUNNING while it scans the table
    running = [row for row in rows if row[1] == "RUNNING"]
    assert len(running) == 1
    assert "system.runtime.queries" in running[0][2]
    finished = [row for row in rows if row[2].startswith("SELECT count")]
    assert finished[0][3] == 1     # one result row recorded


def test_runtime_queries_records_failure():
    r = LocalQueryRunner.tpch("tiny")
    try:
        r.execute("SELECT * FROM tpch.tiny.nonexistent_table")
    except Exception:
        pass
    rows = r.execute(
        "SELECT state, error FROM system.runtime.queries "
        "WHERE query LIKE '%nonexistent_table%' AND state = 'FAILED'").rows
    assert rows and rows[0][1] is not None


def test_runtime_nodes_and_tasks():
    r = LocalQueryRunner.tpch("tiny")
    nodes = r.execute("SELECT node_id, coordinator, state "
                      "FROM system.runtime.nodes").rows
    assert nodes and any(n[1] for n in nodes)
    assert all(n[2] == "active" for n in nodes)
    tasks = r.execute("SELECT query_id, task_id, state "
                      "FROM system.runtime.tasks").rows
    assert tasks


def test_show_tables_system():
    r = LocalQueryRunner.tpch("tiny")
    rows = r.execute("SHOW TABLES FROM system.runtime").rows
    assert ("queries",) in rows and ("nodes",) in rows
