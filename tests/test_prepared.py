"""Prepared-statement fast path: plan cache + EXECUTE ... USING binding.

The contract under test (Trino PREPARE/EXECUTE protocol + the statement
reuse layer, round 9): a prepared Query plans ONCE with value-free
parameter slots; every EXECUTE ... USING re-execution — any values, same
types — hits the plan cache (zero planning) and binds its values into
the SAME warm kernels literal hoisting compiled (zero XLA compiles),
while staying row-identical to the literal-substituted statement the
sqlite oracle verifies. Padded IN-list kernels extend the sharing to
membership lists: every list length within a power-of-two pad bucket
dispatches one executable.
"""

import json
import urllib.parse
import urllib.request

import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.exec import LocalQueryRunner, jit_cache
from trino_tpu.exec import plan_cache as pc
from trino_tpu.expr.functions import days_from_civil
from trino_tpu.sql.analyzer import SemanticError

from oracle import assert_same, load_tpch_sqlite

SF = 0.01


def d(text: str) -> int:
    y, m, dd = text.split("-")
    return days_from_civil(int(y), int(m), int(dd))


@pytest.fixture(scope="module")
def runner():
    return LocalQueryRunner.tpch("tiny")


@pytest.fixture(scope="module")
def oracle():
    conn = load_tpch_sqlite(SF)
    yield conn
    conn.close()


Q6_PREPARED = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= ? AND l_shipdate < ? + INTERVAL '1' YEAR
  AND l_discount BETWEEN ? - 0.01 AND ? + 0.01
  AND l_quantity < ?
"""


def _oracle_q6(oracle, year: int, disc_lo: int, disc_hi: int, qty: int):
    return oracle.execute(f"""
        SELECT sum(l_extendedprice * l_discount) FROM lineitem
        WHERE l_shipdate >= {d(f'{year}-01-01')}
          AND l_shipdate < {d(f'{year + 1}-01-01')}
          AND l_discount BETWEEN {disc_lo} AND {disc_hi}
          AND l_quantity < {qty * 100}
        """).fetchall()


# ----------------------------------------------------- EXECUTE ... USING


def test_execute_without_parameters_still_works(runner):
    runner.execute("PREPARE plain FROM SELECT count(*) FROM region")
    assert runner.execute("EXECUTE plain").only_value() == 5
    runner.execute("DEALLOCATE PREPARE plain")
    with pytest.raises(SemanticError, match="not found"):
        runner.execute("EXECUTE plain")


def test_prepare_execute_using_oracle_parity(runner, oracle):
    runner.execute(f"PREPARE pq6 FROM {Q6_PREPARED}")
    got = runner.execute("EXECUTE pq6 USING DATE '1994-01-01', "
                         "DATE '1994-01-01', 0.06, 0.06, 24")
    assert_same(got.rows, _oracle_q6(oracle, 1994, 5, 7, 24), False)
    got = runner.execute("EXECUTE pq6 USING DATE '1995-01-01', "
                         "DATE '1995-01-01', 0.07, 0.07, 25")
    assert_same(got.rows, _oracle_q6(oracle, 1995, 6, 8, 25), False)


def test_perturbed_execute_zero_misses_plan_hit(runner):
    """THE acceptance criterion: a re-EXECUTE with perturbed values
    reports plan_cache_hits >= 1 (no re-planning) and jit_misses == 0
    (no XLA compiles) — parameter binding + cached-executable dispatch
    is the whole cost."""
    runner.execute(f"PREPARE pq6b FROM {Q6_PREPARED}")
    runner.execute("EXECUTE pq6b USING DATE '1994-01-01', "
                   "DATE '1994-01-01', 0.06, 0.06, 24")
    runner.execute("EXECUTE pq6b USING DATE '1996-01-01', "
                   "DATE '1996-01-01', 0.05, 0.08, 30")
    stats = runner.last_query_stats
    assert stats["plan_cache_hits"] >= 1
    assert stats["plan_cache_misses"] == 0
    assert stats["jit_misses"] == 0
    assert stats["jit_param_hits"] > 0
    # planning was skipped outright, not merely fast
    assert stats["planning_s"] == 0.0


def test_execute_matches_plain_sql(runner):
    """The bound execution must be row-identical to the same statement
    with the values written as literals (the oracle-verified path)."""
    runner.execute(f"PREPARE pq6c FROM {Q6_PREPARED}")
    got = runner.execute("EXECUTE pq6c USING DATE '1995-01-01', "
                         "DATE '1995-01-01', 0.07, 0.07, 25")
    want = runner.execute("""
        SELECT sum(l_extendedprice * l_discount) AS revenue
        FROM lineitem
        WHERE l_shipdate >= DATE '1995-01-01'
          AND l_shipdate < DATE '1995-01-01' + INTERVAL '1' YEAR
          AND l_discount BETWEEN 0.07 - 0.01 AND 0.07 + 0.01
          AND l_quantity < 25""")
    assert_same(got.rows, want.rows, False)


def test_execute_string_parameter(runner):
    """String parameters bake in as literals (dictionary folds are
    host-side) — correct rows, per-value kernels, stable plan key
    across different string lengths (varchar normalizes unbounded)."""
    runner.execute("PREPARE pseg FROM SELECT count(*) FROM customer "
                   "WHERE c_mktsegment = ?")
    base = runner.execute("SELECT count(*) FROM customer "
                          "WHERE c_mktsegment = 'BUILDING'").only_value()
    got = runner.execute("EXECUTE pseg USING 'BUILDING'").only_value()
    assert got == base
    runner.execute("EXECUTE pseg USING 'AUTOMOBILE'")
    stats = runner.last_query_stats
    assert stats["plan_cache_hits"] >= 1     # same varchar type, same plan


def test_execute_null_parameter(runner):
    """USING NULL: no type to key a value-free plan on, so the runner
    substitutes the AST (literal-NULL semantics, plan per execution)
    instead of surfacing an internal cast error."""
    runner.execute("PREPARE pnull FROM "
                   "SELECT count(*) FROM lineitem WHERE l_quantity < ?")
    assert runner.execute("EXECUTE pnull USING NULL").only_value() == 0
    got = runner.execute("EXECUTE pnull USING 24").only_value()
    want = runner.execute("SELECT count(*) FROM lineitem "
                          "WHERE l_quantity < 24").only_value()
    assert got == want   # non-NULL re-execution still takes the fast path


def test_execute_insert_prepared(runner):
    """Non-query prepared statements bind by AST substitution."""
    runner.execute("CREATE TABLE memory.default.prep_ins (a bigint)")
    runner.execute("PREPARE pins FROM "
                   "INSERT INTO memory.default.prep_ins VALUES (?)")
    runner.execute("EXECUTE pins USING 7")
    runner.execute("EXECUTE pins USING 9")
    got = runner.execute(
        "SELECT sum(a), count(*) FROM memory.default.prep_ins")
    assert got.rows == [(16, 2)]
    runner.execute("DROP TABLE memory.default.prep_ins")


# ---------------------------------------------------- arity/type errors


def test_execute_arity_mismatch(runner):
    runner.execute("PREPARE parity FROM "
                   "SELECT count(*) FROM lineitem WHERE l_quantity < ?")
    with pytest.raises(SemanticError, match="expected 1 but found 0"):
        runner.execute("EXECUTE parity")
    with pytest.raises(SemanticError, match="expected 1 but found 2"):
        runner.execute("EXECUTE parity USING 1, 2")


def test_execute_type_mismatch(runner):
    runner.execute("PREPARE ptype FROM "
                   "SELECT count(*) FROM lineitem WHERE l_quantity < ?")
    with pytest.raises(SemanticError, match="cannot compare"):
        runner.execute("EXECUTE ptype USING 'not a number'")
    runner.execute("PREPARE pdate FROM "
                   "SELECT count(*) FROM lineitem WHERE l_shipdate >= ?")
    with pytest.raises(SemanticError, match="cannot compare"):
        runner.execute("EXECUTE pdate USING 'not a date'")


def test_execute_non_constant_parameter(runner):
    runner.execute("PREPARE pconst FROM "
                   "SELECT count(*) FROM lineitem WHERE l_quantity < ?")
    with pytest.raises(SemanticError, match="constant"):
        runner.execute("EXECUTE pconst USING 1 + 1")
    # a column reference fails name resolution (no scope in USING)
    with pytest.raises(SemanticError, match="cannot be resolved"):
        runner.execute("EXECUTE pconst USING l_quantity")


# ------------------------------------------------------ padded IN-lists


def test_in_lists_share_one_executable_within_bucket(runner):
    """IN-lists of lengths 3/5/6 all pad to the minimum bucket (8): after
    warming ANY of them, the others dispatch with zero compiles and the
    jit cache does not grow."""
    runner.execute(
        "SELECT count(*) FROM part WHERE p_size IN (1, 2, 3, 4, 5)")
    size0 = jit_cache.cache_info()
    for in_list in ("(9, 14, 23)",                  # 3 members
                    "(49, 14, 23, 45, 19)",        # 5 members
                    "(49, 14, 23, 45, 19, 3)"):    # 6 members
        runner.execute(
            f"SELECT count(*) FROM part WHERE p_size IN {in_list}")
        stats = runner.last_query_stats
        assert stats["jit_misses"] == 0, \
            f"IN {in_list} recompiled (pad bucket not shared)"
    assert jit_cache.cache_info() == size0


def test_padded_in_oracle_parity(runner, oracle):
    for in_list in ("(9, 14, 23)", "(49, 14, 23, 45, 19)",
                    "(49, 14, 23, 45, 19, 3)"):
        got = runner.execute(
            f"SELECT count(*) FROM part WHERE p_size IN {in_list}")
        want = oracle.execute(
            f"SELECT count(*) FROM part WHERE p_size IN {in_list}"
        ).fetchall()
        assert_same(got.rows, want, False)


def test_padded_in_null_needle_semantics(runner):
    """Null needle -> null membership -> WHERE drops the row (the OR-of-
    eq Kleene semantics the padded kernel replaces)."""
    runner.execute("CREATE TABLE memory.default.pin_null (v bigint)")
    runner.execute("INSERT INTO memory.default.pin_null VALUES "
                   "(1), (NULL), (3), (7)")
    got = runner.execute("SELECT count(*) FROM memory.default.pin_null "
                         "WHERE v IN (1, 3, 5)")
    assert got.only_value() == 2
    got = runner.execute("SELECT count(*) FROM memory.default.pin_null "
                         "WHERE v NOT IN (1, 3, 5)")
    assert got.only_value() == 1     # only 7; NULL is neither in nor out
    runner.execute("DROP TABLE memory.default.pin_null")


def test_prepared_in_list_parameters(runner, oracle):
    """IN (?, ?, ?): members arrive as statement parameters and ride the
    same padded vector literal lists do — after a LITERAL list of the
    same shape warms the bucket, even the FIRST EXECUTE dispatches with
    zero compiles, and perturbed members re-execute warm too."""
    runner.execute(
        "SELECT count(*) FROM part WHERE p_size IN (31, 33, 35)")
    size0 = jit_cache.cache_info()
    runner.execute("PREPARE pin FROM "
                   "SELECT count(*) FROM part WHERE p_size IN (?, ?, ?)")
    got = runner.execute("EXECUTE pin USING 9, 14, 23")
    assert runner.last_query_stats["jit_misses"] == 0
    assert jit_cache.cache_info() == size0
    want = oracle.execute("SELECT count(*) FROM part "
                          "WHERE p_size IN (9, 14, 23)").fetchall()
    assert_same(got.rows, want, False)
    runner.execute("EXECUTE pin USING 4, 11, 37")
    stats = runner.last_query_stats
    assert stats["jit_misses"] == 0
    assert stats["plan_cache_hits"] >= 1


# ----------------------------------------------------------- plan cache


def test_plan_cache_repeated_statement_hits():
    r = LocalQueryRunner.tpch("tiny")
    sql = "SELECT count(*) FROM nation WHERE n_regionkey = 2"
    r.execute(sql)
    assert r.last_query_stats["plan_cache_misses"] == 1
    r.execute(sql)
    assert r.last_query_stats["plan_cache_hits"] == 1
    assert r.last_query_stats["plan_cache_misses"] == 0
    # a DIFFERENT literal is a different statement (plans may specialize
    # on values): miss, while the kernels still share via hoisting
    r.execute("SELECT count(*) FROM nation WHERE n_regionkey = 3")
    assert r.last_query_stats["plan_cache_misses"] == 1


def test_plan_cache_disabled_session_property():
    r = LocalQueryRunner.tpch("tiny")
    r.execute("SET SESSION plan_cache_enabled = false")
    sql = "SELECT count(*) FROM region"
    r.execute(sql)
    r.execute(sql)
    assert r.last_query_stats["plan_cache_hits"] == 0
    assert r.last_query_stats["plan_cache_misses"] == 0   # never consulted


def test_plan_cache_invalidation_insert_and_drop():
    r = LocalQueryRunner.tpch("tiny")
    r.execute("CREATE TABLE memory.default.pc_inv (a bigint)")
    r.execute("INSERT INTO memory.default.pc_inv VALUES (1), (2)")
    sql = "SELECT count(*) FROM memory.default.pc_inv"
    assert r.execute(sql).only_value() == 2
    r.execute(sql)
    assert r.last_query_stats["plan_cache_hits"] == 1
    # INSERT invalidates: the next run re-plans AND sees the new row
    r.execute("INSERT INTO memory.default.pc_inv VALUES (3)")
    assert r.execute(sql).only_value() == 3
    assert r.last_query_stats["plan_cache_misses"] == 1
    assert r.last_query_stats["plan_cache_hits"] == 0
    # DROP + recreate: the cached plan's stale handle must not survive
    r.execute(sql)   # re-warm
    r.execute("DROP TABLE memory.default.pc_inv")
    r.execute("CREATE TABLE memory.default.pc_inv (a bigint)")
    r.execute("INSERT INTO memory.default.pc_inv VALUES (9)")
    assert r.execute(sql).only_value() == 1
    assert r.last_query_stats["plan_cache_misses"] == 1
    r.execute("DROP TABLE memory.default.pc_inv")


def test_plan_cache_lru_eviction():
    r = LocalQueryRunner.tpch("tiny")
    r.execute("SET SESSION plan_cache_max_entries = 2")
    q1 = "SELECT count(*) FROM region"
    q2 = "SELECT count(*) FROM nation"
    q3 = "SELECT count(*) FROM supplier"
    r.execute(q1)
    r.execute(q2)
    r.execute(q3)          # evicts q1 (LRU)
    assert len(r._plan_cache) == 2
    r.execute(q3)
    assert r.last_query_stats["plan_cache_hits"] == 1
    r.execute(q1)          # was evicted: full plan again
    assert r.last_query_stats["plan_cache_misses"] == 1


def test_plan_cache_clone_cannot_shrink_shared_cache():
    """for_query() clones carry per-request (header-overridable) session
    bags — a clone setting plan_cache_max_entries must not resize the
    shared LRU out from under every other session."""
    r = LocalQueryRunner.tpch("tiny")
    r.execute("SELECT count(*) FROM region")
    r.execute("SELECT count(*) FROM nation")
    clone = r.for_query()
    clone.session.properties["plan_cache_max_entries"] = 1
    clone.execute("SELECT count(*) FROM supplier")
    assert len(r._plan_cache) == 3   # clone's bound never applied
    r.execute("SET SESSION plan_cache_max_entries = 1")
    r.execute("SELECT count(*) FROM part")
    assert len(r._plan_cache) == 1   # the owning runner's bound does


def test_plan_cache_put_rejects_stale_generation():
    """put() carries the generation read before planning: a plan built
    against pre-invalidation catalog state must never land (the
    invalidation that should have dropped it already ran)."""
    c = pc.PlanCache()
    table = ("memory", "default", "t")
    gen = c.generation()
    c.invalidate(table)                  # concurrent DDL during planning
    c.put("k", "stale-plan", frozenset({table}), gen=gen)
    assert c.get("k") is None            # rejected
    c.put("k2", "plan", frozenset({("memory", "default", "u")}), gen=gen)
    assert c.get("k2") == "plan"         # unaffected table still lands


def test_plan_cache_ddl_during_planning_not_cached():
    """The runner threads the pre-planning generation into put():
    simulate a clone's INSERT invalidating the scanned table while this
    runner is mid-planning — the stale plan must not be published."""
    r = LocalQueryRunner.tpch("tiny")
    r.execute("SELECT count(*) FROM region")
    (entry,) = r._plan_cache._entries.values()
    (table,) = entry.tables              # region's invalidation key
    r._plan_cache.clear()
    orig = r._plan_for_execution

    def racy(query):
        plan = orig(query)
        r._plan_cache.invalidate(table)  # lands mid-planning
        return plan

    r._plan_for_execution = racy
    try:
        r.execute("SELECT count(*) FROM region")
    finally:
        del r._plan_for_execution
    assert len(r._plan_cache) == 0       # stale plan rejected
    r.execute("SELECT count(*) FROM region")
    assert len(r._plan_cache) == 1       # next execution re-caches


def test_plan_cache_shrink_applies_without_a_miss():
    """SET SESSION plan_cache_max_entries must bind immediately: a
    hit-only steady-state workload never reaches the miss path's
    re-read, and a lowered bound must reclaim plans now."""
    r = LocalQueryRunner.tpch("tiny")
    for q in ("SELECT count(*) FROM region", "SELECT count(*) FROM nation",
              "SELECT count(*) FROM supplier"):
        r.execute(q)
    assert len(r._plan_cache) == 3
    r.execute("SET SESSION plan_cache_max_entries = 1")
    assert len(r._plan_cache) == 1
    assert r._plan_cache.max_entries == 1
    r.execute("RESET SESSION plan_cache_max_entries")
    assert r._plan_cache.max_entries == 256


def test_plan_cache_keys_on_schema_and_plan_properties():
    r = LocalQueryRunner.tpch("tiny")
    sql = "SELECT count(*) FROM lineitem"
    r.execute(sql)
    r.execute("USE tpch.sf1")
    r.execute(sql)       # same text, different schema: different plan
    assert r.last_query_stats["plan_cache_misses"] == 1
    r.execute("SET SESSION join_distribution_type = 'BROADCAST'")
    r.execute(sql)       # plan-affecting property fragments the key
    assert r.last_query_stats["plan_cache_misses"] == 1


def test_distributed_runner_uses_plan_cache():
    """The distributed runner plans through the same cache — a repeated
    shape (or an EXECUTE re-run) reuses the distributed-optimized plan,
    zero planning on re-execution."""
    from trino_tpu.exec.distributed import DistributedQueryRunner
    r = DistributedQueryRunner.tpch("tiny")
    sql = "SELECT count(*) FROM nation"
    r.execute(sql)
    assert r.last_query_stats["plan_cache_misses"] == 1
    r.execute(sql)
    assert r.last_query_stats["plan_cache_hits"] == 1
    r.execute("PREPARE dpq FROM "
              "SELECT count(*) FROM nation WHERE n_regionkey = ?")
    assert r.execute("EXECUTE dpq USING 1").only_value() == 5
    r.execute("EXECUTE dpq USING 2")
    stats = r.last_query_stats
    assert stats["plan_cache_hits"] >= 1
    assert stats["planning_s"] == 0.0


def test_plan_cache_metrics_exported(runner):
    from trino_tpu.obs.metrics import REGISTRY
    runner.execute("SELECT count(*) FROM region")
    text = REGISTRY.render()
    for name in ("trino_tpu_plan_cache_entries",
                 "trino_tpu_plan_cache_hits",
                 "trino_tpu_plan_cache_misses",
                 "trino_tpu_plan_cache_evictions_total",
                 "trino_tpu_plan_cache_invalidations_total"):
        assert name in text
    assert pc.stats()["entries"] >= 1


def test_explain_analyze_footer_shows_plan_cache():
    r = LocalQueryRunner.tpch("tiny")
    out = r.execute(
        "EXPLAIN ANALYZE SELECT count(*) FROM region").only_value()
    assert "plan cache 0 hits / 1 misses" in out
    # EXPLAIN ANALYZE plans through the cache, sharing the entry the
    # plain statement dispatches: both re-runs are hits
    out = r.execute(
        "EXPLAIN ANALYZE SELECT count(*) FROM region").only_value()
    assert "plan cache 1 hits / 0 misses" in out
    r.execute("SELECT count(*) FROM region")
    assert r.last_query_stats["plan_cache_hits"] == 1


def test_server_plan_cache_max_entries_config():
    """Per-request header overrides on pooled clones never resize the
    shared cache, so a deployment sizes it at the server constructor."""
    from trino_tpu.server.app import TrinoServer
    r = LocalQueryRunner.tpch("tiny")
    server = TrinoServer(r, plan_cache_max_entries=1).start()
    try:
        assert r._plan_cache.max_entries == 1
        r.execute("SELECT count(*) FROM region")
        r.execute("SELECT count(*) FROM nation")
        assert len(r._plan_cache) == 1
        # the base session property matches, so a direct plan miss on the
        # owning runner must not snap the bound back to the default
        assert r.session.get("plan_cache_max_entries") == 1
    finally:
        server.stop()


# ------------------------------------------ dictionary content keys


def test_dictionary_content_fingerprint():
    from trino_tpu.page import Dictionary
    d1 = Dictionary(np.asarray(["a", "b", "c"], dtype=object))
    d2 = Dictionary(np.asarray(["a", "b", "c"], dtype=object))
    d3 = Dictionary(np.asarray(["a", "b", "d"], dtype=object))
    assert d1 is not d2
    assert d1 == d2 and hash(d1) == hash(d2)
    assert d1 != d3


def test_identical_dictionary_content_shares_one_trace():
    """Two tables with byte-identical string pools must hit ONE trace of
    a warm kernel — the jit trace cache keys dictionaries by content
    fingerprint, not object identity."""
    import jax
    import jax.numpy as jnp
    from trino_tpu.page import Column, Dictionary, Page

    @jax.jit
    def f(page):
        return page.columns[0].values + 1

    def make_page():
        dct, codes = Dictionary.build(
            np.asarray(["x", "y", "x", "z"], dtype=object))
        return Page((Column(jnp.asarray(codes), None,
                            T.VARCHAR, dct),), 4)

    p1, p2 = make_page(), make_page()
    assert p1.columns[0].dictionary is not p2.columns[0].dictionary
    f(p1)
    f(p2)
    if hasattr(f, "_cache_size"):
        assert f._cache_size() == 1


def test_join_across_content_identical_dictionaries():
    """Two tables whose string pools are byte-identical have the same
    code mapping (content-fingerprint equality), so a string-key join
    across them serves instead of raising 'distinct dictionaries'."""
    r = LocalQueryRunner.tpch("tiny")
    r.execute("CREATE TABLE memory.default.dj1 AS "
              "SELECT n_name, n_nationkey FROM nation")
    r.execute("CREATE TABLE memory.default.dj2 AS "
              "SELECT n_name, n_regionkey FROM nation")
    out = r.execute(
        "SELECT count(*) FROM memory.default.dj1 a, memory.default.dj2 b "
        "WHERE a.n_name = b.n_name").only_value()
    assert out == 25   # 25 unique names, each matches itself once
    # downstream string comparison across the two pools works too
    # (expr/compiler._cmp_strings applies the same fingerprint equality)
    out = r.execute(
        "SELECT count(*) FROM memory.default.dj1 a "
        "JOIN memory.default.dj2 b ON a.n_nationkey = b.n_regionkey "
        "WHERE a.n_name < b.n_name").only_value()
    want = r.execute(
        "SELECT count(*) FROM nation a "
        "JOIN nation b ON a.n_nationkey = b.n_regionkey "
        "WHERE a.n_name < b.n_name").only_value()
    assert out == want


# ------------------------------------------------ HTTP wire protocol


def _post(server, sql, headers=None):
    req = urllib.request.Request(
        f"{server.base_uri}/v1/statement", data=sql.encode(),
        method="POST")
    req.add_header("X-Trino-User", "test")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read()), dict(resp.headers)


def _run(server, sql, headers=None):
    payload, hdrs = _post(server, sql, headers)
    # data may arrive in ANY response including the first: the serving
    # tier's result-cache fast path answers FINISHED inline on the POST
    rows = list(payload.get("data", []))
    while "nextUri" in payload:
        with urllib.request.urlopen(payload["nextUri"]) as resp:
            hdrs.update(dict(resp.headers))
            payload = json.loads(resp.read())
        rows.extend(payload.get("data", []))
    return payload, rows, hdrs


def test_prepared_statement_over_http():
    from trino_tpu.server.app import TrinoServer
    server = TrinoServer(LocalQueryRunner.tpch("tiny")).start()
    try:
        stmt = "SELECT count(*) FROM region WHERE r_regionkey < ?"
        # PREPARE echoes the statement back for the stateless client
        payload, _, hdrs = _run(server, f"PREPARE hp FROM {stmt}")
        added = hdrs.get("X-Trino-Added-Prepare", "")
        name, _, enc = added.partition("=")
        assert urllib.parse.unquote(name) == "hp"
        assert urllib.parse.unquote(enc) == stmt
        # EXECUTE works only when the client re-sends the statement
        header = {"X-Trino-Prepared-Statement":
                  f"hp={urllib.parse.quote(stmt, safe='')}"}
        payload, rows, _ = _run(server, "EXECUTE hp USING 3", header)
        assert payload.get("error") is None
        assert rows == [[3]]
        # without the header the session has no such statement
        payload, _, _ = _run(server, "EXECUTE hp USING 3")
        assert payload.get("error") is not None
        assert "not found" in payload["error"]["message"]
        # DEALLOCATE echoes the name for the client to forget
        payload, _, hdrs = _run(server, "DEALLOCATE PREPARE hp", header)
        assert hdrs.get("X-Trino-Deallocated-Prepare") == "hp"
    finally:
        server.stop()


def test_prepared_http_name_normalization():
    """The echo must carry the PARSER-normalized name: unquoted names
    lowercase (EXECUTE resolves through the parser again, so a raw-case
    echo would install a key EXECUTE can never find), quoted names
    verbatim."""
    from trino_tpu.server.app import TrinoServer
    server = TrinoServer(LocalQueryRunner.tpch("tiny")).start()
    try:
        stmt = "SELECT count(*) FROM region WHERE r_regionkey < ?"
        _, _, hdrs = _run(server, f"PREPARE MyQ FROM {stmt}")
        added = hdrs.get("X-Trino-Added-Prepare", "")
        name, _, enc = added.partition("=")
        assert urllib.parse.unquote(name) == "myq"
        # the client re-sends exactly what was echoed
        payload, rows, _ = _run(server, "EXECUTE MyQ USING 3",
                                {"X-Trino-Prepared-Statement": added})
        assert payload.get("error") is None and rows == [[3]]
        _, _, hdrs = _run(server, "DEALLOCATE PREPARE MyQ")
        assert hdrs.get("X-Trino-Deallocated-Prepare") == "myq"
        # quoted names echo verbatim (spaces and case preserved)
        _, _, hdrs = _run(server, f'PREPARE "My Q" FROM {stmt}')
        added = hdrs.get("X-Trino-Added-Prepare", "")
        name, _, _ = added.partition("=")
        assert urllib.parse.unquote(name) == "My Q"
        payload, rows, _ = _run(server, 'EXECUTE "My Q" USING 3',
                                {"X-Trino-Prepared-Statement": added})
        assert payload.get("error") is None and rows == [[3]]
    finally:
        server.stop()
