"""Fleet-aggregated metrics: merge Prometheus expositions across
processes.

`GET /v1/metrics` on the fleet's shared port answers with the SUM over
the engine process and every live worker — one scrape sees the whole
fleet, exactly like the jmx-prometheus federation a reference
deployment fronts its coordinators with. Counters, gauges, and
histogram bucket/sum/count samples with identical (name, labels) sum;
HELP/TYPE headers keep their first-seen text.
"""

from __future__ import annotations

import http.client
import re
from typing import Dict, List, Optional, Tuple

# the value group must admit negative exponents (5.1e-05 is legal
# exposition a 51us histogram sum actually renders — the PR-12 test
# regex learned this the hard way) and +/-Inf/NaN; float() is the
# actual validator
_SAMPLE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)\s*$")


def merge_prometheus(texts: List[str]) -> str:
    """Sum samples with identical name+labels across expositions,
    preserving first-seen ordering and headers."""
    order: List[Tuple[str, Optional[str]]] = []   # sample keys in order
    values: Dict[Tuple[str, Optional[str]], float] = {}
    headers: Dict[str, List[str]] = {}            # family -> header lines
    family_of: Dict[str, str] = {}                # sample name -> family
    for text in texts:
        family = None
        for line in text.splitlines():
            line = line.rstrip()
            if not line:
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                    family = parts[2]
                    headers.setdefault(family, [])
                    if not any(f" {parts[1]} " in h
                               for h in headers[family]):
                        headers[family].append(line)
                continue
            m = _SAMPLE.match(line)
            if m is None:
                continue
            name, labels, raw = m.group(1), m.group(2), m.group(3)
            try:
                value = float(raw)
            except ValueError:
                continue
            key = (name, labels)
            if key not in values:
                values[key] = 0.0
                order.append(key)
            values[key] += value
            # samples of one family share its prefix (name, name_bucket,
            # name_sum, name_count); remember the family for grouping
            if family is not None and name.startswith(family):
                family_of.setdefault(name, family)
    lines: List[str] = []
    emitted_headers = set()
    for name, labels in order:
        family = family_of.get(name, name)
        if family not in emitted_headers:
            emitted_headers.add(family)
            lines.extend(headers.get(family, []))
        lines.append(f"{name}{labels or ''} "
                     f"{_render_value(values[(name, labels)])}")
    return "\n".join(lines) + "\n"


def _render_value(value: float) -> str:
    """Prometheus exposition rendering, incl. the non-finite values the
    parser admits (int(inf)/int(nan) would raise mid-scrape)."""
    import math
    if math.isnan(value):
        return "NaN"
    if math.isinf(value):
        return "+Inf" if value > 0 else "-Inf"
    return repr(value) if value != int(value) else str(int(value))


def scrape(host: str, port: int, path: str = "/v1/metrics",
           timeout: float = 2.0) -> Optional[str]:
    """One member's exposition, or None when it is unreachable (a
    mid-restart worker must not fail the fleet scrape)."""
    try:
        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            conn.request("GET", path)
            resp = conn.getresponse()
            if resp.status != 200:
                return None
            return resp.read().decode()
        finally:
            conn.close()
    except OSError:
        return None
