"""Benchmark: the BASELINE.md measurement ladder on the real chip.

Rungs (BASELINE.md): #1 q6 tiny-smoke folds into the SF1 run; #2 q1 SF1
(lineitem hash aggregation); #3 q3 SF10 (3-way join); #4 q9 SF100 (6-way
join + partial agg — exercises the spill path: >threshold builds keep only
sorted keys in HBM); #5 TPC-DS SF100 q64/q72 (wide star joins, skewed
keys). Plus the BASELINE metric hash-join probe rows/sec/chip, measured on
a dedicated SF10 lineitem-orders join. Every query runs through the full
engine (parse -> plan -> optimize -> execute). Prints ONE JSON line; the
headline metric stays q6 SF1 wall-clock with the other rungs in "extra".

SF100 rungs run in FRESH SUBPROCESSES (one per rung): the reference's
benchmark discipline separates prewarm from measurement per run
(trino-benchto-benchmarks), and an in-process run after the warm SF1/SF10
runners carries device-state residue (scan caches, kernel workspaces,
fragment intermediates) that made the rungs irreproducible in round 4.
A child prints one JSON line on stdout; the parent merges it.

vs_baseline: the reference repo publishes no numbers (BASELINE.md); the
denominators are ballpark single-node Trino wall-clocks from its
LocalQueryRunner-style benchmarks on server CPUs — q6 SF1 ~1.0s, q1 SF1
~2.5s, q3 SF10 ~10s, q9 SF100 ~100s, q64/q72 SF100 ~120s/~200s — so
vs_baseline > 1 means faster than that estimate. SF100 rungs run ONCE
(they stream 100GB-scale generated data through one chip).

Data scope (BASELINE.md north-star asks for bit-identical rows): the tpch
connector generates seekable spec-shaped hash-stream data, not dbgen
bitstreams (the airlift/dbgen seed tables are not in the reference repo
and cannot be fetched offline — see connector/tpch_gen.py), so the
comparison is same-shape wall-clock, not row-identical output.
"""

import json
import os
import subprocess
import sys
import time

# total wall budget: SF100 rungs are skipped once exceeded so the JSON
# line ALWAYS prints (a single runaway rung must not eat the whole bench)
BUDGET_S = int(os.environ.get("TRINO_TPU_BENCH_BUDGET_S", 5400))
_T0 = time.monotonic()


def _remaining() -> float:
    return BUDGET_S - (time.monotonic() - _T0)


def _ensure_backend() -> str:
    """Probe the configured JAX backend in a SUBPROCESS before this
    process imports jax; if it cannot initialize (the BENCH_r05 rc=1
    class of failure: the TPU tunnel down -> 'Unable to initialize
    backend' out of the first convert_element_type), fall back to CPU by
    setting JAX_PLATFORMS before any jax import — the bench then reports
    CPU numbers instead of dying with nothing parseable. Returns the
    platform this process will run on."""
    if os.environ.get("JAX_PLATFORMS"):
        return os.environ["JAX_PLATFORMS"].split(",")[0].strip() or "cpu"
    try:
        probe = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            capture_output=True, text=True, timeout=180)
        if probe.returncode == 0 and probe.stdout.strip():
            return probe.stdout.strip().splitlines()[-1]
    except Exception:   # noqa: BLE001 — a wedged probe counts as down
        pass
    os.environ["JAX_PLATFORMS"] = "cpu"
    return "cpu"

Q6 = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1994-01-01'
  AND l_shipdate < DATE '1994-01-01' + INTERVAL '1' YEAR
  AND l_discount BETWEEN 0.06 - 0.01 AND 0.06 + 0.01
  AND l_quantity < 24
"""

# literal-variant probes (round 8, parameterized kernel compilation): the
# measured query re-run with every hoistable numeric/date constant
# perturbed. With literal hoisting the variant reuses the warm shape's XLA
# executables, so variant_jit_misses must read 0 and variant_warm_wall_s
# tracks the warm median instead of paying a cold compile — the headline
# number for the dashboards-and-point-filters workload.
Q6_VARIANT = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= DATE '1995-01-01'
  AND l_shipdate < DATE '1995-01-01' + INTERVAL '1' YEAR
  AND l_discount BETWEEN 0.07 - 0.01 AND 0.07 + 0.01
  AND l_quantity < 25
"""

Q1 = """
SELECT l_returnflag, l_linestatus, sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
       avg(l_quantity) AS avg_qty, avg(l_extendedprice) AS avg_price,
       avg(l_discount) AS avg_disc, count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= DATE '1998-12-01' - INTERVAL '90' DAY
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

Q1_VARIANT = Q1.replace("INTERVAL '90' DAY", "INTERVAL '60' DAY")

Q3 = """
SELECT l_orderkey, sum(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING' AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey AND o_orderdate < DATE '1995-03-15'
  AND l_shipdate > DATE '1995-03-15'
GROUP BY l_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate LIMIT 10
"""

Q3_VARIANT = Q3.replace("DATE '1995-03-15'", "DATE '1995-03-08'")

# prepared-statement probes (round 10): the measured query PREPAREd with
# its hoistable constants as `?` markers, EXECUTEd twice with different
# USING values. The second EXECUTE is the statement-reuse fast path —
# plan cache hit + parameter binding into warm kernels — measured against
# re-submitting the identical query as plain SQL (which re-plans).
# (name, prepare_sql, warm USING, perturbed USING, plain-SQL resubmit)
PREPARED = {
    "tpch_q6_sf1": (
        "bench_q6",
        Q6.replace("DATE '1994-01-01'", "?")
          .replace("0.06", "?").replace("l_quantity < 24",
                                        "l_quantity < ?"),
        "DATE '1994-01-01', DATE '1994-01-01', 0.06, 0.06, 24",
        "DATE '1995-01-01', DATE '1995-01-01', 0.07, 0.07, 25",
        Q6_VARIANT),
    "tpch_q1_sf1": (
        "bench_q1",
        Q1.replace("INTERVAL '90' DAY", "?"),
        "INTERVAL '90' DAY", "INTERVAL '60' DAY", Q1_VARIANT),
    "tpch_q3_sf10": (
        "bench_q3",
        Q3.replace("DATE '1995-03-15'", "?"),
        "DATE '1995-03-15', DATE '1995-03-15'",
        "DATE '1995-03-08', DATE '1995-03-08'", Q3_VARIANT),
}

JOIN_MICRO = """
SELECT count(*) FROM lineitem, orders WHERE l_orderkey = o_orderkey
"""

Q5 = """
SELECT n_name, sum(l_extendedprice * (1 - l_discount)) AS revenue
FROM customer, orders, lineitem, supplier, nation, region
WHERE c_custkey = o_custkey AND l_orderkey = o_orderkey
  AND l_suppkey = s_suppkey AND c_nationkey = s_nationkey
  AND s_nationkey = n_nationkey AND n_regionkey = r_regionkey
  AND r_name = 'ASIA' AND o_orderdate >= DATE '1994-01-01'
  AND o_orderdate < DATE '1994-01-01' + INTERVAL '1' YEAR
GROUP BY n_name ORDER BY revenue DESC
"""

Q9 = """
SELECT nation, o_year, sum(amount) AS sum_profit FROM (
  SELECT n_name AS nation, extract(year FROM o_orderdate) AS o_year,
         l_extendedprice * (1 - l_discount) - ps_supplycost * l_quantity
           AS amount
  FROM part, supplier, lineitem, partsupp, orders, nation
  WHERE s_suppkey = l_suppkey AND ps_suppkey = l_suppkey
    AND ps_partkey = l_partkey AND p_partkey = l_partkey
    AND o_orderkey = l_orderkey AND s_nationkey = n_nationkey
    AND p_name LIKE '%green%') AS profit
GROUP BY nation, o_year ORDER BY nation, o_year DESC
"""

Q72 = """
SELECT i_item_desc, w_warehouse_name, d1.d_week_seq,
       sum(CASE WHEN p_promo_sk IS NULL THEN 1 ELSE 0 END) no_promo,
       sum(CASE WHEN p_promo_sk IS NOT NULL THEN 1 ELSE 0 END) promo,
       count(*) total_cnt
FROM catalog_sales
JOIN inventory ON (cs_item_sk = inv_item_sk)
JOIN warehouse ON (w_warehouse_sk = inv_warehouse_sk)
JOIN item ON (i_item_sk = cs_item_sk)
JOIN customer_demographics ON (cs_bill_cdemo_sk = cd_demo_sk)
JOIN household_demographics ON (cs_bill_hdemo_sk = hd_demo_sk)
JOIN date_dim d1 ON (cs_sold_date_sk = d1.d_date_sk)
JOIN date_dim d2 ON (inv_date_sk = d2.d_date_sk)
JOIN date_dim d3 ON (cs_ship_date_sk = d3.d_date_sk)
LEFT JOIN promotion ON (cs_promo_sk = p_promo_sk)
LEFT JOIN catalog_returns ON (cr_item_sk = cs_item_sk
                              AND cr_order_number = cs_order_number)
WHERE d1.d_week_seq = d2.d_week_seq
  AND inv_quantity_on_hand < cs_quantity
  AND d3.d_date > d1.d_date + INTERVAL '5' DAY
  AND hd_buy_potential = '>10000'
  AND d1.d_year = 1999
  AND cd_marital_status = 'D'
GROUP BY i_item_desc, w_warehouse_name, d1.d_week_seq
ORDER BY total_cnt DESC, i_item_desc, w_warehouse_name, d1.d_week_seq
LIMIT 100
"""

Q64 = """
WITH cs_ui AS (
  SELECT cs_item_sk,
         sum(cs_ext_list_price) AS sale,
         sum(cr_refunded_cash + cr_return_amount) AS refund
  FROM catalog_sales, catalog_returns
  WHERE cs_item_sk = cr_item_sk AND cs_order_number = cr_order_number
  GROUP BY cs_item_sk
  HAVING sum(cs_ext_list_price) > 2 * sum(cr_refunded_cash
                                          + cr_return_amount))
SELECT i_product_name, s_store_name, s_zip, d1.d_year,
       count(*) AS cnt,
       sum(ss_wholesale_cost) AS s1, sum(ss_list_price) AS s2,
       sum(ss_coupon_amt) AS s3
FROM store_sales, store_returns, cs_ui, date_dim d1,
     customer, customer_demographics cd1, household_demographics hd1,
     customer_address ad1, income_band ib1, item, store
WHERE ss_store_sk = s_store_sk
  AND ss_sold_date_sk = d1.d_date_sk
  AND ss_customer_sk = c_customer_sk
  AND ss_cdemo_sk = cd1.cd_demo_sk
  AND ss_hdemo_sk = hd1.hd_demo_sk
  AND ss_addr_sk = ad1.ca_address_sk
  AND ss_item_sk = i_item_sk
  AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND ss_item_sk = cs_ui.cs_item_sk
  AND hd1.hd_income_band_sk = ib1.ib_income_band_sk
  AND i_color IN ('maroon', 'burnished', 'dim', 'steel', 'navajo',
                  'chocolate')
  AND i_current_price BETWEEN 35 AND 45
GROUP BY i_product_name, s_store_name, s_zip, d1.d_year
ORDER BY i_product_name, s_store_name, cnt LIMIT 100
"""

# ballpark single-node Java-engine estimates (no published numbers exist)
BASE_Q6_SF1_S = 1.0
BASE_Q1_SF1_S = 2.5
BASE_Q3_SF10_S = 10.0
BASE_Q9_SF100_S = 100.0
BASE_Q64_SF100_S = 120.0
BASE_Q72_SF100_S = 200.0
BASE_JOIN_ROWS_PER_S = 50e6     # ballpark single-node probe throughput

# per-rung literal variants; None = the query has no hoistable constants
# (q9's only constant is a LIKE pattern, which stays static by design)
Q64_VARIANT = Q64.replace("BETWEEN 35 AND 45", "BETWEEN 36 AND 46")
Q72_VARIANT = Q72.replace("d1.d_year = 1999", "d1.d_year = 2000") \
                 .replace("INTERVAL '5' DAY", "INTERVAL '6' DAY")

SF100_RUNGS = {
    "tpch_q9_sf100": (BASE_Q9_SF100_S, "tpch", Q9, None),
    "tpcds_q64_sf100": (BASE_Q64_SF100_S, "tpcds", Q64, Q64_VARIANT),
    "tpcds_q72_sf100": (BASE_Q72_SF100_S, "tpcds", Q72, Q72_VARIANT),
}


def _sf100_runner(catalog: str):
    import trino_tpu
    trino_tpu.enable_persistent_cache()
    from trino_tpu.connector import tpch as tpch_conn
    from trino_tpu.exec import LocalQueryRunner
    # shrink the scan cache so join state owns the HBM, and stream probes
    # in smaller buffers (wide-buffer probe sorts exhaust per-op scratch —
    # round-4 measurement)
    tpch_conn.set_device_cache_budget(1 << 30)
    runner = LocalQueryRunner.tpch("sf100")
    if catalog == "tpcds":
        runner.execute("USE tpcds.sf100")
    runner.execute("SET SESSION probe_coalesce_rows = 4194304")
    return runner


def run_rung(tag: str) -> None:
    """Child mode: execute ONE SF100 rung in this (fresh) process and
    print a single JSON line {"wall_s": ...} or {"error": ...}."""
    base, catalog, sql, variant = SF100_RUNGS[tag]
    _ensure_backend()
    try:
        runner = _sf100_runner(catalog)
        t0 = time.perf_counter()
        rows = runner.execute(sql).rows
        wall = time.perf_counter() - t0
        if tag == "tpch_q9_sf100":
            assert rows, "q9 returned no rows"
        breakdown = _stats_breakdown(runner.last_query_stats)
        if variant is not None and _remaining() > 120:
            breakdown.update(_literal_variant(runner, variant))
        print(json.dumps({"wall_s": round(wall, 2),
                          "retries": runner.stats["retries"],
                          "faults_injected":
                              runner.stats["faults_injected"],
                          "breakdown": breakdown}),
              flush=True)
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the rung must report,
        # not die: even a SystemExit from backend init becomes a parsed
        # error line (the parent merges it as {tag}_error)
        print(json.dumps(
            {"error": f"{type(e).__name__}: {str(e)[:160]}"}), flush=True)


def _run_rung_subprocess(extra: dict, tag: str, base: float) -> None:
    """Launch `python bench.py --rung TAG` and merge its JSON line."""
    timeout = _remaining()
    if timeout < 60:
        extra[f"{tag}_error"] = \
            f"skipped: bench wall budget ({BUDGET_S}s) exhausted"
        return
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--rung", tag],
            capture_output=True, text=True, timeout=timeout,
            cwd=os.path.dirname(os.path.abspath(__file__)))
    except subprocess.TimeoutExpired:
        extra[f"{tag}_error"] = \
            f"timeout: exceeded bench wall budget ({BUDGET_S}s)"
        return
    # one malformed child line must cost ONE rung, not the whole bench
    try:
        line = None
        for ln in reversed(proc.stdout.strip().splitlines()):
            ln = ln.strip()
            if ln.startswith("{"):
                line = ln
                break
        if line is None:
            tail = (proc.stderr or proc.stdout or "").strip()[-200:]
            extra[f"{tag}_error"] = \
                f"rung subprocess rc={proc.returncode}: {tail}"
            return
        got = json.loads(line)
        if "error" in got:
            extra[f"{tag}_error"] = got["error"]
        else:
            wall = float(got["wall_s"])
            extra[f"{tag}_wall_s"] = wall
            extra[f"{tag}_vs_baseline"] = round(base / wall, 3)
            if got.get("retries"):
                extra[f"{tag}_retries"] = int(got["retries"])
            if got.get("faults_injected"):
                extra[f"{tag}_faults_injected"] = int(got["faults_injected"])
            if got.get("breakdown"):
                extra[f"{tag}_breakdown"] = got["breakdown"]
    except Exception as e:  # noqa: BLE001
        extra[f"{tag}_error"] = f"rung result parse: {type(e).__name__}: {e}"


def _time_query(runner, sql, iters=3, breakdown=None, variant=None,
                prepared=None):
    t0 = time.perf_counter()
    rows = runner.execute(sql).rows  # warm-up (compile) run, untimed
    cold = time.perf_counter() - t0
    assert rows, "query returned no rows"
    cold_stats = dict(runner.last_query_stats)
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        runner.execute(sql)
        times.append(time.perf_counter() - t0)
    warm = sorted(times)[len(times) // 2]  # median
    if breakdown is not None:
        breakdown.update(_breakdown(runner, cold, warm, cold_stats))
        if variant is not None:
            breakdown.update(_literal_variant(runner, variant))
        if prepared is not None:
            breakdown.update(_prepared_variant(runner, prepared))
    return warm


def _prepared_variant(runner, spec):
    """The statement-reuse proof: EXECUTE with perturbed USING values
    (cached plan + warm kernels — what the second-and-later dashboard
    query pays) vs re-submitting the identical statement as plain SQL
    (full parse->plan->optimize per run). prepared_plan_cache_hits >= 1
    and prepared_jit_misses == 0 mean the fast path engaged."""
    name, prepare_sql, warm_using, perturbed_using, resubmit_sql = spec
    try:
        runner.execute(f"PREPARE {name} FROM {prepare_sql}")
        runner.execute(f"EXECUTE {name} USING {warm_using}")
        t0 = time.perf_counter()
        runner.execute(f"EXECUTE {name} USING {perturbed_using}")
        execute_wall = time.perf_counter() - t0
        stats = runner.last_query_stats
        # resubmit baseline: plan cache OFF, else the earlier variant run
        # already cached this exact statement's plan and the "full
        # re-plan" baseline would itself be a cache hit
        runner.session.properties["plan_cache_enabled"] = False
        try:
            t0 = time.perf_counter()
            runner.execute(resubmit_sql)
            resubmit_wall = time.perf_counter() - t0
        finally:
            runner.session.properties.pop("plan_cache_enabled", None)
        return {
            "prepared_execute_wall_s": round(execute_wall, 4),
            "prepared_resubmit_wall_s": round(resubmit_wall, 4),
            "prepared_plan_cache_hits":
                int(stats.get("plan_cache_hits", 0)),
            "prepared_jit_misses": int(stats.get("jit_misses", 0)),
            "prepared_jit_param_hits":
                int(stats.get("jit_param_hits", 0)),
        }
    except Exception as e:  # noqa: BLE001 — a probe failure costs a key,
        return {"prepared_error":            # not the rung
                f"{type(e).__name__}: {str(e)[:120]}"}


def _literal_variant(runner, variant_sql):
    """The parameterized-compilation proof: run the measured query with
    every hoistable constant perturbed. variant_jit_misses == 0 means the
    variant dispatched only warm executables (literal hoisting working);
    variant_warm_wall_s is what a dashboard's next parameter choice
    actually pays."""
    t0 = time.perf_counter()
    runner.execute(variant_sql)
    wall = time.perf_counter() - t0
    stats = runner.last_query_stats
    return {
        "variant_warm_wall_s": round(wall, 4),
        "variant_jit_misses": int(stats.get("jit_misses", 0)),
        "variant_jit_param_hits": int(stats.get("jit_param_hits", 0)),
    }


def _stats_breakdown(stats):
    """The collector-snapshot keys every breakdown object shares."""
    return {
        "planning_s": round(stats.get("planning_s", 0.0), 4),
        "execution_s": round(stats.get("execution_s", 0.0), 4),
        "jit_misses": int(stats.get("jit_misses", 0)),
        "jit_param_hits": int(stats.get("jit_param_hits", 0)),
        "plan_cache_hits": int(stats.get("plan_cache_hits", 0)),
        "output_rows": int(stats.get("output_rows", 0)),
        "output_bytes": int(stats.get("output_bytes", 0)),
        "spilled_bytes": int(stats.get("spilled_bytes", 0)),
        # preemptible sliced execution (round 11): slices the measured
        # run executed, bytes checkpointed for resume, and the measured
        # cancel->unwind wall (0 on an unpreempted run — nonzero here
        # means something canceled/killed the rung, worth seeing)
        "slices_executed": int(stats.get("slices_executed", 0)),
        "checkpoint_bytes": int(stats.get("checkpoint_bytes", 0)),
        "preempt_latency_ms": float(
            stats.get("preempt_latency_ms", 0) or 0),
        # compile-vs-execute accounting (round 13): measured XLA compile
        # wall this run paid (0.0 warm) — cold_wall - warm_wall stops
        # being the only compile signal
        "compile_time_ms": float(stats.get("compile_time_ms", 0) or 0),
        "jit_compiles": int(stats.get("jit_compiles", 0)),
    }


def _breakdown(runner, cold, warm, cold_stats):
    """Compile-vs-execute wall split from the query stats collector
    (obs/stats.py): the cold run pays jit builds + XLA compiles, the warm
    median is steady state, and the collector's phase walls split the
    warm run into planning vs device execution."""
    out = _stats_breakdown(runner.last_query_stats)
    out.update({
        "cold_wall_s": round(cold, 4),
        "warm_wall_s": round(warm, 4),
        "compile_overhead_s": round(max(cold - warm, 0.0), 4),
        "cold_jit_misses": int(cold_stats.get("jit_misses", 0)),
    })
    return out


# the multi-chip rung set: grouped agg (q1), repartitioned group-by +
# joins (q3), 6-way join (q5), wide join + partial agg (q9)
MESH_QUERIES = {"tpch_q1": Q1, "tpch_q3": Q3, "tpch_q5": Q5,
                "tpch_q9": Q9}


def run_mesh(out_path=None) -> None:
    """`bench.py --mesh [OUT.json]`: the multi-chip sharded-execution
    report. Runs q1/q3/q5/q9 through DistributedQueryRunner over the
    device mesh — on TPU the real ICI mesh, elsewhere a forced 8-device
    CPU mesh (re-execs with XLA_FLAGS when needed) — verifies row parity
    against the single-device engine, and emits ONE MULTICHIP json line:
    device_count, per-query walls, fused vs staged exchange counts
    (fused-only == pages never staged through the host), per-chip peak
    bytes, and the node-pool budget + source. Writes the same payload to
    OUT.json when given."""
    platform = _ensure_backend()
    flags = os.environ.get("XLA_FLAGS", "")
    if platform == "cpu" and \
            "--xla_force_host_platform_device_count" not in flags:
        env = dict(os.environ)
        env["XLA_FLAGS"] = \
            (flags + " --xla_force_host_platform_device_count=8").strip()
        argv = [sys.executable, os.path.abspath(__file__), "--mesh"]
        if out_path:
            argv.append(out_path)
        sys.exit(subprocess.run(argv, env=env).returncode)

    payload = {"metric": "multichip_mesh", "device_count": 0,
               "queries": {}, "error": None}
    try:
        import jax

        import trino_tpu
        trino_tpu.enable_persistent_cache()
        from trino_tpu.exec import LocalQueryRunner
        from trino_tpu.exec.distributed import DistributedQueryRunner
        from trino_tpu.exec.memory import NODE_POOL

        schema = os.environ.get("TRINO_TPU_MESH_SCHEMA", "tiny")
        dist = DistributedQueryRunner.tpch(schema)
        local = LocalQueryRunner.tpch(schema)
        n = dist.mesh.n
        payload["device_count"] = n
        payload["backend"] = jax.devices()[0].platform
        if NODE_POOL.limit is None:
            # no measured HBM (CPU dev mesh): give the report window an
            # explicit per-chip budget so peak-vs-budget is a real check,
            # with the same per-device enforcement the TPU path uses
            NODE_POOL.set_limit(int(os.environ.get(
                "TRINO_TPU_MESH_POOL_BYTES", 2 << 30)))
            NODE_POOL.budget_source = "dev-mesh"
            NODE_POOL.enforce_per_device = True
        payload["pool_limit_bytes"] = NODE_POOL.limit or 0
        payload["pool_budget_source"] = NODE_POOL.budget_source
        total_staged = 0
        for tag, sql in MESH_QUERIES.items():
            t0 = time.perf_counter()
            rows = dist.execute(sql).rows
            wall = time.perf_counter() - t0
            st = dist.last_query_stats
            expect = local.execute(sql).rows
            total_staged += int(st.get("exchanges_staged", 0))
            payload["queries"][tag] = {
                "wall_s": round(wall, 4),
                "rows": len(rows),
                "oracle_ok": sorted(map(repr, rows))
                == sorted(map(repr, expect)),
                "exchanges_fused": int(st.get("exchanges_fused", 0)),
                "exchanges_staged": int(st.get("exchanges_staged", 0)),
                "exchange_rows": int(st.get("exchange_rows", 0)),
                "exchange_bytes": int(st.get("exchange_bytes", 0)),
            }
        payload["zero_host_page_exchanges"] = total_staged == 0
        peaks = [NODE_POOL.device_peak.get(i, 0) for i in range(n)]
        payload["per_chip_peak_bytes"] = peaks
        limit = NODE_POOL.limit
        payload["per_chip_peak_within_budget"] = \
            None if not limit else all(p <= limit for p in peaks)
        # real per-device allocator peaks when the backend reports them
        # (TPU HBM); absent on the CPU mesh
        try:
            dev_stats = [d.memory_stats() or {} for d in jax.devices()]
            if any("peak_bytes_in_use" in s for s in dev_stats):
                payload["per_chip_allocator_peak_bytes"] = [
                    int(s.get("peak_bytes_in_use", 0)) for s in dev_stats]
        except Exception:   # noqa: BLE001
            pass
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the line must print
        payload["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    if payload.get("error") is None:
        payload.pop("error")
    line = json.dumps(payload)
    print(line, flush=True)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")


def run_lake(out_path=None) -> None:
    """`bench.py --lake [OUT.json]`: the data-plane report. CTAS a
    TPC-H table into a PARTITIONED lake table (round-trip verified
    against the generator connector), then measure the scan ladder the
    lake round exists for:

      cold    first scan — file reads + host->device staging
      warm    repeated scan — scan-cache pages (device), staging = 0
      cached  table-cache scan — HBM-resident columns, staging = 0

    plus a selective pruned scan (files_pruned/row_groups_pruned > 0
    proving partition + zone-map skips) and the INSERT-replay
    exactly-once counter. Always emits its final JSON line."""
    platform = _ensure_backend()
    payload = {"metric": "lake_data_plane", "backend": platform}
    try:
        import trino_tpu
        trino_tpu.enable_persistent_cache()
        from trino_tpu.connector.lake import lake_stats
        from trino_tpu.exec import LocalQueryRunner

        schema = os.environ.get("TRINO_TPU_LAKE_SCHEMA", "tiny")
        runner = LocalQueryRunner.tpch(schema)
        payload["schema"] = schema
        payload["format"] = runner.catalogs.get(
            "lake")._metadata.default_format

        t0 = time.perf_counter()
        runner.execute(
            "CREATE TABLE lake.default.orders_part "
            "WITH (partitioned_by = 'o_orderstatus', "
            "row_group_rows = 65536) AS SELECT * FROM orders")
        payload["ctas_wall_s"] = round(time.perf_counter() - t0, 4)
        src_rows = runner.execute(
            "SELECT count(*) FROM orders").only_value()
        lake_rows = runner.execute(
            "SELECT count(*) FROM lake.default.orders_part").only_value()
        payload["rows"] = int(lake_rows)
        payload["roundtrip_ok"] = bool(lake_rows == src_rows)

        scan = ("SELECT o_orderstatus, count(*), sum(o_totalprice) "
                "FROM lake.default.orders_part GROUP BY o_orderstatus")
        runner.session.set("scan_cache_enabled", True)
        runner.session.set("table_cache_enabled", True)
        runner.session.set("table_cache_min_scans", 2)

        def timed(tag):
            t0 = time.perf_counter()
            rows = runner.execute(scan).rows
            wall = time.perf_counter() - t0
            st = runner.last_query_stats
            payload[f"{tag}_wall_s"] = round(wall, 4)
            payload[f"{tag}_staging_bytes"] = int(
                st.get("scan_staging_bytes", 0))
            payload[f"{tag}_table_cache_hits"] = int(
                st.get("table_cache_hits", 0))
            payload[f"{tag}_scan_cache_hits"] = int(
                st.get("scan_cache_hits", 0))
            return rows

        cold = timed("cold")          # connector read + staging
        warm = timed("warm")          # scan-cache pages + promotion
        cached = timed("cached")      # HBM-resident columns
        payload["scan_parity_ok"] = bool(
            sorted(map(repr, cold)) == sorted(map(repr, warm))
            == sorted(map(repr, cached)))
        payload["cached_zero_staging"] = \
            payload["cached_staging_bytes"] == 0 and \
            payload["cached_table_cache_hits"] > 0

        pruned = runner.execute(
            "SELECT count(*) FROM lake.default.orders_part "
            "WHERE o_orderstatus = 'F' AND o_orderkey < 1000")
        st = runner.last_query_stats
        payload["pruned_scan_rows"] = int(pruned.only_value())
        payload["files_pruned"] = int(st.get("files_pruned", 0))
        payload["row_groups_pruned"] = int(st.get("row_groups_pruned", 0))

        replay_before = lake_stats()["replayed_commits"]
        runner.session.set("fault_injection_rate", 0.5)
        runner.session.set("fault_injection_seed", 1)
        runner.session.set("fault_injection_sites", "fragment")
        runner.session.set("retry_policy", "QUERY")
        runner.session.set("retry_attempts", 5)
        runner.execute("INSERT INTO lake.default.orders_part "
                       "SELECT * FROM orders WHERE o_orderkey < 100")
        insert_retries = int(runner.last_query_stats.get("retries", 0))
        runner.session.set("fault_injection_rate", 0.0)
        extra = runner.execute("SELECT count(*) FROM orders "
                               "WHERE o_orderkey < 100").only_value()
        after = runner.execute(
            "SELECT count(*) FROM lake.default.orders_part").only_value()
        payload["insert_retries"] = insert_retries
        payload["insert_replays"] = \
            lake_stats()["replayed_commits"] - replay_before
        payload["insert_exactly_once"] = bool(
            after == src_rows + extra)
        payload["lake_counters"] = lake_stats()
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the line must print
        payload["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    line = json.dumps(payload)
    print(line, flush=True)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")


def run_mv(out_path=None) -> None:
    """`bench.py --mv [OUT.json]`: the update-on-write cache-tier
    report. Two instruments over one lake table and one incremental
    materialized view:

      refresh ratio   after a 1% append, DELTA refresh (merge only the
                      manifest diff into stored partial states) vs a
                      forced FULL recompute — acceptance: delta wall
                      <= 10% of full wall
      serving trickle a closed loop of 8 MV-rewritable aggregate
                      queries under a 1-write-per-cycle INSERT trickle:
                      update-on-write (refresh republishes the cached
                      results) vs the invalidate-on-write baseline
                      (every write floods the result cache, every
                      query recomputes) — acceptance: >= 5x QPS with
                      ZERO stale answers (every served row set equals
                      the post-write oracle)

    Always emits its final JSON line."""
    platform = _ensure_backend()
    payload = {"metric": "mv_update_on_write", "backend": platform}
    try:
        import trino_tpu
        trino_tpu.enable_persistent_cache()
        from trino_tpu.exec import LocalQueryRunner

        runner = LocalQueryRunner.tpch("tiny")
        # ~240k rows: doubling INSERTs over a 15k-row CTAS seed
        runner.execute(
            "CREATE TABLE lake.default.big AS SELECT o_orderstatus AS k,"
            " o_totalprice AS v, o_orderkey AS n FROM orders")
        for _ in range(4):
            runner.execute("INSERT INTO lake.default.big "
                           "SELECT k, v, n FROM lake.default.big")
        base_rows = runner.execute(
            "SELECT count(*) FROM lake.default.big").only_value()
        payload["base_rows"] = int(base_rows)
        delta_rows = max(1, base_rows // 100)
        payload["delta_rows"] = int(delta_rows)

        def delta_insert_sql(rows):
            return ("INSERT INTO lake.default.big "
                    "SELECT k, v, n FROM lake.default.big "
                    f"LIMIT {rows}")

        delta_insert = delta_insert_sql(delta_rows)

        runner.execute(
            "CREATE MATERIALIZED VIEW lake.default.mv_big AS "
            "SELECT k, sum(v) AS s, count(*) AS c, min(v) AS lo, "
            "max(v) AS hi, avg(v) AS a "
            "FROM lake.default.big GROUP BY k")
        refresh = "REFRESH MATERIALIZED VIEW lake.default.mv_big"
        stats = runner._mv.stats[("lake", "default", "mv_big")]

        def timed_refresh(mode, rows=delta_rows):
            runner.execute(delta_insert_sql(rows))
            runner.session.set("mv_refresh_mode", mode)
            t0 = time.perf_counter()
            runner.execute(refresh)
            return time.perf_counter() - t0

        timed_refresh("AUTO")           # warm the delta-merge kernels
        delta_wall = timed_refresh("AUTO")
        delta10_wall = timed_refresh("AUTO", rows=base_rows // 10)
        timed_refresh("FULL")           # warm the full-recompute path
        full_wall = timed_refresh("FULL")
        assert stats["refreshes_delta"] >= 3, stats
        payload["delta_refresh_wall_s"] = round(delta_wall, 4)
        payload["delta10_refresh_wall_s"] = round(delta10_wall, 4)
        payload["full_refresh_wall_s"] = round(full_wall, 4)
        payload["refresh_ratio"] = round(delta_wall / full_wall, 4)
        payload["refresh_ratio_10pct"] = round(
            delta10_wall / full_wall, 4)
        payload["refresh_ratio_ok"] = bool(
            delta_wall <= 0.10 * full_wall)

        # ---- serving under a write trickle --------------------------
        queries = [
            "SELECT k, sum(v) AS s FROM lake.default.big GROUP BY k "
            "ORDER BY k",
            "SELECT k, count(*) AS c FROM lake.default.big GROUP BY k "
            "ORDER BY k",
            "SELECT k, min(v) AS lo FROM lake.default.big GROUP BY k "
            "ORDER BY k",
            "SELECT k, max(v) AS hi FROM lake.default.big GROUP BY k "
            "ORDER BY k",
            "SELECT k, avg(v) AS a FROM lake.default.big GROUP BY k "
            "ORDER BY k",
            "SELECT k, sum(v) AS s, count(*) AS c FROM lake.default.big "
            "GROUP BY k ORDER BY k",
            "SELECT k, min(v) AS lo, max(v) AS hi FROM lake.default.big "
            "GROUP BY k ORDER BY k",
            "SELECT k, sum(v) AS s, avg(v) AS a FROM lake.default.big "
            "GROUP BY k ORDER BY s DESC",
        ]

        def oracle_answers():
            runner.session.set("mv_rewrite_enabled", False)
            runner.session.set("result_cache_enabled", False)
            out = [runner.execute(q).rows for q in queries]
            runner.session.set("result_cache_enabled", True)
            return out

        def trickle(update_on_write, cycles=3, window_s=1.0):
            runner.session.set("result_cache_enabled", True)
            runner.session.set("mv_rewrite_enabled", update_on_write)
            for q in queries:            # seed the cache tier
                runner.execute(q)
            served = 0
            stale = 0
            wall = 0.0
            for _ in range(cycles):
                t0 = time.perf_counter()
                runner.execute(delta_insert)
                if update_on_write:
                    runner.session.set("mv_refresh_mode", "AUTO")
                    runner.session.set("mv_rewrite_enabled", True)
                    runner.execute(refresh)
                answers = {}
                i = 0
                while time.perf_counter() - t0 < window_s:
                    q = queries[i % len(queries)]
                    answers.setdefault(q, []).append(
                        runner.execute(q).rows)
                    served += 1
                    i += 1
                wall += time.perf_counter() - t0
                expected = oracle_answers()
                runner.session.set(
                    "mv_rewrite_enabled", update_on_write)
                for q, exp in zip(queries, expected):
                    for got in answers.get(q, ()):
                        if got != exp:
                            stale += 1
            return served / wall, stale

        baseline_qps, baseline_stale = trickle(update_on_write=False)
        uow_qps, uow_stale = trickle(update_on_write=True)
        payload["baseline_qps"] = round(baseline_qps, 2)
        payload["update_on_write_qps"] = round(uow_qps, 2)
        payload["qps_speedup"] = round(uow_qps / baseline_qps, 2)
        payload["qps_speedup_ok"] = bool(uow_qps >= 5 * baseline_qps)
        payload["stale_answers"] = int(uow_stale)
        payload["baseline_stale_answers"] = int(baseline_stale)
        payload["zero_stale"] = bool(uow_stale == 0)
        payload["mv_stats"] = dict(stats)
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the line must print
        payload["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    line = json.dumps(payload)
    print(line, flush=True)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")


def run_scrub(out_path=None) -> None:
    """`bench.py --scrub [OUT.json]`: the data-integrity report.

      verify overhead   warm lake scans at lake_verify_checksums off /
                        row_group (default) / file — the acceptance bar
                        is row_group overhead <= 5% over off
      fsck wall         deep pointer->manifest->files->row-groups walk
                        over a multi-hundred-file lake table
      detection latency flip one byte on disk, time to the classified
                        LAKE_DATA_CORRUPTION error (never wrong rows)

    Always emits its final JSON line."""
    platform = _ensure_backend()
    payload = {"metric": "lake_scrub", "backend": platform}
    try:
        import glob

        import trino_tpu
        trino_tpu.enable_persistent_cache()
        from trino_tpu.connector.lake import clear_quarantine, lake_stats
        from trino_tpu.errors import LakeDataCorruptionError
        from trino_tpu.exec import LocalQueryRunner

        schema = os.environ.get("TRINO_TPU_LAKE_SCHEMA", "tiny")
        reps = int(os.environ.get("TRINO_TPU_SCRUB_REPS", "15"))
        n_files = int(os.environ.get("TRINO_TPU_SCRUB_FILES", "240"))
        runner = LocalQueryRunner.tpch(schema)
        payload["schema"] = schema
        lake_dir = runner.catalogs.get("lake")._metadata.base_dir

        runner.execute(
            "CREATE TABLE lake.default.li WITH (row_group_rows = 8192) "
            "AS SELECT * FROM lineitem")
        scan = ("SELECT sum(l_extendedprice), sum(l_quantity), "
                "count(*) FROM lake.default.li WHERE l_quantity > 10")

        # --- verify overhead: same warm scan, three verification
        # levels. "first" clears the verified-content ledger every rep
        # (every digest re-hashed); plain warm reps pay the ledger's
        # steady state — the acceptance number at the row_group default.
        from trino_tpu.connector.lake import clear_verified

        def level_wall(level, first=False):
            runner.session.set("lake_verify_checksums", level)
            runner.execute(scan)            # warm (jit + page cache)
            walls = []
            for _ in range(reps):
                if first:
                    clear_verified()
                t0 = time.perf_counter()
                runner.execute(scan)
                walls.append(time.perf_counter() - t0)
            # best-of-N: the noise floor is the comparable number —
            # scheduler jitter at ms scale would otherwise swamp a
            # zero-cost ledger hit
            return min(walls)

        off = level_wall("off")
        row_group = level_wall("row_group")
        file_level = level_wall("file")
        first_rg = level_wall("row_group", first=True)
        payload["scan_wall_off_s"] = round(off, 5)
        payload["scan_wall_row_group_s"] = round(row_group, 5)
        payload["scan_wall_file_s"] = round(file_level, 5)
        payload["scan_wall_first_verify_s"] = round(first_rg, 5)
        payload["verify_overhead_row_group"] = round(
            (row_group - off) / off, 4)
        payload["verify_overhead_file"] = round(
            (file_level - off) / off, 4)
        payload["verify_overhead_first_scan"] = round(
            (first_rg - off) / off, 4)
        payload["verify_overhead_ok"] = bool(
            payload["verify_overhead_row_group"] <= 0.05)
        runner.session.set("lake_verify_checksums", "row_group")

        # --- fsck wall over a multi-hundred-file table (one file per
        # commit: the worst-case manifest/file fan-out, not row volume)
        runner.execute("CREATE TABLE lake.default.many (x bigint, "
                       "y double)")
        t0 = time.perf_counter()
        for i in range(n_files):
            runner.execute(f"INSERT INTO lake.default.many VALUES "
                           f"({i}, {i}.5), ({i + 1}, {i}.25)")
        payload["ingest_wall_s"] = round(time.perf_counter() - t0, 4)
        payload["lake_files"] = sum(
            len(glob.glob(os.path.join(t, "data", "*")))
            for t in glob.glob(os.path.join(lake_dir, "default", "*")))
        t0 = time.perf_counter()
        report = runner.lake_fsck()
        payload["fsck_wall_s"] = round(time.perf_counter() - t0, 4)
        payload["fsck_ok"] = bool(report["ok"])
        payload["fsck_tables"] = int(report["tables_checked"])

        # --- detection latency: one flipped byte on disk -> classified
        runner.execute("CREATE TABLE lake.default.det AS "
                       "SELECT * FROM nation")
        runner.execute("SELECT count(*) FROM lake.default.det")
        path = sorted(glob.glob(os.path.join(
            lake_dir, "default", "det", "data", "*")))[0]
        with open(path, "r+b") as fh:     # scatter flips: whatever the
            data = bytearray(fh.read())   # scan decodes is affected
            for pos in range(16, len(data), 128):
                data[pos] ^= 0xFF
            fh.seek(0)
            fh.write(data)
        clear_quarantine()
        t0 = time.perf_counter()
        try:
            runner.execute("SELECT count(n_nationkey) "
                           "FROM lake.default.det")
            payload["detection_classified"] = False   # silent wrong rows
        except LakeDataCorruptionError:
            payload["detection_classified"] = True
        payload["detection_latency_s"] = round(
            time.perf_counter() - t0, 5)
        payload["lake_counters"] = lake_stats()
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the line must print
        payload["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    line = json.dumps(payload)
    print(line, flush=True)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")


def run_qps(out_path=None, workers=None) -> None:
    """`bench.py --qps [OUT.json] [--workers N1,N2,...]`: the serving
    tier's QPS instrument. Without `--workers`, the PR-7 single-process
    closed loop (trino_tpu/serve/bench_serve.py). With `--workers`, the
    FLEET scaling curve (trino_tpu/fleet/bench_fleet.py): one rung per
    worker count (0 = single-process baseline), subprocess load
    generators, a cache-MISS pass proving the dispatch path doesn't
    regress behind the proxy hop, and a mid-bench rolling restart
    proving zero dropped queries. Like the main bench, the final JSON
    line ALWAYS prints: a failure lands in an `error` field instead of
    a bare nonzero exit with nothing parseable."""
    platform = _ensure_backend()
    if workers is None and os.environ.get("TRINO_TPU_QPS_WORKERS"):
        raw_workers = os.environ["TRINO_TPU_QPS_WORKERS"]
        try:
            workers = [int(x) for x in raw_workers.split(",")]
        except ValueError:
            # the contract: the final JSON line ALWAYS prints
            line = json.dumps({
                "metric": "fleet_qps", "backend": platform,
                "error": f"bad TRINO_TPU_QPS_WORKERS value "
                         f"{raw_workers!r} (want e.g. '0,1,2,4,8')"})
            print(line, flush=True)
            if out_path:
                with open(out_path, "w") as f:
                    f.write(line + "\n")
            return
    # one env read, mode-specific defaults: the fleet curve runs 5
    # rungs + a miss pass + the restart pass, so its per-rung window is
    # shorter than the single-process loop's
    clients = int(os.environ.get("TRINO_TPU_QPS_CLIENTS", 8))
    env_duration = os.environ.get("TRINO_TPU_QPS_DURATION_S")
    if workers is not None:
        from trino_tpu.fleet.bench_fleet import run_fleet_qps
        metric = "fleet_qps"
        bench = run_fleet_qps
        kwargs = {"worker_counts": workers, "client_procs": clients,
                  "duration_s": float(env_duration) if env_duration
                  else 6.0}
    else:
        from trino_tpu.serve.bench_serve import run_qps_bench
        metric = "serve_qps"
        bench = run_qps_bench
        kwargs = {"clients": clients,
                  "duration_s": float(env_duration) if env_duration
                  else 8.0}
    payload = {"metric": metric, "backend": platform}
    try:
        payload.update(bench(**kwargs))
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the line must print
        payload["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    line = json.dumps(payload)
    print(line, flush=True)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")


def run_chaos_fleet(out_path=None) -> None:
    """`bench.py --chaos-fleet [OUT.json]`: the process-level fault
    matrix (trino_tpu/fleet/bench_fleet.py run_chaos_fleet). One phase
    per process class against a live fleet: kill -9 the ENGINE under
    load (shared-tier hits must stay fully available, misses classify
    as retryable ENGINE_UNAVAILABLE, the supervisor restores an active
    generation), kill -9 a WORKER (siblings hold the shared port, the
    headcount respawns), then a PLANNED `engine_restart()` under a
    closed loop of cache misses (the SCM_RIGHTS listener handoff must
    land errors == 0). The final JSON line ALWAYS prints; `chaos_clean`
    is the single acceptance bit."""
    platform = _ensure_backend()
    payload = {"metric": "chaos_fleet", "backend": platform}
    try:
        from trino_tpu.fleet.bench_fleet import run_chaos_fleet as _run
        payload.update(_run())
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the line must print
        payload["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    line = json.dumps(payload)
    print(line, flush=True)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")


def run_preempt(out_path=None) -> None:
    """`bench.py --preempt [OUT.json]`: the DELETE->executor-freed
    smoke. Starts a long SF1 lineitem scan on a worker thread, cancels
    it mid-flight through the SAME shared cancel event the server's
    DELETE handler sets, and reports the measured cancel-to-freed wall
    plus the slice counters of the preempted run. The acceptance shape:
    `cancel_to_free_ms` is bounded by ~one slice, orders of magnitude
    below `scan_wall_s_estimate` (what the scan had left). Like every
    bench mode, the final JSON line ALWAYS prints — failures land in an
    `error` field."""
    import threading
    platform = _ensure_backend()
    payload = {"metric": "preempt_latency", "backend": platform}
    try:
        import trino_tpu
        trino_tpu.enable_persistent_cache()
        from trino_tpu.errors import QueryCanceledError
        from trino_tpu.exec import LocalQueryRunner
        from trino_tpu.exec.memory import NODE_POOL

        schema = os.environ.get("TRINO_TPU_PREEMPT_SCHEMA", "sf1")
        runner = LocalQueryRunner.tpch(schema)
        long_scan = ("SELECT count(*), sum(l_extendedprice * "
                     "(1 - l_discount)) FROM lineitem "
                     "WHERE l_quantity >= 0")
        # warm run: compiles + stages the table, and tells us what the
        # full scan costs (the denominator of the latency claim)
        t0 = time.perf_counter()
        runner.execute(long_scan)
        full_wall = time.perf_counter() - t0
        payload["scan_wall_s_estimate"] = round(full_wall, 3)
        payload["slice_target_rows"] = int(
            runner.session.get("slice_target_rows"))

        from trino_tpu.exec.deadline import CancelEvent
        outcome = {}
        cancel_event = CancelEvent()

        def worker():
            try:
                runner.execute(long_scan, query_id="bench_preempt",
                               cancel_event=cancel_event)
                outcome["state"] = "finished-before-cancel"
            except QueryCanceledError:
                outcome["state"] = "canceled"
            except BaseException as e:  # noqa: BLE001
                outcome["state"] = f"error: {type(e).__name__}: {e}"
            outcome["done_at"] = time.monotonic()

        th = threading.Thread(target=worker)
        th.start()
        # cancel partway into the warm wall so the scan is mid-flight
        time.sleep(max(min(full_wall * 0.3, 2.0), 0.02))
        cancel_event.cancel()       # the DELETE handler's exact path
        th.join(timeout=max(4 * full_wall, 60))
        stats = runner.last_query_stats
        canceled = outcome.get("state") == "canceled"
        payload.update({
            "outcome": outcome.get("state", "hung"),
            # meaningful only when the cancel actually preempted the
            # scan (a too-fast scan reports its outcome and no latency)
            "cancel_to_free_ms": round(
                (outcome["done_at"] - cancel_event.cancelled_at) * 1000,
                1) if canceled and "done_at" in outcome else None,
            "preempt_latency_ms": float(
                stats.get("preempt_latency_ms", 0) or 0),
            "slices_executed": int(stats.get("slices_executed", 0)),
            "checkpoint_bytes": int(stats.get("checkpoint_bytes", 0)),
            "pool_reserved_after": NODE_POOL.reserved,
        })
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the line must print
        payload["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    line = json.dumps(payload)
    print(line, flush=True)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")


def run_join_micro(out_path=None) -> None:
    """`bench.py --join-micro [OUT.json]`: matmul-vs-gather head-to-head
    (ROADMAP item 1 / ops/join_mxu.py). Builds synthetic probe/build
    tables from TPC-H data at several density/NDV rungs plus the
    many-to-many AGGREGATING-join rung (the TPC-DS q64/q72 shape: match
    multiplicities feed SUM/COUNT without materializing the cross
    product), and times each rung with the MXU router enabled vs pinned
    off. Per rung: warm walls, probe rows/s both ways, the speedup, the
    mxu_joins/mxu_flops counters, the cold run's XLA cost-model compile
    flops (nonzero matmul flops = the MXU kernels really compiled), and
    a row-parity check. The final JSON line ALWAYS prints; failures
    land in an `error` field. TPU re-run is noted as blocked per
    ROADMAP item 5 — these are CPU numbers."""
    platform = _ensure_backend()
    schema = os.environ.get("TRINO_TPU_JOIN_MICRO_SCHEMA", "sf1")
    payload = {"metric": "join_micro", "backend": platform,
               "schema": schema,
               "tpu_note": "CPU numbers; TPU re-run blocked on device "
                           "access (ROADMAP item 5)"}
    try:
        import trino_tpu
        trino_tpu.enable_persistent_cache()
        from trino_tpu.exec import LocalQueryRunner

        probe_rows = int(os.environ.get("TRINO_TPU_JOIN_MICRO_ROWS",
                                        1 << 20))
        runner = LocalQueryRunner.tpch(schema)
        runner.execute(
            "CREATE TABLE memory.default.jm_probe AS "
            "SELECT l_partkey AS kp, l_orderkey % 2048 AS km, "
            "l_orderkey % 64 AS g, l_quantity AS v "
            f"FROM lineitem LIMIT {probe_rows}")
        n_probe = runner.execute(
            "SELECT count(*) FROM memory.default.jm_probe").rows[0][0]
        runner.execute(
            "CREATE TABLE memory.default.jm_build_m2m AS "
            "SELECT l_orderkey % 2048 AS k, l_extendedprice AS w "
            "FROM lineitem LIMIT 32768")
        runner.execute(
            "CREATE TABLE memory.default.jm_build_u4k AS "
            "SELECT p_partkey AS k, p_retailprice AS w FROM part "
            "WHERE p_partkey <= 4000")
        runner.execute(
            "CREATE TABLE memory.default.jm_build_u512 AS "
            "SELECT p_partkey AS k, p_retailprice AS w FROM part "
            "WHERE p_partkey <= 512")
        runner.execute(
            "CREATE TABLE memory.default.jm_build_sparse AS "
            "SELECT p_partkey AS k, p_retailprice AS w FROM part "
            "WHERE p_partkey <= 4000 AND p_partkey % 64 = 0")
        # (name, build table, sql) — the non-fused rungs aggregate a
        # COMPUTED expression so the join-project probe kernel itself
        # is what runs; the m2m rung is the fused aggregating join
        rungs = [
            ("dense_unique_ndv4k", "jm_build_u4k",
             "SELECT count(*), max(v + w) FROM memory.default.jm_probe "
             "p, memory.default.jm_build_u4k b WHERE p.kp = b.k"),
            ("dense_unique_ndv512", "jm_build_u512",
             "SELECT count(*), max(v + w) FROM memory.default.jm_probe "
             "p, memory.default.jm_build_u512 b WHERE p.kp = b.k"),
            ("sparse_density_1_64", "jm_build_sparse",
             "SELECT count(*), max(v + w) FROM memory.default.jm_probe "
             "p, memory.default.jm_build_sparse b WHERE p.kp = b.k"),
            ("m2m_aggregating", "jm_build_m2m",
             "SELECT g, count(*) c, sum(v) sv, sum(w) sw "
             "FROM memory.default.jm_probe p, "
             "memory.default.jm_build_m2m b WHERE p.km = b.k "
             "GROUP BY g ORDER BY g"),
        ]
        out_rungs = []
        for name, build_table, sql in rungs:
            info = runner.execute(
                f"SELECT count(*), count(DISTINCT k), min(k), max(k) "
                f"FROM memory.default.{build_table}").rows[0]
            brows, ndv, kmin, kmax = (int(x) for x in info)
            span = kmax - kmin + 1 if kmax >= kmin else 0
            rung = {"name": name, "build_rows": brows, "ndv": ndv,
                    "span": span,
                    "density": round(ndv / span, 4) if span else 0.0,
                    "duplication": round(brows / max(ndv, 1), 2)}

            def timed(enabled):
                runner.execute("SET SESSION mxu_join_enabled = "
                               + ("true" if enabled else "false"))
                t0 = time.perf_counter()
                res = runner.execute(sql)
                cold_wall = time.perf_counter() - t0
                cold = dict(runner.last_query_stats)
                t0 = time.perf_counter()
                res = runner.execute(sql)
                warm_wall = time.perf_counter() - t0
                warm = dict(runner.last_query_stats)
                return res.rows, cold_wall, warm_wall, cold, warm

            mxu_rows, mxu_cold, mxu_wall, mxu_cstats, mxu_stats = \
                timed(True)
            g_rows, g_cold, g_wall, _g_c, _g_w = timed(False)
            rung.update({
                "routed": "mxu-matmul"
                          if mxu_stats.get("mxu_joins", 0) else "gather",
                "mxu_warm_wall_s": round(mxu_wall, 4),
                "gather_warm_wall_s": round(g_wall, 4),
                "speedup": round(g_wall / max(mxu_wall, 1e-9), 3),
                "probe_rows_s_mxu": round(n_probe / max(mxu_wall, 1e-9)),
                "probe_rows_s_gather": round(
                    n_probe / max(g_wall, 1e-9)),
                "mxu_joins": int(mxu_stats.get("mxu_joins", 0)),
                "mxu_flops": float(mxu_stats.get("mxu_flops", 0)),
                "compile_flops_cold": float(
                    mxu_cstats.get("estimated_flops", 0)),
                "rows_match": sorted(map(str, mxu_rows))
                              == sorted(map(str, g_rows)),
            })
            out_rungs.append(rung)
        payload["probe_rows"] = int(n_probe)
        payload["rungs"] = out_rungs
        # per-operator attribution over the m2m rung: the measured
        # device wall apportions by XLA cost analysis (obs/profiler);
        # the query-level counters carry the matmul flops proof
        runner.execute("SET SESSION mxu_join_enabled = true")
        runner.execute("SET SESSION collect_operator_stats = true")
        runner.execute(rungs[-1][2])
        st = runner.last_query_stats
        ops = sorted(st.get("operators", []),
                     key=lambda o: -o.get("device_ms", 0))[:4]
        payload["m2m_attribution"] = {
            "mxu_joins": int(st.get("mxu_joins", 0)),
            "mxu_flops": float(st.get("mxu_flops", 0)),
            "top_operators_by_device_ms": [
                {"name": o["name"],
                 "device_ms": o.get("device_ms", 0)} for o in ops],
        }
        m2m = out_rungs[-1]
        payload["m2m_speedup"] = m2m["speedup"]
        payload["mxu_beats_gather"] = bool(
            m2m["routed"] == "mxu-matmul" and m2m["speedup"] > 1.0
            and m2m["mxu_flops"] > 0)
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the line must print
        payload["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    line = json.dumps(payload)
    print(line, flush=True)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")


Q18_LADDER = """
SELECT c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice,
       sum(l_quantity)
FROM customer, orders, lineitem
WHERE o_orderkey IN (SELECT l_orderkey FROM lineitem
                     GROUP BY l_orderkey
                     HAVING sum(l_quantity) > 300)
  AND c_custkey = o_custkey AND o_orderkey = l_orderkey
GROUP BY c_name, c_custkey, o_orderkey, o_orderdate, o_totalprice
ORDER BY o_totalprice DESC, o_orderdate LIMIT 100
"""

# a deliberately skewed duplicate-key join: both sides of the self-join
# carry ~4 rows per orderkey, so the build is never unique — the shape
# that exercises the partitioned hybrid join's recursion/heavy paths
# (TPC-H's own joins are all FK->PK unique builds)
SKEW_LADDER = """
SELECT count(*), sum(l2.l_extendedprice)
FROM lineitem l1 JOIN lineitem l2 ON l1.l_orderkey = l2.l_orderkey
"""

# NDV == rows: partial aggregation collapses NOTHING, so the adaptive
# controller must downgrade (full -> shrunken -> bypass) — q9/q18's own
# GROUP BYs genuinely reduce, which the consistent raw-row ratio now
# correctly keeps in full mode
HIGH_NDV_LADDER = """
SELECT l_orderkey, l_linenumber, sum(l_extendedprice), avg(l_quantity)
FROM lineitem GROUP BY l_orderkey, l_linenumber
"""

LADDER_FRACTIONS = (1.0, 0.5, 0.25, 0.125)
LADDER_COUNTERS = ("spilled_bytes", "agg_mode_downgrades",
                   "agg_mode_upgrades", "agg_recursions",
                   "join_recursions", "heavy_key_splits",
                   "spill_fallbacks", "retries")


def run_profile(out_path=None) -> None:
    """`bench.py --profile [OUT.json]`: the device-time-truth report
    (round 13, obs/profiler.py). Runs q1/q6/q9 with operator-level
    collection ON — which since round 13 executes the SAME plan and the
    SAME fused executables as the plain query (no chain splitting; the
    `stats_jit_misses` field proves it: a warm instrumented run
    dispatches zero new kernels) — and reports each query's
    device/compile/host wall split plus its top-5 operators by
    cost-model-apportioned device time. The cold run's compile wall is
    measured at the jit cache's AOT compile sites, not inferred from a
    cold-vs-warm delta. The final JSON line ALWAYS prints — failures
    land in `error` fields, never a silent rc=1."""
    platform = _ensure_backend()
    payload = {"metric": "profile", "backend": platform, "queries": {}}
    try:
        import trino_tpu
        trino_tpu.enable_persistent_cache()
        from trino_tpu.exec import LocalQueryRunner

        schema = os.environ.get(
            "TRINO_TPU_PROFILE_SCHEMA",
            "tiny" if platform == "cpu" else "sf1")
        payload["schema"] = schema
        runner = LocalQueryRunner.tpch(schema)
        runner.session.set("collect_operator_stats", True)
        for tag, sql in (("tpch_q1", Q1), ("tpch_q6", Q6),
                         ("tpch_q9", Q9)):
            qinfo = {}
            payload["queries"][tag] = qinfo
            try:
                t0 = time.perf_counter()
                runner.execute(sql)
                qinfo["cold_wall_s"] = round(time.perf_counter() - t0, 4)
                cold = dict(runner.last_query_stats)
                t0 = time.perf_counter()
                runner.execute(sql)
                qinfo["warm_wall_s"] = round(time.perf_counter() - t0, 4)
                warm = dict(runner.last_query_stats)
                qinfo["cold_compile_time_ms"] = cold.get(
                    "compile_time_ms", 0.0)
                qinfo["cold_jit_compiles"] = cold.get("jit_compiles", 0)
                qinfo["device_time_ms"] = warm.get("device_time_ms", 0.0)
                qinfo["compile_time_ms"] = warm.get("compile_time_ms",
                                                    0.0)
                qinfo["host_time_ms"] = warm.get("host_time_ms", 0.0)
                qinfo["planning_ms"] = round(
                    warm.get("planning_s", 0.0) * 1000, 3)
                # the no-splitting proof: the warm instrumented run must
                # dispatch only executables the cold run compiled
                qinfo["stats_jit_misses"] = warm.get("jit_misses", 0)
                ops = sorted(warm.get("operators", []),
                             key=lambda o: -o.get("device_ms", 0.0))
                qinfo["top_operators_by_device_ms"] = [
                    {"name": o["name"],
                     "device_ms": o.get("device_ms", 0.0),
                     "wall_ms": o.get("wall_ms", 0.0),
                     "output_rows": o.get("output_rows", 0)}
                    for o in ops[:5]]
                dev_sum = sum(o.get("device_ms", 0.0)
                              for o in warm.get("operators", []))
                qinfo["operator_device_ms_sum"] = round(dev_sum, 3)
                # attribution closes: per-operator device shares sum to
                # the measured chain walls (within float rounding)
                qinfo["attribution_closes"] = abs(
                    dev_sum - warm.get("device_time_ms", 0.0)) < 1.0
            except BaseException as e:  # noqa: BLE001
                qinfo["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    except BaseException as e:  # noqa: BLE001 — the JSON line must print
        payload["error"] = f"{type(e).__name__}: {str(e)[:300]}"
    line = json.dumps(payload)
    print(line, flush=True)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")


def run_memory_ladder(out_path=None) -> None:
    """`bench.py --memory-ladder [OUT.json]`: the no-cliff proof. Runs
    q9 / q18 / a skewed self-join under a shrinking forced node pool
    (1x, 1/2, 1/4, 1/8 of each query's measured working set) with
    retry_policy=QUERY, so an over-pool attempt is killed by the
    low-memory killer and the degrade re-run — inheriting the failed
    attempt's adaptive state — finishes under the spill ladder. Emits
    per-rung wall, spilled bytes, and the adaptive counters, plus a
    `no_cliff` boolean: every rung completed (no OOM, no unbounded
    recursion) and wall degrades smoothly (no rung blows up past
    NO_CLIFF_STEP x its predecessor). The final JSON line ALWAYS
    prints — failures land in `error` fields, never a silent rc=1."""
    platform = _ensure_backend()
    payload = {"metric": "memory_ladder", "backend": platform,
               "queries": {}}
    no_cliff = True
    step_tol = float(os.environ.get("TRINO_TPU_LADDER_STEP_TOL", 8.0))
    try:
        import trino_tpu
        trino_tpu.enable_persistent_cache()
        from trino_tpu.exec import LocalQueryRunner
        from trino_tpu.exec.memory import NODE_POOL
        from trino_tpu.exec.query_tracker import TRACKER

        schema = os.environ.get("TRINO_TPU_LADDER_SCHEMA", "tiny")
        payload["schema"] = schema
        runner = LocalQueryRunner.tpch(schema)
        # small pages so buffers/compactions actually stream (one giant
        # fused scan page would hide every adaptive boundary), QUERY
        # retry so the killer's victim gets its spill-forced degrade run
        for k, v in (("page_capacity", 4096),
                     ("scan_page_capacity", 8192),
                     ("spill_partition_count", 8),
                     ("retry_policy", "QUERY")):
            runner.session.set(k, v)

        ladder = {"tpch_q9": Q9, "tpch_q18": Q18_LADDER,
                  "skew_join": SKEW_LADDER,
                  "high_ndv_agg": HIGH_NDV_LADDER}
        for tag, sql in ladder.items():
            qinfo = {"rungs": []}
            payload["queries"][tag] = qinfo
            # working set = the unconstrained run's peak pool
            # reservation (also the warm-compile run)
            wsid = f"ladder_ws_{tag}"
            try:
                t0 = time.perf_counter()
                runner.execute(sql, query_id=wsid)
                base_wall = time.perf_counter() - t0
            except BaseException as e:  # noqa: BLE001
                qinfo["error"] = f"{type(e).__name__}: {str(e)[:300]}"
                no_cliff = False
                continue
            peak = max((q.pool_peak_bytes for q in TRACKER.list()
                        if q.query_id == wsid), default=0)
            ws = max(int(peak), 1 << 20)
            qinfo["working_set_bytes"] = ws
            qinfo["unconstrained_wall_s"] = round(base_wall, 4)
            # warm the spill/recursion kernels at the TIGHTEST rung's
            # config (untimed): the rung walls must measure the adaptive
            # ladder's steady state, not first-spill XLA compiles
            try:
                tight = max(int(ws * LADDER_FRACTIONS[-1]) // 4, 1 << 16)
                for prop in ("join_spill_threshold_bytes",
                             "agg_spill_threshold_bytes",
                             "sort_spill_threshold_bytes"):
                    runner.session.set(prop, tight)
                runner.execute(sql)
            except BaseException:  # noqa: BLE001 — warming is best-effort
                pass
            finally:
                for prop in ("join_spill_threshold_bytes",
                             "agg_spill_threshold_bytes",
                             "sort_spill_threshold_bytes"):
                    runner.session.properties.pop(prop, None)
            prev_wall = None
            for frac in LADDER_FRACTIONS:
                limit = max(int(ws * frac), 1 << 18)
                rung = {"fraction": frac, "pool_limit_bytes": limit}
                qinfo["rungs"].append(rung)
                # the query ledger tracks the pool (mid-collect overflow
                # hands builds to the streaming partitioned join) and
                # the spill thresholds shrink proportionally so blocking
                # operators flush instead of materializing over the rung
                runner.session.set("query_max_memory", limit)
                spill_t = max(limit // 4, 1 << 16)
                for prop in ("join_spill_threshold_bytes",
                             "agg_spill_threshold_bytes",
                             "sort_spill_threshold_bytes"):
                    runner.session.set(prop, spill_t)
                try:
                    with NODE_POOL.limited(limit):
                        t0 = time.perf_counter()
                        runner.execute(sql)
                        rung["wall_s"] = round(
                            time.perf_counter() - t0, 4)
                except KeyboardInterrupt:
                    raise
                except BaseException as e:  # noqa: BLE001
                    rung["error"] = f"{type(e).__name__}: {str(e)[:300]}"
                    no_cliff = False
                    continue
                finally:
                    for prop in ("query_max_memory",
                                 "join_spill_threshold_bytes",
                                 "agg_spill_threshold_bytes",
                                 "sort_spill_threshold_bytes"):
                        runner.session.properties.pop(prop, None)
                stats = runner.last_query_stats
                for key in LADDER_COUNTERS:
                    rung[key] = int(stats.get(key, 0))
                if prev_wall is not None and \
                        rung["wall_s"] > step_tol * max(prev_wall, 1e-3):
                    # a cliff: one halving of memory blew the wall up
                    # by more than the tolerated degradation step
                    rung["cliff"] = True
                    no_cliff = False
                prev_wall = rung["wall_s"]
            totals = {k: sum(r.get(k, 0) for r in qinfo["rungs"])
                      for k in LADDER_COUNTERS}
            qinfo["totals"] = totals
        all_counters = {
            k: sum(q.get("totals", {}).get(k, 0)
                   for q in payload["queries"].values())
            for k in LADDER_COUNTERS}
        payload["counters"] = all_counters
        payload["adaptive_paths_fired"] = bool(
            all_counters.get("agg_mode_downgrades", 0)
            and all_counters.get("join_recursions", 0)
            and all_counters.get("spilled_bytes", 0))
    except KeyboardInterrupt:
        raise
    except BaseException as e:  # noqa: BLE001 — the line must print
        payload["error"] = f"{type(e).__name__}: {str(e)[:300]}"
        no_cliff = False
    payload["no_cliff"] = no_cliff
    line = json.dumps(payload)
    print(line, flush=True)
    if out_path:
        with open(out_path, "w") as f:
            f.write(line + "\n")


def main():
    """Always emits exactly one final JSON line: a backend-init or rung
    failure lands in an `"error"` field (value stays null) instead of a
    bare rc=1 with nothing to parse — the perf trajectory must never
    have a silent hole."""
    extra = {}
    q6 = None
    error = None
    platform = _ensure_backend()
    extra["backend"] = platform
    try:
        import trino_tpu
        # persistent compile cache: repeat rounds skip XLA recompiles
        trino_tpu.enable_persistent_cache()

        from trino_tpu.connector.tpch import table_row_count
        from trino_tpu.exec import LocalQueryRunner

        sf1 = LocalQueryRunner.tpch("sf1")
        bd6, bd1, bd3 = {}, {}, {}
        q6 = _time_query(sf1, Q6, breakdown=bd6, variant=Q6_VARIANT,
                         prepared=PREPARED["tpch_q6_sf1"])
        q1 = _time_query(sf1, Q1, breakdown=bd1, variant=Q1_VARIANT,
                         prepared=PREPARED["tpch_q1_sf1"])
        extra["tpch_q6_sf1_breakdown"] = bd6
        extra["tpch_q1_sf1_wall_s"] = round(q1, 4)
        extra["tpch_q1_sf1_vs_baseline"] = round(BASE_Q1_SF1_S / q1, 3)
        extra["tpch_q1_sf1_breakdown"] = bd1

        # per-operator totals from one instrumented q6 run (runs outside
        # timing for the per-chain fence cost; since round 13 the
        # instrumented run dispatches the SAME fused executables — see
        # --profile for the full device/compile/host report)
        sf1.session.set("collect_operator_stats", True)
        sf1.execute(Q6)
        extra["tpch_q6_sf1_operators"] = \
            sf1.last_query_stats.get("operators", [])
        sf1.session.properties.pop("collect_operator_stats", None)

        sf10_stats = None
        if platform == "cpu" and \
                os.environ.get("TRINO_TPU_BENCH_SF10") != "force":
            # ~6 timed 60M-row runs on the CPU fallback would eat the
            # whole wall budget; the CPU bench is a diagnostic, not the
            # perf trajectory — skip loudly, overridable
            extra["tpch_q3_sf10_error"] = \
                "skipped: cpu backend (TRINO_TPU_BENCH_SF10=force " \
                "overrides)"
        elif _remaining() > 600:
            sf10 = LocalQueryRunner.tpch("sf10")
            q3 = _time_query(sf10, Q3, breakdown=bd3, variant=Q3_VARIANT,
                             prepared=PREPARED["tpch_q3_sf10"])
            extra["tpch_q3_sf10_wall_s"] = round(q3, 4)
            extra["tpch_q3_sf10_vs_baseline"] = round(
                BASE_Q3_SF10_S / q3, 3)
            extra["tpch_q3_sf10_breakdown"] = bd3

            # BASELINE metric: hash-join probe rows/sec/chip (60M-row
            # lineitem probe into a unique 15M-row orders build)
            probe_rows = table_row_count("lineitem", 10.0)
            jm = _time_query(sf10, JOIN_MICRO, iters=2)
            extra["hash_join_probe_rows_per_s_per_chip"] = \
                round(probe_rows / jm)
            extra["hash_join_vs_baseline"] = round(
                (probe_rows / jm) / BASE_JOIN_ROWS_PER_S, 3)
            sf10_stats = sf10.stats
        else:
            extra["tpch_q3_sf10_error"] = \
                f"skipped: bench wall budget ({BUDGET_S}s) nearly spent"

        sf100_env = os.environ.get("TRINO_TPU_BENCH_SF100", "1")
        if sf100_env == "0" or (platform == "cpu"
                                and sf100_env != "force"):
            # SF100 rungs stream 100GB-scale data; on the CPU fallback
            # they would blow the wall budget without producing a
            # comparable number — record WHY instead of a silent hole
            if sf100_env != "0":
                for tag in SF100_RUNGS:
                    extra[f"{tag}_error"] = \
                        "skipped: cpu backend (SF100 rungs are TPU-scale;" \
                        " TRINO_TPU_BENCH_SF100=force overrides)"
        else:
            for tag, (base, _, _, _) in SF100_RUNGS.items():
                _run_rung_subprocess(extra, tag, base)

        # fault-tolerance counters (round 6): nonzero retries on a clean
        # bench mean the engine degraded (memory-forced spill re-runs) —
        # surfaced so a perf regression caused by silent retries is visible
        extra["retries"] = sf1.stats["retries"] + (
            sf10_stats["retries"] if sf10_stats else 0)
        extra["faults_injected"] = sf1.stats["faults_injected"] + (
            sf10_stats["faults_injected"] if sf10_stats else 0)
    except KeyboardInterrupt as e:
        # still emit the JSON line, but PROPAGATE: an interrupted bench
        # must not exit rc=0 looking green to a gating harness
        error = f"{type(e).__name__}: {str(e)[:300]}"
        interrupted = e
    except BaseException as e:  # noqa: BLE001 — the JSON line must print
        # BaseException, not Exception: a backend-init failure that
        # raises SystemExit (or any exotic non-Exception) used to leave
        # rc=1 with nothing parseable — a silent hole in the perf
        # trajectory. The error rides in the JSON line and the process
        # exits 0; the harness reads `error`, not the return code.
        error = f"{type(e).__name__}: {str(e)[:300]}"
        interrupted = None
    else:
        interrupted = None

    payload = {
        "metric": "tpch_q6_sf1_wall_s",
        "value": round(q6, 4) if q6 is not None else None,
        "unit": "s",
        "extra": extra,
    }
    if q6 is not None:
        payload["vs_baseline"] = round(BASE_Q6_SF1_S / q6, 3)
    if error is not None:
        payload["error"] = error
    print(json.dumps(payload), flush=True)
    if interrupted is not None:
        raise interrupted
    if error is not None:
        sys.exit(0)   # explicit: the JSON line IS the report


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--rung":
        run_rung(sys.argv[2])
    elif len(sys.argv) >= 2 and sys.argv[1] == "--mesh":
        run_mesh(sys.argv[2] if len(sys.argv) >= 3 else None)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--lake":
        run_lake(sys.argv[2] if len(sys.argv) >= 3 else None)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--scrub":
        run_scrub(sys.argv[2] if len(sys.argv) >= 3 else None)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--mv":
        run_mv(sys.argv[2] if len(sys.argv) >= 3 else None)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--qps":
        _qps_args = sys.argv[2:]
        _qps_workers = None
        if "--workers" in _qps_args:
            _i = _qps_args.index("--workers")
            try:
                _qps_workers = [int(x)
                                for x in _qps_args[_i + 1].split(",")]
            except (IndexError, ValueError):
                print("usage: bench.py --qps [OUT.json] "
                      "[--workers N1,N2,...]  (e.g. --workers 0,1,2,4,8)",
                      file=sys.stderr)
                sys.exit(2)
            _qps_args = _qps_args[:_i] + _qps_args[_i + 2:]
        run_qps(_qps_args[0] if _qps_args else None,
                workers=_qps_workers)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--chaos-fleet":
        run_chaos_fleet(sys.argv[2] if len(sys.argv) >= 3 else None)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--preempt":
        run_preempt(sys.argv[2] if len(sys.argv) >= 3 else None)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--memory-ladder":
        run_memory_ladder(sys.argv[2] if len(sys.argv) >= 3 else None)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--profile":
        run_profile(sys.argv[2] if len(sys.argv) >= 3 else None)
    elif len(sys.argv) >= 2 and sys.argv[1] == "--join-micro":
        run_join_micro(sys.argv[2] if len(sys.argv) >= 3 else None)
    else:
        main()
