"""MXU-native join path: density-partitioned matmul joins vs the gather
path vs the sqlite oracle (ops/join_mxu.py, the router in
exec/local_planner._prepare_probe, the fused aggregating join in
_mxu_agg_join, and the mesh in-program variant).

Parity discipline: every shape runs FORCED onto the matmul path
(density threshold 0, widened slots so the router cannot decline) and
FORCED off (mxu_join_enabled = false), compared against each other —
the gather path is the reference semantics — and, where the result is
cleanly comparable, against the sqlite oracle. The EXPLAIN strategy
line, the mxu_joins/mxu_flops counters, 8-device mesh parity with
exchanges_staged == 0, and chaos-under-TASK with the path pinned are
asserted alongside.
"""

import jax
import pytest

from trino_tpu.exec import LocalQueryRunner

from oracle import assert_same, load_tpch_sqlite

SF = 0.01


def _mxu_session(r):
    r.execute("SET SESSION mxu_join_density_threshold = 0")
    r.execute("SET SESSION mxu_join_max_slots = 65536")
    return r


@pytest.fixture(scope="module")
def mxu_runner():
    return _mxu_session(LocalQueryRunner.tpch("tiny"))


@pytest.fixture(scope="module")
def gather_runner():
    r = LocalQueryRunner.tpch("tiny")
    r.execute("SET SESSION mxu_join_enabled = false")
    return r


@pytest.fixture(scope="module")
def oracle():
    conn = load_tpch_sqlite(SF)
    yield conn
    conn.close()


def both_ways(mxu_runner, gather_runner, sql, expect_mxu=True):
    """Run forced-on and forced-off; the rows must agree. Returns the
    mxu run's rows + stats."""
    got = mxu_runner.execute(sql)
    stats = dict(mxu_runner.last_query_stats)
    ref = gather_runner.execute(sql)
    assert sorted(map(str, got.rows)) == sorted(map(str, ref.rows)), sql
    if expect_mxu:
        assert stats.get("mxu_joins", 0) > 0, sql
        assert stats.get("mxu_flops", 0) > 0, sql
    else:
        assert stats.get("mxu_joins", 0) == 0, sql
    return got, stats


# ------------------------------------------------------------- parity


def test_join_project_unique_build(mxu_runner, gather_runner, oracle):
    sql = ("SELECT count(*), sum(l_extendedprice) FROM lineitem, part "
           "WHERE l_partkey = p_partkey AND p_size > 25")
    got, _ = both_ways(mxu_runner, gather_runner, sql)
    assert_same(got.rows, oracle.execute(sql).fetchall(), False)


def test_join_project_duplicate_build(mxu_runner, gather_runner, oracle):
    # orders is NOT unique per custkey: the cumsum-expansion kernel
    # runs with the matmul-provided (count, first-pos) pairs
    sql = ("SELECT count(*) FROM customer, orders "
           "WHERE c_custkey = o_custkey AND o_orderstatus = 'F'")
    got, _ = both_ways(mxu_runner, gather_runner, sql)
    assert_same(got.rows, oracle.execute(sql).fetchall(), False)


def test_semijoin_and_anti(mxu_runner, gather_runner, oracle):
    for sql in [
        "SELECT count(*) FROM orders WHERE o_custkey IN "
        "(SELECT c_custkey FROM customer WHERE c_acctbal > 0)",
        "SELECT count(*) FROM orders WHERE o_custkey NOT IN "
        "(SELECT c_custkey FROM customer WHERE c_acctbal > 0)",
        "SELECT count(*) FROM customer c WHERE EXISTS "
        "(SELECT 1 FROM orders o WHERE o.o_custkey = c.c_custkey)",
    ]:
        got, _ = both_ways(mxu_runner, gather_runner, sql)
        assert_same(got.rows, oracle.execute(sql).fetchall(), False)


def test_distinct_project(mxu_runner, gather_runner, oracle):
    sql = ("SELECT DISTINCT s_nationkey FROM supplier, nation "
           "WHERE s_nationkey = n_nationkey")
    got, _ = both_ways(mxu_runner, gather_runner, sql)
    assert_same(got.rows, oracle.execute(sql).fetchall(), False)


def test_aggregating_join(mxu_runner, gather_runner, oracle):
    # probe-side group keys + probe/build-side COUNT/SUM: the fused
    # M = A·Bᵀ path (no cross-product materialization)
    sql = ("SELECT s_nationkey, count(*), sum(s_acctbal), "
           "sum(n_regionkey), count(n_comment) "
           "FROM supplier, nation WHERE s_nationkey = n_nationkey "
           "GROUP BY s_nationkey ORDER BY s_nationkey")
    got, _ = both_ways(mxu_runner, gather_runner, sql)
    assert_same(got.rows, oracle.execute(sql).fetchall(), ordered=True)


def test_aggregating_join_many_to_many():
    # both sides duplicate keys: the shape whose gather-path cross
    # product the matmul path never materializes. One runner, toggled
    # per run (the memory tables live in the runner's catalog).
    r = _mxu_session(LocalQueryRunner.tpch("tiny"))
    r.execute(
        "CREATE TABLE memory.default.mm_probe AS SELECT "
        "l_orderkey % 256 AS k, l_suppkey % 16 AS g, l_quantity AS v "
        "FROM lineitem")
    r.execute(
        "CREATE TABLE memory.default.mm_build AS SELECT "
        "o_orderkey % 256 AS k, o_totalprice AS w FROM orders")
    sql = ("SELECT g, count(*), sum(v), sum(w) FROM "
           "memory.default.mm_probe p, memory.default.mm_build b "
           "WHERE p.k = b.k GROUP BY g ORDER BY g")
    got = r.execute(sql)
    assert r.last_query_stats.get("mxu_joins", 0) > 0
    r.execute("SET SESSION mxu_join_enabled = false")
    ref = r.execute(sql)
    assert got.rows == ref.rows


def test_aggregating_join_build_sum_null_groups():
    # a key whose EVERY build value is NULL: SUM(w) must be NULL for
    # groups that only joined such keys (the #valid-w helper mask),
    # while COUNT(w) reads 0 there
    r = _mxu_session(LocalQueryRunner.tpch("tiny"))
    r.execute(
        "CREATE TABLE memory.default.nb AS SELECT "
        "o_orderkey % 8 AS k, CASE WHEN o_orderkey % 8 = 3 THEN NULL "
        "ELSE o_custkey END AS w FROM orders")
    # a precomputed probe so the group key is a plain probe column
    # (computed group keys sit in a Project the fused path declines)
    r.execute(
        "CREATE TABLE memory.default.np AS SELECT "
        "s_suppkey % 8 AS k, s_suppkey % 4 AS g FROM supplier")
    sql = ("SELECT g, count(*), sum(w), count(w) FROM "
           "memory.default.np p, memory.default.nb b "
           "WHERE p.k = b.k GROUP BY g ORDER BY g")
    got = r.execute(sql)
    assert r.last_query_stats.get("mxu_joins", 0) > 0
    # nulls excluded from count(w): the k=3 build rows are all NULL
    assert any(row[3] < row[1] for row in got.rows)
    r.execute("SET SESSION mxu_join_enabled = false")
    ref = r.execute(sql)
    assert got.rows == ref.rows


def test_aggregating_join_int_sum_magnitude_guard():
    # per-key integer sums at/past 2^53 are beyond f64's exact range:
    # scatter_agg_table's mag_ok must decline the fused path so the
    # gather join's exact int64 arithmetic answers
    r = _mxu_session(LocalQueryRunner.tpch("tiny"))
    r.execute(
        "CREATE TABLE memory.default.huge AS SELECT s_suppkey % 4 AS k, "
        "9007199254740993 + s_suppkey AS w FROM supplier")
    r.execute(
        "CREATE TABLE memory.default.hp AS SELECT s_suppkey % 4 AS k, "
        "s_suppkey % 2 AS g FROM supplier")
    sql = ("SELECT g, sum(w) FROM memory.default.hp p, "
           "memory.default.huge b WHERE p.k = b.k GROUP BY g ORDER BY g")
    got = r.execute(sql)
    r.execute("SET SESSION mxu_join_enabled = false")
    ref = r.execute(sql)
    assert got.rows == ref.rows


def test_sparse_build_declines(mxu_runner, gather_runner):
    # density below the threshold: the router must keep the gather path
    r = LocalQueryRunner.tpch("tiny")   # default threshold 0.05
    sql = ("SELECT count(*) FROM lineitem, part "
           "WHERE l_partkey = p_partkey AND p_partkey % 64 = 0")
    got = r.execute(sql)
    assert r.last_query_stats.get("mxu_joins", 0) == 0
    ref = gather_runner.execute(sql)
    assert got.rows == ref.rows


# ----------------------------------------------- EXPLAIN + counters


def test_explain_strategy_line(mxu_runner, gather_runner):
    sql = ("SELECT count(*) FROM lineitem, part "
           "WHERE l_partkey = p_partkey")
    on = mxu_runner.execute("EXPLAIN " + sql).rows[0][0]
    assert "join strategy: mxu-matmul" in on
    off = gather_runner.execute("EXPLAIN " + sql).rows[0][0]
    assert "join strategy: gather" in off
    assert "join strategy: mxu-matmul" not in off


def test_counters_in_snapshot_and_footer(mxu_runner):
    sql = ("SELECT s_nationkey, count(*) FROM supplier, nation "
           "WHERE s_nationkey = n_nationkey GROUP BY s_nationkey")
    mxu_runner.execute(sql)
    st = mxu_runner.last_query_stats
    assert st["mxu_joins"] > 0 and st["mxu_flops"] > 0
    # the cost-model compile ledger saw the matmul kernels (PR 12's
    # attribution surface) at least once this process
    analyzed = mxu_runner.execute("EXPLAIN ANALYZE " + sql).rows[0][0]
    assert "mxu:" in analyzed and "matmul joins" in analyzed


# ------------------------------------------------------------- mesh


@pytest.mark.mesh
def test_mesh_mxu_parity(gather_runner):
    if len(jax.devices()) < 8:
        pytest.skip("needs the forced 8-device CPU mesh")
    from trino_tpu.exec.distributed import DistributedQueryRunner
    r = _mxu_session(DistributedQueryRunner.tpch("tiny"))
    for sql in [
        "SELECT n_name, count(*) FROM supplier, nation "
        "WHERE s_nationkey = n_nationkey GROUP BY n_name ORDER BY 1",
        "SELECT count(*), sum(l_quantity) FROM lineitem, orders "
        "WHERE l_orderkey = o_orderkey AND o_orderstatus = 'F'",
    ]:
        got = r.execute(sql)
        st = r.last_query_stats
        assert st.get("mesh_devices") == 8
        assert st.get("exchanges_staged") == 0, sql
        assert st.get("mxu_joins", 0) >= 1, sql
        ref = gather_runner.execute(sql)
        assert sorted(map(str, got.rows)) == sorted(map(str, ref.rows))


# ------------------------------------------------------------ chaos


def test_chaos_task_with_mxu_pinned(oracle):
    r = _mxu_session(LocalQueryRunner.tpch("tiny"))
    r.session.set("retry_policy", "TASK")
    r.session.set("fault_injection_rate", 0.2)
    r.session.set("fault_injection_seed", 42)
    sql = ("SELECT s_nationkey, count(*), sum(s_acctbal) "
           "FROM supplier, nation WHERE s_nationkey = n_nationkey "
           "GROUP BY s_nationkey ORDER BY s_nationkey")
    got = r.execute(sql)
    assert_same(got.rows, oracle.execute(sql).fetchall(), ordered=True)


# -------------------------------------------- spilled-build staging


def test_spilled_build_chunked_staging(gather_runner, monkeypatch):
    """PR 10 leftover fix: the keys-on-device spill path stages build
    payload columns chunk-wise (many small transfers, one bounded
    device transient) instead of materializing the whole build again."""
    from trino_tpu.exec.local_planner import LocalExecutionPlanner
    monkeypatch.setattr(LocalExecutionPlanner,
                        "_SPILL_STAGE_CHUNK_BYTES", 1 << 12)
    r = LocalQueryRunner.tpch("tiny")
    r.execute("SET SESSION mxu_join_enabled = false")
    r.execute("SET SESSION join_spill_threshold_bytes = 4096")
    sql = ("SELECT count(*), sum(o_totalprice) FROM lineitem, orders "
           "WHERE l_orderkey = o_orderkey")
    got = r.execute(sql)
    assert r.last_query_stats.get("spilled_bytes", 0) > 0
    ref = gather_runner.execute(sql)
    assert got.rows == ref.rows


# -------------------------------- dispatch-loop cache promotion


def test_dispatch_loop_table_cache_promotes():
    """PR 11 leftover fix: the per-shard dispatch loop now records scan
    frequency and promotes into the device table cache — the second
    dispatch-loop scan serves from HBM with zero host->device bytes."""
    from trino_tpu.exec.distributed import DistributedQueryRunner
    r = DistributedQueryRunner.tpch("tiny")
    r.execute("SET SESSION mesh_execution = false")
    r.execute("SET SESSION table_cache_enabled = true")
    r.execute("SET SESSION table_cache_min_scans = 1")
    sql = "SELECT count(*), sum(s_acctbal) FROM supplier"
    first = r.execute(sql)
    assert r.last_query_stats.get("scan_staging_bytes", 0) > 0
    second = r.execute(sql)
    st = r.last_query_stats
    assert st.get("table_cache_hits", 0) > 0
    assert st.get("scan_staging_bytes") == 0
    assert first.rows == second.rows


# ------------------------------------------------- q64/q72 shapes


@pytest.fixture(scope="module")
def tpcds_oracle():
    from oracle import load_tpcds_sqlite
    conn = load_tpcds_sqlite(SF)
    yield conn
    conn.close()


def test_q72_with_router_enabled(tpcds_oracle):
    r = _mxu_session(LocalQueryRunner.tpch("tiny"))
    r.execute("USE tpcds.tiny")
    engine = """
SELECT i_item_desc, w_warehouse_name, d1.d_week_seq, count(*) total_cnt
FROM catalog_sales
JOIN inventory ON (cs_item_sk = inv_item_sk)
JOIN warehouse ON (w_warehouse_sk = inv_warehouse_sk)
JOIN item ON (i_item_sk = cs_item_sk)
JOIN date_dim d1 ON (cs_sold_date_sk = d1.d_date_sk)
JOIN date_dim d2 ON (inv_date_sk = d2.d_date_sk)
WHERE d1.d_week_seq = d2.d_week_seq
  AND inv_quantity_on_hand < cs_quantity AND d1.d_year = 1999
GROUP BY i_item_desc, w_warehouse_name, d1.d_week_seq
ORDER BY total_cnt DESC, i_item_desc, w_warehouse_name, d1.d_week_seq
LIMIT 100"""
    got = r.execute(engine)
    assert r.last_query_stats.get("mxu_joins", 0) > 0
    assert_same(got.rows, tpcds_oracle.execute(engine).fetchall(),
                ordered=True)


def test_q64_core_with_router_enabled(tpcds_oracle):
    r = _mxu_session(LocalQueryRunner.tpch("tiny"))
    r.execute("USE tpcds.tiny")
    engine = """
SELECT i_product_name, d1.d_year, count(*) AS cnt,
       sum(ss_wholesale_cost) AS s1
FROM store_sales, store_returns, date_dim d1, item
WHERE ss_sold_date_sk = d1.d_date_sk
  AND ss_item_sk = i_item_sk
  AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND i_current_price BETWEEN 35 AND 45
GROUP BY i_product_name, d1.d_year
ORDER BY i_product_name, d1.d_year, cnt LIMIT 100"""
    oracle_sql = engine.replace("BETWEEN 35 AND 45",
                                "BETWEEN 3500 AND 4500")
    got = r.execute(engine)
    assert r.last_query_stats.get("mxu_joins", 0) > 0
    assert_same(got.rows, tpcds_oracle.execute(oracle_sql).fetchall(),
                ordered=True)
