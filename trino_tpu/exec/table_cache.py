"""Device-resident hot-table cache: columns that stay in HBM across
queries.

The serving-tier scan cache (serve/caches.ScanCache) keeps STAGED PAGES
per (table, columns, capacity) — a re-scan with a different capacity or
column subset re-stages from the host. This tier caches the COLUMNS
themselves: full-length device arrays promoted once, then served to any
scan over any subset of the cached columns at any page capacity — the
local dispatch loop wraps them in pages by device-side slicing, and mesh
`shard_map` staging shards them by row range, so a warm repeated scan
does ZERO host->device transfers (counter-proven via the per-query
`scan_staging_bytes` counter, like `exchanges_fused`).

Admission is scan-frequency x size: a (table, columns) working set
becomes a promotion candidate after `table_cache_min_scans` scans, and
eviction under the byte budget drops the entry with the lowest
frequency x recency score first — one giant cold table cannot wipe a
hot dashboard's dimension tables. Residency is accounted against the
per-chip node pool (exec/memory.NodeMemoryPool.reserve_cache): the pool
declines admission that would overflow the chip's HBM budget, and the
per-device residency gauges surface in /v1/metrics and
system.runtime.nodes.

Invalidation rides the PlanCache hook fan-out: ONE DDL/INSERT call
drops cached plans, result sets, staged scan pages, AND the device
columns — a resident column can never outlive a table change.

Like the other serving caches, one instance per owning runner, shared
with `for_query()` clones under a lock.
"""

from __future__ import annotations

import dataclasses
import threading
import time
import weakref
from typing import Dict, List, Optional, Sequence, Tuple

from trino_tpu.exec.plan_cache import _GenerationGuard

TableKey = Tuple[str, str, str]   # (catalog, schema, table)

DEFAULT_MAX_BYTES = 1 << 30
DEFAULT_MIN_SCANS = 2

# process-lifetime counters across every runner's cache (metrics gauges
# + system.runtime.caches)
_STATS = {"hits": 0, "misses": 0, "promotions": 0, "evictions": 0,
          "invalidations": 0, "admission_denied": 0}
_STATS_LOCK = threading.Lock()
_INSTANCES: "weakref.WeakSet[TableCache]" = weakref.WeakSet()


def _count(name: str, n: int = 1) -> None:
    with _STATS_LOCK:
        _STATS[name] += n


def _next_pow2(n: int) -> int:
    out = 8
    while out < n:
        out *= 2
    return out


@dataclasses.dataclass
class ResidentTable:
    """One promoted working set: full-length device columns (capacity =
    pow2(rows)) for a set of column names of one table."""

    table: TableKey
    columns: Dict[str, object]      # name -> page.Column (device arrays)
    rows: int
    nbytes: int
    device: Optional[int]
    freq: int = 0
    last_used: float = 0.0

    def score(self) -> Tuple[int, float]:
        """Eviction order: lowest frequency first, LRU within a tie."""
        return (self.freq, self.last_used)


class TableCache(_GenerationGuard):
    def __init__(self, max_bytes: int = DEFAULT_MAX_BYTES,
                 min_scans: int = DEFAULT_MIN_SCANS):
        self._lock = threading.RLock()
        self.max_bytes = int(max_bytes)
        self.min_scans = int(min_scans)
        self.resident_bytes = 0
        # key = (table, frozenset of column names)
        self._entries: Dict[tuple, ResidentTable] = {}
        # scan-frequency ledger feeding admission (kept separate from
        # entries: a candidate earns its promotion before it costs HBM)
        self._scan_counts: Dict[tuple, int] = {}
        # put-generation race guard (exec/plan_cache._GenerationGuard,
        # the discipline every table-keyed cache layer here shares): a
        # promotion built from pages scanned BEFORE a concurrent
        # INSERT's invalidation must not land AFTER it — callers
        # snapshot generation() before the scan and pass it to
        # promote_from_pages
        self._init_generations()
        _INSTANCES.add(self)

    # ------------------------------------------------------------ probes

    def configure(self, max_bytes: int, min_scans: int) -> None:
        """Session-driven sizing (the OWNING runner applies its
        table_cache_max_bytes/min_scans per query; clones never do)."""
        with self._lock:
            self.min_scans = int(min_scans)
            if int(max_bytes) != self.max_bytes:
                self.max_bytes = int(max_bytes)
                self._evict_to_budget_locked()

    def note_scan(self, table: TableKey,
                  column_names: Sequence[str]) -> int:
        """Record one scan of (table, columns); returns the running
        count — the executor promotes when it reaches min_scans."""
        key = (table, frozenset(column_names))
        with self._lock:
            n = self._scan_counts.get(key, 0) + 1
            self._scan_counts[key] = n
            return n

    def should_promote(self, table: TableKey,
                       column_names: Sequence[str]) -> bool:
        """Not already resident (the caller owns the frequency check —
        it reads the session's table_cache_min_scans)."""
        with self._lock:
            return (table, frozenset(column_names)) not in self._entries \
                and self._find_locked(table, column_names) is None

    def _find_locked(self, table: TableKey,
                     column_names: Sequence[str]
                     ) -> Optional[ResidentTable]:
        """An entry serving ALL requested columns (exact set or a
        superset promoted for a wider scan)."""
        want = set(column_names)
        exact = self._entries.get((table, frozenset(want)))
        if exact is not None:
            return exact
        for (tk, cols), entry in self._entries.items():
            if tk == table and want <= cols:
                return entry
        return None

    def lookup(self, table: TableKey, column_names: Sequence[str],
               count: bool = True) -> Optional[ResidentTable]:
        """Resident entry covering the requested columns, or None.
        `count=True` counts hit/miss and bumps the recency/frequency
        score; count=False is the secondary-shard probe (a mesh scan
        counts once, on shard 0)."""
        with self._lock:
            entry = self._find_locked(table, column_names)
            if entry is None:
                if count:
                    _count("misses")
                return None
            if count:
                entry.freq += 1
                entry.last_used = time.monotonic()
                _count("hits")
            return entry

    def peek(self, table: TableKey, column_names: Sequence[str]) -> bool:
        """lookup() without counters (eligibility probes)."""
        with self._lock:
            return self._find_locked(table, column_names) is not None

    # ---------------------------------------------------------- promotion

    def promote_from_pages(self, table: TableKey,
                           symbols_cols: Sequence[Tuple[str, object]],
                           pages: Sequence, counts: Sequence[int],
                           device: Optional[int] = None,
                           collector=None,
                           gen: Optional[int] = None) -> bool:
        """Build full-length device columns from already-staged scan
        pages (they are ON DEVICE — promotion costs device concats, not
        a host re-read) and admit them under the budget + node pool.
        `gen` is the generation snapshot taken BEFORE the pages were
        scanned: a promotion racing a concurrent INSERT's invalidation
        is rejected rather than landing stale columns."""
        import jax.numpy as jnp

        from trino_tpu.page import Column

        names = [n for n, _ in symbols_cols]
        rows = int(sum(int(c) for c in counts))
        if rows <= 0:
            return False
        live = [(p, int(c)) for p, c in zip(pages, counts) if int(c) > 0]
        columns: Dict[str, object] = {}
        cap = _next_pow2(rows)
        for i, (name, ch) in enumerate(symbols_cols):
            cols = [p.columns[i] for p, _ in live]
            dicts = {c.dictionary.fingerprint for c in cols
                     if c.dictionary is not None}
            if len(dicts) > 1:
                return False    # per-page pools diverge: codes unstable
            if any(c.lengths is not None for c in cols):
                return False    # list layouts: not worth the plumbing
            vals = jnp.concatenate([c.values[:n]
                                    for c, (_, n) in zip(cols, live)])
            if vals.shape[0] < cap:
                pad = jnp.zeros((cap - vals.shape[0],) + vals.shape[1:],
                                dtype=vals.dtype)
                vals = jnp.concatenate([vals, pad])
            valid = None
            if any(c.valid is not None for c in cols):
                valid = jnp.concatenate(
                    [c.valid_mask()[:n] for c, (_, n) in zip(cols, live)])
                if valid.shape[0] < cap:
                    valid = jnp.concatenate(
                        [valid, jnp.zeros(cap - valid.shape[0],
                                          dtype=bool)])
            columns[name] = Column(vals, valid, ch.type,
                                   cols[0].dictionary)
        nbytes = sum(c.nbytes for c in columns.values())
        return self._admit(ResidentTable(table, columns, rows, nbytes,
                                         device, freq=1,
                                         last_used=time.monotonic()),
                           frozenset(names), collector, gen)

    def _admit(self, entry: ResidentTable, colset: frozenset,
               collector=None, gen: Optional[int] = None) -> bool:
        from trino_tpu.exec.memory import NODE_POOL
        with self._lock:
            if self._stale_locked((entry.table,), gen):
                # the table changed while these pages were being
                # scanned: the invalidation that should have dropped
                # them already ran (same race guard as PlanCache.put)
                return False
            if entry.nbytes > self.max_bytes:
                _count("admission_denied")
                return False
            key = (entry.table, colset)
            old = self._entries.pop(key, None)
            if old is not None:
                self._release_locked(old)
            # budget first, then the chip's pool: a declined pool
            # reservation (HBM pressure from live queries) wins
            self._evict_to_budget_locked(incoming=entry.nbytes)
            if not NODE_POOL.reserve_cache(entry.nbytes, entry.device):
                _count("admission_denied")
                return False
            self._entries[key] = entry
            self.resident_bytes += entry.nbytes
            _count("promotions")
        self._span(collector, "table-cache-promote", table=entry.table,
                   bytes=entry.nbytes, rows=entry.rows,
                   columns=len(colset))
        return True

    # ----------------------------------------------------------- eviction

    def _release_locked(self, entry: ResidentTable) -> None:
        from trino_tpu.exec.memory import NODE_POOL
        self.resident_bytes -= entry.nbytes
        NODE_POOL.free_cache(entry.nbytes, entry.device)

    def _evict_to_budget_locked(self, incoming: int = 0) -> None:
        while (self.resident_bytes + incoming > self.max_bytes
               and self._entries):
            key = min(self._entries,
                      key=lambda k: self._entries[k].score())
            victim = self._entries.pop(key)
            self._release_locked(victim)
            _count("evictions")

    def invalidate(self, table: TableKey) -> int:
        """PlanCache hook target: drop every resident column of the
        changed table (and its admission history — the post-change table
        must re-earn residency with fresh data)."""
        with self._lock:
            self._bump_generation_locked(table)
            stale = [k for k in self._entries if k[0] == table]
            for k in stale:
                self._release_locked(self._entries.pop(k))
            for k in [k for k in self._scan_counts if k[0] == table]:
                del self._scan_counts[k]
        if stale:
            _count("invalidations", len(stale))
        return len(stale)

    def clear(self) -> None:
        with self._lock:
            for entry in self._entries.values():
                self._release_locked(entry)
            self._entries.clear()
            self._scan_counts.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    # -------------------------------------------------------------- spans

    @staticmethod
    def _span(collector, name: str, **attrs) -> None:
        if collector is None:
            return
        try:
            with collector.span(name, kind="table-cache", **attrs):
                pass
        except Exception:
            pass


def build_pages(entry: ResidentTable, column_names: Sequence[str],
                cap: int) -> List:
    """Scan pages over a resident entry: one zero-copy page when the
    whole table fits the scan capacity (rows <= cap — the common case,
    scan capacities grow to the table's row envelope), device-side
    slices otherwise. Never touches the host."""
    import jax.numpy as jnp

    from trino_tpu.page import Column, Page
    cols = [entry.columns[n] for n in column_names]
    rows = entry.rows
    if rows <= cap:
        return [Page(tuple(cols), rows)]
    pages = []
    off = 0
    pcap = _next_pow2(cap)
    while off < rows:
        n = min(cap, rows - off)
        sliced = []
        for c in cols:
            vals = c.values[off:off + pcap]
            if vals.shape[0] < pcap:
                vals = jnp.concatenate(
                    [vals, jnp.zeros((pcap - vals.shape[0],)
                                     + vals.shape[1:], dtype=vals.dtype)])
            valid = None
            if c.valid is not None:
                valid = c.valid[off:off + pcap]
                if valid.shape[0] < pcap:
                    valid = jnp.concatenate(
                        [valid, jnp.zeros(pcap - valid.shape[0],
                                          dtype=bool)])
            sliced.append(Column(vals, valid, c.type, c.dictionary))
        pages.append(Page(tuple(sliced), n))
        off += cap
    return pages


def build_shard_page(entry: ResidentTable, column_names: Sequence[str],
                     shard: int, n_shards: int) -> Optional[object]:
    """One shard's slice only (the dispatch-loop path: each shard
    executor materializes just its own row range)."""
    pages = build_shard_pages(entry, column_names, n_shards,
                              only_shard=shard)
    return pages[shard]


def build_shard_pages(entry: ResidentTable, column_names: Sequence[str],
                      n_shards: int,
                      only_shard: Optional[int] = None
                      ) -> List[Optional[object]]:
    """Per-shard pages for mesh staging: shard s holds row range
    [split_range(rows, s, n)) of the resident columns — device-side
    slices (a cross-device placement is an ICI copy, never host bytes)."""
    import jax.numpy as jnp

    from trino_tpu.connector.spi import split_range
    from trino_tpu.page import Column, Page
    cols = [entry.columns[n] for n in column_names]
    rows = entry.rows
    spans = [split_range(rows, s, n_shards) for s in range(n_shards)]
    pcap = _next_pow2(max(max((e - s) for s, e in spans), 1))
    out: List[Optional[object]] = []
    for idx, (s, e) in enumerate(spans):
        n = e - s
        if n <= 0 or (only_shard is not None and idx != only_shard):
            out.append(None)
            continue
        sliced = []
        for c in cols:
            vals = c.values[s:s + pcap]
            if vals.shape[0] < pcap:
                vals = jnp.concatenate(
                    [vals, jnp.zeros((pcap - vals.shape[0],)
                                     + vals.shape[1:], dtype=vals.dtype)])
            valid = None
            if c.valid is not None:
                valid = c.valid[s:s + pcap]
                if valid.shape[0] < pcap:
                    valid = jnp.concatenate(
                        [valid, jnp.zeros(pcap - valid.shape[0],
                                          dtype=bool)])
            sliced.append(Column(vals, valid, c.type, c.dictionary))
        out.append(Page(tuple(sliced), n))
    return out


def table_cache_stats() -> Dict[str, int]:
    """Process counters + residency across live caches (metrics gauges
    and the system.runtime.caches 'table' row)."""
    with _STATS_LOCK:
        out = dict(_STATS)
    caches = list(_INSTANCES)
    out["entries"] = sum(len(c) for c in caches)
    out["bytes"] = sum(c.resident_bytes for c in caches)
    return out


def device_residency() -> Dict[Optional[int], int]:
    """bytes resident per device across live caches (the per-chip
    residency gauge; None = default device)."""
    out: Dict[Optional[int], int] = {}
    for cache in list(_INSTANCES):
        with cache._lock:
            for entry in cache._entries.values():
                out[entry.device] = out.get(entry.device, 0) + entry.nbytes
    return out
