"""Expression engine tests: arithmetic/Java semantics, 3VL, dictionary folding.

Mirrors reference operator/scalar tests + sql/gen PageProcessor tests.
"""

import jax
import numpy as np
import pytest

from trino_tpu import types as T
from trino_tpu.expr import (
    Call, InputRef, Literal, SpecialForm, SpecialKind,
    compile_expression, compile_filter)
from trino_tpu.expr.functions import days_from_civil
from trino_tpu.page import Page


def page_of(*cols):
    arrays, typs, valids = [], [], []
    for c in cols:
        if len(c) == 3:
            a, t, v = c
        else:
            (a, t), v = c, None
        arrays.append(np.asarray(a) if not isinstance(a, np.ndarray) else a)
        typs.append(t)
        valids.append(None if v is None else np.asarray(v, dtype=bool))
    return Page.from_numpy(arrays, typs, valids=valids)


def run(expr, page):
    col = compile_expression(expr)(page)
    return col.to_numpy(int(page.num_rows)).tolist()


def test_arithmetic_java_semantics():
    page = page_of(([7, -7, 9], T.BIGINT), ([2, 2, -4], T.BIGINT))
    a, b = InputRef(0, T.BIGINT), InputRef(1, T.BIGINT)
    # Java integer division truncates toward zero
    assert run(Call("divide", (a, b), T.BIGINT), page) == [3, -3, -2]
    # Java % takes the dividend's sign
    assert run(Call("modulus", (a, b), T.BIGINT), page) == [1, -1, 1]


def test_double_arithmetic_and_null_propagation():
    page = page_of(([1.5, 2.0, 3.0], T.DOUBLE, [1, 0, 1]),
                   ([2.0, 4.0, 5.0], T.DOUBLE))
    e = Call("multiply", (InputRef(0, T.DOUBLE), InputRef(1, T.DOUBLE)), T.DOUBLE)
    assert run(e, page) == [3.0, None, 15.0]


def test_decimal_scaled_arithmetic():
    # decimal(10,2): 1.50 + 2.25 = 3.75 ; 10.00 * 0.50 = 5.0000 -> scale 4
    page = page_of(([150, 1000], T.DecimalType(10, 2)),
                   ([225, 50], T.DecimalType(10, 2)))
    add = Call("add", (InputRef(0, T.DecimalType(10, 2)),
                       InputRef(1, T.DecimalType(10, 2))), T.DecimalType(11, 2))
    assert run(add, page) == [375, 1050]
    mul = Call("multiply", (InputRef(0, T.DecimalType(10, 2)),
                            InputRef(1, T.DecimalType(10, 2))), T.DecimalType(18, 4))
    # 1.50*2.25 = 3.3750 ; 10.00*0.50 = 5.0000 (scale 4)
    assert run(mul, page) == [33750, 50000]


def test_kleene_logic():
    # a AND b with nulls: false AND null = false; true AND null = null
    page = page_of(([True, True, False, False], T.BOOLEAN, [1, 0, 1, 0]),
                   ([True, True, True, True], T.BOOLEAN))
    e = SpecialForm(SpecialKind.AND,
                    (InputRef(0, T.BOOLEAN), InputRef(1, T.BOOLEAN)), T.BOOLEAN)
    assert run(e, page) == [True, None, False, None]
    # false AND null = false (null on the right)
    page2 = page_of(([False, True], T.BOOLEAN),
                    ([True, False], T.BOOLEAN, [0, 0]))
    assert run(e, page2) == [False, None]
    e_or = SpecialForm(SpecialKind.OR,
                       (InputRef(0, T.BOOLEAN), InputRef(1, T.BOOLEAN)), T.BOOLEAN)
    # true OR null = true
    assert run(e_or, page2) == [None, True]


def test_filter_null_is_false():
    page = page_of(([1, 2, 3], T.BIGINT, [1, 0, 1]))
    mask = compile_filter(
        Call("gt", (InputRef(0, T.BIGINT), Literal(1, T.BIGINT)), T.BOOLEAN))(page)
    assert np.asarray(mask).tolist() == [False, False, True]


def test_string_dictionary_folding():
    page = page_of((np.array(["BRASS", "COPPER", "STEEL", "BRASS"], dtype=object),
                    T.VARCHAR))
    col = InputRef(0, T.VARCHAR)
    eq = Call("eq", (col, Literal("BRASS", T.VARCHAR)), T.BOOLEAN)
    assert run(eq, page) == [True, False, False, True]
    lt = Call("lt", (col, Literal("COPPER", T.VARCHAR)), T.BOOLEAN)
    assert run(lt, page) == [True, False, False, True]
    # literal on the left flips
    gt = Call("gt", (Literal("COPPER", T.VARCHAR), col), T.BOOLEAN)
    assert run(gt, page) == [True, False, False, True]
    absent = Call("eq", (col, Literal("GOLD", T.VARCHAR)), T.BOOLEAN)
    assert run(absent, page) == [False, False, False, False]


def test_like():
    page = page_of((np.array(["PROMO BRUSHED", "STANDARD", "PROMO X", "MEDIUM"],
                             dtype=object), T.VARCHAR))
    e = Call("like", (InputRef(0, T.VARCHAR), Literal("PROMO%", T.VARCHAR)),
             T.BOOLEAN)
    assert run(e, page) == [True, False, True, False]
    e2 = Call("like", (InputRef(0, T.VARCHAR), Literal("%D%", T.VARCHAR)), T.BOOLEAN)
    assert run(e2, page) == [True, True, False, True]


def test_string_transform_substr():
    page = page_of((np.array(["alpha", "beta", "gamma"], dtype=object), T.VARCHAR))
    e = Call("substr", (InputRef(0, T.VARCHAR), Literal(1, T.INTEGER),
                        Literal(2, T.INTEGER)), T.VARCHAR)
    assert run(e, page) == ["al", "be", "ga"]
    up = Call("upper", (InputRef(0, T.VARCHAR),), T.VARCHAR)
    assert run(up, page) == ["ALPHA", "BETA", "GAMMA"]


def test_date_extract():
    days = [days_from_civil(1994, 1, 1), days_from_civil(1998, 12, 31),
            days_from_civil(1970, 1, 1), days_from_civil(1969, 7, 20)]
    page = page_of((days, T.DATE))
    col = InputRef(0, T.DATE)
    assert run(Call("year", (col,), T.BIGINT), page) == [1994, 1998, 1970, 1969]
    assert run(Call("month", (col,), T.BIGINT), page) == [1, 12, 1, 7]
    assert run(Call("day", (col,), T.BIGINT), page) == [1, 31, 1, 20]
    assert run(Call("quarter", (col,), T.BIGINT), page) == [1, 4, 1, 3]


def test_date_interval_add():
    d0 = days_from_civil(1994, 1, 31)
    page = page_of(([d0], T.DATE))
    # +1 month clamps to Feb 28
    e = Call("date_add_ym", (InputRef(0, T.DATE), Literal(1, T.INTERVAL_YEAR_MONTH)),
             T.DATE)
    assert run(e, page) == [days_from_civil(1994, 2, 28)]
    # +12 months
    e2 = Call("date_add_ym", (InputRef(0, T.DATE), Literal(12, T.INTERVAL_YEAR_MONTH)),
              T.DATE)
    assert run(e2, page) == [days_from_civil(1995, 1, 31)]


def test_case_switch():
    page = page_of(([1, 2, 3], T.BIGINT))
    col = InputRef(0, T.BIGINT)
    # CASE WHEN x=1 THEN 10 WHEN x=2 THEN 20 ELSE 0 END
    e = SpecialForm(SpecialKind.SWITCH, (
        Call("eq", (col, Literal(1, T.BIGINT)), T.BOOLEAN), Literal(10, T.BIGINT),
        Call("eq", (col, Literal(2, T.BIGINT)), T.BOOLEAN), Literal(20, T.BIGINT),
        Literal(0, T.BIGINT)), T.BIGINT)
    assert run(e, page) == [10, 20, 0]


def test_in_between_coalesce_nullif():
    page = page_of(([1, 5, 9], T.BIGINT, [1, 1, 0]))
    col = InputRef(0, T.BIGINT)
    e_in = SpecialForm(SpecialKind.IN, (col, Literal(1, T.BIGINT),
                                        Literal(9, T.BIGINT)), T.BOOLEAN)
    assert run(e_in, page) == [True, False, None]
    e_bt = SpecialForm(SpecialKind.BETWEEN,
                       (col, Literal(0, T.BIGINT), Literal(5, T.BIGINT)), T.BOOLEAN)
    assert run(e_bt, page) == [True, True, None]
    e_co = SpecialForm(SpecialKind.COALESCE, (col, Literal(-1, T.BIGINT)), T.BIGINT)
    assert run(e_co, page) == [1, 5, -1]
    # NULLIF lowers to IF(a = b, null, a) at translation time
    e_nullif = SpecialForm(SpecialKind.IF, (
        Call("eq", (col, Literal(5, T.BIGINT)), T.BOOLEAN),
        Literal(None, T.BIGINT), col), T.BIGINT)
    assert run(e_nullif, page) == [1, None, None]


def test_cast():
    page = page_of(([1.5, 2.5, -1.5], T.DOUBLE))
    e = Call("cast", (InputRef(0, T.DOUBLE),), T.BIGINT)
    # Java Math.round: floor(x + 0.5)
    assert run(e, page) == [2, 3, -1]
    page2 = page_of(([3, 4, 5], T.BIGINT))
    e2 = Call("cast", (InputRef(0, T.BIGINT),), T.DecimalType(10, 2))
    assert run(e2, page2) == [300, 400, 500]


def test_whole_expression_under_jit():
    # q6-shaped predicate compiled once, fused under jit
    page = page_of(([100.0, 200.0, 300.0], T.DOUBLE),
                   ([0.05, 0.07, 0.09], T.DOUBLE))
    price, disc = InputRef(0, T.DOUBLE), InputRef(1, T.DOUBLE)
    pred = SpecialForm(SpecialKind.AND, (
        Call("ge", (disc, Literal(0.05, T.DOUBLE)), T.BOOLEAN),
        Call("le", (disc, Literal(0.07, T.DOUBLE)), T.BOOLEAN)), T.BOOLEAN)
    proj = Call("multiply", (price, disc), T.DOUBLE)

    @jax.jit
    def fragment(p):
        filtered = p.filter(compile_filter(pred)(p))
        col = compile_expression(proj)(filtered)
        return filtered, col

    filtered, col = fragment(page)
    assert int(filtered.num_rows) == 2
    np.testing.assert_allclose(
        np.asarray(col.values)[:2], [100.0 * 0.05, 200.0 * 0.07])


def test_decimal_divide_no_double_rounding():
    # 0.2450 / 0.50 at output scale 0: true quotient 0.49 -> rounds to 0
    page = page_of(([2450], T.DecimalType(10, 4)), ([50], T.DecimalType(10, 2)))
    e = Call("divide", (InputRef(0, T.DecimalType(10, 4)),
                        InputRef(1, T.DecimalType(10, 2))), T.DecimalType(10, 0))
    assert run(e, page) == [0]


def test_round_digits():
    page = page_of(([1.2345, -1.2345, 2.675], T.DOUBLE))
    e = Call("round_digits", (InputRef(0, T.DOUBLE), Literal(2, T.INTEGER)),
             T.DOUBLE)
    got = run(e, page)
    assert abs(got[0] - 1.23) < 1e-12 and abs(got[1] + 1.23) < 1e-12
