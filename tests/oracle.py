"""sqlite-backed correctness oracle.

Reference parity: testing/trino-testing H2QueryRunner.java — run the same SQL
on the same data in a second engine and diff rows. sqlite is the stdlib
stand-in for H2 (duckdb is not in the image).
"""

from __future__ import annotations

import datetime
import decimal
import math
import sqlite3
from typing import List, Tuple

import numpy as np

from trino_tpu import types as T
from trino_tpu.connector import tpch

_EPOCH = datetime.date(1970, 1, 1)


def _sql_value(v, typ: T.Type):
    if isinstance(typ, T.DecimalType):
        # keep scaled ints; sqlite works in exact integers then
        return int(v)
    if isinstance(typ, (T.DateType,)):
        return int(v)
    return v.item() if isinstance(v, np.generic) else v


def _load_sqlite(connector_module, sf: float, value_fn=None,
                 index_pred=None) -> sqlite3.Connection:
    """Load one generator connector's tables into sqlite. Default value
    mapping keeps decimals as scaled ints (exact integer arithmetic;
    tests rescale in SQL); `value_fn` overrides per-value conversion and
    `index_pred(column) -> bool` selects columns to index."""
    value_fn = value_fn or _sql_value
    conn = sqlite3.connect(":memory:")
    index_ddl = []
    for table, (cols, _) in connector_module.TABLES.items():
        data = connector_module.get_table(table, sf)
        names = [c for c, _ in cols]
        conn.execute(f"CREATE TABLE {table} ({', '.join(names)})")
        arrays = [data[c] for c in names]
        typs = [ty for _, ty in cols]
        rows = zip(*[
            [value_fn(v, ty) for v in arr]
            for arr, ty in zip(arrays, typs)])
        conn.executemany(
            f"INSERT INTO {table} VALUES ({', '.join('?' * len(names))})",
            rows)
        if index_pred is not None:
            index_ddl.extend(
                f"CREATE INDEX idx_{table}_{c} ON {table}({c})"
                for c in names if index_pred(c))
    for ddl in index_ddl:
        conn.execute(ddl)
    conn.commit()
    return conn


def load_tpch_sqlite(sf: float = 0.01) -> sqlite3.Connection:
    return _load_sqlite(tpch, sf)


def load_tpcds_sqlite(sf: float = 0.01) -> sqlite3.Connection:
    from trino_tpu.connector import tpcds
    return _load_sqlite(tpcds, sf)


def _sql_value_float(v, typ: T.Type):
    if isinstance(typ, T.DecimalType):
        # floats instead of scaled ints: lets UNMODIFIED benchmark SQL
        # (decimal literals, arbitrary arithmetic) run on sqlite; the
        # comparison tolerates the float grid (_row_eq dec-vs-float)
        return int(v) / (10 ** typ.scale)
    if isinstance(typ, (T.DateType,)):
        return int(v)
    return v.item() if isinstance(v, np.generic) else v


class _StddevSamp:
    def __init__(self):
        self.vals = []

    def step(self, v):
        if v is not None:
            self.vals.append(float(v))

    def finalize(self):
        n = len(self.vals)
        if n < 2:
            return None
        m = sum(self.vals) / n
        return math.sqrt(sum((x - m) ** 2 for x in self.vals) / (n - 1))


def load_tpcds_sqlite_float(sf: float = 0.01) -> sqlite3.Connection:
    """Float-decimal variant: lets UNMODIFIED benchmark SQL run on
    sqlite, with surrogate-key indexes (sqlite plans nested-loop joins
    and the benchmark queries join every fact to 3-8 dimensions)."""
    from trino_tpu.connector import tpcds
    conn = _load_sqlite(
        tpcds, sf, value_fn=_sql_value_float,
        index_pred=lambda c: c.endswith("_sk")
        or c.endswith("_ticket_number") or c.endswith("_order_number"))
    # benchmark-SQL helpers sqlite lacks
    conn.create_function(
        "concat", -1,
        lambda *a: None if any(x is None for x in a)
        else "".join(str(x) for x in a))
    conn.create_aggregate("stddev_samp", 1, _StddevSamp)
    return conn


def normalize(rows: List[Tuple], sort: bool = False) -> List[Tuple]:
    """Canonical form for comparison: Decimal -> scaled int where exact,
    floats rounded, dates -> ordinal ints."""
    out = []
    for row in rows:
        canon = []
        for v in row:
            if isinstance(v, decimal.Decimal):
                exp = v.as_tuple().exponent
                if exp < 0:
                    # carry the scale: comparing against a float oracle
                    # needs to know the engine's decimal rounding grid
                    canon.append(("dec", int(v.scaleb(-exp)), -exp))
                else:
                    canon.append(("dec", int(v), 0))
            elif isinstance(v, float):
                if math.isnan(v):
                    canon.append(("f", "nan"))
                else:
                    canon.append(("f", round(v, 6)))
            elif isinstance(v, datetime.date):
                canon.append(("d", (v - _EPOCH).days))
            else:
                canon.append(v)
        out.append(tuple(canon))
    if sort:
        out.sort(key=_row_sort_key)
    return out


def _row_sort_key(row: Tuple):
    """Representation-independent multiset ordering: a decimal and the
    float it equals must sort IDENTICALLY on both sides, or engine/oracle
    row pairing drifts and assert_same compares the wrong rows."""
    key = []
    for v in row:
        if isinstance(v, tuple) and v:
            if v[0] == "dec":
                scale = v[2] if len(v) > 2 else 0
                key.append(("n", round(v[1] / (10 ** scale), 4)))
                continue
            if v[0] == "f":
                key.append(("n", float("inf") if v[1] == "nan"
                            else round(float(v[1]), 4)))
                continue
            if v[0] == "d":
                key.append(("n", float(v[1])))
                continue
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            key.append(("n", round(float(v), 4)))
        elif v is None:
            key.append(("~",))
        else:
            key.append(("s", str(v)))
    return key


def assert_same(engine_rows: List[Tuple], oracle_rows: List[Tuple],
                ordered: bool):
    a = normalize(engine_rows, sort=not ordered)
    b = normalize(oracle_rows, sort=not ordered)
    assert len(a) == len(b), \
        f"row count mismatch: engine {len(a)} vs oracle {len(b)}\n" \
        f"engine[:5]={a[:5]}\noracle[:5]={b[:5]}"
    for i, (ra, rb) in enumerate(zip(a, b)):
        assert _row_eq(ra, rb), \
            f"row {i} differs:\n  engine: {ra}\n  oracle: {rb}"


def _row_eq(a: Tuple, b: Tuple) -> bool:
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        if x is None or y is None:
            if x is not y:     # NULL matches only NULL
                return False
            continue
        if isinstance(x, tuple) and x and x[0] == "f":
            if not (isinstance(y, tuple) and y and y[0] == "f"):
                # oracle may return int where engine returns float
                y = ("f", round(float(y[1] if isinstance(y, tuple) else y), 6))
            xa, ya = x[1], y[1]
            if xa == "nan" or ya == "nan":
                if xa != ya:
                    return False
                continue
            if ya == 0:
                if abs(xa) > 1e-9:
                    return False
            elif abs(xa - ya) / max(abs(xa), abs(ya)) > 1e-9:
                return False
        elif isinstance(x, tuple) and x and x[0] == "dec":
            if isinstance(y, tuple) and y and y[0] == "f":
                # engine decimal vs float oracle (e.g. decimal division —
                # Trino types q8's mkt_share decimal(38,4)): equal when the
                # float rounds onto the decimal's grid. Inclusive half-step
                # bound: an avg landing EXACTLY on .xx5 rounds HALF_UP on
                # the engine while the float keeps it — still equal.
                scale = x[2] if len(x) > 2 else 0
                if abs(x[1] - y[1] * 10 ** scale) > 0.5 + 1e-6:
                    return False
            else:
                yv = y[1] if isinstance(y, tuple) else y
                if int(x[1]) != int(yv):
                    return False
        elif isinstance(x, tuple) and x and x[0] == "d":
            yv = y[1] if isinstance(y, tuple) else y
            if int(x[1]) != int(yv):
                return False
        else:
            if x != y:
                return False
    return True
