"""TPC-DS generator connector (subset): deterministic in-memory data.

Reference parity: plugin/trino-tpcds (TpcdsMetadata.java,
TpcdsRecordSetProvider.java) — the reference wraps the teradata dsdgen port;
here a seeded NumPy generator produces the 16 tables the decision-support
benchmark ladder needs (q64/q72 and the common store_sales family), with
spec-shaped schemas, consistent foreign keys, and the fixed date_dim
calendar. Exact dsdgen bitstreams are not load-bearing: correctness is
asserted engine-vs-oracle on the SAME generated rows (the H2QueryRunner
pattern, as with the tpch connector).

Layout conventions match connector/tpch.py: varchars dictionary-encoded,
dates as int32 days since epoch, decimals as scaled int64.
"""

from __future__ import annotations

import math
import zlib
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from trino_tpu import types as T
from trino_tpu.connector.spi import (
    ColumnHandle, ColumnMetadata, Connector, ConnectorMetadata,
    ConnectorPageSource, ConnectorSplitManager, ConnectorTableHandle,
    ColumnStatistics, SchemaTableName, Split, TableMetadata, TableStatistics,
    pad_to_capacity, split_range)
from trino_tpu.expr.functions import days_from_civil
from trino_tpu.page import Column, Dictionary, Page

_D7_2 = T.DecimalType(7, 2)

SCHEMAS = {"tiny": 0.01, "sf1": 1.0, "sf10": 10.0, "sf100": 100.0}

# date_dim is the fixed TPC-DS calendar: 1900-01-02 .. 2100-01-01,
# d_date_sk = Julian day number starting at 2415022
_DATE_ROWS = 73049
_JULIAN_BASE = 2415022
_EPOCH_OFFSET = days_from_civil(1900, 1, 2)   # d_date of sk _JULIAN_BASE

# table -> (columns, base row count at sf1; None = fixed/derived)
_D5_2 = T.DecimalType(5, 2)

TABLES: Dict[str, tuple] = {
    "date_dim": ((
        ("d_date_sk", T.BIGINT), ("d_date_id", T.VarcharType(16)),
        ("d_date", T.DATE), ("d_month_seq", T.BIGINT),
        ("d_week_seq", T.BIGINT), ("d_quarter_seq", T.BIGINT),
        ("d_year", T.BIGINT), ("d_dow", T.BIGINT), ("d_moy", T.BIGINT),
        ("d_dom", T.BIGINT), ("d_qoy", T.BIGINT),
        ("d_fy_year", T.BIGINT), ("d_fy_quarter_seq", T.BIGINT),
        ("d_fy_week_seq", T.BIGINT),
        ("d_day_name", T.VarcharType(9)),
        ("d_quarter_name", T.VarcharType(6)),
        ("d_holiday", T.VarcharType(1)),
        ("d_weekend", T.VarcharType(1)),
        ("d_following_holiday", T.VarcharType(1)),
        ("d_first_dom", T.BIGINT), ("d_last_dom", T.BIGINT),
        ("d_same_day_ly", T.BIGINT), ("d_same_day_lq", T.BIGINT),
        ("d_current_day", T.VarcharType(1)),
        ("d_current_week", T.VarcharType(1)),
        ("d_current_month", T.VarcharType(1)),
        ("d_current_quarter", T.VarcharType(1)),
        ("d_current_year", T.VarcharType(1))), None),
    "time_dim": ((
        ("t_time_sk", T.BIGINT), ("t_time_id", T.VarcharType(16)),
        ("t_time", T.BIGINT), ("t_hour", T.BIGINT),
        ("t_minute", T.BIGINT), ("t_second", T.BIGINT),
        ("t_am_pm", T.VarcharType(2)), ("t_meal_time", T.VarcharType(20)),
        ("t_shift", T.VarcharType(20)),
        ("t_sub_shift", T.VarcharType(20))), None),  # fixed 86400
    "item": ((
        ("i_item_sk", T.BIGINT), ("i_item_id", T.VarcharType(16)),
        ("i_rec_start_date", T.DATE), ("i_rec_end_date", T.DATE),
        ("i_item_desc", T.VarcharType(200)), ("i_current_price", _D7_2),
        ("i_wholesale_cost", _D7_2), ("i_brand_id", T.BIGINT),
        ("i_brand", T.VarcharType(50)), ("i_class_id", T.BIGINT),
        ("i_class", T.VarcharType(50)), ("i_category_id", T.BIGINT),
        ("i_category", T.VarcharType(50)), ("i_manufact_id", T.BIGINT),
        ("i_manufact", T.VarcharType(50)), ("i_size", T.VarcharType(20)),
        ("i_formulation", T.VarcharType(20)), ("i_color", T.VarcharType(20)),
        ("i_units", T.VarcharType(10)), ("i_container", T.VarcharType(10)),
        ("i_manager_id", T.BIGINT),
        ("i_product_name", T.VarcharType(50))), 18_000),
    "customer": ((
        ("c_customer_sk", T.BIGINT), ("c_customer_id", T.VarcharType(16)),
        ("c_current_cdemo_sk", T.BIGINT), ("c_current_hdemo_sk", T.BIGINT),
        ("c_current_addr_sk", T.BIGINT), ("c_first_shipto_date_sk", T.BIGINT),
        ("c_first_sales_date_sk", T.BIGINT),
        ("c_salutation", T.VarcharType(10)),
        ("c_first_name", T.VarcharType(20)),
        ("c_last_name", T.VarcharType(30)),
        ("c_preferred_cust_flag", T.VarcharType(1)),
        ("c_birth_day", T.BIGINT), ("c_birth_month", T.BIGINT),
        ("c_birth_year", T.BIGINT),
        ("c_birth_country", T.VarcharType(20)),
        ("c_login", T.VarcharType(13)),
        ("c_email_address", T.VarcharType(50)),
        ("c_last_review_date_sk", T.BIGINT)), 100_000),
    "customer_address": ((
        ("ca_address_sk", T.BIGINT), ("ca_address_id", T.VarcharType(16)),
        ("ca_street_number", T.VarcharType(10)),
        ("ca_street_name", T.VarcharType(60)),
        ("ca_street_type", T.VarcharType(15)),
        ("ca_suite_number", T.VarcharType(10)),
        ("ca_city", T.VarcharType(60)), ("ca_county", T.VarcharType(30)),
        ("ca_state", T.VarcharType(2)), ("ca_zip", T.VarcharType(10)),
        ("ca_country", T.VarcharType(20)),
        ("ca_gmt_offset", _D5_2),
        ("ca_location_type", T.VarcharType(20))), 50_000),
    "customer_demographics": ((
        ("cd_demo_sk", T.BIGINT), ("cd_gender", T.VarcharType(1)),
        ("cd_marital_status", T.VarcharType(1)),
        ("cd_education_status", T.VarcharType(20)),
        ("cd_purchase_estimate", T.BIGINT),
        ("cd_credit_rating", T.VarcharType(10)),
        ("cd_dep_count", T.BIGINT),
        ("cd_dep_employed_count", T.BIGINT),
        ("cd_dep_college_count", T.BIGINT)), 1_920_800),
    "household_demographics": ((
        ("hd_demo_sk", T.BIGINT), ("hd_income_band_sk", T.BIGINT),
        ("hd_buy_potential", T.VarcharType(15)), ("hd_dep_count", T.BIGINT),
        ("hd_vehicle_count", T.BIGINT)), None),   # fixed 7200
    "income_band": ((
        ("ib_income_band_sk", T.BIGINT), ("ib_lower_bound", T.BIGINT),
        ("ib_upper_bound", T.BIGINT)), None),      # fixed 20
    "store": ((
        ("s_store_sk", T.BIGINT), ("s_store_id", T.VarcharType(16)),
        ("s_rec_start_date", T.DATE), ("s_rec_end_date", T.DATE),
        ("s_closed_date_sk", T.BIGINT),
        ("s_store_name", T.VarcharType(50)),
        ("s_number_employees", T.BIGINT), ("s_floor_space", T.BIGINT),
        ("s_hours", T.VarcharType(20)), ("s_manager", T.VarcharType(40)),
        ("s_market_id", T.BIGINT),
        ("s_geography_class", T.VarcharType(100)),
        ("s_market_desc", T.VarcharType(100)),
        ("s_market_manager", T.VarcharType(40)),
        ("s_division_id", T.BIGINT), ("s_division_name", T.VarcharType(50)),
        ("s_company_id", T.BIGINT), ("s_company_name", T.VarcharType(50)),
        ("s_street_number", T.VarcharType(10)),
        ("s_street_name", T.VarcharType(60)),
        ("s_street_type", T.VarcharType(15)),
        ("s_suite_number", T.VarcharType(10)),
        ("s_city", T.VarcharType(60)),
        ("s_county", T.VarcharType(30)), ("s_state", T.VarcharType(2)),
        ("s_zip", T.VarcharType(10)), ("s_country", T.VarcharType(20)),
        ("s_gmt_offset", _D5_2),
        ("s_tax_precentage", _D5_2)), 12),  # spec's own spelling
    "warehouse": ((
        ("w_warehouse_sk", T.BIGINT), ("w_warehouse_id", T.VarcharType(16)),
        ("w_warehouse_name", T.VarcharType(20)),
        ("w_warehouse_sq_ft", T.BIGINT),
        ("w_street_number", T.VarcharType(10)),
        ("w_street_name", T.VarcharType(60)),
        ("w_street_type", T.VarcharType(15)),
        ("w_suite_number", T.VarcharType(10)),
        ("w_city", T.VarcharType(60)), ("w_county", T.VarcharType(30)),
        ("w_state", T.VarcharType(2)), ("w_zip", T.VarcharType(10)),
        ("w_country", T.VarcharType(20)),
        ("w_gmt_offset", _D5_2)), 5),
    "promotion": ((
        ("p_promo_sk", T.BIGINT), ("p_promo_id", T.VarcharType(16)),
        ("p_start_date_sk", T.BIGINT), ("p_end_date_sk", T.BIGINT),
        ("p_item_sk", T.BIGINT), ("p_cost", T.DecimalType(15, 2)),
        ("p_response_target", T.BIGINT),
        ("p_promo_name", T.VarcharType(50)),
        ("p_channel_dmail", T.VarcharType(1)),
        ("p_channel_email", T.VarcharType(1)),
        ("p_channel_catalog", T.VarcharType(1)),
        ("p_channel_tv", T.VarcharType(1)),
        ("p_channel_radio", T.VarcharType(1)),
        ("p_channel_press", T.VarcharType(1)),
        ("p_channel_event", T.VarcharType(1)),
        ("p_channel_demo", T.VarcharType(1)),
        ("p_channel_details", T.VarcharType(100)),
        ("p_purpose", T.VarcharType(15)),
        ("p_discount_active", T.VarcharType(1))), 300),
    "web_site": ((
        ("web_site_sk", T.BIGINT), ("web_site_id", T.VarcharType(16)),
        ("web_rec_start_date", T.DATE), ("web_rec_end_date", T.DATE),
        ("web_name", T.VarcharType(50)),
        ("web_open_date_sk", T.BIGINT), ("web_close_date_sk", T.BIGINT),
        ("web_class", T.VarcharType(50)), ("web_manager", T.VarcharType(40)),
        ("web_mkt_id", T.BIGINT), ("web_mkt_class", T.VarcharType(50)),
        ("web_mkt_desc", T.VarcharType(100)),
        ("web_market_manager", T.VarcharType(40)),
        ("web_company_id", T.BIGINT),
        ("web_company_name", T.VarcharType(50)),
        ("web_street_number", T.VarcharType(10)),
        ("web_street_name", T.VarcharType(60)),
        ("web_street_type", T.VarcharType(15)),
        ("web_suite_number", T.VarcharType(10)),
        ("web_city", T.VarcharType(60)), ("web_county", T.VarcharType(30)),
        ("web_state", T.VarcharType(2)), ("web_zip", T.VarcharType(10)),
        ("web_country", T.VarcharType(20)),
        ("web_gmt_offset", _D5_2),
        ("web_tax_percentage", _D5_2)), 30),
    "web_page": ((
        ("wp_web_page_sk", T.BIGINT), ("wp_web_page_id", T.VarcharType(16)),
        ("wp_rec_start_date", T.DATE), ("wp_rec_end_date", T.DATE),
        ("wp_creation_date_sk", T.BIGINT), ("wp_access_date_sk", T.BIGINT),
        ("wp_autogen_flag", T.VarcharType(1)), ("wp_customer_sk", T.BIGINT),
        ("wp_url", T.VarcharType(100)), ("wp_type", T.VarcharType(50)),
        ("wp_char_count", T.BIGINT), ("wp_link_count", T.BIGINT),
        ("wp_image_count", T.BIGINT),
        ("wp_max_ad_count", T.BIGINT)), 60),
    "catalog_page": ((
        ("cp_catalog_page_sk", T.BIGINT),
        ("cp_catalog_page_id", T.VarcharType(16)),
        ("cp_start_date_sk", T.BIGINT), ("cp_end_date_sk", T.BIGINT),
        ("cp_department", T.VarcharType(50)),
        ("cp_catalog_number", T.BIGINT),
        ("cp_catalog_page_number", T.BIGINT),
        ("cp_description", T.VarcharType(100)),
        ("cp_type", T.VarcharType(100))), 11_718),
    "call_center": ((
        ("cc_call_center_sk", T.BIGINT),
        ("cc_call_center_id", T.VarcharType(16)),
        ("cc_rec_start_date", T.DATE), ("cc_rec_end_date", T.DATE),
        ("cc_closed_date_sk", T.BIGINT), ("cc_open_date_sk", T.BIGINT),
        ("cc_name", T.VarcharType(50)), ("cc_class", T.VarcharType(50)),
        ("cc_employees", T.BIGINT), ("cc_sq_ft", T.BIGINT),
        ("cc_hours", T.VarcharType(20)), ("cc_manager", T.VarcharType(40)),
        ("cc_mkt_id", T.BIGINT), ("cc_mkt_class", T.VarcharType(50)),
        ("cc_mkt_desc", T.VarcharType(100)),
        ("cc_market_manager", T.VarcharType(40)),
        ("cc_division", T.BIGINT), ("cc_division_name", T.VarcharType(50)),
        ("cc_company", T.BIGINT), ("cc_company_name", T.VarcharType(50)),
        ("cc_street_number", T.VarcharType(10)),
        ("cc_street_name", T.VarcharType(60)),
        ("cc_street_type", T.VarcharType(15)),
        ("cc_suite_number", T.VarcharType(10)),
        ("cc_city", T.VarcharType(60)), ("cc_county", T.VarcharType(30)),
        ("cc_state", T.VarcharType(2)), ("cc_zip", T.VarcharType(10)),
        ("cc_country", T.VarcharType(20)),
        ("cc_gmt_offset", _D5_2),
        ("cc_tax_percentage", _D5_2)), 6),
    "ship_mode": ((
        ("sm_ship_mode_sk", T.BIGINT),
        ("sm_ship_mode_id", T.VarcharType(16)),
        ("sm_type", T.VarcharType(30)), ("sm_code", T.VarcharType(10)),
        ("sm_carrier", T.VarcharType(20)),
        ("sm_contract", T.VarcharType(20))), None),  # fixed 20
    "reason": ((
        ("r_reason_sk", T.BIGINT), ("r_reason_id", T.VarcharType(16)),
        ("r_reason_desc", T.VarcharType(100))), 35),
    "inventory": ((
        ("inv_date_sk", T.BIGINT), ("inv_item_sk", T.BIGINT),
        ("inv_warehouse_sk", T.BIGINT),
        ("inv_quantity_on_hand", T.BIGINT)), None),  # items x wh x weeks
    "store_sales": ((
        ("ss_sold_date_sk", T.BIGINT), ("ss_sold_time_sk", T.BIGINT),
        ("ss_item_sk", T.BIGINT),
        ("ss_customer_sk", T.BIGINT), ("ss_cdemo_sk", T.BIGINT),
        ("ss_hdemo_sk", T.BIGINT), ("ss_addr_sk", T.BIGINT),
        ("ss_store_sk", T.BIGINT), ("ss_promo_sk", T.BIGINT),
        ("ss_ticket_number", T.BIGINT), ("ss_quantity", T.BIGINT),
        ("ss_wholesale_cost", _D7_2), ("ss_list_price", _D7_2),
        ("ss_sales_price", _D7_2), ("ss_ext_discount_amt", _D7_2),
        ("ss_ext_sales_price", _D7_2), ("ss_ext_wholesale_cost", _D7_2),
        ("ss_ext_list_price", _D7_2), ("ss_ext_tax", _D7_2),
        ("ss_coupon_amt", _D7_2),
        ("ss_net_paid", _D7_2), ("ss_net_paid_inc_tax", _D7_2),
        ("ss_net_profit", _D7_2)), 2_880_404),
    "store_returns": ((
        ("sr_returned_date_sk", T.BIGINT), ("sr_return_time_sk", T.BIGINT),
        ("sr_item_sk", T.BIGINT),
        ("sr_customer_sk", T.BIGINT), ("sr_cdemo_sk", T.BIGINT),
        ("sr_hdemo_sk", T.BIGINT), ("sr_addr_sk", T.BIGINT),
        ("sr_store_sk", T.BIGINT), ("sr_reason_sk", T.BIGINT),
        ("sr_ticket_number", T.BIGINT),
        ("sr_return_quantity", T.BIGINT), ("sr_return_amt", _D7_2),
        ("sr_return_tax", _D7_2), ("sr_return_amt_inc_tax", _D7_2),
        ("sr_fee", _D7_2), ("sr_return_ship_cost", _D7_2),
        ("sr_refunded_cash", _D7_2), ("sr_reversed_charge", _D7_2),
        ("sr_store_credit", _D7_2),
        ("sr_net_loss", _D7_2)), None),            # ~10% of store_sales
    "catalog_sales": ((
        ("cs_sold_date_sk", T.BIGINT), ("cs_sold_time_sk", T.BIGINT),
        ("cs_ship_date_sk", T.BIGINT),
        ("cs_bill_customer_sk", T.BIGINT), ("cs_bill_cdemo_sk", T.BIGINT),
        ("cs_bill_hdemo_sk", T.BIGINT), ("cs_bill_addr_sk", T.BIGINT),
        ("cs_ship_customer_sk", T.BIGINT), ("cs_ship_cdemo_sk", T.BIGINT),
        ("cs_ship_hdemo_sk", T.BIGINT), ("cs_ship_addr_sk", T.BIGINT),
        ("cs_call_center_sk", T.BIGINT), ("cs_catalog_page_sk", T.BIGINT),
        ("cs_ship_mode_sk", T.BIGINT),
        ("cs_warehouse_sk", T.BIGINT), ("cs_item_sk", T.BIGINT),
        ("cs_promo_sk", T.BIGINT), ("cs_order_number", T.BIGINT),
        ("cs_quantity", T.BIGINT), ("cs_wholesale_cost", _D7_2),
        ("cs_list_price", _D7_2), ("cs_sales_price", _D7_2),
        ("cs_ext_discount_amt", _D7_2), ("cs_ext_sales_price", _D7_2),
        ("cs_ext_wholesale_cost", _D7_2), ("cs_ext_list_price", _D7_2),
        ("cs_ext_tax", _D7_2), ("cs_coupon_amt", _D7_2),
        ("cs_ext_ship_cost", _D7_2),
        ("cs_net_paid", _D7_2), ("cs_net_paid_inc_tax", _D7_2),
        ("cs_net_paid_inc_ship", _D7_2),
        ("cs_net_paid_inc_ship_tax", _D7_2),
        ("cs_net_profit", _D7_2)), 1_441_548),
    "catalog_returns": ((
        ("cr_returned_date_sk", T.BIGINT),
        ("cr_returned_time_sk", T.BIGINT), ("cr_item_sk", T.BIGINT),
        ("cr_refunded_customer_sk", T.BIGINT),
        ("cr_refunded_cdemo_sk", T.BIGINT),
        ("cr_refunded_hdemo_sk", T.BIGINT),
        ("cr_refunded_addr_sk", T.BIGINT),
        ("cr_returning_customer_sk", T.BIGINT),
        ("cr_returning_cdemo_sk", T.BIGINT),
        ("cr_returning_hdemo_sk", T.BIGINT),
        ("cr_returning_addr_sk", T.BIGINT),
        ("cr_call_center_sk", T.BIGINT), ("cr_catalog_page_sk", T.BIGINT),
        ("cr_ship_mode_sk", T.BIGINT), ("cr_warehouse_sk", T.BIGINT),
        ("cr_reason_sk", T.BIGINT), ("cr_order_number", T.BIGINT),
        ("cr_return_quantity", T.BIGINT), ("cr_return_amount", _D7_2),
        ("cr_return_tax", _D7_2), ("cr_return_amt_inc_tax", _D7_2),
        ("cr_fee", _D7_2), ("cr_return_ship_cost", _D7_2),
        ("cr_refunded_cash", _D7_2), ("cr_reversed_charge", _D7_2),
        ("cr_store_credit", _D7_2), ("cr_net_loss", _D7_2)), None),
    "web_sales": ((
        ("ws_sold_date_sk", T.BIGINT), ("ws_sold_time_sk", T.BIGINT),
        ("ws_ship_date_sk", T.BIGINT), ("ws_item_sk", T.BIGINT),
        ("ws_bill_customer_sk", T.BIGINT), ("ws_bill_cdemo_sk", T.BIGINT),
        ("ws_bill_hdemo_sk", T.BIGINT), ("ws_bill_addr_sk", T.BIGINT),
        ("ws_ship_customer_sk", T.BIGINT), ("ws_ship_cdemo_sk", T.BIGINT),
        ("ws_ship_hdemo_sk", T.BIGINT), ("ws_ship_addr_sk", T.BIGINT),
        ("ws_web_page_sk", T.BIGINT), ("ws_web_site_sk", T.BIGINT),
        ("ws_ship_mode_sk", T.BIGINT), ("ws_warehouse_sk", T.BIGINT),
        ("ws_promo_sk", T.BIGINT), ("ws_order_number", T.BIGINT),
        ("ws_quantity", T.BIGINT), ("ws_wholesale_cost", _D7_2),
        ("ws_list_price", _D7_2), ("ws_sales_price", _D7_2),
        ("ws_ext_discount_amt", _D7_2), ("ws_ext_sales_price", _D7_2),
        ("ws_ext_wholesale_cost", _D7_2), ("ws_ext_list_price", _D7_2),
        ("ws_ext_tax", _D7_2), ("ws_coupon_amt", _D7_2),
        ("ws_ext_ship_cost", _D7_2),
        ("ws_net_paid", _D7_2), ("ws_net_paid_inc_tax", _D7_2),
        ("ws_net_paid_inc_ship", _D7_2),
        ("ws_net_paid_inc_ship_tax", _D7_2),
        ("ws_net_profit", _D7_2)), 719_384),
    "web_returns": ((
        ("wr_returned_date_sk", T.BIGINT),
        ("wr_returned_time_sk", T.BIGINT), ("wr_item_sk", T.BIGINT),
        ("wr_refunded_customer_sk", T.BIGINT),
        ("wr_refunded_cdemo_sk", T.BIGINT),
        ("wr_refunded_hdemo_sk", T.BIGINT),
        ("wr_refunded_addr_sk", T.BIGINT),
        ("wr_returning_customer_sk", T.BIGINT),
        ("wr_returning_cdemo_sk", T.BIGINT),
        ("wr_returning_hdemo_sk", T.BIGINT),
        ("wr_returning_addr_sk", T.BIGINT),
        ("wr_web_page_sk", T.BIGINT), ("wr_reason_sk", T.BIGINT),
        ("wr_order_number", T.BIGINT),
        ("wr_return_quantity", T.BIGINT), ("wr_return_amt", _D7_2),
        ("wr_return_tax", _D7_2), ("wr_return_amt_inc_tax", _D7_2),
        ("wr_fee", _D7_2), ("wr_return_ship_cost", _D7_2),
        ("wr_refunded_cash", _D7_2), ("wr_reversed_charge", _D7_2),
        ("wr_account_credit", _D7_2), ("wr_net_loss", _D7_2)), None),
}

_CATEGORIES = ["Books", "Children", "Electronics", "Home", "Jewelry",
               "Men", "Music", "Shoes", "Sports", "Women"]
_CLASSES = ["accent", "accessories", "archery", "arts", "athletic",
            "baseball", "bathroom", "bedding", "birdal", "blinds/shades",
            "camcorders", "classical", "computers", "country", "curtains",
            "decor", "diamonds", "dresses", "estate", "fiction", "fishing",
            "fitness", "flatware", "football", "fragrances", "furniture"]
_COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
           "black", "blanched", "blue", "blush", "brown", "burlywood",
           "burnished", "chartreuse", "chiffon", "chocolate", "coral",
           "cornflower", "cornsilk", "cream", "cyan", "dark", "deep", "dim",
           "dodger", "drab", "firebrick", "floral", "forest", "frosted",
           "gainsboro", "ghost", "goldenrod", "green", "grey", "honeydew",
           "hot", "indian", "ivory", "khaki", "lace", "lavender", "lawn",
           "lemon", "light", "lime", "linen", "magenta", "maroon", "medium",
           "metallic", "midnight", "mint", "misty", "moccasin", "navajo",
           "navy", "olive", "orange", "orchid", "pale", "papaya", "peach",
           "peru", "pink", "plum", "powder", "puff", "purple", "red", "rose",
           "rosy", "royal", "saddle", "salmon", "sandy", "seashell",
           "sienna", "sky", "slate", "smoke", "snow", "spring", "steel",
           "tan", "thistle", "tomato", "turquoise", "violet", "wheat",
           "white", "yellow"]
_SIZES = ["N/A", "extra large", "large", "medium", "petite", "small"]
_UNITS = ["Box", "Bunch", "Bundle", "Carton", "Case", "Cup", "Dozen",
          "Dram", "Each", "Gram", "Gross", "Lb", "N/A", "Ounce", "Oz",
          "Pallet", "Pound", "Tbl", "Ton", "Tsp", "Unknown"]
_STATES = ["AL", "CA", "FL", "GA", "IL", "IN", "KS", "KY", "LA", "MI",
           "MN", "MO", "NC", "NY", "OH", "OK", "PA", "SC", "TN", "TX",
           "VA", "WA", "WI"]
_BUY_POTENTIAL = [">10000", "0-500", "1001-5000", "501-1000", "5001-10000",
                  "Unknown"]
_EDUCATION = ["2 yr Degree", "4 yr Degree", "Advanced Degree", "College",
              "Primary", "Secondary", "Unknown"]
_CREDIT = ["Good", "High Risk", "Low Risk", "Unknown"]
_DAY_NAMES = ["Sunday", "Monday", "Tuesday", "Wednesday", "Thursday",
              "Friday", "Saturday"]
_FIRST_NAMES = ["James", "John", "Robert", "Michael", "William", "David",
                "Mary", "Patricia", "Linda", "Barbara", "Elizabeth",
                "Jennifer", "Maria", "Susan", "Margaret", "Dorothy"]
_LAST_NAMES = ["Smith", "Johnson", "Williams", "Brown", "Jones", "Miller",
               "Davis", "Garcia", "Rodriguez", "Wilson", "Martinez",
               "Anderson", "Taylor", "Thomas", "Hernandez", "Moore"]
_CITIES = ["Fairview", "Midway", "Oak Grove", "Five Points", "Centerville",
           "Riverside", "Pleasant Hill", "Liberty", "Salem", "Union",
           "Greenville", "Franklin", "Spring Hill", "Shiloh", "Clinton"]

# sales span the calendar years 1998-2002 (dsdgen's active window)
_SALES_MIN = days_from_civil(1998, 1, 1) - _EPOCH_OFFSET + _JULIAN_BASE
_SALES_MAX = days_from_civil(2002, 12, 31) - _EPOCH_OFFSET + _JULIAN_BASE


def _table_seed(table: str, sf: float) -> int:
    return zlib.crc32(f"tpcds:{table}:{round(sf * 1000)}".encode())


def _scaled(base: int, sf: float, lo: int = 1) -> int:
    return max(lo, int(base * sf))


def _row_counts(sf: float) -> Dict[str, int]:
    n_ss = _scaled(2_880_404, sf)
    return {
        "date_dim": _DATE_ROWS,
        "time_dim": 86_400,
        "item": _scaled(18_000, sf, 10),
        "customer": _scaled(100_000, sf, 100),
        "customer_address": _scaled(50_000, sf, 50),
        # fixed-cardinality dimension in the spec; scaled below sf1 to keep
        # tiny-schema tests light
        "customer_demographics": _scaled(1_920_800, min(sf, 1.0) if sf >= 1.0
                                         else sf, 100),
        "household_demographics": 7_200,
        "income_band": 20,
        "store": _scaled(12, sf, 2),
        "warehouse": _scaled(5, sf, 1),
        "promotion": _scaled(300, sf, 10),
        "web_site": _scaled(30, sf, 2),
        "web_page": _scaled(60, sf, 2),
        "catalog_page": _scaled(11_718, sf, 100),
        "call_center": _scaled(6, sf, 2),
        "ship_mode": 20,
        "reason": _scaled(35, sf, 5),
        "store_sales": n_ss,
        "store_returns": max(1, n_ss // 10),
        "catalog_sales": _scaled(1_441_548, sf),
        "web_sales": _scaled(719_384, sf),
        "inventory": 0,    # derived: items x warehouses x weeks
        "catalog_returns": 0,  # derived: ~10% of catalog_sales
        "web_returns": 0,      # derived: ~10% of web_sales
    }


def _ids(prefix: str, n: int) -> np.ndarray:
    return np.array([f"{prefix}{i:012d}" for i in range(1, n + 1)],
                    dtype=object)


# far-future sentinel for rec_end_date-style columns (no NULLs in the
# materialized dims; engine and oracle read the same generated values, so
# comparisons stay consistent)
_OPEN_END_DATE = days_from_civil(2100, 1, 1)

_STREET_TYPES = ["Ave", "Blvd", "Boulevard", "Circle", "Court", "Dr",
                 "Drive", "Lane", "Ln", "Parkway", "Pkwy", "RD", "Road",
                 "ST", "Street", "Way"]


def _names(rng, n):
    f = np.array(_FIRST_NAMES, dtype=object)[
        rng.integers(0, len(_FIRST_NAMES), n)]
    last = np.array(_LAST_NAMES, dtype=object)[
        rng.integers(0, len(_LAST_NAMES), n)]
    return np.array([f"{a} {b}" for a, b in zip(f, last)], dtype=object)


def _phrases(rng, n, max_len):
    words = np.array(_CLASSES, dtype=object)
    picks = rng.integers(0, len(words), size=(n, 3))
    return np.array([" ".join(words[r])[:max_len] for r in picks],
                    dtype=object)


def _address_cols(prefix: str, rng, n) -> Dict[str, np.ndarray]:
    cities = np.array(_CITIES, dtype=object)[
        rng.integers(0, len(_CITIES), n)]
    states = np.array(_STATES, dtype=object)[
        rng.integers(0, len(_STATES), n)]
    return {
        f"{prefix}_street_number": np.array(
            [str(v) for v in rng.integers(1, 1000, n)], dtype=object),
        f"{prefix}_street_name": np.array(
            [f"{c} Street" for c in cities], dtype=object),
        f"{prefix}_street_type": np.array(_STREET_TYPES, dtype=object)[
            rng.integers(0, len(_STREET_TYPES), n)],
        f"{prefix}_suite_number": np.array(
            [f"Suite {v}" for v in rng.integers(0, 100, n)], dtype=object),
        f"{prefix}_city": cities,
        f"{prefix}_county": np.array(
            [f"{s} County" for s in states], dtype=object),
        f"{prefix}_state": states,
        f"{prefix}_zip": np.array(
            [f"{z:05d}" for z in rng.integers(10000, 99999, n)],
            dtype=object),
        f"{prefix}_country": np.full(n, "United States", dtype=object),
        f"{prefix}_gmt_offset": rng.choice(
            np.array([-1000, -900, -800, -700, -600, -500]),
            n).astype(np.int64),
    }


def _gen_table(table: str, sf: float) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(_table_seed(table, sf))
    counts = _row_counts(sf)

    if table == "date_dim":
        n = _DATE_ROWS
        sk = np.arange(_JULIAN_BASE, _JULIAN_BASE + n, dtype=np.int64)
        date = np.arange(_EPOCH_OFFSET, _EPOCH_OFFSET + n, dtype=np.int32)
        # civil fields via numpy datetime64 (exact calendar)
        d64 = date.astype("datetime64[D]")
        y = d64.astype("datetime64[Y]").astype(int) + 1970
        m = d64.astype("datetime64[M]").astype(int) % 12 + 1
        dom = (d64 - d64.astype("datetime64[M]")).astype(int) + 1
        dow = (date + 4) % 7            # 1970-01-01 was a Thursday; 0=Sunday
        week_seq = (np.arange(n) + 1) // 7 + 1
        month_seq = (y - 1900) * 12 + (m - 1)
        qoy = (m - 1) // 3 + 1
        holiday = np.where(rng.random(n) < 0.05, "Y", "N").astype(object)
        no = np.full(n, "N", dtype=object)
        return {
            "d_date_sk": sk,
            "d_date_id": _ids("D", n),
            "d_date": date,
            "d_month_seq": month_seq.astype(np.int64),
            "d_week_seq": week_seq.astype(np.int64),
            "d_quarter_seq": ((y - 1900) * 4 + qoy - 1).astype(np.int64),
            "d_year": y.astype(np.int64),
            "d_dow": dow.astype(np.int64),
            "d_moy": m.astype(np.int64),
            "d_dom": dom.astype(np.int64),
            "d_qoy": qoy.astype(np.int64),
            "d_fy_year": y.astype(np.int64),
            "d_fy_quarter_seq": ((y - 1900) * 4 + qoy - 1).astype(np.int64),
            "d_fy_week_seq": week_seq.astype(np.int64),
            "d_day_name": np.array(_DAY_NAMES, dtype=object)[dow],
            "d_quarter_name": np.array(
                [f"{yy}Q{q}" for yy, q in zip(y, qoy)], dtype=object),
            "d_holiday": holiday,
            "d_weekend": np.where((dow == 0) | (dow == 6), "Y", "N").astype(
                object),
            "d_following_holiday": np.roll(holiday, -1),
            "d_first_dom": (sk - dom + 1).astype(np.int64),
            "d_last_dom": (sk - dom + 28).astype(np.int64),
            "d_same_day_ly": (sk - 365).astype(np.int64),
            "d_same_day_lq": (sk - 91).astype(np.int64),
            "d_current_day": no, "d_current_week": no,
            "d_current_month": no, "d_current_quarter": no,
            "d_current_year": no,
        }

    if table == "time_dim":
        n = 86_400
        t = np.arange(n, dtype=np.int64)
        hour = t // 3600
        return {
            "t_time_sk": t,
            "t_time_id": _ids("T", n),
            "t_time": t,
            "t_hour": hour,
            "t_minute": (t % 3600) // 60,
            "t_second": t % 60,
            "t_am_pm": np.where(hour < 12, "AM", "PM").astype(object),
            "t_meal_time": np.select(
                [(hour >= 6) & (hour <= 8), (hour >= 11) & (hour <= 13),
                 (hour >= 18) & (hour <= 20)],
                [np.full(n, "breakfast", dtype=object),
                 np.full(n, "lunch", dtype=object),
                 np.full(n, "dinner", dtype=object)],
                default="").astype(object),
            "t_shift": np.array(["third", "first", "second"], dtype=object)[
                np.minimum(hour // 8, 2)],
            "t_sub_shift": np.array(
                ["night", "morning", "afternoon", "evening"],
                dtype=object)[np.minimum(hour // 6, 3)],
        }

    if table == "web_site":
        n = counts["web_site"]
        out = {
            "web_site_sk": np.arange(1, n + 1, dtype=np.int64),
            "web_site_id": _ids("WS", n),
            "web_rec_start_date": np.full(
                n, days_from_civil(1997, 8, 16), dtype=np.int32),
            "web_rec_end_date": np.full(n, _OPEN_END_DATE, dtype=np.int32),
            "web_name": np.array([f"site_{i}" for i in range(n)],
                                 dtype=object),
            "web_open_date_sk": rng.integers(
                _SALES_MIN - 1000, _SALES_MIN, n).astype(np.int64),
            "web_close_date_sk": rng.integers(
                _SALES_MAX, _SALES_MAX + 1000, n).astype(np.int64),
            "web_class": np.full(n, "Unknown", dtype=object),
            "web_manager": _names(rng, n),
            "web_mkt_id": rng.integers(1, 7, n).astype(np.int64),
            "web_mkt_class": _phrases(rng, n, 30),
            "web_mkt_desc": _phrases(rng, n, 60),
            "web_market_manager": _names(rng, n),
            "web_company_id": rng.integers(1, 7, n).astype(np.int64),
            "web_company_name": np.array(
                ["pri", "able", "ation", "bar", "ese", "cally"],
                dtype=object)[np.arange(n) % 6],
        }
        out.update(_address_cols("web", rng, n))
        out["web_tax_percentage"] = rng.integers(0, 13, n).astype(np.int64)
        return out

    if table == "web_page":
        n = counts["web_page"]
        return {
            "wp_web_page_sk": np.arange(1, n + 1, dtype=np.int64),
            "wp_web_page_id": _ids("WP", n),
            "wp_rec_start_date": np.full(
                n, days_from_civil(1997, 9, 3), dtype=np.int32),
            "wp_rec_end_date": np.full(n, _OPEN_END_DATE, dtype=np.int32),
            "wp_creation_date_sk": rng.integers(
                _SALES_MIN - 500, _SALES_MIN, n).astype(np.int64),
            "wp_access_date_sk": rng.integers(
                _SALES_MIN, _SALES_MAX, n).astype(np.int64),
            "wp_autogen_flag": np.array(["Y", "N"], dtype=object)[
                rng.integers(0, 2, n)],
            "wp_customer_sk": rng.integers(
                1, counts["customer"] + 1, n).astype(np.int64),
            "wp_url": np.full(n, "http://www.foo.com", dtype=object),
            "wp_type": np.array(
                ["ad", "dynamic", "feedback", "general", "order",
                 "protected", "welcome"], dtype=object)[
                rng.integers(0, 7, n)],
            "wp_char_count": rng.integers(100, 8000, n).astype(np.int64),
            "wp_link_count": rng.integers(2, 25, n).astype(np.int64),
            "wp_image_count": rng.integers(1, 7, n).astype(np.int64),
            "wp_max_ad_count": rng.integers(0, 5, n).astype(np.int64),
        }

    if table == "catalog_page":
        n = counts["catalog_page"]
        return {
            "cp_catalog_page_sk": np.arange(1, n + 1, dtype=np.int64),
            "cp_catalog_page_id": _ids("CP", n),
            "cp_start_date_sk": rng.integers(
                _SALES_MIN, _SALES_MAX - 100, n).astype(np.int64),
            "cp_end_date_sk": rng.integers(
                _SALES_MAX - 100, _SALES_MAX, n).astype(np.int64),
            "cp_department": np.full(n, "DEPARTMENT", dtype=object),
            "cp_catalog_number": (np.arange(n, dtype=np.int64) // 108 + 1),
            "cp_catalog_page_number": (np.arange(n, dtype=np.int64) % 108
                                       + 1),
            "cp_description": _phrases(rng, n, 60),
            "cp_type": np.array(
                ["bi-annual", "monthly", "quarterly"], dtype=object)[
                rng.integers(0, 3, n)],
        }

    if table == "call_center":
        n = counts["call_center"]
        out = {
            "cc_call_center_sk": np.arange(1, n + 1, dtype=np.int64),
            "cc_call_center_id": _ids("CC", n),
            "cc_rec_start_date": np.full(
                n, days_from_civil(1998, 1, 1), dtype=np.int32),
            "cc_rec_end_date": np.full(n, _OPEN_END_DATE, dtype=np.int32),
            "cc_closed_date_sk": np.zeros(n, np.int64),
            "cc_open_date_sk": rng.integers(
                _SALES_MIN - 1000, _SALES_MIN, n).astype(np.int64),
            "cc_name": np.array(
                ["NY Metro", "Mid Atlantic", "Pacific Northwest",
                 "North Midwest", "California", "Hawaii/Alaska"],
                dtype=object)[np.arange(n) % 6],
            "cc_class": np.array(["small", "medium", "large"],
                                 dtype=object)[np.arange(n) % 3],
            "cc_employees": rng.integers(1, 7, n).astype(np.int64) * 100,
            "cc_sq_ft": rng.integers(1, 10, n).astype(np.int64) * 10_000,
            "cc_hours": np.array(["8AM-4PM", "8AM-12AM", "8AM-8AM"],
                                 dtype=object)[np.arange(n) % 3],
            "cc_manager": _names(rng, n),
            "cc_mkt_id": rng.integers(1, 7, n).astype(np.int64),
            "cc_mkt_class": _phrases(rng, n, 30),
            "cc_mkt_desc": _phrases(rng, n, 60),
            "cc_market_manager": _names(rng, n),
            "cc_division": rng.integers(1, 7, n).astype(np.int64),
            "cc_division_name": np.array(
                ["pri", "able", "ation", "bar", "ese", "cally"],
                dtype=object)[np.arange(n) % 6],
            "cc_company": rng.integers(1, 7, n).astype(np.int64),
            "cc_company_name": np.array(
                ["pri", "able", "ation", "bar", "ese", "cally"],
                dtype=object)[np.arange(n) % 6],
        }
        out.update(_address_cols("cc", rng, n))
        out["cc_tax_percentage"] = rng.integers(0, 13, n).astype(np.int64)
        return out

    if table == "ship_mode":
        n = 20
        types = ["EXPRESS", "LIBRARY", "NEXT DAY", "OVERNIGHT", "REGULAR",
                 "TWO DAY"]
        carriers = ["AIRBORNE", "ALLIANCE", "BARIAN", "BOXBUNDLES", "DHL",
                    "DIAMOND", "FEDEX", "GERMA", "GREAT EASTERN", "HARMSTORF",
                    "LATVIAN", "MSC", "ORIENTAL", "PRIVATECARRIER", "RUPEKSA",
                    "TBS", "UPS", "USPS", "ZHOU", "ZOUROS"]
        return {
            "sm_ship_mode_sk": np.arange(1, n + 1, dtype=np.int64),
            "sm_ship_mode_id": _ids("SM", n),
            "sm_type": np.array(types, dtype=object)[np.arange(n) % 6],
            "sm_code": np.array(["AIR", "SURFACE", "SEA"], dtype=object)[
                np.arange(n) % 3],
            "sm_carrier": np.array(carriers, dtype=object),
            "sm_contract": _ids("K", n),
        }

    if table == "reason":
        n = counts["reason"]
        reasons = ["Package was damaged", "Stopped working",
                   "Did not get it on time", "Not the product that was "
                   "ordred", "Parts missing", "Does not work with a product "
                   "that I have", "Gift exchange", "Did not like the color",
                   "Did not like the model", "Did not like the make",
                   "Did not like the warranty", "No service location in my "
                   "area", "Found a better price in a store",
                   "Found a better extended warranty in a store",
                   "reason 15", "reason 16", "reason 17", "reason 18",
                   "reason 19", "reason 20", "reason 21", "reason 22",
                   "reason 23", "reason 24", "reason 25", "reason 26",
                   "reason 27", "reason 28", "reason 29", "reason 30",
                   "reason 31", "reason 32", "reason 33", "reason 34",
                   "reason 35"]
        return {
            "r_reason_sk": np.arange(1, n + 1, dtype=np.int64),
            "r_reason_id": _ids("R", n),
            "r_reason_desc": np.array(reasons[:n] if n <= 35 else
                                      [reasons[i % 35] for i in range(n)],
                                      dtype=object),
        }

    if table == "item":
        n = counts["item"]
        cat_id = rng.integers(1, 11, n)
        class_id = rng.integers(1, 17, n)
        brand_id = cat_id * 1000000 + class_id * 1000 + rng.integers(1, 11, n)
        manu_id = rng.integers(1, 1001, n)
        return {
            "i_item_sk": np.arange(1, n + 1, dtype=np.int64),
            "i_item_id": _ids("I", n),
            "i_rec_start_date": np.full(
                n, days_from_civil(1997, 10, 27), dtype=np.int32),
            "i_rec_end_date": np.full(n, _OPEN_END_DATE, dtype=np.int32),
            "i_item_desc": np.array(
                [f"item description {i % 997}" for i in range(n)],
                dtype=object),
            "i_current_price": rng.integers(50, 30000, n).astype(np.int64),
            "i_wholesale_cost": rng.integers(30, 20000, n).astype(np.int64),
            "i_brand_id": brand_id.astype(np.int64),
            "i_brand": np.array([f"brand#{b % 1000}" for b in brand_id],
                                dtype=object),
            "i_class_id": class_id.astype(np.int64),
            "i_class": np.array(_CLASSES, dtype=object)[
                class_id % len(_CLASSES)],
            "i_category_id": cat_id.astype(np.int64),
            "i_category": np.array(_CATEGORIES, dtype=object)[cat_id - 1],
            "i_manufact_id": manu_id.astype(np.int64),
            "i_manufact": np.array([f"manufact#{m % 997}" for m in manu_id],
                                   dtype=object),
            "i_size": np.array(_SIZES, dtype=object)[
                rng.integers(0, len(_SIZES), n)],
            "i_formulation": np.array(
                [f"formulation {v}" for v in rng.integers(0, 997, n)],
                dtype=object),
            "i_color": np.array(_COLORS, dtype=object)[
                rng.integers(0, len(_COLORS), n)],
            "i_units": np.array(_UNITS, dtype=object)[
                rng.integers(0, len(_UNITS), n)],
            "i_container": np.full(n, "Unknown", dtype=object),
            "i_manager_id": rng.integers(1, 101, n).astype(np.int64),
            "i_product_name": np.array(
                [f"product{i % 4999}ought" for i in range(n)], dtype=object),
        }

    if table == "customer":
        n = counts["customer"]
        first_sale = rng.integers(_SALES_MIN - 1500, _SALES_MIN, n)
        return {
            "c_customer_sk": np.arange(1, n + 1, dtype=np.int64),
            "c_customer_id": _ids("C", n),
            "c_current_cdemo_sk": rng.integers(
                1, counts["customer_demographics"] + 1, n).astype(np.int64),
            "c_current_hdemo_sk": rng.integers(1, 7201, n).astype(np.int64),
            "c_current_addr_sk": rng.integers(
                1, counts["customer_address"] + 1, n).astype(np.int64),
            "c_first_shipto_date_sk": (first_sale + 30).astype(np.int64),
            "c_first_sales_date_sk": first_sale.astype(np.int64),
            "c_salutation": np.array(
                ["Mr.", "Mrs.", "Ms.", "Dr.", "Miss", "Sir"],
                dtype=object)[rng.integers(0, 6, n)],
            "c_first_name": np.array(_FIRST_NAMES, dtype=object)[
                rng.integers(0, len(_FIRST_NAMES), n)],
            "c_last_name": np.array(_LAST_NAMES, dtype=object)[
                rng.integers(0, len(_LAST_NAMES), n)],
            "c_preferred_cust_flag": np.array(["Y", "N"], dtype=object)[
                rng.integers(0, 2, n)],
            "c_birth_day": rng.integers(1, 29, n).astype(np.int64),
            "c_birth_month": rng.integers(1, 13, n).astype(np.int64),
            "c_birth_year": rng.integers(1924, 1993, n).astype(np.int64),
            "c_birth_country": np.array(
                ["UNITED STATES", "CANADA", "GERMANY", "JAPAN", "MEXICO",
                 "FRANCE", "BRAZIL", "INDIA"], dtype=object)[
                rng.integers(0, 8, n)],
            "c_login": np.full(n, "", dtype=object),
            "c_email_address": np.array(
                [f"user{i % 9973}@example.com" for i in range(n)],
                dtype=object),
            "c_last_review_date_sk": rng.integers(
                _SALES_MIN, _SALES_MAX, n).astype(np.int64),
        }

    if table == "customer_address":
        n = counts["customer_address"]
        out = {
            "ca_address_sk": np.arange(1, n + 1, dtype=np.int64),
            "ca_address_id": _ids("A", n),
            "ca_location_type": np.array(
                ["apartment", "condo", "single family"], dtype=object)[
                rng.integers(0, 3, n)],
        }
        out.update(_address_cols("ca", rng, n))
        return out

    if table == "customer_demographics":
        n = counts["customer_demographics"]
        seq = np.arange(n)
        return {
            "cd_demo_sk": np.arange(1, n + 1, dtype=np.int64),
            "cd_gender": np.array(["M", "F"], dtype=object)[seq % 2],
            "cd_marital_status": np.array(
                ["M", "S", "D", "W", "U"], dtype=object)[(seq // 2) % 5],
            "cd_education_status": np.array(_EDUCATION, dtype=object)[
                (seq // 10) % len(_EDUCATION)],
            "cd_purchase_estimate": ((seq // 70) % 20 * 500 + 500).astype(
                np.int64),
            "cd_credit_rating": np.array(_CREDIT, dtype=object)[
                (seq // 1400) % len(_CREDIT)],
            "cd_dep_count": ((seq // 5600) % 7).astype(np.int64),
            "cd_dep_employed_count": ((seq // 39200) % 7).astype(np.int64),
            "cd_dep_college_count": ((seq // 274400) % 7).astype(np.int64),
        }

    if table == "household_demographics":
        n = 7200
        seq = np.arange(n)
        return {
            "hd_demo_sk": np.arange(1, n + 1, dtype=np.int64),
            "hd_income_band_sk": (seq % 20 + 1).astype(np.int64),
            "hd_buy_potential": np.array(_BUY_POTENTIAL, dtype=object)[
                (seq // 20) % len(_BUY_POTENTIAL)],
            "hd_dep_count": ((seq // 120) % 10).astype(np.int64),
            "hd_vehicle_count": ((seq // 1200) % 6).astype(np.int64),
        }

    if table == "income_band":
        n = 20
        lower = np.arange(n, dtype=np.int64) * 10000
        return {
            "ib_income_band_sk": np.arange(1, n + 1, dtype=np.int64),
            "ib_lower_bound": lower + np.where(np.arange(n) == 0, 0, 1),
            "ib_upper_bound": lower + 10000,
        }

    if table == "store":
        n = counts["store"]
        out = {
            "s_store_sk": np.arange(1, n + 1, dtype=np.int64),
            "s_store_id": _ids("S", n),
            "s_rec_start_date": np.full(
                n, days_from_civil(1997, 3, 13), dtype=np.int32),
            "s_rec_end_date": np.full(n, _OPEN_END_DATE, dtype=np.int32),
            "s_closed_date_sk": np.zeros(n, np.int64),
            "s_store_name": np.array(
                ["able", "ation", "bar", "ese", "eing", "cally", "ought",
                 "anti"], dtype=object)[np.arange(n) % 8],
            "s_number_employees": rng.integers(200, 300, n).astype(np.int64),
            "s_floor_space": rng.integers(5_000_000, 10_000_000, n).astype(
                np.int64),
            "s_hours": np.array(["8AM-4PM", "8AM-12AM", "8AM-8AM"],
                                dtype=object)[np.arange(n) % 3],
            "s_manager": _names(rng, n),
            "s_market_id": rng.integers(1, 11, n).astype(np.int64),
            "s_geography_class": np.full(n, "Unknown", dtype=object),
            "s_market_desc": _phrases(rng, n, 60),
            "s_market_manager": _names(rng, n),
            "s_division_id": np.ones(n, np.int64),
            "s_division_name": np.full(n, "Unknown", dtype=object),
            "s_company_id": np.ones(n, np.int64),
            "s_company_name": np.full(n, "Unknown", dtype=object),
        }
        out.update(_address_cols("s", rng, n))
        out["s_tax_precentage"] = rng.integers(0, 12, n).astype(np.int64)
        return out

    if table == "warehouse":
        n = counts["warehouse"]
        out = {
            "w_warehouse_sk": np.arange(1, n + 1, dtype=np.int64),
            "w_warehouse_id": _ids("W", n),
            "w_warehouse_name": np.array(
                [f"Warehouse {i}" for i in range(1, n + 1)], dtype=object),
            "w_warehouse_sq_ft": rng.integers(50_000, 1_000_000, n).astype(
                np.int64),
        }
        out.update(_address_cols("w", rng, n))
        return out

    if table == "promotion":
        n = counts["promotion"]
        start = rng.integers(_SALES_MIN, _SALES_MAX - 60, n)

        def yn(col_seed):
            r2 = np.random.default_rng(_table_seed(table, sf) + col_seed)
            return np.array(["Y", "N"], dtype=object)[r2.integers(0, 2, n)]
        return {
            "p_promo_sk": np.arange(1, n + 1, dtype=np.int64),
            "p_promo_id": _ids("P", n),
            "p_start_date_sk": start.astype(np.int64),
            "p_end_date_sk": (start + rng.integers(10, 60, n)).astype(
                np.int64),
            "p_item_sk": rng.integers(1, counts["item"] + 1, n).astype(
                np.int64),
            "p_cost": np.full(n, 100000, np.int64),   # 1000.00
            "p_response_target": np.ones(n, np.int64),
            "p_promo_name": np.array(
                ["able", "ation", "bar", "ese", "eing", "cally", "ought",
                 "anti", "pri", "n st"], dtype=object)[np.arange(n) % 10],
            "p_channel_dmail": yn(1),
            "p_channel_email": yn(2),
            "p_channel_catalog": yn(3),
            "p_channel_tv": yn(4),
            "p_channel_radio": yn(5),
            "p_channel_press": yn(6),
            "p_channel_event": yn(7),
            "p_channel_demo": yn(8),
            "p_channel_details": _phrases(rng, n, 60),
            "p_purpose": np.full(n, "Unknown", dtype=object),
            "p_discount_active": yn(9),
        }

    raise KeyError(table)


_TABLE_CACHE: Dict[tuple, Dict[str, np.ndarray]] = {}
_DICT_CACHE: Dict[tuple, Dictionary] = {}


# --------------------------------------------------------------------------
# chunked fact streams (round 4): the big tables become stateless
# counter-hash column streams (the tpch_gen design — any column, any row
# range, identical bytes everywhere), which is what makes SF100 q64/q72
# runnable: store_sales SF100 is 288M rows and a scan materializes only the
# columns it reads, chunk by chunk, with no sequential RNG state. The
# dimension tables keep the materialized generator (small).

from trino_tpu.connector import tpch_gen as _HG

_CHUNKED = {"store_sales", "store_returns", "catalog_sales",
            "catalog_returns", "web_sales", "web_returns",
            "inventory", "customer_demographics"}


def _hui(table, col, sf, idx, lo, hi):
    return _HG._ui("tpcds." + table, col, sf, idx, lo, hi)


def _hu64(table, col, sf, idx):
    return _HG._u64("tpcds." + table, col, sf, idx)


def _ss_col(sf, col, idx, c):
    t = "store_sales"
    if col == "ss_sold_date_sk":
        return _hui(t, col, sf, idx, _SALES_MIN, _SALES_MAX)
    if col == "ss_sold_time_sk":
        return _hui(t, col, sf, idx, 28800, 75599)   # store hours
    if col == "ss_item_sk":
        return _hui(t, col, sf, idx, 1, c["item"])
    if col == "ss_customer_sk":
        return _hui(t, col, sf, idx, 1, c["customer"])
    if col == "ss_cdemo_sk":
        return _hui(t, col, sf, idx, 1, c["customer_demographics"])
    if col == "ss_hdemo_sk":
        return _hui(t, col, sf, idx, 1, 7200)
    if col == "ss_addr_sk":
        return _hui(t, col, sf, idx, 1, c["customer_address"])
    if col == "ss_store_sk":
        return _hui(t, col, sf, idx, 1, c["store"])
    if col == "ss_promo_sk":
        return _hui(t, col, sf, idx, 1, c["promotion"])
    if col == "ss_ticket_number":
        return idx.astype(np.int64) // 4 + 1
    if col == "ss_quantity":
        return _hui(t, "ss_quantity", sf, idx, 1, 100)
    qty = _hui(t, "ss_quantity", sf, idx, 1, 100)
    wholesale = _hui(t, "ss_wholesale", sf, idx, 100, 8999)
    lp = wholesale * _hui(t, "ss_lp", sf, idx, 110, 219) // 100
    sp = lp * _hui(t, "ss_sp", sf, idx, 30, 100) // 100
    if col == "ss_wholesale_cost":
        return wholesale
    if col == "ss_list_price":
        return lp
    if col == "ss_sales_price":
        return sp
    if col == "ss_ext_discount_amt":
        return (lp - sp) * qty
    if col == "ss_ext_sales_price":
        return sp * qty
    if col == "ss_ext_wholesale_cost":
        return wholesale * qty
    if col == "ss_ext_list_price":
        return lp * qty
    if col == "ss_coupon_amt":
        disc = (lp - sp) * qty
        return np.where(_hu64(t, "ss_coupon", sf, idx)
                        % np.uint64(1000) < 200, disc // 2, 0)
    if col == "ss_net_paid":
        return sp * qty
    if col == "ss_ext_tax":
        return sp * qty * _hui(t, "ss_tax", sf, idx, 0, 11) // 100
    if col == "ss_net_paid_inc_tax":
        # locals qty/sp are already computed once per call — no recursion
        return sp * qty + sp * qty * _hui(t, "ss_tax", sf, idx,
                                          0, 11) // 100
    if col == "ss_net_profit":
        return (sp - wholesale) * qty
    raise KeyError(col)


def _catalogish_col(t, prefix, sf, col, idx, c, extra):
    """Shared column streams for catalog_sales/web_sales (identical spec
    shape modulo prefix and channel-specific FKs in `extra`)."""
    p = prefix
    if col == f"{p}_sold_date_sk":
        return _hui(t, col, sf, idx, _SALES_MIN, _SALES_MAX)
    if col == f"{p}_sold_time_sk":
        return _hui(t, col, sf, idx, 0, 86399)
    if col == f"{p}_ship_date_sk":
        return _hui(t, f"{p}_sold_date_sk", sf, idx, _SALES_MIN,
                    _SALES_MAX) + _hui(t, f"{p}_ship_delay", sf, idx, 2, 89)
    for role in ("bill", "ship"):
        if col == f"{p}_{role}_customer_sk":
            return _hui(t, col, sf, idx, 1, c["customer"])
        if col == f"{p}_{role}_cdemo_sk":
            return _hui(t, col, sf, idx, 1, c["customer_demographics"])
        if col == f"{p}_{role}_hdemo_sk":
            return _hui(t, col, sf, idx, 1, 7200)
        if col == f"{p}_{role}_addr_sk":
            return _hui(t, col, sf, idx, 1, c["customer_address"])
    if col == f"{p}_ship_mode_sk":
        return _hui(t, col, sf, idx, 1, 20)
    if col == f"{p}_warehouse_sk":
        return _hui(t, col, sf, idx, 1, c["warehouse"])
    if col == f"{p}_item_sk":
        return _hui(t, col, sf, idx, 1, c["item"])
    if col == f"{p}_promo_sk":
        return _hui(t, col, sf, idx, 1, c["promotion"])
    if col == f"{p}_order_number":
        return idx.astype(np.int64) // 3 + 1
    if col == f"{p}_quantity":
        return _hui(t, f"{p}_quantity", sf, idx, 1, 100)
    if col in extra:
        return extra[col](idx)
    qty = _hui(t, f"{p}_quantity", sf, idx, 1, 100)
    wholesale = _hui(t, f"{p}_wholesale", sf, idx, 100, 8999)
    lp = wholesale * _hui(t, f"{p}_lp", sf, idx, 110, 219) // 100
    sp = lp * _hui(t, f"{p}_sp", sf, idx, 30, 100) // 100
    tax = sp * qty * _hui(t, f"{p}_tax", sf, idx, 0, 11) // 100
    ship = sp * qty * _hui(t, f"{p}_shipc", sf, idx, 0, 9) // 100
    if col == f"{p}_wholesale_cost":
        return wholesale
    if col == f"{p}_list_price":
        return lp
    if col == f"{p}_sales_price":
        return sp
    if col == f"{p}_ext_discount_amt":
        return (lp - sp) * qty
    if col == f"{p}_ext_sales_price":
        return sp * qty
    if col == f"{p}_ext_wholesale_cost":
        return wholesale * qty
    if col == f"{p}_ext_list_price":
        return lp * qty
    if col == f"{p}_ext_tax":
        return tax
    if col == f"{p}_coupon_amt":
        disc = (lp - sp) * qty
        return np.where(_hu64(t, f"{p}_coupon", sf, idx)
                        % np.uint64(1000) < 200, disc // 2, 0)
    if col == f"{p}_ext_ship_cost":
        return ship
    if col == f"{p}_net_paid":
        return sp * qty
    if col == f"{p}_net_paid_inc_tax":
        return sp * qty + tax
    if col == f"{p}_net_paid_inc_ship":
        return sp * qty + ship
    if col == f"{p}_net_paid_inc_ship_tax":
        return sp * qty + ship + tax
    if col == f"{p}_net_profit":
        return (sp - wholesale) * qty
    raise KeyError(col)


def _cs_col(sf, col, idx, c):
    t = "catalog_sales"
    extra = {
        "cs_call_center_sk": lambda i: _hui(t, "cs_call_center_sk", sf, i,
                                            1, c["call_center"]),
        "cs_catalog_page_sk": lambda i: _hui(t, "cs_catalog_page_sk", sf, i,
                                             1, c["catalog_page"]),
    }
    return _catalogish_col(t, "cs", sf, col, idx, c, extra)


def _ws_col(sf, col, idx, c):
    t = "web_sales"
    extra = {
        "ws_web_page_sk": lambda i: _hui(t, "ws_web_page_sk", sf, i,
                                         1, c["web_page"]),
        "ws_web_site_sk": lambda i: _hui(t, "ws_web_site_sk", sf, i,
                                         1, c["web_site"]),
    }
    return _catalogish_col(t, "ws", sf, col, idx, c, extra)


def _returns_rowmap(table: str, sf: float, idx: np.ndarray) -> np.ndarray:
    """Return row j references sale row j*10 + jitter — a deterministic
    injective pick (stride 10 > jitter range), the seekable replacement
    for rng.choice(replace=False), so every return matches a real sale
    (q64's ss JOIN sr on ticket+item needs real pairs)."""
    jitter = (_hu64(table, "pick", sf, idx) % np.uint64(10)).astype(np.int64)
    return idx.astype(np.int64) * 10 + jitter


def _sr_col(sf, col, idx, c):
    t = "store_returns"
    r = _returns_rowmap(t, sf, idx).astype(np.uint64)
    if col == "sr_returned_date_sk":
        return _ss_col(sf, "ss_sold_date_sk", r, c) \
            + _hui(t, "sr_delay", sf, idx, 1, 59)
    if col == "sr_return_time_sk":
        return _hui(t, col, sf, idx, 28800, 75599)
    if col == "sr_reason_sk":
        return _hui(t, col, sf, idx, 1, c["reason"])
    if col == "sr_return_quantity":
        return _hui(t, col, sf, idx, 1, 49)

    def amount():
        # shared intermediate computed ONCE per (col, chunk) — see
        # _returnish_col's note on avoiding recursive re-derivation
        qty = _ss_col(sf, "ss_quantity", r, c)
        mult = 1 + (_hu64(t, "sr_amt", sf, idx)
                    % qty.astype(np.uint64)).astype(np.int64)
        return _ss_col(sf, "ss_sales_price", r, c) * mult

    def tax_of(amt):
        return amt * _hui(t, "sr_taxpct", sf, idx, 0, 11) // 100

    if col == "sr_return_amt":
        return amount()
    if col == "sr_return_tax":
        return tax_of(amount())
    if col == "sr_return_amt_inc_tax":
        amt = amount()
        return amt + tax_of(amt)
    if col == "sr_fee":
        return _hui(t, col, sf, idx, 50, 10000)
    if col == "sr_return_ship_cost":
        return _hui(t, col, sf, idx, 0, 10000)
    if col in ("sr_refunded_cash", "sr_reversed_charge",
               "sr_store_credit"):
        # three-way split of the returned amount
        amt = amount()
        cash = amt * _hui(t, "sr_cashpct", sf, idx, 0, 100) // 100
        rest = amt - cash
        charge = rest * _hui(t, "sr_chargepct", sf, idx, 0, 100) // 100
        if col == "sr_refunded_cash":
            return cash
        if col == "sr_reversed_charge":
            return charge
        return rest - charge
    if col == "sr_net_loss":
        return amount() // 2 + _hui(t, "sr_fee", sf, idx, 50, 10000)
    mapping = {"sr_item_sk": "ss_item_sk", "sr_customer_sk":
               "ss_customer_sk", "sr_cdemo_sk": "ss_cdemo_sk",
               "sr_hdemo_sk": "ss_hdemo_sk", "sr_addr_sk": "ss_addr_sk",
               "sr_store_sk": "ss_store_sk",
               "sr_ticket_number": "ss_ticket_number"}
    if col in mapping:
        return _ss_col(sf, mapping[col], r, c)
    raise KeyError(col)


def _returnish_col(t, p, sale_col, sp, sf, col, idx, c, extra):
    """Shared return streams for catalog_returns/web_returns: refunded_*
    mirror the sale's bill_* FKs (same buyer), returning_* are fresh
    draws (possibly a different account)."""
    r = _returns_rowmap(t, sf, idx).astype(np.uint64)
    if col == f"{p}_returned_date_sk":
        return sale_col(sf, f"{sp}_sold_date_sk", r, c) \
            + _hui(t, f"{p}_delay", sf, idx, 1, 59)
    if col == f"{p}_returned_time_sk":
        return _hui(t, col, sf, idx, 0, 86399)
    if col == f"{p}_reason_sk":
        return _hui(t, col, sf, idx, 1, c["reason"])
    if col == f"{p}_return_quantity":
        return _hui(t, col, sf, idx, 1, 49)
    amount_col = f"{p}_return_amount" if p == "cr" else f"{p}_return_amt"

    def amount():
        # shared intermediate computed ONCE per (col, chunk) — recursing
        # through _returnish_col re-derived the whole sale-price hash
        # chain per reference (2-3x waste on SF100 chunk scans)
        return sale_col(sf, f"{sp}_sales_price", r, c) \
            * _hui(t, f"{p}_amt", sf, idx, 1, 19)

    def tax_of(amt):
        return amt * _hui(t, f"{p}_taxpct", sf, idx, 0, 11) // 100

    if col == amount_col:
        return amount()
    if col == f"{p}_return_tax":
        return tax_of(amount())
    if col == f"{p}_return_amt_inc_tax":
        amt = amount()
        return amt + tax_of(amt)
    if col == f"{p}_fee":
        return _hui(t, col, sf, idx, 50, 10000)
    if col == f"{p}_return_ship_cost":
        return _hui(t, col, sf, idx, 0, 10000)
    credit_col = f"{p}_store_credit" if p == "cr" else f"{p}_account_credit"
    if col in (f"{p}_refunded_cash", f"{p}_reversed_charge", credit_col):
        amt = amount()
        cash = amt * _hui(t, f"{p}_cashpct", sf, idx, 0, 100) // 100
        rest = amt - cash
        charge = rest * _hui(t, f"{p}_chargepct", sf, idx, 0, 100) // 100
        if col == f"{p}_refunded_cash":
            return cash
        if col == f"{p}_reversed_charge":
            return charge
        return rest - charge
    if col == f"{p}_net_loss":
        return amount() // 2 + _hui(t, f"{p}_fee", sf, idx, 50, 10000)
    refunded = {
        f"{p}_refunded_customer_sk": f"{sp}_bill_customer_sk",
        f"{p}_refunded_cdemo_sk": f"{sp}_bill_cdemo_sk",
        f"{p}_refunded_hdemo_sk": f"{sp}_bill_hdemo_sk",
        f"{p}_refunded_addr_sk": f"{sp}_bill_addr_sk",
        f"{p}_item_sk": f"{sp}_item_sk",
        f"{p}_order_number": f"{sp}_order_number",
    }
    if col in refunded:
        return sale_col(sf, refunded[col], r, c)
    if col == f"{p}_returning_customer_sk":
        return _hui(t, col, sf, idx, 1, c["customer"])
    if col == f"{p}_returning_cdemo_sk":
        return _hui(t, col, sf, idx, 1, c["customer_demographics"])
    if col == f"{p}_returning_hdemo_sk":
        return _hui(t, col, sf, idx, 1, 7200)
    if col == f"{p}_returning_addr_sk":
        return _hui(t, col, sf, idx, 1, c["customer_address"])
    if col in extra:
        return extra[col](idx, r)
    raise KeyError(col)


def _cr_col(sf, col, idx, c):
    t = "catalog_returns"
    extra = {
        "cr_call_center_sk": lambda i, r: _cs_col(
            sf, "cs_call_center_sk", r, c),
        "cr_catalog_page_sk": lambda i, r: _cs_col(
            sf, "cs_catalog_page_sk", r, c),
        "cr_ship_mode_sk": lambda i, r: _cs_col(sf, "cs_ship_mode_sk",
                                                r, c),
        "cr_warehouse_sk": lambda i, r: _cs_col(sf, "cs_warehouse_sk",
                                                r, c),
    }
    return _returnish_col(t, "cr", _cs_col, "cs", sf, col, idx, c, extra)


def _wr_col(sf, col, idx, c):
    t = "web_returns"
    extra = {
        "wr_web_page_sk": lambda i, r: _ws_col(sf, "ws_web_page_sk", r, c),
    }
    return _returnish_col(t, "wr", _ws_col, "ws", sf, col, idx, c, extra)


def _inv_col(sf, col, idx, c):
    n_items = c["item"]
    n_wh = c["warehouse"]
    per_week = n_items * n_wh
    i = idx.astype(np.int64)
    if col == "inv_date_sk":
        return _SALES_MIN + 7 * (i // per_week)
    if col == "inv_warehouse_sk":
        return (i % per_week) // n_items + 1
    if col == "inv_item_sk":
        return i % n_items + 1
    if col == "inv_quantity_on_hand":
        return _hui("inventory", col, sf, idx, 0, 999)
    raise KeyError(col)


def _cd_col(sf, col, idx, c):
    seq = idx.astype(np.int64)
    if col == "cd_demo_sk":
        return seq + 1
    if col == "cd_purchase_estimate":
        return (seq // 70) % 20 * 500 + 500
    if col == "cd_dep_count":
        return (seq // 5600) % 7
    if col == "cd_dep_employed_count":
        return (seq // 39200) % 7
    if col == "cd_dep_college_count":
        return (seq // 274400) % 7
    raise KeyError(col)   # string columns handled via pools below


_CD_POOLS = {
    "cd_gender": (["M", "F"], lambda seq: seq % 2),
    "cd_marital_status": (["M", "S", "D", "W", "U"],
                          lambda seq: (seq // 2) % 5),
}


def chunk_numeric(table: str, sf: float, col: str, start: int,
                  end: int) -> np.ndarray:
    c = _row_counts(sf)
    idx = np.arange(start, end, dtype=np.uint64)
    fn = {"store_sales": _ss_col, "catalog_sales": _cs_col,
          "store_returns": _sr_col, "catalog_returns": _cr_col,
          "web_sales": _ws_col, "web_returns": _wr_col,
          "inventory": _inv_col, "customer_demographics": _cd_col}[table]
    out = fn(sf, col, idx, c)
    return np.asarray(out, dtype=np.int64)


def chunk_string(table: str, sf: float, col: str, start: int, end: int):
    """(codes int32, sorted pool) for a chunked table's pooled varchar."""
    seq = np.arange(start, end, dtype=np.int64)
    if table == "customer_demographics":
        if col in _CD_POOLS:
            pool, pick = _CD_POOLS[col]
        elif col == "cd_education_status":
            pool, pick = _EDUCATION, lambda s: (s // 10) % len(_EDUCATION)
        elif col == "cd_credit_rating":
            pool, pick = _CREDIT, lambda s: (s // 1400) % len(_CREDIT)
        else:
            raise KeyError(col)
        arr = np.asarray(pool, dtype=object)
        sorted_vals, inv = np.unique(arr, return_inverse=True)
        return inv.astype(np.int32)[pick(seq)], sorted_vals
    raise KeyError((table, col))


def _chunked_get_table(table: str, sf: float) -> Dict[str, np.ndarray]:
    """Materialize a chunked table fully (oracle loading at tiny SF)."""
    n = table_row_count(table, sf)
    out = {}
    for name, typ in TABLES[table][0]:
        if T.is_string(typ):
            codes, pool = chunk_string(table, sf, name, 0, n)
            out[name] = pool[codes]
        else:
            out[name] = chunk_numeric(table, sf, name, 0, n)
    return out


def get_table(table: str, sf: float) -> Dict[str, np.ndarray]:
    key = (table, round(sf * 1000))
    if key not in _TABLE_CACHE:
        if table in _CHUNKED:
            _TABLE_CACHE[key] = _chunked_get_table(table, sf)
        else:
            _TABLE_CACHE[key] = _gen_table(table, sf)
    return _TABLE_CACHE[key]


# FK suffix -> referenced dimension (a fact's *_sk columns draw from the
# dimension's key domain — claiming NDV = fact row count breaks join-order
# costing exactly like tpch's l_partkey did in round 4)
_SK_DOMAIN = {
    "item_sk": "item", "date_sk": "date_dim", "time_sk": "time_dim",
    "customer_sk": "customer", "cdemo_sk": "customer_demographics",
    "hdemo_sk": "household_demographics", "addr_sk": "customer_address",
    "store_sk": "store", "warehouse_sk": "warehouse",
    "promo_sk": "promotion", "income_band_sk": "income_band",
    "band_sk": "income_band", "call_center_sk": "call_center",
    "web_page_sk": "web_page", "catalog_page_sk": "catalog_page",
    "page_sk": "web_page",
    "web_site_sk": "web_site", "ship_mode_sk": "ship_mode",
    "reason_sk": "reason",
}


def _column_ndv(table: str, name: str, sf: float, rows: float) -> float:
    if name.endswith("_sk"):
        # own primary key -> row count; FK -> referenced dimension size
        for suffix, dim in _SK_DOMAIN.items():
            if name.endswith(suffix):
                if dim == table:
                    return rows
                try:
                    return float(table_row_count(dim, sf))
                except KeyError:
                    return rows
        return rows
    if name in ("d_year",):
        return 201.0
    if name in ("d_moy", "d_dom"):
        return 31.0
    if name == "d_week_seq":
        return float(_DATE_ROWS) / 7
    return float(min(rows, 1000.0))


def table_row_count(table: str, sf: float) -> int:
    counts = _row_counts(sf)
    if table == "inventory":
        weeks = len(np.arange(_SALES_MIN, _SALES_MAX, 7))
        return counts["item"] * counts["warehouse"] * weeks
    if table == "store_returns":
        return max(1, counts["store_sales"] // 10)
    if table == "catalog_returns":
        return max(1, counts["catalog_sales"] // 10)
    if table == "web_returns":
        return max(1, counts["web_sales"] // 10)
    return counts[table]


def table_dictionary(table: str, sf: float, column: str) -> Dictionary:
    key = (table, round(sf * 1000), column)
    if key not in _DICT_CACHE:
        if table in _CHUNKED:
            _, pool = chunk_string(table, sf, column, 0, 1)
            _DICT_CACHE[key] = Dictionary(pool)
        else:
            data = get_table(table, sf)[column]
            _DICT_CACHE[key] = Dictionary.build(data)[0]
    return _DICT_CACHE[key]


class TpcdsMetadata(ConnectorMetadata):
    """plugin/trino-tpcds TpcdsMetadata.java analog."""

    def list_schemas(self) -> List[str]:
        return sorted(SCHEMAS)

    def list_tables(self, schema: Optional[str] = None
                    ) -> List[SchemaTableName]:
        schemas = [schema] if schema else sorted(SCHEMAS)
        return [SchemaTableName(s, t) for s in schemas for t in sorted(TABLES)]

    def get_table_handle(self, name: SchemaTableName
                         ) -> Optional[ConnectorTableHandle]:
        if name.schema in SCHEMAS and name.table in TABLES:
            return ConnectorTableHandle(name)
        return None

    def get_table_metadata(self, handle: ConnectorTableHandle
                           ) -> TableMetadata:
        cols = tuple(ColumnMetadata(n, t)
                     for n, t in TABLES[handle.name.table][0])
        return TableMetadata(handle.name, cols)

    def get_table_statistics(self, handle: ConnectorTableHandle
                             ) -> TableStatistics:
        sf = SCHEMAS[handle.name.schema]
        rows = float(table_row_count(handle.name.table, sf))
        cols: Dict[str, ColumnStatistics] = {}
        for name, typ in TABLES[handle.name.table][0]:
            cols[name] = ColumnStatistics(
                null_fraction=0.0,
                distinct_count=_column_ndv(handle.name.table, name, sf,
                                           rows))
        return TableStatistics(rows, cols)

    def apply_filter(self, handle, constraint):
        merged = handle.constraint.intersect(constraint)
        return (ConnectorTableHandle(handle.name, merged, handle.limit),
                constraint)

    def apply_limit(self, handle, limit):
        if handle.limit is not None and handle.limit <= limit:
            return None
        return ConnectorTableHandle(handle.name, handle.constraint, limit)


class TpcdsSplitManager(ConnectorSplitManager):
    def get_splits(self, handle: ConnectorTableHandle,
                   target_splits: int = 1) -> List[Split]:
        sf = SCHEMAS[handle.name.schema]
        rows = table_row_count(handle.name.table, sf)
        parts = max(1, min(target_splits, math.ceil(rows / 4096)))
        return [Split(handle, p, parts, host=p) for p in range(parts)]


class TpcdsPageSource(ConnectorPageSource):
    def pages(self, split: Split, columns: Sequence[ColumnHandle],
              page_capacity: int) -> Iterator[Page]:
        handle = split.table
        table = handle.name.table
        sf = SCHEMAS[handle.name.schema]
        total = table_row_count(table, sf)
        start, end = split_range(total, split.part, split.total_parts)
        if handle.limit is not None:
            end = min(end, start + handle.limit)
        chunked = table in _CHUNKED
        data = None if chunked else get_table(table, sf)
        from trino_tpu.connector.tpch import _host_cached
        for off in range(start, end, page_capacity):
            hi = min(off + page_capacity, end)
            n = hi - off
            cols = []
            for ch in columns:
                hkey = ("tpcds", table, round(sf * 1000), ch.name, off, hi)
                if T.is_string(ch.type):
                    d = table_dictionary(table, sf, ch.name)
                    if chunked:
                        codes = _host_cached(hkey, lambda: chunk_string(
                            table, sf, ch.name, off, hi)[0])
                    else:
                        codes = _host_cached(hkey, lambda: d.encode(
                            data[ch.name][off:hi]))
                    cols.append(Column.from_numpy(
                        pad_to_capacity(codes, page_capacity, 0), ch.type,
                        dictionary=d))
                else:
                    if chunked:
                        arr = _host_cached(hkey, lambda: np.asarray(
                            chunk_numeric(table, sf, ch.name, off, hi),
                            T.to_numpy_dtype(ch.type)))
                    else:
                        # materialized tables: slicing is free — caching
                        # would duplicate _TABLE_CACHE bytes in the LRU
                        arr = np.asarray(data[ch.name][off:hi],
                                         T.to_numpy_dtype(ch.type))
                    cols.append(Column.from_numpy(
                        pad_to_capacity(arr, page_capacity, 0), ch.type))
            yield Page(tuple(cols), n)


def create_connector() -> Connector:
    return Connector("tpcds", TpcdsMetadata(), TpcdsSplitManager(),
                     TpcdsPageSource())
