"""Logical planner tests.

Mirrors sql/planner/TestLogicalPlanner.java + BasePlanTest plan-shape
assertions: plan SQL, assert on node structure.
"""

import pytest

from trino_tpu import types as T
from trino_tpu.connector import tpch, memory
from trino_tpu.connector.spi import CatalogManager
from trino_tpu.metadata import Metadata, Session
from trino_tpu.planner import LogicalPlanner
from trino_tpu.planner.nodes import (
    AggregationNode, FilterNode, GroupIdNode, JoinNode, JoinKind, LimitNode,
    OutputNode, ProjectNode, SemiJoinNode, SortNode, TableScanNode, UnionNode,
    ValuesNode, visit_plan, format_plan, EnforceSingleRowNode)
from trino_tpu.sql import parse_statement
from trino_tpu.sql.analyzer import SemanticError

from test_parser import TPCH


@pytest.fixture(scope="module")
def metadata():
    cm = CatalogManager()
    cm.register("tpch", tpch.create_connector())
    cm.register("memory", memory.create_connector())
    return Metadata(cm)


def plan(metadata, sql):
    return LogicalPlanner(metadata, Session()).plan(parse_statement(sql))


def nodes_of(p, cls):
    return [n for n in visit_plan(p) if isinstance(n, cls)]


def test_scan_filter_project(metadata):
    p = plan(metadata, "SELECT n_name FROM nation WHERE n_regionkey = 1")
    assert isinstance(p, OutputNode)
    assert p.column_names == ("n_name",)
    scans = nodes_of(p, TableScanNode)
    assert len(scans) == 1
    assert str(scans[0].table.name) == "tiny.nation"
    assert len(nodes_of(p, FilterNode)) == 1


def test_aggregation_plan_shape(metadata):
    p = plan(metadata,
             "SELECT l_returnflag, sum(l_quantity) FROM lineitem "
             "GROUP BY l_returnflag")
    aggs = nodes_of(p, AggregationNode)
    assert len(aggs) == 1
    agg = aggs[0]
    assert len(agg.group_by) == 1
    assert agg.aggregations[0][1].name == "sum"
    # agg output name defaults to _colN when unaliased
    assert p.column_names[0] == "l_returnflag"


def test_group_by_ordinal_and_alias(metadata):
    p = plan(metadata,
             "SELECT n_regionkey AS rk, count(*) c FROM nation GROUP BY 1 "
             "ORDER BY c DESC")
    agg = nodes_of(p, AggregationNode)[0]
    assert len(agg.group_by) == 1
    assert len(nodes_of(p, SortNode)) == 1


def test_join_extraction(metadata):
    p = plan(metadata,
             "SELECT c_name, o_orderkey FROM customer JOIN orders "
             "ON c_custkey = o_custkey AND o_totalprice > 100")
    joins = nodes_of(p, JoinNode)
    assert len(joins) == 1
    j = joins[0]
    assert j.kind == JoinKind.INNER
    assert len(j.criteria) == 1
    assert j.filter is not None  # non-equi residual


def test_implicit_cross_join_with_where(metadata):
    p = plan(metadata,
             "SELECT c_name FROM customer, orders WHERE c_custkey = o_custkey")
    joins = nodes_of(p, JoinNode)
    assert len(joins) == 1
    assert joins[0].kind == JoinKind.CROSS
    # predicate stays in WHERE; optimizer will push it into join criteria
    assert len(nodes_of(p, FilterNode)) == 1


def test_in_subquery_plans_semijoin(metadata):
    p = plan(metadata, TPCH[18])
    semis = nodes_of(p, SemiJoinNode)
    assert len(semis) == 1
    assert len(semis[0].source_keys) == 1


def test_correlated_exists_plans_semijoin(metadata):
    p = plan(metadata, """
        SELECT c_name FROM customer
        WHERE EXISTS (SELECT 1 FROM orders WHERE o_custkey = c_custkey)""")
    semis = nodes_of(p, SemiJoinNode)
    assert len(semis) == 1


def test_not_exists(metadata):
    p = plan(metadata, TPCH[22])
    semis = nodes_of(p, SemiJoinNode)
    assert len(semis) == 1
    # scalar subquery becomes enforce-single-row + cross join
    assert len(nodes_of(p, EnforceSingleRowNode)) == 1


def test_correlated_scalar_agg_decorrelates(metadata):
    p = plan(metadata, """
        SELECT p_partkey FROM part, partsupp
        WHERE p_partkey = ps_partkey
          AND ps_supplycost = (SELECT min(ps_supplycost) FROM partsupp
                               WHERE ps_partkey = p_partkey)""")
    # decorrelated: LEFT join against an aggregation grouped by the key
    joins = nodes_of(p, JoinNode)
    assert any(j.kind == JoinKind.LEFT for j in joins)
    aggs = nodes_of(p, AggregationNode)
    assert any(len(a.group_by) == 1 and a.aggregations for a in aggs)


def test_values_and_union(metadata):
    p = plan(metadata, "SELECT * FROM (VALUES (1, 'a'), (2, 'b')) t(x, y)")
    vals = nodes_of(p, ValuesNode)
    assert len(vals) == 1 and len(vals[0].rows) == 2

    p = plan(metadata, "SELECT 1 AS x UNION ALL SELECT 2")
    assert len(nodes_of(p, UnionNode)) == 1

    p = plan(metadata, "SELECT 1 AS x UNION SELECT 2")
    # distinct union adds an aggregation
    assert len(nodes_of(p, AggregationNode)) == 1


def test_rollup_plans_groupid(metadata):
    p = plan(metadata,
             "SELECT n_regionkey, count(*) FROM nation GROUP BY ROLLUP (n_regionkey)")
    gids = nodes_of(p, GroupIdNode)
    assert len(gids) == 1
    assert len(gids[0].grouping_sets) == 2  # (n_regionkey), ()


def test_limit_and_distinct(metadata):
    p = plan(metadata, "SELECT DISTINCT n_regionkey FROM nation LIMIT 3")
    assert len(nodes_of(p, LimitNode)) == 1
    assert len(nodes_of(p, AggregationNode)) == 1


def test_cte(metadata):
    p = plan(metadata, """
        WITH big AS (SELECT o_custkey FROM orders WHERE o_totalprice > 1000)
        SELECT count(*) FROM big""")
    assert len(nodes_of(p, TableScanNode)) == 1
    assert len(nodes_of(p, AggregationNode)) == 1


def test_semantic_errors(metadata):
    with pytest.raises(SemanticError, match="cannot be resolved"):
        plan(metadata, "SELECT nope FROM nation")
    with pytest.raises(SemanticError, match="not found"):
        plan(metadata, "SELECT * FROM nonexistent_table")
    with pytest.raises(SemanticError, match="GROUP BY"):
        plan(metadata, "SELECT n_name, count(*) FROM nation GROUP BY n_regionkey")
    with pytest.raises(SemanticError, match="ambiguous"):
        plan(metadata,
             "SELECT n_nationkey FROM nation a, nation b")


def test_coercions_in_comparison(metadata):
    # l_quantity is decimal(12,2); literal 24 is integer -> coerced
    p = plan(metadata, "SELECT 1 x FROM lineitem WHERE l_quantity < 24")
    f = nodes_of(p, FilterNode)[0]
    assert "lt(" in str(f.predicate)
    # the literal must be scaled to match decimal(12,2): 24 -> 2400
    assert "2400" in str(f.predicate)


@pytest.mark.parametrize("qnum", [q for q in sorted(TPCH) if q != 21])
def test_tpch_plans(metadata, qnum):
    p = plan(metadata, TPCH[qnum])
    assert isinstance(p, OutputNode)
    text = format_plan(p)
    assert "TableScan" in text
