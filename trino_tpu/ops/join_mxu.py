"""MXU-native join kernels: density-partitioned indicator matmuls.

"Density-optimized Intersection-free Mapping and Matrix Multiplication
for Join-Project Operations" (arXiv 2206.04995) lowers join+project,
semijoin, and distinct-project onto blocked matmuls over 0/1
key-indicator matrices: give every key of a dense range its own matrix
column (the intersection-free mapping — slot identity IS key equality,
nothing to re-verify), partition the range into MXU-aligned column
blocks, and let the matrix unit brute-force the lookups the gather path
serves with memory-bound sort-engine / gather passes. JSPIM
(arXiv 2508.08503) motivates routing between the strategies by observed
density and skew — the router lives in
exec/local_planner._prepare_probe and reads the CBO estimates stamped
by planner/optimizer.annotate_adaptive_hints.

Two kernel families:

  matmul_lookup — per probe row, (match count, first sorted build
    position) against the build side's per-key [count, pos] table: one
    (rows x BLOCK) @ (BLOCK x 2) `jnp.dot` per key-range block. The
    result feeds hash_join's existing cumsum-expansion machinery, so
    INNER join-project, semijoin, anti-semijoin and mark probes execute
    as matmul kernels with outputs byte-identical to the gather path.

  aggregate tables (scatter_agg_table + blocked_lookup) — the
    many-to-many aggregating join (TPC-DS q64/q72 shapes). The paper's
    M = A·Bᵀ match multiplicities feed SUM/COUNT directly: the build
    side scatters to per-key [pair count, Σw, #valid w] vectors, each
    probe row matmul-looks-up its key's vector, and the join never
    materializes the cross product — a probe row carries its pair
    multiplicity instead of expanding `count` times through the
    capacity-laddered gather kernels.

Accumulation dtypes (the low-precision-accumulate-safe choice): lookup
matmuls multiply one-hot rows against values bounded by the build row
count, so f32 accumulation is EXACT while every operand stays under
2^24 — the router gates builds at 16M rows. Aggregate tables carry
value sums: f64 on CPU (exact for int64/short-decimal sums < 2^53),
f32 on TPU where f32 is the MXU's native accumulate and the engine's
doubles are approximate anyway.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

# MXU-aligned key-range block width (the 128x128 systolic array tiles
# 512-wide operands without padding waste; CPU Eigen likes it too)
BLOCK = 512

# f32 one-hot lookups are exact only while counts/positions fit the
# mantissa: the router refuses builds at or past this row count
MAX_EXACT_ROWS = 1 << 24

# Accumulation of integer/short-decimal build sums is exact only while
# every per-key total stays inside the accumulation dtype's mantissa:
# 2^53 for f64 (CPU), 2^24 for f32 (TPU/GPU). scatter_agg_table checks
# the built table against the bound for ITS dtype and the router falls
# back to the gather join's exact int64 arithmetic past it.
MAX_EXACT_INT_SUM = float(1 << 53)


def exact_int_sum_bound(dtype) -> float:
    return MAX_EXACT_INT_SUM if dtype == jnp.float64 \
        else float(1 << 24)


def accum_dtype():
    """Aggregate-table accumulation dtype per platform (see module
    docstring): f64 on CPU, f32 on TPU/GPU."""
    try:
        backend = jax.default_backend()
    except Exception:        # pragma: no cover - backend probe failure
        backend = "cpu"
    return jnp.float64 if backend == "cpu" else jnp.float32


def distinct_live_keys(bkey_s: jnp.ndarray,
                       n_live: jnp.ndarray) -> jnp.ndarray:
    """Distinct key count over the sorted live prefix — the numerator of
    the router's observed density (distinct keys / key span)."""
    n = bkey_s.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    boundary = (bkey_s != jnp.roll(bkey_s, 1)).at[0].set(True)
    return jnp.sum(boundary & (idx < n_live)).astype(jnp.int32)


def build_count_pos_table(slots: int):
    """Build-side per-key [match count, first sorted position] table over
    the dense key range [kmin, kmin + slots): the columns of the
    indicator matrix, materialized as the (slots x 2) right-hand matmul
    operand. Dead/out-of-span keys route to a dropped slot. Returns
    op(bkey_s, n_live, kmin) -> f32 (slots, 2)."""

    def op(bkey_s, n_live, kmin):
        n = bkey_s.shape[0]
        idx = jnp.arange(n, dtype=jnp.int32)
        live = idx < n_live
        raw = (bkey_s - kmin).astype(jnp.int64)
        oob = ~live | (raw < 0) | (raw >= slots)
        slot = jnp.where(oob, slots, raw)
        cnt = jnp.zeros(slots + 1, dtype=jnp.float32) \
            .at[slot].add(jnp.where(oob, 0.0, 1.0))
        pos = jnp.full(slots + 1, float(n), dtype=jnp.float32) \
            .at[slot].min(idx.astype(jnp.float32))
        return jnp.stack([cnt[:slots], pos[:slots]], axis=1)

    return op


def blocked_lookup(table: jnp.ndarray, kmin, pkey: jnp.ndarray,
                   block: int = BLOCK) -> jnp.ndarray:
    """The core MXU kernel: per-row one-hot lookup of `table[key - kmin]`
    as a sequence of (rows x block) @ (block x C) matmuls over key-range
    blocks. Out-of-span keys produce all-zero rows (no match — exactly
    the intersection-free contract). Accumulates in the table's dtype."""
    slots, ncols = table.shape
    dtype = table.dtype
    raw = (pkey - kmin).astype(jnp.int64)
    inb = (raw >= 0) & (raw < slots)
    off = jnp.where(inb, raw, -1).astype(jnp.int32)
    n = pkey.shape[0]
    acc = jnp.zeros((n, ncols), dtype=dtype)
    step = min(block, slots)
    for start in range(0, slots, step):
        stop = min(start + step, slots)   # ragged last block is fine
        cols = jnp.arange(start, stop, dtype=jnp.int32)
        onehot = (off[:, None] == cols[None, :]).astype(dtype)
        acc = acc + jnp.dot(onehot, table[start:stop],
                            preferred_element_type=dtype)
    return acc


def matmul_lookup(table: jnp.ndarray, kmin, pkey: jnp.ndarray,
                  block: int = BLOCK
                  ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(count, first sorted position) per probe key via blocked indicator
    matmuls — the MXU replacement for the dense-gather / searchsorted
    probe. Absent keys: count 0 (position is meaningless there; callers
    mask on count)."""
    looked = blocked_lookup(table, kmin, pkey, block=block)
    return (looked[:, 0].astype(jnp.int32),
            looked[:, 1].astype(jnp.int32))


def scatter_agg_table(slots: int, vec_specs, key_channel: int,
                      dtype=None):
    """Build-side accumulation table for the aggregating join: one
    scatter-add per vector over the dense key range. `vec_specs` is a
    tuple of ('cnt',) | ('sum', channel, 'i'|'f') |
    ('validcnt', channel) — the per-key pair count, Σ of a build column
    over live rows (nulls add 0), and the per-key count of non-null
    values of a build column.
    Returns op(build_page, kmin) -> (table (slots x C), distinct_keys,
    mag_ok): distinct feeds the router's density check, and mag_ok is
    False when any INTEGER-kind per-key sum reached the accumulation
    dtype's exact-integer bound (2^53 for f64, 2^24 for f32), so the
    router must fall back to the gather join's exact int64 arithmetic
    (float-kind sums are excluded: f64 is the engine's double
    arithmetic anyway)."""
    from trino_tpu.ops.join import _key_u64
    vec_specs = tuple(vec_specs)

    def op(build, kmin):
        dt = accum_dtype() if dtype is None else dtype
        bkey, bnull = _key_u64(build, (key_channel,))
        live = build.row_mask() & ~bnull
        raw = (bkey - kmin).astype(jnp.int64)
        oob = ~live | (raw < 0) | (raw >= slots)
        slot = jnp.where(oob, slots, raw)
        cols = []
        for spec in vec_specs:
            if spec[0] == "cnt":
                vec = jnp.where(oob, 0.0, 1.0)
            else:
                c = build.column(spec[1])
                valid = c.valid_mask() & ~oob
                if spec[0] == "validcnt":
                    vec = jnp.where(valid, 1.0, 0.0)
                else:
                    vec = jnp.where(valid, c.values.astype(dt), 0)
            cols.append(jnp.zeros(slots + 1, dtype=dt)
                        .at[slot].add(vec.astype(dt))[:slots])
        table = jnp.stack(cols, axis=1)
        cnt_idx = vec_specs.index(("cnt",))
        distinct = jnp.sum(table[:, cnt_idx] > 0).astype(jnp.int32)
        mag_ok = jnp.bool_(True)
        bound = exact_int_sum_bound(dt)
        for i, spec in enumerate(vec_specs):
            if spec[0] == "sum" and spec[2] == "i":
                mag_ok = mag_ok & (jnp.max(jnp.abs(table[:, i]))
                                   < bound)
        return table, distinct, mag_ok

    return op


def key_bounds(channel: int):
    """Live-key min/max in u64 key space for one channel — the fused
    aggregating join's span probe (kmin > kmax signals an all-dead
    build). Returns op(page) -> (kmin, kmax)."""
    from trino_tpu.ops.join import _key_u64

    def op(page):
        key, null = _key_u64(page, (channel,))
        live = page.row_mask() & ~null
        u64max = jnp.uint64(0xFFFFFFFFFFFFFFFF)
        kmin = jnp.min(jnp.where(live, key, u64max))
        kmax = jnp.max(jnp.where(live, key, jnp.uint64(0)))
        return kmin, kmax

    return op


def agg_join_lookup(key_channel: int, group_channels, derive, helpers,
                    block: int = BLOCK):
    """Per-probe-page derived rows for the fused aggregating join: group
    columns pass through, each aggregate becomes a per-row contribution
    built from the row's matmul-looked-up per-key build vector (its pair
    multiplicity / Σw / #valid-w), and rows with no match (or dead /
    null-key rows) filter out — the page that feeds the standard SINGLE
    aggregation is at most probe-sized, never the cross product.

    `derive` entries (one per aggregate, planner-encoded):
      ('pairs',)                 count(*)  -> pair multiplicity
      ('cntp', probe_ch)         count(p.v) -> multiplicity where v valid
      ('sump', probe_ch, 'i'|'f') sum(p.v) -> v * multiplicity (NULL
                                  rides the probe column's validity)
      ('cntb', vec_idx)          count(b.w) -> looked-up #valid-w
      ('sumb', vec_idx, 'i'|'f', helper_pos) sum(b.w) -> looked-up Σw
    `helpers` lists the #valid-w vector indices that must ride along as
    extra summed columns (the post kernel turns them into SUM null
    masks). Returns op(page, table, kmin) -> Page."""
    from trino_tpu import types as T
    from trino_tpu.ops.join import _key_u64
    from trino_tpu.page import Column, Page
    group_channels = tuple(group_channels)
    derive = tuple(derive)
    helpers = tuple(helpers)

    def op(page, table, kmin):
        pkey, pnull = _key_u64(page, (key_channel,))
        looked = blocked_lookup(table, kmin, pkey, block=block)
        cnt = looked[:, 0]
        cnt_i = cnt.astype(jnp.int64)
        live = page.row_mask() & ~pnull & (cnt > 0)
        cols = [page.columns[ch] for ch in group_channels]
        for d in derive:
            if d[0] == "pairs":
                cols.append(Column(cnt_i, None, T.BIGINT, None))
            elif d[0] == "cntp":
                c = page.column(d[1])
                cols.append(Column(jnp.where(c.valid_mask(), cnt_i, 0),
                                   None, T.BIGINT, None))
            elif d[0] == "sump":
                c = page.column(d[1])
                if d[2] == "f":
                    vals = c.values.astype(jnp.float64) * \
                        cnt.astype(jnp.float64)
                    typ = T.DOUBLE
                else:
                    vals = c.values.astype(jnp.int64) * cnt_i
                    typ = T.BIGINT
                cols.append(Column(vals, c.valid, typ, None))
            elif d[0] == "cntb":
                cols.append(Column(looked[:, d[1]].astype(jnp.int64),
                                   None, T.BIGINT, None))
            else:   # 'sumb'
                vals = looked[:, d[1]]
                if d[2] == "f":
                    cols.append(Column(vals.astype(jnp.float64), None,
                                       T.DOUBLE, None))
                else:
                    cols.append(Column(vals.astype(jnp.int64), None,
                                       T.BIGINT, None))
        for h in helpers:
            cols.append(Column(looked[:, h].astype(jnp.int64), None,
                               T.BIGINT, None))
        return Page(tuple(cols), page.num_rows).filter(live)

    return op


def agg_join_post(nk: int, derive, nhelpers: int, out_types):
    """Final shaping after the SINGLE aggregation over derived rows:
    re-type sums/counts to the plan's declared output types, restore SQL
    null semantics for build-side SUMs (NULL when the group saw no
    non-null build value — the summed #valid-w helper is the mask), and
    drop the helper columns. Returns op(agg_page) -> Page."""
    from trino_tpu.page import Column, Page
    derive = tuple(derive)
    out_types = tuple(out_types)

    def op(page):
        cols = list(page.columns[:nk])
        base = nk
        for i, (d, typ) in enumerate(zip(derive, out_types)):
            c = page.columns[base + i]
            if d[0] in ("pairs", "cntp", "cntb"):
                cols.append(Column(c.values, None, typ, None))
            elif d[0] == "sump":
                cols.append(Column(c.values, c.valid, typ, None))
            else:   # 'sumb'
                helper = page.columns[base + len(derive) + d[3]]
                cols.append(Column(c.values, helper.values > 0, typ,
                                   None))
        return Page(tuple(cols), page.num_rows)

    return op


def lookup_flops(rows: int, slots: int, ncols: int) -> int:
    """Cost-model MAC count of one blocked lookup dispatch (2 flops per
    multiply-accumulate — matches XLA's dot cost model), recorded on the
    query's mxu_flops counter per dispatch."""
    return 2 * int(rows) * int(slots) * int(ncols)
