"""Warmup/preload manifest: a cold server start that serves warm.

Reference parity: production deployments front the reference with
warm-up query storms (benchto's prewarm phase) because the first run of
every shape pays planning + codegen. On this engine the costs are plan
cache misses and XLA compiles — both cacheable — so the server takes a
MANIFEST of representative statements at startup
(`TrinoServer(warmup_manifest=...)` or $TRINO_TPU_WARMUP_MANIFEST),
PREPAREs the named ones into the shared prepared-statement map, and
executes each once: that populates the plan cache (value-free keys for
prepared statements — ANY later parameter values hit), traces every
kernel of the shape into the jit cache (loading compiled binaries from
the persistent compilation cache when one is configured, so even the
XLA compile is a disk read), and optionally seeds the result cache.
The first real user request then binds + dispatches: plan_cache_hits=1,
jit_misses=0.

Manifest format (JSON; a bare list of statement specs also loads):

    {"statements": [
      {"name": "dash_q6", "sql": "SELECT ... WHERE l_quantity < ?",
       "using": "24"},
      {"sql": "SELECT count(*) FROM nation"}
    ]}

`name` + `sql` with `?` markers -> PREPARE name FROM sql, then (when
`using` is present) EXECUTE name USING <using>. Plain `sql` executes
directly. A failing statement is recorded in the report and does NOT
abort the server start — a partially warm server beats no server.
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional, Union


def load_manifest(source: Union[str, dict, list]) -> List[Dict[str, Any]]:
    """Path / parsed dict / bare list -> the statement-spec list."""
    if isinstance(source, str):
        with open(source) as f:
            source = json.load(f)
    if isinstance(source, list):
        statements = source
    elif isinstance(source, dict):
        statements = source.get("statements")
        if statements is None:
            raise ValueError(
                "warmup manifest needs a top-level 'statements' list "
                f"(got keys: {sorted(source)})")
    else:
        raise ValueError(
            f"warmup manifest must be a path, dict, or list, "
            f"not {type(source).__name__}")
    out = []
    for i, spec in enumerate(statements):
        if not isinstance(spec, dict) or "sql" not in spec:
            raise ValueError(
                f"warmup statement #{i} needs an object with 'sql' "
                f"(got {spec!r})")
        unknown = sorted(set(spec) - {"name", "sql", "using"})
        if unknown:
            # same strictness as resource-group config: a typo'd key must
            # not silently skip the warmup the operator asked for
            raise ValueError(
                f"warmup statement #{i}: unknown keys {unknown}")
        out.append(spec)
    return out


def apply_warmup(runner, source: Union[str, dict, list]
                 ) -> List[Dict[str, Any]]:
    """Run the manifest against `runner` (the server's BASE runner, so
    PREPAREd names land in the shared map every request can EXECUTE).
    Returns the per-statement report: what warmed, what it cost, what
    the first real request will now skip."""
    report: List[Dict[str, Any]] = []
    for spec in load_manifest(source):
        name = spec.get("name")
        label = name or spec["sql"][:60]
        entry: Dict[str, Any] = {"statement": label}
        t0 = time.perf_counter()
        try:
            if name:
                runner.execute(f"PREPARE {name} FROM {spec['sql']}")
                if spec.get("using"):
                    runner.execute(
                        f"EXECUTE {name} USING {spec['using']}")
            else:
                runner.execute(spec["sql"])
            stats = runner.last_query_stats
            entry.update({
                "wall_s": round(time.perf_counter() - t0, 4),
                "jit_misses": int(stats.get("jit_misses", 0)),
                "plan_cached": int(stats.get("plan_cache_misses", 0)) > 0
                or int(stats.get("plan_cache_hits", 0)) > 0,
            })
        except Exception as e:  # noqa: BLE001 — warm what we can
            entry["error"] = f"{type(e).__name__}: {str(e)[:160]}"
        report.append(entry)
    return report
