"""Serving tier (trino_tpu/serve/): streaming protocol, result/scan
caches, warmup manifest, weighted CPU scheduling, QPS closed loop.

The ISSUE-8 acceptance suite: a streaming client sees its first page
before the query completes, a slow client's backpressure bounds the
ring, result-cache hits are zero-work and INSERT provably invalidates,
2:1 group weights drain 2:1 under concurrent load, and a warmup
manifest leaves the first real EXECUTE fully warm.
"""

import json
import threading
import time
import urllib.request

import pytest

from trino_tpu.exec import LocalQueryRunner
from trino_tpu.server import TrinoServer


def _post(server, sql, headers=None):
    req = urllib.request.Request(
        f"{server.base_uri}/v1/statement", data=sql.encode(),
        method="POST")
    req.add_header("X-Trino-User", "serve-test")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    with urllib.request.urlopen(req) as resp:
        return json.loads(resp.read())


def _get(uri):
    with urllib.request.urlopen(uri) as resp:
        return json.loads(resp.read())


def _drain(server, sql, headers=None):
    payload = _post(server, sql, headers)
    rows = []
    states = [payload["stats"]["state"]]
    while "nextUri" in payload:
        payload = _get(payload["nextUri"])
        states.append(payload["stats"]["state"])
        rows.extend(payload.get("data", []))
    return payload, rows, states


def _tracker_stats(query_id):
    from trino_tpu.exec.query_tracker import TRACKER
    info = next(q for q in TRACKER.list() if q.query_id == query_id)
    return info.stats


# ------------------------------------------------------------ streaming


def test_streaming_first_page_before_completion():
    """The async lifecycle contract: with a 1-chunk ring and a 2-chunk
    result, the client's first data page arrives while the query is
    still RUNNING — execution is paused at the ring, not finished."""
    srv = TrinoServer(LocalQueryRunner.tpch("tiny"),
                      stream_ring_chunks=1, result_cache=False,
                      scan_cache=False).start()
    try:
        payload = _post(srv, "SELECT c_custkey FROM customer")
        first_data_state = None
        rows = []
        states = [payload["stats"]["state"]]
        while "nextUri" in payload:
            payload = _get(payload["nextUri"])
            states.append(payload["stats"]["state"])
            if payload.get("data"):
                if first_data_state is None:
                    first_data_state = payload["stats"]["state"]
                rows.extend(payload["data"])
        assert len(rows) == 1500
        assert first_data_state == "RUNNING", states
        assert states[-1] == "FINISHED"
        assert "FINISHING" in states    # producer-done, ring-draining
    finally:
        srv.stop()


def test_slow_client_backpressure_bounds_ring():
    """A lagging client must pause the producer: the ring never holds
    more than its bound, no matter how large the result."""
    srv = TrinoServer(LocalQueryRunner.tpch("tiny"),
                      stream_ring_chunks=2, result_cache=False,
                      scan_cache=False).start()
    try:
        payload = _post(srv, "SELECT o_orderkey FROM orders")
        qid = payload["id"]
        rows = []
        while "nextUri" in payload:
            time.sleep(0.02)            # the slow client
            payload = _get(payload["nextUri"])
            rows.extend(payload.get("data", []))
        assert len(rows) == 15000       # 15 chunks through a 2-slot ring
        stream = srv._queries[qid].stream
        assert stream.high_watermark <= 2, stream.high_watermark
        assert stream.total_rows == 15000
        stats = _tracker_stats(qid)
        assert stats["streamed_chunks"] >= 15
    finally:
        srv.stop()


def test_stall_timeout_cancels_over_real_http():
    """A client that vanishes mid-stream without DELETE must not pin an
    executor: `stream_stall_timeout_s` fires, the query unwinds as
    CANCELED over the real HTTP path, the executor is freed for the
    next statement, and the leak gate reads pool == 0."""
    from trino_tpu.exec.memory import NODE_POOL
    from trino_tpu.exec.query_tracker import TRACKER
    srv = TrinoServer(LocalQueryRunner.tpch("tiny"),
                      stream_ring_chunks=1, stream_stall_timeout_s=1.0,
                      max_running=1, result_cache=False,
                      scan_cache=False).start()
    try:
        payload = _post(srv, "SELECT o_orderkey FROM orders")
        qid = payload["id"]
        # read until the first data page, then VANISH (no DELETE): the
        # 1-slot ring parks the producer in put()
        while "nextUri" in payload and not payload.get("data"):
            payload = _get(payload["nextUri"])
        assert payload.get("data")
        deadline = time.monotonic() + 15
        info = None
        while time.monotonic() < deadline:
            info = next(q for q in TRACKER.list() if q.query_id == qid)
            if info.state == "CANCELED":
                break
            time.sleep(0.05)
        assert info is not None and info.state == "CANCELED", info.state
        # the ONLY executor (max_running=1) is free again: a follow-up
        # statement dispatches and completes
        done, rows, _ = _drain(srv, "SELECT count(*) FROM nation")
        assert rows == [[25]]
        assert done["stats"]["state"] == "FINISHED"
        # and the canceled query's reservations all rolled back
        deadline = time.monotonic() + 5
        while NODE_POOL.reserved != 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert NODE_POOL.reserved == 0
    finally:
        srv.stop()


def test_stream_ring_unit():
    """ResultStream protocol unit: full chunks publish immediately, the
    partial remainder stages until flush/close (so every non-final
    chunk is exactly chunk_rows — token-aligned with buffered paging),
    ack-on-request frees slots, retry of the current token works,
    acked tokens are gone, close ends."""
    from trino_tpu.serve.streaming import ResultStream
    s = ResultStream(max_chunks=2, chunk_rows=2)
    s.open(["a"], [None])
    s.put([(1,), (2,), (3,)])   # one FULL chunk published, (3,) staged
    assert s.buffered == 1
    status, chunk = s.get(0, timeout=0.1)
    assert status == "chunk" and chunk == [(1,), (2,)]
    assert s.get(0, timeout=0.1)[0] == "chunk"     # same-token retry
    assert s.get(1, timeout=0.05)[0] == "pending"  # remainder staged
    s.close()                   # flushes the partial final chunk
    status, chunk = s.get(1, timeout=0.1)
    assert status == "chunk" and chunk == [(3,)]
    assert s.total_rows == 3
    assert s.get(0, timeout=0.05)[0] == "gone"     # behind the horizon
    assert s.get(2, timeout=0.1)[0] == "end"
    assert s.drained


def test_stream_put_blocks_then_unblocks():
    from trino_tpu.serve.streaming import ResultStream
    s = ResultStream(max_chunks=1, chunk_rows=1)
    s.open(["a"], [None])
    s.put([(0,)])
    done = threading.Event()

    def producer():
        s.put([(1,)])       # blocks until the consumer requests token 1
        done.set()
    th = threading.Thread(target=producer, daemon=True)
    th.start()
    time.sleep(0.1)
    assert not done.is_set()            # full ring is really blocking
    assert s.get(1, timeout=2.0)[0] == "chunk"
    assert done.wait(2.0)
    th.join(timeout=5)


# --------------------------------------------------------- result cache


def test_result_cache_hit_zero_work_and_insert_invalidation():
    """The zero-work contract and the stale-impossible contract, on a
    direct runner: a hit reports planning_s == 0, jit_misses == 0,
    execution_s == 0 with delivery-consistent rows/bytes; INSERT evicts
    result AND scan caches through the plan cache's hooks."""
    r = LocalQueryRunner.tpch("tiny")
    r.session.set("result_cache_enabled", True)
    r.session.set("scan_cache_enabled", True)
    r.execute("CREATE TABLE memory.default.serve_t (a bigint)")
    r.execute("INSERT INTO memory.default.serve_t VALUES 1, 2, 3")
    sql = "SELECT sum(a) FROM memory.default.serve_t"
    assert r.execute(sql).rows == [(6,)]
    miss_stats = dict(r.last_query_stats)
    assert miss_stats["result_cache_misses"] == 1
    assert r.execute(sql).rows == [(6,)]
    hit_stats = dict(r.last_query_stats)
    assert hit_stats["result_cache_hits"] == 1
    assert hit_stats["planning_s"] == 0.0
    assert hit_stats["execution_s"] == 0.0
    assert hit_stats["jit_misses"] == 0
    assert hit_stats["output_rows"] == miss_stats["output_rows"]
    assert hit_stats["output_bytes"] == miss_stats["output_bytes"]
    # INSERT invalidates: the very next run must see the new row (a
    # stale cached 6 is provably impossible, not just unlikely)
    r.execute("INSERT INTO memory.default.serve_t VALUES 10")
    assert r.execute(sql).rows == [(16,)]
    assert r.last_query_stats["result_cache_hits"] == 0
    assert r.last_query_stats["result_cache_misses"] == 1
    # ... and caches again from the fresh data
    assert r.execute(sql).rows == [(16,)]
    assert r.last_query_stats["result_cache_hits"] == 1


def test_scan_cache_hit_and_invalidation():
    r = LocalQueryRunner.tpch("tiny")
    r.session.set("scan_cache_enabled", True)
    sql1 = "SELECT count(*) FROM memory.default.scan_t"
    r.execute("CREATE TABLE memory.default.scan_t (a bigint)")
    r.execute("INSERT INTO memory.default.scan_t VALUES 1, 2")
    assert r.execute(sql1).rows == [(2,)]
    assert r.last_query_stats["scan_cache_misses"] >= 1
    # a DIFFERENT query over the same columns reuses the staged pages
    assert r.execute(
        "SELECT max(a) FROM memory.default.scan_t").rows == [(2,)]
    assert r.last_query_stats["scan_cache_hits"] >= 1
    r.execute("INSERT INTO memory.default.scan_t VALUES 7")
    assert r.execute(sql1).rows == [(3,)]   # invalidated, re-staged


def test_nondeterministic_statements_never_cached():
    """The determinism gate (the engine has no random() yet, so the
    check is exercised on parsed ASTs directly)."""
    from trino_tpu.serve.caches import statement_is_cacheable
    from trino_tpu.sql import parse_statement
    assert not statement_is_cacheable(
        parse_statement("SELECT random() FROM nation"))
    assert not statement_is_cacheable(
        parse_statement("SELECT a, now() FROM t WHERE a < 3"))
    assert statement_is_cacheable(
        parse_statement("SELECT n_name FROM nation WHERE n_nationkey = 1"))


def test_stats_consistent_across_delivery_modes():
    """Satellite contract: QueryInfo.stats rows/bytes identical whether
    the result was buffered (direct runner), streamed (server ring), or
    served from the result cache."""
    sql = "SELECT c_custkey FROM customer"
    buffered = LocalQueryRunner.tpch("tiny")
    buffered.execute(sql)
    base = dict(buffered.last_query_stats)
    assert base["output_rows"] == 1500

    srv = TrinoServer(LocalQueryRunner.tpch("tiny"),
                      result_cache=False, scan_cache=False).start()
    try:
        payload, rows, _ = _drain(srv, sql)
        assert len(rows) == 1500
        streamed = _tracker_stats(payload["id"])
        assert streamed["streamed_chunks"] >= 2
        assert streamed["output_rows"] == base["output_rows"]
        assert streamed["output_bytes"] == base["output_bytes"]
    finally:
        srv.stop()

    cached = LocalQueryRunner.tpch("tiny")
    cached.session.set("result_cache_enabled", True)
    cached.execute(sql)
    cached.execute(sql)
    hit = dict(cached.last_query_stats)
    assert hit["result_cache_hits"] == 1
    assert hit["output_rows"] == base["output_rows"]
    assert hit["output_bytes"] == base["output_bytes"]


# ------------------------------------------------- HTTP fast path + DDL


def test_http_result_cache_fast_path_and_invalidation():
    """Second identical POST answers FINISHED with the data inline (no
    dispatch, no executor) and zero-work stats; INSERT over HTTP evicts
    so the next POST recomputes."""
    srv = TrinoServer(LocalQueryRunner.tpch("tiny")).start()
    try:
        _drain(srv, "CREATE TABLE memory.default.http_t (a bigint)")
        _drain(srv, "INSERT INTO memory.default.http_t VALUES 5, 6")
        sql = "SELECT sum(a) FROM memory.default.http_t"
        _, rows, _ = _drain(srv, sql)
        assert rows == [[11]]
        payload = _post(srv, sql)       # the fast path
        assert payload["stats"]["state"] == "FINISHED"
        assert payload.get("data") == [[11]]
        assert "nextUri" not in payload
        stats = _tracker_stats(payload["id"])
        assert stats["result_cache_hits"] == 1
        assert stats["planning_s"] == 0.0
        assert stats["execution_s"] == 0.0
        assert stats["jit_misses"] == 0
        _drain(srv, "INSERT INTO memory.default.http_t VALUES 100")
        _, rows, _ = _drain(srv, sql)
        assert rows == [[111]]          # stale 11 is impossible
    finally:
        srv.stop()


# --------------------------------------------------- weighted scheduling


def test_weighted_scheduling_drains_2to1():
    """Wall-stride over group weights (the dispatcher's pick logic,
    driven deterministically with fixed equal charges): queues of 3+3
    into 2:1-weighted groups drain wa,wb,wa,wa,wb,wb — two 'wa' per
    'wb' while both queues are backed — and the wall accounting lands
    on the chains."""
    from trino_tpu.exec.resource_groups import ResourceGroupManager
    mgr = ResourceGroupManager()
    mgr.configure("wa", weight=2)
    mgr.configure("wb", weight=1)
    for i in range(3):
        assert mgr.submit("wa", f"qa{i}", f"qa{i}")
        assert mgr.submit("wb", f"qb{i}", f"qb{i}")
    order = []
    for _ in range(6):
        group, item = mgr.take(timeout=1.0)
        order.append(group.name)
        # equal-cost execution slice, charged like the server does
        mgr.charge(group, 0.1)
        mgr.finish(group, str(item))
    assert order == ["wa", "wb", "wa", "wa", "wb", "wb"], order
    by_name = {g.name: g for g in mgr.groups()}
    assert by_name["wa"].scheduled_wall_s == pytest.approx(0.3)
    assert by_name["wb"].scheduled_wall_s == pytest.approx(0.3)


def test_skewed_costs_yield_slots_by_wall():
    """The point of WALL-denominated stride: a group burning 10x-cost
    queries stops monopolizing — with equal weights, the cheap group
    gets picked more often between the heavy group's slices."""
    from trino_tpu.exec.resource_groups import ResourceGroupManager
    mgr = ResourceGroupManager()
    mgr.configure("heavy", weight=1)
    mgr.configure("light", weight=1)
    for i in range(20):
        mgr.submit("heavy", f"qh{i}", f"qh{i}")
        mgr.submit("light", f"ql{i}", f"ql{i}")
    picks = {"heavy": 0, "light": 0}
    for _ in range(24):
        group, item = mgr.take(timeout=1.0)
        picks[group.name] += 1
        mgr.charge(group, 1.0 if group.name == "heavy" else 0.1)
        mgr.finish(group, str(item))
    # per unit wall the light group runs ~10x more queries; well over
    # half the picks must be light once the EWMA estimates converge
    assert picks["light"] > picks["heavy"] * 2, picks


def test_server_charges_wall_to_groups():
    """Server wiring: executor slices charge through to the group
    chain and surface in system.runtime.resource_groups."""
    srv = TrinoServer(LocalQueryRunner.tpch("tiny"), max_running=2,
                      result_cache=False, scan_cache=False).start()
    try:
        for i in range(3):
            _drain(srv, f"SELECT {300 + i}",
                   headers={"X-Trino-Session": "resource_group=wally"})
        by_name = {g.name: g for g in srv.groups.groups()}
        assert by_name["wally"].scheduled_wall_s > 0
        _, rows, _ = _drain(
            srv, "SELECT name, scheduled_wall_ms FROM "
                 "system.runtime.resource_groups WHERE name = 'wally'")
        assert rows and rows[0][1] >= 1, rows
    finally:
        srv.stop()


# ------------------------------------------------------ warmup manifest


def test_warmup_manifest_first_execute_warm(tmp_path):
    """The cold-start contract: after startup with a manifest, the FIRST
    client EXECUTE (new parameter values) binds into a warm plan cache
    and warm kernels — plan_cache_hits == 1, jit_misses == 0."""
    manifest = tmp_path / "warmup.json"
    manifest.write_text(json.dumps({"statements": [
        {"name": "warm_probe",
         "sql": "SELECT n_name FROM nation WHERE n_nationkey = ?",
         "using": "2"},
    ]}))
    srv = TrinoServer(LocalQueryRunner.tpch("tiny"),
                      warmup_manifest=str(manifest)).start()
    try:
        assert srv.warmup_report and \
            "error" not in srv.warmup_report[0], srv.warmup_report
        payload, rows, _ = _drain(srv, "EXECUTE warm_probe USING 9")
        assert rows == [["INDONESIA"]]
        stats = _tracker_stats(payload["id"])
        assert stats["plan_cache_hits"] == 1, stats
        assert stats["jit_misses"] == 0, stats
        assert stats["planning_s"] == 0.0
    finally:
        srv.stop()


def test_warmup_manifest_validation():
    from trino_tpu.serve.warmup import load_manifest
    assert load_manifest([{"sql": "SELECT 1"}]) == [{"sql": "SELECT 1"}]
    with pytest.raises(ValueError, match="statements"):
        load_manifest({"queries": []})
    with pytest.raises(ValueError, match="unknown keys"):
        load_manifest([{"sql": "SELECT 1", "usnig": "1"}])
    with pytest.raises(ValueError, match="needs an object"):
        load_manifest(["SELECT 1"])


# ------------------------------------------------- masked LIMIT kernels


def test_topn_limit_counts_share_one_kernel():
    """Masked fixed-capacity TopN: the count is a runtime operand, so a
    new LIMIT k of a warm shape dispatches zero fresh compiles — the
    warmup-manifest coverage for LIMIT families."""
    from trino_tpu.exec import jit_cache
    r = LocalQueryRunner.tpch("tiny")
    base = "SELECT n_name FROM nation ORDER BY n_nationkey DESC LIMIT {}"
    first = r.execute(base.format(4)).rows
    assert len(first) == 4
    size_before = jit_cache.stats()["size"]
    for k in (1, 7, 19):
        rows = r.execute(base.format(k)).rows
        assert len(rows) == k
        assert r.last_query_stats["jit_misses"] == 0, k
    assert jit_cache.stats()["size"] == size_before


# ------------------------------------------------------------ OTLP spans


def test_otlp_span_export_to_file(tmp_path):
    from trino_tpu.obs.otlp import (install_otlp_exporter,
                                    uninstall_otlp_exporter)
    out = tmp_path / "spans.jsonl"
    exporter = install_otlp_exporter(str(out))
    try:
        r = LocalQueryRunner.tpch("tiny")
        r.execute("SELECT count(*) FROM nation")
        assert exporter.exported >= 1 and exporter.failed == 0
        lines = out.read_text().strip().splitlines()
        payload = json.loads(lines[-1])
        scope = payload["resourceSpans"][0]["scopeSpans"][0]
        spans = scope["spans"]
        assert spans and spans[0]["traceId"] and spans[0]["spanId"]
        names = {s["name"] for s in spans}
        assert "execution" in names     # the phase span made it through
        root = spans[0]
        assert int(root["endTimeUnixNano"]) >= \
            int(root["startTimeUnixNano"])
    finally:
        uninstall_otlp_exporter(exporter)


def test_otlp_off_by_default(monkeypatch):
    from trino_tpu.obs.otlp import install_otlp_exporter
    monkeypatch.delenv("TRINO_TPU_OTLP_ENDPOINT", raising=False)
    monkeypatch.delenv("TRINO_TPU_OTLP_FILE", raising=False)
    assert install_otlp_exporter() is None


# -------------------------------------------------------- introspection


def test_system_runtime_caches_table():
    r = LocalQueryRunner.tpch("tiny")
    rows = r.execute("SELECT cache, entries, hits FROM "
                     "system.runtime.caches ORDER BY cache").rows
    assert [row[0] for row in rows] == ["jit", "plan", "result", "scan",
                                        "table"]
    by_name = {row[0]: row for row in rows}
    assert by_name["jit"][1] >= 0 and by_name["plan"][2] >= 0


# ---------------------------------------------------------- QPS closed loop


def test_qps_smoke():
    """Tier-1 QPS smoke (the CI guard): a short closed loop sustains
    nonzero throughput with bounded p99 and no errors, and cache hits
    are provably zero-work."""
    from trino_tpu.serve.bench_serve import run_qps_bench
    report = run_qps_bench(duration_s=2.0, clients=4, warmup_s=0.5)
    assert report["errors"] == 0, report
    assert report["qps"] > 0, report
    assert report["completed"] > 0
    assert report["p99_ms"] < 30_000, report    # under the wall cap
    assert report["result_cache_hit_rate"] > 0.5, report
    assert report.get("cache_hit_zero_planning") is True
    assert report.get("cache_hit_zero_jit") is True
    assert report.get("cache_hit_zero_execution") is True


@pytest.mark.slow
def test_zz_qps_sweep():
    """Heavy sweep (slow, collected last): the full 8-client loop must
    sustain the acceptance floor on CPU."""
    from trino_tpu.serve.bench_serve import run_qps_bench
    report = run_qps_bench(duration_s=8.0, clients=8)
    assert report["errors"] == 0, report
    assert report["qps"] >= 500, report
    assert report["p99_ms"] < 1000, report
