"""Import hygiene: every trino_tpu module imports cleanly in isolation.

The observability layer threads through runner, planner, tracker, server,
and connectors — exactly the shape that breeds circular imports that only
bite when a module is imported FIRST (e.g. a tool importing
trino_tpu.obs.metrics before trino_tpu.exec). Simulate first-import for
each module by stripping every trino_tpu entry from sys.modules and
importing just that module; the original module objects are restored
afterwards so identity-sensitive state (TRACKER, NODE_POOL, jit cache)
is untouched for the rest of the suite.
"""

import importlib
import pathlib
import sys

import pytest

import trino_tpu

_ROOT = pathlib.Path(trino_tpu.__file__).parent


def _all_modules():
    mods = ["trino_tpu"]
    for path in sorted(_ROOT.rglob("*.py")):
        rel = path.relative_to(_ROOT)
        parts = list(rel.parts[:-1])
        stem = rel.stem
        if stem != "__init__":
            parts.append(stem)
        if parts:
            mods.append("trino_tpu." + ".".join(parts))
    return mods


MODULES = _all_modules()


def test_module_inventory_sane():
    assert "trino_tpu.obs.metrics" in MODULES
    assert "trino_tpu.exec.runner" in MODULES
    assert len(MODULES) > 30


@pytest.mark.parametrize("module", MODULES)
def test_module_imports_in_isolation(module):
    saved = {name: mod for name, mod in sys.modules.items()
             if name == "trino_tpu" or name.startswith("trino_tpu.")}
    for name in list(saved):
        del sys.modules[name]
    try:
        importlib.import_module(module)
    finally:
        # drop the freshly-created duplicates, restore the originals
        for name in list(sys.modules):
            if name == "trino_tpu" or name.startswith("trino_tpu."):
                del sys.modules[name]
        sys.modules.update(saved)


def test_imports_without_pyarrow():
    """pyarrow is STRICTLY optional: with its import blocked (the
    no-pyarrow machine, simulated via sys.modules = None -> ImportError
    on import), every module — the lake connector included — still
    imports, and the lake falls back to the .npz native format."""
    saved = {name: mod for name, mod in sys.modules.items()
             if name == "trino_tpu" or name.startswith("trino_tpu.")}
    arrow_saved = {name: mod for name, mod in sys.modules.items()
                   if name == "pyarrow" or name.startswith("pyarrow.")}
    for name in list(saved) + list(arrow_saved):
        del sys.modules[name]
    sys.modules["pyarrow"] = None   # import pyarrow -> ImportError
    try:
        fmt = importlib.import_module("trino_tpu.connector.lake.format")
        assert fmt.HAVE_PYARROW is False
        assert fmt.default_format() == "npz"
        lake = importlib.import_module("trino_tpu.connector.lake")
        assert lake.HAVE_PYARROW is False
        # the rest of the engine imports clean without pyarrow too
        importlib.import_module("trino_tpu.exec.runner")
    finally:
        for name in list(sys.modules):
            if name == "trino_tpu" or name.startswith("trino_tpu.") \
                    or name == "pyarrow" or name.startswith("pyarrow."):
                del sys.modules[name]
        sys.modules.update(saved)
        sys.modules.update(arrow_saved)


def test_lake_npz_works_without_pyarrow(tmp_path):
    """Functional fallback proof (not just import hygiene): a connector
    forced to the npz format writes/prunes/reads with pyarrow blocked —
    tier-1 still collects AND the lake still serves on that machine."""
    import numpy as np

    from trino_tpu import types as T
    from trino_tpu.connector.lake import format as F
    from trino_tpu.predicate import Domain, Range, TupleDomain
    real = F.HAVE_PYARROW
    try:
        F.HAVE_PYARROW = False
        assert F.default_format() == "npz"
        from trino_tpu.connector import lake
        from trino_tpu.connector.spi import (ColumnMetadata,
                                             SchemaTableName,
                                             TableMetadata)
        from trino_tpu.page import Column, Page
        conn = lake.create_connector(str(tmp_path / "lk"))
        name = SchemaTableName("default", "t")
        conn.metadata.create_table(TableMetadata(
            name, (ColumnMetadata("k", T.BIGINT),)))
        h = conn.metadata.get_table_handle(name)
        sink = conn.page_sink(h, write_token="w1")
        sink.append_page(Page((Column.from_numpy(
            np.arange(10, dtype=np.int64), T.BIGINT),), 10))
        sink.finish()
        total = sum(int(p.num_rows) for s in
                    conn.split_manager.get_splits(h)
                    for p in conn.page_source.pages(
                        s, conn.metadata.get_column_handles(h), 16))
        assert total == 10
        kept, pruned = lake.eligible_files(
            conn._metadata.load_manifest(name),
            TupleDomain.with_column_domains(
                {"k": Domain.from_range(T.BIGINT,
                                        Range.greater_than(50))}))
        assert kept == [] and pruned == 1
    finally:
        F.HAVE_PYARROW = real
